"""Metric tests (analog of tests/shm/metrics_test.cc)."""

import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.graphs import device_graph_from_host, factories
from kaminpar_tpu.ops import metrics


def _dev(g):
    return device_graph_from_host(g)


def _part(dg, values):
    p = np.zeros(dg.n_pad, dtype=np.int32)
    p[: len(values)] = values
    return jnp.asarray(p)


def test_edge_cut_path():
    g = factories.make_path(4)  # 0-1-2-3
    dg = _dev(g)
    assert int(metrics.edge_cut(dg, _part(dg, [0, 0, 1, 1]))) == 1
    assert int(metrics.edge_cut(dg, _part(dg, [0, 1, 0, 1]))) == 3
    assert int(metrics.edge_cut(dg, _part(dg, [0, 0, 0, 0]))) == 0


def test_edge_cut_weighted():
    g = factories.make_path(3, edge_weight=5)
    dg = _dev(g)
    assert int(metrics.edge_cut(dg, _part(dg, [0, 1, 1]))) == 5


def test_block_weights_and_imbalance():
    g = factories.make_path(4)
    dg = _dev(g)
    bw = metrics.block_weights(dg, _part(dg, [0, 0, 0, 1]), 2)
    assert list(np.asarray(bw)) == [3, 1]
    imb = float(metrics.imbalance(dg, _part(dg, [0, 0, 0, 1]), 2))
    assert abs(imb - 0.5) < 1e-6  # max 3 vs perfect 2


def test_feasibility():
    g = factories.make_path(4)
    dg = _dev(g)
    part = _part(dg, [0, 0, 1, 1])
    L = jnp.array([2, 2], dtype=jnp.int32)
    assert bool(metrics.is_feasible(dg, part, L))
    assert int(metrics.total_overload(dg, part, L)) == 0
    part_bad = _part(dg, [0, 0, 0, 1])
    assert not bool(metrics.is_feasible(dg, part_bad, L))
    assert int(metrics.total_overload(dg, part_bad, L)) == 1
