"""Balancer / Jet / FM refinement tests (analog of the reference's
refinement unit coverage, e.g. gain_cache_test.cc validating gains against
recomputation)."""

import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.context import FMRefinementContext, JetRefinementContext
from kaminpar_tpu.graphs import device_graph_from_host, factories
from kaminpar_tpu.ops import metrics
from kaminpar_tpu.ops.balancer import overload_balance, underload_balance
from kaminpar_tpu.ops.jet import jet_refine
from kaminpar_tpu.refinement.fm import fm_refine_host


def _pad_part(dg, values):
    p = np.zeros(dg.n_pad, dtype=np.int32)
    p[: len(values)] = values
    return jnp.asarray(p)


def test_overload_balancer_restores_feasibility():
    g = factories.make_grid_graph(8, 8)
    dg = device_graph_from_host(g)
    # all 64 nodes in block 0 of 4
    part = _pad_part(dg, np.zeros(64, dtype=np.int32))
    caps = jnp.array([17, 17, 17, 17], dtype=jnp.int32)
    balanced = overload_balance(dg, part, 4, caps, jnp.int32(1))
    bw = np.asarray(metrics.block_weights(dg, balanced, 4))
    assert (bw <= 17).all(), bw


def test_overload_balancer_never_overloads_feasible_block():
    # regression: k=3, block 0 heavily overloaded, block 1 has small
    # headroom — incoming movers must not push block 1 over its cap
    g = factories.make_path(12)
    g.node_weights = np.full(12, 10, dtype=np.int64)
    dg = device_graph_from_host(g)
    part = _pad_part(dg, np.array([0] * 8 + [1, 1, 2, 2], dtype=np.int32))
    caps = jnp.array([55, 25, 1000], dtype=jnp.int32)
    from kaminpar_tpu.ops.balancer import overload_balance_round

    out, _ = overload_balance_round(dg, part, 3, caps, jnp.int32(7))
    bw = np.asarray(metrics.block_weights(dg, out, 3))
    assert bw[1] <= 25, bw  # previously-feasible block must stay feasible


def test_overload_balancer_noop_when_feasible():
    g = factories.make_grid_graph(4, 4)
    dg = device_graph_from_host(g)
    part = _pad_part(dg, np.arange(16) // 4)
    caps = jnp.array([5, 5, 5, 5], dtype=jnp.int32)
    out = overload_balance(dg, part, 4, caps, jnp.int32(1))
    assert np.array_equal(np.asarray(out)[:16], np.asarray(part)[:16])


def test_underload_balancer_fills_min_weights():
    g = factories.make_grid_graph(8, 8)
    dg = device_graph_from_host(g)
    part = _pad_part(dg, np.zeros(64, dtype=np.int32))  # block 1 empty
    caps = jnp.array([64, 64], dtype=jnp.int32)
    mins = jnp.array([10, 10], dtype=jnp.int32)
    out = underload_balance(dg, part, 2, caps, mins, jnp.int32(1))
    bw = np.asarray(metrics.block_weights(dg, out, 2))
    assert (bw >= 10).all(), bw


def test_jet_improves_random_partition():
    g = factories.make_grid_graph(10, 10)
    dg = device_graph_from_host(g)
    rng = np.random.default_rng(1)
    part = _pad_part(dg, rng.integers(0, 4, 100))
    caps = jnp.array([30, 30, 30, 30], dtype=jnp.int32)
    before = int(metrics.edge_cut(dg, part))
    out = jet_refine(
        dg, part, 4, caps, jnp.int32(1), JetRefinementContext(), level=0
    )
    after = int(metrics.edge_cut(dg, out))
    assert after < before
    bw = np.asarray(metrics.block_weights(dg, out, 4))
    assert (bw <= 30).all()


def test_fm_host_improves_partition():
    g = factories.make_grid_graph(8, 8)
    dg = device_graph_from_host(g)
    rng = np.random.default_rng(2)
    part = _pad_part(dg, rng.integers(0, 2, 64))
    caps = np.array([40, 40])
    before = int(metrics.edge_cut(dg, part))
    out = fm_refine_host(dg, part, 2, caps, FMRefinementContext(), seed=1)
    after = int(metrics.edge_cut(dg, out))
    assert after < before
    bw = np.asarray(metrics.block_weights(dg, out, 2))
    assert (bw <= 40).all()


def test_k_bucketing_never_uses_phantom_blocks():
    """RefinerPipeline pads k to a power of two with zero-capacity
    phantom blocks (ops/segments.pad_k_bucket); labels must stay < k."""
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.utils.logger import OutputLevel
    from kaminpar_tpu.ops.segments import pad_k_bucket

    k_pad, max_bw, min_bw = pad_k_bucket(5, np.array([10, 10, 10, 10, 10]))
    assert k_pad == 8
    assert max_bw.shape == (8,) and int(max_bw[5:].sum()) == 0
    assert min_bw is None

    g = factories.make_rmat(1 << 10, 6_000, seed=4)
    for k in (3, 5, 11):
        p = KaMinPar("default")
        p.set_output_level(OutputLevel.QUIET)
        part = p.set_graph(g).compute_partition(k=k, epsilon=0.05, seed=2)
        assert part.min() >= 0 and part.max() < k
        assert len(np.unique(part)) == k  # all real blocks populated


def test_chunked_launch_paths_match_fused(monkeypatch):
    """Above MAX_FUSED_EDGE_SLOTS, Jet shrinks its iteration chunk and LP
    refinement runs one round per launch (TPU-worker watchdog guard).
    Force the thresholds down and check both paths still produce valid,
    cap-respecting refinements equivalent to the fused path's quality."""
    import kaminpar_tpu.ops.jet as jet_mod
    import kaminpar_tpu.ops.segments as seg_mod
    from kaminpar_tpu.ops.jet import jet_refine
    from kaminpar_tpu.ops.lp import lp_refine
    from kaminpar_tpu.context import JetRefinementContext

    g = device_graph_from_host(factories.make_rmat(1 << 10, 8_000, seed=9))
    k = 4
    nw = np.asarray(g.node_w)[: int(g.n)]
    cap = jnp.full(k, int(1.05 * np.ceil(nw.sum() / k)), dtype=jnp.int32)
    p0 = jnp.asarray((np.arange(g.n_pad) % k).astype(np.int32))
    cut0 = int(metrics.edge_cut(g, p0))

    fused_jet = jet_refine(g, p0, k, cap, jnp.int32(3), JetRefinementContext(), 0, 2)
    fused_lp = lp_refine(g, p0, k, cap, jnp.int32(3))

    monkeypatch.setattr(jet_mod, "MAX_FUSED_EDGE_SLOTS", 1024)
    monkeypatch.setattr(seg_mod, "MAX_FUSED_EDGE_SLOTS", 1024)
    chunked_jet = jet_refine(g, p0, k, cap, jnp.int32(3), JetRefinementContext(), 0, 2)
    chunked_lp = lp_refine(g, p0, k, cap, jnp.int32(3))

    for part in (chunked_jet, chunked_lp):
        labels = np.asarray(part)[: int(g.n)]
        assert labels.min() >= 0 and labels.max() < k
        bw = np.bincount(labels, weights=nw, minlength=k)
        assert bw.max() <= int(cap[0])
    # same quality class as the fused paths (jet chunk=1 visits the same
    # states, so it is exact; chunked LP may converge slightly differently)
    assert int(metrics.edge_cut(g, chunked_jet)) == int(
        metrics.edge_cut(g, fused_jet)
    )
    assert int(metrics.edge_cut(g, chunked_lp)) < cut0
    assert int(metrics.edge_cut(g, fused_lp)) < cut0


def test_jet_incremental_table_matches_full_rebuild(monkeypatch):
    """The incrementally-maintained (n, k) rating table and the
    candidate-row afterburner must be bitwise-equivalent to full
    rebuilds: integer re-scatter of changed rows is exact, and candidate
    rows contain every edge the filter sums.  Force the delta threshold
    down and compare whole refinements."""
    import kaminpar_tpu.ops.jet as jet_mod
    from kaminpar_tpu.ops.jet import jet_refine
    from kaminpar_tpu.context import JetRefinementContext

    g = device_graph_from_host(factories.make_rmat(1 << 11, 24_000, seed=21))
    k = 8
    nw = np.asarray(g.node_w)[: int(g.n)]
    cap = jnp.full(k, int(1.1 * np.ceil(nw.sum() / k)), dtype=jnp.int32)
    rng = np.random.default_rng(5)
    p0 = np.zeros(g.n_pad, np.int32)
    p0[: int(g.n)] = rng.integers(0, k, int(g.n))
    p0 = jnp.asarray(p0)

    full = np.asarray(
        jet_refine(g, p0, k, cap, jnp.int32(4), JetRefinementContext(), 0, 2)
    )
    # full-width delta budget: candidate pruning keeps everything, so the
    # row-compacted path must reproduce the full path bitwise
    monkeypatch.setattr(jet_mod, "DELTA_MIN_EDGE_SLOTS", 1)
    monkeypatch.setattr(
        jet_mod, "_delta_slots", lambda graph: graph.src.shape[0]
    )
    jet_mod._jet_chunk.clear_cache()
    try:
        delta = np.asarray(
            jet_refine(g, p0, k, cap, jnp.int32(4), JetRefinementContext(), 0, 2)
        )
    finally:
        jet_mod._jet_chunk.clear_cache()
    np.testing.assert_array_equal(delta, full)


def test_jet_candidate_pruning_quality_class(monkeypatch):
    """With a TIGHT delta budget the two-stage candidate pruning admits
    only the best-gain rows per iteration; the refinement must stay
    feasible and land in the same cut class as the unpruned run (pruned
    candidates compete again next iteration)."""
    import kaminpar_tpu.ops.jet as jet_mod
    from kaminpar_tpu.context import JetRefinementContext
    from kaminpar_tpu.ops.jet import jet_refine
    from kaminpar_tpu.ops.metrics import edge_cut

    g = device_graph_from_host(factories.make_rmat(1 << 11, 24_000, seed=21))
    k = 8
    nw = np.asarray(g.node_w)[: int(g.n)]
    cap = jnp.full(k, int(1.1 * np.ceil(nw.sum() / k)), dtype=jnp.int32)
    rng = np.random.default_rng(5)
    p0 = np.zeros(g.n_pad, np.int32)
    p0[: int(g.n)] = rng.integers(0, k, int(g.n))
    p0 = jnp.asarray(p0)

    cut_full = int(
        edge_cut(g, jnp.asarray(jet_refine(
            g, p0, k, cap, jnp.int32(4), JetRefinementContext(), 0, 2)))
    )
    monkeypatch.setattr(jet_mod, "DELTA_MIN_EDGE_SLOTS", 1)
    jet_mod._jet_chunk.clear_cache()
    try:
        pruned_part = jet_refine(
            g, p0, k, cap, jnp.int32(4), JetRefinementContext(), 0, 2
        )
        cut_pruned = int(edge_cut(g, jnp.asarray(pruned_part)))
        bw = np.zeros(k, np.int64)
        np.add.at(bw, np.asarray(pruned_part)[: int(g.n)], nw)
        assert (bw <= int(cap[0])).all()
    finally:
        jet_mod._jet_chunk.clear_cache()
    # same class: pruning costs at most a few percent on this workload
    assert cut_pruned <= 1.1 * cut_full


def test_prune_candidates_to_budget_semantics():
    from kaminpar_tpu.ops.segments import prune_candidates_to_budget

    degrees = jnp.asarray(np.array([3, 5, 2, 4, 1, 7, 0, 0], np.int32))
    gain = jnp.asarray(np.array([10, -2, 7, 7, 1, 3, 0, 0], np.int32))
    cand = jnp.asarray(np.array([1, 1, 1, 1, 1, 1, 0, 0], bool))
    # budget fits everything -> identity
    keep = prune_candidates_to_budget(cand, gain, degrees, 3, 1000)
    np.testing.assert_array_equal(np.asarray(keep), np.asarray(cand))
    # tight budget -> a prefix of the gain order, total degree <= budget
    keep = np.asarray(prune_candidates_to_budget(cand, gain, degrees, 3, 9))
    kept_deg = int(np.asarray(degrees)[keep].sum())
    assert kept_deg <= 9
    assert keep[0]  # gain 10 is always kept first (deg 3 fits)
    assert not keep[1]  # the worst gain goes first when pruning
    # budget monotonicity: a bigger budget keeps a superset
    keep_big = np.asarray(prune_candidates_to_budget(cand, gain, degrees, 3, 12))
    assert (keep <= keep_big).all()


def _afterburner_pair(weight_scale: int, k: int, seed: int):
    """Run the packed (guard-dispatched) afterburner and the exact
    reference filter on the same inputs; return (packed, exact, cand)."""
    from kaminpar_tpu.ops.segments import (
        INT32_MIN,
        afterburner_filter,
        packed_afterburner_gain,
    )

    g = device_graph_from_host(factories.make_rmat(512, 4_000, seed=9))
    n_pad = g.n_pad
    rng = np.random.default_rng(seed)
    ew = np.asarray(g.edge_w).copy()
    real = ew > 0
    ew[real] = rng.integers(1, weight_scale + 1, real.sum())
    edge_w = jnp.asarray(ew)
    part = jnp.asarray(rng.integers(0, k, n_pad).astype(np.int32))
    cand = jnp.asarray(
        (rng.random(n_pad) < 0.4) & (np.arange(n_pad) < int(g.n))
    )
    tgt = jnp.asarray(rng.integers(0, k, n_pad).astype(np.int32))
    next_part = jnp.where(cand, tgt, part)
    # gains scale with the edge weights, so heavy graphs push them past
    # the packed clip range (gain_bits = 31 - 2*ceil(log2 k))
    gain = jnp.asarray(
        rng.integers(-3 * weight_scale, 3 * weight_scale + 1, n_pad)
        .astype(np.int32)
    )
    packed = packed_afterburner_gain(
        g.src, g.dst, edge_w, g.row_ptr, part, next_part, gain, cand, k
    )
    exact = afterburner_filter(
        g.src,
        g.dst,
        edge_w,
        part[g.src],
        part[g.dst],
        jnp.where(cand, gain, INT32_MIN),
        next_part,
        g.src,
        n_pad,
    )
    return np.asarray(packed), np.asarray(exact), np.asarray(cand)


def test_afterburner_clip_guard_heavy_weights():
    """Heavy edge weights push candidate gains past the packed layout's
    clip range (gain_bits=15 at k=256); the runtime guard must dispatch
    the exact path, making the packed entry point agree with the exact
    filter bit-for-bit."""
    packed, exact, cand = _afterburner_pair(
        weight_scale=50_000, k=256, seed=3
    )
    np.testing.assert_array_equal(packed[cand], exact[cand])


def test_afterburner_packed_path_matches_exact_in_range():
    """Below the clip range the packed path itself must equal the exact
    filter (the guard keeps the cheap branch)."""
    packed, exact, cand = _afterburner_pair(weight_scale=50, k=256, seed=4)
    np.testing.assert_array_equal(packed[cand], exact[cand])


def test_fm_threaded_pool_feasible_and_improves():
    """The threaded native FM (NodeTracker claims + atomic gain table)
    must keep the caps and improve the cut; threads=1 must reproduce the
    sequential result bitwise (same rng discipline)."""
    import os

    if os.environ.get("KAMINPAR_TPU_NO_NATIVE_FM", "") == "1":
        import pytest

        pytest.skip("native FM disabled")
    from kaminpar_tpu import native

    if not native.available():
        import pytest

        pytest.skip("no native lib")

    g = factories.make_rmat(1 << 11, 24_000, seed=8)
    dg = device_graph_from_host(g)
    k = 8
    nw = np.asarray(dg.node_w)[: int(dg.n)]
    cap = jnp.full(k, int(1.1 * np.ceil(nw.sum() / k)), dtype=jnp.int32)
    rng = np.random.default_rng(2)
    p0 = np.zeros(dg.n_pad, np.int32)
    p0[: int(dg.n)] = rng.integers(0, k, int(dg.n))
    p0 = jnp.asarray(p0)
    from kaminpar_tpu.ops.metrics import edge_cut

    cut0 = int(edge_cut(dg, p0))
    ctx = FMRefinementContext()

    seq1 = np.asarray(fm_refine_host(dg, p0, k, cap, ctx, seed=5, threads=1))
    seq2 = np.asarray(fm_refine_host(dg, p0, k, cap, ctx, seed=5, threads=1))
    np.testing.assert_array_equal(seq1, seq2)  # deterministic

    for threads in (1, 2, 4):
        out = fm_refine_host(dg, p0, k, cap, ctx, seed=5, threads=threads)
        labels = np.asarray(out)[: int(dg.n)]
        bw = np.bincount(labels, weights=nw, minlength=k)
        assert bw.max() <= int(cap[0]), (threads, bw.max())
        cut = int(edge_cut(dg, out))
        assert cut < cut0, (threads, cut, cut0)


def test_fm_sparse_compact_hashing_cache():
    """The sparse compact-hashing FM path (large-k gain cache,
    compact_hashing_gain_cache.h:34 analog): improves the cut, respects
    caps, and the conn bookkeeping stays exact through rebuilds."""
    from kaminpar_tpu import native

    if not native.available():
        import pytest

        pytest.skip("native library unavailable")
    g = factories.make_rmat(1 << 9, 4000, seed=6)
    dg = device_graph_from_host(g)
    k = 8
    rng = np.random.default_rng(4)
    part_h = rng.integers(0, k, g.n).astype(np.int32)
    nw = g.node_weight_array()
    cap = np.full(k, int(1.1 * nw.sum() / k) + 2, dtype=np.int64)
    part_dev = _pad_part(dg, part_h)
    before = int(metrics.edge_cut(dg, part_dev))

    part_sp = np.array(part_h, copy=True)
    imp = native.fm_refine(
        g, part_sp, k, cap, FMRefinementContext(), seed=9, force_sparse=True
    )
    assert imp is not None and imp > 0
    after = int(metrics.edge_cut(dg, _pad_part(dg, part_sp)))
    assert after < before
    # the returned improvement is the exact cut delta
    assert before - after == imp
    bw = np.zeros(k, dtype=np.int64)
    np.add.at(bw, part_sp, nw)
    assert (bw <= cap).all()

    # dense path on the same instance for comparison: both must land in
    # the same quality ballpark (identical algorithms, different
    # candidate enumeration order)
    part_dn = np.array(part_h, copy=True)
    imp_dn = native.fm_refine(
        g, part_dn, k, cap, FMRefinementContext(), seed=9
    )
    assert imp_dn is not None and imp_dn > 0
    after_dn = int(metrics.edge_cut(dg, _pad_part(dg, part_dn)))
    assert after <= int(1.15 * after_dn) + 5


def test_jet_large_k_degrades_to_lp():
    """jet_refine above JET_DENSE_MAX_ENTRIES must not materialize the
    dense (n, k) table — it degrades to LP refinement rounds and still
    returns a feasible, not-worse partition."""
    import kaminpar_tpu.ops.jet as jet_mod

    g = factories.make_rmat(1 << 9, 4000, seed=3)
    dg = device_graph_from_host(g)
    k = 16
    rng = np.random.default_rng(1)
    part = _pad_part(dg, rng.integers(0, k, g.n))
    nw = g.node_weight_array()
    cap = jnp.asarray(
        np.full(k, int(1.2 * nw.sum() / k) + 2, dtype=np.int32)
    )
    before = int(metrics.edge_cut(dg, part))
    old = jet_mod.JET_DENSE_MAX_ENTRIES
    jet_mod.JET_DENSE_MAX_ENTRIES = 1  # force the large-k fallback
    try:
        out = jet_mod.jet_refine(
            dg, part, k, cap, jnp.int32(7), JetRefinementContext()
        )
    finally:
        jet_mod.JET_DENSE_MAX_ENTRIES = old
    after = int(metrics.edge_cut(dg, out))
    assert after <= before
    bw = np.asarray(metrics.block_weights(dg, out, k))
    assert (bw <= np.asarray(cap)).all()
