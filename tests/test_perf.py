"""Performance observatory (telemetry/perf.py): zero-jaxpr-impact pin,
histogram bucket-edge semantics, compile-cost capture + scope
attribution, pad-waste accounting, memory sampling at barriers, the
`telemetry.top` triage CLI, and the serving-aware report diff."""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kaminpar_tpu import telemetry
from kaminpar_tpu.telemetry import perf
from kaminpar_tpu.telemetry.perf import Histogram
from kaminpar_tpu.utils import timer


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    telemetry.disable()
    yield
    telemetry.reset()
    telemetry.disable()


# ---------------------------------------------------------------------------
# zero device-code impact
# ---------------------------------------------------------------------------


def test_perf_layer_has_zero_jaxpr_impact(monkeypatch):
    """The observatory must be invisible to tracing: the SAME jaxpr
    whether perf is enabled, disabled via KAMINPAR_TPU_PERF=0, or
    telemetry is off entirely — cost capture lives at the compile
    boundary and barriers, never inside jitted code."""
    from kaminpar_tpu.ops.lp import lp_cluster
    from kaminpar_tpu.graphs.csr import device_graph_from_host
    from kaminpar_tpu.graphs import factories

    g = device_graph_from_host(factories.make_grid_graph(8, 8))

    def jaxpr_of_refine():
        def probe(node_w):
            return jnp.cumsum(node_w) + jnp.sum(g.edge_w)

        return str(jax.make_jaxpr(probe)(g.node_w))

    # progress capture off so only the PERF toggle varies between runs
    monkeypatch.setenv("KAMINPAR_TPU_PROGRESS", "0")
    telemetry.disable()
    j_off = jaxpr_of_refine()

    telemetry.enable()
    monkeypatch.setenv("KAMINPAR_TPU_PERF", "0")
    assert not perf.enabled()
    j_perf_off = jaxpr_of_refine()

    monkeypatch.delenv("KAMINPAR_TPU_PERF")
    assert perf.enabled()
    j_perf_on = jaxpr_of_refine()

    assert j_off == j_perf_off == j_perf_on
    # the real pipeline entry is pinned too: lp_cluster's traced shape
    # cannot depend on the perf toggle (it threads no perf state)
    assert lp_cluster is not None


def test_enabled_gates_on_telemetry_and_env(monkeypatch):
    telemetry.disable()
    assert not perf.enabled()
    telemetry.enable()
    assert perf.enabled()
    monkeypatch.setenv("KAMINPAR_TPU_PERF", "0")
    assert not perf.enabled()


# ---------------------------------------------------------------------------
# histogram semantics
# ---------------------------------------------------------------------------


def test_histogram_empty_quantiles_are_none():
    h = Histogram()
    assert h.quantile(0.5) is None
    snap = h.snapshot()
    assert snap["count"] == 0
    assert snap["p50_ms"] is None
    assert snap["p95_ms"] is None
    assert snap["p99_ms"] is None
    assert snap["mean_ms"] is None
    assert snap["buckets"] == []


def test_histogram_boundary_values_land_in_their_bucket():
    h = Histogram()
    edge = Histogram.EDGES[10]
    h.record(edge)  # exactly on a bucket edge
    assert h.counts[10] == 1
    # the quantile is the bucket's upper edge clamped to the observed
    # max — exact for a boundary value
    assert h.quantile(0.5) == pytest.approx(edge)
    # just below the edge lands one bucket down
    h2 = Histogram()
    h2.record(edge * 0.999)
    assert h2.counts[9] == 1


def test_histogram_under_and_overflow_are_clamped():
    h = Histogram()
    h.record(0.0)  # below the first edge
    h.record(1e9)  # beyond the last edge
    assert h.counts[0] == 1
    assert h.counts[-1] == 1
    assert h.count == 2
    assert h.quantile(0.99) == pytest.approx(1e9)  # clamped to max


def test_histogram_percentile_ordering_and_reset():
    h = Histogram()
    for ms in (1, 1, 1, 2, 2, 5, 10, 50, 200, 900):
        h.record(ms / 1000.0)
    snap = h.snapshot()
    assert snap["count"] == 10
    assert snap["p50_ms"] <= snap["p95_ms"] <= snap["p99_ms"]
    assert snap["max_ms"] == pytest.approx(900.0)
    h.reset()
    assert h.count == 0 and h.quantile(0.5) is None


# ---------------------------------------------------------------------------
# compile-cost capture and scope attribution
# ---------------------------------------------------------------------------


def test_compile_capture_attributes_to_open_scope():
    telemetry.enable()
    perf.install()
    perf.reset()
    # a distinctive shape so the in-process jit cache cannot absorb it
    x = jnp.arange(3333, dtype=jnp.float32)

    with timer.scoped_timer("perf-test-scope"):
        y = jax.jit(lambda v: (v * 3.0 + 1.0).sum())(x)
        float(y)

    snap = perf.snapshot()
    assert snap["enabled"] is True
    roof = snap["roofline"]
    assert "perf-test-scope" in roof, sorted(roof)
    row = roof["perf-test-scope"]
    assert row["compiles"] >= 1
    assert row["bytes"] > 0
    # wall joined from the timer tree -> achieved rates + utilization
    assert row["wall_s"] > 0
    assert "hbm_util" in row and row["hbm_util"] >= 0
    assert "deficit_s" in row
    assert snap["totals"]["bytes"] >= row["bytes"]


def test_deficit_uses_exclusive_wall():
    # cost attributed to a non-leaf scope ran in that scope's OWN time;
    # the deficit ranking must not re-count the children's wall
    telemetry.enable()
    perf.reset()
    with timer.scoped_timer("deficit-parent"):
        time.sleep(0.01)
        with timer.scoped_timer("child"):
            time.sleep(0.05)
    with perf._lock:
        perf._scopes["deficit-parent"] = {
            "flops": 1.0, "bytes": 1.0, "output_bytes": 0,
            "temp_bytes": 0, "arg_bytes": 0, "compiles": 1,
            "executables": [],
        }
    row = perf.snapshot()["roofline"]["deficit-parent"]
    assert row["self_s"] < row["wall_s"]
    # utilization is ~0 here, so deficit ~= the exclusive wall — well
    # below the inclusive wall that contains the 50ms child
    assert row["deficit_s"] <= row["self_s"] + 1e-9
    assert row["deficit_s"] < 0.05


def test_peaks_env_override(monkeypatch):
    monkeypatch.setenv("KAMINPAR_TPU_PEAK_GBPS", "123.5")
    monkeypatch.setenv("KAMINPAR_TPU_PEAK_GFLOPS", "456")
    p = perf.peaks()
    assert p["gbps"] == 123.5
    assert p["gflops"] == 456.0
    assert p["source"] == "env"
    monkeypatch.delenv("KAMINPAR_TPU_PEAK_GBPS")
    monkeypatch.delenv("KAMINPAR_TPU_PEAK_GFLOPS")
    p = perf.peaks()
    assert p["source"].startswith("default:")
    assert p["gbps"] > 0 and p["gflops"] > 0


# ---------------------------------------------------------------------------
# pad-waste attribution
# ---------------------------------------------------------------------------


def test_record_padding_aggregates_per_scope_and_bucket():
    telemetry.enable()
    perf.reset()
    with timer.scoped_timer("pad-scope"):
        perf.record_padding(n=100, n_pad=256, m=300, m_pad=512)
        perf.record_padding(n=120, n_pad=256, m=310, m_pad=512)
        perf.record_padding(k=3, k_pad=4)
    rows = perf.snapshot()["pad_waste"]
    by_bucket = {(r["scope"], r["bucket"]): r for r in rows}
    nm = by_bucket[("pad-scope", "256/512/-")]
    assert nm["launches"] == 2
    assert nm["n_real"] == 220 and nm["n_pad"] == 512
    assert nm["n_waste"] == pytest.approx(1 - 220 / 512, abs=1e-4)
    assert nm["m_waste"] == pytest.approx(1 - 610 / 1024, abs=1e-4)
    kk = by_bucket[("pad-scope", "-/-/4")]
    assert kk["k_real"] == 3 and kk["k_pad"] == 4
    assert kk["k_waste"] == pytest.approx(0.25)
    # per-axis totals: k waste must not be masked by the much larger
    # n/m element counts that dominate the cross-axis headline
    axes = perf.snapshot()["totals"]["pad_waste_axes"]
    assert axes["k"] == pytest.approx(0.25)
    assert axes["n"] == pytest.approx(1 - 220 / 512, abs=1e-4)
    assert axes["m"] == pytest.approx(1 - 610 / 1024, abs=1e-4)


def test_device_upload_records_padding():
    from kaminpar_tpu.graphs import factories
    from kaminpar_tpu.graphs.csr import device_graph_from_host

    telemetry.enable()
    perf.reset()
    g = factories.make_grid_graph(10, 10)
    device_graph_from_host(g)
    rows = perf.snapshot()["pad_waste"]
    assert rows, "upload recorded no pad row"
    row = rows[0]
    assert row["n_pad"] >= g.n + 1
    assert row["m_pad"] >= g.m
    assert 0.0 <= row["n_waste"] <= 1.0


def test_record_padding_disabled_is_noop(monkeypatch):
    telemetry.enable()
    perf.reset()
    monkeypatch.setenv("KAMINPAR_TPU_PERF", "0")
    from kaminpar_tpu.caching import record_padding

    record_padding(n=10, n_pad=256)
    monkeypatch.delenv("KAMINPAR_TPU_PERF")
    assert perf.snapshot()["pad_waste"] == []


# ---------------------------------------------------------------------------
# memory watermarks
# ---------------------------------------------------------------------------


def test_sample_memory_records_event_and_snapshot():
    telemetry.enable()
    perf.reset()
    sample = perf.sample_memory("unit-test-stage", level=3)
    assert sample is not None
    assert sample["live_bytes"] >= 0
    assert sample["level"] == 3
    evs = telemetry.events("perf-memory")
    assert evs and evs[-1].attrs["stage"] == "unit-test-stage"
    mem = perf.snapshot()["memory"]
    assert mem["samples"]
    assert mem["peak_live_bytes"] >= 0


def test_sample_memory_disabled_returns_none():
    telemetry.disable()
    assert perf.sample_memory("nope") is None


def test_barriers_sample_memory_during_a_run():
    """End-to-end: a partition run crosses the PR-5 barriers, so the
    report must carry per-stage samples without any checkpoint dir."""
    import kaminpar_tpu as ktp
    from kaminpar_tpu.graphs import factories
    from kaminpar_tpu.telemetry.report import build_run_report
    from kaminpar_tpu.utils.logger import OutputLevel

    telemetry.enable()
    g = factories.make_grid_graph(24, 24)
    p = ktp.KaMinPar("default")
    p.set_output_level(OutputLevel.QUIET)
    p.set_graph(g).compute_partition(k=2, epsilon=0.05, seed=1)
    report = build_run_report()
    mem = report["perf"]["memory"]
    assert mem["samples"], "no barrier samples in a full run"
    stages = {s["stage"] for s in mem["samples"]}
    assert any(st.startswith("initial") or st.startswith("result")
               for st in stages), stages


def test_chrome_trace_emits_memory_counter_track(tmp_path):
    from kaminpar_tpu.telemetry.chrome_trace import chrome_trace

    telemetry.enable()
    perf.reset()
    perf.sample_memory("trace-stage")
    trace = chrome_trace()
    counters = [
        e for e in trace["traceEvents"]
        if e["ph"] == "C" and e["name"] == "memory"
    ]
    assert counters, "perf-memory event produced no counter track"
    assert "live_bytes" in counters[0]["args"]


# ---------------------------------------------------------------------------
# telemetry.top triage CLI
# ---------------------------------------------------------------------------


def _fake_report(with_perf: bool = True) -> dict:
    report = {
        "schema_version": 5 if with_perf else 4,
        "scope_tree": {
            "partitioning": {
                "elapsed_s": 2.0, "count": 1,
                "children": {
                    "coarsening": {"elapsed_s": 1.5, "count": 1,
                                   "children": {}},
                },
            },
        },
        "serving": {"enabled": False},
    }
    if with_perf:
        report["perf"] = {
            "enabled": True,
            "peaks": {"gbps": 100.0, "gflops": 1000.0, "source": "env"},
            "totals": {"flops": 5e6, "bytes": 4e7, "compiles": 3,
                       "wall_s": 2.0, "hbm_util": 0.0002,
                       "pad_waste": 0.25},
            "roofline": {
                "partitioning.coarsening": {
                    "flops": 5e6, "bytes": 4e7, "compiles": 3,
                    "wall_s": 1.5, "calls": 1, "achieved_gbps": 0.027,
                    "achieved_gflops": 0.003, "hbm_util": 0.0003,
                    "flops_util": 0.0, "deficit_s": 1.4995,
                    "output_bytes": 10, "temp_bytes": 0,
                    "executables": [],
                },
            },
            "memory": {
                "peak_live_bytes": 123456,
                "samples": [{"t": 0.5, "stage": "coarsen:1",
                             "live_bytes": 123456}],
                "levels": [{"level": 1, "n": 100, "m": 400,
                            "n_pad": 256, "m_pad": 512,
                            "buffer_bytes": 9000}],
            },
            "pad_waste": [
                {"scope": "partitioning.device-upload",
                 "bucket": "256/512/-", "launches": 1,
                 "n_real": 101, "n_pad": 256, "n_waste": 0.6055,
                 "m_real": 400, "m_pad": 512, "m_waste": 0.2188},
            ],
        }
    return report


def test_top_renders_and_exits_zero(tmp_path, capsys):
    from kaminpar_tpu.telemetry import top

    path = tmp_path / "r.json"
    path.write_text(json.dumps(_fake_report()))
    assert top.main([str(path), "--require-roofline"]) == 0
    out = capsys.readouterr().out
    assert "utilization deficit" in out
    assert "partitioning.coarsening" in out
    assert "pad-waste" in out
    assert "peak live" in out


def test_top_requires_roofline_flag_fails_without_rows(tmp_path, capsys):
    from kaminpar_tpu.telemetry import top

    path = tmp_path / "r.json"
    path.write_text(json.dumps(_fake_report(with_perf=False)))
    assert top.main([str(path)]) == 0  # renders, informational
    assert top.main([str(path), "--require-roofline"]) == 1


def test_top_diff_mode_aligns_scopes(tmp_path, capsys):
    from kaminpar_tpu.telemetry import top

    base = _fake_report()
    cand = _fake_report()
    cand["scope_tree"]["partitioning"]["children"]["coarsening"][
        "elapsed_s"] = 3.0
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(base))
    b.write_text(json.dumps(cand))
    assert top.main([str(b), "--diff", str(a)]) == 0
    out = capsys.readouterr().out
    assert "scope deltas" in out
    assert "1.500->3.000" in out


def test_top_bad_input_is_usage_error(tmp_path):
    from kaminpar_tpu.telemetry import top

    missing = tmp_path / "missing.json"
    assert top.main([str(missing)]) == 2


# ---------------------------------------------------------------------------
# serving-aware diff (satellite: v4 serving sections)
# ---------------------------------------------------------------------------


def _serving_section(served=3, failed=0, hit_rate=0.5, verdicts=None):
    verdicts = verdicts or {}
    requests = []
    for i in range(served):
        rid = f"r{i}"
        requests.append({
            "request_id": rid, "verdict": verdicts.get(rid, "served"),
            "k": 4, "cut": 10, "feasible": True,
        })
    return {
        "enabled": True,
        "requests": requests,
        "counts": {"served": sum(
            1 for r in requests if r["verdict"] == "served"
        ), "anytime": 0, "degraded": 0, "rejected": 0,
            "failed": failed + sum(
                1 for r in requests if r["verdict"] == "failed"
            )},
        "cache": {"hit_rate": hit_rate},
        "drained": False,
    }


def test_diff_gates_serving_served_count_and_hit_rate(tmp_path):
    from kaminpar_tpu.telemetry import diff as diff_mod

    base = {"schema_version": 4, "serving": _serving_section()}
    same = {"schema_version": 4, "serving": _serving_section()}
    lines, failures = diff_mod.diff_reports(base, same)
    assert failures == []

    worse = {
        "schema_version": 4,
        "serving": _serving_section(
            verdicts={"r2": "failed"}, hit_rate=0.1
        ),
    }
    lines, failures = diff_mod.diff_reports(base, worse)
    assert any("served rate regressed" in f for f in failures)
    assert any("hit rate regressed" in f for f in failures)
    assert any("r2: served -> failed" in ln for ln in lines)


def test_diff_serving_rate_not_absolute_count():
    # a smaller candidate batch that served 100% is no regression
    # against a larger base batch that also served 100%
    from kaminpar_tpu.telemetry import diff as diff_mod

    base = {"schema_version": 4, "serving": _serving_section(served=16)}
    cand = {"schema_version": 4, "serving": _serving_section(served=12)}
    _, failures = diff_mod.diff_reports(base, cand)
    assert failures == []


def test_diff_serving_one_sided_is_informational():
    from kaminpar_tpu.telemetry import diff as diff_mod

    base = {"schema_version": 3}
    cand = {"schema_version": 4, "serving": _serving_section()}
    lines, failures = diff_mod.diff_reports(base, cand)
    assert failures == []
    assert any("serve mode" in ln for ln in lines)


def test_diff_hit_rate_threshold_configurable():
    from kaminpar_tpu.telemetry import diff as diff_mod

    base = {"schema_version": 4, "serving": _serving_section(hit_rate=0.5)}
    cand = {"schema_version": 4, "serving": _serving_section(hit_rate=0.42)}
    _, failures = diff_mod.diff_reports(base, cand)
    assert failures == []  # within the default 0.10 absolute drop
    _, failures = diff_mod.diff_reports(
        base, cand, hit_rate_threshold=0.05
    )
    assert any("hit rate regressed" in f for f in failures)


# ---------------------------------------------------------------------------
# windowed cache/bucket stats (satellite: reset_records windowing)
# ---------------------------------------------------------------------------


def test_bounded_cache_window_counters():
    from kaminpar_tpu.caching import BoundedCache

    c = BoundedCache(max_entries=4, max_bytes=1 << 20)
    c.put("a", 1, 8)
    assert c.get("a") == 1
    assert c.get("b") is None
    s = c.stats()
    assert s["hits"] == 1 and s["misses"] == 1
    assert s["window"]["hits"] == 1 and s["window"]["misses"] == 1
    c.begin_window()
    assert c.get("a") == 1
    s = c.stats()
    # lifetime keeps accruing; the window restarted
    assert s["hits"] == 2 and s["window"]["hits"] == 1
    assert s["window"]["misses"] == 0
    assert s["window"]["hit_rate"] == 1.0


def test_bucket_tracker_window_and_per_bucket():
    from kaminpar_tpu.caching import BucketTracker

    t = BucketTracker()
    t.observe(100, 400, 4)
    t.observe(100, 400, 4)
    t.observe(5000, 20000, 8)
    assert t.stats()["hits"] == 1
    pb = t.per_bucket()
    assert sum(pb.values()) == 3 and len(pb) == 2
    t.begin_window()
    t.observe(100, 400, 4)
    s = t.stats()
    assert s["hits"] == 2  # lifetime
    assert s["window"]["hits"] == 1 and s["window"]["misses"] == 0
