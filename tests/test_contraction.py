"""Cluster contraction tests (analog of tests/shm/coarsening/
cluster_contraction_test.cc: contract known toy clusterings, check the
coarse CSR)."""

import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.graphs import (
    device_graph_from_host,
    factories,
    host_graph_from_device,
)
from kaminpar_tpu.ops.contraction import contract_clustering


def _contract(graph, labels_small):
    dg = device_graph_from_host(graph)
    labels = np.arange(dg.n_pad, dtype=np.int32)
    labels[: len(labels_small)] = labels_small
    cg, cn, cm = contract_clustering(dg, jnp.asarray(labels))
    return dg, cg, cn, cm


def test_contract_path_pairs():
    # path 0-1-2-3, clusters {0,1} {2,3} -> coarse path of 2 nodes, 1 edge
    g = factories.make_path(4)
    _, cg, cn, cm = _contract(g, [0, 0, 2, 2])
    assert cn == 2 and cm == 2  # one undirected edge, both directions
    h = host_graph_from_device(cg.graph)
    assert list(h.node_weight_array()) == [2, 2]
    assert h.total_edge_weight == 2


def test_contract_aggregates_parallel_edges():
    # square 0-1-2-3-0; clusters {0,1}, {2,3}: edges (1,2) and (3,0) merge
    g = factories.make_cycle(4)
    _, cg, cn, cm = _contract(g, [0, 0, 2, 2])
    h = host_graph_from_device(cg.graph)
    assert cn == 2 and cm == 2
    assert list(h.edge_weight_array()) == [2, 2]


def test_contract_all_to_one():
    g = factories.make_complete_graph(5)
    _, cg, cn, cm = _contract(g, [0] * 5)
    assert cn == 1 and cm == 0
    h = host_graph_from_device(cg.graph)
    assert list(h.node_weight_array()) == [5]


def test_contract_identity():
    g = factories.make_grid_graph(3, 3)
    _, cg, cn, cm = _contract(g, list(range(9)))
    assert cn == 9 and cm == g.m
    h = host_graph_from_device(cg.graph)
    assert np.array_equal(h.xadj, g.xadj)
    assert np.array_equal(h.adjncy, g.adjncy)


def test_projection_round_trip():
    g = factories.make_grid_graph(4, 4)
    dg, cg, cn, cm = _contract(
        g, np.repeat(np.arange(4), 4).astype(np.int32) * 4
    )
    coarse_part = jnp.asarray(
        np.arange(cg.graph.n_pad, dtype=np.int32) % max(cn, 1)
    )
    fine_part = cg.project_up(coarse_part)
    # all nodes in the same cluster share the fine partition value
    fp = np.asarray(fine_part)[:16]
    labels = np.repeat(np.arange(4), 4) * 4
    for c in np.unique(labels):
        assert len(set(fp[labels == c])) == 1
    # project_down inverts project_up
    down = np.asarray(cg.project_down(fine_part))[:cn]
    up_again = np.asarray(cg.project_up(jnp.asarray(np.concatenate([
        down, np.zeros(cg.graph.n_pad - cn, dtype=down.dtype)]))))[:16]
    assert np.array_equal(fp, up_again)


def test_edge_weight_conservation():
    g = factories.make_rgg2d(300, seed=2)
    dg = device_graph_from_host(g)
    import kaminpar_tpu.ops.lp as lp

    labels = lp.lp_cluster(dg, jnp.int32(15), jnp.int32(3))
    cg, cn, cm = contract_clustering(dg, labels)
    l = np.asarray(labels)[: g.n]
    src = g.edge_sources()
    inter = int((l[src] != l[g.adjncy]).sum())
    h = host_graph_from_device(cg.graph)
    assert h.total_edge_weight == inter
    assert int(h.node_weight_array().sum()) == g.n


def test_combine_labels_intersection():
    """overlay combination: together iff together in BOTH clusterings."""
    import jax.numpy as jnp
    import numpy as np

    from kaminpar_tpu.ops.segments import combine_labels

    l1 = jnp.asarray(np.array([0, 0, 0, 3, 3, 3, 6, 6], dtype=np.int32))
    l2 = jnp.asarray(np.array([0, 0, 2, 2, 4, 4, 6, 7], dtype=np.int32))
    out = np.asarray(combine_labels(l1, l2))
    # groups: {0,1},{2},{3},{4,5},{6},{7}
    assert out[0] == out[1]
    assert len({out[2], out[3], out[4], out[6], out[7], out[0]}) == 6
    assert out[4] == out[5]
    # leaders are min node ids
    assert out[0] == 0 and out[4] == 4


def test_overlay_preset_partitions(rgg2d):
    from kaminpar_tpu import KaMinPar
    from kaminpar_tpu.context import CoarseningAlgorithm
    from kaminpar_tpu.presets import create_context_by_preset_name
    from kaminpar_tpu.utils.logger import OutputLevel

    ctx = create_context_by_preset_name("default")
    ctx.coarsening.algorithm = CoarseningAlgorithm.OVERLAY_CLUSTERING
    part = (
        KaMinPar(ctx)
        .set_output_level(OutputLevel.QUIET)
        .set_graph(rgg2d)
        .compute_partition(k=4, epsilon=0.03, seed=0)
    )
    assert part.shape == (rgg2d.n,)
    assert part.min() >= 0 and part.max() < 4


# ---------------------------------------------------------------------------
# Device-side block-induced subgraph extraction (ops/subgraphs.py)
# ---------------------------------------------------------------------------


def test_device_block_extraction_matches_host():
    """The device extraction must produce the same per-block subgraphs as
    the host extractor (graphs/host.extract_block_subgraphs), up to the
    shared block-major node ordering."""
    import numpy as np

    from kaminpar_tpu.graphs import factories
    from kaminpar_tpu.graphs.csr import device_graph_from_host
    from kaminpar_tpu.graphs.host import extract_block_subgraphs
    from kaminpar_tpu.ops.subgraphs import (
        extract_blocks_device,
        host_graph_from_padded,
        slice_block,
    )

    g = factories.make_rmat(1 << 9, 4_000, seed=9)
    rng = np.random.default_rng(3)
    k = 4
    part = rng.integers(0, k, g.n).astype(np.int64)

    dg = device_graph_from_host(g)
    import jax.numpy as jnp

    padded = np.zeros(dg.n_pad, dtype=np.int32)
    padded[: g.n] = part
    ext = extract_blocks_device(dg, jnp.asarray(padded), k)
    host_ext = extract_block_subgraphs(g, part, k)

    for b in range(k):
        sub_dev, n_b, m_b = slice_block(ext, b, 16, 16)
        sub_host = host_ext.subgraphs[b]
        assert n_b == sub_host.n
        assert m_b == sub_host.m
        got = host_graph_from_padded(sub_dev, n_b, m_b)
        # both extractors number block nodes in ascending global id, so
        # the CSR must match exactly
        np.testing.assert_array_equal(got.xadj, sub_host.xadj)
        # neighbor sets per row match (row-internal order may differ)
        for u in range(n_b):
            np.testing.assert_array_equal(
                np.sort(got.adjncy[got.xadj[u]:got.xadj[u + 1]]),
                np.sort(sub_host.adjncy[sub_host.xadj[u]:sub_host.xadj[u + 1]]),
            )
        np.testing.assert_array_equal(
            got.node_weight_array(), sub_host.node_weight_array()
        )
    # block weights agree with a host recomputation
    nw = g.node_weight_array()
    for b in range(k):
        assert int(ext.block_weights[b]) == int(nw[part == b].sum())


def test_device_extend_partition_end_to_end(monkeypatch):
    """Force the device extend_partition path on a small graph and check
    it produces a feasible partition in the same cut class as the host
    path (deep.py _extend_partition_device)."""
    import numpy as np

    from kaminpar_tpu import kaminpar as kmp_mod
    from kaminpar_tpu.graphs import factories
    from kaminpar_tpu.graphs.host import host_partition_metrics
    from kaminpar_tpu.partitioning import deep as deep_mod

    g = factories.make_rmat(1 << 11, 16_000, seed=4)
    k, eps = 8, 0.03

    def run():
        p = kmp_mod.KaMinPar("default")
        from kaminpar_tpu.utils.logger import OutputLevel

        p.set_output_level(OutputLevel.QUIET)
        return p.set_graph(g).compute_partition(k=k, epsilon=eps, seed=2)

    host_part = run()
    monkeypatch.setattr(deep_mod, "DEVICE_EXTEND_MIN_EDGE_SLOTS", 1)
    dev_part = run()
    res_h = host_partition_metrics(g, host_part, k)
    res_d = host_partition_metrics(g, dev_part, k)
    cap = (1 + eps) * np.ceil(g.node_weight_array().sum() / k)
    assert res_d["block_weights"].max() <= cap
    assert res_d["cut"] <= 1.15 * res_h["cut"]
