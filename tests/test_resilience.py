"""Resilience layer tests: degradation contract, fault-injection chaos
suite, and the strict-balance output gate (docs/robustness.md).

The chaos suite is the acceptance check of ISSUE 3: for every registered
fault site, single-site injection must still yield a partition that
passes the strict-balance output gate, with a `degraded` telemetry event
naming the site and its fallback.
"""

import os

import numpy as np
import pytest

from kaminpar_tpu import resilience, telemetry
from kaminpar_tpu.resilience import (
    CollectiveTimeout,
    DegradationError,
    DeviceOOM,
    NativeUnavailable,
    PlanBlowup,
    RefinerRefused,
    faults,
    gate,
    policy,
    with_fallback,
)


@pytest.fixture(autouse=True)
def _clean_resilience(monkeypatch):
    """Every test starts with closed breakers, zero fault counters, no
    plan, and a fresh telemetry stream."""
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    yield
    resilience.reset()
    telemetry.disable()
    telemetry.reset()


def degraded_sites():
    return [e.attrs["site"] for e in telemetry.events("degraded")]


# ---------------------------------------------------------------------------
# fault-plan parsing
# ---------------------------------------------------------------------------


def test_parse_plan_specs():
    rules = faults.parse_plan("native-fm,refiner:nth=3,lane-gather:0.25,all")
    assert [r.site for r in rules] == [
        "native-fm", "refiner", "lane-gather", "all",
    ]
    assert rules[1].nth == 3
    assert rules[2].prob == 0.25
    assert rules[0].nth is None and rules[0].prob is None


@pytest.mark.parametrize(
    "bad",
    ["nosuchsite", "native-fm:maybe", "refiner:nth=0", "refiner:2.0",
     "refiner:nth=x"],
)
def test_parse_plan_rejects(bad):
    with pytest.raises(faults.FaultPlanError):
        faults.parse_plan(bad)


def test_injection_nth_fires_exactly_once(monkeypatch):
    monkeypatch.setenv(faults.ENV_VAR, "refiner:nth=2")
    faults.maybe_inject("refiner")  # call 1: no fire
    with pytest.raises(DeviceOOM) as ei:
        faults.maybe_inject("refiner")  # call 2: fires
    assert ei.value.injected and ei.value.site == "refiner"
    faults.maybe_inject("refiner")  # call 3: no fire
    assert faults.injected_log() == [{"site": "refiner", "call": 2}]


def test_injection_prob_deterministic_by_seed(monkeypatch):
    from kaminpar_tpu.utils import rng

    monkeypatch.setenv(faults.ENV_VAR, "refiner:0.5")

    def draw_pattern():
        resilience.reset()
        fired = []
        for _ in range(32):
            try:
                faults.maybe_inject("refiner")
                fired.append(False)
            except DeviceOOM:
                fired.append(True)
        return fired

    rng.set_seed(7)
    a = draw_pattern()
    rng.set_seed(7)
    b = draw_pattern()
    rng.set_seed(8)
    c = draw_pattern()
    assert a == b  # same seed -> identical injection pattern
    assert any(a) and not all(a)
    assert a != c  # different seed -> (overwhelmingly likely) different


def test_unregistered_site_is_a_programming_error():
    with pytest.raises(KeyError):
        with_fallback(lambda: 1, lambda exc: 2, site="no-such-site")


# ---------------------------------------------------------------------------
# with_fallback policy
# ---------------------------------------------------------------------------


def test_with_fallback_success_no_events():
    assert with_fallback(lambda: 41, lambda exc: -1, site="refiner") == 41
    assert telemetry.events("degraded") == []


def test_with_fallback_degrades_with_event():
    def boom():
        raise DeviceOOM("synthetic")

    out = with_fallback(boom, lambda exc: "fb", site="device-balancer")
    assert out == "fb"
    (ev,) = telemetry.events("degraded")
    assert ev.attrs["site"] == "device-balancer"
    assert ev.attrs["error"] == "DeviceOOM"
    assert "host balancer" in ev.attrs["fallback"]


def test_with_fallback_classifies_oom_strings():
    class FakeXlaError(RuntimeError):
        pass

    def boom():
        raise FakeXlaError("RESOURCE_EXHAUSTED: out of HBM")

    out = with_fallback(boom, lambda exc: exc, site="device-balancer")
    assert isinstance(out, DeviceOOM)


def test_with_fallback_propagates_unclassified():
    def bug():
        raise ZeroDivisionError("a bug, not a degradation")

    with pytest.raises(ZeroDivisionError):
        with_fallback(bug, lambda exc: "fb", site="refiner")
    assert telemetry.events("degraded") == []


def test_with_fallback_none_fallback_raises_structured():
    def boom():
        raise CollectiveTimeout("down")

    with pytest.raises(CollectiveTimeout):
        with_fallback(boom, None, site="collective")
    assert degraded_sites() == ["collective"]


def test_with_fallback_retry_recovers_and_reports():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise DeviceOOM("transient")
        return "ok"

    out = with_fallback(flaky, lambda exc: "fb", site="refiner", retries=1)
    assert out == "ok"
    (ev,) = telemetry.events("degraded")
    assert ev.attrs["recovered"] is True
    assert ev.attrs["fallback"] == "retry(primary)"
    assert policy.breaker_state("refiner")["consecutive_failures"] == 0


def test_breaker_opens_and_skips_primary():
    ran = {"n": 0}

    def boom():
        ran["n"] += 1
        raise NativeUnavailable("gone")

    for _ in range(policy.BREAKER_THRESHOLD):
        with_fallback(boom, lambda exc: None, site="native-fm")
    assert policy.breaker_state("native-fm")["open"]
    ran_before = ran["n"]
    with_fallback(boom, lambda exc: None, site="native-fm")
    assert ran["n"] == ran_before  # breaker open: primary skipped
    last = telemetry.events("degraded")[-1]
    assert last.attrs["error"] == "circuit-open"


def test_refusals_do_not_latch_breaker():
    for exc_type in (RefinerRefused, PlanBlowup):
        for _ in range(policy.BREAKER_THRESHOLD + 2):
            with_fallback(
                lambda: (_ for _ in ()).throw(exc_type("refused")),
                lambda exc: None,
                site="native-fm" if exc_type is RefinerRefused
                else "lane-gather",
            )
    assert not policy.breaker_state("native-fm")["open"]
    assert not policy.breaker_state("lane-gather")["open"]


# ---------------------------------------------------------------------------
# strict-balance output gate
# ---------------------------------------------------------------------------


def _unit_graph_and_ctx(n=64, k=4):
    from kaminpar_tpu.context import PartitionContext
    from kaminpar_tpu.graphs.factories import make_grid_graph

    rows = int(np.sqrt(n))
    g = make_grid_graph(rows, n // rows)
    p_ctx = PartitionContext()
    p_ctx.setup(g, k=k, epsilon=0.03)
    return g, p_ctx


def test_gate_passes_a_valid_partition():
    g, p_ctx = _unit_graph_and_ctx()
    part = np.arange(g.n, dtype=np.int32) % p_ctx.k
    fixed, verdict = gate.check_and_repair(g, part, p_ctx)
    assert verdict["valid"] and not verdict["repaired"]
    assert verdict["cap_basis"] == "strict-unit"
    assert np.array_equal(fixed, part)


def test_gate_repairs_deliberate_imbalance():
    g, p_ctx = _unit_graph_and_ctx()
    part = np.zeros(g.n, dtype=np.int32)  # everything in block 0
    fixed, verdict = gate.check_and_repair(g, part, p_ctx)
    assert verdict["repaired"] and verdict["valid"]
    assert any(v.startswith("balance") for v in verdict["violations"])
    bw = np.bincount(fixed, minlength=p_ctx.k)
    cap = int(np.ceil((1 + 0.03) * np.ceil(g.n / p_ctx.k)))
    assert bw.max() <= cap
    # strict unit-weight contract: (1+eps) * ceil(n/k)
    assert bw.max() <= p_ctx.unrelaxed_max_block_weights.max()


def test_gate_repairs_out_of_range_labels():
    g, p_ctx = _unit_graph_and_ctx()
    part = np.arange(g.n, dtype=np.int32) % p_ctx.k
    part[3] = -7
    part[11] = p_ctx.k + 100
    fixed, verdict = gate.check_and_repair(g, part, p_ctx)
    assert verdict["repaired"] and verdict["valid"]
    assert any(v.startswith("assignment") for v in verdict["violations"])
    assert fixed.min() >= 0 and fixed.max() < p_ctx.k


def test_gate_no_repair_reports_only():
    g, p_ctx = _unit_graph_and_ctx()
    part = np.zeros(g.n, dtype=np.int32)
    fixed, verdict = gate.check_and_repair(g, part, p_ctx, repair=False)
    assert not verdict["repaired"] and not verdict["valid"]
    assert verdict["max_overload"] > 0
    assert np.array_equal(fixed, part)  # untouched


def test_gate_no_repair_never_touches_the_partition():
    """--no-repair contract: even out-of-range labels come back
    untouched, and `valid` reports the honest unclipped state."""
    g, p_ctx = _unit_graph_and_ctx()
    part = np.arange(g.n, dtype=np.int32) % p_ctx.k
    part[5] = -3  # out of range
    fixed, verdict = gate.check_and_repair(g, part, p_ctx, repair=False)
    assert fixed is part  # the very same object, not a clipped copy
    assert not verdict["valid"] and not verdict["repaired"]
    assert any(v.startswith("assignment") for v in verdict["violations"])


def test_gate_cut_crosscheck_survives_repair():
    """The cut cross-check compares PRE-repair values: a run whose gate
    repairs balance must not report a spurious cut-mismatch."""
    g, p_ctx = _unit_graph_and_ctx()
    part = np.zeros(g.n, dtype=np.int32)  # imbalanced -> repair fires
    reported, _ = gate.recompute_metrics(g, part, p_ctx.k)
    fixed, verdict = gate.check_and_repair(
        g, part, p_ctx, reported_cut=reported
    )
    assert verdict["repaired"]
    assert verdict["cut_match"] is True
    assert not any("cut-mismatch" in v for v in verdict["violations"])
    # cut_recomputed describes the RETURNED (repaired) partition
    cut_final, _ = gate.recompute_metrics(g, fixed, p_ctx.k)
    assert verdict["cut_recomputed"] == cut_final


def test_gate_cut_crosscheck():
    g, p_ctx = _unit_graph_and_ctx()
    part = np.arange(g.n, dtype=np.int32) % p_ctx.k
    cut, _ = gate.recompute_metrics(g, part, p_ctx.k)
    _, ok = gate.check_and_repair(g, part, p_ctx, reported_cut=cut)
    assert ok["cut_match"] is True
    _, bad = gate.check_and_repair(g, part, p_ctx, reported_cut=cut + 5)
    assert bad["cut_match"] is False
    assert any("cut-mismatch" in v for v in bad["violations"])


def test_gate_recompute_matches_host_metrics():
    from kaminpar_tpu.graphs.factories import make_rgg2d
    from kaminpar_tpu.graphs.host import host_partition_metrics

    g = make_rgg2d(256, avg_degree=6, seed=2)
    part = (np.arange(g.n) * 7 % 5).astype(np.int32)
    cut, bw = gate.recompute_metrics(g, part, 5)
    ref = host_partition_metrics(g, part, 5)
    assert cut == ref["cut"]
    assert np.array_equal(bw, ref["block_weights"])


def test_gate_streams_compressed_graphs():
    from kaminpar_tpu.graphs.compressed import compress_host_graph
    from kaminpar_tpu.graphs.factories import make_rgg2d

    g = make_rgg2d(256, avg_degree=6, seed=4)
    cg = compress_host_graph(g)
    part = (np.arange(g.n) % 3).astype(np.int32)
    cut_c, bw_c = gate.recompute_metrics(cg, part, 3)
    cut_h, bw_h = gate.recompute_metrics(g, part, 3)
    assert cut_c == cut_h and np.array_equal(bw_c, bw_h)


# ---------------------------------------------------------------------------
# FM refusal regression: fm_refine -> None / FM_REFUSED route through
# with_fallback, never "treated as zero gain"
# ---------------------------------------------------------------------------


def _fm_setup():
    import jax.numpy as jnp

    from kaminpar_tpu.context import FMRefinementContext
    from kaminpar_tpu.graphs.csr import device_graph_from_host
    from kaminpar_tpu.graphs.factories import make_grid_graph

    g = make_grid_graph(8, 8)
    dg = device_graph_from_host(g)
    part = jnp.asarray(
        np.pad((np.arange(g.n) % 4).astype(np.int32),
               (0, dg.n_pad - g.n))
    )
    caps = np.full(4, g.n, dtype=np.int64)
    return dg, part, caps, FMRefinementContext()


def test_fm_unavailable_routes_to_numpy_fallback(monkeypatch):
    from kaminpar_tpu import native
    from kaminpar_tpu.refinement.fm import fm_refine_host

    monkeypatch.setattr(native, "fm_refine", lambda *a, **kw: None)
    dg, part, caps, fm_ctx = _fm_setup()
    out = fm_refine_host(dg, part, 4, caps, fm_ctx, seed=0)
    assert out.shape[0] == dg.n_pad
    (ev,) = telemetry.events("degraded")
    assert ev.attrs["site"] == "native-fm"
    assert ev.attrs["error"] == "NativeUnavailable"


def test_fm_refusal_returns_partition_unchanged(monkeypatch):
    from kaminpar_tpu import native
    from kaminpar_tpu.refinement.fm import fm_refine_host

    monkeypatch.setattr(
        native, "fm_refine", lambda *a, **kw: native.FM_REFUSED
    )
    dg, part, caps, fm_ctx = _fm_setup()
    out = fm_refine_host(dg, part, 4, caps, fm_ctx, seed=0)
    assert np.array_equal(np.asarray(out), np.asarray(part))
    (ev,) = telemetry.events("degraded")
    assert ev.attrs["site"] == "native-fm"
    assert ev.attrs["error"] == "RefinerRefused"
    # the refusal must not disable native FM for later (feasible) calls
    assert not policy.breaker_state("native-fm")["open"]


# ---------------------------------------------------------------------------
# chaos suite: single-site injection through the full pipeline
# ---------------------------------------------------------------------------


def _run_partition(monkeypatch, fault_plan, *, compression=False,
                   with_fm=False, n=400, k=4):
    """One pipeline run under a fault plan; returns (graph, partition,
    gate verdicts seen, degraded sites seen)."""
    from kaminpar_tpu.context import RefinementAlgorithm
    from kaminpar_tpu.graphs.factories import make_rgg2d
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.presets import create_context_by_preset_name

    monkeypatch.setenv(faults.ENV_VAR, fault_plan)
    ctx = create_context_by_preset_name("default")
    ctx.compression.enabled = compression
    if with_fm:
        ctx.refinement.algorithms = list(ctx.refinement.algorithms) + [
            RefinementAlgorithm.GREEDY_FM
        ]
    g = make_rgg2d(n, avg_degree=8, seed=3)
    solver = KaMinPar(ctx)
    solver.set_graph(g)
    part = solver.compute_partition(k=k, epsilon=0.03, seed=1)
    gates = [e.attrs for e in telemetry.events("output-gate")]
    return g, part, gates, degraded_sites()


CHAOS_CASES = [
    # (site plan, pipeline config kwargs)
    ("native-build:nth=1", {}),
    ("native-ip:nth=1", {}),
    ("native-fm:nth=1", {"with_fm": True}),
    ("refiner:nth=1", {}),
    ("device-balancer:nth=1", {}),
    ("compressed-stream:nth=1", {"compression": True}),
    # allocator-shaped OOM at the device upload: absorbed by the memory
    # governor's recovery ladder (retry at rung 1, tight pads) — the
    # run must still end gate-valid with the degraded event naming the
    # ladder as its fallback
    ("device-oom:nth=1", {}),
]


@pytest.mark.parametrize("plan,cfg", CHAOS_CASES,
                         ids=[p.split(":")[0] for p, _ in CHAOS_CASES])
def test_chaos_single_site(monkeypatch, plan, cfg):
    site = plan.split(":")[0]
    if site in ("native-build", "native-ip", "native-fm"):
        from kaminpar_tpu import native

        if site == "native-build":
            # get_lib caches per process: re-arm it so the injection has
            # a first call to hit
            monkeypatch.setattr(native, "_lib", None)
            monkeypatch.setattr(native, "_tried", False)
        elif not native.available():
            pytest.skip("native library unavailable; site unreachable")
    g, part, gates, degraded = _run_partition(monkeypatch, plan, **cfg)
    # the postcondition: a complete, gate-valid partition
    assert part.shape == (g.n,)
    assert gates and gates[-1]["valid"], gates
    assert gates[-1]["cut_match"] is True
    # the injected site degraded visibly, naming its fallback
    assert site in degraded, (site, degraded)
    ev = [e for e in telemetry.events("degraded")
          if e.attrs["site"] == site][0]
    assert ev.attrs["injected"] is True
    assert ev.attrs["fallback"] == faults.SITES[site].fallback
    # and the fault was logged by the harness
    assert {"site": site, "call": 1} in faults.injected_log()


def test_chaos_lane_gather_site(monkeypatch):
    """lane-gather is gated behind TPU-only probes in the pipeline; the
    chaos contract is exercised at the site wrapper itself."""
    import jax.numpy as jnp

    from kaminpar_tpu.graphs.csr import device_graph_from_host
    from kaminpar_tpu.graphs.factories import make_grid_graph
    from kaminpar_tpu.ops import lane_gather

    monkeypatch.setenv(faults.ENV_VAR, "lane-gather:nth=1")
    dg = device_graph_from_host(make_grid_graph(8, 8))
    pack = lane_gather.edge_plans(dg)
    assert pack is None  # degraded to the XLA gather
    (ev,) = [e for e in telemetry.events("degraded")
             if e.attrs["site"] == "lane-gather"]
    assert ev.attrs["injected"] is True
    # the capped-plan telemetry still fires for report consumers
    plans = telemetry.events("lane-gather-plan")
    assert plans and plans[-1].attrs["capped"] is True
    # second call (fault spent): a real plan is built and cached (the
    # blowup cap is lifted — a pad-dominated toy graph legitimately
    # exceeds the production ratio)
    monkeypatch.setattr(lane_gather, "PLAN_MAX_SLOT_RATIO", float("inf"))
    lane_gather.clear_plan_cache()
    pack2 = lane_gather.edge_plans(dg)
    assert pack2 is not None


def test_chaos_collective_site(monkeypatch):
    from kaminpar_tpu.telemetry.report import build_run_report

    monkeypatch.setenv(faults.ENV_VAR, "collective:nth=1")
    report = build_run_report()
    assert "timers_aggregated" not in report  # degraded to local-only
    assert "collective" in [d["attrs"]["site"] for d in report["degraded"]]
    # the fault-plan echo names the active plan
    assert report["faults"]["plan"] == "collective:nth=1"
    assert report["faults"]["injected"]


def test_chaos_multi_site_sampled(monkeypatch):
    """Sampled multi-site plan: probabilistic faults at several sites at
    once; the pipeline must still meet the gate postcondition."""
    from kaminpar_tpu.utils import rng

    rng.set_seed(13)
    g, part, gates, _ = _run_partition(
        monkeypatch,
        "refiner:0.5,device-balancer:0.5,native-ip:0.5,native-fm:0.5",
        with_fm=True,
    )
    assert part.shape == (g.n,)
    assert gates and gates[-1]["valid"]
    assert gates[-1]["cut_match"] is True


def test_no_repair_keeps_check(monkeypatch):
    """--no-repair plumbing: the gate still checks (and reports) but
    leaves the partition alone."""
    from kaminpar_tpu.cli import build_parser, make_context

    args = build_parser().parse_args(["g.metis", "-k", "4", "--no-repair"])
    ctx = make_context(args)
    assert ctx.resilience.repair is False
    assert ctx.resilience.output_gate is True


# ---------------------------------------------------------------------------
# native build: timeout config + poisoned-cache clean rebuild
# ---------------------------------------------------------------------------


def test_native_build_timeout_env(monkeypatch):
    from kaminpar_tpu import native

    monkeypatch.setenv(native.BUILD_TIMEOUT_ENV, "123.5")
    assert native.build_timeout() == 123.5
    monkeypatch.setenv(native.BUILD_TIMEOUT_ENV, "junk")
    assert native.build_timeout() == native.DEFAULT_BUILD_TIMEOUT_S


def test_native_unusable_cache_dir_degrades(monkeypatch):
    """An unusable cache dir is a degradation (ctypes-free mode), not a
    FileNotFoundError crash from inside _build."""
    from kaminpar_tpu import native

    monkeypatch.setenv(
        native.CACHE_DIR_ENV, "/proc/definitely/not/writable"
    )
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", False)
    assert native.get_lib() is None
    (ev,) = telemetry.events("degraded")
    assert ev.attrs["site"] == "native-build"


def test_cli_rejects_bad_fault_plan_at_startup(monkeypatch, capsys):
    from kaminpar_tpu.cli import main

    monkeypatch.setenv(faults.ENV_VAR, "refner:nth=1")  # typo'd site
    rc = main(["gen:grid2d;rows=4;cols=4", "-k", "2"])
    assert rc == 1
    err = capsys.readouterr().err
    assert "refner" in err and faults.ENV_VAR in err


def test_native_poisoned_cache_clean_rebuild(monkeypatch, tmp_path):
    """A corrupted cached .so must trigger one clean rebuild, not a
    permanent silent fall back to ctypes-free mode."""
    import glob
    import shutil

    from kaminpar_tpu import native

    if not shutil.which("g++"):
        pytest.skip("no C++ toolchain")
    # reuse the package cache's artifact NAME (tag = sources + flags)
    built = glob.glob(os.path.join(native._DIR, "libkmpnative-*.so"))
    if not built:
        built = [native._build()]
    poisoned = tmp_path / os.path.basename(built[0])
    poisoned.write_bytes(b"\x7fELF this is not a shared object")
    monkeypatch.setenv(native.CACHE_DIR_ENV, str(tmp_path))
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", False)
    lib = native.get_lib()
    assert lib is not None  # clean rebuild succeeded
    assert telemetry.events("degraded") == []
    # the poisoned artifact was replaced by a working one
    rebuilt = tmp_path / os.path.basename(built[0])
    assert rebuilt.exists() and rebuilt.stat().st_size > 1000
