"""Distributed CLI tests (apps/dKaMinPar.cc surface)."""

import numpy as np

from kaminpar_tpu.dcli import main

RGG = "/root/reference/misc/rgg2d.metis"


def test_dcli_partitions_file_graph(tmp_path, capfd):
    out = tmp_path / "part.txt"
    rc = main(
        [RGG, "-k", "4", "-n", "2", "-o", str(out), "-T", "--validate"]
    )
    assert rc == 0
    captured = capfd.readouterr()
    # the facade logs the single RESULT line (stderr); the CLI prints TIME
    assert "RESULT cut=" in captured.err
    assert "devices=2" in captured.err
    assert "TIME io=" in captured.out
    # -T prints the finalized dist timer: min/avg/max per scope
    # (kaminpar-dist/timer.cc analog; one process -> min == max)
    assert "min=" in captured.out and "max=" in captured.out
    part = np.loadtxt(out, dtype=np.int64)
    assert part.shape == (1024,)
    assert set(np.unique(part)) <= set(range(4))


def test_dcli_generator_input(capfd):
    rc = main(["gen:rmat;n=256;m=1024;seed=1", "-k", "2", "-n", "2", "-q"])
    assert rc == 0


def test_dcli_streamed_generator_input(capfd):
    """--stream-chunks routes gen: input through the KaGen streaming
    analog (io/skagen.py) — same graph, bounded generation memory."""
    rc = main(
        ["gen:rmat;n=256;m=1024;seed=1", "-k", "2", "-n", "2", "-q",
         "--stream-chunks", "4"]
    )
    assert rc == 0


def test_dcli_errors_without_k(capfd):
    assert main([RGG]) == 1
    assert "need -k" in capfd.readouterr().err


def test_dcli_compressed_input(tmp_path, capfd):
    """dKaMinPar decodes compressed graphs eagerly (terapart input)."""
    from kaminpar_tpu.graphs.compressed import compress_host_graph
    from kaminpar_tpu.io import load_graph, write_compressed

    path = str(tmp_path / "rgg2d.npz")
    write_compressed(path, compress_host_graph(load_graph(RGG)))
    rc = main([path, "-k", "2", "-n", "2", "-f", "compressed", "-q"])
    assert rc == 0


def test_timer_aggregation_single_process():
    """aggregate_across_processes must expose every scope with
    min == avg == max on a single process (the multi-host reduction
    degenerates to the local tree)."""
    from kaminpar_tpu.utils.timer import (
        Timer,
        aggregate_across_processes,
        render_aggregated,
    )

    t = Timer()
    with t.scope("outer"):
        with t.scope("inner"):
            pass
    agg = aggregate_across_processes(t)
    assert set(agg) == {"outer", "outer.inner"}
    s = agg["outer"]
    assert s["min"] == s["avg"] == s["max"] >= 0.0
    assert s["count"] == 1
    out = render_aggregated(agg)
    assert "inner" in out and "min=" in out
