"""CLI tests (apps/KaMinPar.cc surface)."""

import io as std_io
import os
import sys

import numpy as np
import pytest

from kaminpar_tpu.cli import (
    apply_dict_to_context,
    build_parser,
    context_to_dict,
    dump_toml,
    main,
)
from kaminpar_tpu.presets import create_context_by_preset_name

RGG = "/root/reference/misc/rgg2d.metis"


def test_dump_config_roundtrips_through_toml(tmp_path):
    import tomllib

    ctx = create_context_by_preset_name("strong")
    text = "\n".join(dump_toml(context_to_dict(ctx)))
    data = tomllib.loads(text)
    ctx2 = create_context_by_preset_name("default")
    apply_dict_to_context(ctx2, data)
    assert context_to_dict(ctx2) == context_to_dict(ctx)


def test_cli_partitions_and_writes_output(tmp_path, capfd):
    out = tmp_path / "part.txt"
    sizes = tmp_path / "sizes.txt"
    rc = main(
        [
            RGG,
            "-k",
            "4",
            "-e",
            "0.03",
            "-o",
            str(out),
            "--output-block-sizes",
            str(sizes),
            "-T",
            "--validate",
        ]
    )
    assert rc == 0
    captured = capfd.readouterr()  # fd-level: the logger binds the real stderr
    assert "RESULT cut=" in captured.err
    assert "TIME io=" in captured.out

    part = np.loadtxt(out, dtype=np.int32)
    assert part.shape == (1024,)
    assert part.min() >= 0 and part.max() < 4
    bs = np.loadtxt(sizes, dtype=np.int64)
    assert bs.sum() == 1024


def test_cli_config_file_override(tmp_path):
    cfg = tmp_path / "cfg.toml"
    cfg.write_text("[coarsening]\ncontraction_limit = 123\n")
    parser = build_parser()
    args = parser.parse_args([RGG, "-k", "2", "-C", str(cfg)])
    from kaminpar_tpu.cli import make_context

    ctx = make_context(args)
    assert ctx.coarsening.contraction_limit == 123


def test_cli_refinement_override():
    parser = build_parser()
    args = parser.parse_args([RGG, "-k", "2", "--refinement", "lp;jet"])
    from kaminpar_tpu.cli import make_context
    from kaminpar_tpu.context import RefinementAlgorithm

    ctx = make_context(args)
    assert ctx.refinement.algorithms == [
        RefinementAlgorithm.LABEL_PROPAGATION,
        RefinementAlgorithm.JET,
    ]


def test_cli_errors_without_k(capfd):
    assert main([RGG]) == 1
    assert main([]) == 1


def test_cli_machine_timers(capfd):
    rc = main([RGG, "-k", "2", "--machine-timers"])
    assert rc == 0
    out = capfd.readouterr().out
    line = [l for l in out.splitlines() if l.startswith("TIMERS ")]
    assert line, out
    pairs = dict(p.split("=") for p in line[0][len("TIMERS "):].split())
    assert "partitioning" in pairs
    assert float(pairs["partitioning"]) > 0
    assert any(key.startswith("partitioning.") for key in pairs)


def test_cli_degree_bucket_ordering_outputs_file_order(tmp_path):
    """--node-ordering reorders internally but the written partition is
    in original file order (permutation-aware output)."""
    out_nat = tmp_path / "nat.txt"
    out_db = tmp_path / "db.txt"
    remap = tmp_path / "remap.txt"
    assert main([RGG, "-k", "4", "-q", "-o", str(out_nat)]) == 0
    assert main([RGG, "-k", "4", "-q", "--node-ordering", "degree-buckets",
                 "-o", str(out_db), "--output-remapping", str(remap)]) == 0
    mapping = np.loadtxt(remap, dtype=np.int64)
    assert sorted(mapping.tolist()) == list(range(1024))
    from kaminpar_tpu.io import load_graph

    g = load_graph(RGG)
    src, dst = g.edge_sources(), g.adjncy
    for path in (out_nat, out_db):
        part = np.loadtxt(path, dtype=np.int64)
        assert part.shape == (g.n,)
        cut = int((part[src] != part[dst]).sum()) // 2
        assert 0 < cut < g.m  # sane cut in FILE order for both runs
