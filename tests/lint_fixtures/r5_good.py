"""R5 good fixture: plan checked against the blowup cap before use."""
from kaminpar_tpu.ops.lane_gather import build_gather_plan, plan_within_cap


def plan_level(dst, n_pad):
    plan = build_gather_plan(dst, n_pad)
    if not plan_within_cap(plan, dst.shape[0]):
        return None
    return plan


def rating_plan(dst, n_pad):
    """Round 9: the builder's max_slots= abort is itself a cap."""
    return build_gather_plan(dst, n_pad, max_slots=4 * dst.shape[0])
