"""R5 good fixture: plan checked against the blowup cap before use."""
from kaminpar_tpu.ops.lane_gather import build_gather_plan, plan_within_cap


def plan_level(dst, n_pad):
    plan = build_gather_plan(dst, n_pad)
    if not plan_within_cap(plan, dst.shape[0]):
        return None
    return plan
