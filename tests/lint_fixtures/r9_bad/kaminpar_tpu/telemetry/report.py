"""Miniature report producer for the R9 bad quad: the producer was
bumped to 3 but the schema enum (max 2), checker conditional (2) and
fixtures (highest v0) were all left behind — three findings, one per
stale site."""

SCHEMA_VERSION = 3


def build_report():
    return {"schema_version": SCHEMA_VERSION}
