"""Miniature schema checker for the R9 bad quad: conditional still
pins 2 and the only transition fixture is v0 — both stale against the
producer's 3."""


def selftest(report):
    if report.get("schema_version") != 2:
        raise SystemExit("stale report")


def _minimal_v0_report():
    return {"schema_version": 0}
