"""R5 bad fixture: routed-gather plan kept without a slot-cap check."""
from kaminpar_tpu.ops.lane_gather import build_gather_plan


def plan_level(dst, n_pad):
    return build_gather_plan(dst, n_pad)  # line 6: R5 no cap check


def plan_level_logged_only(dst, n_pad, telemetry):
    plan = build_gather_plan(dst, n_pad)  # line 10: R5 logging != a cap
    telemetry.event("plan", num_slots=plan.num_slots)
    return plan


def rating_plan(dst, n_pad):
    """Round 9: a rating engine routing labels[dst] through the lane
    gather must still cap the plan."""
    return build_gather_plan(dst, n_pad)  # line 18: R5
