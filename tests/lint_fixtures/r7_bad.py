# tpulint: disable-file=R2  (rank reads are the shape under test)
"""R7 bad fixture: collectives under rank-dependent control flow —
the SPMD deadlock shape.  Three firings: a direct psum under a rank
guard, a collective reached one helper call deep, and a while-loop
whose trip count is rank-dependent."""
import os

import jax


def _all_reduce(x):
    # collective hidden one call deep
    return jax.lax.psum(x, "mesh")


def broken_report(x):
    if jax.process_index() == 0:
        x = jax.lax.psum(x, "mesh")  # rank 0 enters; 1..7 hang
    return x


def broken_helper_reach(x):
    if int(os.environ.get("TPU_WORKER_RANK", "0")) == 0:
        x = _all_reduce(x)
    return x


def broken_loop(x, agreement):
    while agreement.rank() < 2:
        x = jax.lax.pmean(x, "mesh")
    return x
