"""R1 good fixture: device values stay on device inside jit reach;
host readbacks happen only in plain driver code."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def jitted_entry(x):
    return helper(x)


def helper(x):
    # traced control flow via where, not a python branch
    return jnp.where(jnp.any(x > 0), x.sum() + 1, x.sum())


def driver(x):
    # not reachable from a jit root: host readback is fine here
    out = jitted_entry(x)
    return int(jnp.sum(out)), np.asarray(out)
