"""R1 bad fixture: host-sync primitives in jit-reachable code and spans.

Parsed (never executed) by tests/test_lint.py; line numbers are pinned
there — edit with care.
"""
import jax
import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.utils.timer import scoped_timer


@jax.jit
def jitted_entry(x):
    return helper(x)


def helper(x):
    total = x.sum()
    if jnp.any(x > 0):  # line 20: R1 python branch on traced expr
        total = total + 1
    n = int(jnp.sum(x))  # line 22: R1 int() of a jax value
    val = total.item()  # line 23: R1 .item()
    host = np.asarray(x)  # line 24: R1 device->host copy
    return n + val + host.shape[0]


def span_scope_sync(x):
    with scoped_timer("phase"):
        return np.asarray(x)  # line 30: R1 asarray inside a span scope
