"""R8 bad fixture: broad except handlers that swallow the degradation
contract.  Three firings: a bare except around with_fallback, an
`except Exception` around a site= call, and a broad handler around a
helper that reaches the fault surface one call deep."""
from kaminpar_tpu.resilience.policy import with_fallback


def _guarded_step(fn, x):
    # fault surface reached one call deep
    return with_fallback("lp-refine", fn, x)


def swallow_fallback(fn, x):
    try:
        return with_fallback("coarsen", fn, x)
    except:  # noqa: E722
        return x


def swallow_site(inject, x):
    try:
        return inject(site="refine-step", value=x)
    except Exception:
        return None


def swallow_helper_reach(fn, x):
    try:
        return _guarded_step(fn, x)
    except Exception:
        return x
