"""R1 good fixture: the fleet-observatory hook shape done RIGHT — the
live metrics producers (telemetry/metrics.py inc/set_gauge/mark) are
fed from host-side request records, and the one legitimate end-of-
batch scalar readback lives in a helper OUTSIDE the timer span, so the
span body only makes function calls and the async dispatch queue stays
full while the exporter's cadence thread publishes the scrape file."""
import jax.numpy as jnp

from kaminpar_tpu.telemetry import metrics
from kaminpar_tpu.utils.timer import scoped_timer


def _pull_cut(labels):
    # the batch boundary's single scalar readback — plain module code,
    # not inside a span; the gauge is set from the host value after
    return float(jnp.sum(labels))


def serve_with_hooked_metrics(requests, kernel, labels):
    with scoped_timer("compute"):
        for req in requests:
            labels = kernel(labels, req)
            metrics.mark("kmp_requests_per_second")  # host bookkeeping
    metrics.set_gauge("kmp_edge_cut", _pull_cut(labels))
    return labels
