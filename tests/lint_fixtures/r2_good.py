"""R2 good fixture: all device/backend queries ride the lazy gate."""
from kaminpar_tpu.utils import platform


def pick_backend():
    return platform.default_backend()


def device_list():
    return platform.devices()
