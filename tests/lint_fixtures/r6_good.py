"""R6 good fixture: memory/cost introspection routed through the gated
perf observatory and heap-profiler helpers."""
from kaminpar_tpu.telemetry import perf
from kaminpar_tpu.utils import heap_profiler


def watermark():
    return heap_profiler.live_device_bytes()


def barrier_sample(stage):
    return perf.sample_memory(stage)


def roofline():
    return perf.snapshot()["roofline"]
