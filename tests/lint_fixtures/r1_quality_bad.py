"""R1 bad fixture: the quality-observatory hook shape done WRONG —
per-level cut readbacks and cluster-map pulls written lexically inside
a driver's uncoarsening timer span (the PR-11 hook hazard: every level
would host-sync inside the measured region and charge the span).

Parsed (never executed) by tests/test_lint.py; line numbers are pinned
there — edit with care.
"""
import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.utils.timer import scoped_timer


def uncoarsen_with_inline_metrics(coarsener, graph, partition, cuts):
    with scoped_timer("uncoarsening"):
        while not coarsener.empty():
            graph, partition = coarsener.uncoarsen(partition)
            projected = int(jnp.sum(partition))  # line 19: R1 int()
            cmap_host = np.asarray(coarsener.cmap)  # line 20: R1 copy
            cuts.append((projected, cmap_host.shape[0]))
    return cuts
