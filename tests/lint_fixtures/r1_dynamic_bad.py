"""R1 bad fixture: the dynamic delta-apply hook shape done WRONG —
the host CSR patch pull and the post-apply cut readback written
lexically inside the driver's dynamic-apply timer span (the PR-15 hook
hazard: every delta would host-sync the patched adjacency and a device
scalar inside the measured region, serializing the session mutate
against the device queue and charging the span).

Parsed (never executed) by tests/test_lint.py; line numbers are pinned
there — edit with care.
"""
import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.utils.timer import scoped_timer


def apply_delta_with_inline_pulls(session, batch, labels, out):
    with scoped_timer("dynamic-apply"):
        patched = np.asarray(session.patch(batch))  # line 19: R1 copy
        session.commit(patched)
        out.append(int(jnp.sum(labels)))  # line 21: R1 int()
    return out
