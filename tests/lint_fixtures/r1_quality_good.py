"""R1 good fixture: the quality-observatory hook shape done RIGHT —
the per-level readbacks live in a helper OUTSIDE the driver's timer
span (telemetry/quality.py's note_* pattern: the driver's span body
only makes function calls; the host syncs happen in plain module code
that tpulint's span tracking does not cover)."""
import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.utils.timer import scoped_timer


def _note_level(graph, partition, cmap, cuts):
    # plain helper, not jit-reachable, not lexically inside a span:
    # host readbacks are fine here (the quality.py hook shape)
    cuts.append((int(jnp.sum(partition)), np.asarray(cmap).shape[0]))
    return cuts


def uncoarsen_with_hooked_metrics(coarsener, graph, partition, cuts):
    with scoped_timer("uncoarsening"):
        while not coarsener.empty():
            graph, partition = coarsener.uncoarsen(partition)
            _note_level(graph, partition, coarsener.cmap, cuts)
    return cuts
