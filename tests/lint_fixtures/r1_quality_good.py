"""R1 good fixture: the quality-observatory hook shape done RIGHT —
the driver STAGES per-level references during the span and runs the
host readbacks after it closes (the deep.py/kway.py pending-dumps
pattern).  Since PR 17 the call graph follows same-module helpers one
call deep, so merely factoring the pull into `_note_level` no longer
hides it; the staging below is the real fix."""
import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.utils.timer import scoped_timer


def _note_level(graph, partition, cmap, cuts):
    # host readbacks are fine here: every call site sits outside a span
    cuts.append((int(jnp.sum(partition)), np.asarray(cmap).shape[0]))
    return cuts


def uncoarsen_with_staged_metrics(coarsener, graph, partition, cuts):
    staged = []
    with scoped_timer("uncoarsening"):
        while not coarsener.empty():
            graph, partition = coarsener.uncoarsen(partition)
            # collect by reference only — no device sync in the span
            staged.append((graph, partition, coarsener.cmap))
    for g, p, cm in staged:
        _note_level(g, p, cm, cuts)
    return cuts
