"""R1 good fixture: the out-of-core streaming hook shape done RIGHT.
Two legitimate idioms under the PR-17 call-graph engine:

* `_upload_chunk` carries a def-line suppression: the chunk decode is
  a HOST-BOUNDARY function by contract (the chunkstore owns the staged
  transfer; the asarray views host bytes, not device memory), so the
  suppression on the def clears every call site at once.
* the round's scalar readback `_pull_moved` moves OUTSIDE the span —
  factoring it into a helper no longer hides it from span analysis.
"""
import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.utils.timer import scoped_timer


# host-boundary by contract: decodes a HOST chunk for upload; the
# asarray never touches device memory
# tpulint: disable=R1
def _upload_chunk(store, c):
    return np.asarray(store.chunk(c))


def _pull_moved(labels):
    # the round boundary's single scalar readback — call sites must sit
    # outside the span
    return int(jnp.sum(labels))


def stream_level_with_staged_pulls(store, labels, kernel, out):
    with scoped_timer("stream-lp"):
        for c in range(store.num_chunks):
            labels = kernel(labels, _upload_chunk(store, c))
    out.append(_pull_moved(labels))
    return out
