"""R1 good fixture: the out-of-core streaming hook shape done RIGHT —
chunk decode and the round's scalar readback live in chunkstore-style
helpers OUTSIDE the driver's timer span (external/chunkstore.py's
upload/pull_moved pattern: the span body only makes function calls, so
the host syncs sit in plain module code tpulint's span tracking does
not cover and the async dispatch queue stays full)."""
import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.utils.timer import scoped_timer


def _upload_chunk(store, c):
    # plain helper, not jit-reachable, not lexically inside a span:
    # the decode/copy is fine here (the chunkstore.upload hook shape)
    return np.asarray(store.chunk(c))


def _pull_moved(labels):
    # the round boundary's single scalar readback, factored out like
    # chunkstore.pull_moved
    return int(jnp.sum(labels))


def stream_level_with_hooked_pulls(store, labels, kernel, out):
    with scoped_timer("stream-lp"):
        for c in range(store.num_chunks):
            labels = kernel(labels, _upload_chunk(store, c))
        out.append(_pull_moved(labels))
    return out
