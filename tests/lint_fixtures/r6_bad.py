"""R6 bad fixture: eager device-memory/cost introspection outside the
gated perf helpers."""
import jax


def watermark():
    return sum(x.nbytes for x in jax.live_arrays())


def profile(device):
    return jax.profiler.device_memory_profile(device)


def roofline(compiled):
    return compiled.cost_analysis()


def footprint(compiled):
    return compiled.get_compiled_memory_stats()
