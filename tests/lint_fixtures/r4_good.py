"""R4 good fixture: one wrapper defined once, reused by every level."""
import jax


@jax.jit
def _step(level):
    return level * 2


def per_level_compile(levels):
    return [_step(level) for level in levels]
