"""R2 bad fixture: eager device discovery at import time plus direct
backend queries that bypass the utils.platform gate."""
import jax

DEVICES = jax.devices()  # line 5: R2 eager, at import time


def pick_backend():
    return jax.default_backend()  # line 9: R2 direct, bypasses the gate
