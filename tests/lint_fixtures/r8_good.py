"""R8 good fixture: broad handlers that ROUTE instead of swallow —
re-raise, raise a structured error, or hand the exception to
classify() — plus a narrow handler and a try body that never touches
the fault surface."""
from kaminpar_tpu.resilience.errors import classify
from kaminpar_tpu.resilience.policy import with_fallback


class DegradationError(RuntimeError):
    pass


def routes_via_classify(fn, x):
    try:
        return with_fallback("coarsen", fn, x)
    except Exception as exc:
        return classify(exc, site="coarsen")


def routes_via_raise(fn, x):
    try:
        return with_fallback("refine", fn, x)
    except Exception as exc:
        raise DegradationError("refine failed") from exc


def narrow_handler(fn, x):
    try:
        return with_fallback("lp", fn, x)
    except ValueError:
        # narrow: catches one specific, understood failure
        return x


def broad_but_no_fault_surface(values):
    try:
        return sum(values) / len(values)
    except Exception:
        # try body never reaches the degradation machinery
        return 0.0
