"""R1 good fixture: the dynamic delta-apply hook shape done RIGHT —
the CSR patch work and the cut readback live in session-style helpers
OUTSIDE the driver's timer span (dynamic/session.py's pattern: the
span body only makes function calls, so the host-side patch sits in
plain module code tpulint's span tracking does not cover and the
device queue stays busy)."""
import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.utils.timer import scoped_timer


def _patch_csr(session, batch):
    # plain helper, not jit-reachable, not lexically inside a span:
    # the host CSR patch is fine here (the session.apply hook shape)
    return np.asarray(session.patch(batch))


def _pull_cut(labels):
    # the step boundary's single scalar readback, factored out like
    # the repartition driver's metrics hook
    return int(jnp.sum(labels))


def apply_delta_with_hooked_pulls(session, batch, labels, out):
    with scoped_timer("dynamic-apply"):
        session.commit(_patch_csr(session, batch))
    out.append(_pull_cut(labels))
    return out
