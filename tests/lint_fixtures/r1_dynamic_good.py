"""R1 good fixture: the dynamic delta-apply hook shape done RIGHT —
the host CSR patch is built BEFORE the span opens (the staged host
boundary), so the timed region only dispatches device work.  Since
PR 17 the call graph follows same-module helpers, so hiding the patch
inside `_patch_csr` and calling it from the span no longer passes."""
import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.utils.timer import scoped_timer


def _patch_csr(session, batch):
    # host CSR patch: fine here — every call site sits outside a span
    return np.asarray(session.patch(batch))


def _pull_cut(labels):
    # the step boundary's single scalar readback, also span-free
    return int(jnp.sum(labels))


def apply_delta_with_staged_patch(session, batch, labels, out):
    patch = _patch_csr(session, batch)
    with scoped_timer("dynamic-apply"):
        session.commit(patch)
    out.append(_pull_cut(labels))
    return out
