"""R1 bad fixture: the PR-19 execution-ledger hook shape done WRONG —
the driver feeds the transfer ledger by pulling device values to the
host lexically inside the measured upload span (metering a transfer
must read sizes from host-side metadata, never materialize the
payload: an np.asarray just to count bytes IS a d2h transfer, and an
int() of a device scalar host-syncs the dispatch queue mid-span).

Parsed (never executed) by tests/test_lint.py; line numbers are pinned
there — edit with care.
"""
import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.telemetry import ledger
from kaminpar_tpu.utils.timer import scoped_timer


def upload_with_inline_ledger_pulls(chunks, upload, moved):
    with scoped_timer("device-upload"):
        for chunk in chunks:
            buf = upload(chunk)
            ledger.transfer("h2d", np.asarray(buf).nbytes, "chunk")
        ledger.transfer("d2h", int(jnp.sum(moved)), "stat-pull")
    return moved
