"""R1 bad fixture: the fleet-observatory hook shape done WRONG — the
serving loop feeds the live gauges by pulling device values to the
host lexically inside the measured compute span (the PR-16 metrics
hazard: every request would host-sync mid-span just to publish a
number to the scrape file, serializing the async dispatch queue —
metrics producers are host-side request bookkeeping and must never
read device values).

Parsed (never executed) by tests/test_lint.py; line numbers are pinned
there — edit with care.
"""
import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.telemetry import metrics
from kaminpar_tpu.utils.timer import scoped_timer


def serve_with_inline_gauge_pulls(requests, kernel, labels):
    with scoped_timer("compute"):
        for req in requests:
            labels = kernel(labels, req)
            metrics.set_gauge("kmp_cut", float(jnp.sum(labels)))
            metrics.inc("kmp_moved", value=int(jnp.max(labels)))
            metrics.set_gauge("kmp_last", np.asarray(labels)[-1])
    return labels
