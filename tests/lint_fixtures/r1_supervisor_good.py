"""R1 good fixture: the supervision hook shape done RIGHT — the
watchdog arm/disarm and the heartbeat touch are pure host-side
bookkeeping (resilience/supervisor.py: stage_guard + heartbeat_touch
read no device values), and the one legitimate end-of-stage scalar
readback lives in a helper OUTSIDE the timer span, so the span body
only makes function calls and the async dispatch queue stays full."""
import jax.numpy as jnp

from kaminpar_tpu.resilience.supervisor import heartbeat_touch, stage_guard
from kaminpar_tpu.utils.timer import scoped_timer


def _pull_alive(labels):
    # the stage boundary's single scalar readback, factored out like
    # chunkstore.pull_moved — plain module code, not inside a span
    return int(jnp.sum(labels))


def guarded_run_with_hooked_liveness(levels, kernel, labels, ceiling_s):
    with stage_guard("partition", ceiling_s), scoped_timer("partition"):
        for g in levels:
            labels = kernel(labels, g)
            heartbeat_touch()  # host-side mtime bump, no device read
    return labels, _pull_alive(labels)
