"""R1 good fixture: the PR-19 execution-ledger hook shape done RIGHT —
the factored chokepoint helper meters the upload from host-side array
metadata (`.nbytes` on the host arrays, before device_put — size is
bookkeeping, not a device read), and the one legitimate end-of-phase
stat readback lives in a helper called OUTSIDE the driver's span, its
cost metered by the same hook as it happens."""
import jax.numpy as jnp

from kaminpar_tpu.telemetry import ledger
from kaminpar_tpu.utils.timer import scoped_timer


def _upload_chunk(chunk, upload):
    # the chokepoint helper: size from host metadata, no device read
    ledger.transfer("h2d", chunk.nbytes, kind="chunk")
    return upload(chunk)


def _pull_moved(moved):
    # the phase boundary's single scalar readback — plain driver code,
    # not inside a span; the pull itself is metered as it happens
    ledger.transfer("d2h", moved.nbytes, kind="stat-pull")
    return int(jnp.sum(moved))


def upload_with_hooked_ledger(chunks, upload, moved):
    with scoped_timer("device-upload"):
        done = [_upload_chunk(c, upload) for c in chunks]
    return done, _pull_moved(moved)
