"""R1 bad fixture: the out-of-core streaming hook shape done WRONG —
per-chunk decode pulls and the round's moved-count readback written
lexically inside the driver's stream timer span (the PR-13 hook hazard:
every chunk would host-sync inside the measured region, serializing the
decode against the device and charging the span).

Parsed (never executed) by tests/test_lint.py; line numbers are pinned
there — edit with care.
"""
import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.utils.timer import scoped_timer


def stream_level_with_inline_pulls(store, labels, kernel, out):
    with scoped_timer("stream-lp"):
        for c in range(store.num_chunks):
            block = np.asarray(store.chunk(c))  # line 19: R1 copy
            labels = kernel(labels, block)
            moved = int(jnp.sum(labels))  # line 21: R1 int()
            out.append(moved)
    return out
