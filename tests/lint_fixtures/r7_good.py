# tpulint: disable-file=R2  (rank reads are the shape under test)
"""R7 good fixture: symmetric collectives and the allowlisted
single-writer idiom.  Every rank reaches every collective; the
rank-dependent branch only does host I/O (the checkpoint/report
rank-0-writes shape), never a collective."""
import jax


def symmetric_reduce(x):
    # every rank enters: no guard
    return jax.lax.psum(x, "mesh")


def reduce_then_write(x, path):
    # collective FIRST, symmetric; only the host write is guarded
    total = jax.lax.psum(x, "mesh")
    if jax.process_index() == 0:
        with open(path, "w") as fh:
            fh.write(str(total))
    return total


def guarded_host_only(flag, log):
    # rank-dependent branch with no collective anywhere in reach
    if jax.process_index() == 0:
        log.append(flag)
    return log
