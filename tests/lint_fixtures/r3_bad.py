"""R3 bad fixture: int32 accumulation where the dtypes.py policy rules."""
import jax
import jax.numpy as jnp


def edge_prefix_sums(counts):
    return jnp.cumsum(counts, dtype=jnp.int32)  # line 7: R3


def cut_accumulator(weights, mask):
    return jnp.sum(jnp.where(mask, weights, 0), dtype=jnp.int32)  # line 11


def narrowed(weights, owners, n):
    sums = jax.ops.segment_sum(weights, owners, num_segments=n)
    return jnp.cumsum(sums).astype(jnp.int32)  # line 16: R3 narrowing


def slot_table_sums(edge_w, flat, total):
    """Scatter-add rating table (round 9): slot sums are WEIGHTS."""
    return jax.ops.segment_sum(edge_w, flat, num_segments=total,
                               dtype=jnp.int32)  # line 21: R3
