"""R1 call-graph bad fixture: the helper-hidden host pull.  The span
body only makes a function call — pre-PR-17 tpulint saw nothing — but
`_pull_labels` is a same-module helper whose body syncs the device,
so the one-level call-graph inlining flags the CALL SITE inside the
span (and a second shape: a helper hiding a scalar .item())."""
import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.utils.timer import scoped_timer


def _pull_labels(labels, n):
    # host sync hidden one call deep
    return np.asarray(labels)[:n]


def _read_cut(cut):
    return cut.item()


def refine_with_hidden_pulls(graph, labels, kernel, n, out):
    with scoped_timer("refinement"):
        labels = kernel(graph, labels)
        out.append(_pull_labels(labels, n))
        out.append(_read_cut(jnp.sum(labels)))
    return out
