"""R1 call-graph good fixture: the same helpers as r1_helper_bad.py,
but every call site sits OUTSIDE the span — the device work is
dispatched in the timed region, the staged boundary pulls after it
closes.  The helpers themselves are clean: hostness is a property of
WHERE they are called, not of the def."""
import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.utils.timer import scoped_timer


def _pull_labels(labels, n):
    return np.asarray(labels)[:n]


def _read_cut(cut):
    return cut.item()


def refine_with_staged_pulls(graph, labels, kernel, n, out):
    with scoped_timer("refinement"):
        labels = kernel(graph, labels)
    out.append(_pull_labels(labels, n))
    out.append(_read_cut(jnp.sum(labels)))
    return out
