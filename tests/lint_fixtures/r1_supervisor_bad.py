"""R1 bad fixture: the supervision hook shape done WRONG — the driver
"proves liveness" by pulling device state to the host lexically inside
its guarded timer span (the PR-14 watchdog-hook hazard: every barrier
would host-sync inside the measured region just to touch the heartbeat,
serializing the async dispatch queue against a liveness file — the
heartbeat/watchdog hooks are host-side bookkeeping and must never read
device values).

Parsed (never executed) by tests/test_lint.py; line numbers are pinned
there — edit with care.
"""
import jax.numpy as jnp
import numpy as np

from kaminpar_tpu.utils.timer import scoped_timer


def guarded_run_with_inline_liveness_pulls(levels, kernel, labels, hb):
    with scoped_timer("partition"):
        for g in levels:
            labels = kernel(labels, g)
            alive = int(jnp.sum(labels))  # line 22: R1 int() readback
            hb.write(np.asarray(labels))  # line 23: R1 device->host copy
    return labels, alive
