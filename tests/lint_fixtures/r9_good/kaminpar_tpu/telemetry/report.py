"""Miniature report producer for the R9 good quad: all four pin sites
agree (producer 3, enum max 3, conditional 3, highest fixture v2)."""

SCHEMA_VERSION = 3


def build_report():
    return {"schema_version": SCHEMA_VERSION}
