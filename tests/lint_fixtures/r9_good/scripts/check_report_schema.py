"""Miniature schema checker for the R9 good quad: conditional pins 3,
highest transition fixture is v2 = 3 - 1."""


def selftest(report):
    if report.get("schema_version") != 3:
        raise SystemExit("stale report")


def _minimal_v1_report():
    return {"schema_version": 1}


def _minimal_v2_report():
    return {"schema_version": 2}
