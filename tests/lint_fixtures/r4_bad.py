"""R4 bad fixture: jit wrappers minted per iteration / per evaluation."""
import functools

import jax


def per_level_compile(levels, fn):
    outs = []
    for level in levels:
        step = jax.jit(fn)  # line 10: R4 wrapper built inside a loop
        outs.append(step(level))
    return outs


def per_level_partial(levels, fn):
    while levels:
        step = functools.partial(jax.jit, static_argnames=("k",))(fn)  # 17
        levels = levels[1:]
        step(levels)


def fresh_lambda(x):
    return jax.jit(lambda v: v * 2)(x)  # line 23: R4 fresh lambda
