"""R3 good fixture: accumulators ride the dtypes.py 64-bit policy."""
import jax.numpy as jnp

from kaminpar_tpu.dtypes import ACC_DTYPE


def edge_prefix_sums(counts):
    return jnp.cumsum(counts.astype(ACC_DTYPE))


def cut_accumulator(weights, mask):
    return jnp.sum(jnp.where(mask, weights, 0), dtype=ACC_DTYPE)
