"""R3 good fixture: accumulators ride the dtypes.py 64-bit policy."""
import jax.numpy as jnp

from kaminpar_tpu.dtypes import ACC_DTYPE


def edge_prefix_sums(counts):
    return jnp.cumsum(counts.astype(ACC_DTYPE))


def cut_accumulator(weights, mask):
    return jnp.sum(jnp.where(mask, weights, 0), dtype=ACC_DTYPE)


def slot_table_sums(edge_w, flat, total):
    """Scatter-add rating table (round 9): weights keep ACC_DTYPE."""
    import jax

    return jax.ops.segment_sum(
        edge_w.astype(ACC_DTYPE), flat, num_segments=total
    )
