"""Graph data structure tests (analog of tests/shm/datastructures + graphutils)."""

import numpy as np
import pytest

from kaminpar_tpu.graphs import (
    DeviceGraph,
    HostGraph,
    apply_permutation,
    degree_bucket_permutation,
    device_graph_from_host,
    extract_block_subgraphs,
    factories,
    from_edge_list,
    host_graph_from_device,
    remove_isolated_nodes,
    validate,
)


def test_path_graph_structure():
    g = factories.make_path(5)
    assert g.n == 5 and g.m == 8
    assert list(g.neighbors(0)) == [1]
    assert sorted(g.neighbors(2)) == [1, 3]
    validate(g)


def test_grid_graph_structure():
    g = factories.make_grid_graph(3, 4)
    assert g.n == 12
    assert g.m == 2 * (3 * 3 + 2 * 4)  # horizontal + vertical, both dirs
    assert sorted(g.neighbors(0)) == [1, 4]
    validate(g)


def test_star_and_complete():
    star = factories.make_star(6)
    assert star.n == 7 and star.m == 12
    assert star.degrees()[0] == 6
    comp = factories.make_complete_graph(5)
    assert comp.m == 5 * 4
    validate(star)
    validate(comp)


def test_from_edge_list_merges_duplicates_and_self_loops():
    edges = np.array([[0, 1], [1, 0], [0, 0], [1, 2]])
    g = from_edge_list(3, edges)
    # (0,1) appears twice => merged with weight 2
    assert g.m == 4
    assert g.edge_weights is not None
    w01 = g.edge_weights[g.xadj[0] : g.xadj[1]]
    assert list(w01) == [2]


def test_validate_rejects_asymmetric():
    g = HostGraph(np.array([0, 1, 1]), np.array([1], dtype=np.int32))
    with pytest.raises(ValueError):
        validate(g)


def test_degree_bucket_permutation_orders_by_degree():
    g = factories.make_star(4)  # hub degree 4, leaves degree 1
    perm = degree_bucket_permutation(g)
    pg = apply_permutation(g, perm)
    validate(pg)
    assert pg.degrees().max() == 4
    # hub should be last (highest bucket)
    assert pg.degrees()[-1] == 4
    # edge weights and structure preserved under round trip
    assert pg.m == g.m and pg.n == g.n


def test_remove_isolated_nodes():
    # path 0-1-2 plus isolated nodes 3, 4
    g = HostGraph(
        np.array([0, 1, 3, 4, 4, 4]),
        np.array([1, 0, 2, 1], dtype=np.int32),
    )
    core, perm, num_isolated = remove_isolated_nodes(g)
    assert num_isolated == 2
    assert core.n == 3 and core.m == 4
    validate(core)


def test_device_round_trip(rgg2d):
    dg = device_graph_from_host(rgg2d)
    assert dg.n_pad >= rgg2d.n + 1
    back = host_graph_from_device(dg)
    assert back.n == rgg2d.n and back.m == rgg2d.m
    assert np.array_equal(back.xadj, rgg2d.xadj)
    assert np.array_equal(back.adjncy, rgg2d.adjncy)


def test_device_padding_is_inert(rgg2d):
    import jax.numpy as jnp

    dg = device_graph_from_host(rgg2d)
    # pad edges carry zero weight and point at the pad node
    assert int(dg.edge_w[rgg2d.m :].sum()) == 0
    assert int(dg.node_w[rgg2d.n :].sum()) == 0
    assert bool(jnp.all(dg.src[rgg2d.m :] == dg.n_pad - 1))


def test_extract_block_subgraphs():
    g = factories.make_grid_graph(2, 4)  # nodes 0..7
    part = np.array([0, 0, 1, 1, 0, 0, 1, 1])
    ext = extract_block_subgraphs(g, part, 2)
    assert len(ext.subgraphs) == 2
    for sub in ext.subgraphs:
        assert sub.n == 4
        validate(sub)
    # block 0 = left 2x2 square => 4 undirected internal edges
    assert ext.subgraphs[0].m == 8


def test_kagen_style_generators():
    """KaGen generator parity (dist_skagen.cc analog): every generator
    yields a valid undirected HostGraph of the requested size."""
    from kaminpar_tpu.graphs.factories import generate
    from kaminpar_tpu.graphs.host import validate

    for spec, n_expect in [
        ("rgg2d;n=512;avg_degree=6.0;seed=1", 512),
        ("rgg3d;n=512;avg_degree=6.0;seed=1", 512),
        ("rmat;n=256;m=2048;seed=2", 256),
        ("gnm;n=300;m=1500;seed=3", 300),
        ("ba;n=200;d=3;seed=4", 200),
        ("grid2d;rows=8;cols=9", 72),
        ("grid3d;x=4;y=5;z=6", 120),
    ]:
        g = generate(spec)
        validate(g)
        assert g.n == n_expect, spec
        assert g.m > 0, spec


def test_generator_cli_spec_errors():
    from kaminpar_tpu.graphs.factories import generate
    import pytest as _pytest

    with _pytest.raises(ValueError):
        generate("nosuch;n=5")
