"""Streamed generation tests (kaminpar-io/dist_skagen.cc analog).

The contract under test is KaGen's: the assembled graph is identical
for ANY number of streaming chunks, and chunks cover disjoint
contiguous vertex ranges.
"""

import numpy as np
import pytest

from kaminpar_tpu.graphs.host import validate
from kaminpar_tpu.io.skagen import hostgraph_from_stream, streamed

SPECS = [
    "rmat;n=1024;m=8000;seed=3",
    "gnm;n=500;m=3000;seed=5",
    "rgg2d;n=800;avg_degree=8;seed=2",
    "rgg3d;n=700;avg_degree=8;seed=4",
]


@pytest.mark.parametrize("spec", SPECS)
def test_chunking_invariance(spec):
    ref = hostgraph_from_stream(streamed(spec, num_chunks=1))
    for chunks in (3, 8):
        g = hostgraph_from_stream(streamed(spec, num_chunks=chunks))
        assert g.n == ref.n and g.m == ref.m
        np.testing.assert_array_equal(g.xadj, ref.xadj)
        np.testing.assert_array_equal(g.adjncy, ref.adjncy)
        np.testing.assert_array_equal(
            g.edge_weight_array(), ref.edge_weight_array()
        )


@pytest.mark.parametrize("spec", SPECS)
def test_streamed_graph_is_valid(spec):
    g = hostgraph_from_stream(streamed(spec, num_chunks=4))
    validate(g, undirected=True)
    assert g.n > 0 and g.m > 0


def test_chunk_ranges_cover_disjointly():
    sg = streamed("rmat;n=1024;m=4000;seed=1", num_chunks=7)
    pos = 0
    for c in range(sg.num_chunks):
        v0, v1 = sg.chunk_range(c)
        assert v0 == pos and v1 > v0
        pos = v1
    assert pos == sg.n


def test_chunk_rows_match_assembled_graph():
    sg = streamed("gnm;n=300;m=2000;seed=9", num_chunks=5)
    g = hostgraph_from_stream(sg)
    ch = sg.chunk(2)
    v0, v1 = ch.v_begin, ch.v_end
    for u in range(v0, v1):
        lo, hi = ch.xadj[u - v0], ch.xadj[u - v0 + 1]
        np.testing.assert_array_equal(np.sort(ch.adjncy[lo:hi]),
                                      np.sort(g.neighbors(u)))


def test_large_n_chunk_sort_key_no_overflow():
    """Regression: with a multi-million-vertex chunk span the row sort
    key must not wrap int64 (a power-of-two multiplier did; the key now
    scales by n like from_edge_list's)."""
    spec = "gnm;n=4194304;m=100000;seed=1"
    one = hostgraph_from_stream(streamed(spec, num_chunks=1))
    four = hostgraph_from_stream(streamed(spec, num_chunks=4))
    validate(one, undirected=True)
    np.testing.assert_array_equal(one.xadj, four.xadj)
    np.testing.assert_array_equal(one.adjncy, four.adjncy)


def test_seed_changes_graph():
    a = hostgraph_from_stream(streamed("rmat;n=512;m=3000;seed=1", 2))
    b = hostgraph_from_stream(streamed("rmat;n=512;m=3000;seed=2", 2))
    assert a.m != b.m or not np.array_equal(a.adjncy, b.adjncy)


def test_ba_has_no_streaming_form():
    with pytest.raises(ValueError, match="streaming"):
        streamed("ba;n=100;d=4")


def test_partition_streamed_graph():
    """End-to-end: the streamed graph feeds the normal pipeline."""
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.utils.logger import OutputLevel

    g = hostgraph_from_stream(streamed("rgg2d;n=600;avg_degree=6;seed=4", 4))
    p = KaMinPar("fast")
    p.set_output_level(OutputLevel.QUIET)
    part = p.set_graph(g).compute_partition(k=4, epsilon=0.03, seed=1)
    assert part.shape == (g.n,)
    assert set(np.unique(part)) <= set(range(4))


def test_rgg3d_average_degree_in_range():
    g = hostgraph_from_stream(
        streamed("rgg3d;n=4000;avg_degree=8;seed=1", num_chunks=4)
    )
    avg = g.m / g.n  # HostGraph.m counts directed entries
    assert 5 < avg < 11, avg  # ~8 expected; cube boundary thins it


def test_delaunay_and_fe_grid_factories():
    from kaminpar_tpu.graphs.factories import make_delaunay, make_fe_grid

    d = make_delaunay(500, seed=3)
    validate(d, undirected=True)
    # planar triangulation: undirected edges (m/2) <= 3n - 6, avg deg > 4
    assert d.m // 2 <= 3 * d.n - 6
    assert d.m / d.n > 4

    f = make_fe_grid(20, 30)
    validate(f, undirected=True)
    assert f.n == 600
    # interior nodes of the triangulated grid have degree 6
    degs = f.degrees()
    assert degs.max() == 6
    expected_undirected = (20 * 29) + (30 * 19) + (19 * 29)
    assert f.m == 2 * expected_undirected
