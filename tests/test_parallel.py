"""Multi-device tests on the virtual 8-device CPU mesh.

The analog of the reference's mpirun-on-one-box distributed tests
(tests/CMakeLists.txt:114-117 runs dist tests with 1/2/4 ranks): the same
kernels run over 1, 2, 4, and 8 virtual devices and must produce valid,
cap-respecting results that agree with the single-chip path's metrics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kaminpar_tpu.graphs.factories import make_grid_graph, make_rmat
from kaminpar_tpu.graphs.csr import device_graph_from_host
from kaminpar_tpu.ops.metrics import edge_cut as sc_edge_cut
from kaminpar_tpu.parallel import (
    dist_edge_cut,
    dist_graph_from_host,
    dist_lp_cluster,
    dist_lp_refine,
    make_mesh,
)


def cluster_stats(graph, labels_np):
    """(num_clusters, max_cluster_weight) on host."""
    n = graph.n
    lab = labels_np[:n]
    w = np.zeros(labels_np.shape[0], dtype=np.int64)
    np.add.at(w, lab, graph.node_weight_array()[:n])
    return len(np.unique(lab)), int(w.max())


@pytest.mark.parametrize("n_devices", [1, 2, 4, 8])
def test_dist_lp_cluster_valid_and_capped(n_devices):
    graph = make_grid_graph(24, 24)
    mesh = make_mesh(n_devices)
    dg = dist_graph_from_host(graph, mesh)
    cap = 40
    labels = np.asarray(dist_lp_cluster(dg, cap, seed=1))
    n = graph.n
    # labels are node ids in range
    assert labels.min() >= 0 and labels.max() < dg.n_pad
    nclusters, max_w = cluster_stats(graph, labels)
    assert max_w <= cap
    # LP on a grid must actually coarsen
    assert nclusters < n // 2


def test_dist_lp_cluster_agrees_across_device_counts():
    """The reference pins dist invariants under 1/2/4 ranks on one box
    (tests/CMakeLists.txt:114-117).  Bulk-synchronous commit order
    differs per device count, so cluster COUNTS are compared within a
    moderate band across 1/2/4/8 devices — and every count must respect
    the cap and actually coarsen (the hard invariants are exact)."""
    graph = make_grid_graph(16, 16)
    cap = 32
    counts = {}
    for nd in (1, 2, 4, 8):
        mesh = make_mesh(nd)
        dg = dist_graph_from_host(graph, mesh)
        labels = np.asarray(dist_lp_cluster(dg, cap, seed=3))
        nclusters, max_w = cluster_stats(graph, labels)
        assert max_w <= cap, nd
        assert nclusters < graph.n // 2, nd
        counts[nd] = nclusters
    lo, hi = min(counts.values()), max(counts.values())
    # measured spread on this fixture is ~15%; 1.6x catches topology-
    # breaking regressions while tolerating commit-order divergence
    assert hi <= 1.6 * lo, counts


def test_dist_lp_cluster_rerun_is_deterministic():
    """Same mesh + same seed must be bitwise-reproducible (the dist
    analog of the shm rerun-determinism pin in the reference's
    endtoend tests)."""
    graph = make_grid_graph(16, 16)
    mesh = make_mesh(4)
    dg = dist_graph_from_host(graph, mesh)
    a = np.asarray(dist_lp_cluster(dg, 32, seed=3))
    b = np.asarray(dist_lp_cluster(dg, 32, seed=3))
    np.testing.assert_array_equal(a, b)


def test_dist_edge_cut_matches_host():
    graph = make_rmat(256, 2048, seed=7)
    mesh = make_mesh(4)
    dg = dist_graph_from_host(graph, mesh)
    part = np.random.default_rng(0).integers(0, 4, size=dg.n_pad)
    part = jnp.asarray(part, dtype=jnp.int32)
    got = int(dist_edge_cut(dg, part))

    src = graph.edge_sources()
    p = np.asarray(part)
    want = int(
        graph.edge_weight_array()[p[src] != p[graph.adjncy]].sum() // 2
    )
    assert got == want


def test_dist_lp_refine_improves_cut_and_respects_caps():
    graph = make_grid_graph(20, 20)
    mesh = make_mesh(8)
    dg = dist_graph_from_host(graph, mesh)
    k = 4
    rng = np.random.default_rng(5)
    part0 = np.zeros(dg.n_pad, dtype=np.int32)
    part0[: graph.n] = rng.integers(0, k, size=graph.n)
    total_w = int(graph.node_weight_array().sum())
    max_bw = jnp.full(k, int(1.1 * total_w / k) + 1, dtype=jnp.int32)

    cut0 = int(dist_edge_cut(dg, jnp.asarray(part0)))
    part1 = np.asarray(
        dist_lp_refine(dg, jnp.asarray(part0), k, max_bw, seed=2)
    )
    cut1 = int(dist_edge_cut(dg, jnp.asarray(part1)))
    assert cut1 < cut0

    bw = np.zeros(k, dtype=np.int64)
    np.add.at(bw, part1[: graph.n], graph.node_weight_array())
    assert (bw <= np.asarray(max_bw)).all()
    # pad nodes keep their (clipped) labels; real labels in range
    assert part1[: graph.n].min() >= 0 and part1[: graph.n].max() < k


def test_dist_matches_single_chip_quality():
    """Dist LP clustering should coarsen comparably to the single-chip
    kernel (same algorithm family, different commit protocol)."""
    from kaminpar_tpu.ops.lp import lp_cluster

    graph = make_grid_graph(24, 24)
    dev = device_graph_from_host(graph)
    sc_labels = np.asarray(lp_cluster(dev, jnp.int32(40), jnp.int32(1)))
    sc_n = len(np.unique(sc_labels[: graph.n]))

    mesh = make_mesh(8)
    dg = dist_graph_from_host(graph, mesh)
    d_labels = np.asarray(dist_lp_cluster(dg, 40, seed=1))
    d_n = cluster_stats(graph, d_labels)[0]
    assert 0.25 * sc_n <= d_n <= 4.0 * sc_n


def test_dkaminpar_end_to_end():
    """Distributed deep multilevel on 8 devices: feasible partition with a
    cut comparable to the single-chip pipeline (dist_endtoend_test analog)."""
    from kaminpar_tpu import KaMinPar
    from kaminpar_tpu.parallel import dKaMinPar
    from kaminpar_tpu.utils.logger import OutputLevel

    graph = make_grid_graph(64, 64)
    k, eps = 4, 0.03

    dpart = (
        dKaMinPar("default", n_devices=8)
        .set_graph(graph)
        .compute_partition(k=k, epsilon=eps, seed=1)
    )
    assert dpart.shape == (graph.n,)
    assert dpart.min() >= 0 and dpart.max() < k

    nw = graph.node_weight_array()
    bw = np.zeros(k, dtype=np.int64)
    np.add.at(bw, dpart, nw)
    cap = int((1 + eps) * np.ceil(nw.sum() / k)) + int(nw.max())
    assert (bw <= cap).all()

    src = graph.edge_sources()
    dcut = int(graph.edge_weight_array()[dpart[src] != dpart[graph.adjncy]].sum() // 2)

    sc = KaMinPar("default")
    sc.set_output_level(OutputLevel.QUIET)
    spart = sc.set_graph(graph).compute_partition(k=k, epsilon=eps, seed=1)
    scut = int(graph.edge_weight_array()[spart[src] != spart[graph.adjncy]].sum() // 2)

    # same algorithm family; allow slack for the different commit protocol
    assert dcut <= 3 * scut + 16


# -- dist parity components (coloring, colored LP, Jet, balancer, HEM) ----


@pytest.mark.parametrize("n_devices", [2, 8])
def test_dist_coloring_is_valid(n_devices):
    from kaminpar_tpu.parallel import dist_greedy_coloring

    graph = make_grid_graph(20, 20)
    mesh = make_mesh(n_devices)
    dg = dist_graph_from_host(graph, mesh)
    colors, nc = dist_greedy_coloring(dg, seed=5)
    colors, nc = np.asarray(colors), int(nc)
    src, dst = graph.edge_sources(), graph.adjncy
    assert (colors[src] != colors[dst]).all()
    assert (colors[: graph.n] >= 0).all()
    # greedy coloring of a grid (max degree 4) should use few colors
    assert nc <= 16


def test_dist_colored_lp_improves_cut_under_caps():
    from kaminpar_tpu.parallel import dist_colored_lp_refine

    graph = make_grid_graph(24, 24)
    mesh = make_mesh(4)
    dg = dist_graph_from_host(graph, mesh)
    k = 4
    rng = np.random.default_rng(0)
    part = np.zeros(dg.n_pad, np.int32)
    part[: graph.n] = rng.integers(0, k, graph.n)
    nw = graph.node_weight_array()
    cap = int(np.ceil(nw.sum() / k * 1.1))
    caps = jnp.full((k,), cap, jnp.int32)
    cut0 = int(dist_edge_cut(dg, jnp.asarray(part)))
    ref = np.asarray(
        dist_colored_lp_refine(dg, jnp.asarray(part), k, caps, 11)
    )
    cut1 = int(dist_edge_cut(dg, jnp.asarray(ref)))
    bw = np.bincount(ref[: graph.n], weights=nw, minlength=k)
    assert cut1 <= cut0
    assert bw.max() <= cap


def test_dist_node_balancer_restores_feasibility():
    from kaminpar_tpu.parallel import dist_node_balance

    graph = make_grid_graph(24, 24)
    mesh = make_mesh(4)
    dg = dist_graph_from_host(graph, mesh)
    k = 4
    nw = graph.node_weight_array()
    cap = int(np.ceil(nw.sum() / k * 1.05))
    caps = jnp.full((k,), cap, jnp.int32)
    part = np.zeros(dg.n_pad, np.int32)  # everything in block 0
    bal = np.asarray(dist_node_balance(dg, jnp.asarray(part), k, caps, 5))
    bw = np.bincount(bal[: graph.n], weights=nw, minlength=k)
    assert bw.max() <= cap


def test_dist_jet_beats_batched_lp_start():
    from kaminpar_tpu.parallel import dist_jet_refine

    graph = make_grid_graph(24, 24)
    mesh = make_mesh(4)
    dg = dist_graph_from_host(graph, mesh)
    k = 4
    rng = np.random.default_rng(1)
    part = np.zeros(dg.n_pad, np.int32)
    part[: graph.n] = rng.integers(0, k, graph.n)
    nw = graph.node_weight_array()
    cap = int(np.ceil(nw.sum() / k * 1.1))
    caps = jnp.full((k,), cap, jnp.int32)
    cut0 = int(dist_edge_cut(dg, jnp.asarray(part)))
    ref = np.asarray(dist_jet_refine(dg, jnp.asarray(part), k, caps, 13))
    cut1 = int(dist_edge_cut(dg, jnp.asarray(ref)))
    bw = np.bincount(ref[: graph.n], weights=nw, minlength=k)
    assert cut1 < cut0
    assert bw.max() <= cap


def test_dist_hem_is_a_matching_on_edges():
    from kaminpar_tpu.parallel import dist_hem_cluster

    graph = make_grid_graph(16, 16)
    mesh = make_mesh(4)
    dg = dist_graph_from_host(graph, mesh)
    nw = graph.node_weight_array()
    cap = int(nw.sum())
    lab = np.asarray(dist_hem_cluster(dg, cap, seed=5))[: graph.n]
    sizes = np.bincount(lab, minlength=graph.n)
    assert sizes.max() <= 2  # matching: clusters of at most two nodes
    eset = set(zip(graph.edge_sources().tolist(), graph.adjncy.tolist()))
    for u in range(graph.n):
        if lab[u] != u:
            assert (u, lab[u]) in eset  # pairs are real edges
    # a grid has a near-perfect matching; handshaking should find most
    assert (sizes == 2).sum() >= graph.n // 4


def test_dist_hem_lp_coarsens_further_than_hem():
    from kaminpar_tpu.parallel import dist_hem_cluster, dist_hem_lp_cluster

    graph = make_grid_graph(16, 16)
    mesh = make_mesh(4)
    dg = dist_graph_from_host(graph, mesh)
    cap = 32
    hem = np.asarray(dist_hem_cluster(dg, cap, seed=5))[: graph.n]
    hemlp = np.asarray(dist_hem_lp_cluster(dg, cap, seed=5))[: graph.n]
    assert len(np.unique(hemlp)) <= len(np.unique(hem))
    nw = graph.node_weight_array()
    cw = np.bincount(hemlp, weights=nw, minlength=graph.n)
    assert cw.max() <= cap


def test_dist_local_lp_keeps_clusters_on_device():
    from kaminpar_tpu.ops.lp import LPConfig

    graph = make_grid_graph(16, 16)
    mesh = make_mesh(4)
    dg = dist_graph_from_host(graph, mesh)
    labels = np.asarray(
        dist_lp_cluster(dg, 32, seed=7, cfg=LPConfig(dist_local_only=True))
    )[: graph.n]
    n_loc = dg.n_pad // 4
    owner_of_label = labels // n_loc
    owner_of_node = np.arange(graph.n) // n_loc
    assert (owner_of_label == owner_of_node).all()


def test_dist_presets_and_factories():
    from kaminpar_tpu.parallel import (
        create_dist_context_by_preset_name,
        get_dist_preset_names,
    )

    names = get_dist_preset_names()
    for expected in (
        "default", "strong", "largek", "xterapart",
        "europar23-fast", "europar23-strong",
    ):
        assert expected in names
    for name in names:
        ctx = create_dist_context_by_preset_name(name)
        assert ctx.shm is not None


@pytest.mark.slow  # alive since the shard_map compat shim (round 12) but past the
# tier-1 870 s budget on the CPU fallback; dist tier-1 coverage lives in
# tests/test_dist_resilience.py / test_dist_chaos.py
def test_dist_random_initial_partitioning():
    """RANDOM dist IP variant (kaminpar-dist/factories.cc:72-88): the
    coarsest graph gets uniform random blocks; balancers + refiners must
    still deliver a feasible partition."""
    from kaminpar_tpu.parallel import dKaMinPar, create_dist_context_by_preset_name
    from kaminpar_tpu.parallel.dist_context import (
        DistInitialPartitioningAlgorithm,
    )

    ctx = create_dist_context_by_preset_name("default")
    ctx.initial_partitioning = DistInitialPartitioningAlgorithm.RANDOM
    # force the leveled path (coarsen + per-level refinement): the full
    # refiner list incl. balancers is what repairs the random start's
    # imbalance, exactly as in the reference's dist deep pipeline
    ctx.shm.coarsening.contraction_limit = 50
    ctx.replication_min_nodes_per_device = 0
    graph = make_grid_graph(32, 32)
    k = 4
    part = (
        dKaMinPar(ctx, n_devices=4)
        .set_graph(graph)
        .compute_partition(k=k, epsilon=0.03, seed=1)
    )
    assert part.shape == (graph.n,)
    nw = graph.node_weight_array()
    bw = np.zeros(k, dtype=np.int64)
    np.add.at(bw, part, nw)
    assert bw.max() <= np.ceil(1.03 * nw.sum() / k) + 1
    assert len(np.unique(part)) == k


def test_comm_accounting_table():
    """Collective accounting: a dist LP run inside a comm_phase scope
    registers halo/psum traffic; the table renders per-phase lines."""
    import jax.numpy as jnp

    from kaminpar_tpu.parallel import (
        dist_graph_from_host,
        dist_lp_cluster,
        make_mesh,
    )
    from kaminpar_tpu.parallel.mesh import (
        comm_phase,
        comm_table,
        reset_comm_log,
    )

    reset_comm_log()
    mesh = make_mesh(4)
    # unusual size so this call traces fresh (trace-time accounting sees
    # nothing on a jit cache hit from an earlier test's identical shapes)
    host = make_grid_graph(18, 18)
    graph = dist_graph_from_host(host, mesh)
    with comm_phase("test-lp"):
        labels = dist_lp_cluster(graph, 16, seed=5)
    assert labels.shape[0] >= host.n
    table = comm_table()
    assert "test-lp" in table
    assert "all_to_all(halo)" in table
    reset_comm_log()
    assert "no collectives" in comm_table()


@pytest.mark.slow  # alive since the shard_map compat shim (round 12) but past the
# tier-1 870 s budget on the CPU fallback; dist tier-1 coverage lives in
# tests/test_dist_resilience.py / test_dist_chaos.py
def test_dkaminpar_strong_preset_end_to_end():
    from kaminpar_tpu.parallel import dKaMinPar

    graph = make_grid_graph(48, 48)
    k, eps = 4, 0.03
    part = (
        dKaMinPar("strong", n_devices=4)
        .set_graph(graph)
        .compute_partition(k=k, epsilon=eps, seed=1)
    )
    assert part.shape == (graph.n,)
    nw = graph.node_weight_array()
    bw = np.zeros(k, dtype=np.int64)
    np.add.at(bw, part, nw)
    cap = int((1 + eps) * np.ceil(nw.sum() / k)) + int(nw.max())
    assert (bw <= cap).all()


@pytest.mark.parametrize("n_devices", [1, 4])
def test_dist_cluster_balancer_restores_feasibility(n_devices):
    from kaminpar_tpu.parallel import dist_cluster_balance

    graph = make_grid_graph(24, 24)
    mesh = make_mesh(n_devices)
    dg = dist_graph_from_host(graph, mesh)
    k = 4
    nw = graph.node_weight_array()
    cap = int(np.ceil(nw.sum() / k * 1.05))
    caps = jnp.full((k,), cap, jnp.int32)
    part = np.zeros(dg.n_pad, np.int32)  # everything in block 0
    bal = np.asarray(dist_cluster_balance(dg, jnp.asarray(part), k, caps, 5))
    bw = np.bincount(bal[: graph.n], weights=nw, minlength=k)
    assert bw.max() <= cap


def test_dist_cluster_balancer_noop_on_feasible_partition():
    from kaminpar_tpu.parallel import dist_cluster_balance

    graph = make_grid_graph(16, 16)
    mesh = make_mesh(4)
    dg = dist_graph_from_host(graph, mesh)
    k = 4
    # balanced column partition is already feasible: balancer must not touch
    part = np.zeros(dg.n_pad, np.int32)
    cols = np.arange(graph.n) % 16
    part[: graph.n] = cols * k // 16
    nw = graph.node_weight_array()
    cap = int(np.ceil(nw.sum() / k * 1.05))
    caps = jnp.full((k,), cap, jnp.int32)
    bal = np.asarray(dist_cluster_balance(dg, jnp.asarray(part), k, caps, 5))
    np.testing.assert_array_equal(bal[: graph.n], part[: graph.n])


def test_dist_cluster_balancer_moves_whole_clusters_when_needed():
    """A block whose border nodes all have high loss still gets rebalanced:
    whole connected clusters move at once (the reason ClusterBalancer
    exists, cluster_balancer.cc)."""
    from kaminpar_tpu.parallel import dist_cluster_balance
    from kaminpar_tpu.graphs.host import from_edge_list

    # two dense-ish communities joined weakly; both start in block 0
    rng = np.random.default_rng(7)
    n_half = 32
    edges, weights = [], []
    for c in range(2):
        base = c * n_half
        for i in range(n_half):
            for j in rng.choice(n_half, size=4, replace=False):
                if i != j:
                    edges.append((base + i, base + j))
                    weights.append(10)
    edges.append((0, n_half))  # weak bridge
    weights.append(1)
    graph = from_edge_list(2 * n_half, np.array(edges), np.array(weights))
    mesh = make_mesh(2)
    dg = dist_graph_from_host(graph, mesh)
    k = 2
    nw = graph.node_weight_array()
    cap = int(np.ceil(nw.sum() / k * 1.1))
    caps = jnp.full((k,), cap, jnp.int32)
    part = np.zeros(dg.n_pad, np.int32)
    bal = np.asarray(dist_cluster_balance(dg, jnp.asarray(part), k, caps, 3))
    bw = np.bincount(bal[: graph.n], weights=nw, minlength=k)
    assert bw.max() <= cap


def test_torus_mesh_runs_dist_pipeline():
    """A true (2, 4) 2D mesh is a drop-in for every dist kernel: all
    collectives name both axes and jax flattens them row-major (the
    grid-alltoall analog, kaminpar-mpi/grid_alltoall.h:1-45)."""
    import numpy as np

    from kaminpar_tpu.graphs.factories import make_grid_graph
    from kaminpar_tpu.parallel import (
        dist_edge_cut,
        dist_graph_from_host,
        dist_lp_cluster,
        make_torus_mesh,
    )

    mesh = make_torus_mesh(2, 4)
    assert mesh.devices.shape == (2, 4)
    assert len({d.id for d in mesh.devices.flat}) == 8
    host = make_grid_graph(8, 8)
    graph = dist_graph_from_host(host, mesh)
    labels = dist_lp_cluster(graph, 8, seed=0)
    part = np.asarray(labels)[: host.n] % 2
    import jax.numpy as jnp

    cut = dist_edge_cut(graph, jnp.asarray(
        np.pad(part, (0, graph.n_pad - host.n)).astype(np.int32)))
    assert 0 < int(cut) <= host.m


@pytest.mark.slow  # alive since the shard_map compat shim (round 12) but past the
# tier-1 870 s budget on the CPU fallback; dist tier-1 coverage lives in
# tests/test_dist_resilience.py / test_dist_chaos.py
def test_dist_quality_tracks_shm():
    """The distributed driver's cut stays within 2x of the shm pipeline
    on the same graph (dist refinement is chunked/bulk-synchronous, so
    exact parity is not expected — the reference makes the same
    trade, dkaminpar vs kaminpar)."""
    from kaminpar_tpu.graphs.factories import make_rmat
    from kaminpar_tpu.graphs.host import host_partition_metrics
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.parallel import dKaMinPar
    from kaminpar_tpu.utils.logger import OutputLevel

    g = make_rmat(1 << 12, 30_000, seed=13)
    shm = KaMinPar("fast")
    shm.set_output_level(OutputLevel.QUIET)
    part_shm = shm.set_graph(g).compute_partition(k=8, epsilon=0.05, seed=1)
    cut_shm = host_partition_metrics(g, part_shm, 8)["cut"]

    dist = dKaMinPar("default", n_devices=4).set_graph(g)
    dist.set_output_level(OutputLevel.QUIET)
    part_dist = dist.compute_partition(k=8, epsilon=0.05, seed=1)
    cut_dist = host_partition_metrics(g, part_dist, 8)["cut"]

    assert cut_dist <= 2 * cut_shm, (cut_dist, cut_shm)


@pytest.mark.parametrize("n_devices", [2, 8])
def test_halo_exchange_delivers_ghost_labels(n_devices):
    """The interface->ghost all_to_all must deliver, for every device,
    exactly the current owned values of its ghost nodes (the
    synchronize_ghost_node_clusters contract) — checked against a direct
    host-side gather through the ghost-id table."""
    from jax.sharding import PartitionSpec as P

    from kaminpar_tpu.parallel.mesh import halo_exchange
    # the version-portable shim (check_vma vs check_rep) the dist
    # kernels route through
    from kaminpar_tpu.parallel.mesh import shard_map_compat as shard_map_fn

    host = make_rmat(1 << 10, 8_000, seed=17)
    mesh = make_mesh(n_devices)
    g = dist_graph_from_host(host, mesh)
    D = n_devices
    n_pad = g.n_pad
    g_loc = g.g_loc
    vals = jnp.asarray(np.arange(n_pad, dtype=np.int32) * 7 + 3)

    def per_device(vals_l, send_idx_l, recv_map_l):
        return halo_exchange(vals_l, send_idx_l, recv_map_l, g_loc)

    from kaminpar_tpu.parallel.mesh import NODE_AXIS

    ghosts = shard_map_fn(
        per_device,
        mesh=mesh,
        in_specs=(P(NODE_AXIS), P(NODE_AXIS), P(NODE_AXIS)),
        out_specs=P(NODE_AXIS),
        check_vma=False,
    )(vals, g.send_idx, g.recv_map)

    ghosts_np = np.asarray(ghosts).reshape(D, g_loc)
    gid_np = np.asarray(g.ghost_gid).reshape(D, g_loc)
    vals_np = np.asarray(vals)
    pad_node = n_pad - 1
    for d in range(D):
        real = gid_np[d] != pad_node
        np.testing.assert_array_equal(
            ghosts_np[d][real], vals_np[gid_np[d][real]]
        )


@pytest.mark.slow  # alive since the shard_map compat shim (round 12) but past the
# tier-1 870 s budget on the CPU fallback; dist tier-1 coverage lives in
# tests/test_dist_resilience.py / test_dist_chaos.py
def test_dist_deep_mode_quality_2_vs_8_devices():
    """DEEP-mode dist driver (k-doubling uncoarsening with block spans,
    per-block extension + mesh refinement — deep_multilevel.cc analog):
    2-device and 8-device runs must land in the same cut class, and both
    within a band of the single-chip pipeline."""
    from kaminpar_tpu import KaMinPar
    from kaminpar_tpu.context import PartitioningMode
    from kaminpar_tpu.parallel import dKaMinPar
    from kaminpar_tpu.parallel.dist_context import (
        create_dist_context_by_preset_name,
    )
    from kaminpar_tpu.utils.logger import OutputLevel

    graph = make_grid_graph(64, 64)
    k, eps = 8, 0.03
    src = graph.edge_sources()
    ew = graph.edge_weight_array()
    nw = graph.node_weight_array()
    cap = int((1 + eps) * np.ceil(nw.sum() / k)) + int(nw.max())

    cuts = {}
    for n_devices in (2, 8):
        ctx = create_dist_context_by_preset_name("default")
        assert ctx.mode == PartitioningMode.DEEP
        part = (
            dKaMinPar(ctx, n_devices=n_devices)
            .set_graph(graph)
            .compute_partition(k=k, epsilon=eps, seed=3)
        )
        bw = np.zeros(k, dtype=np.int64)
        np.add.at(bw, part, nw)
        assert (bw <= cap).all(), f"infeasible at {n_devices} devices"
        cuts[n_devices] = int(ew[part[src] != part[graph.adjncy]].sum() // 2)

    sc = KaMinPar("default")
    sc.set_output_level(OutputLevel.QUIET)
    spart = sc.set_graph(graph).compute_partition(k=k, epsilon=eps, seed=3)
    scut = int(ew[spart[src] != spart[graph.adjncy]].sum() // 2)

    # the cut class is pinned on both mesh sizes: within 2x of each other
    # and within 2x of the single-chip pipeline (+ additive slack for the
    # tiny-graph regime)
    assert cuts[2] <= 2 * cuts[8] + 16 and cuts[8] <= 2 * cuts[2] + 16
    for c in cuts.values():
        assert c <= 2 * scut + 16


@pytest.mark.parametrize("n_devices", [2, 8])
def test_sharded_contraction_matches_host(n_devices):
    """The sharded migrate contraction (parallel/dist_contraction.py) must
    produce exactly the coarse graph the host contraction builds — same
    dense relabeling (ascending leader id), same summed edge weights."""
    from kaminpar_tpu.graphs.host import contract_clustering_host
    from kaminpar_tpu.parallel.dist_contraction import (
        dist_contract_clustering,
    )

    graph = make_rmat(1 << 9, 4_000, seed=13)
    rng = np.random.default_rng(1)
    mesh = make_mesh(n_devices)
    dg = dist_graph_from_host(graph, mesh)
    # a plausible clustering: labels point at random neighbors-or-self
    labels = np.arange(dg.n_pad, dtype=np.int64)
    pick = rng.integers(0, graph.n, graph.n)
    merge = rng.random(graph.n) < 0.7
    labels[: graph.n] = np.where(merge, pick, labels[: graph.n])
    # one pointer hop makes most chains collapse like LP leaders do
    labels[: graph.n] = labels[labels[: graph.n]]

    coarse_h, cmap_h = contract_clustering_host(graph, labels[: graph.n])
    coarse_d, cmap_d = dist_contract_clustering(
        dg, graph.n, graph.node_weight_array(), labels
    )
    np.testing.assert_array_equal(cmap_d, cmap_h)
    assert coarse_d.n == coarse_h.n
    np.testing.assert_array_equal(coarse_d.xadj, coarse_h.xadj)
    np.testing.assert_array_equal(
        coarse_d.node_weight_array(), coarse_h.node_weight_array()
    )
    # per-row neighbor/weight sets match (row order may differ)
    for u in range(coarse_h.n):
        lo_h, hi_h = coarse_h.xadj[u], coarse_h.xadj[u + 1]
        lo_d, hi_d = coarse_d.xadj[u], coarse_d.xadj[u + 1]
        h = sorted(zip(coarse_h.adjncy[lo_h:hi_h],
                       coarse_h.edge_weight_array()[lo_h:hi_h]))
        d = sorted(zip(coarse_d.adjncy[lo_d:hi_d],
                       coarse_d.edge_weight_array()[lo_d:hi_d]))
        assert h == d, f"row {u} differs"


def test_dist_pipeline_with_forced_sharded_contraction(monkeypatch):
    """End-to-end dist run with the single-device contraction budget
    forced to zero: every level must go through the sharded migrate
    contraction, and the partition stays feasible."""
    from kaminpar_tpu.parallel import dKaMinPar
    from kaminpar_tpu.parallel import dist_partitioner as dp_mod

    monkeypatch.setattr(dp_mod, "MAX_FUSED_EDGE_SLOTS", 0)
    graph = make_grid_graph(48, 48)
    k, eps = 4, 0.03
    part = (
        dKaMinPar("default", n_devices=8)
        .set_graph(graph)
        .compute_partition(k=k, epsilon=eps, seed=2)
    )
    nw = graph.node_weight_array()
    bw = np.zeros(k, dtype=np.int64)
    np.add.at(bw, part, nw)
    cap = int((1 + eps) * np.ceil(nw.sum() / k)) + int(nw.max())
    assert (bw <= cap).all()


def test_dist_singleton_postpasses_coarsen_low_degree_graphs():
    """Two-hop + isolated post-passes on the dist path
    (label_propagation.h:872-1191 analog): singletons sharing a favored
    cluster merge, isolated nodes pack into weight-capped bins."""
    from kaminpar_tpu.graphs.factories import make_isolated_graph, make_star
    from kaminpar_tpu.parallel.dist_lp import dist_singleton_postpasses

    # star: LP can cap-out the hub cluster, leaving leaf singletons that
    # all favor the hub's cluster -> two-hop merges them
    g = make_star(33)
    labels = np.arange(64, dtype=np.int64)  # everything singleton
    out = dist_singleton_postpasses(g, labels, max_cluster_weight=8)
    lab = out[: g.n]
    nclusters = len(np.unique(lab))
    assert nclusters < g.n  # merged something
    cw = np.zeros(g.n, dtype=np.int64)
    np.add.at(cw, lab, g.node_weight_array())
    assert cw.max() <= 8

    # isolated nodes pack under the cap
    gi = make_isolated_graph(12)
    labels = np.arange(32, dtype=np.int64)
    out = dist_singleton_postpasses(gi, labels, max_cluster_weight=4)
    lab = out[: gi.n]
    cw = np.zeros(gi.n, dtype=np.int64)
    np.add.at(cw, lab, gi.node_weight_array())
    assert cw.max() <= 4
    assert len(np.unique(lab)) <= 4  # 12 unit nodes / cap 4 -> >= 3 bins


def test_dist_singleton_postpasses_weighted_and_multibin():
    """Cap exactness for non-unit weights, and multi-bin packing within a
    favored group (both were bugs caught in review)."""
    from kaminpar_tpu.graphs.factories import make_isolated_graph, make_star
    from kaminpar_tpu.parallel.dist_lp import dist_singleton_postpasses

    gi = make_isolated_graph(4)
    gi.node_weights = np.full(4, 3, dtype=np.int64)
    out = dist_singleton_postpasses(gi, np.arange(8, dtype=np.int64), 4)
    cw = np.zeros(8, np.int64)
    np.add.at(cw, out[:4], gi.node_weights)
    assert cw.max() <= 4  # 3+3 > 4: no pair may form

    g = make_star(20)
    out = dist_singleton_postpasses(g, np.arange(32, dtype=np.int64), 4)
    ncl = len(np.unique(out[: g.n]))
    assert ncl <= 8  # leaves pack into multiple cap-4 bins, not one prefix


# -- DistributedCompressedGraph analog ---------------------------------------


def _dist_graph_fields_equal(a, b):
    for f in ("src", "dst", "edge_w", "node_w", "dst_local", "ghost_gid",
              "send_idx", "recv_map"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f)), err_msg=f
        )
    assert int(a.n) == int(b.n) and int(a.m) == int(b.m)


@pytest.mark.parametrize("n_devices", [2, 8])
def test_dist_graph_from_compressed_matches_host(n_devices):
    """Sharded ingestion from the compressed stream must be bitwise
    identical to sharding the decoded graph
    (distributed_compressed_graph.h parity contract)."""
    from kaminpar_tpu.graphs.compressed import compress_host_graph
    from kaminpar_tpu.parallel import dist_graph_from_compressed

    g = make_rmat(1 << 10, 8000, seed=11)
    cg = compress_host_graph(g)
    mesh = make_mesh(n_devices)
    a = dist_graph_from_compressed(cg, mesh)
    b = dist_graph_from_host(cg.decode(), mesh)
    _dist_graph_fields_equal(a, b)


def test_dist_graph_from_compressed_weighted_edges():
    from kaminpar_tpu.graphs.compressed import compress_host_graph
    from kaminpar_tpu.graphs.factories import make_grid_graph
    from kaminpar_tpu.graphs.host import HostGraph
    from kaminpar_tpu.parallel import dist_graph_from_compressed

    base = make_grid_graph(16, 16)
    rng = np.random.default_rng(3)
    # weight each undirected edge consistently in both directions
    src = base.edge_sources()
    lo = np.minimum(src, base.adjncy)
    hi = np.maximum(src, base.adjncy)
    ew = ((lo * 31 + hi * 7) % 9 + 1).astype(np.int64)
    g = HostGraph(base.xadj, base.adjncy, edge_weights=ew)
    cg = compress_host_graph(g)
    mesh = make_mesh(4)
    a = dist_graph_from_compressed(cg, mesh)
    b = dist_graph_from_host(cg.decode(), mesh)
    _dist_graph_fields_equal(a, b)


@pytest.mark.slow  # alive since the shard_map compat shim (round 12) but past the
# tier-1 870 s budget on the CPU fallback; dist tier-1 coverage lives in
# tests/test_dist_resilience.py / test_dist_chaos.py
def test_dkaminpar_partitions_compressed_via_shard_streaming(monkeypatch):
    """dKaMinPar keeps a compressed input compressed: the finest-level
    ingestion must go through dist_graph_from_compressed (the graph is
    large enough to coarsen, so the branch actually runs)."""
    from kaminpar_tpu.graphs.compressed import compress_host_graph
    from kaminpar_tpu.parallel import dKaMinPar, dist_partitioner
    from kaminpar_tpu.utils.logger import OutputLevel

    calls = []
    real = dist_partitioner.dist_graph_from_compressed
    monkeypatch.setattr(
        dist_partitioner, "dist_graph_from_compressed",
        lambda *a, **kw: (calls.append(1), real(*a, **kw))[1],
    )
    g = make_rmat(1 << 13, 60000, seed=5)
    cg = compress_host_graph(g)
    solver = dKaMinPar("default", mesh=make_mesh(4))
    solver.set_output_level(OutputLevel.QUIET)
    part = solver.set_graph(cg).compute_partition(k=4, epsilon=0.03, seed=1)
    assert calls, "compressed ingestion branch never ran"
    assert part.shape == (g.n,)
    nw = g.node_weight_array()
    bw = np.zeros(4, dtype=np.int64)
    np.add.at(bw, part, nw)
    cap = (1 + 0.03) * np.ceil(nw.sum() / 4)
    assert bw.max() <= cap


@pytest.mark.slow  # alive since the shard_map compat shim (round 12) but past the
# tier-1 870 s budget on the CPU fallback; dist tier-1 coverage lives in
# tests/test_dist_resilience.py / test_dist_chaos.py
def test_dkaminpar_compressed_kway_sharded_never_materializes(monkeypatch):
    """In the terapart regime (kway mode + sharded contraction + no
    singleton post-pass firing) the plain fine CSR must never exist:
    decode() is patched to raise."""
    from kaminpar_tpu.graphs.compressed import (
        CompressedHostGraph,
        compress_host_graph,
    )
    from kaminpar_tpu.parallel import dKaMinPar, dist_partitioner
    from kaminpar_tpu.context import PartitioningMode
    from kaminpar_tpu.utils.logger import OutputLevel

    g = make_rmat(1 << 13, 60000, seed=5)
    cg = compress_host_graph(g)
    # force the sharded contraction path (graph "above" the budget)
    monkeypatch.setattr(dist_partitioner, "MAX_FUSED_EDGE_SLOTS", 1)

    def boom(self):
        raise AssertionError("fine CSR materialized on the compressed path")

    monkeypatch.setattr(CompressedHostGraph, "decode", boom)
    solver = dKaMinPar("default", mesh=make_mesh(4))
    solver.ctx.mode = PartitioningMode.KWAY
    solver.set_output_level(OutputLevel.QUIET)
    part = solver.set_graph(cg).compute_partition(k=4, epsilon=0.03, seed=1)
    assert part.shape == (g.n,)
    assert set(np.unique(part)) <= set(range(4))


@pytest.mark.slow  # alive since the shard_map compat shim (round 12) but past the
# tier-1 870 s budget on the CPU fallback; dist tier-1 coverage lives in
# tests/test_dist_resilience.py / test_dist_chaos.py
def test_dkaminpar_copy_graph_clears_compressed_state():
    """Regression: copy_graph after a compressed set_graph must not
    leave the stale compressed topology driving the finest level."""
    from kaminpar_tpu.graphs.compressed import compress_host_graph
    from kaminpar_tpu.parallel import dKaMinPar
    from kaminpar_tpu.utils.logger import OutputLevel

    a = make_rmat(1 << 13, 60000, seed=1)
    b = make_rmat(1 << 13, 60000, seed=2)
    solver = dKaMinPar("default", mesh=make_mesh(2))
    solver.set_output_level(OutputLevel.QUIET)
    solver.set_graph(compress_host_graph(a))
    p1 = solver.compute_partition(k=4, epsilon=0.03, seed=1)
    solver.copy_graph(None, b.xadj, b.adjncy, adjwgt=b.edge_weights)
    p2 = solver.compute_partition(k=4, epsilon=0.03, seed=1)
    fresh = dKaMinPar("default", mesh=make_mesh(2))
    fresh.set_output_level(OutputLevel.QUIET)
    p3 = fresh.set_graph(b).compute_partition(k=4, epsilon=0.03, seed=1)
    np.testing.assert_array_equal(p2, p3)
    assert p1.shape == (a.n,)


def test_sharded_contraction_star_skew(monkeypatch):
    """Skew-proofing (global_cluster_contraction.cc:1100+ handles
    arbitrary coarse-node distributions): contracting a clustering whose
    coarse graph is a STAR — every coarse edge is incident to one hub —
    must not overflow the migrate buckets.  Hash-bucketed pairs spread
    the hub's rows across all devices (cv varies); the old cu-ownership
    chunking sent every row to the hub's owner and raised.  Buckets are
    pinched tight so concentration would overflow."""
    from kaminpar_tpu.graphs.factories import make_star
    from kaminpar_tpu.graphs.host import contract_clustering_host
    from kaminpar_tpu.parallel import dist_contraction as dc_mod
    from kaminpar_tpu.parallel.dist_contraction import (
        dist_contract_clustering,
    )

    n = 1 << 13
    g = make_star(n - 1)  # hub 0 + (n-1) leaves
    mesh = make_mesh(8)
    dg = dist_graph_from_host(g, mesh)
    # singleton clustering: the coarse graph IS the star
    labels = np.arange(dg.n_pad, dtype=np.int64)
    # tight buckets: per-peer capacity ~m_loc/2 per device pair; the
    # hub-owner flood of the old scheme (~m_loc rows/peer) would raise
    monkeypatch.setattr(dc_mod, "BUCKET_MIN", 1 << 10)
    dc_mod._dist_contract_edges_impl.clear_cache()
    try:
        coarse_d, cmap_d = dist_contract_clustering(
            dg, g.n, g.node_weight_array(), labels
        )
    finally:
        dc_mod._dist_contract_edges_impl.clear_cache()
    coarse_h, cmap_h = contract_clustering_host(
        g, labels[: g.n]
    )
    np.testing.assert_array_equal(cmap_d, cmap_h)
    np.testing.assert_array_equal(coarse_d.xadj, coarse_h.xadj)
    np.testing.assert_array_equal(coarse_d.adjncy, coarse_h.adjncy)


def test_sharded_contraction_powerlaw_skew(monkeypatch):
    """Power-law clustering sharded over 8 devices: cluster sizes follow
    a heavy-tailed distribution (a few giant clusters absorb most
    nodes), so a handful of coarse nodes carry most coarse edges.  Must
    contract without the overflow escape hatch and match the host
    contraction exactly."""
    from kaminpar_tpu.graphs.host import contract_clustering_host
    from kaminpar_tpu.parallel import dist_contraction as dc_mod
    from kaminpar_tpu.parallel.dist_contraction import (
        dist_contract_clustering,
    )

    g = make_rmat(1 << 12, 60_000, seed=5)
    mesh = make_mesh(8)
    dg = dist_graph_from_host(g, mesh)
    rng = np.random.default_rng(11)
    # zipf-ish cluster assignment: cluster c gets ~1/(c+1)^1.2 of nodes
    ncl = 64
    p = 1.0 / np.arange(1, ncl + 1) ** 1.2
    cl = rng.choice(ncl, size=g.n, p=p / p.sum())
    # labels must be leader node ids (min node of each cluster)
    leaders = np.full(ncl, -1, dtype=np.int64)
    for c in range(ncl):
        members = np.flatnonzero(cl == c)
        if len(members):
            leaders[c] = members[0]
    labels = np.arange(dg.n_pad, dtype=np.int64)
    labels[: g.n] = leaders[cl]
    monkeypatch.setattr(dc_mod, "BUCKET_MIN", 1 << 10)
    dc_mod._dist_contract_edges_impl.clear_cache()
    try:
        coarse_d, cmap_d = dist_contract_clustering(
            dg, g.n, g.node_weight_array(), labels
        )
    finally:
        dc_mod._dist_contract_edges_impl.clear_cache()
    coarse_h, cmap_h = contract_clustering_host(g, labels[: g.n])
    np.testing.assert_array_equal(cmap_d, cmap_h)
    np.testing.assert_array_equal(coarse_d.xadj, coarse_h.xadj)
    for u in range(coarse_h.n):
        lo_h, hi_h = coarse_h.xadj[u], coarse_h.xadj[u + 1]
        lo_d, hi_d = coarse_d.xadj[u], coarse_d.xadj[u + 1]
        h = sorted(zip(coarse_h.adjncy[lo_h:hi_h],
                       coarse_h.edge_weight_array()[lo_h:hi_h]))
        d = sorted(zip(coarse_d.adjncy[lo_d:hi_d],
                       coarse_d.edge_weight_array()[lo_d:hi_d]))
        assert h == d, f"row {u} differs"


@pytest.mark.slow  # alive since the shard_map compat shim (round 12) but past the
# tier-1 870 s budget on the CPU fallback; dist tier-1 coverage lives in
# tests/test_dist_resilience.py / test_dist_chaos.py
def test_mesh_subgroup_replication_fires_and_stays_feasible():
    """Mesh-subgroup replication (deep_multilevel.cc:79-153 +
    replicator.cc analog): once the graph drops below
    replication_min_nodes_per_device * D, G replicas coarsen as one
    block-diagonal union over the mesh, each replica gets its own IP,
    and the best replica's partition continues the main uncoarsening.
    The partition must stay feasible and the phase must actually fire."""
    from kaminpar_tpu.parallel import dKaMinPar
    from kaminpar_tpu.parallel.dist_context import (
        create_dist_context_by_preset_name,
    )

    ctx = create_dist_context_by_preset_name("default")
    ctx.shm.coarsening.contraction_limit = 200
    ctx.replication_min_nodes_per_device = 2048
    k, eps = 4, 0.03
    g = make_grid_graph(48, 48)
    dp = dKaMinPar(ctx, n_devices=8).set_graph(g)
    part = dp.compute_partition(k=k, epsilon=eps, seed=2)
    info = dp._replication_info
    assert info is not None and info["G"] > 1, info
    assert info["best_replica"] >= 0
    nw = g.node_weight_array()
    bw = np.zeros(k, dtype=np.int64)
    np.add.at(bw, part, nw)
    cap = int((1 + eps) * np.ceil(nw.sum() / k)) + int(nw.max())
    assert (bw <= cap).all(), bw


def test_replication_union_helpers():
    """union_graph / replica_bounds / slice_replica round-trip."""
    from kaminpar_tpu.graphs.host import contract_clustering_host
    from kaminpar_tpu.parallel.replication import (
        choose_replication_factor,
        replica_bounds_after_contraction,
        slice_replica,
        union_graph,
    )

    g = make_rmat(1 << 8, 2_000, seed=2)
    G = 4
    u = union_graph(g, G)
    assert u.n == G * g.n and u.m == G * g.m
    # each component slices back to the original graph
    for r in range(G):
        sub = slice_replica(u, r * g.n, (r + 1) * g.n)
        np.testing.assert_array_equal(sub.xadj, g.xadj)
        np.testing.assert_array_equal(sub.adjncy, g.adjncy)
    # contraction of a per-replica clustering keeps replica coarse-id
    # ranges contiguous
    labels = np.arange(u.n, dtype=np.int64)
    labels[: g.n] = labels[: g.n] // 2 * 2  # pair up replica 0 only
    coarse, cmap = contract_clustering_host(u, labels)
    bounds = replica_bounds_after_contraction(
        cmap, [r * g.n for r in range(G + 1)]
    )
    assert bounds[0] == 0 and bounds[-1] == coarse.n
    assert all(bounds[i] <= bounds[i + 1] for i in range(G))
    # replication factor: restores min nodes/device, power of two, <= D
    assert choose_replication_factor(10_000, 8, 2048) == 2
    assert choose_replication_factor(3_000, 8, 2048) == 8
    assert choose_replication_factor(100_000, 8, 2048) == 1
    assert choose_replication_factor(1_000, 1, 2048) == 1


@pytest.mark.slow  # alive since the shard_map compat shim (round 12) but past the
# tier-1 870 s budget on the CPU fallback; dist tier-1 coverage lives in
# tests/test_dist_resilience.py / test_dist_chaos.py
def test_dist_deep_k64_quality_vs_shm():
    """dist deep at k=64 must land within 10% of the shm pipeline on the
    same graph (the extend-on-mesh + replication lineage carries real
    multilevel bipartitions per block; VERDICT r3 item 8)."""
    from kaminpar_tpu import KaMinPar
    from kaminpar_tpu.parallel import dKaMinPar
    from kaminpar_tpu.utils.logger import OutputLevel

    graph = make_rmat(1 << 13, 120_000, seed=6)
    k, eps = 64, 0.03
    nw = graph.node_weight_array()
    src = graph.edge_sources()
    ew = graph.edge_weight_array()
    cap = int((1 + eps) * np.ceil(nw.sum() / k)) + int(nw.max())

    part = (
        dKaMinPar("default", n_devices=8)
        .set_graph(graph)
        .compute_partition(k=k, epsilon=eps, seed=3)
    )
    bw = np.zeros(k, dtype=np.int64)
    np.add.at(bw, part, nw)
    assert (bw <= cap).all()
    dist_cut = int(ew[part[src] != part[graph.adjncy]].sum() // 2)

    sc = KaMinPar("default")
    sc.set_output_level(OutputLevel.QUIET)
    spart = sc.set_graph(graph).compute_partition(k=k, epsilon=eps, seed=3)
    shm_cut = int(ew[spart[src] != spart[graph.adjncy]].sum() // 2)
    assert dist_cut <= 1.10 * shm_cut + 16, (dist_cut, shm_cut)


def test_make_mesh_2d_honors_explicit_devices():
    """The (rows, cols) path must use the caller's device selection and
    order, not silently rebuild from jax.devices()."""
    import jax

    devs = list(jax.devices()[:8])[::-1]
    mesh = make_mesh((2, 4), devices=devs)
    assert mesh.devices.shape == (2, 4)
    assert [d.id for d in mesh.devices.flat] == [d.id for d in devs]
