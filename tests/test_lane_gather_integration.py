"""Lane-routed rating integration: LP/Jet results must be BITWISE
identical to the unrouted engines.

The routed paths change only the ORDER in which (owner, label, weight)
triples reach the rating reductions; every reduction involved (sort by
owner+label, integer group totals, segment_sum, cumsum-diff spans) is
order-independent, so routing must not change a single label.  On CPU
the Pallas kernel runs in interpreter mode.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import kaminpar_tpu.ops.lane_gather as lg
from kaminpar_tpu.context import JetRefinementContext
from kaminpar_tpu.graphs import device_graph_from_host, factories
from kaminpar_tpu.ops import metrics
from kaminpar_tpu.ops.jet import jet_refine
from kaminpar_tpu.ops.lp import LPConfig, lp_cluster, lp_refine


from contextlib import contextmanager


@contextmanager
def unrouted():
    """Force the plain path for the comparison run (saves/restores any
    pre-set opt-out so the fixture's routed runs stay routed)."""
    import os

    prev = os.environ.get("KAMINPAR_TPU_LANE_GATHER")
    os.environ["KAMINPAR_TPU_LANE_GATHER"] = "0"
    try:
        yield
    finally:
        if prev is None:
            del os.environ["KAMINPAR_TPU_LANE_GATHER"]
        else:
            os.environ["KAMINPAR_TPU_LANE_GATHER"] = prev


@pytest.fixture
def routed(monkeypatch):
    monkeypatch.delenv("KAMINPAR_TPU_LANE_GATHER", raising=False)
    monkeypatch.setattr(lg, "INTERPRET", True)
    monkeypatch.setattr(lg, "MIN_EDGE_SLOTS", 0)
    # the blowup cap would send these tiny skewed test graphs to the XLA
    # fallback (making the routed/unrouted comparison vacuous): lift it
    monkeypatch.setattr(lg, "PLAN_MAX_SLOT_RATIO", float("inf"))
    monkeypatch.setattr(lg, "lane_gather_supported", lambda: True)
    lg.clear_plan_cache()
    yield
    lg.clear_plan_cache()


def _graph():
    return device_graph_from_host(factories.make_rmat(1 << 10, 8000, seed=5))


def test_lp_cluster_routed_is_bitwise_identical(routed):
    dg = _graph()
    routed_labels = np.asarray(lp_cluster(dg, jnp.int32(64), jnp.int32(3)))
    lg.clear_plan_cache()
    with unrouted():
        plain_labels = np.asarray(
            lp_cluster(dg, jnp.int32(64), jnp.int32(3))
        )
    np.testing.assert_array_equal(routed_labels, plain_labels)


def test_lp_refine_routed_is_bitwise_identical(routed):
    dg = _graph()
    k = 8
    rng = np.random.default_rng(0)
    part = np.zeros(dg.n_pad, np.int32)
    part[: dg.n] = rng.integers(0, k, dg.n)
    part = jnp.asarray(part)
    nw = int(np.asarray(dg.node_w).sum())
    cap = jnp.full(k, int(1.1 * nw / k) + 1, dtype=jnp.int32)
    cfg = LPConfig(num_iterations=3, refinement=True, allow_tie_moves=False)

    out_r = np.asarray(lp_refine(dg, part, k, cap, jnp.int32(2), cfg))
    lg.clear_plan_cache()
    with unrouted():
        out_p = np.asarray(lp_refine(dg, part, k, cap, jnp.int32(2), cfg))
    np.testing.assert_array_equal(out_r, out_p)


def test_jet_routed_is_bitwise_identical(routed):
    dg = _graph()
    k = 8
    rng = np.random.default_rng(1)
    part = np.zeros(dg.n_pad, np.int32)
    part[: dg.n] = rng.integers(0, k, dg.n)
    part = jnp.asarray(part)
    nw = int(np.asarray(dg.node_w).sum())
    cap = jnp.full(k, int(1.2 * nw / k) + 1, dtype=jnp.int32)
    ctx = JetRefinementContext()

    out_r = np.asarray(jet_refine(dg, part, k, cap, jnp.int32(4), ctx))
    lg.clear_plan_cache()
    with unrouted():
        out_p = np.asarray(jet_refine(dg, part, k, cap, jnp.int32(4), ctx))
    np.testing.assert_array_equal(out_r, out_p)
    assert int(metrics.edge_cut(dg, jnp.asarray(out_r))) <= int(
        metrics.edge_cut(dg, part)
    )


def test_contraction_routed_is_bitwise_identical(routed):
    from kaminpar_tpu.ops.contraction import contract_clustering
    from kaminpar_tpu.ops.lp import lp_cluster

    dg = _graph()
    labels = lp_cluster(dg, jnp.int32(64), jnp.int32(9))
    cg_r, n_r, m_r = contract_clustering(dg, labels)
    lg.clear_plan_cache()
    with unrouted():
        cg_p, n_p, m_p = contract_clustering(dg, labels)
    assert (n_r, m_r) == (n_p, m_p)
    np.testing.assert_array_equal(
        np.asarray(cg_r.cmap), np.asarray(cg_p.cmap)
    )
    for field in ("row_ptr", "src", "dst", "edge_w", "node_w"):
        np.testing.assert_array_equal(
            np.asarray(getattr(cg_r.graph, field)),
            np.asarray(getattr(cg_p.graph, field)),
            err_msg=field,
        )
