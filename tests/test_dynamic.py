"""Dynamic repartitioning: sessions, delta ingestion, warm v-cycles.

Covers the PR-15 subsystem (kaminpar_tpu/dynamic/): DeltaBatch
validation through the GraphFormatError taxonomy, the padded-bucket
in-place/rebuild CSR patch path (incl. the `dynamic-apply` chaos
site), the delta-chain identity (no full re-hash per mutate, no
aliasing against plain graph digests), neighbor-majority seeding, the
warm/cold/replica decision + the PR-4 diff cut gate, mid-chain
kill-and-resume cut-identity (the KAMINPAR_TPU_STOP_AT hard-kill
idiom), the serving session request kinds, the schema-v11 `dynamic`
report section, and the per-bucket pad-slack surfacing.
"""

from __future__ import annotations

import importlib.util
import json
import os

import numpy as np
import pytest

from kaminpar_tpu import caching, resilience, telemetry
from kaminpar_tpu.dynamic import (
    DeltaBatch,
    GraphSession,
    random_delta_batch,
    repartition,
    run_chain,
    seed_new_vertices,
    summarize,
    synth_chain,
)
from kaminpar_tpu.graphs.factories import make_rgg2d
from kaminpar_tpu.graphs.host import (
    from_edge_list,
    host_partition_metrics,
    validate as validate_graph,
)
from kaminpar_tpu.io.errors import GraphFormatError
from kaminpar_tpu.kaminpar import KaMinPar
from kaminpar_tpu.presets import create_context_by_preset_name
from kaminpar_tpu.resilience import checkpoint as ckpt_mod
from kaminpar_tpu.resilience.checkpoint import (
    SimulatedPreemption,
    graph_fingerprint,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N, K = 1024, 4


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(ckpt_mod.STOP_AT_ENV, raising=False)
    monkeypatch.delenv(resilience.FAULTS_ENV_VAR, raising=False)
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    yield
    resilience.reset()
    telemetry.disable()
    telemetry.reset()


def _graph():
    return make_rgg2d(N, avg_degree=8, seed=3)


def _tiny():
    # path 0-1-2-3 plus a triangle 3-4-5-3
    return from_edge_list(6, np.array(
        [[0, 1], [1, 2], [2, 3], [3, 4], [4, 5], [5, 3]]))


_PART_CACHE = {}


def _partitioned_session(session_id="s", k=K, seed=1):
    """A session over the shared test graph with a committed initial
    partition (the expensive cold run is computed once per module)."""
    g = _graph()
    key = (k, seed)
    if key not in _PART_CACHE:
        ctx = create_context_by_preset_name("default")
        solver = KaMinPar(ctx)
        solver.set_output_level(0)
        solver.set_graph(g)
        part = solver.compute_partition(k=k, seed=seed)
        cut = int(host_partition_metrics(g, part, k)["cut"])
        _PART_CACHE[key] = (np.asarray(part, dtype=np.int32), cut)
    part, cut = _PART_CACHE[key]
    s = GraphSession(session_id, g, k=k)
    s.commit_partition(part.copy(), cut, gate_valid=True)
    return s


# ---------------------------------------------------------------------------
# DeltaBatch validation (io.GraphFormatError taxonomy)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("delta,frag", [
    ({"edge_inserts": [[0, 99]]}, "out of range"),
    ({"edge_inserts": [[2, 2]]}, "self loops"),
    ({"edge_inserts": [[0, 1]]}, "already exists"),
    ({"edge_inserts": [[0, 4], [4, 0]]}, "duplicate pair"),
    ({"edge_deletes": [[0, 5]]}, "does not exist"),
    ({"edge_deletes": [[0, 1], [1, 0]]}, "duplicate pair"),
    ({"edge_weight_updates": [[0, 5]], "update_weights": [2]},
     "does not exist"),
    ({"edge_weight_updates": [[0, 1]]}, "requires update_weights"),
    ({"edge_inserts": [[0, 4]], "insert_weights": [0]}, ">= 1"),
    ({"vertex_removes": [9]}, "out of range"),
    ({"vertex_removes": [1, 1]}, "duplicate"),
    ({"node_weight_updates": [[1, 0]]}, ">= 1"),
    ({"vertex_adds": -1}, ">= 0"),
    ({"bogus_key": 1}, "unknown delta key"),
])
def test_delta_validation_errors(delta, frag):
    s = GraphSession("v", _tiny(), k=2)
    with pytest.raises(GraphFormatError) as ei:
        s.apply(DeltaBatch.from_dict(delta))
    assert frag in str(ei.value)


def test_failed_apply_leaves_session_untouched():
    s = GraphSession("v", _tiny(), k=2)
    chain0, n0, m0 = s.chain, s.graph.n, s.graph.m
    with pytest.raises(GraphFormatError):
        s.apply(DeltaBatch.from_dict({"edge_deletes": [[0, 5]]}))
    assert (s.chain, s.graph.n, s.graph.m) == (chain0, n0, m0)
    assert s.deltas_applied == 0


# ---------------------------------------------------------------------------
# the CSR patch path
# ---------------------------------------------------------------------------


def test_patch_matches_rebuilt_graph():
    s = GraphSession("p", _tiny(), k=2)
    s.apply(DeltaBatch.from_dict({
        "edge_inserts": [[0, 2], [1, 6]],  # 6 = the added vertex
        "insert_weights": [3, 1],
        "edge_deletes": [[3, 4]],
        "edge_weight_updates": [[0, 1]],
        "update_weights": [7],
        "vertex_adds": 1,
        "node_weight_updates": [[2, 5]],
    }))
    g = s.graph
    validate_graph(g)
    # expected: the same edge set built from scratch
    expected = from_edge_list(7, np.array(
        [[0, 1], [1, 2], [2, 3], [4, 5], [5, 3], [0, 2], [1, 6]]),
        edge_weights=np.array([7, 1, 1, 1, 1, 3, 1]),
        node_weights=np.array([1, 1, 5, 1, 1, 1, 1]),
    )
    assert np.array_equal(g.xadj, expected.xadj)
    assert np.array_equal(g.adjncy, expected.adjncy)
    assert np.array_equal(g.edge_weight_array(),
                          expected.edge_weight_array())
    assert np.array_equal(g.node_weight_array(),
                          expected.node_weight_array())


def test_vertex_remove_compacts_and_remaps_partition():
    s = GraphSession("p", _tiny(), k=2)
    s.commit_partition(np.array([0, 0, 0, 1, 1, 1], dtype=np.int32),
                       cut=1)
    s.apply(DeltaBatch.from_dict({"vertex_removes": [1]}))
    g = s.graph
    assert g.n == 5
    validate_graph(g)
    # old 2..5 shift down to 1..4; edges 0-1(old 0-1? removed), the
    # old (1,2) edge is gone with vertex 1
    assert np.array_equal(s.partition, np.array([0, 0, 1, 1, 1]))
    # the removed vertex's incident edge mass left the graph
    assert g.m == 2 * 4  # path 1-2 gone, 0 isolated: 2-3,3-4,4-5? ->
    # remaining undirected edges: (2,3),(3,4),(4,5),(5,3) minus none
    # = 4 edges, stored twice


def test_reinsert_after_delete_in_one_batch():
    s = GraphSession("p", _tiny(), k=2)
    s.apply(DeltaBatch.from_dict({
        "edge_deletes": [[0, 1]],
        "edge_inserts": [[0, 1]],
        "insert_weights": [9],
    }))
    g = s.graph
    w = g.edge_weight_array()[
        (g.edge_sources() == 0) & (g.adjncy == 1)]
    assert list(w) == [9]


def test_in_place_vs_rebuild_bucket_accounting():
    s = GraphSession("b", _graph(), k=K)
    epoch0 = s.device_epoch
    info = s.apply(random_delta_batch(s.graph, seed=5, edge_churn=0.005))
    assert info["in_place"] and s.in_place == 1 and s.rebuilds == 0
    assert s.device_epoch == epoch0
    # a delta past the padded edge bucket's slack must rebuild
    m_pad = caching.pad_size(max(s.graph.m, 1))
    need = (m_pad - s.graph.m) // 2 + 8
    big = random_delta_batch(
        s.graph, seed=6,
        edge_churn=float(need + 1) / max(s.graph.m // 2, 1),
        insert_frac=1.0)
    info2 = s.apply(big)
    assert not info2["in_place"] and s.rebuilds == 1
    assert s.device_epoch == epoch0 + 1
    # executable-identity accounting: the in-place commit was a bucket
    # hit, the crossing a miss
    stats = s.tracker.stats()
    assert stats["hits"] >= 1 and stats["misses"] >= 2


def test_dynamic_apply_fault_forces_rebuild(monkeypatch):
    monkeypatch.setenv(resilience.FAULTS_ENV_VAR, "dynamic-apply:nth=1")
    s = GraphSession("c", _graph(), k=K)
    info = s.apply(random_delta_batch(s.graph, seed=5, edge_churn=0.005))
    assert not info["in_place"] and s.rebuilds == 1 and s.in_place == 0
    deg = [e for e in telemetry.events("degraded")
           if e.attrs.get("site") == "dynamic-apply"]
    assert deg and deg[0].attrs.get("injected")


# ---------------------------------------------------------------------------
# the delta-chain identity (satellite: no O(m) re-hash, no aliasing)
# ---------------------------------------------------------------------------


def test_chain_hash_deterministic_and_sensitive():
    g1, g2 = _tiny(), _tiny()
    a, b = GraphSession("a", g1, k=2), GraphSession("b", g2, k=2)
    assert a.digest() == b.digest()
    d1 = DeltaBatch.from_dict({"edge_inserts": [[0, 3]]})
    d2 = DeltaBatch.from_dict({"edge_inserts": [[0, 4]]})
    a.apply(d1)
    b.apply(DeltaBatch.from_dict({"edge_inserts": [[0, 3]]}))
    assert a.digest() == b.digest()
    c = GraphSession("c", _tiny(), k=2)
    c.apply(d2)
    assert c.digest() != a.digest()


def test_chain_digest_never_aliases_plain_digests():
    """The anti-aliasing guard: a (possibly poisoned) chain digest
    lives in the `dyn:`-prefixed domain, plain full_graph_digest
    values are bare hex — no differing graph's exact digest can ever
    equal a session's chain identity."""
    s = GraphSession("a", _tiny(), k=2)
    s.apply(DeltaBatch.from_dict({"edge_inserts": [[0, 3]]}))
    assert s.digest().startswith("dyn:")
    other = caching.full_graph_digest(make_rgg2d(256, avg_degree=4,
                                                 seed=1))
    assert not other.startswith("dyn:")
    assert s.digest() != other
    # poisoning the chain keeps it in the dyn: domain via the stamp
    assert caching.full_graph_digest(s.graph) == s.digest()


def test_mutate_digest_is_chain_not_rehash():
    """full_graph_digest on a session graph reads the stamped chain —
    the digest must change with the chain even though the adjacency
    bytes also changed, and must NOT equal the raw re-hash (which
    would mean the O(m) sweep ran)."""
    s = GraphSession("a", _graph(), k=K)
    s.apply(random_delta_batch(s.graph, seed=5, edge_churn=0.005))
    stamped = caching.full_graph_digest(s.graph)
    assert stamped == s.digest()
    # the raw adjacency re-hash of the same object (stamp removed)
    raw_copy = from_edge_list(s.graph.n, np.stack(
        [s.graph.edge_sources(), s.graph.adjncy], axis=1),
        edge_weights=s.graph.edge_weight_array(), symmetrize=False)
    assert caching.full_graph_digest(raw_copy) != stamped


def test_repartition_points_fork_the_chain():
    """Two histories with the SAME deltas but different repartition
    points must not share an identity (the partition state is part of
    the session's cache identity)."""
    d1 = {"edge_inserts": [[0, 3]]}
    d2 = {"edge_inserts": [[0, 4]]}
    a = GraphSession("a", _tiny(), k=2)
    a.apply(DeltaBatch.from_dict(d1))
    a.commit_partition(np.array([0, 0, 0, 1, 1, 1], np.int32), cut=2)
    a.apply(DeltaBatch.from_dict(d2))
    b = GraphSession("b", _tiny(), k=2)
    b.apply(DeltaBatch.from_dict(d1))
    b.apply(DeltaBatch.from_dict(d2))
    assert a.digest() != b.digest()


def test_session_fingerprint_keys_checkpoints_and_cache():
    s = GraphSession("a", _graph(), k=K)
    assert graph_fingerprint(s.graph) == s.fingerprint()
    ctx = create_context_by_preset_name("default")
    key0 = caching.result_cache_key(s.graph, ctx)
    s.apply(random_delta_batch(s.graph, seed=5, edge_churn=0.005))
    key1 = caching.result_cache_key(s.graph, ctx)
    assert key0 != key1 and key0[1] == key1[1]
    assert graph_fingerprint(s.graph) == s.fingerprint()


# ---------------------------------------------------------------------------
# seeding + the warm/cold/replica policy
# ---------------------------------------------------------------------------


def test_seed_new_vertices_majority_and_fill():
    g = from_edge_list(7, np.array(
        [[0, 1], [1, 2], [3, 4], [4, 5], [2, 6], [1, 6]]))
    part = np.array([0, 0, 0, 1, 1, 1, -1], dtype=np.int32)
    seeded, cnt = seed_new_vertices(g, part, k=2)
    assert cnt == 1 and seeded[6] == 0  # both neighbors in block 0
    # an isolated newcomer falls back to headroom fill
    g2 = from_edge_list(5, np.array([[0, 1], [2, 3]]))
    part2 = np.array([0, 0, 1, 1, -1], dtype=np.int32)
    seeded2, cnt2 = seed_new_vertices(
        g2, part2, k=2, max_block_weights=np.array([3, 3]))
    assert cnt2 == 1 and seeded2[4] in (0, 1)
    # a chain of newcomers resolves over the bounded passes
    g3 = from_edge_list(4, np.array([[0, 1], [1, 2], [2, 3]]))
    part3 = np.array([0, 0, -1, -1], dtype=np.int32)
    seeded3, _ = seed_new_vertices(g3, part3, k=2)
    assert (seeded3 >= 0).all()


def test_warm_decision_low_drift():
    s = _partitioned_session()
    s.apply(random_delta_batch(s.graph, seed=11, edge_churn=0.005))
    ctx = create_context_by_preset_name("default")
    out = repartition(s, ctx, k=K, seed=1)
    assert out.mode == "warm"
    assert out.drift is not None and out.drift < ctx.dynamic.drift_threshold
    assert out.feasible
    # 2 = the committed initial partition + this repartition
    assert s.repartitions == 2 and s.last_cut == out.cut
    ev = [e for e in telemetry.events("dynamic")
          if e.attrs.get("action") == "repartition"]
    assert ev and ev[-1].attrs["mode"] == "warm"


def test_drift_exceeds_threshold_on_uniform_churn():
    """The cheap half of the cold-decision story (the full compute is
    the slow-marked test below): adversarial uniform churn lands above
    the default drift threshold."""
    s = _partitioned_session()
    s.apply(random_delta_batch(s.graph, seed=12, edge_churn=1.0,
                               insert_frac=1.0, uniform_frac=1.0))
    ctx = create_context_by_preset_name("default")
    assert s.drift_estimate() > ctx.dynamic.drift_threshold


@pytest.mark.slow  # a full-size cold run on a churn-doubled graph —
# the decision threshold itself is asserted by the cheap test above
def test_cold_decision_high_drift():
    s = _partitioned_session()
    # adversarial uniform churn at high volume: drift above threshold
    s.apply(random_delta_batch(s.graph, seed=12, edge_churn=1.0,
                               insert_frac=1.0, uniform_frac=1.0))
    ctx = create_context_by_preset_name("default")
    assert s.drift_estimate() > ctx.dynamic.drift_threshold
    out = repartition(s, ctx, k=K, seed=1)
    assert out.mode == "cold" and out.feasible


def test_replica_race_keeps_better_cut():
    s = _partitioned_session()
    s.apply(random_delta_batch(s.graph, seed=13, edge_churn=0.005))
    ctx = create_context_by_preset_name("default")
    ctx.dynamic.replicas = 2
    out = repartition(s, ctx, k=K, seed=1)
    assert out.mode == "replica"
    assert len(out.replica_cuts) == 2
    assert out.cut == min(out.replica_cuts) or out.cut in out.replica_cuts
    assert out.warm_wall_s is not None and out.cold_wall_s is not None


def test_warm_preserves_cut_on_unchanged_graph():
    s = _partitioned_session()
    before = s.last_cut
    ctx = create_context_by_preset_name("default")
    out = repartition(s, ctx, k=K, seed=1)
    assert out.mode == "warm"
    # a refinement-only warm pass over an already-refined partition
    # must not regress the cut past the diff gate
    assert out.cut <= before * (1.0 + ctx.dynamic.cut_gate_threshold)
    assert out.stable is not False or out.escalated


# ---------------------------------------------------------------------------
# chain driver: determinism + mid-chain kill-and-resume (satellite)
# ---------------------------------------------------------------------------


def _chain_ctx(ckpt_dir=None, resume=False):
    ctx = create_context_by_preset_name("default")
    if ckpt_dir is not None:
        ctx.resilience.checkpoint_dir = str(ckpt_dir)
        ctx.resilience.resume = resume
    return ctx


def test_chain_kill_and_resume_cut_identical(tmp_path):
    g = _graph()
    batches = synth_chain(g, steps=3, seed=50, edge_churn=0.01,
                          vertex_adds_every=2)

    # reference: the uninterrupted chain
    part_ref, section_ref = run_chain(
        g, batches, _chain_ctx(tmp_path / "ref"), k=K, seed=1)
    cuts_ref = section_ref["cut_trajectory"]
    assert len(cuts_ref) == 4

    # the same chain, hard-killed at step 1's warm v-cycle barrier
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    os.environ[ckpt_mod.STOP_AT_ENV] = "vcycle:0!"
    try:
        with pytest.raises(SimulatedPreemption):
            run_chain(make_rgg2d(N, avg_degree=8, seed=3), batches,
                      _chain_ctx(tmp_path / "kill"), k=K, seed=1)
    finally:
        os.environ.pop(ckpt_mod.STOP_AT_ENV, None)

    # resume: fast-forwards the completed steps, re-enters the killed
    # one through the facade's own manifest — cut-identical throughout
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    part_res, section_res = run_chain(
        make_rgg2d(N, avg_degree=8, seed=3), batches,
        _chain_ctx(tmp_path / "kill", resume=True), k=K, seed=1)
    assert section_res["cut_trajectory"] == cuts_ref
    assert np.array_equal(part_res, part_ref)
    assert (section_res["sessions"][0]["chain"]
            == section_ref["sessions"][0]["chain"])
    # the durable resume record (the chain-resume event is wiped by
    # the next compute's stream reset)
    assert section_res["resumed_from_step"] == 0
    assert "resumed_from_step" not in section_ref


@pytest.mark.slow  # the mid-chain kill test above covers the resume
# machinery; this adds the register-barrier variant (runs in plain
# pytest, like the dist suite's slow marks — tier-1 budget)
def test_chain_kill_during_register_resumes(tmp_path):
    """The register step owns the telemetry/checkpoint stream like any
    single-shot run (no wrapping timer scope — GLOBAL_TIMER.idle()
    decides stream ownership): a hard kill at its result barrier
    resumes instantly from the snapshot, cut-identical."""
    g = _graph()
    batches = synth_chain(g, steps=1, seed=55, edge_churn=0.01)
    part_ref, sec_ref = run_chain(
        g, batches, _chain_ctx(tmp_path / "ref"), k=K, seed=1)
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    os.environ[ckpt_mod.STOP_AT_ENV] = "result!"
    try:
        with pytest.raises(SimulatedPreemption):
            run_chain(make_rgg2d(N, avg_degree=8, seed=3), batches,
                      _chain_ctx(tmp_path / "kill"), k=K, seed=1)
    finally:
        os.environ.pop(ckpt_mod.STOP_AT_ENV, None)
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    part_res, sec_res = run_chain(
        make_rgg2d(N, avg_degree=8, seed=3), batches,
        _chain_ctx(tmp_path / "kill", resume=True), k=K, seed=1)
    assert sec_res["cut_trajectory"] == sec_ref["cut_trajectory"]
    assert np.array_equal(part_res, part_ref)


def test_chain_resume_replays_batches_without_drift_inflation(tmp_path):
    """A resume whose fast-forward REPLAYS applied deltas (kill after
    step 2 of 3) must land on the same decisions as the uninterrupted
    chain — in particular the recomputed step's drift must NOT be
    inflated by the replayed delta mass (the accumulators are reset to
    the committed-step boundary)."""
    g = _graph()
    batches = synth_chain(g, steps=3, seed=70, edge_churn=0.01)
    part_ref, sec_ref = run_chain(
        g, batches, _chain_ctx(tmp_path / "ref"), k=K, seed=1)
    # simulate a kill BETWEEN steps 2 and 3: run the truncated chain
    # (its chain state records step 2), then resume with the full list
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    run_chain(make_rgg2d(N, avg_degree=8, seed=3), batches[:2],
              _chain_ctx(tmp_path / "kill"), k=K, seed=1)
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    part_res, sec_res = run_chain(
        make_rgg2d(N, avg_degree=8, seed=3), batches,
        _chain_ctx(tmp_path / "kill", resume=True), k=K, seed=1)
    assert sec_res["resumed_from_step"] == 2
    assert sec_res["cut_trajectory"] == sec_ref["cut_trajectory"]
    assert np.array_equal(part_res, part_ref)
    # the recomputed step's decision row must MATCH the reference —
    # drift included (the inflation bug flipped warm to cold here)
    ref_row = sec_ref["decisions"][3]
    res_row = sec_res["decisions"][3]
    assert res_row["mode"] == ref_row["mode"] == "warm"
    assert res_row["drift"] == pytest.approx(ref_row["drift"])


def test_chain_state_mismatch_restarts_cleanly(tmp_path):
    g = _graph()
    batches = synth_chain(g, steps=1, seed=60, edge_churn=0.005)
    part1, sec1 = run_chain(g, batches, _chain_ctx(tmp_path), k=K,
                            seed=1)
    # poison the stored chain hash: resume must NOT trust the state
    jpath = tmp_path / "dynamic" / "chain-state.json"
    state = json.loads(jpath.read_text())
    state["chain"] = "poisoned"
    jpath.write_text(json.dumps(state))
    part2, sec2 = run_chain(
        make_rgg2d(N, avg_degree=8, seed=3), batches,
        _chain_ctx(tmp_path, resume=True), k=K, seed=1)
    # a clean restart reproduces the deterministic chain
    assert sec2["cut_trajectory"] == sec1["cut_trajectory"]
    assert np.array_equal(part1, part2)


# ---------------------------------------------------------------------------
# serving surface
# ---------------------------------------------------------------------------


def test_serving_session_kinds():
    from kaminpar_tpu.serving import PartitionRequest, PartitionService

    svc = PartitionService("default")
    spec = "gen:rgg2d;n=1024;avg_degree=8;seed=3"
    recs = svc.serve([
        PartitionRequest(spec, k=K, kind="register", session="s1",
                         seed=1, request_id="r1"),
        PartitionRequest(spec, k=K, kind="register", session="s1",
                         request_id="r1b"),          # duplicate-session
        PartitionRequest("", k=0, kind="mutate", session="s1",
                         delta={"edge_inserts": [[0, 999]]},
                         request_id="r2"),
        PartitionRequest("", k=0, kind="repartition", session="s1",
                         seed=1, request_id="r3"),
        PartitionRequest("", k=0, kind="mutate", session="ghost",
                         delta={"edge_inserts": [[0, 1]]},
                         request_id="r4"),           # unknown-session
        PartitionRequest("", k=0, kind="mutate", session="s1",
                         delta={"edge_deletes": [[0, 999998]]},
                         request_id="r5"),           # malformed delta
        PartitionRequest("", k=0, kind="bogus", session="s1",
                         request_id="r6"),
    ])
    by_id = {r.request_id: r for r in recs}
    assert by_id["r1"].verdict == "served" and by_id["r1"].cut >= 0
    assert by_id["r1b"].verdict == "rejected"
    assert by_id["r1b"].reason == "duplicate-session"
    assert by_id["r2"].verdict == "served"
    assert by_id["r2"].reason in ("in-place", "rebuild")
    assert by_id["r3"].verdict == "served" and by_id["r3"].cut >= 0
    assert by_id["r4"].reason == "unknown-session"
    assert by_id["r5"].verdict == "failed"
    assert by_id["r5"].reason == "malformed-input"
    assert by_id["r6"].reason == "invalid-parameters"
    d = svc.dynamic_summary()
    assert d["enabled"] and d["counts"]["deltas"] == 1
    assert len(d["sessions"]) == 1
    assert d["sessions"][0]["repartitions"] == 2  # register + repart
    # the failed mutate left the session consistent (still servable)
    rec = svc.serve([PartitionRequest(
        "", k=0, kind="repartition", session="s1", seed=1,
        request_id="r7")])[0]
    assert rec.verdict == "served"


def test_serving_mutate_degradation_visible(monkeypatch):
    """An injected dynamic-apply fault during a serving mutate must
    surface as verdict `degraded` (the matrix row's contract), not be
    swallowed into `served`."""
    from kaminpar_tpu.serving import PartitionRequest, PartitionService

    svc = PartitionService("default")
    svc.serve([PartitionRequest(
        "gen:rgg2d;n=1024;avg_degree=8;seed=3", k=K, kind="register",
        session="s1", seed=1, request_id="reg")])
    monkeypatch.setenv(resilience.FAULTS_ENV_VAR, "dynamic-apply:nth=1")
    rec = svc.serve([PartitionRequest(
        "", k=0, kind="mutate", session="s1",
        delta={"edge_inserts": [[0, 999]]}, request_id="mut")])[0]
    assert rec.verdict == "degraded"
    assert rec.degraded_sites == ["dynamic-apply"]
    assert rec.reason == "rebuild"


def test_serving_session_epsilon_sticks():
    """A repartition request without an explicit epsilon reuses the
    epsilon the session was REGISTERED with, not the wire default."""
    from kaminpar_tpu.serving import PartitionRequest, PartitionService

    svc = PartitionService("default")
    recs = svc.serve([
        PartitionRequest(
            "gen:rgg2d;n=1024;avg_degree=8;seed=3", k=K,
            kind="register", session="s1", epsilon=0.2, seed=1,
            request_id="reg"),
        PartitionRequest("", k=0, kind="repartition", session="s1",
                         epsilon=None, seed=1, request_id="rep"),
    ])
    assert [r.verdict for r in recs] == ["served", "served"]
    assert svc._sessions["s1"].epsilon == 0.2


def test_serving_process_isolation_rejects_sessions():
    from kaminpar_tpu.serving import (
        PartitionRequest,
        PartitionService,
        ServiceConfig,
    )

    svc = PartitionService("default", ServiceConfig(isolation="process"))
    try:
        rec = svc.submit(PartitionRequest(
            "gen:rgg2d;n=256;avg_degree=4;seed=1", k=2,
            kind="register", session="s1"))
        assert rec is not None and rec.reason == "session-isolation"
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# report surface (schema v11) + pad slack (satellite)
# ---------------------------------------------------------------------------


def _checker():
    spec = importlib.util.spec_from_file_location(
        "check_report_schema",
        os.path.join(_REPO, "scripts", "check_report_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_dynamic_section_schema_valid():
    s = _partitioned_session()
    s.apply(random_delta_batch(s.graph, seed=21, edge_churn=0.005))
    out = repartition(s, create_context_by_preset_name("default"),
                      k=K, seed=1)
    section = summarize([s], [
        {"session": s.id, "step": 0, "mode": "cold", "drift": None,
         "cut_before": None, "cut": 10, "feasible": True,
         "stable": None, "escalated": False, "seeded": 0,
         "wall_s": 0.1, "warm_wall_s": None, "cold_wall_s": 0.1},
        {**out.to_row(s.id, step=1), "in_place": True},
    ])
    telemetry.annotate(dynamic=section)
    checker = _checker()
    from kaminpar_tpu.telemetry.report import SCHEMA_PATH, build_run_report

    report = build_run_report()
    assert report["schema_version"] == 14
    assert report["dynamic"]["enabled"]
    schema = json.load(open(SCHEMA_PATH))
    errors = (checker.validate_instance(report["dynamic"],
                                        schema["properties"]["dynamic"])
              + checker.version_checks(report))
    assert errors == [], errors


def test_report_dynamic_disabled_default():
    from kaminpar_tpu.telemetry.report import build_run_report

    telemetry.annotate(result={"cut": 0, "imbalance": 0.0,
                               "feasible": True})
    report = build_run_report()
    assert report["dynamic"] == {"enabled": False}


def test_pad_slack_rows_and_totals():
    from kaminpar_tpu.telemetry import perf

    perf.record_padding(n=100, n_pad=256, m=400, m_pad=512, k=4, k_pad=4)
    snap = perf.snapshot()
    row = snap["pad_waste"][0]
    assert row["n_slack"] == 156 and row["m_slack"] == 112
    assert row["k_slack"] == 0
    assert snap["totals"]["pad_slack_axes"] == {"n": 156, "m": 112,
                                                "k": 0}
