"""tpulint: per-rule fixture firing, suppressions, baseline ratchet,
CLI exit codes, and the package-clean gate (the acceptance criterion:
`python -m kaminpar_tpu.lint kaminpar_tpu/` exits 0 vs the checked-in
baseline)."""

import json
import os

import pytest

from kaminpar_tpu.lint import (
    LintConfig,
    diff_against_baseline,
    lint_file,
    lint_paths,
    lint_source,
    load_baseline,
    write_baseline,
)
from kaminpar_tpu.lint.__main__ import DEFAULT_BASELINE, main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")
PACKAGE = os.path.join(REPO, "kaminpar_tpu")


def _findings(name):
    return lint_file(os.path.join(FIXTURES, name))


# --- every rule fires on its bad fixture at the pinned lines --------------

BAD_EXPECT = {
    "r1_bad.py": [("R1", 20), ("R1", 22), ("R1", 23), ("R1", 24), ("R1", 30)],
    # the PR-11 quality-observatory hook shape: per-level cut/cmap
    # pulls lexically inside a driver's uncoarsening span
    "r1_quality_bad.py": [("R1", 19), ("R1", 20)],
    # the PR-13 streaming hook shape: chunk decode + moved-count pulls
    # lexically inside a driver's stream span
    "r1_stream_bad.py": [("R1", 19), ("R1", 21)],
    # the PR-15 dynamic delta-apply hook shape: the host CSR patch
    # pull + cut readback lexically inside a driver's dynamic-apply
    # span
    "r1_dynamic_bad.py": [("R1", 19), ("R1", 21)],
    # the PR-14 supervision hook shape: liveness "proof" pulls device
    # state lexically inside the guarded driver span (the watchdog/
    # heartbeat hooks are host-side bookkeeping and read no device
    # values)
    "r1_supervisor_bad.py": [("R1", 22), ("R1", 23)],
    # the PR-16 fleet-observatory hook shape: live-gauge pulls of
    # device values lexically inside the measured compute span (the
    # metrics producers are host-side request bookkeeping)
    "r1_metrics_bad.py": [("R1", 23), ("R1", 24), ("R1", 25)],
    "r2_bad.py": [("R2", 5), ("R2", 9)],
    "r3_bad.py": [("R3", 7), ("R3", 11), ("R3", 16), ("R3", 21)],
    "r4_bad.py": [("R4", 10), ("R4", 17), ("R4", 23)],
    "r5_bad.py": [("R5", 6), ("R5", 10), ("R5", 18)],
    "r6_bad.py": [("R6", 7), ("R6", 11), ("R6", 15), ("R6", 19)],
}


@pytest.mark.parametrize("name", sorted(BAD_EXPECT))
def test_rule_fires_on_bad_fixture(name):
    got = [(f.rule, f.line) for f in _findings(name)]
    assert got == BAD_EXPECT[name]


@pytest.mark.parametrize(
    "name", ["r1_good.py", "r1_quality_good.py", "r1_stream_good.py",
             "r1_dynamic_good.py",
             "r1_supervisor_good.py", "r1_metrics_good.py", "r2_good.py",
             "r3_good.py", "r4_good.py", "r5_good.py", "r6_good.py"]
)
def test_rule_silent_on_good_fixture(name):
    assert _findings(name) == []


# --- finding metadata ------------------------------------------------------

def test_findings_carry_symbol_and_code():
    by_line = {f.line: f for f in _findings("r1_bad.py")}
    assert by_line[23].symbol == "helper"
    assert ".item()" in by_line[23].code
    assert by_line[30].symbol == "span_scope_sync"
    mod_level = {f.line: f for f in _findings("r2_bad.py")}
    assert mod_level[5].symbol == "<module>"


# --- suppressions ----------------------------------------------------------

R2_SNIPPET = "import jax\n\n\ndef f():\n    return jax.devices()\n"


def test_same_line_suppression():
    src = R2_SNIPPET.replace(
        "return jax.devices()",
        "return jax.devices()  # tpulint: disable=R2",
    )
    assert lint_source(src, "x.py") == []


def test_comment_line_above_suppression():
    src = R2_SNIPPET.replace(
        "    return jax.devices()",
        "    # bounded: test harness only  # tpulint: disable=R2\n"
        "    return jax.devices()",
    )
    assert lint_source(src, "x.py") == []


def test_file_level_suppression():
    src = "# tpulint: disable-file=R2\n" + R2_SNIPPET
    assert lint_source(src, "x.py") == []


def test_suppression_of_other_rule_does_not_hide():
    src = R2_SNIPPET.replace(
        "return jax.devices()",
        "return jax.devices()  # tpulint: disable=R1",
    )
    assert [f.rule for f in lint_source(src, "x.py")] == ["R2"]


def test_gate_module_is_exempt():
    findings = lint_source(R2_SNIPPET, "kaminpar_tpu/utils/platform.py")
    assert findings == []


# --- baseline --------------------------------------------------------------

def test_baseline_roundtrip_and_diff(tmp_path):
    findings = _findings("r3_bad.py")
    path = tmp_path / "baseline.json"
    write_baseline(str(path), findings)
    entries = load_baseline(str(path))
    assert len(entries) == len(findings)

    diff = diff_against_baseline(findings, entries)
    assert diff.new == [] and len(diff.accepted) == len(findings)
    assert diff.stale == []

    # a fresh finding not in the baseline is NEW
    extra = _findings("r5_bad.py")
    diff = diff_against_baseline(findings + extra, entries)
    assert [f.rule for f in diff.new] == ["R5"] * len(extra)

    # a fixed finding leaves a STALE entry (the ratchet signal)
    diff = diff_against_baseline(findings[1:], entries)
    assert len(diff.stale) == 1 and diff.new == []


def test_baseline_is_line_churn_stable(tmp_path):
    src = R2_SNIPPET
    findings = lint_source(src, "x.py")
    path = tmp_path / "b.json"
    write_baseline(str(path), findings)
    # shift every line down: same code, different line numbers
    shifted = "# a new leading comment\n\n" + src
    diff = diff_against_baseline(
        lint_source(shifted, "x.py"), load_baseline(str(path))
    )
    assert diff.new == [] and diff.stale == []


# --- CLI -------------------------------------------------------------------

def test_cli_exit_codes(tmp_path, capsys):
    bad = os.path.join(FIXTURES, "r2_bad.py")
    good = os.path.join(FIXTURES, "r2_good.py")
    assert main([good, "--no-baseline"]) == 0
    assert main([bad, "--no-baseline"]) == 1
    assert main([str(tmp_path / "missing.py")]) == 2
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "R1" in out and "R5" in out


def test_cli_select_subset():
    bad = os.path.join(FIXTURES, "r2_bad.py")
    # selecting a rule the file does not violate -> clean
    assert main([bad, "--no-baseline", "--select", "R5"]) == 0
    assert main([bad, "--no-baseline", "--select", "R2"]) == 1
    assert main([bad, "--select", "R9"]) == 2


def test_cli_json_format(capsys):
    bad = os.path.join(FIXTURES, "r5_bad.py")
    assert main([bad, "--no-baseline", "--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["total"] == 3
    assert payload["new"][0]["rule"] == "R5"


def test_cli_write_baseline_refuses_subsets(tmp_path, capsys):
    """--write-baseline must not truncate the checked-in baseline to a
    rule or path subset's findings."""
    bad = os.path.join(FIXTURES, "r2_bad.py")
    assert main([bad, "--select", "R2", "--write-baseline"]) == 2
    assert main([bad, "--write-baseline"]) == 2  # path subset, default file
    capsys.readouterr()
    # an explicit --baseline target is fine for a subset
    out = tmp_path / "b.json"
    assert main([bad, "--write-baseline", "--baseline", str(out)]) == 0
    assert load_baseline(str(out))


# --- the acceptance gate ---------------------------------------------------

def test_package_is_clean_against_checked_in_baseline():
    """`python -m kaminpar_tpu.lint kaminpar_tpu/` must exit 0: every
    finding is either fixed, suppressed with a justification, or in
    scripts/tpulint_baseline.json (ratchet: only ever shrink it)."""
    assert os.path.exists(DEFAULT_BASELINE), "baseline file is checked in"
    findings = lint_paths([PACKAGE], LintConfig())
    diff = diff_against_baseline(findings, load_baseline(DEFAULT_BASELINE))
    assert diff.new == [], "\n".join(f.render() for f in diff.new)


def test_syntax_error_reports_e0_even_with_rule_subset():
    cfg = LintConfig()
    cfg.rules = ("R2",)
    findings = lint_source("def f(:\n", "broken.py", cfg)
    assert [f.rule for f in findings] == ["E0"]
