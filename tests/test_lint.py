"""tpulint: per-rule fixture firing, suppressions, baseline ratchet,
CLI exit codes, and the package-clean gate (the acceptance criterion:
`python -m kaminpar_tpu.lint kaminpar_tpu/` exits 0 vs the checked-in
baseline)."""

import json
import os

import pytest

from kaminpar_tpu.lint import (
    LintConfig,
    diff_against_baseline,
    lint_file,
    lint_paths,
    lint_source,
    load_baseline,
    write_baseline,
)
from kaminpar_tpu.lint.__main__ import DEFAULT_BASELINE, main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")
PACKAGE = os.path.join(REPO, "kaminpar_tpu")


def _findings(name):
    return lint_file(os.path.join(FIXTURES, name))


# --- every rule fires on its bad fixture at the pinned lines --------------

BAD_EXPECT = {
    "r1_bad.py": [("R1", 20), ("R1", 22), ("R1", 23), ("R1", 24), ("R1", 30)],
    # the PR-11 quality-observatory hook shape: per-level cut/cmap
    # pulls lexically inside a driver's uncoarsening span
    "r1_quality_bad.py": [("R1", 19), ("R1", 20)],
    # the PR-13 streaming hook shape: chunk decode + moved-count pulls
    # lexically inside a driver's stream span
    "r1_stream_bad.py": [("R1", 19), ("R1", 21)],
    # the PR-15 dynamic delta-apply hook shape: the host CSR patch
    # pull + cut readback lexically inside a driver's dynamic-apply
    # span
    "r1_dynamic_bad.py": [("R1", 19), ("R1", 21)],
    # the PR-14 supervision hook shape: liveness "proof" pulls device
    # state lexically inside the guarded driver span (the watchdog/
    # heartbeat hooks are host-side bookkeeping and read no device
    # values)
    "r1_supervisor_bad.py": [("R1", 22), ("R1", 23)],
    # the PR-16 fleet-observatory hook shape: live-gauge pulls of
    # device values lexically inside the measured compute span (the
    # metrics producers are host-side request bookkeeping)
    "r1_metrics_bad.py": [("R1", 23), ("R1", 24), ("R1", 25)],
    # the PR-17 call-graph shape: host pulls hidden one helper call
    # deep — the span body only makes function calls, the call graph
    # flags the call sites
    "r1_helper_bad.py": [("R1", 24), ("R1", 25)],
    # the PR-19 execution-ledger hook shape: transfer metering fed by
    # device-value pulls lexically inside the measured upload span
    # (ledger pulls inside a driver span = R1; the factored chokepoint
    # helpers metering from host metadata are clean)
    "r1_ledger_bad.py": [("R1", 22), ("R1", 23)],
    "r2_bad.py": [("R2", 5), ("R2", 9)],
    "r3_bad.py": [("R3", 7), ("R3", 11), ("R3", 16), ("R3", 21)],
    "r4_bad.py": [("R4", 10), ("R4", 17), ("R4", 23)],
    "r5_bad.py": [("R5", 6), ("R5", 10), ("R5", 18)],
    "r6_bad.py": [("R6", 7), ("R6", 11), ("R6", 15), ("R6", 19)],
    # SPMD collective symmetry: direct, helper-reached, and loop-guarded
    "r7_bad.py": [("R7", 18), ("R7", 24), ("R7", 30)],
    # exception hygiene: bare except, except-Exception around site=,
    # and a broad handler around a helper reaching the fault surface
    "r8_bad.py": [("R8", 16), ("R8", 23), ("R8", 30)],
}


@pytest.mark.parametrize("name", sorted(BAD_EXPECT))
def test_rule_fires_on_bad_fixture(name):
    got = [(f.rule, f.line) for f in _findings(name)]
    assert got == BAD_EXPECT[name]


@pytest.mark.parametrize(
    "name", ["r1_good.py", "r1_quality_good.py", "r1_stream_good.py",
             "r1_dynamic_good.py", "r1_helper_good.py", "r1_ledger_good.py",
             "r1_supervisor_good.py", "r1_metrics_good.py", "r2_good.py",
             "r3_good.py", "r4_good.py", "r5_good.py", "r6_good.py",
             "r7_good.py", "r8_good.py"]
)
def test_rule_silent_on_good_fixture(name):
    assert _findings(name) == []


# --- finding metadata ------------------------------------------------------

def test_findings_carry_symbol_and_code():
    by_line = {f.line: f for f in _findings("r1_bad.py")}
    assert by_line[23].symbol == "helper"
    assert ".item()" in by_line[23].code
    assert by_line[30].symbol == "span_scope_sync"
    mod_level = {f.line: f for f in _findings("r2_bad.py")}
    assert mod_level[5].symbol == "<module>"


# --- suppressions ----------------------------------------------------------

R2_SNIPPET = "import jax\n\n\ndef f():\n    return jax.devices()\n"


def test_same_line_suppression():
    src = R2_SNIPPET.replace(
        "return jax.devices()",
        "return jax.devices()  # tpulint: disable=R2",
    )
    assert lint_source(src, "x.py") == []


def test_comment_line_above_suppression():
    src = R2_SNIPPET.replace(
        "    return jax.devices()",
        "    # bounded: test harness only  # tpulint: disable=R2\n"
        "    return jax.devices()",
    )
    assert lint_source(src, "x.py") == []


def test_file_level_suppression():
    src = "# tpulint: disable-file=R2\n" + R2_SNIPPET
    assert lint_source(src, "x.py") == []


def test_suppression_of_other_rule_does_not_hide():
    src = R2_SNIPPET.replace(
        "return jax.devices()",
        "return jax.devices()  # tpulint: disable=R1",
    )
    assert [f.rule for f in lint_source(src, "x.py")] == ["R2"]


def test_gate_module_is_exempt():
    findings = lint_source(R2_SNIPPET, "kaminpar_tpu/utils/platform.py")
    assert findings == []


# --- baseline --------------------------------------------------------------

def test_baseline_roundtrip_and_diff(tmp_path):
    findings = _findings("r3_bad.py")
    path = tmp_path / "baseline.json"
    write_baseline(str(path), findings)
    entries = load_baseline(str(path))
    assert len(entries) == len(findings)

    diff = diff_against_baseline(findings, entries)
    assert diff.new == [] and len(diff.accepted) == len(findings)
    assert diff.stale == []

    # a fresh finding not in the baseline is NEW
    extra = _findings("r5_bad.py")
    diff = diff_against_baseline(findings + extra, entries)
    assert [f.rule for f in diff.new] == ["R5"] * len(extra)

    # a fixed finding leaves a STALE entry (the ratchet signal)
    diff = diff_against_baseline(findings[1:], entries)
    assert len(diff.stale) == 1 and diff.new == []


def test_baseline_is_line_churn_stable(tmp_path):
    src = R2_SNIPPET
    findings = lint_source(src, "x.py")
    path = tmp_path / "b.json"
    write_baseline(str(path), findings)
    # shift every line down: same code, different line numbers
    shifted = "# a new leading comment\n\n" + src
    diff = diff_against_baseline(
        lint_source(shifted, "x.py"), load_baseline(str(path))
    )
    assert diff.new == [] and diff.stale == []


# --- CLI -------------------------------------------------------------------

def test_cli_exit_codes(tmp_path, capsys):
    bad = os.path.join(FIXTURES, "r2_bad.py")
    good = os.path.join(FIXTURES, "r2_good.py")
    assert main([good, "--no-baseline"]) == 0
    assert main([bad, "--no-baseline"]) == 1
    assert main([str(tmp_path / "missing.py")]) == 2
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "R1" in out and "R5" in out


def test_cli_select_subset():
    bad = os.path.join(FIXTURES, "r2_bad.py")
    # selecting a rule the file does not violate -> clean
    assert main([bad, "--no-baseline", "--select", "R5"]) == 0
    assert main([bad, "--no-baseline", "--select", "R2"]) == 1
    assert main([bad, "--select", "R42"]) == 2  # unknown rule


def test_cli_json_format(capsys):
    bad = os.path.join(FIXTURES, "r5_bad.py")
    assert main([bad, "--no-baseline", "--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["total"] == 3
    assert payload["new"][0]["rule"] == "R5"


def test_cli_write_baseline_refuses_subsets(tmp_path, capsys):
    """--write-baseline must not truncate the checked-in baseline to a
    rule or path subset's findings."""
    bad = os.path.join(FIXTURES, "r2_bad.py")
    assert main([bad, "--select", "R2", "--write-baseline"]) == 2
    assert main([bad, "--write-baseline"]) == 2  # path subset, default file
    capsys.readouterr()
    # an explicit --baseline target is fine for a subset
    out = tmp_path / "b.json"
    assert main([bad, "--write-baseline", "--baseline", str(out)]) == 0
    assert load_baseline(str(out))


# --- the acceptance gate ---------------------------------------------------

def test_package_is_clean_against_checked_in_baseline():
    """`python -m kaminpar_tpu.lint kaminpar_tpu/` must exit 0: every
    finding is either fixed, suppressed with a justification, or in
    scripts/tpulint_baseline.json (ratchet: only ever shrink it)."""
    assert os.path.exists(DEFAULT_BASELINE), "baseline file is checked in"
    findings = lint_paths([PACKAGE], LintConfig())
    diff = diff_against_baseline(findings, load_baseline(DEFAULT_BASELINE))
    assert diff.new == [], "\n".join(f.render() for f in diff.new)


def test_syntax_error_reports_e0_even_with_rule_subset():
    cfg = LintConfig()
    cfg.rules = ("R2",)
    findings = lint_source("def f(:\n", "broken.py", cfg)
    assert [f.rule for f in findings] == ["E0"]


# --- PR 17: call-graph semantics -------------------------------------------

_HELPER_SRC = (
    "import numpy as np\n"
    "from kaminpar_tpu.utils.timer import scoped_timer\n\n\n"
    "def _pull(x):\n"
    "    return np.asarray(x)\n\n\n"
    "def run(x, out):\n"
    "    with scoped_timer('t'):\n"
    "        out.append(_pull(x))\n"
    "    return out\n"
)


def test_callgraph_flags_same_module_helper_call_site():
    """The pre-PR-17 loophole: a span body that only makes function
    calls.  The call graph flags the CALL SITE, not the helper def."""
    findings = lint_source(_HELPER_SRC, "x.py")
    assert [(f.rule, f.line) for f in findings] == [("R1", 11)]
    assert "_pull" in findings[0].message


def test_def_line_suppression_declares_host_boundary():
    """`# tpulint: disable=R1` on (above) a def clears the helper's
    summary for that rule — every call site at once."""
    src = _HELPER_SRC.replace(
        "def _pull(x):", "# tpulint: disable=R1\ndef _pull(x):"
    )
    assert lint_source(src, "x.py") == []


def test_lambda_payloads_are_deferred():
    """`payload=lambda: ...` thunks (the checkpoint-barrier shape) run
    outside the hot path — never span findings."""
    src = (
        "import numpy as np\n"
        "from kaminpar_tpu.utils.timer import scoped_timer\n\n\n"
        "def run(x, ckpt):\n"
        "    with scoped_timer('t'):\n"
        "        ckpt.barrier(payload=lambda: np.asarray(x))\n"
    )
    assert lint_source(src, "x.py") == []


# --- PR 17: R9 schema-pin consistency --------------------------------------

def _r9_config(root):
    cfg = LintConfig()
    cfg.r9_root = str(root)
    return cfg


def test_r9_good_quad_is_clean():
    from kaminpar_tpu.lint.schema_pins import check_schema_pins

    assert check_schema_pins(_r9_config(
        os.path.join(FIXTURES, "r9_good")
    )) == []


def test_r9_bad_quad_flags_each_stale_site():
    from kaminpar_tpu.lint.schema_pins import check_schema_pins

    findings = check_schema_pins(_r9_config(
        os.path.join(FIXTURES, "r9_bad")
    ))
    assert [f.rule for f in findings] == ["R9"] * 3
    paths = [f.path for f in findings]
    assert any(p.endswith("run_report.schema.json") for p in paths)
    assert sum(p.endswith("check_report_schema.py") for p in paths) == 2


_R9_SKEWS = {
    # bump ONE site of the good quad; the finding must name that site
    # (or, for a producer bump, the producer line — the other three
    # still agree with each other)
    "producer": (
        "kaminpar_tpu/telemetry/report.py",
        "SCHEMA_VERSION = 3", "SCHEMA_VERSION = 4",
        "report.py",
    ),
    "schema": (
        "kaminpar_tpu/telemetry/run_report.schema.json",
        "[1, 2, 3]", "[1, 2, 3, 4]",
        "run_report.schema.json",
    ),
    "checker": (
        "scripts/check_report_schema.py",
        "!= 3:", "!= 4:",
        "check_report_schema.py",
    ),
    "fixture": (
        "scripts/check_report_schema.py",
        "def _minimal_v2_report():", "def _minimal_v3_report():",
        "check_report_schema.py",
    ),
}


@pytest.mark.parametrize("site", sorted(_R9_SKEWS))
def test_r9_fails_when_one_pin_site_bumped_alone(site, tmp_path):
    import shutil

    from kaminpar_tpu.lint.schema_pins import check_schema_pins

    rel, old, new, expect_suffix = _R9_SKEWS[site]
    root = tmp_path / "quad"
    shutil.copytree(os.path.join(FIXTURES, "r9_good"), root)
    target = root / rel
    text = target.read_text()
    assert old in text
    target.write_text(text.replace(old, new))

    findings = check_schema_pins(_r9_config(root))
    assert findings, f"single-site bump of {site} must not pass"
    assert any(f.path.endswith(expect_suffix) for f in findings)


def test_r9_clean_on_the_real_repo_pins():
    """The actual producer/schema/checker/fixture quad is consistent —
    the standalone gate check_all.sh runs."""
    from kaminpar_tpu.lint.schema_pins import check_schema_pins

    assert check_schema_pins() == []


# --- PR 17: CLI output formats, rule filtering, baseline growth ------------

def test_cli_rules_alias_filters(capsys):
    bad = os.path.join(FIXTURES, "r2_bad.py")
    assert main([bad, "--no-baseline", "--rules", "R5"]) == 0
    assert main([bad, "--no-baseline", "--rules", "R2,R5"]) == 1
    capsys.readouterr()


def test_cli_json_reports_baseline_entries(capsys):
    bad = os.path.join(FIXTURES, "r5_bad.py")
    assert main([bad, "--no-baseline", "--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["baseline_entries"] == 0
    assert payload["stale_baseline_entries"] == 0


def test_cli_sarif_format(capsys):
    bad = os.path.join(FIXTURES, "r5_bad.py")
    assert main([bad, "--no-baseline", "--format", "sarif"]) == 1
    sarif = json.loads(capsys.readouterr().out)
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"R1", "R9"} <= rule_ids
    assert run["results"], "findings must surface as results"
    res = run["results"][0]
    assert res["ruleId"] == "R5"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("r5_bad.py")
    assert loc["region"]["startLine"] >= 1
    assert run["properties"]["totalFindings"] == 3


def test_cli_write_baseline_refuses_growth(tmp_path, capsys):
    """The ratchet only shrinks: regenerating over an existing baseline
    with MORE findings than entries is refused."""
    good = os.path.join(FIXTURES, "r2_good.py")
    bad = os.path.join(FIXTURES, "r2_bad.py")
    out = tmp_path / "b.json"
    # seed an empty baseline from a clean file
    assert main([good, "--write-baseline", "--baseline", str(out)]) == 0
    assert load_baseline(str(out)) == []
    # growing it is refused, and the file is untouched
    assert main([bad, "--write-baseline", "--baseline", str(out)]) == 2
    assert load_baseline(str(out)) == []
    capsys.readouterr()
    # equal-or-shrinking rewrites still work
    fresh = tmp_path / "fresh.json"
    assert main([bad, "--write-baseline", "--baseline", str(fresh)]) == 0
    assert main([bad, "--write-baseline", "--baseline", str(fresh)]) == 0


def test_checked_in_baseline_is_empty():
    """PR 17 acceptance: the package is clean against an EMPTY baseline
    — zero accepted findings left."""
    assert load_baseline(DEFAULT_BASELINE) == []
