"""Gain cache tests — mirrors the reference's gain_cache_test.cc: cached
gains must equal recomputation after arbitrary move sequences."""

import jax.numpy as jnp
import numpy as np
import pytest

from kaminpar_tpu.graphs.csr import device_graph_from_host
from kaminpar_tpu.graphs.factories import make_grid_graph, make_rmat
from kaminpar_tpu.refinement.gains import (
    HostDeltaGainCache,
    HostDenseGainCache,
    best_moves_from_cache,
    build_dense_gain_cache,
    on_the_fly_gains,
    update_dense_gain_cache,
)


def _reference_conn(host, part, k):
    conn = np.zeros((host.n, k), dtype=np.int64)
    np.add.at(
        conn,
        (host.edge_sources(), part[host.adjncy]),
        host.edge_weight_array(),
    )
    return conn


def test_device_dense_cache_matches_reference_build():
    host = make_grid_graph(8, 8)
    k = 4
    rng = np.random.default_rng(0)
    part = rng.integers(0, k, host.n).astype(np.int32)
    dg = device_graph_from_host(host)
    padded = np.zeros(dg.n_pad, np.int32)
    padded[: host.n] = part
    conn = np.asarray(build_dense_gain_cache(dg, jnp.asarray(padded), k))
    np.testing.assert_array_equal(conn[: host.n], _reference_conn(host, part, k))
    # pad rows are all-zero (pad edges have weight 0)
    assert (conn[host.n :] == 0).all()


def test_device_dense_cache_incremental_update_matches_rebuild():
    """The move() protocol: after a bulk move round, the incrementally
    updated cache equals a fresh build from the new partition."""
    host = make_rmat(256, 2048, seed=3)
    k = 5
    rng = np.random.default_rng(1)
    part = rng.integers(0, k, host.n).astype(np.int32)
    dg = device_graph_from_host(host)
    p0 = np.zeros(dg.n_pad, np.int32)
    p0[: host.n] = part
    conn = build_dense_gain_cache(dg, jnp.asarray(p0), k)
    for round_ in range(3):
        new = p0.copy()
        movers = rng.random(host.n) < 0.3
        new[: host.n][movers] = rng.integers(0, k, movers.sum())
        conn = update_dense_gain_cache(
            conn, dg, jnp.asarray(p0), jnp.asarray(new), k
        )
        fresh = build_dense_gain_cache(dg, jnp.asarray(new), k)
        np.testing.assert_array_equal(np.asarray(conn), np.asarray(fresh))
        p0 = new


def test_best_moves_from_cache_respects_caps_and_gains():
    host = make_grid_graph(6, 6)
    k = 2
    part = np.zeros(host.n, np.int32)
    part[host.n // 2 :] = 1
    dg = device_graph_from_host(host)
    p = np.zeros(dg.n_pad, np.int32)
    p[: host.n] = part
    conn = build_dense_gain_cache(dg, jnp.asarray(p), k)
    nw = np.zeros(dg.n_pad, np.int64)
    nw[: host.n] = host.node_weight_array()
    bw = np.bincount(part, weights=host.node_weight_array(), minlength=k)
    # generous caps: every move feasible
    caps = jnp.full((k,), int(bw.max() * 2), jnp.int32)
    best, gain = best_moves_from_cache(
        conn,
        jnp.asarray(p),
        jnp.asarray(nw, jnp.int32),
        jnp.asarray(bw, jnp.int32),
        caps,
        k,
    )
    best, gain = np.asarray(best), np.asarray(gain)
    ref = _reference_conn(host, part, k)
    for u in range(host.n):
        own = ref[u, part[u]]
        other = 1 - part[u]
        assert best[u] == other
        assert gain[u] == ref[u, other] - own
    # zero caps: nothing feasible
    best2, _ = best_moves_from_cache(
        conn,
        jnp.asarray(p),
        jnp.asarray(nw, jnp.int32),
        jnp.asarray(bw, jnp.int32),
        jnp.zeros((k,), jnp.int32),
        k,
    )
    assert (np.asarray(best2)[: host.n] == -1).all()


def test_on_the_fly_gains_enumerates_adjacent_blocks():
    host = make_grid_graph(4, 4)
    k = 2
    part = (np.arange(host.n) % 4 >= 2).astype(np.int32)
    dg = device_graph_from_host(host)
    p = np.zeros(dg.n_pad, np.int32)
    p[: host.n] = part
    seg, key, w = (
        np.asarray(x) for x in on_the_fly_gains(dg, jnp.asarray(p), k)
    )
    ref = _reference_conn(host, part, k)
    got = np.zeros_like(ref)
    for s, b, ww in zip(seg, key, w):
        if s >= 0 and s < host.n:
            got[s, b] += ww
    np.testing.assert_array_equal(got, ref)


def test_host_cache_incremental_equals_rebuild_after_moves():
    host = make_rmat(128, 1024, seed=5)
    k = 4
    rng = np.random.default_rng(2)
    part = rng.integers(0, k, host.n).astype(np.int32)
    cache = HostDenseGainCache(host, part, k)
    for _ in range(50):
        u = int(rng.integers(0, host.n))
        b_from = int(part[u])
        b_to = int(rng.integers(0, k))
        if b_to == b_from:
            continue
        part[u] = b_to
        cache.apply_move(u, b_from, b_to)
    np.testing.assert_array_equal(cache.conn, _reference_conn(host, part, k))


def test_host_delta_cache_is_speculative():
    host = make_grid_graph(5, 5)
    k = 2
    part = (np.arange(host.n) % 5 >= 2).astype(np.int32)
    base = HostDenseGainCache(host, part, k)
    snapshot = base.conn.copy()
    delta = HostDeltaGainCache(base)
    delta.apply_move(12, int(part[12]), 1 - int(part[12]))
    # base untouched until commit
    np.testing.assert_array_equal(base.conn, snapshot)
    # delta view consistent with a real apply
    part2 = part.copy()
    part2[12] = 1 - part[12]
    ref2 = _reference_conn(host, part2, k)
    for u in host.neighbors(12):
        for b in range(k):
            assert delta._conn(int(u), b) == ref2[int(u), b]
    delta.commit()
    np.testing.assert_array_equal(base.conn, ref2)
    # clear() path: discarded moves leave the base alone
    delta2 = HostDeltaGainCache(base)
    delta2.apply_move(0, int(part2[0]), 1 - int(part2[0]))
    delta2.clear()
    np.testing.assert_array_equal(base.conn, ref2)
