"""Tool subcommand + aux subsystem tests (apps/tools analogs)."""

import numpy as np

from kaminpar_tpu.tools import main as tools_main

RGG = "/root/reference/misc/rgg2d.metis"


def test_properties(capfd):
    assert tools_main(["properties", RGG]) == 0
    out = capfd.readouterr().out
    assert "n=1024 m=4113" in out
    assert "isolated_nodes=2" in out  # rgg2d ships 2 isolated nodes


def test_partition_properties(tmp_path, capfd):
    part = tmp_path / "p.txt"
    np.savetxt(part, np.arange(1024) % 4, fmt="%d")
    assert tools_main(["partition-properties", RGG, str(part)]) == 0
    out = capfd.readouterr().out
    assert "k=4 cut=" in out


def test_compress_decompress_roundtrip(tmp_path, capfd):
    comp = tmp_path / "g.npz"
    back = tmp_path / "g.metis"
    assert tools_main(["compress", RGG, "-o", str(comp)]) == 0
    assert tools_main(["decompress", str(comp), "-o", str(back)]) == 0
    from kaminpar_tpu.io import load_graph

    a = load_graph(RGG)
    b = load_graph(str(back))
    # compression sorts neighborhoods; compare canonical forms
    assert (a.xadj == b.xadj).all()
    for u in range(a.n):
        assert (np.sort(a.neighbors(u)) == np.sort(b.neighbors(u))).all()


def test_rearrange_preserves_structure(tmp_path):
    out = tmp_path / "r.metis"
    assert tools_main(["rearrange", RGG, "-o", str(out)]) == 0
    from kaminpar_tpu.io import load_graph

    a = load_graph(RGG)
    b = load_graph(str(out))
    assert a.n == b.n and a.m == b.m
    # degree multiset preserved
    assert sorted(a.degrees()) == sorted(b.degrees())


def test_components_tool(capfd):
    assert tools_main(["components", RGG]) == 0
    out = capfd.readouterr().out
    assert "components=" in out


def test_components_kernel_matches_host():
    import jax.numpy as jnp

    from kaminpar_tpu.graphs.csr import device_graph_from_host
    from kaminpar_tpu.graphs.factories import make_grid_graph, make_matching_graph
    from kaminpar_tpu.ops.components import count_components

    g = make_grid_graph(8, 8)
    assert count_components(device_graph_from_host(g)) == 1
    g2 = make_matching_graph(10)  # 10 disjoint edges
    assert count_components(device_graph_from_host(g2)) == 10


def test_heap_profiler_and_statistics(capfd):
    from kaminpar_tpu.cli import main as cli_main
    from kaminpar_tpu.utils import heap_profiler, statistics

    try:
        rc = cli_main([RGG, "-k", "2", "-H", "--statistics"])
        assert rc == 0
        out = capfd.readouterr().out
        assert "partitioning: peak" in out
        # live-HBM tracking (device-buffer peak via jax.live_arrays
        # sampling at level boundaries) — works on every backend
        assert "live HBM" in out
        assert "STATS" in out
        assert "cut_after_jet" in out  # default refiner is Jet
    finally:
        heap_profiler.disable()
        heap_profiler.reset()
        statistics.disable()
        statistics.reset()
