"""Telemetry layer tests: span/event stream, exporters, satellites.

Covers the observability contract: span nesting mirrors the timer tree,
disabled mode records nothing, the Chrome-trace export conforms to the
trace-event schema, the run report round-trips through JSON and passes
the checked-in schema (scripts/check_report_schema.py — the tier-1
schema-drift backstop), and the lane-gather / FM decision events fire on
forced code paths.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

import kaminpar_tpu as ktp
from kaminpar_tpu import telemetry
from kaminpar_tpu.graphs import factories
from kaminpar_tpu.utils import timer

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_report_schema",
        os.path.join(_REPO, "scripts", "check_report_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


# ---------------------------------------------------------------------------
# core stream
# ---------------------------------------------------------------------------


def test_disabled_mode_is_noop():
    t = timer.Timer()
    with t.scope("a"):
        with t.scope("b"):
            pass
    telemetry.event("should-not-record", x=1)
    telemetry.annotate(k=16)
    assert telemetry.spans() == []
    assert telemetry.events() == []
    assert telemetry.run_info() == {}
    # the timer itself still recorded normally
    assert t.elapsed("a") >= 0.0 and t.root.children["a"].count == 1


def test_span_nesting_matches_timer_tree():
    telemetry.enable()
    t = timer.Timer()
    with t.scope("a"):
        with t.scope("b"):
            pass
        with t.scope("b"):  # second visit of the same tree node
            pass
    with t.scope("c"):
        pass
    spans = telemetry.spans()
    paths = [s.path for s in spans]
    # children close before parents (exit-order stream)
    assert paths == ["a.b", "a.b", "a", "c"]
    # every span path exists in the timer tree with matching totals
    by_path = {}
    for s in spans:
        by_path.setdefault(s.path, []).append(s)
    for path, ss in by_path.items():
        node_elapsed = t.elapsed(*path.split("."))
        assert node_elapsed >= sum(s.duration for s in ss) - 1e-6
    # nesting: the child span lies within its parent's window
    parent = next(s for s in spans if s.path == "a")
    for child in (s for s in spans if s.path == "a.b"):
        assert child.start >= parent.start - 1e-9
        assert child.start + child.duration <= (
            parent.start + parent.duration + 1e-6
        )


def test_reset_guard_when_nested():
    telemetry.enable()
    telemetry.event("outer")
    assert timer.GLOBAL_TIMER.idle()
    with timer.GLOBAL_TIMER.scope("open"):
        assert not timer.GLOBAL_TIMER.idle()
    assert len(telemetry.events()) == 1


# ---------------------------------------------------------------------------
# Chrome-trace exporter
# ---------------------------------------------------------------------------


def test_chrome_trace_conforms_to_trace_event_schema(tmp_path):
    from kaminpar_tpu.telemetry.chrome_trace import write_chrome_trace

    telemetry.enable()
    t = timer.Timer()
    with t.scope("phase"):
        with t.scope("inner"):
            pass
    telemetry.event("decision", verdict="yes", value=np.int64(3))

    out = tmp_path / "run.trace.json"
    write_chrome_trace(str(out))
    trace = json.loads(out.read_text())

    assert isinstance(trace["traceEvents"], list) and trace["traceEvents"]
    phases = {e["ph"] for e in trace["traceEvents"]}
    assert "X" in phases and "i" in phases and "M" in phases
    for e in trace["traceEvents"]:
        assert isinstance(e["name"], str)
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] in ("X", "i"):
            assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        if e["ph"] == "X":
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
            assert isinstance(e["args"]["path"], str)
        if e["ph"] == "i":
            assert e["s"] in ("g", "p", "t")
    # numpy attr values were coerced to JSON scalars
    inst = next(e for e in trace["traceEvents"] if e["ph"] == "i")
    assert inst["args"]["value"] == 3


# ---------------------------------------------------------------------------
# run report: end-to-end, JSON round trip, checked-in schema
# ---------------------------------------------------------------------------


def test_run_report_roundtrip_and_schema(tmp_path):
    from kaminpar_tpu.telemetry.report import SCHEMA_PATH, write_run_report

    from kaminpar_tpu.utils.logger import OutputLevel

    telemetry.enable()
    g = factories.make_grid_graph(16, 16)
    p = ktp.KaMinPar("default")
    p.set_output_level(OutputLevel.QUIET)
    part = p.set_graph(g).compute_partition(k=4, epsilon=0.05, seed=1)
    assert len(part) == g.n

    out = tmp_path / "report.json"
    report = write_run_report(str(out), extra_run={"io_seconds": 0.0})

    # round-trips through json.loads unchanged
    loaded = json.loads(out.read_text())
    assert loaded == json.loads(json.dumps(report))

    # headline content
    assert loaded["schema_version"] == 14
    assert loaded["run"]["k"] == 4
    assert loaded["run"]["graph"]["n"] == g.n
    assert loaded["result"]["cut"] >= 0
    assert isinstance(loaded["result"]["feasible"], bool)
    assert "partitioning" in loaded["scope_tree"]
    assert loaded["comm"]["caveat"]
    assert loaded["lane_gather"]["mode"] in (
        "not-probed", "probed", "forced-on", "opt-out"
    )
    # schema v2 sections: non-empty progress (at least one LP series
    # with per-iteration moved values and one Jet series with cut
    # values) and compile accounting with per-phase seconds
    prog = loaded["progress"]
    assert prog, "v2 report must carry progress series"
    lp_series = [p for p in prog if p["kind"] == "lp"]
    jet_or_fm = [p for p in prog if p["kind"] in ("jet", "fm")]
    assert lp_series and "moved" in lp_series[0]["series"]
    assert jet_or_fm
    jets = [p for p in jet_or_fm if p["kind"] == "jet"]
    assert jets and jets[0]["series"]["cut"], jets
    assert jets[0]["iterations"] == len(jets[0]["series"]["cut"])
    assert all(p["path"] for p in prog)  # scope-tree aligned
    comp = loaded["compile"]
    # in-process jit caches may legitimately absorb every compile by the
    # time this test runs, so the count is not asserted positive here
    # (check_all.sh's fresh-process chaos stage pins `compiles > 0`);
    # the structure and key set must be intact either way
    assert "caveat" in comp and isinstance(comp["phases"], dict)
    for key in ("trace_s", "lower_s", "compile_s", "compiles",
                "persistent_cache_hits", "persistent_cache_misses"):
        assert key in comp["totals"], key
    # schema v3/v4 sections: well-formed defaults for a run that used
    # neither checkpointing, a deadline budget, nor the serving layer
    assert loaded["checkpoint"] == {"enabled": False}
    assert loaded["anytime"] == {"anytime": False}
    assert loaded["serving"] == {"enabled": False}
    # schema v5 perf section: the observatory ran with telemetry (pad
    # rows always accrue; roofline rows depend on cold compiles, so
    # only the structure is pinned here — check_all's fresh-process
    # stage asserts non-empty cost rows)
    perf_sec = loaded["perf"]
    assert perf_sec["enabled"] is True
    for key in ("peaks", "totals", "roofline", "memory", "pad_waste"):
        assert key in perf_sec, key
    assert perf_sec["pad_waste"], "pad sites recorded nothing"
    assert perf_sec["memory"]["samples"], "barriers sampled nothing"
    assert perf_sec["peaks"]["gbps"] > 0

    # validates against the checked-in schema (drift backstop)
    checker = _load_checker()
    schema = json.loads(open(SCHEMA_PATH).read())
    errors = checker.validate_instance(loaded, schema)
    assert errors == [], errors
    # and through the CLI entry point
    assert checker.main([str(out)]) == 0


def test_check_report_schema_rejects_drift(tmp_path):
    from kaminpar_tpu.telemetry.report import SCHEMA_PATH

    checker = _load_checker()
    schema = json.loads(open(SCHEMA_PATH).read())
    broken = {"schema_version": "one", "run": {}}  # wrong type + missing keys
    errors = checker.validate_instance(broken, schema)
    assert any("schema_version" in e for e in errors)
    assert any("missing required" in e for e in errors)
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(broken))
    assert checker.main([str(bad)]) == 1


def test_cli_trace_and_report(tmp_path):
    """`--trace-out` + `--report-json` on a sample graph produce a valid
    trace-event file and a schema-conforming report (acceptance path)."""
    from kaminpar_tpu import cli

    graph_path = tmp_path / "g.metis"
    g = factories.make_grid_graph(12, 12)
    from kaminpar_tpu.io.metis import write_metis

    write_metis(g, str(graph_path))
    trace_path = tmp_path / "t.json"
    report_path = tmp_path / "r.json"
    rc = cli.main(
        [
            str(graph_path), "-k", "2", "-q",
            "--trace-out", str(trace_path),
            "--report-json", str(report_path),
        ]
    )
    assert rc == 0
    trace = json.loads(trace_path.read_text())
    assert any(e["ph"] == "X" for e in trace["traceEvents"])
    report = json.loads(report_path.read_text())
    checker = _load_checker()
    from kaminpar_tpu.telemetry.report import SCHEMA_PATH

    schema = json.loads(open(SCHEMA_PATH).read())
    assert checker.validate_instance(report, schema) == []
    assert report["result"]["cut"] >= 0


# ---------------------------------------------------------------------------
# progress layer: zero-overhead-when-disabled, series capture, counters
# ---------------------------------------------------------------------------


def _tiny_refine_setup():
    import jax.numpy as jnp

    from kaminpar_tpu.graphs.csr import device_graph_from_host

    g = factories.make_grid_graph(8, 8)
    dg = device_graph_from_host(g)
    part0 = jnp.asarray((np.arange(dg.n_pad) % 4).astype(np.int32))
    mbw = jnp.asarray(np.full(4, g.n, dtype=np.int64).astype(np.int32))
    return dg, part0, mbw


def test_zero_overhead_jaxpr_when_disabled():
    """The zero-overhead contract: with telemetry off the instrumented
    loops trace to the IDENTICAL jaxpr (no extra carry, no retrace) —
    the stats buffer is an optional pytree leaf that is None when
    disabled, and enabling/disabling telemetry must not latch."""
    import jax
    import jax.numpy as jnp

    from kaminpar_tpu.ops import lp as lp_mod
    from kaminpar_tpu.telemetry import progress as progress_mod

    dg, part0, mbw = _tiny_refine_setup()
    cfg = lp_mod.LPConfig(refinement=True)

    def trace_public():
        return str(jax.make_jaxpr(
            lambda p: lp_mod.lp_refine(
                dg, p, 4, mbw, jnp.int32(1), cfg, num_iterations=2
            )
        )(part0))

    assert not telemetry.enabled()
    before = trace_public()
    telemetry.enable()
    telemetry.disable()
    after = trace_public()  # toggling must not latch instrumentation
    assert before == after

    # the instrumented variant REALLY differs: one extra while-carry
    def fused(p, stats):
        out = lp_mod._lp_refine_fused(
            dg, p, 4, mbw, jnp.int32(1), cfg, 2, None, stats
        )
        return out[0] if isinstance(out, tuple) else out

    j_off = jax.make_jaxpr(lambda p: fused(p, None))(part0)
    buf = progress_mod.new_buffer(2, 2)
    j_on = jax.make_jaxpr(lambda p, b: fused(p, b))(part0, buf)

    def iter_eqns(jaxpr):
        for e in jaxpr.eqns:
            yield e
            for v in e.params.values():
                subs = v if isinstance(v, (tuple, list)) else (v,)
                for sub in subs:
                    inner = getattr(sub, "jaxpr", None)
                    if inner is not None:
                        yield from iter_eqns(inner)

    def carry_width(jaxpr):
        whiles = [
            e for e in iter_eqns(jaxpr.jaxpr)
            if e.primitive.name == "while"
        ]
        assert whiles, "expected a lax.while_loop in the refine jaxpr"
        return max(len(e.outvars) for e in whiles)

    assert carry_width(j_on) == carry_width(j_off) + 1
    assert str(j_on) != str(j_off)


def test_progress_capture_gates_on_telemetry(monkeypatch):
    from kaminpar_tpu.telemetry import progress as progress_mod

    assert not progress_mod.capture()
    telemetry.enable()
    assert progress_mod.capture()
    monkeypatch.setenv(progress_mod.ENV_VAR, "0")
    assert not progress_mod.capture()  # explicit opt-out wins


def test_progress_buffer_roundtrip_and_gap_compression():
    """record/emit round trip: sentinel rows (early-converged loops,
    cross-round gaps) are compressed out, loop order preserved."""
    import jax.numpy as jnp

    from kaminpar_tpu.telemetry import progress as progress_mod

    telemetry.enable()
    buf = progress_mod.new_buffer(6, 2)
    buf = progress_mod.record(buf, jnp.int32(0), jnp.int32(5), jnp.int32(50))
    buf = progress_mod.record(buf, jnp.int32(1), jnp.int32(3), jnp.int32(30))
    # gap at rows 2-3 (a round that early-exited), then a later round
    buf = progress_mod.record(buf, jnp.int32(4), jnp.int32(1), jnp.int32(10))
    # out-of-range row must drop, not clamp onto row 5
    buf = progress_mod.record(buf, jnp.int32(99), jnp.int32(7), jnp.int32(70))
    with progress_mod.tag(level=3):
        progress_mod.emit("lp", ("moved", "active"), buf, round=1)
    series = telemetry.progress_series("lp")
    assert len(series) == 1
    s = series[0]
    assert s.iterations == 3
    assert s.series["moved"] == [5, 3, 1]
    assert s.series["active"] == [50, 30, 10]
    assert s.attrs["level"] == 3 and s.attrs["round"] == 1


def test_balancer_progress_series():
    """An infeasible input drives real balancer rounds; the series
    records per-round moved nodes and residual violation mass."""
    import jax.numpy as jnp

    from kaminpar_tpu.graphs.csr import device_graph_from_host
    from kaminpar_tpu.ops.balancer import overload_balance

    telemetry.enable()
    g = factories.make_grid_graph(8, 8)
    dg = device_graph_from_host(g)
    part = jnp.zeros(dg.n_pad, dtype=jnp.int32)  # everything in block 0
    caps = jnp.asarray(np.full(4, 20, dtype=np.int64).astype(np.int32))
    out = overload_balance(dg, part, 4, caps, jnp.int32(1))
    assert out.shape == part.shape
    series = telemetry.progress_series("balancer")
    assert len(series) == 1
    s = series[0]
    assert s.attrs["direction"] == "overload"
    assert s.iterations >= 1
    assert sum(s.series["moved"]) > 0
    # violation mass is non-increasing across rounds
    viol = s.series["violation"]
    assert all(b <= a for a, b in zip(viol, viol[1:]))


def test_fm_numpy_progress_series(monkeypatch):
    import jax.numpy as jnp

    from kaminpar_tpu.graphs.csr import device_graph_from_host
    from kaminpar_tpu.refinement.fm import fm_refine_host

    telemetry.enable()
    monkeypatch.setenv("KAMINPAR_TPU_NO_NATIVE_FM", "1")
    g = factories.make_grid_graph(8, 8)
    dg = device_graph_from_host(g)
    rng = np.random.default_rng(0)
    part = jnp.asarray(
        rng.integers(0, 4, dg.n_pad).astype(np.int32)
    )
    fm_ctx = ktp.context_from_preset("default").refinement.fm
    max_bw = np.full(4, g.n, dtype=np.int64)
    fm_refine_host(dg, part, 4, max_bw, fm_ctx, seed=0)
    series = telemetry.progress_series("fm")
    assert len(series) == 1
    s = series[0]
    assert s.attrs["engine"] == "numpy"
    assert s.iterations >= 1
    assert len(s.series["gain"]) == s.iterations
    assert len(s.series["moved"]) == s.iterations


def test_chrome_trace_metadata_and_counter_tracks(tmp_path):
    """Satellite: rank-labeled process/thread metadata tracks and
    ("ph": "C") counter tracks rendered from progress series."""
    from kaminpar_tpu.telemetry import progress as progress_mod
    from kaminpar_tpu.telemetry.chrome_trace import write_chrome_trace

    telemetry.enable()
    t = timer.Timer()
    with t.scope("phase"):
        pass
    buf = progress_mod.new_buffer(3, 1)
    import jax.numpy as jnp

    for i in range(3):
        buf = progress_mod.record(buf, jnp.int32(i), jnp.int32(9 - i))
    progress_mod.emit("lp", ("moved",), buf)

    out = tmp_path / "t.json"
    write_chrome_trace(str(out))
    trace = json.loads(out.read_text())
    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    names = {e["name"] for e in meta}
    assert "process_name" in names and "thread_name" in names
    proc = next(e for e in meta if e["name"] == "process_name")
    assert "rank" in proc["args"]["name"]
    counters = [e for e in trace["traceEvents"]
                if e["ph"] == "C" and e["cat"] == "progress"]
    assert len(counters) == 3
    assert counters[0]["name"] == "lp.moved"
    assert [c["args"]["moved"] for c in counters] == [9, 8, 7]
    # counter timestamps are monotone within the series window
    ts = [c["ts"] for c in counters]
    assert ts == sorted(ts) and all(x >= 0 for x in ts)
    # the series pull itself is metered (schema v13): the execution
    # ledger's cumulative transfer-bytes track rides the same trace
    xfer = [e for e in trace["traceEvents"]
            if e["ph"] == "C" and e["name"] == "transfer-bytes"]
    assert xfer and xfer[-1]["args"]["d2h_total"] > 0


# ---------------------------------------------------------------------------
# compile-cost accounting
# ---------------------------------------------------------------------------


def test_compile_accounting_attributes_to_open_scope():
    import jax
    import jax.numpy as jnp

    telemetry.enable()  # installs the jax.monitoring listeners
    from kaminpar_tpu.telemetry import compile_account

    compile_account.reset()
    with timer.GLOBAL_TIMER.scope("compile-probe"):
        # a fresh function identity forces a real trace+compile
        jax.jit(lambda x: x * 2 + 1)(jnp.arange(8)).block_until_ready()
    snap = compile_account.snapshot()
    assert snap["totals"]["compiles"] >= 1
    assert snap["totals"]["compile_s"] > 0
    assert "compile-probe" in snap["phases"]
    assert snap["phases"]["compile-probe"]["compiles"] >= 1
    # disabled: the listeners stay installed but record nothing
    compile_account.reset()
    telemetry.disable()
    jax.jit(lambda x: x * 3 + 2)(jnp.arange(8)).block_until_ready()
    assert compile_account.snapshot()["totals"]["compiles"] == 0


# ---------------------------------------------------------------------------
# telemetry.diff: regression gate
# ---------------------------------------------------------------------------


def _reference_report(cut=100, wall=10.0):
    return {
        "schema_version": 2,
        "run": {"partition_seconds": wall},
        "result": {"cut": cut, "imbalance": 0.0, "feasible": True},
        "scope_tree": {
            "partitioning": {
                "elapsed_s": wall, "count": 1,
                "children": {
                    "coarsening": {
                        "elapsed_s": wall / 2, "count": 1, "children": {}
                    }
                },
            }
        },
        "progress": [
            {"kind": "jet", "path": "partitioning.jet", "t0": 0.0,
             "t1": 1.0, "iterations": 3,
             "series": {"cut": [120, 110, cut], "moved": [5, 3, 0]},
             "attrs": {"round": 0}},
        ],
        "compile": {"caveat": "c", "totals": {"compile_s": 1.0,
                                              "compiles": 3},
                    "phases": {}},
    }


def test_diff_identical_reports_pass(tmp_path, capsys):
    from kaminpar_tpu.telemetry import diff as diff_mod

    a = tmp_path / "a.json"
    a.write_text(json.dumps(_reference_report()))
    assert diff_mod.main([str(a), str(a)]) == 0
    out = capsys.readouterr().out
    assert "DIFF OK" in out


def test_diff_detects_cut_and_wall_regressions(tmp_path, capsys):
    from kaminpar_tpu.telemetry import diff as diff_mod

    base = tmp_path / "base.json"
    base.write_text(json.dumps(_reference_report()))
    # injected 20% regressions must fail at the default 10% thresholds
    worse_cut = tmp_path / "cut.json"
    worse_cut.write_text(json.dumps(_reference_report(cut=120)))
    assert diff_mod.main([str(base), str(worse_cut)]) == 1
    worse_wall = tmp_path / "wall.json"
    worse_wall.write_text(json.dumps(_reference_report(wall=12.0)))
    assert diff_mod.main([str(base), str(worse_wall)]) == 1
    # ...and pass when the caller raises the thresholds
    assert diff_mod.main(
        [str(base), str(worse_cut), "--cut-threshold", "0.5"]
    ) == 0
    assert diff_mod.main(
        [str(base), str(worse_wall), "--wall-threshold", "0.5"]
    ) == 0
    err = capsys.readouterr().err
    assert "REGRESSION" in err


def test_diff_feasibility_regression_and_json_mode(tmp_path, capsys):
    from kaminpar_tpu.telemetry import diff as diff_mod

    base = tmp_path / "base.json"
    base.write_text(json.dumps(_reference_report()))
    infeasible = _reference_report()
    infeasible["result"]["feasible"] = False
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(infeasible))
    assert diff_mod.main([str(base), str(bad), "--json"]) == 1
    verdict = json.loads(capsys.readouterr().out.strip())
    assert verdict["pass"] is False
    assert any("feasibility" in f for f in verdict["failures"])


def test_diff_bad_input_is_usage_error(tmp_path):
    from kaminpar_tpu.telemetry import diff as diff_mod

    junk = tmp_path / "junk.json"
    junk.write_text("{}")
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(_reference_report()))
    assert diff_mod.main([str(junk), str(ok)]) == 2
    assert diff_mod.main([str(tmp_path / "missing.json"), str(ok)]) == 2


def test_diff_aligns_progress_by_kind_path_level(tmp_path, capsys):
    from kaminpar_tpu.telemetry import diff as diff_mod

    base = _reference_report()
    cand = _reference_report()
    cand["progress"][0]["iterations"] = 2
    cand["progress"][0]["series"]["cut"] = [120, 100]
    cand["progress"][0]["series"]["moved"] = [5, 0]
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(base))
    b.write_text(json.dumps(cand))
    assert diff_mod.main([str(a), str(b)]) == 0  # convergence is info-only
    out = capsys.readouterr().out
    assert "iters 3 -> 2" in out


# ---------------------------------------------------------------------------
# schema v1..v7 transition (scripts/check_report_schema.py)
# ---------------------------------------------------------------------------


def test_schema_accepts_v1_through_v7(tmp_path):
    from kaminpar_tpu.telemetry.report import SCHEMA_PATH

    checker = _load_checker()
    schema = json.loads(open(SCHEMA_PATH).read())
    v1 = checker._minimal_v1_report()
    assert checker.validate_instance(v1, schema) == []
    assert checker.version_checks(v1) == []
    # a v2 report without its sections must be rejected...
    v2_missing = dict(v1, schema_version=2)
    assert any(
        "progress" in e or "compile" in e
        for e in checker.version_checks(v2_missing)
    )
    # ...and a complete v2 fixture accepted
    v2 = checker._minimal_v2_report()
    assert checker.validate_instance(v2, schema) == []
    assert checker.version_checks(v2) == []
    # v3 additionally requires the checkpoint/anytime sections
    v3_missing = dict(v2, schema_version=3)
    assert any(
        "checkpoint" in e or "anytime" in e
        for e in checker.version_checks(v3_missing)
    )
    v3 = checker._minimal_v3_report()
    assert checker.validate_instance(v3, schema) == []
    assert checker.version_checks(v3) == []
    # v4 additionally requires the serving section
    v4_missing = dict(v3, schema_version=4)
    assert any("serving" in e for e in checker.version_checks(v4_missing))
    v4 = checker._minimal_v4_report()
    assert checker.validate_instance(v4, schema) == []
    assert checker.version_checks(v4) == []
    # v5 additionally requires the perf section
    v5_missing = dict(v4, schema_version=5)
    assert any("perf" in e for e in checker.version_checks(v5_missing))
    v5 = checker._minimal_v5_report()
    assert checker.validate_instance(v5, schema) == []
    assert checker.version_checks(v5) == []
    # v6 additionally requires the memory_budget section
    v6_missing = dict(v5, schema_version=6)
    assert any("memory_budget" in e
               for e in checker.version_checks(v6_missing))
    v6 = checker._minimal_v6_report()
    assert checker.validate_instance(v6, schema) == []
    assert checker.version_checks(v6) == []
    # v7 additionally requires the quality section
    v7_missing = dict(v6, schema_version=7)
    assert any("quality" in e for e in checker.version_checks(v7_missing))
    v7 = checker._minimal_v7_report()
    assert checker.validate_instance(v7, schema) == []
    assert checker.version_checks(v7) == []
    # v8 additionally requires the dist_resilience section
    v8_missing = dict(v7, schema_version=8)
    assert any("dist_resilience" in e
               for e in checker.version_checks(v8_missing))
    v8 = checker._minimal_v8_report()
    assert checker.validate_instance(v8, schema) == []
    assert checker.version_checks(v8) == []
    # v9 additionally requires the external section
    v9_missing = dict(v8, schema_version=9)
    assert any("external" in e
               for e in checker.version_checks(v9_missing))
    v9 = checker._minimal_v9_report()
    assert checker.validate_instance(v9, schema) == []
    assert checker.version_checks(v9) == []
    # v10 additionally requires the supervision section
    v10_missing = dict(v9, schema_version=10)
    assert any("supervision" in e
               for e in checker.version_checks(v10_missing))
    v10 = checker._minimal_v10_report()
    assert checker.validate_instance(v10, schema) == []
    assert checker.version_checks(v10) == []
    # v11 additionally requires the dynamic section
    v11_missing = dict(v10, schema_version=11)
    assert any("dynamic" in e
               for e in checker.version_checks(v11_missing))
    v11 = checker._minimal_v11_report()
    assert checker.validate_instance(v11, schema) == []
    assert checker.version_checks(v11) == []
    # v12 additionally requires the tracing section
    v12_missing = dict(v11, schema_version=12)
    assert any("tracing" in e
               for e in checker.version_checks(v12_missing))
    v12 = dict(v12_missing, tracing={"enabled": False, "traces": []})
    assert checker.validate_instance(v12, schema) == []
    assert checker.version_checks(v12) == []
    # v13 additionally requires the ledger section
    v13_missing = dict(v12, schema_version=13)
    assert any("ledger" in e
               for e in checker.version_checks(v13_missing))
    v13 = dict(v13_missing, ledger={"enabled": False})
    assert checker.validate_instance(v13, schema) == []
    assert checker.version_checks(v13) == []
    # v14 additionally requires the integrity section
    v14_missing = dict(v13, schema_version=14)
    assert any("integrity" in e
               for e in checker.version_checks(v14_missing))
    v14 = dict(v14_missing, integrity={"enabled": False})
    assert checker.validate_instance(v14, schema) == []
    assert checker.version_checks(v14) == []
    # v15 is not a known version
    v15 = dict(v1, schema_version=15)
    assert any("schema_version" in e
               for e in checker.validate_instance(v15, schema))
    # CLI path: the v1 fixture as a file validates end to end
    p = tmp_path / "v1.json"
    p.write_text(json.dumps(v1))
    assert checker.main([str(p)]) == 0


# ---------------------------------------------------------------------------
# decision events on forced code paths
# ---------------------------------------------------------------------------


def test_lane_gather_force_enable_event(monkeypatch):
    from kaminpar_tpu.ops import lane_gather

    telemetry.enable()
    monkeypatch.setenv("KAMINPAR_TPU_LANE_GATHER", "1")
    monkeypatch.setattr(lane_gather, "_PROBE_STATUS", {"mode": "not-probed"})

    import jax.numpy as jnp

    class G:
        pass

    g = G()
    g.n_pad = 128
    g.dst = jnp.asarray(np.arange(64) % 128, dtype=jnp.int32)
    g.src = jnp.asarray(np.arange(64) % 128, dtype=jnp.int32)
    g.edge_w = jnp.ones(64, dtype=jnp.int32)
    plans = lane_gather.maybe_edge_plans(g)
    # force-enable skips the size gate and the timing race, but the
    # platform/correctness gate still applies — on the CPU test backend
    # the Mosaic kernel is unavailable, so routing stays off (no crash)
    assert plans is None
    events = telemetry.events("lane-gather-probe")
    assert len(events) == 1 and events[0].attrs["verdict"] == "forced-on"
    assert events[0].attrs["supported"] is False
    assert "reason" in events[0].attrs
    status = lane_gather.probe_status()
    assert status["mode"] == "forced-on"
    assert status["env_override"] == "1"
    # the decision is cached: a second call emits no duplicate event
    assert lane_gather.maybe_edge_plans(g) is None
    assert len(telemetry.events("lane-gather-probe")) == 1


def test_lane_gather_opt_out_status(monkeypatch):
    from kaminpar_tpu.ops import lane_gather

    monkeypatch.setenv("KAMINPAR_TPU_LANE_GATHER", "0")
    monkeypatch.setattr(lane_gather, "_PROBE_STATUS", {"mode": "not-probed"})

    class G:
        pass

    g = G()
    assert lane_gather.maybe_edge_plans(g) is None
    assert lane_gather.probe_status()["mode"] == "opt-out"


def test_lane_gather_probe_event_records_verdict(monkeypatch):
    from kaminpar_tpu.ops import lane_gather

    telemetry.enable()
    monkeypatch.delenv("KAMINPAR_TPU_LANE_GATHER", raising=False)
    lane_gather.lane_gather_supported.cache_clear()
    try:
        supported = lane_gather.lane_gather_supported()
        # CPU test platform: the Mosaic kernel is unavailable
        assert supported is False
        events = telemetry.events("lane-gather-probe")
        assert len(events) == 1
        assert events[0].attrs["verdict"] == "disabled"
        assert "reason" in events[0].attrs
        assert lane_gather.probe_status()["mode"] == "probed"
    finally:
        lane_gather.lane_gather_supported.cache_clear()


def test_fm_refusal_sentinel_and_event():
    from kaminpar_tpu import native

    if not native.available():
        pytest.skip("native library unavailable (no compiler)")
    telemetry.enable()
    g = factories.make_path(8)
    k = 0x10000 + 1  # above the sparse engine's 16-bit tag limit
    part = np.arange(8, dtype=np.int32) % 4
    max_bw = np.full(k, 100, dtype=np.int64)
    fm_ctx = ktp.context_from_preset("default").refinement.fm
    ret = native.fm_refine(
        g, part, k, max_bw, fm_ctx, seed=0, force_sparse=True
    )
    assert ret == native.FM_REFUSED
    events = telemetry.events("fm-refused")
    assert len(events) == 1
    assert events[0].attrs["k"] == k


def test_fm_runs_normally_below_limit():
    from kaminpar_tpu import native

    if not native.available():
        pytest.skip("native library unavailable (no compiler)")
    telemetry.enable()
    g = factories.make_grid_graph(8, 8)
    rng = np.random.default_rng(0)
    part = rng.integers(0, 4, g.n).astype(np.int32)
    max_bw = np.full(4, g.n, dtype=np.int64)
    fm_ctx = ktp.context_from_preset("default").refinement.fm
    ret = native.fm_refine(g, part, 4, max_bw, fm_ctx, seed=0)
    assert ret is not None and ret >= 0
    assert telemetry.events("fm-refused") == []


# ---------------------------------------------------------------------------
# comm accounting: shape keying, retrace events, caveat
# ---------------------------------------------------------------------------


def test_comm_accounting_shape_keyed_with_caveat():
    from kaminpar_tpu.parallel import mesh

    telemetry.enable()
    mesh.reset_comm_log()
    try:
        with mesh.comm_phase("phase-a"):
            mesh.account_collective("psum(x)", 128, shape=(4, 8))
            mesh.account_collective("psum(x)", 128, shape=(4, 8))
            mesh.account_collective("psum(x)", 64, shape=(2, 8))  # retrace
        records = mesh.comm_records()
        assert len(records) == 2  # one row per traced shape
        by_shape = {tuple(r["shape"]): r for r in records}
        assert by_shape[(4, 8)]["traced_calls"] == 2
        assert by_shape[(4, 8)]["payload_bytes_per_device"] == 256
        assert by_shape[(2, 8)]["traced_calls"] == 1
        table = mesh.comm_table()
        assert "TRACE time" in table or "cache" in table  # the caveat
        traces = telemetry.events("jit-trace")
        assert len(traces) == 2
        assert [e.attrs["retrace"] for e in traces] == [False, True]
    finally:
        mesh.reset_comm_log()


def test_comm_table_marks_cache_hit_phases():
    """A phase opened with ZERO traced collectives is an executable-cache
    hit — comm_table must say so explicitly instead of leaving it
    indistinguishable from a silent phase (ADVICE round 5 low #4)."""
    from kaminpar_tpu.parallel import mesh

    mesh.reset_comm_log()
    try:
        with mesh.comm_phase("warm"):
            mesh.account_collective("psum(x)", 128, shape=(4, 8))
        # second opening: program cached, nothing traces
        with mesh.comm_phase("warm"):
            pass
        with mesh.comm_phase("cold-cache-hit"):
            pass  # opened, traced nothing at all
        assert mesh.phase_opens() == {"warm": 2, "cold-cache-hit": 1}
        assert mesh.cache_hit_phases() == ["cold-cache-hit"]
        table = mesh.comm_table()
        assert "cold-cache-hit" in table and "cache-hit" in table
        # the traced row notes its extra (cached) openings
        assert "opened 2x" in table
        from kaminpar_tpu.telemetry.report import build_run_report

        report = build_run_report()
        assert report["comm"]["phase_opens"]["warm"] == 2
    finally:
        mesh.reset_comm_log()


def test_dist_run_populates_comm_records():
    from kaminpar_tpu.parallel import dKaMinPar, make_mesh, mesh

    from kaminpar_tpu.parallel.dist_context import (
        create_dist_context_by_preset_name,
    )

    telemetry.enable()
    mesh.reset_comm_log()
    try:
        g = factories.make_grid_graph(32, 32)
        ctx = create_dist_context_by_preset_name("default")
        # force a distributed coarsening level so collectives trace
        ctx.shm.coarsening.contraction_limit = 50
        ctx.replication_min_nodes_per_device = 0
        solver = dKaMinPar(ctx, mesh=make_mesh(2))
        try:
            part = solver.set_graph(g).compute_partition(k=2, seed=1)
        except TypeError as e:
            # older jax: shard_map lacks check_vma — the whole dist layer
            # is unavailable in this environment, not a telemetry defect
            pytest.skip(f"dist layer unavailable on this jax: {e}")
        assert len(part) == g.n
        from kaminpar_tpu.telemetry.report import build_run_report

        report = build_run_report()
        assert report["run"].get("devices") == 2
        assert report["result"]["cut"] >= 0
        # at least one collective was traced and attributed to a phase
        assert report["comm"]["records"], report["comm"]
        # the record=True shard_map path: the dist loops must emit
        # progress series built from already-replicated scalars (this is
        # the ONLY coverage of the tuple-out_specs variant, so keep it
        # in the same test that proves the dist layer works at all)
        dist_series = [
            p for p in report["progress"]
            if p["kind"] in ("dist-lp", "dist-jet")
        ]
        assert dist_series, [p["kind"] for p in report["progress"]]
        assert any(
            p["series"].get("moved") or p["series"].get("cut")
            for p in dist_series
        )
    finally:
        mesh.reset_comm_log()
