"""Telemetry layer tests: span/event stream, exporters, satellites.

Covers the observability contract: span nesting mirrors the timer tree,
disabled mode records nothing, the Chrome-trace export conforms to the
trace-event schema, the run report round-trips through JSON and passes
the checked-in schema (scripts/check_report_schema.py — the tier-1
schema-drift backstop), and the lane-gather / FM decision events fire on
forced code paths.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

import kaminpar_tpu as ktp
from kaminpar_tpu import telemetry
from kaminpar_tpu.graphs import factories
from kaminpar_tpu.utils import timer

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_report_schema",
        os.path.join(_REPO, "scripts", "check_report_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


# ---------------------------------------------------------------------------
# core stream
# ---------------------------------------------------------------------------


def test_disabled_mode_is_noop():
    t = timer.Timer()
    with t.scope("a"):
        with t.scope("b"):
            pass
    telemetry.event("should-not-record", x=1)
    telemetry.annotate(k=16)
    assert telemetry.spans() == []
    assert telemetry.events() == []
    assert telemetry.run_info() == {}
    # the timer itself still recorded normally
    assert t.elapsed("a") >= 0.0 and t.root.children["a"].count == 1


def test_span_nesting_matches_timer_tree():
    telemetry.enable()
    t = timer.Timer()
    with t.scope("a"):
        with t.scope("b"):
            pass
        with t.scope("b"):  # second visit of the same tree node
            pass
    with t.scope("c"):
        pass
    spans = telemetry.spans()
    paths = [s.path for s in spans]
    # children close before parents (exit-order stream)
    assert paths == ["a.b", "a.b", "a", "c"]
    # every span path exists in the timer tree with matching totals
    by_path = {}
    for s in spans:
        by_path.setdefault(s.path, []).append(s)
    for path, ss in by_path.items():
        node_elapsed = t.elapsed(*path.split("."))
        assert node_elapsed >= sum(s.duration for s in ss) - 1e-6
    # nesting: the child span lies within its parent's window
    parent = next(s for s in spans if s.path == "a")
    for child in (s for s in spans if s.path == "a.b"):
        assert child.start >= parent.start - 1e-9
        assert child.start + child.duration <= (
            parent.start + parent.duration + 1e-6
        )


def test_reset_guard_when_nested():
    telemetry.enable()
    telemetry.event("outer")
    assert timer.GLOBAL_TIMER.idle()
    with timer.GLOBAL_TIMER.scope("open"):
        assert not timer.GLOBAL_TIMER.idle()
    assert len(telemetry.events()) == 1


# ---------------------------------------------------------------------------
# Chrome-trace exporter
# ---------------------------------------------------------------------------


def test_chrome_trace_conforms_to_trace_event_schema(tmp_path):
    from kaminpar_tpu.telemetry.chrome_trace import write_chrome_trace

    telemetry.enable()
    t = timer.Timer()
    with t.scope("phase"):
        with t.scope("inner"):
            pass
    telemetry.event("decision", verdict="yes", value=np.int64(3))

    out = tmp_path / "run.trace.json"
    write_chrome_trace(str(out))
    trace = json.loads(out.read_text())

    assert isinstance(trace["traceEvents"], list) and trace["traceEvents"]
    phases = {e["ph"] for e in trace["traceEvents"]}
    assert "X" in phases and "i" in phases and "M" in phases
    for e in trace["traceEvents"]:
        assert isinstance(e["name"], str)
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] in ("X", "i"):
            assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        if e["ph"] == "X":
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
            assert isinstance(e["args"]["path"], str)
        if e["ph"] == "i":
            assert e["s"] in ("g", "p", "t")
    # numpy attr values were coerced to JSON scalars
    inst = next(e for e in trace["traceEvents"] if e["ph"] == "i")
    assert inst["args"]["value"] == 3


# ---------------------------------------------------------------------------
# run report: end-to-end, JSON round trip, checked-in schema
# ---------------------------------------------------------------------------


def test_run_report_roundtrip_and_schema(tmp_path):
    from kaminpar_tpu.telemetry.report import SCHEMA_PATH, write_run_report

    from kaminpar_tpu.utils.logger import OutputLevel

    telemetry.enable()
    g = factories.make_grid_graph(16, 16)
    p = ktp.KaMinPar("default")
    p.set_output_level(OutputLevel.QUIET)
    part = p.set_graph(g).compute_partition(k=4, epsilon=0.05, seed=1)
    assert len(part) == g.n

    out = tmp_path / "report.json"
    report = write_run_report(str(out), extra_run={"io_seconds": 0.0})

    # round-trips through json.loads unchanged
    loaded = json.loads(out.read_text())
    assert loaded == json.loads(json.dumps(report))

    # headline content
    assert loaded["schema_version"] == 1
    assert loaded["run"]["k"] == 4
    assert loaded["run"]["graph"]["n"] == g.n
    assert loaded["result"]["cut"] >= 0
    assert isinstance(loaded["result"]["feasible"], bool)
    assert "partitioning" in loaded["scope_tree"]
    assert loaded["comm"]["caveat"]
    assert loaded["lane_gather"]["mode"] in (
        "not-probed", "probed", "forced-on", "opt-out"
    )

    # validates against the checked-in schema (drift backstop)
    checker = _load_checker()
    schema = json.loads(open(SCHEMA_PATH).read())
    errors = checker.validate_instance(loaded, schema)
    assert errors == [], errors
    # and through the CLI entry point
    assert checker.main([str(out)]) == 0


def test_check_report_schema_rejects_drift(tmp_path):
    from kaminpar_tpu.telemetry.report import SCHEMA_PATH

    checker = _load_checker()
    schema = json.loads(open(SCHEMA_PATH).read())
    broken = {"schema_version": "one", "run": {}}  # wrong type + missing keys
    errors = checker.validate_instance(broken, schema)
    assert any("schema_version" in e for e in errors)
    assert any("missing required" in e for e in errors)
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(broken))
    assert checker.main([str(bad)]) == 1


def test_cli_trace_and_report(tmp_path):
    """`--trace-out` + `--report-json` on a sample graph produce a valid
    trace-event file and a schema-conforming report (acceptance path)."""
    from kaminpar_tpu import cli

    graph_path = tmp_path / "g.metis"
    g = factories.make_grid_graph(12, 12)
    from kaminpar_tpu.io.metis import write_metis

    write_metis(g, str(graph_path))
    trace_path = tmp_path / "t.json"
    report_path = tmp_path / "r.json"
    rc = cli.main(
        [
            str(graph_path), "-k", "2", "-q",
            "--trace-out", str(trace_path),
            "--report-json", str(report_path),
        ]
    )
    assert rc == 0
    trace = json.loads(trace_path.read_text())
    assert any(e["ph"] == "X" for e in trace["traceEvents"])
    report = json.loads(report_path.read_text())
    checker = _load_checker()
    from kaminpar_tpu.telemetry.report import SCHEMA_PATH

    schema = json.loads(open(SCHEMA_PATH).read())
    assert checker.validate_instance(report, schema) == []
    assert report["result"]["cut"] >= 0


# ---------------------------------------------------------------------------
# decision events on forced code paths
# ---------------------------------------------------------------------------


def test_lane_gather_force_enable_event(monkeypatch):
    from kaminpar_tpu.ops import lane_gather

    telemetry.enable()
    monkeypatch.setenv("KAMINPAR_TPU_LANE_GATHER", "1")
    monkeypatch.setattr(lane_gather, "_PROBE_STATUS", {"mode": "not-probed"})

    import jax.numpy as jnp

    class G:
        pass

    g = G()
    g.n_pad = 128
    g.dst = jnp.asarray(np.arange(64) % 128, dtype=jnp.int32)
    g.src = jnp.asarray(np.arange(64) % 128, dtype=jnp.int32)
    g.edge_w = jnp.ones(64, dtype=jnp.int32)
    plans = lane_gather.maybe_edge_plans(g)
    # force-enable skips the size gate and the timing race, but the
    # platform/correctness gate still applies — on the CPU test backend
    # the Mosaic kernel is unavailable, so routing stays off (no crash)
    assert plans is None
    events = telemetry.events("lane-gather-probe")
    assert len(events) == 1 and events[0].attrs["verdict"] == "forced-on"
    assert events[0].attrs["supported"] is False
    assert "reason" in events[0].attrs
    status = lane_gather.probe_status()
    assert status["mode"] == "forced-on"
    assert status["env_override"] == "1"
    # the decision is cached: a second call emits no duplicate event
    assert lane_gather.maybe_edge_plans(g) is None
    assert len(telemetry.events("lane-gather-probe")) == 1


def test_lane_gather_opt_out_status(monkeypatch):
    from kaminpar_tpu.ops import lane_gather

    monkeypatch.setenv("KAMINPAR_TPU_LANE_GATHER", "0")
    monkeypatch.setattr(lane_gather, "_PROBE_STATUS", {"mode": "not-probed"})

    class G:
        pass

    g = G()
    assert lane_gather.maybe_edge_plans(g) is None
    assert lane_gather.probe_status()["mode"] == "opt-out"


def test_lane_gather_probe_event_records_verdict(monkeypatch):
    from kaminpar_tpu.ops import lane_gather

    telemetry.enable()
    monkeypatch.delenv("KAMINPAR_TPU_LANE_GATHER", raising=False)
    lane_gather.lane_gather_supported.cache_clear()
    try:
        supported = lane_gather.lane_gather_supported()
        # CPU test platform: the Mosaic kernel is unavailable
        assert supported is False
        events = telemetry.events("lane-gather-probe")
        assert len(events) == 1
        assert events[0].attrs["verdict"] == "disabled"
        assert "reason" in events[0].attrs
        assert lane_gather.probe_status()["mode"] == "probed"
    finally:
        lane_gather.lane_gather_supported.cache_clear()


def test_fm_refusal_sentinel_and_event():
    from kaminpar_tpu import native

    if not native.available():
        pytest.skip("native library unavailable (no compiler)")
    telemetry.enable()
    g = factories.make_path(8)
    k = 0x10000 + 1  # above the sparse engine's 16-bit tag limit
    part = np.arange(8, dtype=np.int32) % 4
    max_bw = np.full(k, 100, dtype=np.int64)
    fm_ctx = ktp.context_from_preset("default").refinement.fm
    ret = native.fm_refine(
        g, part, k, max_bw, fm_ctx, seed=0, force_sparse=True
    )
    assert ret == native.FM_REFUSED
    events = telemetry.events("fm-refused")
    assert len(events) == 1
    assert events[0].attrs["k"] == k


def test_fm_runs_normally_below_limit():
    from kaminpar_tpu import native

    if not native.available():
        pytest.skip("native library unavailable (no compiler)")
    telemetry.enable()
    g = factories.make_grid_graph(8, 8)
    rng = np.random.default_rng(0)
    part = rng.integers(0, 4, g.n).astype(np.int32)
    max_bw = np.full(4, g.n, dtype=np.int64)
    fm_ctx = ktp.context_from_preset("default").refinement.fm
    ret = native.fm_refine(g, part, 4, max_bw, fm_ctx, seed=0)
    assert ret is not None and ret >= 0
    assert telemetry.events("fm-refused") == []


# ---------------------------------------------------------------------------
# comm accounting: shape keying, retrace events, caveat
# ---------------------------------------------------------------------------


def test_comm_accounting_shape_keyed_with_caveat():
    from kaminpar_tpu.parallel import mesh

    telemetry.enable()
    mesh.reset_comm_log()
    try:
        with mesh.comm_phase("phase-a"):
            mesh.account_collective("psum(x)", 128, shape=(4, 8))
            mesh.account_collective("psum(x)", 128, shape=(4, 8))
            mesh.account_collective("psum(x)", 64, shape=(2, 8))  # retrace
        records = mesh.comm_records()
        assert len(records) == 2  # one row per traced shape
        by_shape = {tuple(r["shape"]): r for r in records}
        assert by_shape[(4, 8)]["traced_calls"] == 2
        assert by_shape[(4, 8)]["payload_bytes_per_device"] == 256
        assert by_shape[(2, 8)]["traced_calls"] == 1
        table = mesh.comm_table()
        assert "TRACE time" in table or "cache" in table  # the caveat
        traces = telemetry.events("jit-trace")
        assert len(traces) == 2
        assert [e.attrs["retrace"] for e in traces] == [False, True]
    finally:
        mesh.reset_comm_log()


def test_comm_table_marks_cache_hit_phases():
    """A phase opened with ZERO traced collectives is an executable-cache
    hit — comm_table must say so explicitly instead of leaving it
    indistinguishable from a silent phase (ADVICE round 5 low #4)."""
    from kaminpar_tpu.parallel import mesh

    mesh.reset_comm_log()
    try:
        with mesh.comm_phase("warm"):
            mesh.account_collective("psum(x)", 128, shape=(4, 8))
        # second opening: program cached, nothing traces
        with mesh.comm_phase("warm"):
            pass
        with mesh.comm_phase("cold-cache-hit"):
            pass  # opened, traced nothing at all
        assert mesh.phase_opens() == {"warm": 2, "cold-cache-hit": 1}
        assert mesh.cache_hit_phases() == ["cold-cache-hit"]
        table = mesh.comm_table()
        assert "cold-cache-hit" in table and "cache-hit" in table
        # the traced row notes its extra (cached) openings
        assert "opened 2x" in table
        from kaminpar_tpu.telemetry.report import build_run_report

        report = build_run_report()
        assert report["comm"]["phase_opens"]["warm"] == 2
    finally:
        mesh.reset_comm_log()


def test_dist_run_populates_comm_records():
    from kaminpar_tpu.parallel import dKaMinPar, make_mesh, mesh

    from kaminpar_tpu.parallel.dist_context import (
        create_dist_context_by_preset_name,
    )

    telemetry.enable()
    mesh.reset_comm_log()
    try:
        g = factories.make_grid_graph(32, 32)
        ctx = create_dist_context_by_preset_name("default")
        # force a distributed coarsening level so collectives trace
        ctx.shm.coarsening.contraction_limit = 50
        ctx.replication_min_nodes_per_device = 0
        solver = dKaMinPar(ctx, mesh=make_mesh(2))
        try:
            part = solver.set_graph(g).compute_partition(k=2, seed=1)
        except TypeError as e:
            # older jax: shard_map lacks check_vma — the whole dist layer
            # is unavailable in this environment, not a telemetry defect
            pytest.skip(f"dist layer unavailable on this jax: {e}")
        assert len(part) == g.n
        from kaminpar_tpu.telemetry.report import build_run_report

        report = build_run_report()
        assert report["run"].get("devices") == 2
        assert report["result"]["cut"] >= 0
        # at least one collective was traced and attributed to a phase
        assert report["comm"]["records"], report["comm"]
    finally:
        mesh.reset_comm_log()
