"""Memory-pressure governor tests (resilience/memory.py).

The acceptance contract of ISSUE 8: a run either fits its declared
memory budget or degrades through a deterministic ladder — it never
dies with RESOURCE_EXHAUSTED.  Covered here:

  * estimator units + the estimator-vs-watermark accuracy bound
    (estimate within [1x, 2x] of the measured peak on bench shapes,
    never under);
  * pad-policy modes (the rung-1 lever) and BoundedCache.evict_to with
    the eviction-cause split (the caching satellite);
  * the ladder-equivalence suite: a forced rung (KAMINPAR_TPU_MEM_RUNG)
    must complete gate-valid at EVERY rung, and spill/reload
    uncoarsening must be cut-identical to the unspilled run;
  * budget-driven engagement: a budget at ~25% of the measured peak
    completes with rung >= 1 and no surfaced RESOURCE_EXHAUSTED;
  * injected `device-oom` faults: single shot recovers at the next
    rung, `always` walks the ladder down to host-only, and a failing
    host-only rung surfaces DeviceOOM with rungs_exhausted=True;
  * the dormancy pin: with no budget the governor changes neither
    jaxprs nor cuts.
"""

import numpy as np
import pytest

from kaminpar_tpu import caching, resilience, telemetry
from kaminpar_tpu.graphs.factories import make_rgg2d
from kaminpar_tpu.graphs.host import host_partition_metrics
from kaminpar_tpu.kaminpar import KaMinPar
from kaminpar_tpu.presets import create_context_by_preset_name
from kaminpar_tpu.resilience import memory as mem


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for var in (mem.ENV_BUDGET, mem.ENV_FORCE_RUNG, mem.ENV_GOVERNOR,
                resilience.FAULTS_ENV_VAR):
        monkeypatch.delenv(var, raising=False)
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    yield
    resilience.reset()
    telemetry.disable()
    telemetry.reset()


def _partition(g, k=8, seed=1, contraction_limit=500):
    ctx = create_context_by_preset_name("default")
    ctx.coarsening.contraction_limit = contraction_limit
    solver = KaMinPar(ctx)
    solver.set_graph(g)
    solver.set_output_level(0)
    part = solver.compute_partition(k=k, epsilon=0.03, seed=seed)
    return part, host_partition_metrics(g, part, k)["cut"]


def _gate():
    gates = [e.attrs for e in telemetry.events("output-gate")]
    return gates[-1] if gates else None


# ---------------------------------------------------------------------------
# estimator units
# ---------------------------------------------------------------------------


def test_estimator_monotone_and_rung_ordered():
    base = mem.estimate_run_bytes(10_000, 80_000, 8)
    assert base > 0
    assert mem.estimate_run_bytes(40_000, 320_000, 8) > base
    assert mem.estimate_run_bytes(10_000, 80_000, 512) >= base
    # rung ordering: each rung prices no more than its predecessor
    rungs = [
        mem.estimate_rung_bytes(r, 100_000, 800_000, 16)
        for r in range(5)
    ]
    assert rungs[1] <= rungs[0]  # tight pads never cost more
    assert rungs[2] < rungs[1]  # spilled hierarchy is leaner
    # rung 3 prices the graph ACTUALLY uploaded (spilled mode) — for a
    # given (n, m) that is the rung-2 figure; whether a fine graph can
    # fit at all is rung_fits' question (the floor bucket always can)
    assert rungs[3] == rungs[2]
    assert rungs[4] == 0  # host-only: no device bytes
    assert mem.min_serveable_bytes(100_000, 800_000, 16) == rungs[2]
    budget = rungs[2] - 1  # too small for a device-resident run
    assert not mem.rung_fits(2, 100_000, 800_000, 16, budget)
    assert mem.rung_fits(3, 100_000, 800_000, 16, budget)
    assert mem.rung_fits(4, 100_000, 800_000, 16, 0)


def test_padded_bucket_modes():
    nb, mb, kb = mem.padded_bucket(5000, 40_000, 5, "bucketed")
    nt, mt, kt = mem.padded_bucket(5000, 40_000, 5, "tight")
    assert nt <= nb and mt <= mb and kt == kb
    assert nt >= 5001 and mt >= 40_000


def test_budget_sources(monkeypatch):
    assert mem.budget_bytes() is None
    monkeypatch.setenv(mem.ENV_BUDGET, "123456")
    assert mem.budget_bytes() == 123456
    ctx = create_context_by_preset_name("default")
    ctx.resilience.memory_budget = 999.0
    assert mem.budget_bytes(ctx) == 999  # declared ctx budget wins
    monkeypatch.setenv(mem.ENV_GOVERNOR, "0")
    assert not mem.governor_enabled()


# ---------------------------------------------------------------------------
# pad-policy modes (the rung-1 lever) + evict_to (caching satellite)
# ---------------------------------------------------------------------------


def test_pad_policy_scope_modes():
    assert caching.pad_policy() == "bucketed"
    assert caching.pad_size(5000, 256) == 8192
    with caching.pad_policy_scope("tight"):
        assert caching.pad_policy() == "tight"
        assert caching.pad_size(5000, 256) == 5120  # granularity only
        assert caching.pad_size(100, 256) == 256  # floor unchanged
    assert caching.pad_policy() == "bucketed"
    with pytest.raises(ValueError):
        with caching.pad_policy_scope("nonsense"):
            pass


def test_pad_policy_is_thread_local():
    import threading

    seen = {}

    def probe():
        seen["other"] = caching.pad_policy()

    with caching.pad_policy_scope("tight"):
        t = threading.Thread(target=probe)
        t.start()
        t.join()
    assert seen["other"] == "bucketed"


def test_evict_to_sheds_lru_and_counts_pressure():
    c = caching.BoundedCache(max_entries=16, max_bytes=1 << 20)
    for i in range(4):
        c.put(i, f"v{i}", nbytes=100)
    c.get(0)  # 0 becomes most-recently-used
    freed = c.evict_to(150, cause="pressure")
    assert freed == 300  # 1, 2, 3 dropped (LRU order), 0 kept
    assert c.get(0) == "v0"
    st = c.stats()
    assert st["evictions_pressure"] == 3
    assert st["evictions_capacity"] == 0
    assert st["window"]["evictions_pressure"] == 3
    # capacity evictions stay separately attributed
    for i in range(10, 40):
        c.put(i, "x", nbytes=0)
    st = c.stats()
    assert st["evictions_capacity"] > 0
    assert st["evictions_pressure"] == 3
    # the window split resets with begin_window, lifetime is kept
    c.begin_window()
    assert c.stats()["window"]["evictions_pressure"] == 0
    assert c.stats()["evictions_pressure"] == 3
    # evict_to(0) sheds every byte-carrying entry
    c.put("big", "v", nbytes=100)
    assert c.evict_to(0) >= 100
    assert c.nbytes == 0


def test_shed_caches_hits_registered_targets():
    c = caching.BoundedCache(max_entries=4, max_bytes=1 << 20)
    c.put("a", "v", nbytes=512)
    mem.register_shed_target(c)
    freed = mem.shed_caches(0)
    assert freed >= 512
    assert len(c) == 0


# ---------------------------------------------------------------------------
# estimator-vs-watermark accuracy (the calibration contract)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n,k", [(4096, 4), (8192, 8)])
def test_estimator_vs_watermark(monkeypatch, n, k):
    """On the bench shapes the estimate must bound the measured
    live-bytes watermark from above (never under) while staying within
    2x of it — an under-estimate admits a run the budget cannot hold, a
    wild over-estimate rejects servable requests."""
    monkeypatch.setenv(mem.ENV_BUDGET, str(10**12))  # track, never bind
    g = make_rgg2d(n, avg_degree=8, seed=1)
    _partition(g, k=k)
    st = mem.state()
    assert st is not None and st.watermark > 0
    est = mem.estimate_run_bytes(g.n, g.m, k)
    assert est >= st.watermark, "estimator under-prices the peak"
    assert est <= 2 * st.watermark, "estimator over-prices 2x+"


# ---------------------------------------------------------------------------
# ladder equivalence (KAMINPAR_TPU_MEM_RUNG test hook)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("rung", [1, 2, 3, 4])
def test_forced_rung_completes_gate_valid(monkeypatch, rung):
    monkeypatch.setenv(mem.ENV_FORCE_RUNG, str(rung))
    monkeypatch.setenv(mem.ENV_BUDGET, str(10**12))
    g = make_rgg2d(3000, avg_degree=8, seed=3)
    part, cut = _partition(g, k=8, contraction_limit=500)
    assert part.shape == (g.n,)
    gate = _gate()
    assert gate and gate["valid"], gate
    info = telemetry.run_info()["memory_budget"]
    assert info["rung"] == rung
    assert info["initial_rung"] == rung
    assert not info["exhausted"]


def test_spill_reload_uncoarsening_is_cut_identical(monkeypatch):
    """Rung 2 drops coarse levels to host CSR at the barriers and
    re-uploads them during uncoarsening; deterministic pad buckets make
    the restored arrays bitwise-identical, so the cut must match the
    unspilled run exactly."""
    g = make_rgg2d(8192, avg_degree=8, seed=3)
    _, base_cut = _partition(g, k=8, contraction_limit=500)
    monkeypatch.setenv(mem.ENV_FORCE_RUNG, "2")
    monkeypatch.setenv(mem.ENV_BUDGET, str(10**12))
    _, spill_cut = _partition(g, k=8, contraction_limit=500)
    assert spill_cut == base_cut
    info = telemetry.run_info()["memory_budget"]
    assert info["spills"]["count"] >= 1, info
    assert info["spills"]["reloads"] >= 1, info
    assert info["spills"]["bytes"] > 0
    assert telemetry.events("memory-spill")
    assert telemetry.events("memory-reload")


def test_tiny_budget_engages_ladder_and_completes(monkeypatch):
    """The headline acceptance criterion: a budget at ~25% of the
    unconstrained run's measured peak must complete with exit-0
    semantics, a gate-valid partition, memory_budget.rung >= 1, and no
    surfaced RESOURCE_EXHAUSTED."""
    g = make_rgg2d(8192, avg_degree=8, seed=1)
    monkeypatch.setenv(mem.ENV_BUDGET, str(10**12))
    _partition(g, k=8)
    peak = mem.state().watermark
    assert peak > 0
    monkeypatch.setenv(mem.ENV_BUDGET, str(max(int(peak * 0.25), 1)))
    part, cut = _partition(g, k=8)  # must not raise
    assert part.shape == (g.n,)
    gate = _gate()
    assert gate and gate["valid"], gate
    info = telemetry.run_info()["memory_budget"]
    assert info["rung"] >= 1, info
    assert info["budget_bytes"] == max(int(peak * 0.25), 1)
    assert not info["exhausted"]


# ---------------------------------------------------------------------------
# injected OOMs: recovery, full walk, exhaustion
# ---------------------------------------------------------------------------


def test_injected_oom_recovers_at_next_rung(monkeypatch):
    monkeypatch.setenv(resilience.FAULTS_ENV_VAR, "device-oom:nth=1")
    g = make_rgg2d(2000, avg_degree=8, seed=3)
    part, cut = _partition(g, k=4, contraction_limit=2000)
    assert part.shape == (g.n,)
    gate = _gate()
    assert gate and gate["valid"]
    events = [e.attrs for e in telemetry.events("degraded")
              if e.attrs["site"] == "device-oom"]
    assert events and events[-1]["rung"] == 1
    assert events[-1]["injected"] is True
    info = telemetry.run_info()["memory_budget"]
    assert info["enabled"] and info["rung"] == 1


def test_always_oom_walks_ladder_to_host_only(monkeypatch):
    """`device-oom` at EVERY device entry (upload/contraction/refine)
    fails rungs 0-3; the host-only rung has no device entry points, so
    the run completes there — the never-RESOURCE_EXHAUSTED contract in
    its most hostile configuration."""
    monkeypatch.setenv(resilience.FAULTS_ENV_VAR, "device-oom")
    g = make_rgg2d(1500, avg_degree=8, seed=3)
    part, cut = _partition(g, k=4, contraction_limit=2000)
    assert part.shape == (g.n,)
    gate = _gate()
    assert gate and gate["valid"]
    info = telemetry.run_info()["memory_budget"]
    assert info["rung"] == mem.RUNG_HOST_ONLY
    assert not info["exhausted"]


def test_rung_exhaustion_is_crash_shaped(monkeypatch):
    """When even the host-only rung fails, the DeviceOOM surfaces with
    rungs_exhausted=True — the single crash-shaped OOM verdict (the one
    the serving per-class breaker may latch)."""
    def boom(graph, ctx):
        raise MemoryError("host allocator refused too")

    monkeypatch.setattr(mem, "host_only_partition", boom)
    monkeypatch.setenv(resilience.FAULTS_ENV_VAR, "device-oom")
    g = make_rgg2d(1000, avg_degree=8, seed=3)
    ctx = create_context_by_preset_name("default")
    solver = KaMinPar(ctx)
    solver.set_graph(g)
    solver.set_output_level(0)
    with pytest.raises(resilience.DeviceOOM) as exc_info:
        solver.compute_partition(k=4, epsilon=0.03, seed=1)
    assert exc_info.value.rungs_exhausted is True


def test_kill_switch_disables_the_ladder(monkeypatch):
    monkeypatch.setenv(mem.ENV_GOVERNOR, "0")
    monkeypatch.setenv(resilience.FAULTS_ENV_VAR, "device-oom:nth=1")
    g = make_rgg2d(1000, avg_degree=8, seed=3)
    ctx = create_context_by_preset_name("default")
    solver = KaMinPar(ctx)
    solver.set_graph(g)
    solver.set_output_level(0)
    with pytest.raises(resilience.DeviceOOM) as exc_info:
        solver.compute_partition(k=4, epsilon=0.03, seed=1)
    assert exc_info.value.rungs_exhausted is False  # retryable, unladdered


# ---------------------------------------------------------------------------
# semi-external building blocks
# ---------------------------------------------------------------------------


def test_host_lp_cluster_shrinks_and_respects_compaction():
    g = make_rgg2d(3000, avg_degree=8, seed=5)
    labels = mem._host_lp_cluster(g, max_cluster_weight=50)
    assert labels.shape == (g.n,)
    c_n = int(labels.max()) + 1
    assert 0 < c_n < g.n  # genuinely coarsened
    assert set(np.unique(labels)) == set(range(c_n))  # compact ids


def test_host_contract_preserves_weight_and_symmetry():
    from kaminpar_tpu.graphs.csr import validate

    g = make_rgg2d(2000, avg_degree=8, seed=5)
    labels = mem._host_lp_cluster(g, max_cluster_weight=40)
    coarse, cmap = mem._host_contract(g, labels)
    assert int(coarse.total_node_weight) == int(g.total_node_weight)
    # inter-cluster edge weight is conserved (self-loops dropped)
    fine_w = np.ones(g.m, dtype=np.int64)
    src = np.repeat(np.arange(g.n), np.diff(np.asarray(g.xadj)))
    inter = labels[src] != labels[np.asarray(g.adjncy)]
    assert int(coarse.edge_weight_array().sum()) == int(
        fine_w[inter].sum()
    )
    validate(coarse)  # CSR invariants incl. symmetric twins


def test_forced_semi_external_streams_by_default(monkeypatch):
    """Rung 3's primary is the device-streamed external subsystem
    (ISSUE 13): a forced rung 3 with a budget the stream fits emits
    `stream` events; the legacy host-chunked numpy LP is its FALLBACK
    (tests/test_external.py pins the demotion path and `semi-external`
    event there)."""
    monkeypatch.setenv(mem.ENV_FORCE_RUNG, "3")
    monkeypatch.setenv(mem.ENV_BUDGET, "6000000")
    g = make_rgg2d(8000, avg_degree=8, seed=3)
    part, cut = _partition(g, k=8)
    assert part.shape == (g.n,)
    gate = _gate()
    assert gate and gate["valid"]
    ev = telemetry.events("stream")
    assert ev and ev[-1].attrs["coarse_n"] < g.n


def test_host_lp_cluster_cap_exact_on_weighted_graph():
    """The rung-3 host LP's cluster-weight cap is EXACT: the per-chunk
    prefix pass accepts only joins that keep every target at or under
    the cap (the vectorized apply used to overshoot by up to a chunk's
    worth of concurrent joins on weighted graphs)."""
    g = make_rgg2d(3000, avg_degree=8, seed=11)
    rng = np.random.default_rng(13)
    g.node_weights = rng.integers(1, 9, g.n).astype(np.int64)
    cap = 25
    # small chunks force cross-chunk and within-chunk concurrent joins
    labels = mem._host_lp_cluster(g, max_cluster_weight=cap,
                                  chunk_nodes=256)
    cw = np.zeros(int(labels.max()) + 1, dtype=np.int64)
    np.add.at(cw, labels, g.node_weights)
    members = np.bincount(labels)
    over = np.flatnonzero(cw > cap)
    # a singleton heavier than the cap never joined anything and is
    # legitimately over; every JOINED cluster respects the cap exactly
    assert all(members[c] == 1 for c in over), (
        [(int(c), int(cw[c]), int(members[c])) for c in over[:5]]
    )
    assert len(np.unique(labels)) < g.n  # still genuinely coarsens


# ---------------------------------------------------------------------------
# dormancy: zero impact without a budget
# ---------------------------------------------------------------------------


def test_governor_dormant_without_budget():
    g = make_rgg2d(2000, avg_degree=8, seed=3)
    _partition(g, k=4, contraction_limit=2000)
    # no memory_budget annotation, no governor events
    assert "memory_budget" not in telemetry.run_info()
    assert not telemetry.events("memory-budget")
    assert not telemetry.events("memory-spill")
    assert not telemetry.events("memory-pressure")


def test_jaxpr_identical_with_and_without_governor(monkeypatch):
    """The dormancy pin: arming the governor (big budget, rung 0) must
    not change a single traced jaxpr — every hook is host-side."""
    import jax
    import jax.numpy as jnp

    from kaminpar_tpu.graphs import factories
    from kaminpar_tpu.graphs.csr import device_graph_from_host
    from kaminpar_tpu.ops.lp import lp_cluster

    monkeypatch.setenv("KAMINPAR_TPU_PROGRESS", "0")
    dg = device_graph_from_host(factories.make_grid_graph(8, 8))

    def trace():
        return str(
            jax.make_jaxpr(
                lambda seed: lp_cluster(dg, jnp.int32(100), seed)
            )(jnp.int32(7))
        )

    base = trace()
    monkeypatch.setenv(mem.ENV_BUDGET, str(10**12))
    assert trace() == base