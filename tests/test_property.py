"""Property tests: random graphs x presets -> valid, balanced, deterministic.

The reference's end-to-end suite asserts cut/feasibility/determinism on a
handful of fixed graphs (tests/endtoend/shm_endtoend_test.cc:28-80); this
sweeps randomized structures (sparse, dense, star-heavy, disconnected,
weighted) through the main presets.
"""

import numpy as np
import pytest

from kaminpar_tpu.graphs.host import HostGraph, from_edge_list, host_partition_metrics
from kaminpar_tpu.kaminpar import KaMinPar
from kaminpar_tpu.utils.logger import OutputLevel


def _random_graph(rng, kind: str) -> HostGraph:
    n = int(rng.integers(40, 400))
    if kind == "sparse":
        e = rng.integers(0, n, size=(2 * n, 2))
    elif kind == "dense":
        e = rng.integers(0, n, size=(12 * n, 2))
    elif kind == "star-heavy":
        hub = rng.integers(0, max(n // 10, 1), size=6 * n)
        leaf = rng.integers(0, n, size=6 * n)
        e = np.stack([hub, leaf], axis=1)
    else:  # disconnected: two halves, no cross edges
        half = n // 2
        e1 = rng.integers(0, half, size=(2 * half, 2))
        e2 = rng.integers(half, n, size=(2 * half, 2))
        e = np.concatenate([e1, e2])
    e = e[e[:, 0] != e[:, 1]]
    node_w = (
        rng.integers(1, 6, size=n) if kind == "dense" else None
    )
    edge_w = rng.integers(1, 9, size=len(e)) if kind == "sparse" else None
    return from_edge_list(n, e, node_weights=node_w, edge_weights=edge_w)


@pytest.mark.parametrize("kind", ["sparse", "dense", "star-heavy", "disconnected"])
@pytest.mark.parametrize("preset", ["default", "fast"])
def test_random_graphs_partition_validly(kind, preset):
    import zlib

    # reproducible across processes (hash() is PYTHONHASHSEED-randomized)
    rng = np.random.default_rng(zlib.crc32(f"{kind}-{preset}".encode()))
    for trial in range(3):
        g = _random_graph(rng, kind)
        k = int(rng.choice([2, 3, 5, 8]))
        eps = 0.10
        p = KaMinPar(preset)
        p.set_output_level(OutputLevel.QUIET)
        part = p.set_graph(g).compute_partition(k=k, epsilon=eps, seed=trial)

        assert part.shape == (g.n,)
        assert part.min() >= 0 and part.max() < k
        res = host_partition_metrics(g, part, k)
        # the guarantee is the context's (relaxed) per-block caps
        # (PartitionContext.setup small-block relaxation), not the raw
        # (1+eps)*perfect bound
        caps = np.asarray(p.ctx.partition.max_block_weights)
        assert (res["block_weights"] <= caps).all(), (kind, preset, k, trial)

        # determinism: same seed, same result
        p2 = KaMinPar(preset)
        p2.set_output_level(OutputLevel.QUIET)
        part2 = p2.set_graph(g).compute_partition(k=k, epsilon=eps, seed=trial)
        assert (part == part2).all(), (kind, preset, k, trial)
