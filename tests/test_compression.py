"""Compressed-graph subsystem tests (the reference's
tests/shm/datastructures/compressed_graph_test.cc checks compressed vs CSR
equivalence; tests/common/ covers the varint codecs)."""

import numpy as np
import pytest

from kaminpar_tpu import native
from kaminpar_tpu.graphs.compressed import compress_host_graph
from kaminpar_tpu.graphs.factories import (
    make_grid_graph,
    make_isolated_graph,
    make_rmat,
    make_star,
)


@pytest.mark.parametrize(
    "graph",
    [
        make_grid_graph(8, 8),
        make_star(31),
        make_rmat(256, 1024, seed=5),
        make_isolated_graph(10),
    ],
    ids=["grid", "star", "rmat", "isolated"],
)
def test_compressed_equals_csr(graph):
    # the "gap" codec round-trips the CSR EXACTLY; the default ("auto",
    # v2 when native) may reorder within rows (interval members first,
    # like the reference's interval decode) — covered by the v2 tests
    cg = compress_host_graph(graph, codec="gap")
    assert cg.n == graph.n and cg.m == graph.m
    back = cg.decode()
    assert (back.xadj == graph.xadj).all()
    assert (back.adjncy == graph.adjncy).all()
    for u in [0, graph.n // 2, graph.n - 1] if graph.n else []:
        assert (cg.neighbors(u) == graph.neighbors(u)).all()


def test_varint_codec_roundtrip_fuzz():
    rng = np.random.default_rng(0)
    for _ in range(5):
        n = int(rng.integers(1, 50))
        deg = rng.integers(0, 20, size=n)
        xadj = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(deg, out=xadj[1:])
        adjncy = np.sort(
            rng.integers(0, max(1, 10 * n), size=int(xadj[-1])).astype(np.int32)
        )
        # per-node sorted neighborhoods
        for u in range(n):
            adjncy[xadj[u] : xadj[u + 1]] = np.sort(adjncy[xadj[u] : xadj[u + 1]])
        data, off = native.encode_gaps(xadj, adjncy)
        assert (native.decode_gaps(xadj, off, data) == adjncy).all()
        # numpy fallback produces the identical stream
        d2, o2 = native._encode_gaps_np(n, xadj, adjncy.astype(np.int32))
        assert (d2 == data).all() and (o2 == off).all()


def test_compression_saves_memory():
    g = make_rmat(1 << 12, 1 << 15, seed=2)
    cg = compress_host_graph(g)
    assert cg.compression_ratio() > 1.5


def test_compressed_binary_roundtrip(tmp_path):
    from kaminpar_tpu.io import load_graph, write_compressed

    g = make_rmat(512, 2048, seed=9)
    cg = compress_host_graph(g)
    path = str(tmp_path / "g.npz")
    write_compressed(path, cg)
    back = load_graph(path)  # auto-detects the compressed container
    assert back.n == g.n and back.m == g.m
    dec = back.decode()
    # default codec (v2) may reorder within rows; compare row sets
    assert (dec.xadj == g.xadj).all()
    assert _row_sets(dec) == _row_sets(g)


def test_terapart_preset_partitions_compressed(rgg2d):
    from kaminpar_tpu import KaMinPar
    from kaminpar_tpu.utils.logger import OutputLevel

    part = (
        KaMinPar("terapart")
        .set_output_level(OutputLevel.QUIET)
        .set_graph(rgg2d)
        .compute_partition(k=8, epsilon=0.03, seed=0)
    )
    assert part.shape == (rgg2d.n,)
    nw = rgg2d.node_weight_array()
    bw = np.zeros(8, dtype=np.int64)
    np.add.at(bw, part, nw)
    cap = int(1.03 * np.ceil(nw.sum() / 8)) + int(nw.max())
    assert (bw <= cap).all()


def test_linear_time_kway_preset(rgg2d):
    from kaminpar_tpu import KaMinPar
    from kaminpar_tpu.utils.logger import OutputLevel

    part = (
        KaMinPar("linear-time-kway")
        .set_output_level(OutputLevel.QUIET)
        .set_graph(rgg2d)
        .compute_partition(k=4, epsilon=0.03, seed=0)
    )
    assert part.shape == (rgg2d.n,)
    assert part.min() >= 0 and part.max() < 4


# ---------------------------------------------------------------------------
# v2 codec: interval + streamvbyte-class residuals + varint weights
# (native/codec2.cpp — TeraPart compressed_neighborhoods parity)
# ---------------------------------------------------------------------------


def _row_sets(g):
    return [
        sorted(g.adjncy[g.xadj[u]:g.xadj[u + 1]].tolist())
        for u in range(g.n)
    ]


def test_v2_codec_roundtrip_unweighted():
    from kaminpar_tpu import native
    from kaminpar_tpu.graphs.compressed import compress_host_graph

    if not native.available():
        import pytest

        pytest.skip("native toolchain unavailable")
    for gmaker in (
        lambda: make_grid_graph(20, 20),  # interval-rich
        lambda: make_rmat(1 << 10, 8_000, seed=5),
    ):
        g = gmaker()
        cg = compress_host_graph(g, codec="v2")
        assert cg.codec == "v2"
        back = cg.decode()
        assert back.n == g.n and back.m == g.m
        np.testing.assert_array_equal(back.xadj, g.xadj)
        assert _row_sets(back) == _row_sets(g)
        # per-node decode agrees with bulk decode
        for u in (0, 1, g.n // 2, g.n - 1):
            np.testing.assert_array_equal(
                cg.neighbors(u), back.adjncy[back.xadj[u]:back.xadj[u + 1]]
            )


def test_v2_codec_roundtrip_weighted_pairs():
    from kaminpar_tpu import native
    from kaminpar_tpu.graphs.compressed import compress_host_graph

    if not native.available():
        import pytest

        pytest.skip("native toolchain unavailable")
    g = make_grid_graph(16, 16)
    rng = np.random.default_rng(3)
    g.edge_weights = rng.integers(1, 1000, g.m).astype(np.int64)
    cg = compress_host_graph(g, codec="v2")
    assert cg.wdata is not None
    back = cg.decode()
    # (neighbor, weight) multisets per row survive the emit reordering
    for u in range(g.n):
        orig = sorted(zip(
            g.adjncy[g.xadj[u]:g.xadj[u + 1]].tolist(),
            np.asarray(g.edge_weights)[g.xadj[u]:g.xadj[u + 1]].tolist(),
        ))
        got = sorted(zip(
            back.adjncy[back.xadj[u]:back.xadj[u + 1]].tolist(),
            np.asarray(back.edge_weights)[back.xadj[u]:back.xadj[u + 1]].tolist(),
        ))
        assert orig == got, f"row {u}"


def test_v2_codec_beats_gap_codec_on_interval_graphs():
    """Interval encoding must pay off where the reference's does: on
    neighborhoods with consecutive runs (grids after degree-bucket
    ordering, cliques)."""
    from kaminpar_tpu import native
    from kaminpar_tpu.graphs.compressed import compress_host_graph
    from kaminpar_tpu.graphs.host import from_edge_list

    if not native.available():
        import pytest

        pytest.skip("native toolchain unavailable")
    # a union of cliques: every neighborhood is one long run
    blocks, size = 16, 24
    edges = []
    for b in range(blocks):
        base = b * size
        for i in range(size):
            for j in range(i + 1, size):
                edges.append((base + i, base + j))
    g = from_edge_list(blocks * size, np.array(edges))
    v1 = compress_host_graph(g, codec="gap")
    v2 = compress_host_graph(g, codec="v2")
    assert v2.data.nbytes < 0.35 * v1.data.nbytes
    assert v2.decode().m == g.m
    assert v2.compression_ratio() > 8


def test_compressed_binary_roundtrips_v2(tmp_path):
    from kaminpar_tpu import native
    from kaminpar_tpu.graphs.compressed import compress_host_graph
    from kaminpar_tpu.io.compressed_binary import (
        load_compressed,
        write_compressed,
    )

    if not native.available():
        import pytest

        pytest.skip("native toolchain unavailable")
    g = make_rmat(1 << 9, 4_000, seed=2)
    rng = np.random.default_rng(0)
    g.edge_weights = rng.integers(1, 50, g.m).astype(np.int64)
    cg = compress_host_graph(g, codec="v2")
    path = str(tmp_path / "g.npz")
    write_compressed(path, cg)
    lg = load_compressed(path)
    assert lg.codec == "v2"
    assert _row_sets(lg.decode()) == _row_sets(g)


def test_decode_range_matches_full_decode():
    """decode_range must agree with full decode on every codec and
    weight configuration (the shard-streaming ingestion contract)."""
    from kaminpar_tpu.graphs.compressed import compress_host_graph
    from kaminpar_tpu.graphs.factories import make_rmat
    from kaminpar_tpu.graphs.host import HostGraph
    from kaminpar_tpu import native

    base = make_rmat(1 << 9, 4000, seed=2)
    src = base.edge_sources()
    lo = np.minimum(src, base.adjncy)
    hi = np.maximum(src, base.adjncy)
    ew = ((lo * 13 + hi * 5) % 7 + 1).astype(np.int64)
    weighted = HostGraph(base.xadj, base.adjncy, edge_weights=ew)
    codecs = ["gap"] + (["v2"] if native.available() else [])
    for codec in codecs:
        for g in (base, weighted):
            cg = compress_host_graph(g, codec=codec)
            full = cg.decode()
            for v0, v1 in [(0, g.n), (0, 0), (g.n, g.n), (17, 173),
                           (g.n // 2, g.n)]:
                xr, adjn, w = cg.decode_range(v0, v1)
                np.testing.assert_array_equal(
                    xr, cg.xadj[v0:v1 + 1] - cg.xadj[v0]
                )
                s, e = int(cg.xadj[v0]), int(cg.xadj[v1])
                np.testing.assert_array_equal(adjn, full.adjncy[s:e])
                if g.edge_weights is not None:
                    np.testing.assert_array_equal(
                        w, full.edge_weight_array()[s:e]
                    )


def test_compress_from_stream_matches_bulk():
    """Chunked stream compression must encode exactly the graph the
    assembled-CSR path encodes (decode round-trip equality)."""
    from kaminpar_tpu.graphs.compressed import (
        compress_from_stream,
        compress_host_graph,
    )
    from kaminpar_tpu.io.skagen import hostgraph_from_stream, streamed

    sg = streamed("rmat;n=2048;m=20000;seed=5", num_chunks=7)
    host = hostgraph_from_stream(sg)
    cg = compress_from_stream(sg)
    bulk = compress_host_graph(host)
    assert cg.codec == bulk.codec
    dec = cg.decode()
    np.testing.assert_array_equal(dec.xadj, host.xadj)
    ref = bulk.decode()
    np.testing.assert_array_equal(dec.adjncy, ref.adjncy)
    np.testing.assert_array_equal(
        dec.edge_weight_array(), ref.edge_weight_array()
    )


def test_device_graph_from_compressed_bitwise():
    """The chunked device upload must produce a DeviceGraph bitwise equal
    to uploading the decoded CSR (downstream kernels and compile caches
    see identical arrays)."""
    from kaminpar_tpu.graphs.compressed import compress_host_graph
    from kaminpar_tpu.graphs.csr import (
        device_graph_from_compressed,
        device_graph_from_host,
    )
    from kaminpar_tpu.graphs.factories import make_rmat

    host = make_rmat(1 << 11, 30_000, seed=9)
    cg = compress_host_graph(host)
    a = device_graph_from_compressed(cg, chunk_nodes=300)
    b = device_graph_from_host(cg.decode())
    for field in ("row_ptr", "src", "dst", "edge_w", "node_w"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, field)), np.asarray(getattr(b, field)),
            err_msg=field,
        )
    assert int(a.n) == int(b.n) and int(a.m) == int(b.m)


def test_compressed_partition_metrics_matches_host():
    from kaminpar_tpu.graphs.compressed import (
        compress_host_graph,
        compressed_partition_metrics,
    )
    from kaminpar_tpu.graphs.factories import make_rmat
    from kaminpar_tpu.graphs.host import host_partition_metrics

    host = make_rmat(1 << 10, 12_000, seed=3)
    cg = compress_host_graph(host)
    rng = np.random.default_rng(0)
    part = rng.integers(0, 8, host.n)
    a = compressed_partition_metrics(cg, part, 8, chunk_nodes=100)
    b = host_partition_metrics(host, part, 8)
    assert a["cut"] == b["cut"]
    np.testing.assert_array_equal(a["block_weights"], b["block_weights"])
    assert a["imbalance"] == b["imbalance"]


def test_compressed_compute_partition_no_decode():
    """End-to-end deep partition from a still-compressed graph: the
    facade must not decode (TeraPart compute parity), and the partition
    must equal the decoded-input run exactly (the chunked upload is
    bitwise-identical)."""
    import kaminpar_tpu as ktp
    from kaminpar_tpu.graphs.compressed import compress_host_graph
    from kaminpar_tpu.graphs.factories import make_grid_graph

    # a graph with NO isolated nodes: isolated-node preprocessing is a
    # host-CSR consumer and would legitimately force the decode fallback
    host = make_grid_graph(64, 64)
    cg = compress_host_graph(host)

    p1 = ktp.KaMinPar("default")
    p1.set_graph(cg)
    part_c = p1.compute_partition(k=8, epsilon=0.03, seed=1)
    assert getattr(p1, "_decoded", None) is None  # stayed compressed

    p2 = ktp.KaMinPar("default")
    p2.set_graph(host)
    part_h = p2.compute_partition(k=8, epsilon=0.03, seed=1)
    np.testing.assert_array_equal(part_c, part_h)


def test_compressed_compute_with_isolated_nodes_no_decode():
    """Isolated nodes must NOT force a decode: the core graph is
    extracted compressed-to-compressed (chunk-streamed re-encode) and
    isolated nodes refill blocks by headroom — same semantics as the
    decoded path, so the cut must MATCH the decoded-input run."""
    import kaminpar_tpu as ktp
    from kaminpar_tpu.graphs.compressed import (
        compress_host_graph,
        compressed_partition_metrics,
    )
    from kaminpar_tpu.graphs.factories import make_rmat
    from kaminpar_tpu.graphs.host import host_partition_metrics

    host = make_rmat(1 << 12, 60_000, seed=4)  # has isolated nodes
    assert int((host.degrees() == 0).sum()) > 0
    cg = compress_host_graph(host)
    p = ktp.KaMinPar("default")
    p.set_graph(cg)
    k, eps = 8, 0.03
    part = p.compute_partition(k=k, epsilon=eps, seed=1)
    assert getattr(p, "_decoded", None) is None
    m = compressed_partition_metrics(cg, part, k)
    nw = host.node_weight_array()
    cap = (1 + eps) * np.ceil(nw.sum() / k)
    assert m["block_weights"].max() <= cap

    ph = ktp.KaMinPar("default").set_graph(host).compute_partition(
        k=k, epsilon=eps, seed=1
    )
    mh = host_partition_metrics(host, ph, k)
    assert m["cut"] == mh["cut"], (m["cut"], mh["cut"])


def test_extract_core_compressed_roundtrip():
    """Compressed core extraction must equal remove_isolated_nodes on
    the decoded graph (same rows, remapped ids, per-row sorted)."""
    from kaminpar_tpu.graphs.compressed import (
        compress_host_graph,
        extract_core_compressed,
    )
    from kaminpar_tpu.graphs.factories import make_rmat
    from kaminpar_tpu.graphs.host import remove_isolated_nodes

    host = make_rmat(1 << 10, 6_000, seed=2)
    assert int((host.degrees() == 0).sum()) > 0
    cg = compress_host_graph(host)
    core_cg, core_ids, iso_ids = extract_core_compressed(
        cg, chunk_nodes=100
    )
    core_ref, perm, _ = remove_isolated_nodes(host)
    dec = core_cg.decode()
    assert dec.n == core_ref.n and dec.m == core_ref.m
    np.testing.assert_array_equal(dec.xadj, core_ref.xadj)
    # per-row neighbor sets match (order may differ: re-encode sorts)
    for u in range(dec.n):
        a = sorted(dec.adjncy[dec.xadj[u]:dec.xadj[u + 1]])
        b = sorted(core_ref.adjncy[core_ref.xadj[u]:core_ref.xadj[u + 1]])
        assert a == b, u
    assert len(core_ids) + len(iso_ids) == host.n


def test_extract_core_compressed_weighted_roundtrip():
    """Weighted twin of the core-extraction roundtrip: edge weights must
    survive the per-row re-sort + re-encode (the v2 emit-order hazard)
    and node weights must subset to the core."""
    from kaminpar_tpu.graphs.compressed import (
        compress_host_graph,
        extract_core_compressed,
    )
    from kaminpar_tpu.graphs.factories import make_rmat
    from kaminpar_tpu.graphs.host import remove_isolated_nodes

    host = make_rmat(1 << 10, 6_000, seed=2)
    rng = np.random.default_rng(7)
    ew = host.edge_weight_array().copy()
    # make_rmat graphs carry multiplicity weights; scramble further, but
    # keep the symmetric invariant w(u,v) == w(v,u) via a canonical key
    src = host.edge_sources()
    lo = np.minimum(src, host.adjncy)
    hi = np.maximum(src, host.adjncy)
    ew = 1 + ((lo * 7919 + hi * 104729) % 97).astype(np.int64)
    host.edge_weights = ew
    host.node_weights = rng.integers(1, 9, host.n).astype(np.int64)
    assert int((host.degrees() == 0).sum()) > 0
    cg = compress_host_graph(host)
    core_cg, core_ids, iso_ids = extract_core_compressed(
        cg, chunk_nodes=100
    )
    core_ref, _, _ = remove_isolated_nodes(host)
    dec = core_cg.decode()
    np.testing.assert_array_equal(dec.xadj, core_ref.xadj)
    np.testing.assert_array_equal(
        np.asarray(core_cg.node_weights), core_ref.node_weight_array()
    )
    dw = dec.edge_weight_array()
    rw = core_ref.edge_weight_array()
    for u in range(dec.n):
        a = sorted(zip(dec.adjncy[dec.xadj[u]:dec.xadj[u + 1]],
                       dw[dec.xadj[u]:dec.xadj[u + 1]]))
        b = sorted(zip(core_ref.adjncy[core_ref.xadj[u]:core_ref.xadj[u + 1]],
                       rw[core_ref.xadj[u]:core_ref.xadj[u + 1]]))
        assert a == b, u
