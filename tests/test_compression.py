"""Compressed-graph subsystem tests (the reference's
tests/shm/datastructures/compressed_graph_test.cc checks compressed vs CSR
equivalence; tests/common/ covers the varint codecs)."""

import numpy as np
import pytest

from kaminpar_tpu import native
from kaminpar_tpu.graphs.compressed import compress_host_graph
from kaminpar_tpu.graphs.factories import (
    make_grid_graph,
    make_isolated_graph,
    make_rmat,
    make_star,
)


@pytest.mark.parametrize(
    "graph",
    [
        make_grid_graph(8, 8),
        make_star(31),
        make_rmat(256, 1024, seed=5),
        make_isolated_graph(10),
    ],
    ids=["grid", "star", "rmat", "isolated"],
)
def test_compressed_equals_csr(graph):
    cg = compress_host_graph(graph)
    assert cg.n == graph.n and cg.m == graph.m
    back = cg.decode()
    assert (back.xadj == graph.xadj).all()
    assert (back.adjncy == graph.adjncy).all()
    for u in [0, graph.n // 2, graph.n - 1] if graph.n else []:
        assert (cg.neighbors(u) == graph.neighbors(u)).all()


def test_varint_codec_roundtrip_fuzz():
    rng = np.random.default_rng(0)
    for _ in range(5):
        n = int(rng.integers(1, 50))
        deg = rng.integers(0, 20, size=n)
        xadj = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(deg, out=xadj[1:])
        adjncy = np.sort(
            rng.integers(0, max(1, 10 * n), size=int(xadj[-1])).astype(np.int32)
        )
        # per-node sorted neighborhoods
        for u in range(n):
            adjncy[xadj[u] : xadj[u + 1]] = np.sort(adjncy[xadj[u] : xadj[u + 1]])
        data, off = native.encode_gaps(xadj, adjncy)
        assert (native.decode_gaps(xadj, off, data) == adjncy).all()
        # numpy fallback produces the identical stream
        d2, o2 = native._encode_gaps_np(n, xadj, adjncy.astype(np.int32))
        assert (d2 == data).all() and (o2 == off).all()


def test_compression_saves_memory():
    g = make_rmat(1 << 12, 1 << 15, seed=2)
    cg = compress_host_graph(g)
    assert cg.compression_ratio() > 1.5


def test_compressed_binary_roundtrip(tmp_path):
    from kaminpar_tpu.io import load_graph, write_compressed

    g = make_rmat(512, 2048, seed=9)
    cg = compress_host_graph(g)
    path = str(tmp_path / "g.npz")
    write_compressed(path, cg)
    back = load_graph(path)  # auto-detects the compressed container
    assert back.n == g.n and back.m == g.m
    dec = back.decode()
    assert (dec.adjncy == g.adjncy).all()


def test_terapart_preset_partitions_compressed(rgg2d):
    from kaminpar_tpu import KaMinPar
    from kaminpar_tpu.utils.logger import OutputLevel

    part = (
        KaMinPar("terapart")
        .set_output_level(OutputLevel.QUIET)
        .set_graph(rgg2d)
        .compute_partition(k=8, epsilon=0.03, seed=0)
    )
    assert part.shape == (rgg2d.n,)
    nw = rgg2d.node_weight_array()
    bw = np.zeros(8, dtype=np.int64)
    np.add.at(bw, part, nw)
    cap = int(1.03 * np.ceil(nw.sum() / 8)) + int(nw.max())
    assert (bw <= cap).all()


def test_linear_time_kway_preset(rgg2d):
    from kaminpar_tpu import KaMinPar
    from kaminpar_tpu.utils.logger import OutputLevel

    part = (
        KaMinPar("linear-time-kway")
        .set_output_level(OutputLevel.QUIET)
        .set_graph(rgg2d)
        .compute_partition(k=4, epsilon=0.03, seed=0)
    )
    assert part.shape == (rgg2d.n,)
    assert part.min() >= 0 and part.max() < 4
