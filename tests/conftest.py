"""Test configuration: force an 8-device virtual CPU platform.

This is the TPU analog of the reference's mpirun-on-one-box testing
(tests/CMakeLists.txt:114-117 runs distributed tests with 1/2/4 ranks on a
single machine): XLA's host platform is split into 8 virtual devices so the
multi-chip sharding paths compile and execute without TPU hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The image's sitecustomize registers the axon (remote-TPU tunnel) PJRT
# plugin at interpreter start and overrides jax_platforms to "axon,cpu";
# the env var alone cannot opt out, and initializing the axon backend
# blocks for minutes establishing the tunnel.  Force the config back to
# CPU before any backend is initialized so the suite runs on the 8
# virtual CPU devices.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    # the tier-1 command (ROADMAP.md) runs `-m 'not slow'`: heavy tests
    # past the 870 s budget opt out with this marker and still run in a
    # plain `pytest tests/`
    config.addinivalue_line(
        "markers",
        "slow: heavy tests excluded from the tier-1 time budget",
    )


@pytest.fixture(autouse=True)
def _seed():
    from kaminpar_tpu.utils import rng

    rng.set_seed(0)
    yield


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Drop compiled executables between test modules.

    The suite compiles thousands of CPU executables in one process; the
    accumulated JIT state eventually segfaulted XLA's CPU compiler mid-
    suite (reproducible at the same test, absent when the same tests run
    in a fresh process).  Clearing per module keeps the live-executable
    population bounded at a small recompile cost."""
    yield
    jax.clear_caches()


@pytest.fixture
def rgg2d():
    from kaminpar_tpu.io import load_graph

    return load_graph("/root/reference/misc/rgg2d.metis")
