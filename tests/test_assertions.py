"""Leveled assertion tests (kaminpar-common/assert.h KASSERT analog)."""

import numpy as np
import pytest

from kaminpar_tpu.utils.assertions import (
    AssertionLevel,
    assertion_level,
    heavy_assertions_enabled,
    kassert,
    set_assertion_level,
)


@pytest.fixture(autouse=True)
def _restore_level():
    level = assertion_level()
    yield
    set_assertion_level(level)


def test_kassert_raises_at_active_level():
    set_assertion_level(AssertionLevel.NORMAL)
    with pytest.raises(AssertionError, match="boom"):
        kassert(False, "boom", AssertionLevel.NORMAL)
    kassert(True, "fine", AssertionLevel.NORMAL)


def test_kassert_skips_disabled_levels():
    set_assertion_level(AssertionLevel.LIGHT)
    # HEAVY check is compiled out: the callable must not even run
    kassert(lambda: 1 / 0, "never evaluated", AssertionLevel.HEAVY)
    assert not heavy_assertions_enabled()
    set_assertion_level("heavy")
    assert heavy_assertions_enabled()


def test_always_level_fires_even_at_zero():
    set_assertion_level(AssertionLevel.ALWAYS)
    with pytest.raises(AssertionError):
        kassert(False, "always", AssertionLevel.ALWAYS)


def test_heavy_level_validates_graph_in_set_graph():
    from kaminpar_tpu.graphs.host import HostGraph
    from kaminpar_tpu.kaminpar import KaMinPar

    # asymmetric adjacency: 0->1 without the reverse edge
    bad = HostGraph(
        xadj=np.array([0, 1, 1], dtype=np.int64),
        adjncy=np.array([1], dtype=np.int32),
    )
    set_assertion_level(AssertionLevel.HEAVY)
    with pytest.raises(ValueError):
        KaMinPar("default").set_graph(bad)
    # at normal level the same graph is accepted without validation
    set_assertion_level(AssertionLevel.NORMAL)
    KaMinPar("default").set_graph(bad)


def test_mtkahypar_adapter_is_gated():
    from kaminpar_tpu.refinement.mtkahypar import (
        mtkahypar_available,
        mtkahypar_refine_host,
    )

    if mtkahypar_available():  # pragma: no cover - not in this image
        pytest.skip("external mtkahypar present")
    from kaminpar_tpu.graphs.factories import make_grid_graph

    g = make_grid_graph(4, 4)
    with pytest.raises(RuntimeError, match="mtkahypar"):
        mtkahypar_refine_host(g, np.zeros(16, dtype=np.int32), 2, epsilon=0.03)
