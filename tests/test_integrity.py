"""Integrity sentinels (resilience/integrity.py): silent-data-corruption
defense.

Covers the four legs of the integrity contract (docs/robustness.md):
invariant sentinels at the phase boundaries, checksummed exchange,
sampled re-execution audits, and corruption chaos — plus the bounded
retry-from-last-good-barrier ladder, the `all`-plan exclusion of
corruption sites, the KAMINPAR_TPU_INTEGRITY=0 kill switch, the jaxpr
dormancy pin, and the schema-v14 `integrity` report section.
"""

import dataclasses
import importlib.util
import json
import os

import numpy as np
import pytest

from kaminpar_tpu import resilience, telemetry
from kaminpar_tpu.graphs import factories
from kaminpar_tpu.resilience import faults, integrity, with_fallback
from kaminpar_tpu.resilience.errors import IntegrityViolation

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_integrity(monkeypatch):
    """Every test starts with zero fault counters, no plan, integrity
    enabled at default knobs, and a fresh telemetry stream."""
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    monkeypatch.delenv(integrity.ENV_INTEGRITY, raising=False)
    monkeypatch.delenv(integrity.ENV_AUDIT_FRACTION, raising=False)
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    yield
    resilience.reset()
    telemetry.disable()
    telemetry.reset()


def _contracted(rows=16, cols=16, seed=1):
    """One real contraction of a grid graph: (fine device graph,
    CoarseGraph, coarse n)."""
    import jax.numpy as jnp

    from kaminpar_tpu.graphs.csr import device_graph_from_host
    from kaminpar_tpu.ops.contraction import contract_clustering
    from kaminpar_tpu.ops.lp import LPConfig, lp_cluster

    dg = device_graph_from_host(factories.make_grid_graph(rows, cols))
    labels = lp_cluster(
        dg, jnp.asarray(64, dtype=dg.node_w.dtype), jnp.int32(seed),
        LPConfig(num_iterations=2),
    )
    coarse, c_n, _ = contract_clustering(dg, labels)
    return dg, coarse, c_n


# ---------------------------------------------------------------------------
# invariant sentinels: contraction boundary
# ---------------------------------------------------------------------------


def test_contraction_sentinels_pass_clean():
    dg, coarse, c_n = _contracted()
    integrity.check_contraction(
        dg, coarse.cmap, coarse.graph, level=0, fine_n=int(dg.n),
        coarse_n=c_n,
    )
    s = integrity.summary()
    assert s["enabled"] and s["checks"] >= 5
    assert s["violations"] == [] and s["verdict"] == "clean"
    assert s["wall_s"] >= 0.0


def test_sentinel_catches_corrupted_coarse_edge_weight():
    import jax.numpy as jnp

    dg, coarse, c_n = _contracted()
    ew = np.array(np.asarray(coarse.graph.edge_w), copy=True)
    ew.reshape(-1)[0] ^= ew.dtype.type(1 << 5)
    bad = dataclasses.replace(coarse.graph, edge_w=jnp.asarray(ew))
    with pytest.raises(IntegrityViolation) as exc:
        integrity.check_contraction(
            dg, coarse.cmap, bad, level=3, fine_n=int(dg.n),
            coarse_n=c_n,
        )
    assert exc.value.invariant in (
        "edge-weight-conservation", "coarse-csr-symmetry",
    )
    assert exc.value.level == 3
    row = integrity.summary()["violations"][0]
    assert row["invariant"] == exc.value.invariant
    assert row["level"] == 3 and row["scope"] == "coarsen:3"
    # the violation is also a telemetry event
    ev = [e for e in telemetry.events("integrity")
          if e.attrs.get("action") == "violation"]
    assert ev and ev[0].attrs["invariant"] == exc.value.invariant


def test_sentinel_catches_corrupted_cmap():
    import jax.numpy as jnp

    dg, coarse, c_n = _contracted()
    cm = np.array(np.asarray(coarse.cmap), copy=True)
    cm[0] = c_n + 1000  # far out of the coarse id range
    with pytest.raises(IntegrityViolation) as exc:
        integrity.check_contraction(
            dg, jnp.asarray(cm), coarse.graph, level=0,
            fine_n=int(dg.n), coarse_n=c_n,
        )
    # any named invariant is a detection; the range check names it best
    assert exc.value.invariant in (
        "cmap-range", "edge-weight-conservation",
    )


def test_sentinel_catches_corrupted_node_weight():
    import jax.numpy as jnp

    dg, coarse, c_n = _contracted()
    nw = np.array(np.asarray(coarse.graph.node_w), copy=True)
    nw[0] += nw.dtype.type(7)
    bad = dataclasses.replace(coarse.graph, node_w=jnp.asarray(nw))
    with pytest.raises(IntegrityViolation) as exc:
        integrity.check_contraction(
            dg, coarse.cmap, bad, level=0, fine_n=int(dg.n),
            coarse_n=c_n,
        )
    assert exc.value.invariant == "node-weight-conservation"


# ---------------------------------------------------------------------------
# invariant sentinels: refinement boundary (pure host tuples)
# ---------------------------------------------------------------------------


def test_refinement_cut_regression_detected():
    with pytest.raises(IntegrityViolation) as exc:
        integrity.check_refinement(
            (10, True, 0, 3), (12, True, 0, 3), k=4, level=1,
        )
    assert exc.value.invariant == "cut-non-increase"
    assert exc.value.level == 1


def test_refinement_partition_range_detected():
    with pytest.raises(IntegrityViolation) as exc:
        integrity.check_refinement(
            (10, True, 0, 3), (8, True, 0, 7), k=4, level=0,
        )
    assert exc.value.invariant == "partition-range"


def test_refinement_balancer_tradeoff_is_not_corruption():
    # an infeasible input legitimately trades cut for balance
    integrity.check_refinement(
        (10, False, 0, 3), (14, True, 0, 3), k=4, level=0,
    )
    # feasible -> infeasible never triggers the cut check either
    integrity.check_refinement(
        (10, True, 0, 3), (14, False, 0, 3), k=4, level=0,
    )
    assert integrity.summary()["violations"] == []


def test_refinement_none_probes_are_noops():
    integrity.check_refinement(None, (1, True, 0, 0), k=4, level=0)
    integrity.check_refinement((1, True, 0, 0), None, k=4, level=0)
    assert integrity.summary()["checks"] == 0


# ---------------------------------------------------------------------------
# kill switch
# ---------------------------------------------------------------------------


def test_kill_switch_disables_every_leg(monkeypatch):
    import jax.numpy as jnp

    dg, coarse, c_n = _contracted()
    monkeypatch.setenv(integrity.ENV_INTEGRITY, "0")
    assert not integrity.enabled()
    # a grossly corrupted contraction sails through: sentinels dormant
    nw = np.array(np.asarray(coarse.graph.node_w), copy=True)
    nw[0] += nw.dtype.type(99)
    bad = dataclasses.replace(coarse.graph, node_w=jnp.asarray(nw))
    integrity.check_contraction(
        dg, coarse.cmap, bad, level=0, fine_n=int(dg.n), coarse_n=c_n,
    )
    # probes return None, digest verification is vacuous
    assert integrity.refine_probe(dg, coarse.cmap, None, None) is None
    integrity.verify_digest("feedface", np.arange(4), what="x")
    assert integrity.summary() == {"enabled": False}


# ---------------------------------------------------------------------------
# checksummed exchange
# ---------------------------------------------------------------------------


def test_content_digest_roundtrip_and_mismatch():
    a = np.arange(64, dtype=np.int32)
    d = integrity.content_digest(a)
    integrity.verify_digest(d, a, what="unit", site="cache-poison")
    b = a.copy()
    b[0] ^= 1 << 7
    with pytest.raises(IntegrityViolation) as exc:
        integrity.verify_digest(d, b, what="unit", site="cache-poison")
    assert exc.value.invariant == "exchange-digest"
    s = integrity.summary()["digests"]
    assert s["verified"] == 2 and s["mismatched"] == 1
    # a missing expected digest verifies vacuously (pre-upgrade data)
    integrity.verify_digest("", b, what="unit")


def test_digest_distinguishes_dtype_reinterpretation():
    a = np.arange(8, dtype=np.int32)
    assert integrity.content_digest(a) != integrity.content_digest(
        a.view(np.uint32)
    )


def test_snapshot_sha_verified_on_read(tmp_path):
    from kaminpar_tpu.io.snapshot import (
        SnapshotError,
        read_snapshot,
        write_snapshot,
    )

    path = str(tmp_path / "x.npz")
    arrays = {"adjncy": np.arange(100, dtype=np.int32)}
    _, sha = write_snapshot(path, arrays)
    out = read_snapshot(path, sha)
    assert np.array_equal(out["adjncy"], arrays["adjncy"])
    # flip one at-rest byte: the sha check must fire BEFORE np.load
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0x40]))
    with pytest.raises(SnapshotError):
        read_snapshot(path, sha)


# ---------------------------------------------------------------------------
# corruption chaos helpers
# ---------------------------------------------------------------------------


def test_chaos_flip_array_fires_once(monkeypatch):
    monkeypatch.setenv(faults.ENV_VAR, "cache-poison:nth=1")
    resilience.reset()
    a = np.arange(16, dtype=np.int32)
    out = integrity.chaos_flip_array("cache-poison", a)
    assert out[0] == a[0] ^ (1 << 7) and not np.array_equal(out, a)
    assert np.array_equal(a, np.arange(16, dtype=np.int32))  # copy, not in place
    # nth=1 consumed: the second call is a no-op passthrough
    again = integrity.chaos_flip_array("cache-poison", a)
    assert again is a
    assert {"site": "cache-poison", "call": 1} in faults.injected_log()


def test_chaos_flip_file_mutates_at_rest_bytes(tmp_path, monkeypatch):
    monkeypatch.setenv(faults.ENV_VAR, "spill-corrupt:nth=1")
    resilience.reset()
    path = str(tmp_path / "chunk.bin")
    with open(path, "wb") as f:
        f.write(bytes(range(64)))
    before = open(path, "rb").read()
    assert integrity.chaos_flip_file("spill-corrupt", path) is True
    after = open(path, "rb").read()
    assert before != after and len(before) == len(after)
    # one flipped bit in exactly one byte
    diff = [i for i in range(64) if before[i] != after[i]]
    assert len(diff) == 1
    # consumed: no second mutation
    assert integrity.chaos_flip_file("spill-corrupt", path) is False


def test_all_plan_excludes_corruption_sites(monkeypatch):
    """`all` covers degradation-contract sites only: corruption chaos
    (IntegrityViolation-typed sites) is opt-in by name — two corruption
    injections in one run would exhaust the retry budget by
    construction."""
    monkeypatch.setenv(faults.ENV_VAR, "all:nth=1")
    resilience.reset()
    # corruption sites skip the `all` rule entirely
    faults.maybe_inject("bit-flip:contraction")
    faults.maybe_inject("spill-corrupt")
    # a degradation-contract site still fires
    with pytest.raises(faults.SITES["refiner"].exc):
        faults.maybe_inject("refiner")


def test_colon_site_plan_parsing():
    rules = faults.parse_plan(
        "bit-flip:contraction:nth=1,spill-corrupt:0.5,bit-flip:partition"
    )
    assert [r.site for r in rules] == [
        "bit-flip:contraction", "spill-corrupt", "bit-flip:partition",
    ]
    assert rules[0].nth == 1
    assert rules[1].prob == 0.5
    assert rules[2].nth is None and rules[2].prob is None


# ---------------------------------------------------------------------------
# the retry ladder + the with_fallback carve-out
# ---------------------------------------------------------------------------


def test_with_fallback_never_absorbs_integrity_violation():
    def primary():
        raise integrity.violation("cut-non-increase", "unit", scope="t")

    with pytest.raises(IntegrityViolation):
        with_fallback(primary, lambda: "swallowed", site="refiner")


def test_run_with_retry_recovers_once():
    calls = {"n": 0}

    def body():
        calls["n"] += 1
        if calls["n"] == 1:
            raise integrity.violation(
                "edge-weight-conservation", "unit", level=0, scope="t",
            )
        return "ok"

    assert integrity.run_with_retry(body, where="unit") == "ok"
    s = integrity.summary()
    assert s["retries"] == 1 and s["recovered"] == 1
    assert s["verdict"] == "recovered"
    actions = [e.attrs.get("action")
               for e in telemetry.events("integrity")]
    assert "retry" in actions and "recovered" in actions


def test_run_with_retry_bounded_corrupt_result():
    def body():
        raise integrity.violation(
            "cmap-surjective", "unit", level=2, scope="t",
        )

    with pytest.raises(IntegrityViolation):
        integrity.run_with_retry(body, where="unit")
    s = integrity.summary()
    assert s["retries"] == integrity.MAX_RETRIES
    assert s["recovered"] == 0 and s["verdict"] == "corrupt-result"


# ---------------------------------------------------------------------------
# sampled re-execution audits
# ---------------------------------------------------------------------------


def test_audit_fraction_one_audits_every_contraction(monkeypatch):
    monkeypatch.setenv(integrity.ENV_AUDIT_FRACTION, "1.0")
    dg, coarse, c_n = _contracted()
    integrity.check_contraction(
        dg, coarse.cmap, coarse.graph, level=0, fine_n=int(dg.n),
        coarse_n=c_n,
    )
    s = integrity.summary()
    assert s["audit_fraction"] == 1.0
    ent = s["audits"]["contraction-weights"]
    assert ent == {"audited": 1, "mismatched": 0}


def test_audit_mismatch_is_a_violation():
    with pytest.raises(IntegrityViolation) as exc:
        integrity.record_audit("unit-scope", mismatched=True, level=1)
    assert exc.value.invariant == "audit:unit-scope"
    ent = integrity.summary()["audits"]["unit-scope"]
    assert ent == {"audited": 1, "mismatched": 1}


def test_audit_sampling_is_deterministic(monkeypatch):
    monkeypatch.setenv(integrity.ENV_AUDIT_FRACTION, "0.5")
    first = [integrity.should_audit("scope-a") for _ in range(32)]
    integrity.reset()  # clears the per-scope call counters
    second = [integrity.should_audit("scope-a") for _ in range(32)]
    assert first == second
    assert any(first) and not all(first)  # 0.5 actually samples


def test_audit_off_by_default():
    assert integrity.audit_fraction() == 0.0
    assert not integrity.should_audit("anything")


# ---------------------------------------------------------------------------
# end-to-end: chaos proof + dormancy + schema
# ---------------------------------------------------------------------------


def _partition(k=4, seed=1):
    from kaminpar_tpu.graphs.factories import make_rgg2d
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.presets import create_context_by_preset_name
    from kaminpar_tpu.utils import rng

    rng.set_seed(0)
    ctx = create_context_by_preset_name("default")
    # force real coarsening levels at n=400 so the contraction chaos
    # site has a first call to hit
    ctx.coarsening.contraction_limit = 50
    g = make_rgg2d(400, avg_degree=8, seed=3)
    solver = KaMinPar(ctx)
    solver.set_graph(g)
    part = solver.compute_partition(k=k, epsilon=0.03, seed=seed)
    return np.asarray(part)


def test_bitflip_chaos_detect_retry_recover_cut_identical(monkeypatch):
    """The chaos proof: an injected contraction bit-flip is detected by
    a named invariant, recovered in one retry, and the final partition
    is IDENTICAL to the uninjected run (recovery is lossless).  With
    detection kill-switched the same injection yields a measurably
    different (silently corrupt) result."""
    baseline = _partition()

    resilience.reset()
    telemetry.reset()
    monkeypatch.setenv(faults.ENV_VAR, "bit-flip:contraction:nth=1")
    injected = _partition()
    s = integrity.summary()
    assert s["verdict"] == "recovered", s
    assert s["retries"] == 1 and s["recovered"] == 1
    invariants = {v["invariant"] for v in s["violations"]}
    assert invariants & {
        "edge-weight-conservation", "coarse-csr-symmetry",
    }, invariants
    assert all(v["level"] is not None for v in s["violations"])
    assert {"site": "bit-flip:contraction",
            "call": 1} in faults.injected_log()
    assert np.array_equal(injected, baseline)

    # A/B: same injection, detection off -> silently different result
    resilience.reset()
    telemetry.reset()
    monkeypatch.setenv(integrity.ENV_INTEGRITY, "0")
    corrupt = _partition()
    assert integrity.summary() == {"enabled": False}
    assert not np.array_equal(corrupt, baseline)


def test_jaxpr_dormancy_lp_jet_contraction(monkeypatch):
    """The acceptance pin: the LP / Jet / contraction programs trace to
    bitwise-identical jaxprs whether integrity is on, off, or the
    sentinels have already compiled — every gate is a SEPARATE jitted
    reduction, never a branch inside the pipeline jaxprs."""
    import jax
    import jax.numpy as jnp

    from kaminpar_tpu.graphs.csr import device_graph_from_host
    from kaminpar_tpu.ops import jet as jet_mod
    from kaminpar_tpu.ops import lp as lp_mod
    from kaminpar_tpu.ops.contraction import _contract_part1

    g = factories.make_grid_graph(8, 8)
    dg = device_graph_from_host(g)
    part0 = jnp.asarray((np.arange(dg.n_pad) % 4).astype(np.int32))

    # progress capture off so only the INTEGRITY toggle varies
    monkeypatch.setenv("KAMINPAR_TPU_PROGRESS", "0")

    def traces():
        cluster = str(jax.make_jaxpr(
            lambda s: lp_mod.lp_cluster(
                dg, jnp.asarray(64, dtype=dg.node_w.dtype), s,
                lp_mod.LPConfig(num_iterations=2),
            )
        )(jnp.int32(3)))
        jet = str(jax.make_jaxpr(
            lambda p: jet_mod._jet_build_conn(dg, p, 4)
        )(part0))
        contraction = str(jax.make_jaxpr(
            lambda lab: _contract_part1(dg, lab)
        )(part0))
        return cluster, jet, contraction

    assert integrity.enabled()
    j_on = traces()
    # warm the sentinel jits too: compiled sentinels must not leak in
    dg2, coarse, c_n = _contracted(8, 8)
    integrity.check_contraction(
        dg2, coarse.cmap, coarse.graph, level=0, fine_n=int(dg2.n),
        coarse_n=c_n,
    )
    j_warm = traces()
    monkeypatch.setenv(integrity.ENV_INTEGRITY, "0")
    j_off = traces()
    assert j_on == j_warm == j_off


def test_report_schema_v14_integrity_section():
    from kaminpar_tpu.telemetry.report import (
        SCHEMA_PATH,
        SCHEMA_VERSION,
        build_run_report,
    )

    assert SCHEMA_VERSION == 14
    _partition(k=2)
    report = build_run_report()
    assert report["schema_version"] == 14
    integ = report["integrity"]
    assert integ["enabled"] is True
    assert integ["checks"] > 0 and integ["verdict"] == "clean"
    assert integ["digests"]["mismatched"] == 0

    spec = importlib.util.spec_from_file_location(
        "check_report_schema",
        os.path.join(_REPO, "scripts", "check_report_schema.py"),
    )
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)
    schema = json.loads(open(SCHEMA_PATH).read())
    assert checker.validate_instance(report, schema) == []
    assert checker.version_checks(report) == []


def test_overhead_pct_metering():
    integrity.reset()
    assert integrity.overhead_pct(0.0) == 0.0
    dg, coarse, c_n = _contracted(8, 8)
    integrity.check_contraction(
        dg, coarse.cmap, coarse.graph, level=0, fine_n=int(dg.n),
        coarse_n=c_n,
    )
    wall = integrity.summary()["wall_s"]
    assert wall > 0.0
    assert integrity.overhead_pct(wall * 100) == pytest.approx(
        1.0, rel=0.2
    )
