"""Serving-layer tests: admission control, per-request fault isolation,
bounded caches, drain semantics — plus the PR-6 per-run resilience-state
regression suite (docs/robustness.md, serving contract).

The load-with-chaos test is the acceptance check of ISSUE 6: a mixed
batch (varying n, k, eps, one deliberately malformed graph, fault
sampling on) must finish with every served result gate-valid, the
poisoned request failed in isolation, and zero cross-request
contamination of telemetry scopes or checkpoint state.
"""

import json
import threading
import time

import numpy as np
import pytest

from kaminpar_tpu import caching, resilience, telemetry
from kaminpar_tpu.graphs.factories import make_rgg2d
from kaminpar_tpu.resilience import checkpoint as ckpt_mod
from kaminpar_tpu.resilience import deadline as deadline_mod
from kaminpar_tpu.resilience import faults, runstate
from kaminpar_tpu.serving import (
    PartitionRequest,
    PartitionService,
    ServiceConfig,
)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(ckpt_mod.STOP_AT_ENV, raising=False)
    monkeypatch.delenv(resilience.FAULTS_ENV_VAR, raising=False)
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    yield
    resilience.reset()
    telemetry.disable()
    telemetry.reset()


def _gen(n=600, seed=3):
    return f"gen:rgg2d;n={n};avg_degree=8;seed={seed}"


def _svc(**cfg):
    return PartitionService("default", ServiceConfig(**cfg))


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


def test_admission_queue_depth_cap():
    svc = _svc(max_queue_depth=2)
    assert svc.submit(PartitionRequest(_gen(), k=4)) is None
    assert svc.submit(PartitionRequest(_gen(), k=4)) is None
    rec = svc.submit(PartitionRequest(_gen(), k=4))
    assert rec is not None and rec.verdict == "rejected"
    assert rec.reason == "queue-full"


def test_admission_cost_caps():
    # caps are BYTES of estimated device footprint since the memory
    # governor unified the sizing model (resilience/memory.py): derive
    # the thresholds from the estimator so the test tracks calibration
    from kaminpar_tpu.resilience.memory import estimate_run_bytes

    small = estimate_run_bytes(600, 600 * 8, 4)
    svc = _svc(
        max_queued_cost=int(small * 1.5), max_request_cost=int(small * 2)
    )
    # a single oversized request is refused outright
    rec = svc.submit(PartitionRequest(_gen(n=4096), k=4))
    assert rec is not None and rec.reason == "request-too-large"
    # and the aggregate cap holds across queued requests
    assert svc.submit(PartitionRequest(_gen(n=600), k=4)) is None
    rec2 = svc.submit(PartitionRequest(_gen(n=600), k=4))
    assert rec2 is not None and rec2.reason == "cost-cap"
    # every admission decision is a record in the batch, nothing queued
    # was lost
    assert [r.verdict for r in svc.records] == ["rejected", "rejected"]


def test_admission_insufficient_memory():
    """A declared memory budget rejects requests whose MINIMUM
    device-resident footprint (rung-2 spilled estimate) cannot fit —
    sized from the gen spec, never a load; unsized file-backed inputs
    skip the rule (the 'unsized' convention)."""
    from kaminpar_tpu.presets import create_context_by_preset_name
    from kaminpar_tpu.resilience.memory import min_serveable_bytes

    ctx = create_context_by_preset_name("default")
    ctx.resilience.memory_budget = float(
        min_serveable_bytes(600, 4800, 4) + 1
    )
    svc = PartitionService(ctx, ServiceConfig())
    # fits the budget: admitted
    assert svc.submit(PartitionRequest(_gen(n=600), k=4)) is None
    # far too big for the declared budget even spilled: rejected with
    # the structured verdict, not queued toward an allocator death
    rec = svc.submit(PartitionRequest(_gen(n=200_000), k=4))
    assert rec is not None and rec.verdict == "rejected"
    assert rec.reason == "insufficient-memory"
    assert rec.n > 0  # sized without loading
    # an unsized file path cannot be sized: the rule does not fire
    rec2 = svc.submit(
        PartitionRequest("/nonexistent/never-loaded.metis", k=4)
    )
    assert rec2 is None


def test_admission_invalid_parameters():
    rec = _svc().submit(PartitionRequest(_gen(), k=0))
    assert rec is not None and rec.reason == "invalid-parameters"


def test_serving_admit_fault_site(monkeypatch):
    """The `serving-admit` injection forces a structured rejection (with
    the standard degraded event) and spends itself: the next submit is
    admitted."""
    monkeypatch.setenv(resilience.FAULTS_ENV_VAR, "serving-admit:nth=1")
    svc = _svc()
    rec = svc.submit(PartitionRequest(_gen(), k=4))
    assert rec is not None and rec.reason == "fault-injected"
    assert {"site": "serving-admit", "call": 1} in faults.injected_log()
    degraded = [e.attrs["site"] for e in telemetry.events("degraded")]
    assert "serving-admit" in degraded
    assert svc.submit(PartitionRequest(_gen(), k=4)) is None


# ---------------------------------------------------------------------------
# result cache + executable buckets
# ---------------------------------------------------------------------------


def test_result_cache_hit_on_identical_request():
    svc = _svc()
    recs = svc.serve([
        PartitionRequest(_gen(), k=4, seed=1),
        PartitionRequest(_gen(), k=4, seed=1),
    ])
    assert [r.verdict for r in recs] == ["served", "served"]
    assert not recs[0].cached and recs[1].cached
    assert recs[0].cut == recs[1].cut
    s = svc.summary()
    assert s["cache"]["result"]["hits"] == 1
    assert s["cache"]["hit_rate"] == 0.5
    # a different (k) forks the ctx fingerprint: no false sharing
    (r3,) = svc.serve([PartitionRequest(_gen(), k=8, seed=1)])
    assert not r3.cached


def test_result_cache_entry_cap_evicts_lru():
    svc = _svc(result_cache_entries=1)
    svc.serve([
        PartitionRequest(_gen(seed=3), k=4, seed=1),
        PartitionRequest(_gen(seed=4), k=4, seed=1),  # evicts seed=3
        PartitionRequest(_gen(seed=3), k=4, seed=1),  # recompute
    ])
    stats = svc.result_cache_stats()
    assert stats["entries"] == 1
    assert stats["evictions"] >= 1
    assert stats["hits"] == 0


def test_serving_cache_fault_forces_miss(monkeypatch):
    monkeypatch.setenv(resilience.FAULTS_ENV_VAR, "serving-cache:nth=2")
    svc = _svc()
    recs = svc.serve([
        PartitionRequest(_gen(), k=4, seed=1),
        PartitionRequest(_gen(), k=4, seed=1),  # lookup 2: injected
    ])
    # the second request recomputed (forced miss + evict) but stayed
    # correct and gate-valid — and the engaged site is on the verdict
    # even though the facade reset the telemetry stream at compute entry
    assert not recs[1].cached
    assert recs[1].verdict == "degraded"
    assert "serving-cache" in recs[1].degraded_sites
    assert recs[0].cut == recs[1].cut
    assert {"site": "serving-cache", "call": 2} in faults.injected_log()


def test_executable_bucket_reuse_accounting():
    tracker = caching.BucketTracker()
    assert tracker.observe(600, 4400, 4) == tracker.observe(610, 4500, 4)
    assert tracker.observe(600, 4400, 8) != tracker.observe(600, 4400, 4)
    stats = tracker.stats()
    assert stats == {
        "buckets": 2, "hits": 2, "misses": 2, "hit_rate": 0.5,
        "window": {"hits": 2, "misses": 2, "hit_rate": 0.5},
    }


def test_bounded_cache_byte_budget():
    c = caching.BoundedCache(max_entries=100, max_bytes=100)
    assert c.put("a", "x", 60) and c.put("b", "y", 60)  # evicts a
    assert c.get("a") is None and c.get("b") == "y"
    assert not c.put("huge", "z", 1000)  # refused, cache intact
    assert c.get("b") == "y"
    assert c.stats()["oversize"] == 1


# ---------------------------------------------------------------------------
# per-request fault isolation
# ---------------------------------------------------------------------------


def _malformed_metis(tmp_path):
    p = tmp_path / "poison.metis"
    p.write_text("3 2\n1 2\n999999 1\n2\n")  # out-of-range neighbor id
    return str(p)


def test_malformed_graph_fails_in_isolation(tmp_path):
    svc = _svc()
    recs = svc.serve([
        PartitionRequest(_gen(), k=4, seed=1, request_id="good-1"),
        PartitionRequest(_malformed_metis(tmp_path), k=4,
                         request_id="poison"),
        PartitionRequest(_gen(), k=4, seed=1, request_id="good-2"),
    ])
    by_id = {r.request_id: r for r in recs}
    assert by_id["poison"].verdict == "failed"
    assert by_id["poison"].reason == "malformed-input"
    assert by_id["poison"].error == "GraphFormatError"
    for rid in ("good-1", "good-2"):
        assert by_id[rid].verdict == "served"
        assert by_id[rid].feasible and by_id[rid].gate_valid
    # an input failure says nothing about the request class: no breaker
    assert svc._class_failures == {}


def test_crash_failures_open_per_class_breaker(monkeypatch):
    """Three crash-shaped failures in one request class reject the
    fourth at admission — without poisoning other classes.  Since the
    memory governor, a DeviceOOM is crash-shaped only once the recovery
    ladder is EXHAUSTED (every rung including host-only failed)."""
    from kaminpar_tpu import kaminpar as kp

    def boom(self, **kwargs):
        err = resilience.DeviceOOM("synthetic device OOM")
        err.rungs_exhausted = True  # ladder ran out of rungs
        raise err

    monkeypatch.setattr(kp.KaMinPar, "compute_partition", boom)
    svc = _svc()
    recs = svc.serve(
        [PartitionRequest(_gen(), k=4, seed=s) for s in (1, 2, 3)]
    )
    assert [r.verdict for r in recs] == ["failed"] * 3
    assert all(r.error == "DeviceOOM" for r in recs)
    # same class (same executable bucket): rejected at admission
    rej = svc.submit(PartitionRequest(_gen(), k=4, seed=4))
    assert rej is not None and rej.reason == "breaker-open"
    # a different class (different k bucket) is still admitted
    assert svc.submit(PartitionRequest(_gen(), k=16, seed=4)) is None


def test_ladder_retryable_oom_never_latches_breaker(monkeypatch):
    """A DeviceOOM that the recovery ladder could still retry (no
    `rungs_exhausted` stamp — only reachable at this boundary in a
    governor-disabled process) indicts the BUDGET, not the request
    class: the per-class breaker must not advance."""
    from kaminpar_tpu import kaminpar as kp

    def boom(self, **kwargs):
        raise resilience.DeviceOOM("retryable device OOM")

    monkeypatch.setattr(kp.KaMinPar, "compute_partition", boom)
    svc = _svc()
    recs = svc.serve(
        [PartitionRequest(_gen(), k=4, seed=s) for s in (1, 2, 3)]
    )
    assert [r.verdict for r in recs] == ["failed"] * 3
    assert all(r.error == "DeviceOOM" for r in recs)
    # the class breaker stayed closed: the next same-class request runs
    assert svc._class_failures == {}
    assert svc.submit(PartitionRequest(_gen(), k=4, seed=4)) is None


def test_deadline_request_winds_down_anytime_and_next_is_clean():
    """A per-request deadline yields an `anytime` verdict; the NEXT
    request gets a fresh run state — the stop verdict cannot leak
    (the satellite-1 hazard, service-level view)."""
    svc = _svc()
    recs = svc.serve([
        PartitionRequest(_gen(n=900, seed=4), k=8, seed=1,
                         deadline_s=1e-4),
        PartitionRequest(_gen(), k=4, seed=1),
    ])
    assert recs[0].verdict == "anytime"
    assert recs[0].reason == "budget"
    assert recs[0].feasible
    assert recs[1].verdict == "served"  # no inherited wind-down
    # anytime results are NOT cached: a later identical request with
    # time to do better must recompute
    assert svc.result_cache_stats()["entries"] == 1  # only the served one


def test_drain_rejects_queued_requests():
    svc = _svc()
    for s in (1, 2, 3):
        assert svc.submit(PartitionRequest(_gen(), k=4, seed=s)) is None
    svc.drain()
    recs = svc.run_pending()
    assert [r.verdict for r in recs] == ["rejected"] * 3
    assert all(r.reason == "draining" for r in recs)
    s = svc.summary()
    assert s["drained"] is True
    assert s["counts"]["rejected"] == 3
    # late submissions are rejected at admission, still one record each
    late = svc.submit(PartitionRequest(_gen(), k=4, seed=9))
    assert late is not None and late.reason == "draining"


# ---------------------------------------------------------------------------
# the ISSUE-6 acceptance batch: load with chaos
# ---------------------------------------------------------------------------


def test_mixed_chaos_batch_isolates_and_stays_gate_valid(
    tmp_path, monkeypatch
):
    monkeypatch.setenv(
        resilience.FAULTS_ENV_VAR,
        "refiner:0.3,device-balancer:0.3,native-ip:0.3",
    )
    svc = _svc()
    requests = [
        PartitionRequest(_gen(n=600, seed=3), k=4, seed=1),
        PartitionRequest(_gen(n=600, seed=3), k=4, epsilon=0.1, seed=1),
        PartitionRequest(_gen(n=900, seed=4), k=8, seed=2),
        PartitionRequest(_malformed_metis(tmp_path), k=4,
                         request_id="poison"),
        PartitionRequest(_gen(n=600, seed=3), k=4, seed=1),  # cache path
        PartitionRequest(_gen(n=400, seed=5), k=2, seed=3),
    ]
    recs = svc.serve(requests)
    assert len(recs) == len(requests)
    by_id = {r.request_id: r for r in recs}
    # the poisoned request failed ALONE
    assert by_id["poison"].verdict == "failed"
    completed = [r for r in recs if r.verdict in
                 ("served", "anytime", "degraded")]
    assert len(completed) == len(requests) - 1
    for rec in completed:
        assert rec.feasible, rec.to_dict()
        if rec.gate_valid is not None:  # cache hits reuse the verdict
            assert rec.gate_valid, rec.to_dict()
    # zero cross-request contamination:
    #  * each record carries its own request's shape, not a neighbor's
    assert by_id[requests[2].request_id].k == 8
    assert by_id[requests[5].request_id].k == 2
    #  * no checkpoint manager or resume state survived the batch
    assert ckpt_mod.active() is None
    assert not ckpt_mod.suspended()
    #  * the telemetry stream belongs to the LAST computed request only
    runs = [e for e in telemetry.events("coarsening-level")]
    ks = {telemetry.run_info().get("k")}
    assert ks <= {requests[5].k, None}, (ks, runs)
    # the serving summary is schema-shaped (validated end-to-end by the
    # check_all smoke; here: the invariants)
    s = svc.annotate()
    assert s["counts"]["failed"] == 1
    assert sum(s["counts"].values()) == len(requests)
    # every completed request consulted the result cache (hits are NOT
    # guaranteed under chaos: a degraded run is deliberately not cached)
    result_stats = s["cache"]["result"]
    assert result_stats["hits"] + result_stats["misses"] == len(completed)
    json.dumps(s)  # JSON-clean


# ---------------------------------------------------------------------------
# per-run resilience state (the satellite-1 regression suite)
# ---------------------------------------------------------------------------


def test_two_sequential_runs_share_process_without_state_leak(tmp_path):
    """Back-to-back facade runs in ONE process: run A is preempted mid-
    pipeline with a checkpoint on disk; run B (same process, resume NOT
    requested) must neither consume A's resume state nor inherit its
    stop verdict."""
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.presets import create_context_by_preset_name
    from kaminpar_tpu.resilience.checkpoint import SimulatedPreemption

    g = make_rgg2d(800, avg_degree=8, seed=3)
    ctx_a = create_context_by_preset_name("default")
    ctx_a.coarsening.contraction_limit = 50
    ctx_a.resilience.checkpoint_dir = str(tmp_path / "ckpt")
    import os

    os.environ[ckpt_mod.STOP_AT_ENV] = "coarsen:1!"
    try:
        with pytest.raises(SimulatedPreemption):
            solver_a = KaMinPar(ctx_a)
            solver_a.set_output_level(0)
            solver_a.set_graph(g)
            solver_a.compute_partition(k=4, epsilon=0.03, seed=1)
    finally:
        os.environ.pop(ckpt_mod.STOP_AT_ENV, None)
    assert (tmp_path / "ckpt" / "manifest.json").exists()

    # run B: fresh solver, SAME process, no --resume
    ctx_b = create_context_by_preset_name("default")
    ctx_b.coarsening.contraction_limit = 50
    ctx_b.resilience.checkpoint_dir = str(tmp_path / "ckpt-b")
    solver_b = KaMinPar(ctx_b)
    solver_b.set_output_level(0)
    solver_b.set_graph(g)
    part = solver_b.compute_partition(k=4, epsilon=0.03, seed=1)
    assert part.shape == (g.n,)
    assert solver_b.last_anytime is None  # A's verdict did not leak
    actions = [e.attrs.get("action") for e in telemetry.events("checkpoint")]
    assert "resumed" not in actions  # A's resume state was not consumed
    assert not deadline_mod.triggered()


def test_stale_stop_verdict_does_not_survive_begin_run():
    deadline_mod.request_stop("stop-at:test")
    assert deadline_mod.should_stop()
    deadline_mod.begin_run(None, None)
    # non-signal stop reasons are run-local: gone with the old run
    assert not deadline_mod.should_stop()
    # signal-shaped stops persist across begin_run (the PR-5 contract:
    # a SIGTERM during graph load winds down the run that follows)...
    deadline_mod.request_stop("sigterm")
    deadline_mod.begin_run(None, None)
    assert deadline_mod.should_stop()
    assert deadline_mod.state()["reason"] == "sigterm"
    # ...and only clear() (test isolation) drops it
    deadline_mod.clear()
    assert not deadline_mod.should_stop()


def test_runstate_thread_isolation():
    """Interleaved runs in different threads own independent deadline
    state; a process-wide signal stops every thread (drain semantics)."""
    results = {}
    first_done = threading.Barrier(3)  # both workers + the main thread
    signal_raised = threading.Event()

    def worker(name, budget):
        deadline_mod.begin_run(budget, None)
        if budget:
            time.sleep(0.01)  # let the tiny budget expire
        results[name] = {
            "stopped": deadline_mod.should_stop(),
            "reason": deadline_mod.state().get("reason"),
        }
        first_done.wait(timeout=10)
        assert signal_raised.wait(timeout=10)
        results[name + "/after-signal"] = deadline_mod.should_stop()

    ta = threading.Thread(target=worker, args=("a", 1e-4))
    tb = threading.Thread(target=worker, args=("b", None))
    ta.start()
    tb.start()
    first_done.wait(timeout=10)  # both first verdicts are recorded
    runstate.signal_stop("sigterm")
    signal_raised.set()
    ta.join(timeout=10)
    tb.join(timeout=10)
    assert results["a"]["stopped"] is True
    assert results["a"]["reason"] == "budget"
    assert results["b"]["stopped"] is False  # a's expiry stayed in a
    assert results["a/after-signal"] is True
    assert results["b/after-signal"] is True  # signals reach every run
    runstate.clear_signal()


def test_checkpoint_manager_is_per_run_object(tmp_path):
    """activate/suspend bookkeeping lives on the current run object: a
    begin_run (fresh run) structurally drops the previous manager."""
    mgr = ckpt_mod.CheckpointManager(str(tmp_path), "g", "c")
    ckpt_mod.activate(mgr)
    ckpt_mod.suspend()
    assert ckpt_mod.active() is mgr and ckpt_mod.suspended()
    deadline_mod.begin_run(None, None)
    assert ckpt_mod.active() is None
    assert not ckpt_mod.suspended()


# ---------------------------------------------------------------------------
# batch spec loader (the CLI surface)
# ---------------------------------------------------------------------------


def test_batch_spec_roundtrip(tmp_path):
    from kaminpar_tpu.serving.batch import BatchSpecError, load_batch

    spec = {
        "config": {"max_queue_depth": 7, "default_deadline_s": 2.5},
        "requests": [
            {"graph": _gen(), "k": 4, "epsilon": 0.05, "seed": 9,
             "priority": 2, "id": "hi"},
            {"graph": "some/path.metis", "k": 2},
        ],
    }
    p = tmp_path / "batch.json"
    p.write_text(json.dumps(spec))
    requests, config = load_batch(str(p))
    assert config.max_queue_depth == 7
    assert config.default_deadline_s == 2.5
    assert requests[0].request_id == "hi"
    assert requests[0].priority == 2 and requests[0].seed == 9
    assert requests[1].request_id == "req-2"
    for bad in (
        {"requests": []},
        {"requests": [{"graph": "x"}]},  # no k
        {"config": {"nope": 1}, "requests": [{"graph": "x", "k": 2}]},
        "not a batch",
    ):
        p.write_text(json.dumps(bad))
        with pytest.raises(BatchSpecError):
            load_batch(str(p))


def test_priority_orders_the_queue():
    svc = _svc()
    order = []
    real = PartitionService._execute

    def record_order(self, req, *args, **kwargs):
        order.append(req.request_id)
        return real(self, req, *args, **kwargs)

    PartitionService._execute = record_order
    try:
        svc.serve([
            PartitionRequest(_gen(), k=4, seed=1, priority=0,
                             request_id="low"),
            PartitionRequest(_gen(), k=4, seed=1, priority=5,
                             request_id="high"),
        ])
    finally:
        PartitionService._execute = real
    assert order == ["high", "low"]


# ---------------------------------------------------------------------------
# review-hardening regressions
# ---------------------------------------------------------------------------


def test_full_graph_digest_sees_what_the_sampling_fingerprint_misses():
    """The result-cache key must cover interior edges and edge weights —
    exactly the blind spots of the O(1) resume fingerprint."""
    from kaminpar_tpu.resilience.checkpoint import graph_fingerprint

    g = make_rgg2d(4096, avg_degree=16, seed=1)
    base = caching.full_graph_digest(g)

    # an interior adjacency entry beyond the sampled head/tail window
    g2 = make_rgg2d(4096, avg_degree=16, seed=1)
    mid = g2.adjncy.shape[0] // 2
    assert 4096 < mid < g2.adjncy.shape[0] - 4096
    g2.adjncy[mid] = (g2.adjncy[mid] + 1) % g2.n
    assert graph_fingerprint(g) == graph_fingerprint(g2)  # the blind spot
    assert caching.full_graph_digest(g2) != base

    # edge weights, which the sampling fingerprint never reads
    g3 = make_rgg2d(4096, avg_degree=16, seed=1)
    g3.edge_weights = np.full(g3.adjncy.shape[0], 2, dtype=np.int32)
    assert graph_fingerprint(g) == graph_fingerprint(g3)
    assert caching.full_graph_digest(g3) != base

    # and the combined serving key forks where the digest forks
    from kaminpar_tpu.presets import create_context_by_preset_name

    ctx = create_context_by_preset_name("default")
    assert (caching.result_cache_key(g, ctx)
            != caching.result_cache_key(g3, ctx))


def test_serve_drains_instead_of_rejecting_large_batches():
    """A single-producer batch bigger than the queue caps runs in
    windows; nothing is spuriously rejected queue-full/cost-cap."""
    svc = _svc(max_queue_depth=2)
    recs = svc.serve([
        PartitionRequest(_gen(), k=4, seed=1, request_id=f"r{i}")
        for i in range(5)
    ])
    assert len(recs) == 5
    assert [r.verdict for r in recs].count("rejected") == 0
    assert all(r.verdict == "served" for r in recs)


def test_pending_duplicate_id_rejected_then_reusable():
    svc = _svc()
    assert svc.submit(PartitionRequest(_gen(), k=4, seed=1,
                                       request_id="dup")) is None
    rej = svc.submit(PartitionRequest(_gen(), k=4, seed=1,
                                      request_id="dup"))
    assert rej is not None and rej.reason == "duplicate-id"
    svc.run_pending()
    # a completed id may be reused (re-submission of the same request)
    assert svc.submit(PartitionRequest(_gen(), k=4, seed=1,
                                       request_id="dup")) is None


def test_batch_spec_rejects_duplicate_ids_and_parses_string_bools(
    tmp_path,
):
    from kaminpar_tpu.serving.batch import BatchSpecError, load_batch

    p = tmp_path / "batch.json"
    # an explicit id colliding with a generated default ("req-2")
    p.write_text(json.dumps([
        {"graph": _gen(), "k": 4, "id": "req-2"},
        {"graph": _gen(), "k": 4},
    ]))
    with pytest.raises(BatchSpecError, match="duplicate"):
        load_batch(str(p))

    p.write_text(json.dumps({
        "config": {"keep_partitions": "false"},
        "requests": [{"graph": _gen(), "k": 4}],
    }))
    _, config = load_batch(str(p))
    assert config.keep_partitions is False  # bool("false") would be True

    p.write_text(json.dumps({
        "config": {"keep_partitions": "maybe"},
        "requests": [{"graph": _gen(), "k": 4}],
    }))
    with pytest.raises(BatchSpecError, match="boolean"):
        load_batch(str(p))


def test_file_backed_crashes_latch_the_admission_visible_class(
    tmp_path, monkeypatch
):
    """Admission can only ever see "unsized" for a file path (it never
    loads the input), so crash-shaped failures must latch that class
    too — otherwise the documented breaker-open rejection can never
    fire for file-backed requests."""
    from kaminpar_tpu import kaminpar as kp

    path = tmp_path / "tri.metis"
    path.write_text("3 3\n2 3\n1 3\n1 2\n")

    def boom(self, **kwargs):
        err = resilience.DeviceOOM("synthetic device OOM")
        err.rungs_exhausted = True  # crash-shaped: the ladder ran dry
        raise err

    monkeypatch.setattr(kp.KaMinPar, "compute_partition", boom)
    svc = _svc()
    recs = svc.serve([
        PartitionRequest(str(path), k=2, request_id=f"f{i}")
        for i in range(3)
    ])
    assert [r.verdict for r in recs] == ["failed"] * 3
    rej = svc.submit(PartitionRequest(str(path), k=2, request_id="f4"))
    assert rej is not None and rej.reason == "breaker-open"


def test_batch_spec_wraps_field_coercion_errors(tmp_path):
    """Every malformed spec field must surface as BatchSpecError (the
    CLI's exit-2 contract), never a raw TypeError/ValueError."""
    from kaminpar_tpu.serving.batch import BatchSpecError, load_batch

    p = tmp_path / "batch.json"
    for bad in (
        {"config": {"max_queue_depth": None},
         "requests": [{"graph": _gen(), "k": 2}]},
        {"requests": [{"graph": _gen(), "k": "four"}]},
        {"requests": [{"graph": _gen(), "k": 2, "seed": "abc"}]},
    ):
        p.write_text(json.dumps(bad))
        with pytest.raises(BatchSpecError):
            load_batch(str(p))


def test_admission_rejected_counter_excludes_drain_rejections():
    svc = _svc()
    assert svc.submit(PartitionRequest(_gen(), k=0)) is not None  # bad k
    for i in range(3):
        svc.submit(PartitionRequest(_gen(), k=4, seed=1,
                                    request_id=f"q{i}"))
    svc.drain()
    try:
        svc.run_pending()
    finally:
        deadline_mod.clear()
    s = svc.summary()
    # 1 admission rejection + 3 drain rejections share the verdict...
    assert s["counts"]["rejected"] == 4
    # ...but the admission metric counts only its own
    assert s["admission"]["rejected"] == 1
    assert s["drained"] is True


def test_reset_records_bounds_long_lived_services():
    svc = _svc()
    svc.serve([PartitionRequest(_gen(), k=4, seed=1)])
    window = svc.reset_records()
    assert len(window) == 1 and window[0].verdict == "served"
    assert svc.records == []
    assert svc.summary()["admission"]["rejected"] == 0
    # cache state survives the reset: the same request replays
    (rec,) = svc.serve([PartitionRequest(_gen(), k=4, seed=1)])
    assert rec.cached


def test_reset_records_windows_latency_and_cache_stats():
    """The windowing satellite: after reset_records() a long-lived
    service reports per-window hit rates and fresh latency histograms,
    while lifetime counters keep accruing."""
    svc = _svc()
    svc.serve([PartitionRequest(_gen(), k=4, seed=1)])
    s1 = svc.summary()
    assert s1["latency"]["phases"]["total"]["count"] == 1
    assert s1["cache"]["result"]["window"]["misses"] == 1
    svc.reset_records()
    s2 = svc.summary()
    # latency histograms restarted with the window
    assert s2["latency"]["phases"]["total"]["count"] == 0
    assert s2["latency"]["classes"] == {}
    # window counters restarted, lifetime kept
    assert s2["cache"]["result"]["window"]["misses"] == 0
    assert s2["cache"]["result"]["misses"] == 1
    # the next window's cache hit lands in the fresh window stats
    svc.serve([PartitionRequest(_gen(), k=4, seed=1)])
    s3 = svc.summary()
    assert s3["cache"]["result"]["window"]["hits"] == 1
    assert s3["cache"]["result"]["window"]["hit_rate"] == 1.0
    assert s3["latency"]["phases"]["total"]["count"] == 1


def test_latency_phase_breakdown_and_class_rollup():
    """Serving latency metrics: every executed request carries a
    per-phase breakdown, the summary exposes p50/p95/p99 per phase, and
    the per-class rollup joins latency with executable reuse."""
    svc = _svc()
    recs = svc.serve([
        PartitionRequest(_gen(), k=4, seed=1, request_id="l1"),
        PartitionRequest(_gen(), k=4, seed=1, request_id="l2"),  # cached
    ])
    for rec in recs:
        assert rec.phases, rec
        for key in ("admission_wait_ms", "resolve_ms", "compute_ms",
                    "gate_ms", "total_ms"):
            assert key in rec.phases, rec.phases
        assert rec.phases["total_ms"] >= 0
    # the cache hit spent no compute/gate time
    assert recs[1].cached and recs[1].phases["compute_ms"] == 0.0

    lat = svc.summary()["latency"]
    total = lat["phases"]["total"]
    assert total["count"] == 2
    assert total["p50_ms"] <= total["p95_ms"] <= total["p99_ms"]
    for phase in ("admission_wait", "resolve", "compute", "gate"):
        assert lat["phases"][phase]["count"] == 2
    # both requests share one shape class; the compiled-once bucket was
    # sighted once (the cache hit never touched an executable)
    assert len(lat["classes"]) == 1
    (cls_stats,) = lat["classes"].values()
    assert cls_stats["requests"] == 2
    assert cls_stats["executable_sightings"] == 1
    assert cls_stats["p95_ms"] is not None


def test_failed_request_records_latency():
    svc = _svc()
    (rec,) = svc.serve(
        [PartitionRequest("/nonexistent/path.metis", k=4)]
    )
    assert rec.verdict == "failed"
    assert rec.phases["total_ms"] >= 0
    assert svc.summary()["latency"]["phases"]["total"]["count"] == 1


def test_concurrent_submit_respects_caps():
    """submit() is safe for concurrent producers: the depth cap holds
    exactly and the bookkeeping maps stay consistent."""
    svc = _svc(max_queue_depth=16)
    results = []

    def producer(t):
        for i in range(40):
            results.append(
                svc.submit(PartitionRequest(
                    _gen(), k=4, request_id=f"t{t}-{i}"))
            )

    threads = [threading.Thread(target=producer, args=(t,))
               for t in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    queued = [r for r in results if r is None]
    assert len(svc._queue) == len(queued) == 16
    assert set(svc._queued_cost) == set(svc._order) == {
        req.request_id for req in svc._queue
    }
    rejected = [r for r in results if r is not None]
    assert all(r.reason == "queue-full" for r in rejected)
