"""Checkpoint/resume + deadline-budget tests (docs/robustness.md).

Kill-and-resume equivalence is the acceptance check of ISSUE 5: a run
hard-interrupted at each barrier kind (coarsen / initial / uncoarsen)
and resumed must produce a gate-valid partition with a cut within
tolerance of the uninterrupted run, without re-running completed
coarsening levels.  The deadline suite asserts `time_budget` yields a
gate-valid partition annotated ``anytime: true``, and the fault-site
tests cover the `checkpoint-write` / `checkpoint-load` degradations.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from kaminpar_tpu import resilience, telemetry
from kaminpar_tpu.graphs.factories import make_rgg2d
from kaminpar_tpu.kaminpar import KaMinPar
from kaminpar_tpu.presets import create_context_by_preset_name
from kaminpar_tpu.resilience import checkpoint as ckpt_mod
from kaminpar_tpu.resilience import deadline as deadline_mod
from kaminpar_tpu.resilience.checkpoint import SimulatedPreemption

N, K, CONTRACTION_LIMIT = 1500, 4, 50
CUT_TOLERANCE = 0.15


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(ckpt_mod.STOP_AT_ENV, raising=False)
    monkeypatch.delenv(resilience.FAULTS_ENV_VAR, raising=False)
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    yield
    resilience.reset()
    telemetry.disable()
    telemetry.reset()


def _graph():
    return make_rgg2d(N, avg_degree=8, seed=3)


def _run(ckpt_dir=None, resume=False, stop_at=None, seed=1, budget=None,
         grace=None):
    """One deep pipeline run; returns (solver, graph, partition, metrics)."""
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    if stop_at is not None:
        os.environ[ckpt_mod.STOP_AT_ENV] = stop_at
    else:
        os.environ.pop(ckpt_mod.STOP_AT_ENV, None)
    ctx = create_context_by_preset_name("default")
    ctx.coarsening.contraction_limit = CONTRACTION_LIMIT
    if ckpt_dir is not None:
        ctx.resilience.checkpoint_dir = str(ckpt_dir)
        ctx.resilience.resume = resume
    if budget is not None:
        ctx.resilience.time_budget = budget
    if grace is not None:
        ctx.resilience.budget_grace = grace
    g = _graph()
    solver = KaMinPar(ctx)
    solver.set_output_level(0)
    solver.set_graph(g)
    part = solver.compute_partition(k=K, epsilon=0.03, seed=seed)
    os.environ.pop(ckpt_mod.STOP_AT_ENV, None)
    return solver, g, part, solver.result_metrics(g, part)


def _gate_valid():
    gates = telemetry.events("output-gate")
    assert gates, "no output-gate event"
    return gates[-1].attrs["valid"]


@pytest.fixture(scope="module")
def baseline_metrics():
    """The uninterrupted run's metrics (one run shared by the module)."""
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    try:
        _, _, _, m = _run()
        return m
    finally:
        resilience.reset()
        telemetry.disable()
        telemetry.reset()


# ---------------------------------------------------------------------------
# io/snapshot: atomicity + checksums
# ---------------------------------------------------------------------------


def test_snapshot_roundtrip_and_checksum(tmp_path):
    from kaminpar_tpu.io.snapshot import (
        SnapshotError, read_snapshot, write_snapshot,
    )

    path = str(tmp_path / "snap.npz")
    arrays = {"a": np.arange(10, dtype=np.int64), "b": np.ones(3)}
    nbytes, sha = write_snapshot(path, arrays)
    assert nbytes == os.path.getsize(path)
    back = read_snapshot(path, sha)
    np.testing.assert_array_equal(back["a"], arrays["a"])
    # no stray temp files (atomic protocol)
    assert [f for f in os.listdir(tmp_path) if f.endswith(".tmp")] == []
    # truncation must surface as a structured checksum error
    with open(path, "r+b") as f:
        f.truncate(nbytes // 2)
    with pytest.raises(SnapshotError):
        read_snapshot(path, sha)


# ---------------------------------------------------------------------------
# kill-and-resume equivalence at every barrier kind
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "stop_at", ["coarsen:1!", "initial!", "uncoarsen:1!"],
    ids=["coarsen", "initial", "uncoarsen"],
)
def test_kill_and_resume_equivalence(tmp_path, baseline_metrics, stop_at):
    d = tmp_path / "ckpt"
    with pytest.raises(SimulatedPreemption):
        _run(ckpt_dir=d, stop_at=stop_at)
    manifest = json.load(open(d / "manifest.json"))
    want_stage = stop_at.rstrip("!").split(":")[0]
    assert manifest["stage"] == want_stage

    completed_levels = sum(
        1 for name in manifest["snapshots"] if name.startswith("level-")
    )
    _, _, part, m = _run(ckpt_dir=d, resume=True)
    assert _gate_valid()
    assert m["feasible"]
    base = baseline_metrics["cut"]
    assert abs(m["cut"] - base) <= max(2, CUT_TOLERANCE * base), (
        f"resumed cut {m['cut']} vs baseline {base}"
    )
    # no completed coarsening level re-ran: the resumed run's
    # coarsening-level events start past the restored hierarchy
    rerun_levels = [
        e.attrs["level"] for e in telemetry.events("coarsening-level")
    ]
    assert all(lvl > completed_levels for lvl in rerun_levels), (
        f"levels {rerun_levels} re-ran below restored depth "
        f"{completed_levels}"
    )
    # the report records where the run resumed from
    summary = telemetry.run_info()["checkpoint"]
    assert summary["resumed_from"] is not None


def test_graceful_preemption_winds_down_to_valid_result(tmp_path):
    """The SIGTERM path (driven via the STOP_AT soft hook): the run
    finishes early, passes the gate, annotates anytime, and leaves a
    final `result` checkpoint that a --resume returns instantly."""
    d = tmp_path / "ckpt"
    solver, g, part, m = _run(ckpt_dir=d, stop_at="coarsen:1")
    assert _gate_valid()
    assert m["feasible"]
    assert solver.last_anytime and solver.last_anytime["anytime"]
    assert solver.last_anytime["reason"].startswith("stop-at")
    manifest = json.load(open(d / "manifest.json"))
    assert manifest["stage"] == "result"
    # resume: the result snapshot comes back without re-partitioning
    _, _, part2, m2 = _run(ckpt_dir=d, resume=True)
    np.testing.assert_array_equal(part, part2)
    assert telemetry.run_info()["checkpoint"]["resumed_from"] == "result"


def test_checkpoint_mismatch_degrades_to_clean_restart(tmp_path):
    d = tmp_path / "ckpt"
    with pytest.raises(SimulatedPreemption):
        _run(ckpt_dir=d, stop_at="uncoarsen:1!", seed=1)
    # different seed => different ctx fingerprint => clean restart
    _, _, _, m = _run(ckpt_dir=d, resume=True, seed=2)
    assert _gate_valid() and m["feasible"]
    actions = [
        e.attrs.get("action") for e in telemetry.events("checkpoint")
    ]
    assert "clean-restart" in actions


def test_resume_on_empty_dir_is_fresh_start(tmp_path, baseline_metrics):
    _, _, _, m = _run(ckpt_dir=tmp_path / "empty", resume=True)
    assert _gate_valid()
    assert m["cut"] == baseline_metrics["cut"]  # plain deterministic run


# ---------------------------------------------------------------------------
# fault sites: checkpoint-write / checkpoint-load
# ---------------------------------------------------------------------------


def test_checkpoint_write_fault_degrades_to_memory_only(tmp_path, monkeypatch):
    monkeypatch.setenv(resilience.FAULTS_ENV_VAR, "checkpoint-write:nth=1")
    d = tmp_path / "ckpt"
    _, _, _, m = _run(ckpt_dir=d)
    assert _gate_valid() and m["feasible"]
    degraded = [e.attrs["site"] for e in telemetry.events("degraded")]
    assert "checkpoint-write" in degraded
    summary = telemetry.run_info()["checkpoint"]
    assert summary["memory_only"] is True


def test_corrupted_snapshot_falls_back_to_previous_generation(
    tmp_path, baseline_metrics
):
    d = tmp_path / "ckpt"
    with pytest.raises(SimulatedPreemption):
        _run(ckpt_dir=d, stop_at="uncoarsen:1!")
    manifest = json.load(open(d / "manifest.json"))
    state_file = manifest["snapshots"]["state"]["file"]
    with open(d / state_file, "r+b") as f:
        f.truncate(64)  # truncated snapshot: checksum must fail
    _, _, _, m = _run(ckpt_dir=d, resume=True)
    assert _gate_valid() and m["feasible"]
    degraded = [e.attrs["site"] for e in telemetry.events("degraded")]
    assert "checkpoint-load" in degraded
    base = baseline_metrics["cut"]
    assert abs(m["cut"] - base) <= max(2, CUT_TOLERANCE * base)


def test_unusable_checkpoint_dir_degrades_with_warning(baseline_metrics):
    _, _, _, m = _run(ckpt_dir="/proc/kaminpar/definitely/not/writable")
    assert _gate_valid() and m["feasible"]
    summary = telemetry.run_info()["checkpoint"]
    assert summary["enabled"] is False
    events = [
        e.attrs.get("action") for e in telemetry.events("checkpoint")
    ]
    assert "dir-unusable" in events


# ---------------------------------------------------------------------------
# deadline budget / anytime contract
# ---------------------------------------------------------------------------


def test_time_budget_returns_gate_valid_anytime_partition():
    solver, g, part, m = _run(budget=1e-3, grace=120.0)
    assert _gate_valid()
    assert m["feasible"]
    assert part.shape == (N,)
    assert (part >= 0).all() and (part < K).all()
    anytime = solver.last_anytime
    assert anytime and anytime["anytime"] and anytime["reason"] == "budget"
    assert anytime["budget_s"] == pytest.approx(1e-3)
    assert anytime["grace_s"] == pytest.approx(120.0)
    assert anytime["elapsed_s"] >= 0


def test_generous_budget_never_triggers_anytime():
    solver, _, _, m = _run(budget=3600.0)
    assert solver.last_anytime is None
    assert m["feasible"]


def test_deadline_unit_budget_and_stop_request():
    deadline_mod.install_budget(1e-4, grace_s=5.0)
    import time as time_mod

    time_mod.sleep(0.01)
    assert deadline_mod.should_stop()
    assert deadline_mod.triggered()
    st = deadline_mod.state()
    assert st["anytime"] and st["reason"] == "budget"
    deadline_mod.clear()
    assert not deadline_mod.should_stop()
    deadline_mod.request_stop("sigterm")
    assert deadline_mod.should_stop()
    assert deadline_mod.state()["reason"] == "sigterm"
    deadline_mod.clear()


def test_barrier_is_noop_without_manager():
    assert ckpt_mod.active() is None
    assert ckpt_mod.barrier("coarsen", level=1, scheme="deep") is True
    assert telemetry.events("checkpoint") == []


# ---------------------------------------------------------------------------
# SIGINT bugfix: open timer scopes are closed, emergency report validates
# ---------------------------------------------------------------------------


def test_interrupt_unwind_closes_open_timer_scopes():
    from kaminpar_tpu.utils import timer

    t = timer.Timer()
    s1 = t.scope("partitioning")
    s2 = t.scope("coarsening")
    s1.__enter__()
    s2.__enter__()  # simulate SIGINT deep inside a jitted loop
    assert not t.idle()
    closed = t.unwind()
    assert closed == 2
    assert t.idle()
    tree = t.root.children
    assert "partitioning" in tree
    assert "coarsening" in tree["partitioning"].children
    assert tree["partitioning"].count == 1


def test_cli_keyboard_interrupt_writes_schema_valid_report(
    tmp_path, monkeypatch
):
    """A forced interrupt surfacing from inside the pipeline must yield
    exit 130 and a schema-valid emergency run report with the
    interrupted spans closed."""
    from kaminpar_tpu import cli
    from kaminpar_tpu.utils import timer

    def fake_compute(self, **kwargs):
        # leave scopes open, as a KeyboardInterrupt surfacing from a
        # jitted while_loop does
        timer.GLOBAL_TIMER.reset()
        cm1 = timer.GLOBAL_TIMER.scope("partitioning")
        cm2 = timer.GLOBAL_TIMER.scope("coarsening")
        cm1.__enter__()
        cm2.__enter__()
        raise KeyboardInterrupt

    monkeypatch.setattr(KaMinPar, "compute_partition", fake_compute)
    report = tmp_path / "emergency.json"
    rc = cli.main([
        "gen:grid2d;rows=8;cols=8", "-k", "2", "-q",
        "--report-json", str(report),
    ])
    deadline_mod.uninstall_signal_handlers()
    assert rc == 130
    assert report.exists()
    r = json.load(open(report))
    assert r["anytime"]["anytime"] is True
    assert r["run"]["interrupted"] is True
    # the open scopes were force-closed into the tree
    assert "partitioning" in r["scope_tree"]
    # and the artifact validates against the checked-in schema
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts",
                                      "check_report_schema.py"),
         str(report)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# fingerprints / manifest units
# ---------------------------------------------------------------------------


def test_graph_fingerprint_distinguishes_graphs():
    g1 = make_rgg2d(400, avg_degree=8, seed=3)
    g2 = make_rgg2d(400, avg_degree=8, seed=4)
    assert ckpt_mod.graph_fingerprint(g1) == ckpt_mod.graph_fingerprint(g1)
    assert ckpt_mod.graph_fingerprint(g1) != ckpt_mod.graph_fingerprint(g2)


def test_ctx_fingerprint_ignores_resilience_knobs():
    c1 = create_context_by_preset_name("default")
    c2 = create_context_by_preset_name("default")
    c2.resilience.checkpoint_dir = "/somewhere"
    c2.resilience.resume = True
    c2.resilience.time_budget = 5.0
    assert ckpt_mod.ctx_fingerprint(c1) == ckpt_mod.ctx_fingerprint(c2)
    c2.seed = 99
    assert ckpt_mod.ctx_fingerprint(c1) != ckpt_mod.ctx_fingerprint(c2)
