"""Lane-routed gather plan + Pallas kernel (ops/lane_gather.py).

On CPU the kernel runs in interpreter mode; the on-device Mosaic
lowering is probed separately by lane_gather_supported() and measured
by scripts/microbench_gather.py.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from kaminpar_tpu.ops.lane_gather import (
    L,
    build_gather_plan,
    lane_gather,
    route_codata,
)


def _check_plan(idx, table_len, chunk_rows=None):
    kwargs = {} if chunk_rows is None else {"chunk_rows": chunk_rows}
    plan = build_gather_plan(jnp.asarray(idx), table_len, **kwargs)
    rng = np.random.RandomState(7)
    table = rng.randint(0, 1 << 30, table_len).astype(np.int32)
    got = np.asarray(lane_gather(jnp.asarray(table), plan, interpret=True))
    inv = np.asarray(plan.inv)

    # every original position is served by exactly one routed slot
    served = inv[inv >= 0]
    assert sorted(served.tolist()) == list(range(len(idx)))
    # routed slots carry the right table values
    ok = inv >= 0
    np.testing.assert_array_equal(got[ok], table[idx[inv[ok]]])
    return plan, got


def test_single_chunk_small():
    rng = np.random.RandomState(0)
    idx = rng.randint(0, 1024, 300).astype(np.int32)
    plan, _ = _check_plan(idx, 1024)
    assert plan.C == 1
    assert plan.H % plan.S == 0


def test_multi_chunk():
    rng = np.random.RandomState(1)
    idx = rng.randint(0, 64 * L, 1000).astype(np.int32)
    plan, _ = _check_plan(idx, 64 * L, chunk_rows=16)
    assert plan.C == 4


def test_skewed_lanes():
    # all indices hit the same lane — worst-case padding, still correct
    idx = (np.arange(200, dtype=np.int32) % 5) * L + 3
    plan, _ = _check_plan(idx, 8 * L)
    assert plan.H * L >= 200


def test_duplicate_and_boundary_indices():
    idx = np.array([0, 0, 1023, 1023, 512, 0], dtype=np.int32)
    _check_plan(idx, 1024)


def test_route_codata_alignment():
    rng = np.random.RandomState(3)
    table_len = 16 * L
    m = 500
    idx = rng.randint(0, table_len, m).astype(np.int32)
    co = rng.randint(0, 1 << 20, m).astype(np.int32)
    plan = build_gather_plan(jnp.asarray(idx), table_len)
    co_r = np.asarray(route_codata(plan, jnp.asarray(co), -7))
    inv = np.asarray(plan.inv)
    ok = inv >= 0
    np.testing.assert_array_equal(co_r[ok], co[inv[ok]])
    assert (co_r[~ok] == -7).all()


def test_plan_rejects_unaligned_table():
    with pytest.raises(ValueError):
        build_gather_plan(jnp.zeros(4, jnp.int32), 100)


def test_untouched_chunks_get_no_tiles():
    # indices confined to one of 4 chunks: the plan must not stream the
    # other 3 table chunks at all
    rng = np.random.RandomState(5)
    chunk_rows = 16
    idx = (chunk_rows * L + rng.randint(0, chunk_rows * L, 200)).astype(
        np.int32
    )  # all in chunk 1
    plan, _ = _check_plan(idx, 64 * L, chunk_rows=chunk_rows)
    assert plan.C == 4
    assert set(np.asarray(plan.tile_chunk).tolist()) == {1}


def test_empty_plan_is_valid():
    plan = build_gather_plan(jnp.zeros(0, jnp.int32), 1024)
    rng = np.random.RandomState(0)
    table = jnp.asarray(rng.randint(0, 100, 1024).astype(np.int32))
    out = np.asarray(lane_gather(table, plan, interpret=True))
    assert (np.asarray(plan.inv) == -1).all()
    assert out.shape[0] == plan.num_slots


def test_plan_rejects_out_of_range_indices():
    with pytest.raises(ValueError):
        build_gather_plan(jnp.array([-1, 5], jnp.int32), 1024)
    with pytest.raises(ValueError):
        build_gather_plan(
            jnp.array([5, 64 * L * 2], jnp.int32), 64 * L, chunk_rows=64
        )


# ---------------------------------------------------------------------------
# plan-blowup cap (ADVICE round 5 medium): hub-skewed index arrays must
# fall back to the XLA gather instead of pinning an inflated plan
# ---------------------------------------------------------------------------


def _skewed_graph(n_pad=1024, m=512):
    """Every edge targets node 0: one lane soaks all m indices, so the
    routed height is ~m rows and num_slots ~ m * 128 >> 2 * m."""
    import jax.numpy as jnp

    class G:
        pass

    g = G()
    g.n_pad = n_pad
    g.dst = jnp.zeros(m, dtype=jnp.int32)
    g.src = jnp.zeros(m, dtype=jnp.int32)
    g.edge_w = jnp.ones(m, dtype=jnp.int32)
    return g


def test_plan_within_cap_predicate():
    from kaminpar_tpu.ops import lane_gather as lg

    uniform = build_gather_plan(
        jnp.arange(1024, dtype=jnp.int32) % (8 * L), 8 * L
    )
    assert lg.plan_within_cap(uniform, 1024)
    skewed = build_gather_plan(jnp.zeros(512, jnp.int32), 8 * L)
    assert not lg.plan_within_cap(skewed, 512)


def test_edge_plans_discards_blown_up_plan_and_emits_event():
    from kaminpar_tpu import telemetry
    from kaminpar_tpu.ops import lane_gather as lg

    telemetry.enable()
    telemetry.reset()
    lg.clear_plan_cache()
    try:
        g = _skewed_graph()
        assert lg.edge_plans(g) is None
        events = telemetry.events("lane-gather-plan")
        assert len(events) == 1
        attrs = events[0].attrs
        assert attrs["capped"] is True
        assert attrs["m"] == 512
        assert attrs["num_slots"] > 2 * 512
        assert attrs["pad_overhead"] == pytest.approx(
            attrs["num_slots"] / 512, rel=1e-3
        )
        # the verdict is cached: a second call rebuilds nothing
        assert lg.edge_plans(g) is None
        assert len(telemetry.events("lane-gather-plan")) == 1
    finally:
        lg.clear_plan_cache()
        telemetry.reset()
        telemetry.disable()


def test_edge_plans_keeps_affordable_plan_and_reports_overhead():
    from kaminpar_tpu import telemetry
    from kaminpar_tpu.ops import lane_gather as lg

    telemetry.enable()
    telemetry.reset()
    lg.clear_plan_cache()
    try:
        import jax.numpy as jnp

        class G:
            pass

        g = G()
        g.n_pad = 8 * L
        m = 8 * L * 4
        g.dst = jnp.arange(m, dtype=jnp.int32) % (8 * L)  # uniform
        g.src = jnp.zeros(m, dtype=jnp.int32)
        g.edge_w = jnp.ones(m, dtype=jnp.int32)
        plans = lg.edge_plans(g)
        assert plans is not None
        (ev,) = telemetry.events("lane-gather-plan")
        assert ev.attrs["capped"] is False
        assert ev.attrs["num_slots"] <= 2 * m
    finally:
        lg.clear_plan_cache()
        telemetry.reset()
        telemetry.disable()
