"""Hierarchy dump tests (kaminpar-shm/partitioning/debug.cc analog)."""

import glob
import os

import numpy as np

from kaminpar_tpu.cli import main
from kaminpar_tpu.io import load_graph

RGG = "/root/reference/misc/rgg2d.metis"


def test_debug_dumps_write_hierarchy_files(tmp_path):
    rc = main(
        [
            RGG, "-k", "4", "-q",
            # rgg2d is below the default contraction limit (no levels);
            # force a real hierarchy so the per-level dumps exist
            "--contraction-limit", "64",
            "--debug-dump", "toplevel-graph", "toplevel-partition",
            "coarsest-graph", "coarsest-partition", "graph-hierarchy",
            "partition-hierarchy",
            "--debug-dump-dir", str(tmp_path),
        ]
    )
    assert rc == 0

    # toplevel graph round-trips through the METIS writer
    top = load_graph(str(tmp_path / "rgg2d.toplevel.metis"))
    orig = load_graph(RGG)
    assert top.n == orig.n and top.m == orig.m

    # toplevel partition matches the input size and k
    part = np.loadtxt(tmp_path / "rgg2d.toplevel.part", dtype=np.int64)
    assert part.shape == (orig.n,)
    assert set(np.unique(part)) <= set(range(4))

    # coarsest artifacts and at least one per-level artifact exist
    assert (tmp_path / "rgg2d.coarsest.metis").exists()
    assert (tmp_path / "rgg2d.coarsest.part").exists()
    coarsest = load_graph(str(tmp_path / "rgg2d.coarsest.metis"))
    cpart = np.loadtxt(tmp_path / "rgg2d.coarsest.part", dtype=np.int64)
    assert cpart.shape == (coarsest.n,)
    assert glob.glob(os.path.join(tmp_path, "rgg2d.level*.metis"))
    assert glob.glob(os.path.join(tmp_path, "rgg2d.level*.part"))
