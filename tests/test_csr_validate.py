"""csr.validate(): structural invariant checker (ISSUE 3 satellite).

Covers host and device graphs, the dtype/padding policy, and the
KAMINPAR_TPU_ASSERTS=1 gating used by the output gate.
"""

import dataclasses

import numpy as np
import pytest

from kaminpar_tpu.graphs import csr
from kaminpar_tpu.graphs.csr import CSRInvariantError, device_graph_from_host
from kaminpar_tpu.graphs.factories import make_grid_graph
from kaminpar_tpu.graphs.host import HostGraph


def _host():
    return make_grid_graph(4, 4)


def test_valid_host_graph_passes():
    csr.validate(_host())


def test_valid_device_graph_passes():
    csr.validate(device_graph_from_host(_host()))


def test_valid_compressed_graph_passes():
    from kaminpar_tpu.graphs.compressed import compress_host_graph

    csr.validate(compress_host_graph(_host()))


def test_ragged_offsets_rejected():
    g = _host()
    xadj = g.xadj.copy()
    xadj[2] = xadj[3] + 5  # non-monotone
    bad = HostGraph(xadj=xadj, adjncy=g.adjncy,
                    node_weights=None, edge_weights=None)
    with pytest.raises(CSRInvariantError, match="non-decreasing"):
        csr.validate(bad)


def test_offset_start_and_end_rejected():
    g = _host()
    xadj = g.xadj.copy()
    xadj[0] = 1
    bad = HostGraph(xadj=xadj, adjncy=g.adjncy,
                    node_weights=None, edge_weights=None)
    with pytest.raises(CSRInvariantError, match="start at 0"):
        csr.validate(bad)
    xadj = g.xadj.copy()
    xadj[-1] -= 1
    bad = HostGraph(xadj=xadj, adjncy=g.adjncy,
                    node_weights=None, edge_weights=None)
    with pytest.raises(CSRInvariantError):
        csr.validate(bad)


def test_out_of_range_neighbor_rejected():
    g = _host()
    adj = g.adjncy.copy()
    adj[0] = g.n + 3
    bad = HostGraph(xadj=g.xadj, adjncy=adj,
                    node_weights=None, edge_weights=None)
    with pytest.raises(CSRInvariantError, match="out of"):
        csr.validate(bad)


def test_asymmetry_rejected():
    g = _host()
    adj = g.adjncy.copy()
    # retarget one directed edge; its reverse twin is now missing
    adj[0] = (adj[0] + 2) % g.n
    bad = HostGraph(xadj=g.xadj, adjncy=adj,
                    node_weights=None, edge_weights=None)
    with pytest.raises(CSRInvariantError, match="symmetry"):
        csr.validate(bad)
    csr.validate(bad, undirected=False)  # directed view is fine


def test_dtype_policy_rejected():
    # the HostGraph constructor coerces dtypes, so a policy violation
    # only arises from post-construction mutation (or a foreign object)
    bad = _host()
    bad.adjncy = bad.adjncy.astype(np.int64)
    with pytest.raises(CSRInvariantError, match="dtype"):
        csr.validate(bad)


def test_device_padding_violations_rejected():
    import jax.numpy as jnp

    dg = device_graph_from_host(_host())
    # corrupt a pad edge: point it at a real node with nonzero weight
    m = int(dg.m)
    bad = dataclasses.replace(
        dg, edge_w=dg.edge_w.at[dg.m_pad - 1].set(7)
    )
    with pytest.raises(CSRInvariantError, match="pad edges"):
        csr.validate(bad)
    bad = dataclasses.replace(
        dg, node_w=dg.node_w.at[dg.n_pad - 1].set(1)
    )
    with pytest.raises(CSRInvariantError, match="pad nodes"):
        csr.validate(bad)
    bad = dataclasses.replace(dg, dst=dg.dst.at[m].set(0))
    with pytest.raises(CSRInvariantError, match="parked"):
        csr.validate(bad)
    del jnp


def test_maybe_validate_gated_by_env(monkeypatch):
    g = _host()
    adj = g.adjncy.copy()
    adj[0] = g.n + 3
    bad = HostGraph(xadj=g.xadj, adjncy=adj,
                    node_weights=None, edge_weights=None)
    monkeypatch.delenv(csr.ASSERTS_ENV, raising=False)
    csr.maybe_validate(bad)  # gate closed: free, no exception
    monkeypatch.setenv(csr.ASSERTS_ENV, "1")
    assert csr.asserts_enabled()
    with pytest.raises(CSRInvariantError, match="at upload"):
        csr.maybe_validate(bad, where="upload")
