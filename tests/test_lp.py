"""LP clustering kernel tests (analog of the reference's lp_clusterer
coverage via cluster_contraction_test + e2e)."""

import jax.numpy as jnp
import numpy as np
import pytest

from kaminpar_tpu.graphs import device_graph_from_host, factories, from_edge_list
from kaminpar_tpu.ops.lp import LPConfig, lp_cluster, lp_refine


def _labels(graph, cap, seed=42, **kw):
    dg = device_graph_from_host(graph)
    return dg, np.asarray(lp_cluster(dg, jnp.int32(cap), jnp.int32(seed), **kw))


def test_disjoint_triangles_merge():
    g = from_edge_list(6, np.array([[0, 1], [1, 2], [0, 2], [3, 4], [4, 5], [3, 5]]))
    _, l = _labels(g, 100)
    l = l[:6]
    assert len(set(l[:3])) == 1
    assert len(set(l[3:6])) == 1
    assert l[0] != l[3]


def test_weight_cap_respected():
    g = factories.make_path(16)
    _, l = _labels(g, 3)
    sizes = np.bincount(l[:16])
    assert sizes.max() <= 3


def test_weighted_nodes_cap():
    g = factories.make_path(6)
    g.node_weights = np.array([5, 1, 1, 1, 1, 5], dtype=np.int64)
    dg = device_graph_from_host(g)
    l = np.asarray(lp_cluster(dg, jnp.int32(6), jnp.int32(1)))[:6]
    w = np.zeros(6, dtype=np.int64)
    np.add.at(w, l, np.asarray(g.node_weights))
    assert w.max() <= 6


def test_isolated_nodes_clustered():
    g = factories.make_empty_graph(12)
    _, l = _labels(g, 4)
    sizes = np.bincount(l[:12], minlength=12)
    assert sizes.max() <= 4
    assert (sizes > 0).sum() == 3  # 12 unit nodes / cap 4


def test_star_cap():
    g = factories.make_star(9)
    _, l = _labels(g, 3)
    sizes = np.bincount(l[:10], minlength=10)
    assert sizes.max() <= 3


def test_determinism():
    g = factories.make_rgg2d(400, seed=7)
    _, l1 = _labels(g, 20, seed=5)
    _, l2 = _labels(g, 20, seed=5)
    assert np.array_equal(l1, l2)


def test_community_restriction():
    # two triangles bridged by an edge; communities forbid merging across
    g = from_edge_list(
        6, np.array([[0, 1], [1, 2], [0, 2], [3, 4], [4, 5], [3, 5], [2, 3]])
    )
    dg = device_graph_from_host(g)
    comm = np.zeros(dg.n_pad, dtype=np.int32)
    comm[3:6] = 1
    l = np.asarray(
        lp_cluster(dg, jnp.int32(100), jnp.int32(1), communities=jnp.asarray(comm))
    )[:6]
    # no cluster spans both communities
    for c in set(l):
        members = np.flatnonzero(l == c)
        assert len(set(comm[members])) == 1


def test_lp_refine_improves_cut():
    from kaminpar_tpu.ops import metrics

    g = factories.make_grid_graph(8, 8)
    dg = device_graph_from_host(g)
    rng = np.random.default_rng(0)
    part = np.zeros(dg.n_pad, dtype=np.int32)
    part[:64] = rng.integers(0, 2, 64)
    part_j = jnp.asarray(part)
    cut_before = int(metrics.edge_cut(dg, part_j))
    refined = lp_refine(
        dg, part_j, 2, jnp.array([40, 40], dtype=jnp.int32), jnp.int32(3)
    )
    cut_after = int(metrics.edge_cut(dg, refined))
    assert cut_after < cut_before
    bw = np.bincount(np.asarray(refined)[:64], minlength=2,
                     weights=np.ones(64)).astype(int)
    assert bw.max() <= 40


def test_hashed_rating_table_winner_sums_are_exact():
    """Every slot's winner label gets the exact total connection weight
    (all edges with one label hash to one slot), and with enough slots the
    table enumerates every adjacent cluster."""
    from kaminpar_tpu.ops.segments import hashed_rating_table

    g = factories.make_rmat(64, 512, seed=9)
    dg = device_graph_from_host(g)
    rng = np.random.default_rng(4)
    labels = np.zeros(dg.n_pad, np.int32)
    labels[: g.n] = rng.integers(0, g.n, g.n)
    labels[g.n :] = np.arange(g.n, dg.n_pad)
    lab_j = jnp.asarray(labels)
    neighbor = lab_j[dg.dst]
    slot_label, slot_w = (
        np.asarray(x)
        for x in hashed_rating_table(
            dg.src, neighbor, dg.edge_w, dg.n_pad, 128, 17
        )
    )
    # brute-force per-(node, label) sums
    src = np.asarray(dg.src)
    ew = np.asarray(dg.edge_w)
    nb = np.asarray(neighbor)
    ref = {}
    for s, l, w in zip(src, nb, ew):
        if w:
            ref[(int(s), int(l))] = ref.get((int(s), int(l)), 0) + int(w)
    for u in range(g.n):
        row_lab = slot_label[u]
        row_w = slot_w[u]
        for lab, w in zip(row_lab, row_w):
            if lab >= 0 and w > 0:
                assert ref[(u, int(lab))] == int(w), (u, lab)


def test_lp_cluster_hash_engine_quality_and_caps():
    """The hashed engine must produce a valid, cap-respecting clustering
    of comparable quality to the exact sort engine."""
    g = factories.make_rmat(512, 4096, seed=11)
    dg = device_graph_from_host(g)
    cap = 40
    stats = {}
    for name in ("sort", "hash"):
        lab = np.asarray(
            lp_cluster(
                dg, jnp.int32(cap), jnp.int32(5), LPConfig(rating=name)
            )
        )[: g.n]
        w = np.zeros(dg.n_pad, np.int64)
        np.add.at(w, lab, g.node_weight_array())
        assert w.max() <= cap, name
        stats[name] = len(np.unique(lab))
    # both engines coarsen; hash within 2x of sort's cluster count
    assert stats["hash"] <= max(2 * stats["sort"], stats["sort"] + 64)


def test_lp_refine_dense_engine_matches_expected_semantics():
    """Refinement (k blocks) auto-selects the dense engine; behavior must
    stay cap-respecting and improving, like test_lp_refine_improves_cut."""
    from kaminpar_tpu.ops import metrics

    g = factories.make_grid_graph(16, 16)
    dg = device_graph_from_host(g)
    rng = np.random.default_rng(2)
    part = np.zeros(dg.n_pad, dtype=np.int32)
    part[: g.n] = rng.integers(0, 4, g.n)
    part_j = jnp.asarray(part)
    cut_before = int(metrics.edge_cut(dg, part_j))
    caps = jnp.full((4,), 70, jnp.int32)
    refined = lp_refine(dg, part_j, 4, caps, jnp.int32(3))
    cut_after = int(metrics.edge_cut(dg, refined))
    assert cut_after < cut_before
    bw = np.bincount(np.asarray(refined)[: g.n], minlength=4)
    assert bw.max() <= 70


def test_rating_top3_by_sort_matches_bruteforce():
    from kaminpar_tpu.ops.segments import INT32_MIN, rating_top3_by_sort

    g = factories.make_rmat(256, 2048, seed=13)
    dg = device_graph_from_host(g)
    rng = np.random.default_rng(7)
    labels = np.arange(dg.n_pad, dtype=np.int32)
    labels[: g.n] = rng.integers(0, g.n, g.n)
    nb = jnp.asarray(labels)[dg.dst]
    out = [np.asarray(x) for x in rating_top3_by_sort(dg, nb, 23)]
    l1, v1, l2, v2, l3, v3 = out
    src, dst, ew = (
        np.asarray(dg.src),
        np.asarray(dg.dst),
        np.asarray(dg.edge_w),
    )
    for u in range(g.n):
        sums = {}
        for s, d, w in zip(src, dst, ew):
            if s == u and w:
                lab = labels[d]
                sums[lab] = sums.get(lab, 0) + int(w)
        ranked = sorted(sums.items(), key=lambda kv: -kv[1])
        got = [(l1[u], v1[u]), (l2[u], v2[u]), (l3[u], v3[u])]
        for j in range(min(3, len(ranked))):
            # labels may differ on exact weight ties; weights must match
            assert got[j][1] == ranked[j][1], (u, j)
            assert sums[got[j][0]] == ranked[j][1], (u, j)
        for j in range(len(ranked), 3):
            assert got[j][0] == -1 and got[j][1] == INT32_MIN


def test_lp_cluster_sort2_engine_quality_and_caps():
    g = factories.make_rmat(512, 4096, seed=11)
    dg = device_graph_from_host(g)
    cap = 40
    lab = np.asarray(
        lp_cluster(dg, jnp.int32(cap), jnp.int32(5), LPConfig(rating="sort2"))
    )[: g.n]
    w = np.zeros(dg.n_pad, np.int64)
    np.add.at(w, lab, g.node_weight_array())
    assert w.max() <= cap
    assert len(np.unique(lab)) < g.n // 2  # actually coarsens


def test_sort2_engine_enforces_communities():
    """sort2 gained the v-cycle community restriction (a node-level check
    on the top-K candidates): no cluster may span two communities."""
    g = factories.make_grid_graph(8, 8)
    dg = device_graph_from_host(g)
    comm_np = (np.arange(dg.n_pad) % 2).astype(np.int32)
    labels = np.asarray(
        lp_cluster(
            dg, jnp.int32(16), jnp.int32(0), LPConfig(rating="sort2"),
            communities=jnp.asarray(comm_np),
        )
    )[: g.n]
    # every node's cluster leader shares its community
    assert (comm_np[labels] == comm_np[: g.n]).all()


def test_lp_refine_never_increases_cut():
    """Regression (the afterburner bug): bulk-synchronous LP refinement
    used to DOUBLE the cut via simultaneous adjacent moves.  On dense
    random graphs the refined cut must never exceed the input cut."""
    from kaminpar_tpu.ops import metrics

    for seed in (0, 1, 2):
        g = factories.make_rmat(2048, 16384, seed=seed)
        dg = device_graph_from_host(g)
        rng = np.random.default_rng(seed)
        k = 8
        part = np.zeros(dg.n_pad, np.int32)
        part[: g.n] = rng.integers(0, k, g.n)
        part_j = jnp.asarray(part)
        nw = g.node_weight_array()
        caps = jnp.full((k,), int(np.ceil(nw.sum() / k * 1.1)), jnp.int32)
        cut0 = int(metrics.edge_cut(dg, part_j))
        out = lp_refine(dg, part_j, k, caps, jnp.int32(seed + 7))
        cut1 = int(metrics.edge_cut(dg, out))
        assert cut1 <= cut0, (seed, cut0, cut1)


def test_delta_rounds_match_full_rounds(monkeypatch):
    """Delta rounds (active rows compacted into the m_pad/4 buffer) must
    make bitwise-identical decisions to full rounds: per-row rating sees
    the same groups/totals/tie-hashes, and inactive nodes cannot move
    either way.  Force the delta threshold down and compare end-to-end
    clustering and refinement outputs against the unpatched paths."""
    import kaminpar_tpu.ops.lp as lp_mod

    g = factories.make_rmat(1 << 11, 20_000, seed=13)
    dg = device_graph_from_host(g)
    mcw = jnp.int32(max(1, int(g.node_weight_array().sum() // 16)))

    full_labels = np.asarray(lp_cluster(dg, mcw, jnp.int32(5)))

    k = 8
    rng = np.random.default_rng(3)
    part = np.zeros(dg.n_pad, np.int32)
    part[: g.n] = rng.integers(0, k, g.n)
    caps = jnp.full(
        (k,), int(np.ceil(g.node_weight_array().sum() / k * 1.1)), jnp.int32
    )
    full_part = np.asarray(lp_refine(dg, jnp.asarray(part), k, caps, jnp.int32(2)))

    monkeypatch.setattr(lp_mod, "DELTA_MIN_EDGE_SLOTS", 1)
    lp_mod._lp_cluster_impl.clear_cache()
    lp_mod._lp_refine_fused.clear_cache()
    try:
        delta_labels = np.asarray(lp_cluster(dg, mcw, jnp.int32(5)))
        delta_part = np.asarray(
            lp_refine(dg, jnp.asarray(part), k, caps, jnp.int32(2))
        )
    finally:
        lp_mod._lp_cluster_impl.clear_cache()
        lp_mod._lp_refine_fused.clear_cache()

    np.testing.assert_array_equal(delta_labels, full_labels)
    np.testing.assert_array_equal(delta_part, full_part)


def test_chunked_cluster_launches_match_fused(monkeypatch):
    """Above MAX_FUSED_EDGE_SLOTS, LP clustering runs one round per
    launch (the TPU-worker watchdog guard the refiners already had; a
    fused multi-round clustering loop at 128M-slot shapes reproducibly
    killed the worker).  All-integer state means the chunked path must
    visit the fused path's states BITWISE."""
    import jax.numpy as jnp
    import numpy as np

    import kaminpar_tpu.ops.lp as lp_mod
    import kaminpar_tpu.ops.segments as seg_mod
    from kaminpar_tpu.graphs import device_graph_from_host, factories
    from kaminpar_tpu.ops.lp import lp_cluster

    g = device_graph_from_host(factories.make_rmat(1 << 10, 8_000, seed=9))
    cap = jnp.int32(40)
    fused = np.asarray(lp_cluster(g, cap, jnp.int32(5)))
    calls = []
    real = lp_mod._lp_cluster_chunked
    monkeypatch.setattr(
        lp_mod, "_lp_cluster_chunked",
        lambda *a, **kw: (calls.append(1), real(*a, **kw))[1],
    )
    monkeypatch.setattr(seg_mod, "MAX_FUSED_EDGE_SLOTS", 1024)
    chunked = np.asarray(lp_cluster(g, cap, jnp.int32(5)))
    assert calls, "chunked clustering branch never ran"
    np.testing.assert_array_equal(chunked, fused)
