"""C API tests (ckaminpar.h parity): the pointer-level entry used by the
embedded interpreter, and a real C program linking libckaminpar_tpu.so."""

import ctypes
import os
import subprocess
import sys
import sysconfig
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ring_csr(n):
    xadj = np.arange(0, 2 * n + 1, 2, dtype=np.int64)
    adjncy = np.empty(2 * n, dtype=np.int32)
    for u in range(n):
        adjncy[2 * u] = (u - 1) % n
        adjncy[2 * u + 1] = (u + 1) % n
    return xadj, adjncy


def test_compute_from_pointers_roundtrip():
    """Drive the C-ABI entry exactly as the shim does: raw addresses."""
    from kaminpar_tpu.capi import compute_from_pointers

    n = 16
    xadj, adjncy = _ring_csr(n)
    out = np.full(n, -1, dtype=np.int32)
    cut = compute_from_pointers(
        n,
        xadj.ctypes.data,
        adjncy.ctypes.data,
        0,
        0,
        out.ctypes.data,
        2,
        0.03,
        1,
        "default",
    )
    assert cut >= 2  # a ring cut into 2 parts has cut >= 2
    assert set(np.unique(out)) == {0, 1}
    sizes = np.bincount(out, minlength=2)
    assert sizes.max() <= int(np.ceil(n / 2 * 1.03))


@pytest.mark.skipif(
    not os.path.exists("/usr/bin/g++") and not os.path.exists("/usr/local/bin/g++"),
    reason="no C++ toolchain",
)
def test_c_program_links_and_partitions(tmp_path):
    from kaminpar_tpu.native.build_capi import build

    from kaminpar_tpu.resilience import NativeUnavailable

    try:
        lib = build(str(tmp_path))
    except (
        subprocess.CalledProcessError, NativeUnavailable
    ) as e:  # pragma: no cover
        pytest.skip(f"C ABI build failed: {str(e)[:200]}")

    driver = tmp_path / "driver.c"
    driver.write_text(textwrap.dedent("""
        #include <stdio.h>
        #include <stdlib.h>
        #include "ckaminpar_tpu.h"

        int main(void) {
          enum { N = 16 };
          int64_t xadj[N + 1];
          int32_t adjncy[2 * N];
          for (int u = 0; u <= N; ++u) xadj[u] = 2 * u;
          for (int u = 0; u < N; ++u) {
            adjncy[2 * u] = (u + N - 1) % N;
            adjncy[2 * u + 1] = (u + 1) % N;
          }
          int32_t part[N];
          kmp_partitioner *p = kmp_create("default", 1);
          if (!p) { fprintf(stderr, "create failed\\n"); return 2; }
          int64_t cut = kmp_compute_partition(p, N, xadj, adjncy, NULL,
                                              NULL, 2, 0.03, part);
          if (cut < 0) { fprintf(stderr, "%s\\n", kmp_last_error(p)); return 3; }
          printf("cut=%lld\\n", (long long)cut);
          int sizes[2] = {0, 0};
          for (int u = 0; u < N; ++u) {
            if (part[u] < 0 || part[u] > 1) return 4;
            sizes[part[u]]++;
          }
          printf("sizes=%d,%d\\n", sizes[0], sizes[1]);
          kmp_free(p);
          return 0;
        }
    """))
    exe = tmp_path / "driver"
    version = sysconfig.get_config_var("LDVERSION")
    libdir = sysconfig.get_config_var("LIBDIR")
    subprocess.run(
        [
            "g++", str(driver), "-o", str(exe),
            f"-I{os.path.join(REPO, 'include')}",
            f"-L{tmp_path}", "-lckaminpar_tpu",
            f"-L{libdir}", f"-lpython{version}",
            f"-Wl,-rpath,{tmp_path}", f"-Wl,-rpath,{libdir}",
        ],
        check=True,
        capture_output=True,
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    res = subprocess.run(
        [str(exe)], env=env, capture_output=True, text=True, timeout=600
    )
    assert res.returncode == 0, res.stderr[-500:]
    lines = dict(
        kv.split("=") for kv in res.stdout.strip().splitlines() if "=" in kv
    )
    assert int(lines["cut"]) >= 2
    s0, s1 = (int(x) for x in lines["sizes"].split(","))
    assert s0 + s1 == 16 and max(s0, s1) <= 9


class _FakeNkGraph:
    """Duck-typed stand-in for networkit.Graph (the adapter only touches
    this interface)."""

    def __init__(self, n, edges, weights=None):
        self._n = n
        self._edges = edges
        self._w = weights or {}

    def numberOfNodes(self):
        return self._n

    def isDirected(self):
        return False

    def isWeighted(self):
        return bool(self._w)

    def iterEdges(self):
        return iter(self._edges)

    def weight(self, u, v):
        return self._w.get((u, v), 1.0)


def test_networkit_adapter_surface():
    from kaminpar_tpu.bindings import NetworKitKaMinPar

    # 4x4 grid as an edge list
    edges = []
    for r in range(4):
        for c in range(4):
            u = r * 4 + c
            if c < 3:
                edges.append((u, u + 1))
            if r < 3:
                edges.append((u, u + 4))
    part = NetworKitKaMinPar(_FakeNkGraph(16, edges), seed=1).computePartitionWithEpsilon(2, 0.03)
    assert part.shape == (16,)
    sizes = np.bincount(part, minlength=2)
    assert sizes.max() <= 9


def test_networkit_adapter_rejects_directed():
    from kaminpar_tpu.bindings.networkit import networkit_to_host

    class Directed(_FakeNkGraph):
        def isDirected(self):
            return True

    with pytest.raises(ValueError):
        networkit_to_host(Directed(2, [(0, 1)]))


def test_kaminpar_tpu_platform_override_stays_on_cpu():
    """KAMINPAR_TPU_PLATFORM=cpu with NO JAX_PLATFORMS in the env must
    keep the C-ABI entry on the cpu backend.  Importing the package has
    already latched jax's `jax_platforms` config from the (empty) env
    by the time compute_from_pointers runs, so the platform gate must
    push the restriction into the live config, not just the env (the
    round-5 verdict Weak #2 hang class)."""
    import sys as _sys

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["KAMINPAR_TPU_PLATFORM"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    code = textwrap.dedent("""
        import numpy as np
        from kaminpar_tpu.capi import compute_from_pointers
        n = 8
        xadj = np.arange(0, 2 * n + 1, 2, dtype=np.int64)
        adjncy = np.empty(2 * n, dtype=np.int32)
        for u in range(n):
            adjncy[2 * u] = (u - 1) % n
            adjncy[2 * u + 1] = (u + 1) % n
        out = np.full(n, -1, dtype=np.int32)
        cut = compute_from_pointers(
            n, xadj.ctypes.data, adjncy.ctypes.data, 0, 0,
            out.ctypes.data, 2, 0.03, 1, "default")
        import jax
        assert jax.default_backend() == "cpu", jax.default_backend()
        print("BACKEND_OK", cut)
    """)
    res = subprocess.run(
        [_sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=570,
    )
    assert res.returncode == 0, res.stderr[-500:]
    assert "BACKEND_OK" in res.stdout
