"""Supervision-layer tests: the hard wall-clock watchdog, supervised
worker execution for serving, liveness heartbeats, and the schema-v10
``supervision`` report section (docs/robustness.md, supervision
contract).

The process-isolation tests spawn real worker subprocesses (the
containment machinery under test must kill a genuinely hung child and
classify a genuinely dead one), so the graphs are tiny and the chaos
directives fire *before* the child imports anything heavy.
"""

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from kaminpar_tpu import resilience, telemetry
from kaminpar_tpu.resilience import StageHang, WorkerCrash, faults
from kaminpar_tpu.resilience import deadline as deadline_mod
from kaminpar_tpu.resilience import supervisor
from kaminpar_tpu.serving import (
    PartitionRequest,
    PartitionService,
    ServiceConfig,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(resilience.FAULTS_ENV_VAR, raising=False)
    monkeypatch.delenv(supervisor.ENV_HARD_DEADLINE_S, raising=False)
    monkeypatch.delenv(supervisor.ENV_HEARTBEAT_FILE, raising=False)
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    yield
    resilience.reset()
    telemetry.disable()
    telemetry.reset()


def _gen(n=600, seed=3):
    return f"gen:rgg2d;n={n};avg_degree=8;seed={seed}"


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_report_schema",
        os.path.join(REPO, "scripts", "check_report_schema.py"),
    )
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)
    return checker


# ---------------------------------------------------------------------------
# watchdog + hard-ceiling resolution (host-side units)
# ---------------------------------------------------------------------------


def test_hard_ceiling_resolution(monkeypatch):
    # no budget, no env: no ceiling — hang containment is opt-in
    assert supervisor.hard_ceiling(0.0) is None
    assert supervisor.hard_ceiling(None) is None
    # derived: max(factor * budget, budget + grace) — the grace floor
    # keeps a tight anytime budget from arming a self-defeating ceiling
    assert supervisor.hard_ceiling(0.05, 30.0, 10.0) == pytest.approx(
        30.05
    )
    assert supervisor.hard_ceiling(100.0, 30.0, 10.0) == pytest.approx(
        1000.0
    )
    # factor 0 disables the derived ceiling
    assert supervisor.hard_ceiling(100.0, 30.0, 0.0) is None
    # env override wins over everything
    monkeypatch.setenv(supervisor.ENV_HARD_DEADLINE_S, "7.5")
    assert supervisor.hard_ceiling(100.0, 30.0, 10.0) == 7.5
    assert supervisor.hard_ceiling(0.0) == 7.5
    monkeypatch.setenv(supervisor.ENV_HARD_DEADLINE_S, "0")
    assert supervisor.env_ceiling() is None


def test_watchdog_converts_overrun_to_stage_hang():
    """An armed stage that blows its ceiling gets a StageHang delivered
    at the next bytecode boundary, carrying the stage, ceiling, and the
    stuck timer-scope path."""
    from kaminpar_tpu.utils import timer

    caught = {}

    def victim():
        try:
            with timer.scoped_timer("victim-phase"):
                with supervisor.stage_guard("unit-stage", 0.3):
                    t0 = time.time()
                    while time.time() - t0 < 8.0:
                        time.sleep(0.01)
        except StageHang as e:
            caught["exc"] = e

    t = threading.Thread(target=victim)
    t.start()
    t.join(12.0)
    exc = caught.get("exc")
    assert exc is not None, "watchdog never fired"
    assert exc.stage == "unit-stage"
    assert exc.ceiling_s == 0.3
    # the hang record carries the scope that was open when it expired
    hangs = supervisor.hang_log()
    assert hangs and hangs[-1]["stage"] == "unit-stage"
    assert "victim-phase" in hangs[-1]["path"]
    assert supervisor.watchdog_stats()["fired"] >= 1
    # a stage-hang telemetry event landed in the stream
    assert any(e.name == "stage-hang" for e in telemetry.events())


def test_stage_guard_without_ceiling_is_noop():
    before = supervisor.watchdog_stats()["armed"]
    with supervisor.stage_guard("noop", None):
        pass
    with supervisor.stage_guard("noop", 0.0):
        pass
    assert supervisor.watchdog_stats()["armed"] == before


def test_with_fallback_never_swallows_watchdog_verdicts():
    """An async-delivered StageHang landing inside a guarded primary is
    a process-level hang verdict, not that site's degradation — it must
    propagate to the containment boundary."""
    def primary():
        raise StageHang("delivered mid-primary")

    with pytest.raises(StageHang):
        resilience.with_fallback(
            primary, lambda exc: "swallowed", site="refiner",
        )
    # the INJECTED StageHang (the worker-hang chaos site) still follows
    # the normal injection path
    rec = resilience.with_fallback(
        lambda: (_ for _ in ()).throw(
            StageHang("injected", injected=True)
        ),
        lambda exc: "fell-back", site="worker-hang",
    )
    assert rec == "fell-back"


def test_deadline_budget_emits_watchdog_armed_event():
    deadline_mod.begin_run(1.0)
    events = [e for e in telemetry.events() if e.name == "watchdog-armed"]
    assert events, "no watchdog-armed event for a budgeted run"
    assert events[0].attrs["ceiling_s"] >= 1.0
    assert events[0].attrs["budget_s"] == 1.0
    # an unbudgeted run arms nothing
    telemetry.reset()
    telemetry.enable()
    deadline_mod.begin_run(None)
    assert not [e for e in telemetry.events()
                if e.name == "watchdog-armed"]


def test_watchdog_armed_event_respects_factor_zero():
    """ctx.resilience.hard_deadline_factor=0 disables the derived
    ceiling — the facade arms nothing, so the event must not claim
    otherwise (it reports what is ACTUALLY armed)."""
    deadline_mod.begin_run(1.0, 30.0, 0.0)
    assert not [e for e in telemetry.events()
                if e.name == "watchdog-armed"]
    # a custom factor sizes the reported ceiling
    telemetry.reset()
    telemetry.enable()
    deadline_mod.begin_run(100.0, 30.0, 2.0)
    ev = [e for e in telemetry.events() if e.name == "watchdog-armed"]
    assert ev and ev[0].attrs["ceiling_s"] == pytest.approx(200.0)


def test_injected_hang_without_ceiling_fails_fast():
    """A worker-hang chaos rule on a request with NO hard ceiling must
    fail the request immediately (the supervisor could never time it
    out) instead of hanging the queue forever."""
    from kaminpar_tpu.resilience.supervisor import WorkerPool

    os.environ[resilience.FAULTS_ENV_VAR] = "worker-hang:nth=1"
    pool = WorkerPool()
    try:
        t0 = time.time()
        with pytest.raises(StageHang) as ei:
            pool.run_request("fast-fail", _gen(), None, None,
                             k=4, epsilon=0.03, seed=1, ceiling_s=None)
        assert time.time() - t0 < 5.0, "fail-fast path took too long"
        assert ei.value.injected
        assert pool.stats["spawned"] == 0  # never even spawned a worker
    finally:
        del os.environ[resilience.FAULTS_ENV_VAR]
        pool.shutdown()


def test_worker_fault_sites_registered_and_parseable():
    assert "worker-hang" in faults.SITES
    assert "worker-crash" in faults.SITES
    assert faults.SITES["worker-hang"].exc is StageHang
    assert faults.SITES["worker-crash"].exc is WorkerCrash
    rules = faults.parse_plan("worker-hang:nth=2,worker-crash")
    assert rules[0].site == "worker-hang" and rules[0].nth == 2
    assert rules[1].site == "worker-crash" and rules[1].nth is None


def test_marshalled_errors_reraise_as_their_own_types():
    """The worker error protocol: a classified in-worker failure is
    re-raised in the parent as its own type — a ladder-retryable
    DeviceOOM stays retryable (never a crash verdict), rung exhaustion
    stays crash-shaped."""
    from kaminpar_tpu.resilience.errors import DeviceOOM
    from kaminpar_tpu.resilience.supervisor import _raise_marshalled

    with pytest.raises(DeviceOOM) as ei:
        _raise_marshalled({
            "type": "error", "error": "DeviceOOM",
            "detail": "retryable", "rungs_exhausted": False,
        })
    assert ei.value.rungs_exhausted is False
    with pytest.raises(DeviceOOM) as ei:
        _raise_marshalled({
            "type": "error", "error": "DeviceOOM",
            "detail": "exhausted", "rungs_exhausted": True,
        })
    assert ei.value.rungs_exhausted is True
    with pytest.raises(ValueError):
        _raise_marshalled({
            "type": "error", "error": "ValueError", "detail": "bad",
        })
    from kaminpar_tpu.io import GraphFormatError

    with pytest.raises(GraphFormatError):
        _raise_marshalled({
            "type": "error", "error": "GraphFormatError",
            "detail": "truncated",
        })


# ---------------------------------------------------------------------------
# heartbeats
# ---------------------------------------------------------------------------


def test_heartbeat_mtime_advances_across_barriers(tmp_path):
    """The checkpoint-barrier hook touches the heartbeat file: its
    mtime strictly advances across an inproc run's barriers, so an
    external supervisor polling stat() sees forward progress."""
    from kaminpar_tpu.graphs.factories import make_rgg2d
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.utils.logger import OutputLevel

    hb = tmp_path / "heartbeat"
    supervisor.set_heartbeat(str(hb))
    assert hb.exists()
    m0 = hb.stat().st_mtime_ns
    count0 = supervisor.heartbeat_state()["count"]
    g = make_rgg2d(256, avg_degree=8, seed=1)
    p = KaMinPar("default")
    p.set_output_level(OutputLevel.QUIET)
    part = p.set_graph(g).compute_partition(k=2, epsilon=0.05, seed=1)
    assert len(part) == g.n
    state = supervisor.heartbeat_state()
    assert state["count"] > count0, "no barrier ever touched the file"
    assert hb.stat().st_mtime_ns > m0, "mtime did not advance"


# ---------------------------------------------------------------------------
# supervised worker execution (real subprocesses)
# ---------------------------------------------------------------------------


def _psvc(**cfg):
    cfg.setdefault("isolation", "process")
    return PartitionService("default", ServiceConfig(**cfg))


def test_worker_hang_verdict_and_queue_keeps_serving(monkeypatch):
    """An injected child hang (the worker genuinely sleeps forever) is
    SIGKILLed past its 2nd request's hard ceiling and surfaces as
    verdict failed/worker-hang; the requests before AND after it are
    served normally by fresh warm workers."""
    monkeypatch.setenv(resilience.FAULTS_ENV_VAR, "worker-hang:nth=2")
    svc = _psvc()
    try:
        reqs = [
            PartitionRequest(_gen(seed=1), k=4, seed=1, request_id="a"),
            PartitionRequest(_gen(seed=2), k=4, seed=1, request_id="b",
                             hard_deadline_s=1.0),
            PartitionRequest(_gen(seed=3), k=4, seed=1, request_id="c"),
        ]
        recs = svc.serve(reqs)
        by_id = {r.request_id: r for r in recs}
        assert by_id["a"].verdict == "served" and by_id["a"].feasible
        assert by_id["b"].verdict == "failed"
        assert by_id["b"].reason == "worker-hang"
        assert by_id["b"].error == "StageHang"
        assert by_id["b"].hard_ceiling_s == 1.0
        assert by_id["c"].verdict == "served" and by_id["c"].feasible
        sup = svc.supervision_summary()
        assert sup["enabled"] and sup["isolation"] == "process"
        assert sup["workers"]["killed"] == 1
        assert sup["hangs"] and sup["hangs"][0]["request"] == "b"
        # the serving counts surface the supervision reason
        counts = svc.summary()["counts"]
        assert counts["failed"] == 1 and counts["worker-hang"] == 1
    finally:
        svc.close()


def test_worker_crash_and_same_class_breaker(monkeypatch):
    """Three injected child SIGKILLs (the native-segfault stand-in) in
    one request class open the per-class breaker — the 4th same-class
    request is rejected at admission — while a different class still
    serves from a fresh worker."""
    monkeypatch.setenv(
        resilience.FAULTS_ENV_VAR,
        "worker-crash:nth=1,worker-crash:nth=2,worker-crash:nth=3",
    )
    svc = _psvc()
    try:
        crash_reqs = [
            PartitionRequest(_gen(n=600, seed=s), k=4, seed=1,
                             request_id=f"x{s}")
            for s in (1, 2, 3)
        ]
        recs = svc.serve(crash_reqs)
        assert [r.verdict for r in recs] == ["failed"] * 3
        assert [r.reason for r in recs] == ["worker-crash"] * 3
        assert all(r.error == "WorkerCrash" for r in recs)
        # 4th request of the SAME class: rejected at admission
        rec = svc.submit(
            PartitionRequest(_gen(n=600, seed=9), k=4, request_id="x9")
        )
        assert rec is not None and rec.verdict == "rejected"
        assert rec.reason == "breaker-open"
        # a DIFFERENT class still serves (chaos plan exhausted at nth=3)
        ok = svc.serve([
            PartitionRequest(_gen(n=2048, seed=1), k=4, seed=1,
                             request_id="other"),
        ])
        assert ok[-1].verdict == "served" and ok[-1].feasible
        sup = svc.supervision_summary()
        assert sup["workers"]["crashed"] == 3
        assert svc.summary()["counts"]["worker-crash"] == 3
    finally:
        svc.close()


def test_worker_recycled_after_max_requests():
    """Leak containment: the warm worker is retired after N requests
    and the next request gets a fresh one (recycle count advances; the
    service never notices)."""
    svc = _psvc(worker_max_requests=1)
    try:
        recs = svc.serve([
            PartitionRequest(_gen(n=256, seed=1), k=2, seed=1,
                             request_id="r1"),
            PartitionRequest(_gen(n=256, seed=2), k=2, seed=1,
                             request_id="r2"),
        ])
        assert [r.verdict for r in recs] == ["served", "served"]
        stats = svc.supervision_summary()["workers"]
        assert stats["recycled"] >= 1
        assert stats["spawned"] == 2
        assert stats["requests"] == 2
    finally:
        svc.close()


def test_object_graph_ships_as_npz_and_spool_is_cleaned():
    """An in-memory HostGraph request exchanges through the npz spool
    — and the per-request scratch files (graph AND result) are
    unlinked once the request completes, so a long-lived service does
    not leak a CSR copy per request."""
    from kaminpar_tpu.graphs.factories import make_rgg2d

    svc = _psvc()
    try:
        g = make_rgg2d(256, avg_degree=8, seed=1)
        recs = svc.serve([
            PartitionRequest(g, k=2, seed=1, request_id="obj"),
        ])
        assert recs[0].verdict == "served" and recs[0].feasible
        spool = svc._pool._spool
        leftovers = [f for f in os.listdir(spool) if f.endswith(".npz")]
        assert leftovers == [], leftovers
    finally:
        svc.close()


def test_retryable_worker_oom_does_not_latch_breaker(monkeypatch):
    """Satellite contract: a ladder-retryable DeviceOOM inside a worker
    (governor kill-switched, so it escapes to the isolation boundary)
    is marshalled back as a classified DeviceOOM re-raise — verdict
    `failed` with error DeviceOOM, NOT a worker-crash — and never
    latches the per-class breaker (it indicts the budget, not the
    class)."""
    monkeypatch.setenv(resilience.FAULTS_ENV_VAR, "device-oom:always")
    monkeypatch.setenv("KAMINPAR_TPU_MEM_GOVERNOR", "0")
    svc = _psvc()
    try:
        recs = svc.serve([
            PartitionRequest(_gen(n=256, seed=1), k=2, seed=1,
                             request_id="oom"),
        ])
        assert recs[0].verdict == "failed"
        assert recs[0].error == "DeviceOOM"
        assert recs[0].reason not in ("worker-crash", "worker-hang")
        # the worker did NOT die — a marshalled error keeps it warm
        assert svc.supervision_summary()["workers"]["crashed"] == 0
        # and the class breaker holds no strike
        assert svc._class_failures == {}
    finally:
        svc.close()


def test_inproc_clean_batch_bitwise_unchanged():
    """The supervision refactor must not touch inproc execution: a
    clean batch served inproc returns bitwise the same partitions as
    the facade called directly with the same inputs."""
    from kaminpar_tpu.graphs.factories import generate
    from kaminpar_tpu.kaminpar import KaMinPar
    from kaminpar_tpu.utils.logger import OutputLevel

    specs = [(_gen(n=600, seed=1), 4), (_gen(n=600, seed=2), 2)]
    svc = PartitionService(
        "default", ServiceConfig(keep_partitions=True)
    )
    assert svc._pool is None  # inproc default: no worker machinery
    recs = svc.serve([
        PartitionRequest(g, k=k, seed=7, request_id=f"q{i}")
        for i, (g, k) in enumerate(specs)
    ])
    assert [r.verdict for r in recs] == ["served", "served"]
    for rec, (g, k) in zip(recs, specs):
        p = KaMinPar("default")
        p.set_output_level(OutputLevel.QUIET)
        ref = p.set_graph(generate(g)).compute_partition(k=k, seed=7)
        assert np.array_equal(rec.partition, ref), rec.request_id
        # no ceiling resolved: nothing supervision-shaped on the record
        assert rec.hard_ceiling_s is None


# ---------------------------------------------------------------------------
# schema v10 report surface
# ---------------------------------------------------------------------------


def test_supervision_disabled_default_for_single_shot_runs():
    from kaminpar_tpu.telemetry.report import build_run_report

    report = build_run_report()
    assert report["schema_version"] == 14
    assert report["supervision"] == {"enabled": False}


def test_supervision_section_schema_valid(tmp_path):
    """A populated supervision section (heartbeat + a recorded hang)
    validates against the checked-in schema, and the disabled default
    stays the section for runs that configured nothing."""
    from kaminpar_tpu.telemetry.report import SCHEMA_PATH, build_run_report

    supervisor.set_heartbeat(str(tmp_path / "hb"))
    supervisor.record_hang({
        "stage": "worker-compute", "path": "partitioning.coarsening",
        "ceiling_s": 2.0, "request": "req-1", "worker_pid": 42,
    })
    telemetry.annotate(
        result={"cut": -1, "imbalance": 0.0, "feasible": False}
    )
    report = build_run_report()
    sup = report["supervision"]
    assert sup["enabled"] is True
    assert sup["hangs"][0]["stage"] == "worker-compute"
    assert sup["heartbeat"]["count"] >= 1
    checker = _load_checker()
    schema = json.load(open(SCHEMA_PATH))
    errors = checker.validate_instance(report, schema)
    errors += checker.version_checks(report)
    assert errors == [], errors


def test_service_config_rejects_unknown_isolation():
    with pytest.raises(ValueError):
        PartitionService("default", ServiceConfig(isolation="thread"))


def test_batch_spec_parses_supervision_fields(tmp_path):
    from kaminpar_tpu.serving.batch import load_batch

    spec = {
        "config": {"isolation": "process", "worker_max_requests": 4,
                   "hard_deadline_s": 5.0},
        "requests": [
            {"graph": _gen(), "k": 4, "id": "a",
             "hard_deadline_s": 2.0},
            {"graph": _gen(), "k": 4, "id": "b"},
        ],
    }
    path = tmp_path / "batch.json"
    path.write_text(json.dumps(spec))
    requests, config = load_batch(str(path))
    assert config.isolation == "process"
    assert config.worker_max_requests == 4
    assert config.hard_deadline_s == 5.0
    assert requests[0].hard_deadline_s == 2.0
    assert requests[1].hard_deadline_s is None
