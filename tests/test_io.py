"""IO tests (analog of kaminpar-io usage in the reference test suite)."""

import numpy as np
import pytest

from kaminpar_tpu.graphs import factories, validate
from kaminpar_tpu.io import (
    load_graph,
    load_metis,
    load_parhip,
    parse_metis,
    read_partition,
    write_metis,
    write_parhip,
    write_partition,
)


def test_parse_metis_unweighted():
    g = parse_metis("3 2\n2\n1 3\n2\n")
    assert g.n == 3 and g.m == 4
    assert list(g.neighbors(1)) == [0, 2]
    assert g.node_weights is None and g.edge_weights is None


def test_parse_metis_weighted():
    text = "2 1 11\n5 2 7\n3 1 7\n"
    g = parse_metis(text)
    assert list(g.node_weights) == [5, 3]
    assert list(g.edge_weights) == [7, 7]


def test_parse_metis_comments_and_isolated():
    g = parse_metis("% hello\n3 1\n2\n1\n\n")
    assert g.n == 3 and g.m == 2
    assert g.degrees()[2] == 0


def test_reference_sample_graphs_agree():
    metis = load_metis("/root/reference/misc/rgg2d.metis")
    p32 = load_parhip("/root/reference/misc/rgg2d-32bit.parhip")
    p64 = load_parhip("/root/reference/misc/rgg2d-64bit.parhip")
    for other in (p32, p64):
        assert np.array_equal(metis.xadj, other.xadj)
        assert np.array_equal(metis.adjncy, other.adjncy)
    validate(metis)
    assert metis.n == 1024 and metis.m == 2 * 4113


def test_metis_round_trip(tmp_path):
    g = factories.make_grid_graph(5, 5)
    path = str(tmp_path / "g.metis")
    write_metis(g, path)
    g2 = load_metis(path)
    assert np.array_equal(g.xadj, g2.xadj)
    assert np.array_equal(g.adjncy, g2.adjncy)


def test_parhip_round_trip(tmp_path):
    g = factories.make_rgg2d(200, seed=3)
    nw = np.arange(1, g.n + 1, dtype=np.int64)
    g.node_weights = nw
    path = str(tmp_path / "g.parhip")
    write_parhip(g, path)
    g2 = load_parhip(path)
    assert np.array_equal(g.xadj, g2.xadj)
    assert np.array_equal(g.adjncy, g2.adjncy)
    assert np.array_equal(g2.node_weights, nw)


def test_partition_round_trip(tmp_path):
    part = np.array([0, 1, 2, 1, 0], dtype=np.int32)
    path = str(tmp_path / "part.txt")
    write_partition(path, part)
    assert np.array_equal(read_partition(path), part)


def test_load_graph_auto_detect(tmp_path):
    g = factories.make_path(10)
    mp = str(tmp_path / "a.graph")
    pp = str(tmp_path / "a.parhip")
    write_metis(g, mp)
    write_parhip(g, pp)
    assert load_graph(mp).m == g.m
    assert load_graph(pp).m == g.m


def test_load_graph_degree_bucket_ordering(tmp_path):
    """read_graph NodeOrdering analog: degree-buckets rearrangement."""
    import numpy as np

    from kaminpar_tpu.io import load_graph, write_remapping

    g_nat = load_graph("/root/reference/misc/rgg2d.metis")
    g_db = load_graph(
        "/root/reference/misc/rgg2d.metis", ordering="degree-buckets"
    )
    assert g_db.n == g_nat.n and g_db.m == g_nat.m
    deg = np.diff(g_db.xadj)
    # bucket = floor(log2(deg)) + 1 (0 for isolated) must be sorted
    bucket = np.where(
        deg > 0, np.floor(np.log2(np.maximum(deg, 1))) + 1, 0
    )
    assert (np.diff(bucket) >= 0).all()

    path = tmp_path / "remap.txt"
    write_remapping(str(path), np.arange(g_db.n))
    assert np.loadtxt(path, dtype=np.int64).shape == (g_db.n,)


# ---------------------------------------------------------------------------
# lazy/mmap compressed containers (the external scheme's disk tier)
# ---------------------------------------------------------------------------


def test_lazy_compressed_load_mmaps_and_decodes_identically(tmp_path):
    """load_compressed(lazy=True) on a raw-stored container mmaps the
    byte streams (chunk-granular page-in) and decodes bitwise-identically
    to the eager path."""
    import numpy as np

    from kaminpar_tpu.graphs.factories import make_rgg2d
    from kaminpar_tpu.graphs.compressed import compress_host_graph
    from kaminpar_tpu.io.compressed_binary import (
        is_compressed_file,
        load_compressed,
        write_compressed,
    )

    g = make_rgg2d(4000, avg_degree=8, seed=9)
    cg = compress_host_graph(g)
    path = str(tmp_path / "g.npz")
    write_compressed(path, cg, compress=False)
    assert is_compressed_file(path)
    lazy = load_compressed(path, lazy=True)
    assert isinstance(lazy.data, np.memmap)
    eager = load_compressed(path)
    for v0, v1 in ((0, 128), (1000, 1600), (g.n - 64, g.n)):
        xr1, a1, w1 = lazy.decode_range(v0, v1)
        xr2, a2, w2 = eager.decode_range(v0, v1)
        assert np.array_equal(np.asarray(a1), np.asarray(a2))
        assert np.array_equal(np.asarray(xr1), np.asarray(xr2))
    assert lazy.decode().m == g.m


def test_lazy_compressed_load_bounded_peak(tmp_path):
    """The lazy path's host allocation stays bounded: loading + one
    chunk decode allocates a small fraction of what the eager
    full-container materialization pays (the full-file RAM spike the
    satellite exists to remove).  Measured with tracemalloc — the
    host-side twin of the PR-7 device-memory sampler (numpy routes
    allocations through the traced PyDataMem domain; np.memmap pages
    are owned by the OS cache and never hit it)."""
    import tracemalloc

    import numpy as np

    from kaminpar_tpu.graphs.host import HostGraph
    from kaminpar_tpu.graphs.compressed import compress_host_graph
    from kaminpar_tpu.io.compressed_binary import (
        load_compressed,
        write_compressed,
    )

    # a ring graph with a large, incompressible-ish payload: every
    # varint stream byte matters, so the container's `data` member is
    # the dominant cost the lazy path must NOT materialize
    n = 200_000
    src = np.arange(n, dtype=np.int64)
    right = (src + 1) % n
    left = (src - 1) % n
    adj = np.empty(2 * n, dtype=np.int32)
    adj[0::2] = np.minimum(left, right)
    adj[1::2] = np.maximum(left, right)
    xadj = np.arange(0, 2 * n + 1, 2, dtype=np.int64)
    g = HostGraph(xadj=xadj, adjncy=adj)
    cg = compress_host_graph(g)
    path = str(tmp_path / "big.npz")
    write_compressed(path, cg, compress=False)
    data_bytes = int(cg.data.nbytes)

    def peak(load):
        tracemalloc.start()
        graph = load()
        graph.decode_range(0, 4096)  # one chunk's worth of pages
        _, p = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        del graph
        return p

    lazy_peak = peak(lambda: load_compressed(path, lazy=True))
    eager_peak = peak(lambda: load_compressed(path))
    # the eager path materializes the full data member; the lazy path
    # must stay well under it (O(n) offsets + one decoded chunk)
    assert eager_peak >= data_bytes, (eager_peak, data_bytes)
    assert lazy_peak < 0.5 * eager_peak, (lazy_peak, eager_peak)
