"""Dist chaos past the toys (VERDICT item 8's >= 2^17-edge bar).

The small dist-resilience suite exercises the protocol on grids; this
module injects a fault into a dist run on a generator graph big enough
to shard organically (n=16384, avg degree 16 -> m >= 2^17 directed edge
slots, well past the single-shard regime) and demands the full
recovery story at once: the agreed OOM ladder absorbs the injected
allocator failure, the result is complete and gate-valid, and the
comm-table accounting recorded real per-phase collective payloads while
it happened (recovery exercised WITH the mesh collectives live, not on
a degenerate one-device layout).
"""

import os

import numpy as np
import pytest

from kaminpar_tpu import resilience, telemetry
from kaminpar_tpu.graphs.factories import make_rgg2d
from kaminpar_tpu.parallel import dKaMinPar, make_mesh
from kaminpar_tpu.parallel.dist_context import (
    create_dist_context_by_preset_name,
)
from kaminpar_tpu.resilience import memory as memory_mod


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(resilience.FAULTS_ENV_VAR, raising=False)
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    yield
    resilience.reset()
    telemetry.disable()
    telemetry.reset()


def test_dist_chaos_recovery_on_organically_sharded_graph(monkeypatch):
    from kaminpar_tpu.parallel.mesh import comm_records, reset_comm_log

    g = make_rgg2d(16384, avg_degree=16, seed=5)
    assert int(g.m) >= (1 << 17), "graph under the past-the-toys bar"

    monkeypatch.setenv(resilience.FAULTS_ENV_VAR, "device-oom:nth=1")
    reset_comm_log()
    ctx = create_dist_context_by_preset_name("default")
    solver = dKaMinPar(ctx, mesh=make_mesh(4)).set_graph(g)
    part = solver.compute_partition(k=8, epsilon=0.03, seed=1)

    # recovery: the injected OOM walked the agreed ladder to rung 1
    deg = [
        e.attrs for e in telemetry.events("degraded")
        if e.attrs["site"] == "device-oom"
    ]
    assert deg and deg[-1]["rung"] == 1 and deg[-1]["injected"]
    assert deg[-1]["triggering_rank"] == 0
    st = memory_mod.state()
    assert st is not None and st.rung == 1

    # the result is complete and gate-valid
    assert part.shape == (g.n,)
    gates = telemetry.events("output-gate")
    assert gates and gates[-1].attrs["valid"]
    bw = np.zeros(8, dtype=np.int64)
    np.add.at(bw, part, np.asarray(g.node_weight_array()))
    assert bw.min() > 0  # all 8 blocks populated

    # the mesh collectives were LIVE during recovery: per-phase comm
    # rows with non-zero per-device payload bytes were traced
    records = comm_records()
    assert records, "no comm-table rows recorded"
    payload = [
        r for r in records if r.get("payload_bytes_per_device", 0) > 0
    ]
    assert payload, records
    phases = {r.get("phase") for r in records}
    assert any("coarsening" in (p or "") for p in phases) or any(
        "refinement" in (p or "") for p in phases
    ), phases

    # and the dist resilience section audits the run
    from kaminpar_tpu.telemetry.report import build_run_report

    sect = build_run_report()["dist_resilience"]
    assert sect["enabled"] and sect["audits"] >= 1
    assert sect["ladder"]["rung"] == 1
