"""Rating-engine tests (ops/rating.py): scatter-add slot-table
exactness, engine equivalence vs the sort engine under the shared
tie-break hash, the collision-safe fallback, density-adaptive
selection, the fused-round jaxpr pin, and bench-path dormancy.

The equivalence contract (ISSUE 9): the scatter-add and sort rating
engines pick IDENTICAL clusters given the same tie-break hash — either
because every row is fully rated (slot budget covers the graph) or
because the per-round guard fell back to the sort engine.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kaminpar_tpu.graphs import device_graph_from_host, factories
from kaminpar_tpu.ops.lp import LPConfig, lp_cluster
from kaminpar_tpu.ops.rating import (
    best_from_slots,
    best_from_slots_pallas,
    scatter_slot_ratings,
    select_engine,
)


def _slot_bruteforce_ref(dg, labels):
    """Per-(node, label) exact connection sums from the raw edge list."""
    src, dst, ew = (np.asarray(dg.src), np.asarray(dg.dst),
                    np.asarray(dg.edge_w))
    nb = labels[dst]
    ref = {}
    for s, lab, w in zip(src, nb, ew):
        if w:
            ref[(int(s), int(lab))] = ref.get((int(s), int(lab)), 0) + int(w)
    return ref


def test_scatter_slot_ratings_exact_and_flagged():
    """Every rated slot carries the EXACT connection sum, and a
    fully_rated row's slots enumerate every adjacent label."""
    g = factories.make_rmat(256, 2048, seed=9)
    dg = device_graph_from_host(g)
    rng = np.random.default_rng(4)
    labels = np.arange(dg.n_pad, dtype=np.int32)
    labels[: g.n] = rng.integers(0, g.n, g.n)
    nb = jnp.asarray(labels)[dg.dst]
    ref = _slot_bruteforce_ref(dg, labels)
    per_node = {}
    for (u, lab) in ref:
        per_node.setdefault(u, set()).add(lab)
    for S in (8, 64):
        sl, sw, fr = (
            np.asarray(x)
            for x in scatter_slot_ratings(
                dg.src, nb, dg.edge_w, dg.n_pad, S, 17
            )
        )
        for u in range(g.n):
            rated = {}
            for lab, w in zip(sl[u], sw[u]):
                if lab >= 0 and w > 0:
                    # exactness: a rated label's sum is the true sum
                    assert ref[(u, int(lab))] == int(w), (S, u, lab)
                    rated[int(lab)] = int(w)
            if fr[u] and u in per_node:
                # completeness: fully-rated rows rated every label
                assert per_node[u] <= set(rated), (S, u)
        # more slots must not rate fewer rows
    assert fr[: g.n].mean() > 0.5


@pytest.mark.parametrize(
    "make",
    [
        lambda: factories.make_rmat(512, 4096, seed=11),  # degree-skewed
        lambda: factories.make_star(32),                  # hub row
        lambda: factories.make_path(64),                  # unit weights
    ],
    ids=["rmat-skewed", "star", "path-unit"],
)
def test_engine_equivalence_scatter_vs_sort(make):
    """Fully-rated scatter rounds pick the SAME clusters as the sort
    engine (shared tie-break hash), bitwise across the whole
    clustering: rounds, post-passes, convergence."""
    g = make()
    dg = device_graph_from_host(g)
    cap = jnp.int32(max(4, int(g.node_weight_array().sum()) // 12))
    l_sort = np.asarray(
        lp_cluster(dg, cap, jnp.int32(5), LPConfig(rating="sort"))
    )
    l_scat = np.asarray(
        lp_cluster(
            dg, cap, jnp.int32(5),
            LPConfig(rating="scatter", num_slots=256, scatter_fallback=0.0),
        )
    )
    np.testing.assert_array_equal(l_sort, l_scat)


def test_scatter_collision_fallback_is_exact():
    """With a starved slot budget and a zero fallback threshold every
    contested round must take the sort branch — end-to-end output
    bitwise equal to the sort engine's."""
    g = factories.make_rmat(512, 4096, seed=11)
    dg = device_graph_from_host(g)
    l_sort = np.asarray(
        lp_cluster(dg, jnp.int32(40), jnp.int32(5), LPConfig(rating="sort"))
    )
    l_fb = np.asarray(
        lp_cluster(
            dg, jnp.int32(40), jnp.int32(5),
            LPConfig(rating="scatter", num_slots=2, scatter_fallback=0.0),
        )
    )
    np.testing.assert_array_equal(l_sort, l_fb)


def test_scatter_default_quality_and_caps():
    """Default scatter settings: caps respected, graph actually
    coarsens, and the cut-relevant cluster count stays within 2x of the
    exact sort engine's (the hash-engine quality contract, tightened)."""
    g = factories.make_rmat(512, 4096, seed=11)
    dg = device_graph_from_host(g)
    cap = 40
    counts = {}
    for name in ("sort", "scatter"):
        lab = np.asarray(
            lp_cluster(dg, jnp.int32(cap), jnp.int32(5),
                       LPConfig(rating=name))
        )[: g.n]
        w = np.zeros(dg.n_pad, np.int64)
        np.add.at(w, lab, g.node_weight_array())
        assert w.max() <= cap, name
        counts[name] = len(np.unique(lab))
    assert counts["scatter"] <= max(2 * counts["sort"],
                                    counts["sort"] + 64)


def test_scatter_global_label_space():
    """The owner-sharded dist layout rates GLOBAL cluster ids from
    n_loc-row tables: labels beyond the row count must be rated
    verbatim, never clipped into the row domain (which would silently
    merge every remote label into one)."""
    n_rows, label_space = 4, 64
    owner = jnp.array([0, 0, 1, 1, 2], dtype=jnp.int32)
    nb = jnp.array([37, 59, 59, 5, 37], dtype=jnp.int32)
    w = jnp.array([3, 4, 5, 6, 7], dtype=jnp.int32)
    sl, sw, fr = (
        np.asarray(x)
        for x in scatter_slot_ratings(
            owner, nb, w, n_rows, 16, 11, label_space=label_space
        )
    )
    assert fr.all()
    rated = {
        (u, int(lab)): int(wt)
        for u in range(n_rows)
        for lab, wt in zip(sl[u], sw[u])
        if lab >= 0 and wt > 0
    }
    assert rated == {(0, 37): 3, (0, 59): 4, (1, 59): 5, (1, 5): 6,
                     (2, 37): 7}


def test_select_engine_density_rule():
    """The 1402.3281 adaptivity rule: dense for refinement-sized label
    spaces, scatter inside the slot budget, sort2 beyond it (sort when
    the layout has no row spans); forced names pass through."""
    assert select_engine("auto", 16, 1 << 20, 1 << 24)[0] == "dense"
    assert select_engine(
        "auto", 1 << 20, 1 << 20, 1 << 24, num_slots=32,
        degree_skew=400.0,
    )[0] == "scatter"  # avg degree 16, RMAT-class skew
    assert select_engine(
        "auto", 1 << 20, 1 << 14, 1 << 24, num_slots=32,
        degree_skew=400.0,
    )[0] == "sort2"  # avg degree 1024
    assert select_engine(
        "auto", 1 << 20, 1 << 14, 1 << 24, num_slots=32,
        degree_skew=400.0, row_spans=False,
    )[0] == "sort"
    assert select_engine("hash", 16, 1 << 20, 1 << 24)[0] == "hash"
    # low-skew (uniform/geometric) graphs keep sort2: barred tie
    # chains measurably derail their coarsening (see select_engine)
    assert select_engine(
        "auto", 1 << 20, 1 << 20, 1 << 24, num_slots=32,
        avg_degree=8.0, degree_skew=2.5,
    )[0] == "sort2"
    # unmeasured skew defaults conservative (no scatter on the static
    # shape-only path; the coarsener measures and re-resolves)
    assert select_engine(
        "auto", 1 << 20, 1 << 20, 1 << 24, num_slots=32
    )[0] == "sort2"
    # measured stats override the padded-shape approximation
    assert select_engine(
        "auto", 1 << 20, 1 << 20, 1 << 24, num_slots=32,
        avg_degree=500.0, degree_skew=2.0,
    )[0] == "sort2"


def test_fused_round_jaxpr_identical_with_telemetry_idle():
    """The jaxpr pin (ISSUE 9 satellite): the fused scatter round must
    stay BITWISE-identical with progress/perf telemetry off — enabling
    the telemetry layer without capture must not touch the traced
    computation (the PR-4 zero-overhead contract extended to the new
    engine)."""
    import kaminpar_tpu.ops.lp as lp_mod
    from kaminpar_tpu import telemetry

    g = factories.make_rmat(256, 2048, seed=3)
    dg = device_graph_from_host(g)
    cfg = LPConfig(rating="scatter")

    def trace():
        return str(
            jax.make_jaxpr(
                lambda mcw, seed: lp_mod._lp_cluster_fused_rounds(
                    dg, mcw, seed, None, cfg, 4
                )
            )(jnp.int32(40), jnp.int32(1))
        )

    was_enabled = telemetry.enabled()
    try:
        telemetry.disable()
        j_off = trace()
        telemetry.enable()
        j_on = trace()
    finally:
        telemetry.disable() if not was_enabled else telemetry.enable()
    assert j_on == j_off


def test_bench_path_dormancy_wall_bounded():
    """Pin the r05-regression diagnosis (ISSUE 9 satellite): with
    telemetry ON (bench.py's configuration) a clustering emits NO
    per-round host events — only per-call progress series — and the
    perf observatory / memory governor add no per-round host work.  The
    wall bound is deliberately generous: it exists to catch a
    reintroduced per-round host sync (which multiplies wall by the
    round count), not scheduler jitter."""
    from kaminpar_tpu import telemetry
    from kaminpar_tpu.resilience import memory as memory_mod

    g = factories.make_rmat(1 << 11, 20_000, seed=1)
    dg = device_graph_from_host(g)
    cfg = LPConfig(rating="scatter")
    # warm: compile outside the timed region (bench measures min-over-
    # seeds for the same reason)
    jax.block_until_ready(lp_cluster(dg, jnp.int32(64), jnp.int32(1), cfg))
    was_enabled = telemetry.enabled()
    spills = []
    orig_note = memory_mod.note_spill
    memory_mod.note_spill = lambda b: spills.append(b)
    try:
        telemetry.enable()
        telemetry.reset()
        t0 = time.perf_counter()
        jax.block_until_ready(
            lp_cluster(dg, jnp.int32(64), jnp.int32(2), cfg)
        )
        wall = time.perf_counter() - t0
        events = telemetry.events()
        series = telemetry.progress_series()
    finally:
        memory_mod.note_spill = orig_note
        telemetry.enable() if was_enabled else telemetry.disable()
    # one progress series per clustering call, NO per-round events, no
    # governor work while dormant
    assert not events, [e.name for e in events]
    assert len(series) <= 1
    assert not spills
    assert wall < 30.0, f"bench-path clustering took {wall:.1f}s"


def test_best_from_slots_pallas_interpret_matches_lax():
    """The optional Pallas rate+argmax core (platform-gated, lax path
    default) computes the same unconstrained best/own values in
    interpret mode."""
    g = factories.make_rmat(128, 1024, seed=7)
    dg = device_graph_from_host(g)
    rng = np.random.default_rng(2)
    labels = np.arange(dg.n_pad, dtype=np.int32)
    labels[: g.n] = rng.integers(0, g.n, g.n)
    lab_j = jnp.asarray(labels)
    nb = lab_j[dg.dst]
    slot_label, slot_w, _ = scatter_slot_ratings(
        dg.src, nb, dg.edge_w, dg.n_pad, 32, 13
    )
    # unconstrained reference via the lax path
    b_ref, w_ref, own_ref = best_from_slots(
        slot_label, slot_w, lab_j,
        jnp.zeros((dg.n_pad,), slot_w.dtype), dg.node_w,
        jnp.zeros((dg.n_pad,), slot_w.dtype), 13, require_fit=False,
    )
    b_pl, w_pl, own_pl = best_from_slots_pallas(
        slot_label, slot_w, lab_j, 13, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(b_ref), np.asarray(b_pl))
    np.testing.assert_array_equal(np.asarray(w_ref), np.asarray(w_pl))
    np.testing.assert_array_equal(np.asarray(own_ref), np.asarray(own_pl))


def test_dist_scatter_engine_valid_and_capped():
    """The scatter engine through shard_map (engine flag threaded via
    the static cfg): valid, cap-respecting clustering on the virtual
    mesh, identical across 1 and 4 devices."""
    from kaminpar_tpu.parallel import (
        dist_graph_from_host,
        dist_lp_cluster,
        make_mesh,
    )

    graph = factories.make_grid_graph(16, 16)
    cfg = LPConfig(rating="scatter")
    outs = []
    for nd in (1, 4):
        mesh = make_mesh(nd)
        dg = dist_graph_from_host(graph, mesh)
        try:
            labels = np.asarray(
                dist_lp_cluster(dg, 40, seed=1, cfg=cfg)
            )
        except TypeError as e:
            if "check_vma" in str(e):
                # this environment's jax predates shard_map(check_vma=)
                # — the whole dist suite fails the same way at seed
                pytest.skip("shard_map lacks check_vma on this jax")
            raise
        lab = labels[: graph.n]
        w = np.zeros(labels.shape[0], dtype=np.int64)
        np.add.at(w, lab, graph.node_weight_array()[: graph.n])
        assert w.max() <= 40
        assert len(np.unique(lab)) < graph.n
        outs.append(labels)
