"""Out-of-core streaming partitioner tests (kaminpar_tpu/external/).

The ISSUE-13 acceptance surface:

  * streaming-vs-in-core equivalence: same graph, same seed -> a
    gate-valid result whose cut is within the diff-gate threshold of
    the in-core deep run;
  * chunk-size invariance: two chunk targets -> bitwise-identical
    partitions AND identical coarse hierarchy shapes (the
    round-start-rating + global-apply design makes the stream's result
    independent of its chunking);
  * kill-and-resume mid-stream: a hard preemption at a `stream-coarsen`
    barrier resumes cut-identical to the uninterrupted run;
  * a forced-tiny-budget end-to-end run whose telemetry proves the fine
    level was never device-resident (external.fine_device_resident_bytes
    == 0, overlap > 0, >= 1 stream event);
  * the chunk store: range coverage, source agreement (CSR vs
    compressed), the disk spill tier, and the generator-spec wrapper
    that never materializes the fine graph;
  * the streaming LP's exact cluster-weight cap;
  * schema: the v9 `external` report section validates.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

from kaminpar_tpu import resilience, telemetry
from kaminpar_tpu.context import PartitioningMode
from kaminpar_tpu.external import chunkstore, stream_coarsen
from kaminpar_tpu.graphs.compressed import compress_host_graph
from kaminpar_tpu.graphs.factories import make_rgg2d
from kaminpar_tpu.graphs.host import host_partition_metrics
from kaminpar_tpu.kaminpar import KaMinPar
from kaminpar_tpu.presets import create_context_by_preset_name
from kaminpar_tpu.resilience import memory as mem
from kaminpar_tpu.resilience.checkpoint import (
    STOP_AT_ENV,
    SimulatedPreemption,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for var in (mem.ENV_BUDGET, mem.ENV_FORCE_RUNG, mem.ENV_GOVERNOR,
                STOP_AT_ENV, resilience.FAULTS_ENV_VAR):
        monkeypatch.delenv(var, raising=False)
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    yield
    resilience.reset()
    telemetry.disable()
    telemetry.reset()


def _ctx(chunk_edges=1 << 13, **kw):
    ctx = create_context_by_preset_name("default")
    ctx.partitioning.mode = PartitioningMode.EXTERNAL
    ctx.external.chunk_edges = chunk_edges
    for key, value in kw.items():
        setattr(ctx.external, key, value)
    return ctx


def _run(graph, ctx, k=4, seed=1):
    solver = KaMinPar(ctx)
    solver.set_graph(graph)
    solver.set_output_level(0)
    return solver.compute_partition(k=k, epsilon=0.03, seed=seed)


def _gate():
    gates = [e.attrs for e in telemetry.events("output-gate")]
    return gates[-1] if gates else None


# ---------------------------------------------------------------------------
# chunk store
# ---------------------------------------------------------------------------


def test_chunk_plan_covers_and_shares_one_bucket():
    g = make_rgg2d(4000, avg_degree=8, seed=2)
    store = chunkstore.build_store(g, target_edges=2048)
    assert store.num_chunks > 1
    # contiguous full coverage
    assert store.ranges[0][0] == 0 and store.ranges[-1][1] == g.n
    for (a, b), (c, _) in zip(store.ranges, store.ranges[1:]):
        assert b == c
    # one shared bucket: every chunk fits e_pad
    for c in range(store.num_chunks):
        assert store.chunk_edges(c) <= store.e_pad
        block = store.chunk_host(c)
        assert block.src_local.shape == (store.e_pad,)
        assert block.dst.shape == (store.e_pad,)
    assert store.decoded_bytes > 0


def test_chunk_sources_agree_csr_vs_compressed():
    g = make_rgg2d(3000, avg_degree=8, seed=2)
    cg = compress_host_graph(g)
    s1 = chunkstore.build_store(g, target_edges=4096)
    s2 = chunkstore.build_store(cg, target_edges=4096)
    assert s1.num_chunks == s2.num_chunks and s1.e_pad == s2.e_pad
    for c in range(s1.num_chunks):
        b1, b2 = s1.chunk_host(c), s2.chunk_host(c)
        assert (b1.v0, b1.v1, b1.m_real) == (b2.v0, b2.v1, b2.m_real)
        assert np.array_equal(b1.src_local, b2.src_local)
        assert np.array_equal(b1.dst, b2.dst)
        assert np.array_equal(b1.w, b2.w)


def test_spill_tier_writes_once_and_rereads(tmp_path):
    g = make_rgg2d(2000, avg_degree=8, seed=3)
    spill = str(tmp_path / "spill")
    store = chunkstore.build_store(g, target_edges=2048, spill_dir=spill)
    first = [store.chunk_host(c) for c in range(store.num_chunks)]
    assert store.spilled_bytes > 0
    files = sorted(
        f for f in os.listdir(spill)
        if f.startswith("chunk-") and f.endswith(".npz")
    )
    assert len(files) == store.num_chunks
    assert os.path.exists(os.path.join(spill, "spill.json"))  # cache key
    spilled_once = store.spilled_bytes
    second = [store.chunk_host(c) for c in range(store.num_chunks)]
    assert store.spilled_bytes == spilled_once  # written exactly once
    for b1, b2 in zip(first, second):
        assert np.array_equal(b1.dst, b2.dst)
        assert np.array_equal(b1.w, b2.w)


def test_generator_spec_wrapper_never_materializes():
    spec = "gen:rgg2d;n=2048;avg_degree=8;seed=4"
    sg = chunkstore.StreamedSpecGraph(spec, target_edges=4096)
    assert not hasattr(sg, "adjncy")
    assert sg.n == 2048 and sg.m == int(sg.xadj[-1]) > 0
    # iter_rows covers the degree prefix exactly
    total = 0
    for v0, v1, adj, ew in sg.iter_rows():
        assert len(adj) == int(sg.xadj[v1] - sg.xadj[v0])
        total += len(adj)
    assert total == sg.m
    # the assembled twin agrees (chunk determinism)
    host = sg.to_host_graph()
    assert host.n == sg.n and host.m == sg.m


# ---------------------------------------------------------------------------
# streaming LP semantics
# ---------------------------------------------------------------------------


def test_stream_lp_cap_is_exact_on_weighted_graph():
    g = make_rgg2d(1500, avg_degree=8, seed=5)
    rng = np.random.default_rng(7)
    node_w = rng.integers(1, 9, g.n).astype(np.int64)
    g.node_weights = node_w
    cap = 24
    store = chunkstore.build_store(g, target_edges=1024)
    labels, cluster_w, nw_dev = stream_coarsen.make_vectors(store, node_w)
    labels, cluster_w, _ = stream_coarsen.stream_lp(
        store, labels, cluster_w, nw_dev, cap, seed=1, rounds=3
    )
    lab = chunkstore.pull_labels(labels, g.n)
    cw = np.zeros(g.n, dtype=np.int64)
    np.add.at(cw, lab, node_w)
    members = np.bincount(lab, minlength=g.n)
    # every multi-member cluster respects the cap EXACTLY; a singleton
    # heavier than the cap never moved and is legitimately over it
    over = np.flatnonzero(cw > cap)
    assert all(members[c] == 1 for c in over), (
        f"cap overshoot on joined clusters: "
        f"{[(int(c), int(cw[c]), int(members[c])) for c in over[:5]]}"
    )
    assert len(np.unique(lab)) < g.n  # it did cluster


# ---------------------------------------------------------------------------
# end-to-end: equivalence, invariance, resume, budget
# ---------------------------------------------------------------------------


def test_streaming_vs_incore_equivalence():
    g = make_rgg2d(8192, avg_degree=8, seed=1)
    ext = _run(g, _ctx(chunk_edges=1 << 13), k=4, seed=1)
    cut_ext = host_partition_metrics(g, ext, 4)["cut"]
    gate = _gate()
    assert gate and gate["valid"]
    deep_ctx = create_context_by_preset_name("default")
    deep = _run(g, deep_ctx, k=4, seed=1)
    cut_deep = host_partition_metrics(g, deep, 4)["cut"]
    # the telemetry.diff regression threshold (10%) is the contract;
    # both directions (streaming may win)
    assert cut_ext <= 1.10 * cut_deep + 1, (cut_ext, cut_deep)


def test_chunk_size_invariance():
    g = make_rgg2d(4096, avg_degree=8, seed=1)
    parts, shapes = [], []
    for chunk_edges in (1 << 11, 1 << 13, 10 ** 9):
        telemetry.reset()
        parts.append(_run(g, _ctx(chunk_edges=chunk_edges), k=4, seed=1))
        shapes.append([
            (e.attrs["coarse_n"], e.attrs["coarse_m"])
            for e in telemetry.events("stream")
        ])
    assert np.array_equal(parts[0], parts[1])
    assert np.array_equal(parts[0], parts[2])
    assert shapes[0] == shapes[1] == shapes[2]
    assert shapes[0], "no streamed levels recorded"


def test_kill_and_resume_mid_stream_is_cut_identical(tmp_path, monkeypatch):
    g = make_rgg2d(8192, avg_degree=8, seed=1)
    ref = _run(g, _ctx(), k=4, seed=1)
    ref_cut = host_partition_metrics(g, ref, 4)["cut"]

    ckpt_dir = str(tmp_path / "ckpt")
    monkeypatch.setenv(STOP_AT_ENV, "stream-coarsen:0!")
    killed_ctx = _ctx()
    killed_ctx.resilience.checkpoint_dir = ckpt_dir
    with pytest.raises(SimulatedPreemption):
        _run(g, killed_ctx, k=4, seed=1)
    monkeypatch.delenv(STOP_AT_ENV)
    assert os.path.exists(os.path.join(ckpt_dir, "manifest.json"))

    resume_ctx = _ctx()
    resume_ctx.resilience.checkpoint_dir = ckpt_dir
    resume_ctx.resilience.resume = True
    resumed = _run(g, resume_ctx, k=4, seed=1)
    cut = host_partition_metrics(g, resumed, 4)["cut"]
    assert cut == ref_cut
    ev = [e.attrs for e in telemetry.events("resume")
          if e.attrs.get("scheme") == "external"]
    assert ev and ev[-1]["levels_restored"] >= 1


def test_kill_during_incore_phase_keeps_pinned_stream_maps(
    tmp_path, monkeypatch
):
    g = make_rgg2d(8192, avg_degree=8, seed=1)
    ref = _run(g, _ctx(), k=4, seed=1)
    ref_cut = host_partition_metrics(g, ref, 4)["cut"]

    ckpt_dir = str(tmp_path / "ckpt")
    monkeypatch.setenv(STOP_AT_ENV, "initial!")
    killed_ctx = _ctx()
    killed_ctx.resilience.checkpoint_dir = ckpt_dir
    with pytest.raises(SimulatedPreemption):
        _run(g, killed_ctx, k=4, seed=1)
    monkeypatch.delenv(STOP_AT_ENV)
    # the stream-level snapshot is pinned past the deep barriers
    manifest = json.load(open(os.path.join(ckpt_dir, "manifest.json")))
    assert any(
        name.startswith("stream-level-") for name in manifest["snapshots"]
    ), sorted(manifest["snapshots"])

    resume_ctx = _ctx()
    resume_ctx.resilience.checkpoint_dir = ckpt_dir
    resume_ctx.resilience.resume = True
    resumed = _run(g, resume_ctx, k=4, seed=1)
    assert host_partition_metrics(g, resumed, 4)["cut"] == ref_cut


def test_tiny_budget_streams_and_fine_level_stays_off_device(monkeypatch):
    g = make_rgg2d(16384, avg_degree=8, seed=1)
    budget = int(mem.estimate_run_bytes(g.n, g.m, 4) * 0.25)
    monkeypatch.setenv(mem.ENV_BUDGET, str(budget))
    part = _run(g, _ctx(chunk_edges=1 << 14), k=4, seed=1)
    assert part.shape == (g.n,)
    gate = _gate()
    assert gate and gate["valid"]
    section = telemetry.run_info().get("external")
    assert section and section["enabled"]
    assert section["streamed_levels"] >= 1
    assert section["fine_device_resident_bytes"] == 0
    assert section["overlap_frac"] > 0
    assert section["chunks_total"] >= 1
    streams = telemetry.events("stream")
    assert streams, "no stream telemetry events"
    # the stream's chunk buffer is a fraction of the fine CSR it avoided
    assert section["fine_csr_bytes"] > 0
    lvl0 = section["levels"][0]
    assert lvl0["chunk_buffer_bytes"] < section["fine_csr_bytes"]


def test_generator_spec_end_to_end():
    spec = "gen:rgg2d;n=4096;avg_degree=8;seed=2"
    sg = chunkstore.StreamedSpecGraph(spec, target_edges=1 << 12)
    part = _run(sg, _ctx(chunk_edges=1 << 12), k=4, seed=1)
    assert part.shape == (sg.n,)
    gate = _gate()
    assert gate and gate["valid"]
    metrics = chunkstore.streamed_partition_metrics(sg, part, 4)
    assert metrics["cut"] >= 0 and metrics["imbalance"] <= 0.04


# ---------------------------------------------------------------------------
# rung-3 reroute + platform surfaces
# ---------------------------------------------------------------------------


def test_forced_rung3_streams_on_device(monkeypatch):
    """The memory ladder's rung 3 now routes through the streamed
    subsystem (the host-only numpy LP is its fallback)."""
    monkeypatch.setenv(mem.ENV_FORCE_RUNG, "3")
    monkeypatch.setenv(mem.ENV_BUDGET, str(6_000_000))
    g = make_rgg2d(8000, avg_degree=8, seed=3)
    ctx = create_context_by_preset_name("default")
    part = _run(g, ctx, k=8, seed=1)
    assert part.shape == (g.n,)
    gate = _gate()
    assert gate and gate["valid"]
    streams = telemetry.events("stream")
    assert streams and streams[-1].attrs["coarse_n"] < g.n


def test_rung3_demotes_to_host_lp_on_stream_failure(monkeypatch):
    """A non-OOM failure of the streamed subsystem degrades to the
    legacy host-chunked LP path with a `degraded` event."""
    monkeypatch.setenv(mem.ENV_FORCE_RUNG, "3")
    monkeypatch.setenv(mem.ENV_BUDGET, str(6_000_000))

    def boom(graph, ctx, facade=None):
        raise RuntimeError("stream subsystem unavailable")

    import kaminpar_tpu.external.driver as driver_mod

    monkeypatch.setattr(driver_mod, "external_partition", boom)
    g = make_rgg2d(2500, avg_degree=8, seed=3)
    ctx = create_context_by_preset_name("default")
    part = _run(g, ctx, k=8, seed=1)
    assert part.shape == (g.n,)
    gate = _gate()
    assert gate and gate["valid"]
    deg = [e.attrs for e in telemetry.events("degraded")
           if e.attrs.get("site") == "semi-external-stream"]
    assert deg, "no demotion event"
    assert telemetry.events("semi-external"), "legacy path never ran"


def test_serving_admission_prices_the_stream(monkeypatch):
    """External-scheme services admit graphs far over the in-core
    budget: the admission floor is the stream state, not the resident
    hierarchy."""
    n, m, k = 1 << 20, (1 << 20) * 16, 64
    budget = mem.min_streamable_bytes(n, k) * 2
    assert mem.min_serveable_bytes(n, m, k) > budget  # in-core refuses
    monkeypatch.setenv(mem.ENV_BUDGET, str(budget))
    from kaminpar_tpu.serving.service import PartitionRequest, PartitionService

    ext_ctx = create_context_by_preset_name("default")
    ext_ctx.partitioning.mode = PartitioningMode.EXTERNAL
    svc = PartitionService(ext_ctx)
    req = PartitionRequest(
        graph=f"gen:rmat;n={n};m={m};seed=1", k=k, request_id="big"
    )
    rejected = svc.submit(req)
    assert rejected is None, getattr(rejected, "reason", rejected)

    in_core = PartitionService(create_context_by_preset_name("default"))
    rej = in_core.submit(PartitionRequest(
        graph=f"gen:rmat;n={n};m={m};seed=1", k=k, request_id="big2"
    ))
    assert rej is not None and rej.reason == "insufficient-memory"


def test_external_report_section_is_schema_valid(monkeypatch):
    g = make_rgg2d(4096, avg_degree=8, seed=1)
    _run(g, _ctx(), k=4, seed=1)
    from kaminpar_tpu.telemetry.report import SCHEMA_PATH, build_run_report

    report = build_run_report()
    assert report["schema_version"] == 14
    assert report["external"]["enabled"] is True
    spec = importlib.util.spec_from_file_location(
        "check_report_schema",
        os.path.join(REPO, "scripts", "check_report_schema.py"),
    )
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)
    schema = json.load(open(SCHEMA_PATH))
    errors = checker.validate_instance(report, schema)
    errors += checker.version_checks(report)
    assert errors == [], errors


def test_incore_runs_carry_disabled_external_default():
    g = make_rgg2d(1024, avg_degree=8, seed=1)
    ctx = create_context_by_preset_name("default")
    _run(g, ctx, k=4, seed=1)
    from kaminpar_tpu.telemetry.report import build_run_report

    assert build_run_report()["external"] == {"enabled": False}
