"""Pallas kernel tests (interpret mode — numerical twin of the XLA path)."""

import numpy as np
import jax.numpy as jnp
import pytest

from kaminpar_tpu.ops.pallas_kernels import TILE_N, best_from_dense_pallas
from kaminpar_tpu.ops.segments import best_from_dense


@pytest.mark.parametrize("require_fit", [True, False])
@pytest.mark.parametrize("with_allowed", [False, True])
def test_best_from_dense_pallas_matches_xla(require_fit, with_allowed):
    rng = np.random.default_rng(0)
    n_pad, k = 2 * TILE_N, 8
    conn = jnp.asarray(rng.integers(0, 100, size=(n_pad, k)), dtype=jnp.int32)
    labels = jnp.asarray(rng.integers(0, k, size=n_pad), dtype=jnp.int32)
    cw = jnp.asarray(rng.integers(0, 50, size=k), dtype=jnp.int32)
    node_w = jnp.asarray(rng.integers(1, 5, size=n_pad), dtype=jnp.int32)
    cap = jnp.full((k,), 52, dtype=jnp.int32)
    allowed = (
        jnp.asarray(rng.integers(0, 2, size=k).astype(bool))
        if with_allowed
        else None
    )
    salt = jnp.int32(7)

    ref = best_from_dense(
        conn, labels, cw, node_w, cap, salt,
        require_fit=require_fit, allowed=allowed,
    )
    got = best_from_dense_pallas(
        conn, labels, cw, node_w, cap, salt,
        require_fit=require_fit, allowed=allowed, interpret=True,
    )
    for a, b, name in zip(ref, got, ("best", "best_w", "w_own")):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=name)
