"""Distributed resilience suite (docs/robustness.md, dist contract).

The acceptance checks of ISSUE 12: (a) full-hierarchy dist resume —
a run hard-killed at EVERY dist barrier kind and resumed must produce a
partition IDENTICAL to the uninterrupted run's (the dist pipeline is
rerun-deterministic, so cut-identical is array-identical here), and a
resume under a different device count must degrade to a logged clean
restart, never a wrong answer; (b) the cross-rank agreed OOM ladder —
a DeviceOOM injected on one rank walks every rank down the ladder
together (allgather-max agreement, unit-tested against a simulated
divergent fleet) and still ends gate-valid; (c) rank-scoped chaos +
divergence sentinels — `site@rank=K` fault addressing fires on rank K
only, and a simulated stage/rung skew at a barrier raises a structured
RankDivergence with the per-rank dump.
"""

import os

import numpy as np
import pytest

from kaminpar_tpu import resilience, telemetry
from kaminpar_tpu.graphs.factories import make_grid_graph, make_star
from kaminpar_tpu.parallel import dKaMinPar, make_mesh
from kaminpar_tpu.parallel.dist_context import (
    create_dist_context_by_preset_name,
)
from kaminpar_tpu.resilience import agreement, faults
from kaminpar_tpu.resilience import checkpoint as ckpt_mod
from kaminpar_tpu.resilience import memory as memory_mod
from kaminpar_tpu.resilience.checkpoint import SimulatedPreemption
from kaminpar_tpu.resilience.errors import DeviceOOM, RankDivergence

GRID = 32  # 1024 nodes, 3 dist levels under the test contraction limit
K = 4
CONTRACTION_LIMIT = 30


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for var in (ckpt_mod.STOP_AT_ENV, resilience.FAULTS_ENV_VAR,
                agreement.ENV_SIM_RANK, agreement.ENV_SIM_RANKS,
                memory_mod.ENV_FORCE_RUNG, memory_mod.ENV_BUDGET):
        monkeypatch.delenv(var, raising=False)
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    yield
    resilience.reset()
    telemetry.disable()
    telemetry.reset()


def _run(ckpt=None, resume=False, stop_at=None, n_devices=4, seed=1,
         gather=None):
    """One dist deep pipeline run with >= 2 coarsening levels.
    ``gather`` installs an allgather override AFTER the internal reset
    (resilience.reset clears any installed override)."""
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    if gather is not None:
        agreement.set_gather_override(gather)
    if stop_at is not None:
        os.environ[ckpt_mod.STOP_AT_ENV] = stop_at
    else:
        os.environ.pop(ckpt_mod.STOP_AT_ENV, None)
    ctx = create_dist_context_by_preset_name("default")
    ctx.shm.coarsening.contraction_limit = CONTRACTION_LIMIT
    # keep the subgroup-replication phase out of the way: these tests
    # exercise the main dist coarsen/initial/uncoarsen barrier lineage
    ctx.replication_min_nodes_per_device = 0
    if ckpt is not None:
        ctx.shm.resilience.checkpoint_dir = str(ckpt)
        ctx.shm.resilience.resume = resume
    g = make_grid_graph(GRID, GRID)
    solver = dKaMinPar(ctx, mesh=make_mesh(n_devices)).set_graph(g)
    try:
        part = solver.compute_partition(k=K, epsilon=0.03, seed=seed)
    finally:
        # the SimulatedPreemption raise path must not leak the hook
        # into later tests (monkeypatch.delenv would RESTORE it on
        # teardown, leaking it past this module)
        os.environ.pop(ckpt_mod.STOP_AT_ENV, None)
    return solver, g, part


@pytest.fixture(scope="module")
def baseline():
    """The uninterrupted run's partition (shared across the module)."""
    resilience.reset()
    telemetry.reset()
    telemetry.enable()
    try:
        _, _, part = _run()
        return np.asarray(part)
    finally:
        resilience.reset()
        telemetry.disable()
        telemetry.reset()


def _gate_valid() -> bool:
    gates = telemetry.events("output-gate")
    assert gates, "no output-gate event"
    return bool(gates[-1].attrs["valid"])


# ---------------------------------------------------------------------------
# (a) full-hierarchy dist resume
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "stop_at",
    ["dist-coarsen:1!", "dist-initial!", "dist-uncoarsen:1!",
     "dist-uncoarsen:0!"],
)
def test_dist_kill_and_resume_is_cut_identical(tmp_path, baseline, stop_at):
    """Hard-kill at each dist barrier KIND (coarsen / initial /
    uncoarsen, plus the finest uncoarsen level), resume, and demand the
    IDENTICAL partition — the dist pipeline is rerun-deterministic, so
    any divergence means the resume re-entered wrong."""
    d = tmp_path / "ckpt"
    with pytest.raises(SimulatedPreemption):
        _run(ckpt=d, stop_at=stop_at)
    _, _, part = _run(ckpt=d, resume=True)
    resumes = telemetry.events("resume")
    assert resumes, "resumed run recorded no resume event"
    stage = resumes[-1].attrs["stage"]
    assert stage == stop_at.rstrip("!").split(":")[0], stage
    assert _gate_valid()
    np.testing.assert_array_equal(np.asarray(part), baseline)


def test_dist_resume_finest_barrier_with_pending_extension(tmp_path):
    """Kill at dist-uncoarsen:0 while current_k < k (shallow hierarchy
    relative to k): the finest barrier's keep list has pruned EVERY
    level snapshot, so the resume restores a level-less state — it must
    still extend the RESTORED partition on the mesh (cut-identical to
    the uninterrupted run), never discard it into the shm fallback."""

    def _ext_run(ckpt=None, resume=False, stop_at=None):
        resilience.reset()
        telemetry.reset()
        telemetry.enable()
        if stop_at is not None:
            os.environ[ckpt_mod.STOP_AT_ENV] = stop_at
        ctx = create_dist_context_by_preset_name("default")
        # one dist level and compute_k_for_n(n) = 4 < k = 16: the
        # level-0 barrier records current_k=4 with k-extension pending
        ctx.shm.coarsening.contraction_limit = 300
        ctx.replication_min_nodes_per_device = 0
        if ckpt is not None:
            ctx.shm.resilience.checkpoint_dir = str(ckpt)
            ctx.shm.resilience.resume = resume
        g = make_grid_graph(GRID, GRID)
        solver = dKaMinPar(ctx, mesh=make_mesh(4)).set_graph(g)
        try:
            return solver.compute_partition(k=16, epsilon=0.03, seed=1)
        finally:
            os.environ.pop(ckpt_mod.STOP_AT_ENV, None)

    base = np.asarray(_ext_run())
    assert len(np.unique(base)) == 16  # the extension really ran
    d = tmp_path / "ckpt"
    with pytest.raises(SimulatedPreemption):
        _ext_run(ckpt=d, stop_at="dist-uncoarsen:0!")
    import json

    man = json.load(open(d / "manifest.json"))
    assert man["meta"]["current_k"] < 16  # extension was still pending
    part = np.asarray(_ext_run(ckpt=d, resume=True))
    assert telemetry.events("resume"), "restored nothing"
    np.testing.assert_array_equal(part, base)


def test_dist_resume_skips_completed_levels(tmp_path):
    """A resume at dist-initial must NOT re-run coarsening: no
    dist-coarsen barrier checkpoints are offered again (level snapshots
    are carried by reference, not rewritten)."""
    d = tmp_path / "ckpt"
    with pytest.raises(SimulatedPreemption):
        _run(ckpt=d, stop_at="dist-initial!")
    _, _, _ = _run(ckpt=d, resume=True)
    ckpt_events = [
        e.attrs for e in telemetry.events("checkpoint")
        if e.attrs.get("stage") == "dist-coarsen"
    ]
    assert ckpt_events == [], ckpt_events
    resumed = telemetry.events("resume")[-1].attrs
    assert resumed["levels_restored"] >= 2


def test_dist_resume_under_different_device_count_restarts_clean(
    tmp_path, baseline
):
    """The per-rank shard-fingerprint vector detects a device-count
    change: the resume degrades to a LOGGED clean restart (never a
    wrong answer) and the run completes gate-valid."""
    d = tmp_path / "ckpt"
    with pytest.raises(SimulatedPreemption):
        _run(ckpt=d, stop_at="dist-coarsen:1!", n_devices=4)
    _, g, part = _run(ckpt=d, resume=True, n_devices=2)
    restarts = [
        e.attrs for e in telemetry.events("checkpoint")
        if e.attrs.get("action") == "clean-restart"
    ]
    assert restarts and "shard fingerprints" in restarts[-1]["error"]
    assert not telemetry.events("resume")  # nothing was resumed
    assert _gate_valid()
    assert part.shape == (g.n,)


def test_dist_checkpoint_meta_carries_shard_vector(tmp_path):
    """Every dist barrier's manifest meta records the per-rank shard
    fingerprints + the full hierarchy depth (the keep-list prunes
    consumed levels, but per-level seeds must survive)."""
    import json

    d = tmp_path / "ckpt"
    with pytest.raises(SimulatedPreemption):
        _run(ckpt=d, stop_at="dist-uncoarsen:1!")
    man = json.load(open(d / "manifest.json"))
    assert man["scheme"] == "dist"
    assert man["stage"] == "dist-uncoarsen"
    meta = man["meta"]
    assert len(meta["shards"]) == 4  # one fingerprint per device
    assert meta["num_levels"] >= 2
    assert meta["current_k"] >= 2
    # hierarchy levels are serialized once, by reference
    snaps = set(man["snapshots"])
    assert any(s.startswith("dist-level-") for s in snaps)
    assert "state" in snaps


# ---------------------------------------------------------------------------
# (b) cross-rank agreed OOM ladder
# ---------------------------------------------------------------------------


def test_agree_max_adopts_fleet_maximum():
    """allgather-max agreement against a simulated divergent fleet:
    the local rank proposes 1, the (simulated) peer proposes 2 — both
    adopt 2, and the peer is named the triggering rank."""
    agreement.set_gather_override(
        lambda row: np.stack([row, row + 1])
    )
    try:
        agreed, trig = agreement.agree_max(1)
        assert (agreed, trig) == (2, 1)
        agreed, trig = memory_mod.agree_rung(1)
        assert (agreed, trig) == (2, 1)
    finally:
        agreement.set_gather_override(None)


def test_one_rank_oom_walks_all_ranks_down_the_ladder(baseline):
    """`device-oom@rank=0:nth=1`: the single injected OOM engages the
    agreed ladder (rung 1, tight pads), the degraded event names the
    triggering rank, and the run ends gate-valid — with a cut identical
    to baseline is NOT required (tight pads re-bucket), but the result
    must be complete and valid."""
    os.environ[resilience.FAULTS_ENV_VAR] = "device-oom@rank=0:nth=1"
    try:
        _, g, part = _run()
    finally:
        os.environ.pop(resilience.FAULTS_ENV_VAR, None)
    deg = [
        e.attrs for e in telemetry.events("degraded")
        if e.attrs["site"] == "device-oom"
    ]
    assert deg and deg[-1]["rung"] == 1
    assert deg[-1]["triggering_rank"] == 0
    assert deg[-1]["injected"] is True
    st = memory_mod.state()
    assert st is not None and st.rung == 1 and st.engaged
    assert _gate_valid()
    assert part.shape == (g.n,)


def test_peer_rung_proposal_raises_local_rung():
    """A (simulated) peer proposing a higher rung pulls the local rank
    up past its own proposal — the agreement half of 'all ranks land on
    the same rung'."""
    calls = {"n": 0}

    def peer_two_rungs_up(row):
        calls["n"] += 1
        return np.stack([row, row + 2])

    agreement.set_gather_override(peer_two_rungs_up)
    try:
        agreed, trig = memory_mod.agree_rung(1)
    finally:
        agreement.set_gather_override(None)
    assert calls["n"] == 1
    assert agreed == 3 and trig == 1


def test_dist_forced_rung2_spills_and_reloads_cut_identical(baseline):
    """KAMINPAR_TPU_MEM_RUNG=2: the host-spilled shard hierarchy —
    per-level DistGraphs dropped at the barriers and rebuilt on demand
    during uncoarsening.  memory-spill AND memory-reload events must be
    present, and because the rebuild is deterministic the partition is
    IDENTICAL to the normal run's under the same pad policy... which
    rung 2 changes (tight pads), so the assertion here is validity +
    spill/reload accounting, with the cut-identity of spill/reload
    itself covered by the resume suite (same rebuild path)."""
    os.environ[memory_mod.ENV_FORCE_RUNG] = "2"
    try:
        _, g, part = _run()
    finally:
        os.environ.pop(memory_mod.ENV_FORCE_RUNG, None)
    spills = telemetry.events("memory-spill")
    reloads = telemetry.events("memory-reload")
    assert spills, "rung-2 dist run spilled nothing"
    assert reloads, "rung-2 dist run reloaded nothing"
    st = memory_mod.state()
    assert st is not None and st.spills >= 1 and st.reloads >= 1
    assert _gate_valid()
    assert part.shape == (g.n,)


def test_dist_ladder_host_only_rung(baseline):
    """The dist ladder's last rung (host-only recursive bisection) is
    reachable and gate-valid — the forced shm-only rung 3 maps onto it
    (DIST_RUNG_ORDER skips semi-external)."""
    os.environ[memory_mod.ENV_FORCE_RUNG] = "3"
    try:
        _, g, part = _run()
    finally:
        os.environ.pop(memory_mod.ENV_FORCE_RUNG, None)
    assert telemetry.events("host-only-partition")
    assert _gate_valid()
    assert part.shape == (g.n,)


# ---------------------------------------------------------------------------
# (c) rank-scoped chaos addressing
# ---------------------------------------------------------------------------


def test_parse_plan_rank_scoped():
    rules = faults.parse_plan(
        "device-oom@rank=1:nth=2,refiner:0.5,all@rank=0"
    )
    assert rules[0].site == "device-oom"
    assert rules[0].rank == 1 and rules[0].nth == 2
    assert rules[1].rank is None
    assert rules[2].site == "all" and rules[2].rank == 0


@pytest.mark.parametrize(
    "bad",
    ["device-oom@rk=1:nth=1", "device-oom@rank=x", "device-oom@rank=-1",
     "nosite@rank=0"],
)
def test_parse_plan_rank_scoped_rejects(bad):
    with pytest.raises(faults.FaultPlanError):
        faults.parse_plan(bad)


def test_rank_scoped_injection_fires_on_matching_rank_only(monkeypatch):
    monkeypatch.setenv(resilience.FAULTS_ENV_VAR, "refiner@rank=1:nth=1")
    # this process is rank 0: the rule is inert
    faults.maybe_inject("refiner")
    assert faults.injected_log() == []
    # impersonate rank 1 (the SIM override): the next matching call
    # fires — the per-site counter kept advancing, so re-arm nth
    faults.reset()
    monkeypatch.setenv(resilience.FAULTS_ENV_VAR, "refiner@rank=1:nth=1")
    monkeypatch.setenv(agreement.ENV_SIM_RANK, "1")
    with pytest.raises(DeviceOOM) as ei:
        faults.maybe_inject("refiner")
    assert ei.value.injected
    assert faults.injected_log() == [
        {"site": "refiner", "call": 1, "rank": 1}
    ]


def test_rank_scoped_fault_inert_on_dist_run(baseline):
    """A dist pipeline run with `device-oom@rank=1:nth=1` on a rank-0
    process must inject NOTHING — no degraded events, ladder never
    engages, partition identical to baseline."""
    os.environ[resilience.FAULTS_ENV_VAR] = "device-oom@rank=1:nth=1"
    try:
        _, _, part = _run()
    finally:
        os.environ.pop(resilience.FAULTS_ENV_VAR, None)
    assert telemetry.events("degraded") == []
    st = memory_mod.state()
    assert st is None or st.rung == 0
    np.testing.assert_array_equal(np.asarray(part), baseline)


# ---------------------------------------------------------------------------
# (c) divergence sentinels
# ---------------------------------------------------------------------------


def test_divergence_sentinel_fires_on_stage_skew():
    """A simulated fleet where rank 1 reports a different stage hash at
    the first dist barrier: the sentinel converts the silent skew into
    a structured RankDivergence with the per-rank dump."""
    try:
        with pytest.raises(RankDivergence) as ei:
            _run(gather=lambda row: np.stack(
                [row, row + np.array([1, 0, 0])]
            ))
    finally:
        agreement.set_gather_override(None)
    err = ei.value
    assert len(err.ranks) == 2
    assert err.site == "rank-divergence"
    events = telemetry.events("rank-divergence")
    assert events and events[-1].attrs["fields"] == ["stage"]
    # the per-rank dump was annotated into the report state BEFORE the
    # raise, so even an emergency report carries it
    from kaminpar_tpu.telemetry.report import build_run_report

    report = build_run_report()
    sect = report["dist_resilience"]
    assert sect["enabled"] and sect["divergence"]["fields"] == ["stage"]
    assert len(sect["divergence"]["ranks"]) == 2


def test_divergence_sentinel_fires_on_rung_skew():
    try:
        with pytest.raises(RankDivergence):
            _run(gather=lambda row: np.stack(
                [row, row + np.array([0, 2, 0])]
            ))
    finally:
        agreement.set_gather_override(None)
    assert telemetry.events("rank-divergence")[-1].attrs["fields"] == [
        "rung"
    ]


def test_divergence_sentinel_injected_site():
    """The registered `rank-divergence` chaos site exercises the abort
    path without a skewed fleet."""
    os.environ[resilience.FAULTS_ENV_VAR] = "rank-divergence:nth=1"
    try:
        with pytest.raises(RankDivergence) as ei:
            _run()
    finally:
        os.environ.pop(resilience.FAULTS_ENV_VAR, None)
    assert ei.value.injected


def test_sentinel_audits_counted_in_report(baseline):
    """A clean dist run audits every barrier and reports the count in
    the dist_resilience section (single rank: trivially agreeing)."""
    solver, _, _ = _run()
    from kaminpar_tpu.telemetry.report import build_run_report

    report = build_run_report()
    sect = report["dist_resilience"]
    assert sect["enabled"]
    assert sect["audits"] >= 4  # >= 2 coarsen + initial + uncoarsens
    assert sect["ranks"] == 1 and sect["rank"] == 0
    assert len(sect["shard_fingerprints"]) == 4
    assert sect["ladder"] == {"agreed": True, "rung": 0}


# ---------------------------------------------------------------------------
# sharding-plan pricing (the preflight satellite)
# ---------------------------------------------------------------------------


def test_shard_sizes_price_the_heaviest_shard():
    """A star graph concentrates the hub's edges in shard 0: the
    sharding plan's m_loc must cover the ACTUAL heaviest shard, which
    the uniform ceil(m/D) estimate undercounts."""
    from kaminpar_tpu.parallel.dist_graph import shard_sizes

    g = make_star(1 << 10)  # hub + 1024 leaves, hub row holds half of m
    xadj = np.asarray(g.xadj, dtype=np.int64)
    D = 4
    n_loc, m_loc, counts = shard_sizes(xadj, D)
    assert sum(counts) == int(g.m)
    assert max(counts) > -(-int(g.m) // D)  # skew: heaviest > uniform
    assert m_loc >= max(counts)


def test_shard_fingerprints_detect_device_count_and_graph():
    from kaminpar_tpu.parallel.dist_graph import shard_fingerprints

    g = make_grid_graph(16, 16)
    fp4 = shard_fingerprints(g, 4)
    assert len(fp4) == 4 and len(set(fp4)) > 1
    assert shard_fingerprints(g, 4) == fp4  # deterministic
    assert len(shard_fingerprints(g, 2)) == 2
    g2 = make_grid_graph(16, 17)
    assert shard_fingerprints(g2, 4) != fp4


def test_preflight_refuses_on_shard_estimate(monkeypatch):
    """preflight prices the given (per-shard) shape against the budget
    and refuses with a ladder-retryable DeviceOOM before any upload."""
    from kaminpar_tpu.resilience.runstate import current

    st = memory_mod.GovernorState()
    st.budget = 1  # nothing fits one byte
    current().memory = st
    try:
        with pytest.raises(DeviceOOM) as ei:
            memory_mod.preflight(1 << 16, 1 << 20, 8, where="dist")
        assert not ei.value.rungs_exhausted
        assert "preflight@dist" in str(ei.value)
    finally:
        current().memory = None
