"""64-bit weight build (KAMINPAR_TPU_64BIT=1, kaminpar_tpu/dtypes.py).

The analog of the reference's KAMINPAR_64BIT_[NODE|EDGE]WEIGHTS CMake
options (CMakeLists.txt:67-75).  The flag must be set before first
import, so the regression runs in a subprocess: a graph whose TOTAL EDGE
WEIGHT exceeds 2^31 — arithmetically impossible to partition correctly
in the int32 build — must partition feasibly with the device cut
matching an independent int64 numpy recomputation.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SCRIPT = r"""
import json
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

from kaminpar_tpu.dtypes import ACC_DTYPE, X64_WEIGHTS
assert X64_WEIGHTS
import jax.numpy as jnp
assert ACC_DTYPE == jnp.int64

from kaminpar_tpu.graphs.factories import make_rmat
from kaminpar_tpu.graphs.host import HostGraph, host_partition_metrics
from kaminpar_tpu.kaminpar import KaMinPar
from kaminpar_tpu.utils.logger import OutputLevel

base = make_rmat(1 << 11, 20_000, seed=29)
# heavy edge weights: total edge weight ~ 40e3 * 2^17 * 2 ≈ 10.5e9 > 2^31
rng = np.random.default_rng(1)
m = base.m
ew = rng.integers(1 << 15, 1 << 17, m).astype(np.int64)
# symmetrize: weight must match for both directions of an edge
src = base.edge_sources()
key = np.minimum(src, base.adjncy).astype(np.int64) * (1 << 32) + np.maximum(
    src, base.adjncy
)
order = np.argsort(key, kind="stable")
ew_sym = np.empty_like(ew)
ew_pairs = ew[order].reshape(-1, 2)
ew_pairs[:, 1] = ew_pairs[:, 0]
ew_sym[order] = ew_pairs.reshape(-1)
g = HostGraph(xadj=base.xadj, adjncy=base.adjncy, edge_weights=ew_sym)
total_ew = int(ew_sym.sum())
assert total_ew > 2**31, total_ew

p = KaMinPar("default")
p.set_output_level(OutputLevel.QUIET)
part = p.set_graph(g).compute_partition(k=4, epsilon=0.03, seed=1)
res = host_partition_metrics(g, part, 4)

# distributed smoke under the flag: the dist graph buffers must hold
# int64 weights (they silently wrapped before the plumbing)
from kaminpar_tpu.parallel import dKaMinPar
dp = dKaMinPar(n_devices=2)
dp.set_output_level(OutputLevel.QUIET)
dpart = dp.set_graph(g).compute_partition(k=4, epsilon=0.03, seed=1)
dres = host_partition_metrics(g, np.asarray(dpart), 4)
print(json.dumps({
    "cut": int(res["cut"]),
    "imbalance": float(res["imbalance"]),
    "dist_cut": int(dres["cut"]),
    "dist_imbalance": float(dres["imbalance"]),
    "total_edge_weight": total_ew,
}))
"""


def test_64bit_build_partitions_graph_with_overflowing_edge_weights():
    env = dict(os.environ)
    env["KAMINPAR_TPU_64BIT"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    out = subprocess.run(
        [sys.executable, "-c", _SCRIPT],
        capture_output=True,
        text=True,
        env=env,
        cwd=REPO,
        timeout=1200,
    )
    assert out.returncode == 0, out.stderr[-4000:]
    line = out.stdout.strip().splitlines()[-1]
    res = json.loads(line)
    assert res["total_edge_weight"] > 2**31
    assert res["imbalance"] <= 0.03 + 1e-9
    # sane cut: positive, below total edge weight / 2
    assert 0 < res["cut"] < res["total_edge_weight"] // 2
    assert 0 < res["dist_cut"] < res["total_edge_weight"] // 2
    assert res["dist_imbalance"] <= 0.03 + 1e-9
