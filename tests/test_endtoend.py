"""End-to-end partition tests.

Analog of tests/endtoend/shm_endtoend_test.cc:28-80: partitions empty,
unweighted, and weighted graphs plus the checked-in real graph; asserts cut
quality, feasibility, and rerun determinism.
"""

import numpy as np
import pytest

import kaminpar_tpu as ktp
from kaminpar_tpu.context import PartitioningMode
from kaminpar_tpu.graphs import factories


def _cut(g, part):
    src = g.edge_sources()
    ew = g.edge_weight_array()
    return int(ew[part[src] != part[g.adjncy]].sum()) // 2


def _check(g, part, ctx, k):
    assert len(part) == g.n
    assert part.min() >= 0 and part.max() < k
    bw = np.zeros(k, dtype=np.int64)
    np.add.at(bw, part, g.node_weight_array())
    assert (bw <= ctx.partition.max_block_weights).all(), (
        bw,
        ctx.partition.max_block_weights,
    )


@pytest.mark.parametrize("mode", [PartitioningMode.DEEP, PartitioningMode.KWAY])
def test_rgg2d_partition(rgg2d, mode):
    ctx = ktp.context_from_preset("default")
    ctx.partitioning.mode = mode
    p = ktp.KaMinPar(ctx).set_graph(rgg2d)
    part = p.compute_partition(k=4, epsilon=0.03, seed=1)
    _check(rgg2d, part, ctx, 4)
    # sane quality: random 4-way cut on rgg2d is ~6100; multilevel < 150
    assert _cut(rgg2d, part) < 200


def test_determinism(rgg2d):
    ctx = ktp.context_from_preset("default")
    parts = [
        ktp.KaMinPar(ctx).set_graph(rgg2d).compute_partition(k=4, seed=7)
        for _ in range(2)
    ]
    assert np.array_equal(parts[0], parts[1])


def test_weighted_graph():
    g = factories.make_grid_graph(12, 12)
    rng = np.random.default_rng(5)
    g.node_weights = rng.integers(1, 5, g.n).astype(np.int64)
    g.edge_weights = None
    ctx = ktp.context_from_preset("default")
    p = ktp.KaMinPar(ctx).set_graph(g)
    part = p.compute_partition(k=3, epsilon=0.05, seed=2)
    _check(g, part, ctx, 3)


def test_graph_with_isolated_nodes():
    # grid + isolated tail
    g = factories.make_grid_graph(6, 6)
    n = g.n + 4
    xadj = np.concatenate([g.xadj, np.full(4, g.m)])
    g2 = ktp.HostGraph(xadj, g.adjncy)
    ctx = ktp.context_from_preset("default")
    part = ktp.KaMinPar(ctx).set_graph(g2).compute_partition(k=2, seed=1)
    _check(g2, part, ctx, 2)


def test_only_isolated_nodes():
    g = factories.make_empty_graph(10)
    ctx = ktp.context_from_preset("default")
    part = ktp.KaMinPar(ctx).set_graph(g).compute_partition(k=3, seed=1)
    _check(g, part, ctx, 3)


def test_k1():
    g = factories.make_grid_graph(4, 4)
    ctx = ktp.context_from_preset("default")
    part = ktp.KaMinPar(ctx).set_graph(g).compute_partition(k=1, seed=1)
    assert (part == 0).all()


def test_nonpow2_k(rgg2d):
    ctx = ktp.context_from_preset("default")
    part = ktp.KaMinPar(ctx).set_graph(rgg2d).compute_partition(k=6, seed=4)
    _check(rgg2d, part, ctx, 6)
    assert len(np.unique(part)) == 6


def test_infeasible_raises():
    g = factories.make_grid_graph(4, 4)
    ctx = ktp.context_from_preset("default")
    p = ktp.KaMinPar(ctx).set_graph(g)
    with pytest.raises(ValueError):
        p.compute_partition(k=2, max_block_weights=np.array([4, 4]))


def test_deep_with_device_bipartition_extension():
    """Large-block extension through the device bipartition path
    (helper.cc:220 analog): force the threshold low so every extension
    uses it; results must stay feasible with a sane cut."""
    from kaminpar_tpu.graphs.factories import make_grid_graph
    from kaminpar_tpu.kaminpar import KaMinPar

    g = make_grid_graph(40, 40)
    p = KaMinPar("default")
    p.ctx.partitioning.device_bipartition_threshold = 64
    part = p.set_graph(g).compute_partition(k=8, epsilon=0.03, seed=3)
    nw = g.node_weight_array()
    bw = np.zeros(8, np.int64)
    np.add.at(bw, part, nw)
    cap = int((1 + 0.03) * np.ceil(nw.sum() / 8)) + int(nw.max())
    assert bw.max() <= cap
    src = g.edge_sources()
    cut = int((part[src] != part[g.adjncy]).sum()) // 2
    # grid 40x40 into 8 blocks: a sane cut is well under 400
    assert cut < 400
