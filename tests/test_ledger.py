"""Execution ledger (telemetry/ledger.py): launch counts pinned against
a hand-counted round loop, the launch-honest bytes join (2x rounds =>
2x ledger bytes while the compile-time figure stays flat), transfer
metering at the chokepoints, the donation audit on a crafted donated
jit, the supervised-worker marshal, the schema-v13 report section with
its v12 fixture pin, and the standing dormancy contract
(KAMINPAR_TPU_LEDGER=0 => bitwise-identical jaxprs, every hook a noop).
"""

import functools
import importlib.util
import json
import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kaminpar_tpu import telemetry
from kaminpar_tpu.telemetry import ledger
from kaminpar_tpu.utils.timer import scoped_timer

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_report_schema",
        os.path.join(_REPO, "scripts", "check_report_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


# ---------------------------------------------------------------------------
# dormancy contract
# ---------------------------------------------------------------------------


def test_ledger_has_zero_jaxpr_impact(monkeypatch):
    """The standing dormancy pin: every ledger hook is host-side
    (dispatch boundaries, host pulls, compile results), so the traced
    jaxpr is bitwise identical whether the ledger is on, killed via
    KAMINPAR_TPU_LEDGER=0, or telemetry is off entirely."""

    def jaxpr_of_probe():
        def probe(x):
            return jnp.cumsum(x) * 2 + jnp.sum(x)

        return str(jax.make_jaxpr(probe)(jnp.arange(64, dtype=jnp.int32)))

    monkeypatch.setenv("KAMINPAR_TPU_PROGRESS", "0")
    telemetry.disable()
    j_off = jaxpr_of_probe()

    telemetry.enable()
    monkeypatch.setenv(ledger.ENV_VAR, "0")
    assert not ledger.enabled()
    j_killed = jaxpr_of_probe()

    monkeypatch.delenv(ledger.ENV_VAR)
    assert ledger.enabled()
    j_on = jaxpr_of_probe()

    assert j_off == j_killed == j_on


def test_disabled_every_entry_point_is_noop(monkeypatch):
    telemetry.enable()
    monkeypatch.setenv(ledger.ENV_VAR, "0")
    ledger.transfer("h2d", 4096, kind="csr-upload")
    assert ledger.donation_begin((jnp.zeros(4),), kind="x") is None
    assert ledger.donation_end(None) is None
    assert ledger.marshal_summary() is None
    snap = ledger.snapshot()
    assert snap["enabled"] is False
    assert snap["transfers"]["totals"]["h2d_bytes"] == 0
    assert snap["launches"] == {}


# ---------------------------------------------------------------------------
# launch ledger
# ---------------------------------------------------------------------------


def test_launch_counts_match_hand_counted_loop():
    """Five warm dispatches of one executable inside a scope are five
    ledger launches, all costed (the fastpath gate routes warm calls
    through the Python dispatch path while the ledger is armed)."""
    telemetry.enable()

    @jax.jit
    def round_fn(x):
        return x * 2 + 1

    x = jnp.arange(1024, dtype=jnp.int32)
    ledger.reset()
    with scoped_timer("ledger-harness"):
        for _ in range(5):
            x = round_fn(x)
        x.block_until_ready()

    totals = ledger.launch_totals()
    assert totals["ledger-harness"]["launches"] == 5
    assert totals["ledger-harness"]["uncosted"] == 0
    assert totals["ledger-harness"]["bytes"] > 0


def test_ledger_bytes_scale_with_rounds_compile_stays_flat():
    """The acceptance pin: 2x rounds => 2x ledger bytes for the scope,
    while the compile-time cost registry does not grow (no recompile —
    the extra bytes come from the launch join, not from XLA)."""
    telemetry.enable()

    @jax.jit
    def round_fn(x):
        return x * 3 - 1

    warm = round_fn(jnp.arange(512, dtype=jnp.int32))
    warm.block_until_ready()

    def run(rounds):
        ledger.reset()
        x = jnp.arange(512, dtype=jnp.int32)
        with scoped_timer("coarsening"):
            with scoped_timer("lp"):
                for _ in range(rounds):
                    x = round_fn(x)
                x.block_until_ready()
        snap = ledger.snapshot()
        entry = snap["launches"]["coarsening.lp"]
        return entry, snap["totals"]["costed_executables"]

    two, costed_after_two = run(2)
    four, costed_after_four = run(4)

    assert two["launches"] == 2 and four["launches"] == 4
    assert two["uncosted_launches"] == four["uncosted_launches"] == 0
    assert two["bytes"] > 0
    assert four["bytes"] == pytest.approx(2 * two["bytes"])
    assert four["flops"] == pytest.approx(2 * two["flops"])
    # compile-time figure flat: the warm executable was registered once
    assert costed_after_four == costed_after_two


def test_lp_chunked_rounds_are_hand_counted(monkeypatch):
    """Integration: force the chunked LP clustering path (one round per
    launch) and pin the ledger's count of the round executable against
    a hand count of the round-launch calls; the per-round convergence
    readback shows up as the scope's stat-pull d2h rows."""
    import kaminpar_tpu.ops.lp as lp_mod
    import kaminpar_tpu.ops.segments as seg_mod
    from kaminpar_tpu.graphs import device_graph_from_host, factories
    from kaminpar_tpu.ops.lp import lp_cluster

    telemetry.enable()
    monkeypatch.setattr(seg_mod, "MAX_FUSED_EDGE_SLOTS", 512)
    g = device_graph_from_host(factories.make_rmat(1 << 9, 4_000, seed=3))

    calls = []
    real = lp_mod._lp_cluster_round_launch
    monkeypatch.setattr(
        lp_mod, "_lp_cluster_round_launch",
        lambda *a, **kw: (calls.append(1), real(*a, **kw))[1],
    )
    ledger.reset()
    with scoped_timer("coarsening"):
        with scoped_timer("lp"):
            np.asarray(lp_cluster(g, jnp.int32(40), jnp.int32(4)))

    assert calls, "chunked clustering path never ran"
    snap = ledger.snapshot()
    entry = snap["launches"]["coarsening.lp"]
    round_counts = [
        c for name, c in entry["executables"].items()
        if "lp_cluster_round" in name
    ]
    assert round_counts == [len(calls)]
    assert entry["uncosted_launches"] == 0
    pulls = [
        r for r in snap["transfers"]["rows"]
        if r["scope"] == "coarsening.lp" and r["kind"] == "stat-pull"
    ]
    assert len(pulls) == 1 and pulls[0]["count"] == len(calls)


# ---------------------------------------------------------------------------
# transfer ledger
# ---------------------------------------------------------------------------


def test_transfer_totals_match_known_sequence():
    telemetry.enable()
    ledger.reset()
    with scoped_timer("partitioning"):
        with scoped_timer("device-upload"):
            ledger.transfer("h2d", 1000, kind="csr-upload")
            ledger.transfer("h2d", 24, kind="csr-upload")
        with scoped_timer("uncoarsening"):
            ledger.transfer("d2h", 8, kind="stat-pull")
            ledger.transfer("d2h", 8, kind="stat-pull")
            ledger.transfer("d2h", 512, kind="checkpoint-spill")
    # ignored: bad direction, zero and negative sizes, unintelligible
    ledger.transfer("sideways", 64, kind="x")
    ledger.transfer("h2d", 0, kind="x")
    ledger.transfer("d2h", -5, kind="x")
    ledger.transfer("d2h", "many", kind="x")

    t = ledger.snapshot()["transfers"]
    assert t["totals"] == {
        "h2d_bytes": 1024, "d2h_bytes": 528, "h2d_count": 2,
        "d2h_count": 3,
    }
    by_kind = {(r["scope"], r["direction"], r["kind"]): r for r in t["rows"]}
    up = by_kind[("partitioning.device-upload", "h2d", "csr-upload")]
    assert up["bytes"] == 1024 and up["count"] == 2
    pull = by_kind[("partitioning.uncoarsening", "d2h", "stat-pull")]
    assert pull["bytes"] == 16 and pull["count"] == 2
    # rows sorted by descending bytes
    assert [r["bytes"] for r in t["rows"]] == sorted(
        (r["bytes"] for r in t["rows"]), reverse=True
    )
    # phase rollup: first two dotted segments
    assert t["by_phase"]["partitioning.device-upload"]["h2d_bytes"] == 1024
    assert t["by_phase"]["partitioning.uncoarsening"]["d2h_bytes"] == 528


def test_device_upload_chokepoint_meters_h2d():
    from kaminpar_tpu.graphs import device_graph_from_host, factories

    telemetry.enable()
    ledger.reset()
    with scoped_timer("partitioning"):
        with scoped_timer("device-upload"):
            g = device_graph_from_host(factories.make_grid_graph(8, 8))
    assert g is not None
    t = ledger.snapshot()["transfers"]
    uploads = [
        r for r in t["rows"]
        if r["direction"] == "h2d" and "upload" in r["kind"]
    ]
    assert uploads and sum(r["bytes"] for r in uploads) > 0


def test_transfer_events_render_as_chrome_counter_track(tmp_path):
    from kaminpar_tpu.telemetry.chrome_trace import write_chrome_trace

    telemetry.enable()
    ledger.reset()
    ledger.transfer("h2d", 100, kind="csr-upload")
    ledger.transfer("d2h", 40, kind="stat-pull")
    ledger.transfer("h2d", 60, kind="chunk-upload")

    out = tmp_path / "run.trace.json"
    write_chrome_trace(str(out))
    trace = json.loads(out.read_text())
    counters = [
        e for e in trace["traceEvents"]
        if e["ph"] == "C" and e["name"] == "transfer-bytes"
    ]
    assert len(counters) == 3
    assert [c["args"]["h2d_total"] for c in counters] == [100, 100, 160]
    assert [c["args"]["d2h_total"] for c in counters] == [0, 40, 40]
    # cumulative => monotone: a Perfetto counter track needs no
    # re-aggregation
    assert counters == sorted(counters, key=lambda c: c["ts"])


# ---------------------------------------------------------------------------
# donation audit
# ---------------------------------------------------------------------------


def test_donation_honored_on_donated_jit():
    telemetry.enable()
    ledger.reset()

    @functools.partial(jax.jit, donate_argnums=(0,))
    def bump(x):
        return x + 1

    x = jnp.arange(2048, dtype=jnp.int32)
    x.block_until_ready()
    nbytes = int(x.nbytes)
    with scoped_timer("coarsening"):
        with scoped_timer("lp"):
            tok = ledger.donation_begin((x,), kind="lp-round")
            y = bump(x)
            y.block_until_ready()
            audit = ledger.donation_end(tok)
    assert audit == {"requested": 1, "honored": 1, "bytes_saved": nbytes}
    don = ledger.snapshot()["donation"]["coarsening.lp"]
    assert don["requested"] == 1 and don["honored"] == 1
    assert don["bytes_saved"] == nbytes == don["requested_bytes"]


def test_donation_declined_without_donate_argnums():
    telemetry.enable()
    ledger.reset()

    @jax.jit
    def keep(x):
        return x + 1

    x = jnp.arange(2048, dtype=jnp.int32)
    x.block_until_ready()
    tok = ledger.donation_begin((x,), kind="lp-round")
    y = keep(x)
    y.block_until_ready()
    audit = ledger.donation_end(tok)
    assert audit == {"requested": 1, "honored": 0, "bytes_saved": 0}
    # the undonated input is still alive and readable
    assert int(x[0]) == 0


def test_compile_side_alias_metadata_is_parsed():
    """register_executable's input_output_alias parse — the compile-time
    half of the audit — sees the donated parameter."""
    telemetry.enable()

    @functools.partial(jax.jit, donate_argnums=(0,))
    def bump(x):
        return x * 2

    lowered = bump.lower(jnp.arange(256, dtype=jnp.float32))
    exe = lowered.compile()
    runtime_exe = getattr(exe, "runtime_executable", lambda: None)()
    target = runtime_exe if runtime_exe is not None else exe
    assert ledger._parse_donated_params(target) >= 1


# ---------------------------------------------------------------------------
# supervised-worker marshal
# ---------------------------------------------------------------------------


def test_marshal_summary_pickles_and_absorbs_transfers_only():
    telemetry.enable()

    @jax.jit
    def f(x):
        return x + 1

    ledger.reset()
    with scoped_timer("worker"):
        f(jnp.arange(64, dtype=jnp.int32)).block_until_ready()
        ledger.transfer("h2d", 300, kind="csr-upload")
        ledger.transfer("d2h", 70, kind="stat-pull")

    summary = ledger.marshal_summary()
    assert summary["launches"] >= 1
    assert summary["h2d_bytes"] == 300 and summary["d2h_bytes"] == 70
    # rides a multiprocessing reply: must pickle cleanly
    wire = pickle.loads(pickle.dumps(summary))
    assert wire == summary

    # parent side: transfer totals fold in under the current scope,
    # launch counts deliberately do not (they cannot join per-scope
    # costs across the process boundary, and a fake uncosted entry
    # would poison the parent's honest stamps)
    ledger.reset()
    with scoped_timer("serving"):
        with scoped_timer("request"):
            ledger.absorb(wire)
    snap = ledger.snapshot()
    assert snap["totals"]["launches"] == 0
    t = snap["transfers"]
    assert t["totals"]["h2d_bytes"] == 300
    assert t["totals"]["d2h_bytes"] == 70
    kinds = {(r["direction"], r["kind"]) for r in t["rows"]}
    assert kinds == {("h2d", "worker"), ("d2h", "worker")}
    assert all(r["scope"] == "serving.request" for r in t["rows"])


def test_absorb_tolerates_missing_and_none():
    telemetry.enable()
    ledger.reset()
    ledger.absorb(None)
    ledger.absorb({})
    ledger.absorb({"launches": 3})  # no byte keys — nothing to fold
    totals = ledger.snapshot()["transfers"]["totals"]
    assert totals["h2d_bytes"] == 0 and totals["d2h_bytes"] == 0


# ---------------------------------------------------------------------------
# schema v13 report section (+ v12 fixture pin)
# ---------------------------------------------------------------------------


def test_report_carries_v13_ledger_section():
    import kaminpar_tpu as ktp
    from kaminpar_tpu.graphs import factories
    from kaminpar_tpu.telemetry.report import build_run_report
    from kaminpar_tpu.utils.logger import OutputLevel

    telemetry.enable()
    g = factories.make_grid_graph(16, 16)
    p = ktp.KaMinPar("default")
    p.set_output_level(OutputLevel.QUIET)
    part = p.set_graph(g).compute_partition(k=4, epsilon=0.05, seed=1)
    assert len(part) == g.n

    report = build_run_report()
    assert report["schema_version"] == 14
    led = report["ledger"]
    assert led["enabled"] is True
    assert led["totals"]["launches"] >= 1
    assert led["transfers"]["totals"]["h2d_bytes"] > 0

    checker = _load_checker()
    assert checker.version_checks(report) == []
    schema = json.load(open(os.path.join(
        _REPO, "kaminpar_tpu", "telemetry", "run_report.schema.json"
    )))
    assert checker.validate_instance(report, schema) == []

    # v12 fixture pin: a pre-ledger report stays valid at its own
    # version, and v13 without the ledger section is a hard error
    v12 = {k: v for k, v in report.items() if k != "ledger"}
    v12["schema_version"] = 12
    assert checker.version_checks(v12) == []
    v13_missing = dict(v12, schema_version=13)
    assert any("ledger" in e for e in checker.version_checks(v13_missing))
