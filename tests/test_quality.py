"""Quality observatory (telemetry/quality.py): attribution invariants,
coarsening-floor correctness vs a brute-force recompute, jaxpr-dormancy
pin, verdict classification units, schema v7 transition, the triage CLI
contract, and the dist rollup smoke."""

import json
import os

import numpy as np
import pytest

import kaminpar_tpu as ktp
from kaminpar_tpu import telemetry
from kaminpar_tpu.graphs import factories
from kaminpar_tpu.graphs.host import HostGraph, host_partition_metrics
from kaminpar_tpu.telemetry import quality
from kaminpar_tpu.utils.logger import OutputLevel

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


def _run_report(graph, k=4, seed=1, preset="default"):
    from kaminpar_tpu.telemetry.report import build_run_report

    telemetry.enable()
    p = ktp.KaMinPar(preset)
    p.set_output_level(OutputLevel.QUIET)
    part = p.set_graph(graph).compute_partition(k=k, epsilon=0.05,
                                                seed=seed)
    return build_run_report(), part


# ---------------------------------------------------------------------------
# the attribution-sums-to-total invariant, end to end (rgg2d)
# ---------------------------------------------------------------------------


_RGG_CACHE = {}


def _rgg_run():
    """One shared small rgg2d pipeline run (module-memoized: the
    invariant test and the end-to-end CLI test read the same report, so
    tier-1 pays for one partition, not two)."""
    if "report" not in _RGG_CACHE:
        g = factories.make_rgg2d(4096, avg_degree=8, seed=1)
        report, part = _run_report(g, k=4)
        _RGG_CACHE.update(report=report, part=part, graph=g,
                          headline=quality.headline())
    return _RGG_CACHE


def test_attribution_invariant_on_rgg2d():
    """Every attribution row satisfies the exact per-level identity
    coarsening_locked + refinement_left == gap == refined - bound, the
    level-0 row is the identity push (floor == bound == final cut,
    locked == 0), and the headline fractions are consistent."""
    run = _rgg_run()
    g, report, part = run["graph"], run["report"], run["part"]
    q = report["quality"]
    assert q["enabled"] and q["finalized"], q.get("enabled")
    levels = q["levels"]
    assert levels and q["final_cut"] is not None
    by_level = {row["level"]: row for row in levels}
    l0 = by_level[0]
    assert l0["floor_cut"] == q["final_cut"] == l0["bound_cut"]
    assert l0["coarsening_locked"] == 0
    rows = [r for r in levels if r.get("gap") is not None and r["level"] > 0]
    assert rows, "no attribution rows on an rgg2d run"
    for row in rows:
        assert (
            row["coarsening_locked"] + row["refinement_left"] == row["gap"]
        ), row
        assert row["gap"] == row["refined_cut"] - row["bound_cut"], row
        # a level that ran at the final k is bounded by the final cut
        if row.get("k_at_level") == 4:
            assert row["bound_cut"] == q["final_cut"], row
    totals = q["totals"]
    assert totals["attribution_rows"] == len(rows)
    assert totals["gap_mass"] == sum(r["gap"] for r in rows)
    lf, rf = (totals["coarsening_locked_frac"],
              totals["refinement_left_frac"])
    if lf is not None:
        assert 0.0 <= lf <= 1.0 and 0.0 <= rf <= 1.0
        assert abs(lf + rf - 1.0) < 1e-6
    # the final cut the attribution is anchored to matches the real one
    assert q["final_cut"] == host_partition_metrics(g, part, 4)["cut"]
    # coarsening stats rode along for every contraction
    for row in rows:
        stats = row.get("coarsening")
        assert stats and 0.0 <= stats["singleton_frac"] <= 1.0, row
        assert stats["max_cluster_size"] >= 1


# ---------------------------------------------------------------------------
# coarsening-floor correctness vs a brute-force recompute (tiny graph)
# ---------------------------------------------------------------------------


def _tiny_graph():
    """A weighted path of 8 nodes (edge i-(i+1) has weight i+1)."""
    n = 8
    src = np.arange(n - 1)
    dst = src + 1
    w = src + 1
    edges = np.concatenate([np.stack([src, dst, w], 1),
                            np.stack([dst, src, w], 1)])
    order = np.lexsort((edges[:, 1], edges[:, 0]))
    edges = edges[order]
    xadj = np.zeros(n + 1, dtype=np.int64)
    np.add.at(xadj, edges[:, 0] + 1, 1)
    return HostGraph(
        xadj=np.cumsum(xadj),
        adjncy=edges[:, 1].astype(np.int32),
        node_weights=np.arange(1, n + 1),
        edge_weights=edges[:, 2],
    )


def _brute_force_floor(g, cmaps, part):
    """Independent recompute: compose the maps, pick each cluster's
    weighted-majority block (ties -> smaller block id), push back to the
    input graph and sum the cut."""
    node_w = g.node_weight_array()
    src, adj, ew = g.edge_sources(), g.adjncy, g.edge_weight_array()

    def cut(p):
        return int(ew[p[src] != p[adj]].sum() // 2)

    floors = {}
    phi = np.arange(g.n)
    for level in sorted(cmaps):
        phi = np.asarray(cmaps[level])[phi]
        q = {}
        for c in np.unique(phi):
            weights = {}
            for v in np.flatnonzero(phi == c):
                weights[part[v]] = weights.get(part[v], 0) + int(node_w[v])
            best = max(weights.items(), key=lambda kv: (kv[1], -kv[0]))
            q[c] = best[0]
        pushed = np.asarray([q[c] for c in phi], dtype=np.int32)
        floors[level] = cut(pushed)
    return floors


def test_floor_matches_bruteforce_on_tiny_graph():
    g = _tiny_graph()
    # two handmade contractions: pairs, then quads
    cmaps = {
        1: np.repeat(np.arange(4), 2),   # 8 -> 4
        2: np.repeat(np.arange(2), 2),   # 4 -> 2
    }
    part = np.asarray([0, 0, 1, 1, 1, 0, 1, 1], dtype=np.int32)

    telemetry.enable()
    qh = quality.begin("test")
    assert qh is not None
    try:
        quality.note_cmap(1, cmaps[1], 8)
        quality.note_cmap(2, cmaps[2], 4)
        quality.note_refined(1, cut=7, k=2)
        quality.note_refined(2, cut=9, k=2)
        quality.finalize_host(qh, g, part)
    finally:
        quality.end(qh)

    section = quality.snapshot()
    assert section["enabled"] and section["finalized"]
    expected = _brute_force_floor(g, cmaps, part)
    final_cut = section["final_cut"]
    by_level = {row["level"]: row for row in section["levels"]}
    for level, floor in expected.items():
        row = by_level[level]
        assert row["floor_cut"] == floor, (level, row, floor)
        assert row["coarsening_locked"] == floor - final_cut
        assert row["refinement_left"] == row["refined_cut"] - floor
        assert row["gap"] == row["coarsening_locked"] + row["refinement_left"]
    # floors are NOT monotone and may undercut the final cut: majority
    # rounding can trade balance for cut (here level 2 collapses to one
    # block — cut 0 — which is exactly the documented caveat)
    assert expected[2] == 0 and expected[1] > 0


def test_weighted_majority_ties_and_weights():
    phi = np.asarray([0, 0, 1, 1, 1])
    part = np.asarray([2, 1, 0, 0, 1])
    # cluster 0: block 2 (w=1) vs block 1 (w=1) -> tie -> smaller id 1
    # cluster 1: block 0 (w=1+1) vs block 1 (w=5) -> block 1
    w = np.asarray([1, 1, 1, 1, 5])
    q = quality.weighted_majority(phi, part, w, 2)
    assert q.tolist() == [1, 1]
    # unweighted majority
    q2 = quality.weighted_majority(phi, part, np.ones(5, np.int64), 2)
    assert q2.tolist() == [1, 0]


# ---------------------------------------------------------------------------
# jaxpr dormancy: LP / Jet / contraction trace identically on / off
# ---------------------------------------------------------------------------


def test_quality_layer_has_zero_jaxpr_impact(monkeypatch):
    """The acceptance pin: the LP, Jet, and contraction programs trace
    to bitwise-identical jaxprs whether the quality layer is on, off via
    KAMINPAR_TPU_QUALITY=0, or telemetry is disabled entirely — every
    hook is host-side driver code (cuts go through the separately-jitted
    ops.metrics.edge_cut_jit)."""
    import jax
    import jax.numpy as jnp

    from kaminpar_tpu.graphs.csr import device_graph_from_host
    from kaminpar_tpu.ops import jet as jet_mod
    from kaminpar_tpu.ops import lp as lp_mod
    from kaminpar_tpu.ops.contraction import _contract_part1

    g = factories.make_grid_graph(8, 8)
    dg = device_graph_from_host(g)
    part0 = jnp.asarray((np.arange(dg.n_pad) % 4).astype(np.int32))
    mbw = jnp.asarray(np.full(4, g.n, dtype=np.int64).astype(np.int32))
    cfg = lp_mod.LPConfig(refinement=True)

    def traces():
        lp = str(jax.make_jaxpr(
            lambda p: lp_mod.lp_refine(
                dg, p, 4, mbw, jnp.int32(1), cfg, num_iterations=2
            )
        )(part0))
        cluster = str(jax.make_jaxpr(
            lambda s: lp_mod.lp_cluster(
                dg, jnp.asarray(64, dtype=dg.node_w.dtype), s,
                lp_mod.LPConfig(num_iterations=2),
            )
        )(jnp.int32(3)))
        jet = str(jax.make_jaxpr(
            lambda p: jet_mod._jet_build_conn(dg, p, 4)
        )(part0))
        contraction = str(jax.make_jaxpr(
            lambda lab: _contract_part1(dg, lab)
        )(part0))
        return lp, cluster, jet, contraction

    # progress capture off so only the QUALITY toggle varies
    monkeypatch.setenv("KAMINPAR_TPU_PROGRESS", "0")
    telemetry.disable()
    j_telemetry_off = traces()

    telemetry.enable()
    monkeypatch.setenv("KAMINPAR_TPU_QUALITY", "0")
    assert not quality.enabled()
    j_quality_off = traces()

    monkeypatch.delenv("KAMINPAR_TPU_QUALITY")
    assert quality.enabled()
    # an OPEN recording scope must not change tracing either
    qh = quality.begin("test")
    try:
        j_quality_on = traces()
    finally:
        quality.end(qh)

    assert j_telemetry_off == j_quality_off == j_quality_on


def test_hooks_are_noops_when_disabled(monkeypatch):
    monkeypatch.setenv("KAMINPAR_TPU_QUALITY", "0")
    telemetry.enable()
    assert quality.begin("x") is None
    quality.end(None)  # balanced no-op
    # hooks without an open scope record nothing and never touch args
    quality.note_cmap(1, object(), 4)  # would explode if not gated
    quality.note_projected(1, cut=5)
    quality.note_refined(1, cut=5)
    assert quality.snapshot() == {"enabled": False}
    assert quality.headline() is None


# ---------------------------------------------------------------------------
# verdict classification units
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("series,expected", [
    # LP-style moved series: reached zero -> converged
    ({"moved": [50, 10, 0]}, "converged"),
    # still moving in bulk when the loop ended -> budget-capped
    ({"moved": [50, 45, 40]}, "budget-capped"),
    # decayed to a trickle but nonzero -> stalled
    ({"moved": [100, 20, 3]}, "stalled"),
    # Jet: cut stopped improving with movers left -> stalled
    ({"cut": [100, 90, 90, 90], "moved": [9, 9, 9, 9]}, "stalled"),
    # Jet: still improving in the tail -> budget-capped
    ({"cut": [100, 90, 80, 70], "moved": [9, 9, 9, 9]}, "budget-capped"),
    # Jet: movers drained -> converged
    ({"cut": [100, 90, 80], "moved": [9, 3, 0]}, "converged"),
    # FM: last pass gained nothing -> converged
    ({"gain": [40, 10, 0]}, "converged"),
    # FM: still gaining when the pass budget ended -> budget-capped
    ({"gain": [40, 30, 20]}, "budget-capped"),
    # empty series -> converged (the loop never ran)
    ({}, "converged"),
])
def test_classify_series(series, expected):
    v = quality.classify_series(series)
    assert v["verdict"] == expected, (series, v)
    assert v["realized"] >= 0


def test_classify_series_gain_mass():
    v = quality.classify_series({"cut": [100, 70, 60], "moved": [5, 4, 2]})
    assert v["realized"] == 40 and v["remaining"] == 2
    v = quality.classify_series({"moved": [30, 20, 0]})
    assert v["realized"] == 50 and v["remaining"] == 0


def test_level_verdict_rollup_and_skip_events():
    assert quality.level_verdict([]) is None
    assert quality.level_verdict(
        [{"verdict": "converged"}, {"verdict": "stalled"}]
    ) == "stalled"
    assert quality.level_verdict(
        [{"verdict": "stalled"}, {"verdict": "budget-capped"}]
    ) == "budget-capped"
    # a deadline refine-skipped event marks its level budget-capped
    telemetry.enable()
    qh = quality.begin("test")
    try:
        quality.note_refined(2, cut=10, k=2)
        telemetry.event("refine-skipped", level=2, algorithm="jet",
                        reason="deadline")
    finally:
        quality.end(qh)
    section = quality.snapshot()
    row = {r["level"]: r for r in section["levels"]}[2]
    assert row["verdict"] == "budget-capped"
    assert any(v.get("skipped") for v in row["verdicts"])


# ---------------------------------------------------------------------------
# schema v7 + fixtures
# ---------------------------------------------------------------------------


def _load_checker():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_report_schema",
        os.path.join(_REPO, "scripts", "check_report_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_schema_v7_quality_section_and_fixtures():
    from kaminpar_tpu.telemetry.report import SCHEMA_PATH

    checker = _load_checker()
    schema = json.loads(open(SCHEMA_PATH).read())
    # every transition fixture v1..v6 still validates
    for fixture in (checker._minimal_v1_report(),
                    checker._minimal_v2_report(),
                    checker._minimal_v3_report(),
                    checker._minimal_v4_report(),
                    checker._minimal_v5_report(),
                    checker._minimal_v6_report()):
        assert checker.validate_instance(fixture, schema) == []
        assert checker.version_checks(fixture) == []
    # v7 requires the quality section
    v7_missing = dict(checker._minimal_v6_report(), schema_version=7)
    assert any("quality" in e for e in checker.version_checks(v7_missing))
    v7 = dict(v7_missing, quality={"enabled": False})
    assert checker.validate_instance(v7, schema) == []
    assert checker.version_checks(v7) == []
    # a populated quality section validates against the declared shape
    v7full = dict(v7, quality={
        "enabled": True, "scheme": "deep", "finalized": True,
        "final_cut": 10,
        "levels": [{"level": 1, "projected_cut": 14, "refined_cut": 12,
                    "floor_cut": 11, "bound_cut": 10,
                    "coarsening_locked": 1, "refinement_left": 1,
                    "gap": 2, "verdict": "stalled",
                    "coarsening": {"internal_ew_ratio": 0.5,
                                   "singleton_frac": 0.1}}],
        "totals": {"attribution_rows": 1, "gap_mass": 2,
                   "locked_mass": 1, "left_mass": 1,
                   "coarsening_locked_frac": 0.5,
                   "refinement_left_frac": 0.5, "worst_level": 1},
        "ranks": [{"rank": 0, "gap_mass": 2}],
    })
    assert checker.validate_instance(v7full, schema) == []
    # a bad verdict enum is caught
    v7bad = json.loads(json.dumps(v7full))
    v7bad["quality"]["levels"][0]["verdict"] = "fine"
    assert any("verdict" in e or "enum" in e
               for e in checker.validate_instance(v7bad, schema))


# ---------------------------------------------------------------------------
# triage CLI: render + exit codes (the telemetry.top contract)
# ---------------------------------------------------------------------------


def _cli_report(with_quality=True):
    report = {"schema_version": 7}
    if with_quality:
        report["quality"] = {
            "enabled": True, "scheme": "deep", "finalized": True,
            "final_cut": 100,
            "levels": [
                {"level": 0, "refined_cut": 100, "floor_cut": 100,
                 "bound_cut": 100, "coarsening_locked": 0,
                 "refinement_left": 0, "gap": 0},
                {"level": 1, "coarse_n": 64, "projected_cut": 130,
                 "refined_cut": 120, "floor_cut": 104, "bound_cut": 100,
                 "coarsening_locked": 4, "refinement_left": 16,
                 "gap": 20, "k_at_level": 4, "verdict": "stalled",
                 "coarsening": {"internal_ew_ratio": 0.4,
                                "singleton_frac": 0.3}},
                {"level": 2, "coarse_n": 16, "projected_cut": 140,
                 "refined_cut": 130, "floor_cut": 124, "bound_cut": 100,
                 "coarsening_locked": 24, "refinement_left": 6,
                 "gap": 30, "k_at_level": 4,
                 "verdict": "budget-capped"},
            ],
            "totals": {"attribution_rows": 2, "gap_mass": 50,
                       "locked_mass": 28, "left_mass": 22,
                       "coarsening_locked_frac": 0.56,
                       "refinement_left_frac": 0.44, "worst_level": 2},
        }
    return report


def test_cli_renders_and_ranks(tmp_path, capsys):
    path = tmp_path / "r.json"
    path.write_text(json.dumps(_cli_report()))
    assert quality.main([str(path)]) == 0
    out = capsys.readouterr().out
    # ranked by gap: level 2 (gap 30) before level 1 (gap 20)
    assert out.index("\n2 ") < out.index("\n1 ")
    assert "coarsening_locked_frac=0.56" in out
    assert "budget-capped" in out
    # the worst level is mostly locked -> the advice targets coarsening
    assert "aim at coarsening" in out


def test_cli_exit_codes(tmp_path, capsys):
    path = tmp_path / "r.json"
    path.write_text(json.dumps(_cli_report()))
    assert quality.main([str(path), "--require-attribution"]) == 0
    # no quality section: renders a note, exits 0; the CI flag makes it 1
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps(_cli_report(with_quality=False)))
    assert quality.main([str(bare)]) == 0
    assert quality.main([str(bare), "--require-attribution"]) == 1
    capsys.readouterr()
    # IO / not-a-report errors exit 2 (telemetry.top contract)
    assert quality.main([str(tmp_path / "missing.json")]) == 2
    notreport = tmp_path / "x.json"
    notreport.write_text("{}")
    assert quality.main([str(notreport)]) == 2
    # --json emits the section as one JSON object
    assert quality.main([str(path), "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["totals"]["worst_level"] == 2


def test_cli_diff_mode(tmp_path, capsys):
    base = _cli_report()
    cand = json.loads(json.dumps(base))
    cand["quality"]["levels"][2]["coarsening_locked"] = 10
    cand["quality"]["levels"][2]["verdict"] = "converged"
    pb, pc = tmp_path / "b.json", tmp_path / "c.json"
    pb.write_text(json.dumps(base))
    pc.write_text(json.dumps(cand))
    assert quality.main([str(pc), "--diff", str(pb)]) == 0
    out = capsys.readouterr().out
    assert "locked 24 -> 10" in out
    assert "verdict budget-capped -> converged" in out


def test_telemetry_diff_carries_quality_block(tmp_path, capsys):
    from kaminpar_tpu.telemetry.diff import diff_quality

    base, cand = _cli_report(), _cli_report()
    cand["quality"]["levels"][1]["refinement_left"] = 2
    lines, failures = diff_quality(base, cand)
    assert failures == []  # informational, never gated
    assert any("left 16 -> 2" in ln for ln in lines)
    # pre-v7 baseline: a schema transition, not a regression
    lines, failures = diff_quality({}, cand)
    assert failures == [] and any("only cand" in ln for ln in lines)


# ---------------------------------------------------------------------------
# report integration + dist rollup smoke
# ---------------------------------------------------------------------------


def test_report_quality_section_disabled_default():
    from kaminpar_tpu.telemetry.report import build_run_report

    telemetry.enable()
    report = build_run_report()
    assert report["schema_version"] == 14
    assert report["quality"] == {"enabled": False}


def test_rank_rollup_single_process():
    telemetry.enable()
    qh = quality.begin("test")
    try:
        quality.note_cmap(1, np.repeat(np.arange(4), 2), 8)
        quality.note_refined(1, cut=9, k=2)
        quality.finalize_host(qh, _tiny_graph(),
                              np.asarray([0, 0, 0, 0, 1, 1, 1, 1]))
    finally:
        quality.end(qh)
    rows = quality.rank_rollup()
    assert len(rows) == 1 and rows[0]["rank"] == 0
    assert rows[0]["gap_mass"] == quality.snapshot()["totals"]["gap_mass"]
    # the dist driver annotates this into the report's quality section
    from kaminpar_tpu.telemetry.report import build_run_report

    telemetry.annotate(quality_ranks=rows)
    report = build_run_report()
    assert report["quality"]["ranks"] == rows
    assert report["quality"]["enabled"]


def test_verdicts_exclude_other_hierarchies_series():
    """Progress series share one stream AND one level numbering across
    nested/sequential hierarchies; the verdict join must only pick up
    series tagged with the PUBLISHED hierarchy's id (a nested IP run's
    budget-capped LP must not flip the outer level's verdict)."""
    from kaminpar_tpu.telemetry import progress as progress_mod

    telemetry.enable()
    outer = quality.begin("deep")
    quality.note_refined(1, cut=9, k=2)
    with progress_mod.tag(level=1,
                          quality_hierarchy=quality.current_id()):
        progress_mod.emit_host("lp", {"moved": [5, 0]}, phase="refine")
    inner = quality.begin("deep")
    assert quality.current_id() == inner.hid != outer.hid
    with progress_mod.tag(level=1,
                          quality_hierarchy=quality.current_id()):
        # still moving in bulk -> budget-capped, but it belongs to the
        # INNER hierarchy's level 1
        progress_mod.emit_host("lp", {"moved": [50, 40]}, phase="refine")
    quality.end(inner)
    quality.finalize_host(outer, _tiny_graph(),
                          np.asarray([0, 0, 0, 0, 1, 1, 1, 1]))
    quality.end(outer)
    section = quality.snapshot()
    row = {r["level"]: r for r in section["levels"]}[1]
    assert row["verdict"] == "converged", row
    assert len(row["verdicts"]) == 1


def test_block_map_from_spans():
    class Span:
        def __init__(self, first, count):
            self.first, self.count = first, count

    # tuples and span objects produce the same map
    tuples = [(0, 2), (2, 1), (3, 1)]
    objs = [Span(*t) for t in tuples]
    bm = quality.block_map_from_spans(tuples, 4)
    assert bm.tolist() == [0, 0, 1, 2]
    assert quality.block_map_from_spans(objs, 4).tolist() == bm.tolist()
    # at the final k there is nothing to map
    assert quality.block_map_from_spans([(0, 1)] * 4, 4) is None


def test_interrupted_hierarchy_publishes_partial_section():
    """A hierarchy that recorded cuts but never finalized (preempted
    run) still lands in the report — marked unfinalized, no floors."""
    telemetry.enable()
    qh = quality.begin("deep")
    try:
        quality.note_projected(2, cut=40, k=2)
        quality.note_refined(2, cut=30, k=2)
    finally:
        quality.end(qh)
    section = quality.snapshot()
    assert section["enabled"] and not section["finalized"]
    row = {r["level"]: r for r in section["levels"]}[2]
    assert row["refined_cut"] == 30 and "floor_cut" not in row
    assert quality.attribution_rows({"quality": section}) == []


def test_nested_hierarchies_do_not_corrupt_outer():
    """A nested IP run (dist driver's shm KaMinPar) opens its own scope;
    the outer hierarchy's record is untouched and its later finalize
    wins the published section."""
    g = _tiny_graph()
    telemetry.enable()
    outer = quality.begin("dist")
    quality.note_cmap(1, np.repeat(np.arange(4), 2), 8)
    quality.note_refined(1, cut=9, k=2)
    inner = quality.begin("deep")
    quality.note_cmap(1, np.zeros(2, dtype=np.int64), 2)
    quality.note_refined(1, cut=1, k=2)
    quality.finalize_host(inner, _tiny_graph(), np.zeros(8, np.int32))
    quality.end(inner)
    # outer state is intact
    assert outer.cmaps[1].shape[0] == 8
    quality.finalize_host(outer, g, np.asarray([0, 0, 0, 0, 1, 1, 1, 1]))
    quality.end(outer)
    section = quality.snapshot()
    assert section["scheme"] == "dist"
    assert {r["level"] for r in section["levels"]} >= {0, 1}


def test_end_to_end_report_cli_and_headline(tmp_path):
    """Full pipeline -> report -> quality CLI exit 0 with an
    attribution row; the CLI headline line is available."""
    report = _rgg_run()["report"]
    assert _rgg_run()["headline"] is not None
    path = tmp_path / "report.json"
    path.write_text(json.dumps(report))
    assert quality.main([str(path), "--require-attribution"]) == 0
    # the generic schema checker accepts the produced report
    checker = _load_checker()
    from kaminpar_tpu.telemetry.report import SCHEMA_PATH

    schema = json.loads(open(SCHEMA_PATH).read())
    errors = (checker.validate_instance(report, schema)
              + checker.version_checks(report))
    assert errors == [], errors
