"""Initial bipartitioning tests (analog of the reference's initial
partitioning coverage inside e2e tests)."""

import numpy as np

from kaminpar_tpu.context import InitialPartitioningContext, InitialRefinementContext
from kaminpar_tpu.graphs import factories
from kaminpar_tpu.initial import bipartition, fm_bipartition_refine
from kaminpar_tpu.initial.bipartitioner import _host_block_weights, _host_cut
from kaminpar_tpu.initial.flat import (
    bfs_bipartition,
    ggg_bipartition,
    random_bipartition,
)


def test_flat_bipartitioners_produce_valid_partitions():
    g = factories.make_grid_graph(10, 10)
    mw = np.array([55, 55])
    rng = np.random.default_rng(0)
    for fn in (random_bipartition, bfs_bipartition, ggg_bipartition):
        part = fn(g, mw, rng)
        assert set(np.unique(part)) <= {0, 1}
        bw = _host_block_weights(g, part)
        assert bw.sum() == 100


def test_fm_refine_reduces_cut():
    g = factories.make_grid_graph(8, 8)
    rng = np.random.default_rng(1)
    part = rng.integers(0, 2, 64).astype(np.int8)
    before = _host_cut(g, part)
    imp = fm_bipartition_refine(
        g, part, np.array([40, 40]), InitialRefinementContext(), rng
    )
    after = _host_cut(g, part)
    assert imp >= 0 and after <= before
    assert (_host_block_weights(g, part) <= 40).all()


def test_multilevel_bipartition_quality_path():
    g = factories.make_path(200)
    part = bipartition(
        g, np.array([103, 103]), InitialPartitioningContext(),
        np.random.default_rng(0),
    )
    assert _host_cut(g, part) <= 3  # optimum is 1


def test_multilevel_bipartition_grid():
    g = factories.make_grid_graph(16, 16)
    part = bipartition(
        g, np.array([135, 135]), InitialPartitioningContext(),
        np.random.default_rng(0),
    )
    cut = _host_cut(g, part)
    bw = _host_block_weights(g, part)
    assert (bw <= 135).all()
    assert cut <= 32  # optimum 16

def test_weighted_bipartition():
    g = factories.make_path(20)
    g.node_weights = np.ones(20, dtype=np.int64)
    g.node_weights[0] = 10
    part = bipartition(
        g, np.array([16, 16]), InitialPartitioningContext(),
        np.random.default_rng(3),
    )
    assert (_host_block_weights(g, part) <= 16).all()
