"""Initial bipartitioning tests (analog of the reference's initial
partitioning coverage inside e2e tests)."""

import numpy as np
import pytest

from kaminpar_tpu.context import InitialPartitioningContext, InitialRefinementContext
from kaminpar_tpu.graphs import factories
from kaminpar_tpu.initial import bipartition, fm_bipartition_refine
from kaminpar_tpu.initial.bipartitioner import _host_block_weights, _host_cut
from kaminpar_tpu.initial.flat import (
    bfs_bipartition,
    ggg_bipartition,
    random_bipartition,
)


def test_flat_bipartitioners_produce_valid_partitions():
    g = factories.make_grid_graph(10, 10)
    mw = np.array([55, 55])
    rng = np.random.default_rng(0)
    for fn in (random_bipartition, bfs_bipartition, ggg_bipartition):
        part = fn(g, mw, rng)
        assert set(np.unique(part)) <= {0, 1}
        bw = _host_block_weights(g, part)
        assert bw.sum() == 100


def test_fm_refine_reduces_cut():
    g = factories.make_grid_graph(8, 8)
    rng = np.random.default_rng(1)
    part = rng.integers(0, 2, 64).astype(np.int8)
    before = _host_cut(g, part)
    imp = fm_bipartition_refine(
        g, part, np.array([40, 40]), InitialRefinementContext(), rng
    )
    after = _host_cut(g, part)
    assert imp >= 0 and after <= before
    assert (_host_block_weights(g, part) <= 40).all()


@pytest.mark.parametrize("native_ip", [True, False])
def test_multilevel_bipartition_quality_path(native_ip, monkeypatch):
    if not native_ip:
        monkeypatch.setenv("KAMINPAR_TPU_NO_NATIVE_IP", "1")
    g = factories.make_path(200)
    part = bipartition(
        g, np.array([103, 103]), InitialPartitioningContext(),
        np.random.default_rng(0),
    )
    assert _host_cut(g, part) <= 3  # optimum is 1


@pytest.mark.parametrize("native_ip", [True, False])
def test_multilevel_bipartition_grid(native_ip, monkeypatch):
    if not native_ip:
        monkeypatch.setenv("KAMINPAR_TPU_NO_NATIVE_IP", "1")
    g = factories.make_grid_graph(16, 16)
    part = bipartition(
        g, np.array([135, 135]), InitialPartitioningContext(),
        np.random.default_rng(0),
    )
    cut = _host_cut(g, part)
    bw = _host_block_weights(g, part)
    assert (bw <= 135).all()
    assert cut <= 32  # optimum 16

@pytest.mark.parametrize("native_ip", [True, False])
def test_weighted_bipartition(native_ip, monkeypatch):
    if not native_ip:
        monkeypatch.setenv("KAMINPAR_TPU_NO_NATIVE_IP", "1")
    g = factories.make_path(20)
    g.node_weights = np.ones(20, dtype=np.int64)
    g.node_weights[0] = 10
    part = bipartition(
        g, np.array([16, 16]), InitialPartitioningContext(),
        np.random.default_rng(3),
    )
    assert (_host_block_weights(g, part) <= 16).all()


def test_native_bipartitioner_matches_python_class():
    """The native (C++) multilevel bipartitioner must produce feasible
    partitions of the same quality class as the numpy path (it replaces
    it whenever the toolchain is available — ip.cpp)."""
    from kaminpar_tpu import native

    if not native.available():
        import pytest

        pytest.skip("native toolchain unavailable")
    g = factories.make_grid_graph(24, 24)
    ctx = InitialPartitioningContext()
    caps = np.array([297, 297])
    part = native.ml_bipartition(g, caps, ctx, seed=11)
    assert part is not None and part.dtype == np.int8
    assert set(np.unique(part)) <= {0, 1}
    assert (_host_block_weights(g, part) <= caps).all()
    assert _host_cut(g, part) <= 48  # optimum 24, same band as python

    # determinism: same seed, same result
    part2 = native.ml_bipartition(g, caps, ctx, seed=11)
    assert np.array_equal(part, part2)


def test_native_bipartitioner_weighted_feasible():
    from kaminpar_tpu import native

    if not native.available():
        import pytest

        pytest.skip("native toolchain unavailable")
    rng = np.random.default_rng(2)
    g = factories.make_grid_graph(16, 16)
    g.node_weights = rng.integers(1, 9, g.n).astype(np.int64)
    total = int(g.node_weights.sum())
    cap = int(1.05 * np.ceil(total / 2))
    part = native.ml_bipartition(g, [cap, cap], InitialPartitioningContext(), seed=5)
    assert (_host_block_weights(g, part) <= cap).all()
