"""BFS kernel + BFS extractor tests (bfs_extractor.cc analog coverage:
the reference's dist tests extract BFS regions around seeds and validate
the resulting shm graph)."""

import jax.numpy as jnp
import numpy as np
import pytest

from kaminpar_tpu.graphs.bfs_extractor import extract_bfs_subgraph, _host_bfs
from kaminpar_tpu.graphs.csr import device_graph_from_host
from kaminpar_tpu.graphs.factories import (
    make_grid_graph,
    make_path,
    make_star,
)
from kaminpar_tpu.graphs.host import validate
from kaminpar_tpu.ops.bfs import UNREACHED, bfs_hops


def test_bfs_hops_on_path():
    g = make_path(10)
    dg = device_graph_from_host(g)
    hops = np.asarray(bfs_hops(dg, jnp.array([0], jnp.int32), jnp.int32(3)))
    expect = [0, 1, 2, 3] + [UNREACHED] * 6
    assert hops[: g.n].tolist() == expect


def test_bfs_hops_multi_seed_matches_host_bfs():
    g = make_grid_graph(12, 12)
    dg = device_graph_from_host(g)
    seeds = np.array([0, 77, 143], dtype=np.int64)
    hops_dev = np.asarray(
        bfs_hops(dg, jnp.asarray(seeds, jnp.int32), jnp.int32(4))
    )[: g.n]
    hops_host = _host_bfs(g, seeds, 4)
    reached = hops_host <= 4
    assert (hops_dev[reached] == hops_host[reached]).all()
    assert (hops_dev[~reached] == UNREACHED).all()


def test_bfs_hops_ignores_pad_seeds():
    g = make_star(5)
    dg = device_graph_from_host(g)
    hops = np.asarray(
        bfs_hops(dg, jnp.array([-1, 0], jnp.int32), jnp.int32(2))
    )
    assert hops[0] == 0
    assert (hops[1 : g.n] == 1).all()


@pytest.mark.parametrize("use_device_hops", [False, True])
def test_extract_bfs_subgraph_grid(use_device_hops):
    g = make_grid_graph(10, 10)
    k = 2
    part = (np.arange(g.n) % 10 >= 5).astype(np.int32)  # left/right halves
    seeds = np.array([0])
    hops = None
    if use_device_hops:
        dg = device_graph_from_host(g)
        hops = np.asarray(
            bfs_hops(dg, jnp.asarray(seeds, jnp.int32), jnp.int32(2))
        )
    ext = extract_bfs_subgraph(g, part, seeds, max_hops=2, k=k, hops=hops)
    validate(ext.graph)
    # region of corner node at radius 2 on a grid: 6 nodes
    assert ext.num_region == 6
    assert ext.graph.n == ext.num_region + k
    # total node weight is conserved (region + pseudo exterior)
    assert ext.graph.node_weight_array().sum() == g.node_weight_array().sum()
    # pseudo-node weights = exterior block weights
    in_region = np.zeros(g.n, dtype=bool)
    in_region[ext.node_mapping] = True
    for b in range(k):
        expect = g.node_weight_array()[(~in_region) & (part == b)].sum()
        assert ext.graph.node_weight_array()[ext.num_region + b] == expect
    # every interior edge of the region is preserved with its weight
    sub = ext.graph
    # region-internal degree check on original corner node (id 0 -> new 0)
    assert ext.node_mapping[0] == 0
    assert ext.partition[: ext.num_region].tolist() == part[ext.node_mapping].tolist()


def test_extract_project_back():
    g = make_grid_graph(6, 6)
    k = 2
    part = (np.arange(g.n) % 6 >= 3).astype(np.int32)
    ext = extract_bfs_subgraph(g, part, np.array([14]), max_hops=1, k=k)
    rp = ext.partition.copy()
    rp[: ext.num_region] = 1 - rp[: ext.num_region]  # flip the region
    out = ext.project_back(rp, part)
    flipped = np.zeros(g.n, dtype=bool)
    flipped[ext.node_mapping] = True
    assert (out[flipped] == 1 - part[flipped]).all()
    assert (out[~flipped] == part[~flipped]).all()


def test_extract_conserves_cut_between_region_and_exterior():
    """Weight of edges from region to exterior block b must equal the
    region->pseudo-b edge weights (the contracted exterior keeps the
    region's attachment, bfs_extractor.h:28-46)."""
    g = make_grid_graph(8, 8)
    k = 2
    part = (np.arange(g.n) % 8 >= 4).astype(np.int32)
    ext = extract_bfs_subgraph(g, part, np.array([27]), max_hops=2, k=k)
    in_region = np.zeros(g.n, dtype=bool)
    in_region[ext.node_mapping] = True
    src, dst, ew = g.edge_sources(), g.adjncy, g.edge_weight_array()
    for b in range(k):
        expect = ew[
            in_region[src] & ~in_region[dst] & (part[dst] == b)
        ].sum()
        sub = ext.graph
        ssrc, sdst, sew = (
            sub.edge_sources(),
            sub.adjncy,
            sub.edge_weight_array(),
        )
        got = sew[
            (ssrc < ext.num_region) & (sdst == ext.num_region + b)
        ].sum()
        assert got == expect
