"""Fleet-observatory tests: the live metrics registry + Prometheus
exporter (telemetry/metrics.py), end-to-end request tracing across the
supervised-worker boundary (telemetry/tracing.py), and the promoted
communication-volume accounting (schema-v12 ``comm`` section + live
``kmp_comm_*`` counters), per docs/observability.md.

The worker round-trip test spawns a REAL supervised worker subprocess
(the boundary under test is the marshal of worker-side spans back to
the parent), so the graphs are tiny — same discipline as
tests/test_supervision.py.
"""

import json
import os
import re

import pytest

from kaminpar_tpu import resilience, telemetry
from kaminpar_tpu.telemetry import metrics as metrics_mod
from kaminpar_tpu.telemetry import tracing
from kaminpar_tpu.serving import (
    PartitionRequest,
    PartitionService,
    ServiceConfig,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Prometheus text-format sample line (metric, optional labels, value).
SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? "
    r"([+-]?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?|NaN|[+-]?Inf)$"
)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(metrics_mod.ENV_VAR, raising=False)
    monkeypatch.delenv(metrics_mod.ENV_CADENCE, raising=False)
    monkeypatch.delenv(resilience.FAULTS_ENV_VAR, raising=False)
    resilience.reset()
    metrics_mod.reset()
    telemetry.reset()
    tracing.reset_traces()
    telemetry.enable()
    yield
    resilience.reset()
    metrics_mod.reset()
    telemetry.disable()
    telemetry.reset()
    tracing.reset_traces()


def _gen(n=600, seed=3):
    return f"gen:rgg2d;n={n};avg_degree=8;seed={seed}"


def _load_checker():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_report_schema",
        os.path.join(REPO, "scripts", "check_report_schema.py"),
    )
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)
    return checker


# ---------------------------------------------------------------------------
# registry math
# ---------------------------------------------------------------------------


def test_counter_and_gauge_registry_math(tmp_path):
    metrics_mod.configure(str(tmp_path / "m.prom"))
    metrics_mod.inc("kmp_x_total", "x", value=2.0, phase="a")
    metrics_mod.inc("kmp_x_total", value=3.0, phase="a")
    metrics_mod.inc("kmp_x_total", phase="b")
    assert metrics_mod.gauge_value("kmp_x_total", phase="a") == 5.0
    assert metrics_mod.gauge_value("kmp_x_total", phase="b") == 1.0
    # gauges overwrite, counters accumulate
    metrics_mod.set_gauge("kmp_g", 7.5)
    metrics_mod.set_gauge("kmp_g", 2.5)
    assert metrics_mod.gauge_value("kmp_g") == 2.5
    metrics_mod.observe("kmp_lat_seconds", 0.25)
    snap = metrics_mod.snapshot()
    assert snap["kmp_x_total"] == {"a": 5.0, "b": 1.0}
    assert "kmp_lat_seconds" in snap


def test_window_rate_math_with_injected_clock():
    t = [0.0]
    wr = metrics_mod.WindowRate(
        "kmp_r", "rate", window_s=10.0, clock=lambda: t[0]
    )
    assert wr.rate() == 0.0
    wr.mark()
    wr.mark(n=4)  # 5 marks in the first instant
    # covered window floors at 1 s: a burst reads events/s, not events/eps
    assert wr.rate() == 5.0
    t[0] = 5.0
    assert wr.rate() == 1.0  # 5 marks / 5 s covered
    t[0] = 9.0
    assert wr.rate() == pytest.approx(5.0 / 9.0)
    # past the window the old marks are pruned
    t[0] = 10.5
    assert wr.rate() == 0.0
    # a new burst divides by the FULL window once runtime exceeds it
    t[0] = 11.0
    wr.mark(n=3)
    assert wr.rate() == pytest.approx(3.0 / 10.0)


def test_producers_noop_while_dormant():
    assert not metrics_mod.enabled()
    metrics_mod.inc("kmp_x_total")
    metrics_mod.set_gauge("kmp_g", 1.0)
    metrics_mod.observe("kmp_l_seconds", 0.1)
    metrics_mod.mark("kmp_r")
    assert metrics_mod.snapshot() == {}
    assert metrics_mod.write_now() is None
    assert metrics_mod.rate("kmp_r") == 0.0
    assert metrics_mod.gauge_value("kmp_g") is None


# ---------------------------------------------------------------------------
# Prometheus rendering + atomic scrape file
# ---------------------------------------------------------------------------


def test_prometheus_escaping(tmp_path):
    metrics_mod.configure(str(tmp_path / "m.prom"))
    metrics_mod.inc(
        "kmp_esc_total", "help with \\ slash\nand newline",
        cls='he said "hi"\nover\\there',
    )
    text = metrics_mod.render()
    assert "# HELP kmp_esc_total help with \\\\ slash\\nand newline" in text
    assert 'cls="he said \\"hi\\"\\nover\\\\there"' in text
    # the escaped sample still parses as ONE line
    sample = [
        l for l in text.splitlines()
        if l.startswith("kmp_esc_total")
    ]
    assert len(sample) == 1 and SAMPLE_RE.match(sample[0]), sample


def test_scrape_file_atomic_and_parseable(tmp_path):
    path = tmp_path / "metrics.prom"
    metrics_mod.configure(str(path))
    metrics_mod.inc("kmp_requests_total", "Requests.", verdict="served")
    metrics_mod.mark("kmp_requests_per_second", "rps")
    metrics_mod.observe("kmp_latency_seconds", 0.1)
    out = metrics_mod.write_now()
    assert out == str(path) and path.exists()
    # atomic publish: no torn tmp file left next to the scrape target
    assert list(tmp_path.glob("*.tmp.*")) == []
    text = path.read_text()
    assert text.endswith("\n")
    for line in text.splitlines():
        if line.startswith("#"):
            assert re.match(r"^# (HELP|TYPE) kmp_", line), line
        else:
            assert SAMPLE_RE.match(line), line
    assert 'kmp_requests_total{verdict="served"} 1' in text
    # summary family renders _sum/_count
    assert "kmp_latency_seconds_count 1" in text


# ---------------------------------------------------------------------------
# dormancy: the exporter must never perturb traced computations
# ---------------------------------------------------------------------------


def test_metrics_dormancy_jaxpr(tmp_path):
    """The kill switch off => bitwise-identical jaxprs.  The probe runs
    the exact producer that executes at trace time inside jitted dist
    code (mesh.account_collective -> metrics.inc when armed); arming
    the exporter must not change what gets traced."""
    import jax
    import jax.numpy as jnp

    from kaminpar_tpu.parallel import mesh
    from kaminpar_tpu.resilience import runstate

    x = jnp.arange(16, dtype=jnp.int32)

    def trace():
        # a fresh function object per pass: jax caches traces per
        # callable, and the producer must run on BOTH passes
        def probe(v):
            mesh.account_collective(
                "psum(probe)", int(v.size) * 4, shape=v.shape
            )
            return jnp.sum(v * 2)

        runstate.begin()  # fresh comm log either way
        with mesh.comm_phase("probe"):
            return str(jax.make_jaxpr(probe)(x))

    assert not metrics_mod.enabled()
    off = trace()
    metrics_mod.configure(str(tmp_path / "m.prom"))
    assert metrics_mod.enabled()
    on = trace()
    assert off == on
    # ... while the live counter really did fire on the armed pass
    assert metrics_mod.gauge_value(
        "kmp_comm_bytes_total", phase="probe"
    ) == 64.0
    assert metrics_mod.gauge_value(
        "kmp_comm_calls_total", phase="probe"
    ) == 1.0


# ---------------------------------------------------------------------------
# request tracing across a REAL supervised worker
# ---------------------------------------------------------------------------


def test_trace_roundtrip_real_worker(tmp_path):
    """Two process-isolated requests: each trace carries the service
    lifecycle spans, the worker-spawn-ship overhead row, and the
    worker's OWN compute scopes marshalled back and re-based into the
    parent timeline (pid-stamped, after the ship overhead)."""
    path = tmp_path / "metrics.prom"
    svc = PartitionService(
        "default",
        ServiceConfig(isolation="process", metrics_file=str(path)),
    )
    try:
        recs = svc.serve([
            PartitionRequest(_gen(seed=1), k=4, seed=1, request_id="t1"),
            PartitionRequest(_gen(seed=2), k=4, seed=1, request_id="t2"),
        ])
        assert [r.verdict for r in recs] == ["served", "served"]
    finally:
        svc.close()

    snap = tracing.snapshot()
    assert snap["enabled"] and len(snap["traces"]) == 2
    by_req = {t["request_id"]: t for t in snap["traces"]}
    for rid in ("t1", "t2"):
        tr = by_req[rid]
        names = {(s["name"], s["origin"]) for s in tr["spans"]}
        for name in ("admission", "queue-wait", "resolve", "compute",
                     "gate"):
            assert (name, "service") in names, (rid, sorted(names))
        assert ("worker-spawn-ship", "service") in names
        workers = [s for s in tr["spans"] if s["origin"] == "worker"]
        assert "worker-compute" in {s["name"] for s in workers}
        assert all(s["attrs"].get("worker_pid") for s in workers)
        # ship overhead is attributed BEFORE the worker's own window
        ship = next(
            s for s in tr["spans"] if s["name"] == "worker-spawn-ship"
        )
        wc = next(s for s in workers if s["name"] == "worker-compute")
        assert wc["start_ms"] >= ship["start_ms"]
        assert tr["attrs"].get("verdict") == "served"

    # close() left a final scrape: the batch is fully accounted
    text = path.read_text()
    assert 'kmp_requests_total{verdict="served"} 2' in text
    assert "kmp_requests_per_second" in text


# ---------------------------------------------------------------------------
# comm promotion: run-scoped log, v12 section, live counters
# ---------------------------------------------------------------------------


def test_comm_log_scoped_per_run_two_requests():
    """Satellite pin: the collective account lives on the RunState, so
    request N+1 (a fresh run, as the serving facade installs one per
    request) never reports request N's traffic — reset_comm_log() needs
    no per-request call site."""
    from kaminpar_tpu.parallel import mesh
    from kaminpar_tpu.resilience import runstate

    runstate.begin()
    with mesh.comm_phase("coarsening"):
        mesh.account_collective("psum(x)", 1024, shape=(256,))
    assert mesh.comm_phase_totals()["coarsening"]["bytes_total"] == 1024

    runstate.begin()  # request 2: fresh run, fresh log
    assert mesh.comm_records() == []
    with mesh.comm_phase("refinement"):
        mesh.account_collective("all_gather(y)", 512, shape=(128,))
    totals = mesh.comm_phase_totals()
    assert "coarsening" not in totals
    assert totals["refinement"] == {"bytes_total": 512, "calls": 1}


def test_comm_section_schema_valid_on_dist_smoke(tmp_path):
    """A real multi-device run populates the promoted v12 ``comm``
    section (per-phase rollup summing to bytes_total summing to the
    records), the whole report stays schema-valid, and the live
    kmp_comm_* counters mirror the account exactly."""
    from kaminpar_tpu.graphs.factories import make_rgg2d
    from kaminpar_tpu.parallel import dKaMinPar, make_mesh
    from kaminpar_tpu.resilience import runstate
    from kaminpar_tpu.telemetry.report import SCHEMA_PATH, build_run_report

    metrics_mod.configure(str(tmp_path / "m.prom"))
    runstate.begin()
    g = make_rgg2d(4096, avg_degree=8, seed=7)
    solver = dKaMinPar("default", mesh=make_mesh(4)).set_graph(g)
    part = solver.compute_partition(k=6, epsilon=0.03, seed=1)
    assert part.shape == (g.n,)

    report = build_run_report()
    comm = report["comm"]
    assert comm["phases"], "per-phase rollup empty on a dist run"
    assert comm["bytes_total"] > 0
    assert comm["bytes_total"] == sum(
        t["bytes_total"] for t in comm["phases"].values()
    )
    assert comm["bytes_total"] == sum(
        r["payload_bytes_per_device"] for r in comm["records"]
    )
    for totals in comm["phases"].values():
        assert totals["bytes_total"] > 0 and totals["calls"] > 0

    checker = _load_checker()
    schema = json.load(open(SCHEMA_PATH))
    errors = checker.validate_instance(report, schema)
    errors += checker.version_checks(report)
    assert errors == [], errors

    for phase, totals in comm["phases"].items():
        assert metrics_mod.gauge_value(
            "kmp_comm_bytes_total", phase=phase
        ) == float(totals["bytes_total"])
        assert metrics_mod.gauge_value(
            "kmp_comm_calls_total", phase=phase
        ) == float(totals["calls"])


# ---------------------------------------------------------------------------
# schema version pins
# ---------------------------------------------------------------------------


def test_schema_version_pins():
    from kaminpar_tpu.telemetry.report import SCHEMA_PATH, SCHEMA_VERSION

    assert SCHEMA_VERSION == 14
    checker = _load_checker()
    schema = json.load(open(SCHEMA_PATH))
    # the v11 fixture (pre-tracing) still validates untouched
    v11 = checker._minimal_v11_report()
    assert checker.validate_instance(v11, schema) == []
    assert checker.version_checks(v11) == []
    # claiming v12 without a tracing section is flagged
    v12_missing = dict(v11, schema_version=12)
    assert any(
        "tracing" in e for e in checker.version_checks(v12_missing)
    )
    v12 = dict(v12_missing, tracing={"enabled": False, "traces": []})
    assert checker.validate_instance(v12, schema) == []
    assert checker.version_checks(v12) == []
    # claiming v13 without a ledger section is flagged
    v13_missing = dict(v12, schema_version=13)
    assert any(
        "ledger" in e for e in checker.version_checks(v13_missing)
    )
    v13 = dict(v13_missing, ledger={"enabled": False})
    assert checker.validate_instance(v13, schema) == []
    assert checker.version_checks(v13) == []
    # claiming v14 without an integrity section is flagged
    v14_missing = dict(v13, schema_version=14)
    assert any(
        "integrity" in e for e in checker.version_checks(v14_missing)
    )
    v14 = dict(v14_missing, integrity={"enabled": False})
    assert checker.validate_instance(v14, schema) == []
    assert checker.version_checks(v14) == []
    # an unknown future version is rejected, not silently accepted
    v15 = dict(v14, schema_version=15)
    assert any(
        "schema_version" in e
        for e in checker.validate_instance(v15, schema)
    )
