"""Malformed-input hardening for the graph parsers (ISSUE 3 satellite).

Contract: a corrupted METIS/ParHiP file surfaces as GraphFormatError
naming the line (text) or byte offset (binary) — never as an
IndexError / OverflowError / struct error from deep inside numpy, and
never as a silent half-parsed graph that fails later.
"""

import numpy as np
import pytest

from kaminpar_tpu.graphs.factories import make_grid_graph
from kaminpar_tpu.io import GraphFormatError, parse_metis, parse_parhip
from kaminpar_tpu.io.metis import write_metis
from kaminpar_tpu.io.parhip import write_parhip


# ---------------------------------------------------------------------------
# METIS: targeted corruption fixtures
# ---------------------------------------------------------------------------

GOOD_METIS = "4 4\n2 3\n1 3\n1 2 4\n3\n"


def test_good_metis_parses():
    g = parse_metis(GOOD_METIS)
    assert g.n == 4 and g.m == 8


@pytest.mark.parametrize(
    "text,needle",
    [
        ("", "empty"),
        ("4\n", "header"),
        ("x 4\n2 3\n1 3\n1 2 4\n3\n", "non-integer header"),
        ("-4 4\n", "negative"),
        ("4 999999\n2 3\n1 3\n1 2 4\n3\n", "file is only"),
        ("4 4\n2 3\n1 3\n", "truncated"),  # node lines missing
        ("4 4\n2 3\n1 x\n1 2 4\n3\n", "non-integer token"),
        ("4 4\n2 3\n1 99999999999999999999999\n1 2 4\n3\n", "overflow"),
        ("4 4\n2 3\n1 3\n1 2 9\n3\n", "out of range"),  # neighbor 9 > n
        ("4 4\n2 3\n1 3\n1 2 0\n3\n", "out of range"),  # ids are 1-based
        ("4 5\n2 3\n1 3\n1 2 4\n3\n", "header claims"),  # m mismatch
        ("4 4 011\n2 3\n1 3\n1 2 4\n3\n", "malformed adjacency"),
        # fmt=11 makes token counts odd
        ("4 4 10\n-1 2 3\n1 1 3\n1 1 2 4\n1 3\n", "negative node weight"),
    ],
)
def test_metis_corruptions_raise_structured(text, needle):
    with pytest.raises(GraphFormatError) as ei:
        parse_metis(text)
    assert needle in str(ei.value)


def test_metis_error_names_the_line():
    with pytest.raises(GraphFormatError) as ei:
        parse_metis("% comment\n4 4\n2 3\n1 3\n1 2 bad\n3\n")
    assert ei.value.line == 5  # original file line, comments included


def test_load_metis_attaches_path(tmp_path):
    from kaminpar_tpu.io import load_metis

    p = tmp_path / "broken.metis"
    p.write_text("4 4\n2 3\n1 x\n1 2 4\n3\n")
    with pytest.raises(GraphFormatError) as ei:
        load_metis(str(p))
    assert ei.value.path == str(p)
    assert "broken.metis" in str(ei.value)


# ---------------------------------------------------------------------------
# ParHiP: targeted corruption fixtures
# ---------------------------------------------------------------------------


def _good_parhip_bytes(tmp_path) -> bytes:
    g = make_grid_graph(6, 6)
    path = tmp_path / "g.parhip"
    write_parhip(g, str(path))
    return path.read_bytes()


def test_good_parhip_roundtrip(tmp_path):
    data = _good_parhip_bytes(tmp_path)
    g = parse_parhip(data)
    assert g.n == 36


def test_parhip_truncated_header():
    with pytest.raises(GraphFormatError) as ei:
        parse_parhip(b"\x00" * 10)
    assert "header" in str(ei.value) and ei.value.offset == 10


def test_parhip_truncated_body(tmp_path):
    data = _good_parhip_bytes(tmp_path)
    for cut in (30, len(data) // 2, len(data) - 4):
        with pytest.raises(GraphFormatError) as ei:
            parse_parhip(data[:cut])
        assert "truncated" in str(ei.value)
        assert ei.value.offset == cut


def test_parhip_non_monotone_offsets(tmp_path):
    data = bytearray(_good_parhip_bytes(tmp_path))
    # offsets are uint32 starting at byte 24: swap two to break order
    off = np.frombuffer(bytes(data[24 : 24 + 4 * 37]), dtype=np.uint32)
    off = off.copy()
    off[3], off[4] = off[10], off[2]
    data[24 : 24 + 4 * 37] = off.tobytes()
    with pytest.raises(GraphFormatError) as ei:
        parse_parhip(bytes(data))
    assert "non-monotone" in str(ei.value) or "aligned" in str(ei.value)


def test_parhip_out_of_range_adjncy(tmp_path):
    data = bytearray(_good_parhip_bytes(tmp_path))
    adj_start = 24 + 4 * 37  # header + (n+1) uint32 offsets
    data[adj_start : adj_start + 4] = np.uint32(999).tobytes()
    with pytest.raises(GraphFormatError) as ei:
        parse_parhip(bytes(data))
    assert "out of range" in str(ei.value)
    assert ei.value.offset == adj_start


# ---------------------------------------------------------------------------
# fuzz: seeded random corruption must never escape GraphFormatError
# ---------------------------------------------------------------------------


def _assert_structured_or_ok(parse, blob):
    try:
        parse(blob)
    except GraphFormatError:
        pass  # structured: exactly the contract
    # any other exception type propagates and fails the test


def test_metis_fuzz_corruption(tmp_path):
    g = make_grid_graph(8, 8)
    path = tmp_path / "f.metis"
    write_metis(g, str(path))
    base = path.read_text()
    rng = np.random.default_rng(1234)
    junk = "x-%57 \n"
    for _ in range(150):
        chars = list(base)
        for _ in range(int(rng.integers(1, 6))):
            pos = int(rng.integers(0, len(chars)))
            chars[pos] = junk[int(rng.integers(0, len(junk)))]
        _assert_structured_or_ok(parse_metis, "".join(chars))


def test_metis_fuzz_truncation(tmp_path):
    g = make_grid_graph(8, 8)
    path = tmp_path / "f.metis"
    write_metis(g, str(path))
    base = path.read_text()
    rng = np.random.default_rng(99)
    for _ in range(40):
        cut = int(rng.integers(0, len(base)))
        _assert_structured_or_ok(parse_metis, base[:cut])


def test_parhip_fuzz_corruption(tmp_path):
    base = _good_parhip_bytes(tmp_path)
    rng = np.random.default_rng(4321)
    for _ in range(150):
        blob = bytearray(base)
        for _ in range(int(rng.integers(1, 6))):
            pos = int(rng.integers(0, len(blob)))
            blob[pos] = int(rng.integers(0, 256))
        _assert_structured_or_ok(parse_parhip, bytes(blob))


def test_parhip_fuzz_truncation(tmp_path):
    base = _good_parhip_bytes(tmp_path)
    rng = np.random.default_rng(77)
    for _ in range(40):
        cut = int(rng.integers(0, len(base)))
        _assert_structured_or_ok(parse_parhip, base[:cut])
