// C ABI shim for the TPU-native KaMinPar framework.
//
// Parity component for the reference's C wrapper (kaminpar-shm/ckaminpar.cc
// wraps the C++ KaMinPar class).  Here the engine is Python/JAX, so the C
// surface embeds a CPython interpreter (one per process, lazily) and calls
// kaminpar_tpu.capi.compute_from_pointers, which wraps the caller's raw CSR
// buffers as numpy arrays without copying and runs the standard pipeline.
//
// Build (see kaminpar_tpu/native/build_capi.py):
//   g++ -O3 -shared -fPIC ckaminpar.cpp $(python3-config --includes) \
//       $(python3-config --ldflags --embed) -o libckaminpar_tpu.so

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>

extern "C" {

struct kmp_partitioner {
  std::string preset;
  int seed;
  std::string last_error;
};

static bool ensure_python() {
  if (Py_IsInitialized()) return true;
  Py_InitializeEx(0);
  if (!Py_IsInitialized()) return false;
  // release the GIL acquired by initialization so OTHER threads'
  // PyGILState_Ensure in kmp_compute_partition can take it (the header
  // documents GIL-serialized multi-threaded use)
  PyEval_SaveThread();
  return true;
}

kmp_partitioner *kmp_create(const char *preset, int seed) {
  if (!ensure_python()) return nullptr;
  auto *p = new kmp_partitioner();
  p->preset = preset ? preset : "default";
  p->seed = seed;
  return p;
}

void kmp_free(kmp_partitioner *p) { delete p; }

const char *kmp_last_error(kmp_partitioner *p) {
  return p ? p->last_error.c_str() : "null partitioner";
}

int64_t kmp_compute_partition(kmp_partitioner *p, int64_t n,
                              const int64_t *xadj, const int32_t *adjncy,
                              const int32_t *vwgt, const int32_t *adjwgt,
                              int32_t k, double epsilon, int32_t *out) {
  if (!p) return -1;
  p->last_error.clear();
  if (n < 0 || !xadj || (!adjncy && xadj[n] > 0) || !out || k <= 0) {
    p->last_error = "invalid arguments";
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int64_t result = -1;
  PyObject *mod = nullptr, *ret = nullptr;
  mod = PyImport_ImportModule("kaminpar_tpu.capi");
  if (!mod) goto fail;
  // pointers cross the ABI as integers; the Python side wraps them with
  // numpy without copying (np.ctypeslib.as_array)
  ret = PyObject_CallMethod(
      mod, "compute_from_pointers", "LLLLLLidLs", (long long)n,
      (long long)(intptr_t)xadj, (long long)(intptr_t)adjncy,
      (long long)(intptr_t)vwgt, (long long)(intptr_t)adjwgt,
      (long long)(intptr_t)out, (int)k, epsilon, (long long)p->seed,
      p->preset.c_str());
  if (!ret) goto fail;
  result = PyLong_AsLongLong(ret);
  if (PyErr_Occurred()) goto fail;
  Py_DECREF(ret);
  Py_DECREF(mod);
  PyGILState_Release(gil);
  return result;

fail:
  if (PyErr_Occurred()) {
    PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
    PyErr_Fetch(&type, &value, &tb);
    PyObject *s = value ? PyObject_Str(value) : nullptr;
    const char *msg = s ? PyUnicode_AsUTF8(s) : "unknown python error";
    p->last_error = msg ? msg : "unknown python error";
    Py_XDECREF(s);
    Py_XDECREF(type);
    Py_XDECREF(value);
    Py_XDECREF(tb);
  } else {
    p->last_error = "unknown error";
  }
  Py_XDECREF(ret);
  Py_XDECREF(mod);
  PyGILState_Release(gil);
  return -1;
}

}  // extern "C"
