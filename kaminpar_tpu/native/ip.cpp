// Sequential multilevel 2-way bipartitioner (native host runtime).
//
// The native equivalent of kaminpar_tpu/initial/{coarsening,flat,fm,
// bipartitioner}.py — itself the analog of the reference's sequential
// initial partitioning stack (kaminpar-shm/initial_partitioning/:
// initial_coarsener.cc, initial_{bfs,ggg,random}_bipartitioner.h,
// initial_fm_refiner.h:68, initial_pool_bipartitioner.h:24-56,
// initial_multilevel_bipartitioner.cc:55,83).  The reference keeps this
// stage sequential C++ per thread by design; the Python/numpy port of it
// became the single largest host cost of the TPU pipeline (a 16k-node
// coarsest graph costs ~60 s in pure-python FM loops), so this file
// restores the reference's design point: the whole multilevel
// bipartition — LP coarsening, flat pool, FM at every level — runs
// native, exposed through one C ABI entry point called via ctypes.
//
// Algorithmic behavior matches the Python implementation (same config
// knobs, same stopping rules, same pool adaptivity); node visit order
// and tie-breaking use a private RNG, so cuts differ seed-to-seed from
// the numpy path the way two reference threads' results do.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <deque>
#include <queue>
#include <tuple>
#include <vector>

namespace {

// ---------------------------------------------------------------- RNG --
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed ^ 0x9E3779B97F4A7C15ULL) {
    if (s == 0) s = 0x2545F4914F6CDD1DULL;
  }
  uint64_t next() {
    // splitmix64
    uint64_t z = (s += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
  }
  int64_t below(int64_t n) { return n > 0 ? (int64_t)(next() % (uint64_t)n) : 0; }
  uint32_t tie() { return (uint32_t)(next() >> 32); }
};

// -------------------------------------------------------------- Graph --
struct Graph {
  int64_t n = 0, m = 0;
  const int64_t* xadj = nullptr;
  const int32_t* adjncy = nullptr;
  const int64_t* node_w = nullptr;
  const int64_t* edge_w = nullptr;
  // backing storage for coarse levels (views point into these)
  std::vector<int64_t> xadj_v, node_w_v, edge_w_v;
  std::vector<int32_t> adjncy_v;

  void adopt() {
    xadj = xadj_v.data();
    adjncy = adjncy_v.data();
    node_w = node_w_v.data();
    edge_w = edge_w_v.data();
    n = (int64_t)xadj_v.size() - 1;
    m = (int64_t)adjncy_v.size();
  }
  int64_t total_node_weight() const {
    int64_t t = 0;
    for (int64_t u = 0; u < n; ++u) t += node_w[u];
    return t;
  }
};

struct Level {
  Graph coarse;
  std::vector<int32_t> cmap;  // fine node -> coarse node
};

// ------------------------------------------------- LP coarsening pass --
// Async in-order size-constrained LP (initial_coarsener.cc behavior):
// visit nodes in random order, move each to its best-rated cluster under
// the weight cap.  Dense rating array + touched list (the RatingMap
// small-map analog at these sizes).
int64_t lp_cluster(const Graph& g, int64_t max_cluster_weight, Rng& rng,
                   std::vector<int32_t>& labels, int iterations = 3) {
  const int64_t n = g.n;
  labels.resize(n);
  for (int64_t u = 0; u < n; ++u) labels[u] = (int32_t)u;
  if (n == 0 || g.m == 0) return n;

  std::vector<int64_t> cw(g.node_w, g.node_w + n);
  std::vector<int64_t> rating(n, 0);
  std::vector<int32_t> touched;
  touched.reserve(64);
  std::vector<int32_t> order(n);
  for (int64_t u = 0; u < n; ++u) order[u] = (int32_t)u;

  for (int it = 0; it < iterations; ++it) {
    // Fisher–Yates shuffle
    for (int64_t i = n - 1; i > 0; --i) {
      int64_t j = rng.below(i + 1);
      std::swap(order[i], order[j]);
    }
    int64_t moves = 0;
    for (int64_t idx = 0; idx < n; ++idx) {
      const int32_t u = order[idx];
      const int64_t lo = g.xadj[u], hi = g.xadj[u + 1];
      if (lo == hi) continue;
      touched.clear();
      for (int64_t e = lo; e < hi; ++e) {
        const int32_t c = labels[g.adjncy[e]];
        if (rating[c] == 0) touched.push_back(c);
        rating[c] += g.edge_w[e];
      }
      const int32_t own = labels[u];
      const int64_t wu = g.node_w[u];
      int64_t best_r = (rating[own] > 0) ? rating[own] : 0;
      int32_t best_c = own;
      uint32_t best_t = 0;
      for (int32_t c : touched) {
        if (c == own) continue;
        if (cw[c] + wu > max_cluster_weight) continue;
        const int64_t r = rating[c];
        if (r > best_r) {
          best_r = r;
          best_c = c;
          best_t = rng.tie();
        } else if (r == best_r && r > 0) {
          const uint32_t t = rng.tie();
          if (t > best_t) {
            best_c = c;
            best_t = t;
          }
        }
      }
      for (int32_t c : touched) rating[c] = 0;
      if (best_c != own) {
        cw[own] -= wu;
        cw[best_c] += wu;
        labels[u] = best_c;
        ++moves;
      }
    }
    if (moves == 0) break;
  }
  // count distinct clusters
  std::vector<int64_t> seen(n, 0);
  int64_t distinct = 0;
  for (int64_t u = 0; u < n; ++u) {
    if (!seen[labels[u]]) {
      seen[labels[u]] = 1;
      ++distinct;
    }
  }
  return distinct;
}

// ------------------------------------------------------- contraction --
// Sequential analog of contraction/cluster_contraction.h: dense leader
// remap, bucket fine nodes by coarse id, dedup edges per coarse node
// with a dense rating map.
void contract(const Graph& g, const std::vector<int32_t>& labels,
              Graph& coarse, std::vector<int32_t>& cmap) {
  const int64_t n = g.n;
  std::vector<int32_t> remap(n, -1);
  cmap.resize(n);
  int32_t c_n = 0;
  for (int64_t u = 0; u < n; ++u) {
    int32_t l = labels[u];
    if (remap[l] < 0) remap[l] = c_n++;
    cmap[u] = remap[l];
  }
  // bucket fine nodes by coarse id (counting sort)
  std::vector<int64_t> bstart(c_n + 1, 0);
  for (int64_t u = 0; u < n; ++u) ++bstart[cmap[u] + 1];
  for (int32_t c = 0; c < c_n; ++c) bstart[c + 1] += bstart[c];
  std::vector<int32_t> bucket(n);
  {
    std::vector<int64_t> pos(bstart.begin(), bstart.end() - 1);
    for (int64_t u = 0; u < n; ++u) bucket[pos[cmap[u]]++] = (int32_t)u;
  }
  coarse.node_w_v.assign(c_n, 0);
  for (int64_t u = 0; u < n; ++u) coarse.node_w_v[cmap[u]] += g.node_w[u];

  coarse.xadj_v.assign(c_n + 1, 0);
  coarse.adjncy_v.clear();
  coarse.edge_w_v.clear();
  std::vector<int64_t> rating(c_n, 0);
  std::vector<int32_t> touched;
  for (int32_t c = 0; c < c_n; ++c) {
    touched.clear();
    for (int64_t i = bstart[c]; i < bstart[c + 1]; ++i) {
      const int32_t u = bucket[i];
      for (int64_t e = g.xadj[u]; e < g.xadj[u + 1]; ++e) {
        const int32_t cv = cmap[g.adjncy[e]];
        if (cv == c) continue;
        if (rating[cv] == 0) touched.push_back(cv);
        rating[cv] += g.edge_w[e];
      }
    }
    for (int32_t cv : touched) {
      coarse.adjncy_v.push_back(cv);
      coarse.edge_w_v.push_back(rating[cv]);
      rating[cv] = 0;
    }
    coarse.xadj_v[c + 1] = (int64_t)coarse.adjncy_v.size();
  }
  coarse.adopt();
}

// ------------------------------------------------- flat bipartitioners --
// Shared growth postlude: admit a random weight-prefix of the remainder
// (the fragmented-remainder bulk admit both python growers use).
void bulk_admit_rest(const Graph& g, std::vector<int8_t>& part, int64_t& w0,
                     int64_t target0, int64_t stop_at,
                     const std::vector<int8_t>& taken, Rng& rng) {
  std::vector<int32_t> rest;
  for (int64_t u = 0; u < g.n; ++u)
    if (!taken[u]) rest.push_back((int32_t)u);
  for (int64_t i = (int64_t)rest.size() - 1; i > 0; --i)
    std::swap(rest[i], rest[rng.below(i + 1)]);
  for (int32_t u : rest) {
    if (w0 >= stop_at) break;
    if (w0 + g.node_w[u] <= target0) {
      part[u] = 0;
      w0 += g.node_w[u];
    }
  }
}

void random_bipartition(const Graph& g, const int64_t max_bw[2], Rng& rng,
                        std::vector<int8_t>& part) {
  const int64_t n = g.n;
  part.assign(n, 0);
  int64_t w[2] = {0, 0};
  std::vector<int32_t> order(n);
  for (int64_t u = 0; u < n; ++u) order[u] = (int32_t)u;
  for (int64_t i = n - 1; i > 0; --i)
    std::swap(order[i], order[rng.below(i + 1)]);
  for (int64_t i = 0; i < n; ++i) {
    const int32_t u = order[i];
    int b = (int)(rng.next() & 1);
    if (w[b] + g.node_w[u] > max_bw[b]) b = 1 - b;
    part[u] = (int8_t)b;
    w[b] += g.node_w[u];
  }
}

// Greedy BFS growth (initial_bfs_bipartitioner.h:41): grow block 0 from
// a random seed node-by-node in queue order, skipping too-heavy nodes,
// reseeding into unexplored components.
void bfs_bipartition(const Graph& g, const int64_t max_bw[2], Rng& rng,
                     std::vector<int8_t>& part) {
  const int64_t n = g.n;
  part.assign(n, 1);
  if (n == 0) return;
  const int64_t total = g.total_node_weight();
  const int64_t target0 = max_bw[0];
  const int64_t stop_at = std::max(total - max_bw[1], (total + 1) / 2);

  std::vector<int8_t> visited(n, 0);
  std::vector<int32_t> queue;
  queue.reserve(n);
  int64_t head = 0;
  int64_t w0 = 0;
  int32_t seed = (int32_t)rng.below(n);
  visited[seed] = 1;
  queue.push_back(seed);
  int64_t visited_count = 1;
  while (w0 < stop_at) {
    if (head == (int64_t)queue.size()) {
      if (visited_count == n) break;
      // reseed into an unvisited component
      int32_t s = -1;
      // random probe first (fast on large remainders), linear fallback
      for (int tries = 0; tries < 16; ++tries) {
        int32_t c = (int32_t)rng.below(n);
        if (!visited[c]) {
          s = c;
          break;
        }
      }
      if (s < 0) {
        for (int64_t u = 0; u < n; ++u)
          if (!visited[u]) {
            s = (int32_t)u;
            break;
          }
      }
      visited[s] = 1;
      ++visited_count;
      queue.push_back(s);
    }
    const int32_t u = queue[head++];
    if (w0 + g.node_w[u] <= target0) {
      part[u] = 0;
      w0 += g.node_w[u];
    }
    for (int64_t e = g.xadj[u]; e < g.xadj[u + 1]; ++e) {
      const int32_t v = g.adjncy[e];
      if (!visited[v]) {
        visited[v] = 1;
        ++visited_count;
        queue.push_back(v);
      }
    }
  }
}

// Greedy graph growing (initial_ggg_bipartitioner.h:18): absorb the
// frontier node with the highest gain (connection to block 0 minus
// connection to block 1 approximated as connection growth, like the
// python port: gain = accumulated connection to block 0).
void ggg_bipartition(const Graph& g, const int64_t max_bw[2], Rng& rng,
                     std::vector<int8_t>& part) {
  const int64_t n = g.n;
  part.assign(n, 1);
  if (n == 0) return;
  const int64_t total = g.total_node_weight();
  const int64_t target0 = max_bw[0];
  const int64_t stop_at = std::max(total - max_bw[1], (total + 1) / 2);

  std::vector<int64_t> gain(n, -1);
  std::vector<int8_t> taken(n, 0);
  using Entry = std::tuple<int64_t, uint32_t, int32_t>;  // (gain, tie, u)
  std::priority_queue<Entry> pq;
  int32_t seed = (int32_t)rng.below(n);
  gain[seed] = 0;
  pq.push({0, rng.tie(), seed});
  int64_t w0 = 0;
  while (w0 < stop_at) {
    int32_t u = -1;
    while (!pq.empty()) {
      auto [gq, t, cand] = pq.top();
      pq.pop();
      if (!taken[cand] && gain[cand] == gq) {
        u = cand;
        break;
      }
    }
    if (u < 0) {
      // reseed or bulk-admit the fragmented remainder
      int32_t s = -1;
      for (int tries = 0; tries < 16; ++tries) {
        int32_t c = (int32_t)rng.below(n);
        if (!taken[c] && gain[c] < 0) {
          s = c;
          break;
        }
      }
      if (s < 0) {
        bulk_admit_rest(g, part, w0, target0, stop_at, taken, rng);
        break;
      }
      gain[s] = 0;
      pq.push({0, rng.tie(), s});
      continue;
    }
    if (w0 + g.node_w[u] > target0) {
      taken[u] = 1;  // too heavy: drop from frontier, stays in block 1
      continue;
    }
    taken[u] = 1;
    part[u] = 0;
    w0 += g.node_w[u];
    for (int64_t e = g.xadj[u]; e < g.xadj[u + 1]; ++e) {
      const int32_t v = g.adjncy[e];
      if (taken[v]) continue;
      gain[v] = (gain[v] < 0 ? 0 : gain[v]) + g.edge_w[e];
      pq.push({gain[v], rng.tie(), v});
    }
  }
}

// ------------------------------------------------------------ metrics --
int64_t cut_of(const Graph& g, const std::vector<int8_t>& part) {
  int64_t cut = 0;
  for (int64_t u = 0; u < g.n; ++u)
    for (int64_t e = g.xadj[u]; e < g.xadj[u + 1]; ++e)
      if (part[u] != part[g.adjncy[e]]) cut += g.edge_w[e];
  return cut / 2;
}

int64_t overload_of(const Graph& g, const std::vector<int8_t>& part,
                    const int64_t max_bw[2]) {
  int64_t w[2] = {0, 0};
  for (int64_t u = 0; u < g.n; ++u) w[part[u]] += g.node_w[u];
  return std::max<int64_t>(w[0] - max_bw[0], 0) +
         std::max<int64_t>(w[1] - max_bw[1], 0);
}

// ------------------------------------------------------------- 2-way FM --
struct FmConfig {
  int disabled;
  int stopping_rule;  // 0 = simple, 1 = adaptive
  int64_t num_fruitless_moves;
  double alpha;
  int64_t num_iterations;
};

// One FM pass (initial_fm_refiner.h:68 / python _fm_pass): two PQs with
// lazy deletion, best-prefix rollback, simple/adaptive stopping.
int64_t fm_pass(const Graph& g, std::vector<int8_t>& part,
                const int64_t max_bw[2], const FmConfig& cfg, Rng& rng) {
  const int64_t n = g.n;
  std::vector<int64_t> gain(n, 0);
  int64_t block_w[2] = {0, 0};
  for (int64_t u = 0; u < n; ++u) {
    block_w[part[u]] += g.node_w[u];
    int64_t ext = 0, internal = 0;
    for (int64_t e = g.xadj[u]; e < g.xadj[u + 1]; ++e) {
      if (part[g.adjncy[e]] != part[u])
        ext += g.edge_w[e];
      else
        internal += g.edge_w[e];
    }
    gain[u] = ext - internal;
  }
  using Entry = std::tuple<int64_t, uint32_t, int32_t>;
  std::priority_queue<Entry> pqs[2];
  std::vector<uint32_t> tie(n);
  for (int64_t u = 0; u < n; ++u) {
    tie[u] = rng.tie();
    pqs[part[u]].push({gain[u], tie[u], (int32_t)u});
  }
  std::vector<int8_t> locked(n, 0);

  // stopping state
  int64_t fruitless = 0;
  int64_t steps = 0;
  double mean = 0.0, m2 = 0.0;

  std::vector<int32_t> moves;
  moves.reserve(n);
  int64_t cur_delta = 0, best_delta = 0;
  size_t best_len = 0;

  while (true) {
    // peek the best valid candidate of each block
    int have[2] = {0, 0};
    Entry top[2];
    for (int b = 0; b < 2; ++b) {
      auto& pq = pqs[b];
      while (!pq.empty()) {
        auto [gq, t, u] = pq.top();
        if (locked[u] || part[u] != b || gain[u] != gq) {
          pq.pop();
          continue;
        }
        top[b] = pq.top();
        have[b] = 1;
        break;
      }
    }
    int pick = -1;
    // prefer the feasible move with higher (gain, tie)
    for (int b = 0; b < 2; ++b) {
      if (!have[b]) continue;
      const int32_t u = std::get<2>(top[b]);
      if (block_w[1 - b] + g.node_w[u] > max_bw[1 - b]) continue;
      if (pick < 0 || top[b] > top[pick]) pick = b;
    }
    if (pick < 0) {
      // no balance-feasible move: move from the heavier block
      const int heavier = block_w[1] > block_w[0] ? 1 : 0;
      if (!have[heavier]) break;
      pick = heavier;
    }
    const auto [gq, t, u] = top[pick];
    const int b = pick;
    pqs[b].pop();

    locked[u] = 1;
    part[u] = (int8_t)(1 - b);
    block_w[b] -= g.node_w[u];
    block_w[1 - b] += g.node_w[u];
    cur_delta += gq;
    moves.push_back(u);

    // stopping update
    if (cfg.stopping_rule == 0) {
      fruitless = gq > 0 ? 0 : fruitless + 1;
    } else {
      ++steps;
      const double d = (double)gq - mean;
      mean += d / (double)steps;
      m2 += d * ((double)gq - mean);
    }
    if (cur_delta > best_delta) {
      best_delta = cur_delta;
      best_len = moves.size();
    }

    for (int64_t e = g.xadj[u]; e < g.xadj[u + 1]; ++e) {
      const int32_t v = g.adjncy[e];
      const int64_t w = g.edge_w[e];
      if (part[v] == b)
        gain[v] += 2 * w;
      else
        gain[v] -= 2 * w;
      if (!locked[v]) pqs[part[v]].push({gain[v], tie[v], v});
    }
    gain[u] = -gain[u];

    if (cfg.stopping_rule == 0) {
      if (fruitless >= cfg.num_fruitless_moves) break;
    } else if (steps >= 2) {
      const double variance = m2 / (double)(steps - 1);
      if (mean < 0 &&
          (double)steps * mean * mean > cfg.alpha * variance + 10.0)
        break;
    }
  }
  for (size_t i = best_len; i < moves.size(); ++i)
    part[moves[i]] = (int8_t)(1 - part[moves[i]]);
  return best_delta;
}

int64_t fm_refine(const Graph& g, std::vector<int8_t>& part,
                  const int64_t max_bw[2], const FmConfig& cfg, Rng& rng) {
  if (cfg.disabled || g.n == 0) return 0;
  int64_t total = 0;
  const int64_t iters = std::max<int64_t>(1, cfg.num_iterations);
  for (int64_t i = 0; i < iters; ++i) {
    const int64_t imp = fm_pass(g, part, max_bw, cfg, rng);
    total += imp;
    if (imp == 0) break;
  }
  return total;
}

// --------------------------------------------------------------- pool --
struct PoolConfig {
  int64_t min_reps, min_nonadaptive_reps, max_reps;
  double rep_multiplier;
  int adaptive;
  int enable[3];  // bfs, ggg, random
  FmConfig fm;
};

void pool_bipartition(const Graph& g, const int64_t max_bw[2],
                      const PoolConfig& cfg, Rng& rng,
                      std::vector<int8_t>& best_part) {
  struct PoolEntry {
    int which;  // 0 bfs, 1 ggg, 2 random
    int64_t runs = 0;
    double mean = 0.0;
  };
  std::vector<PoolEntry> entries;
  for (int i = 0; i < 3; ++i)
    if (cfg.enable[i]) entries.push_back({i});
  if (entries.empty()) entries.push_back({2});

  int64_t n_reps = (int64_t)std::llround(cfg.rep_multiplier *
                                         (double)cfg.min_reps);
  n_reps = std::max<int64_t>(1, std::min(n_reps, cfg.max_reps));

  std::vector<int8_t> part;
  int64_t best_overload = INT64_MAX, best_cut = INT64_MAX;
  best_part.assign(g.n, 0);
  for (int64_t rep = 0; rep < n_reps; ++rep) {
    size_t skip = entries.size();  // index of the entry to skip (none)
    if (cfg.adaptive && rep >= cfg.min_nonadaptive_reps &&
        entries.size() > 1) {
      // skip the worst-scoring bipartitioner this rep
      double worst = -1.0;
      for (size_t i = 0; i < entries.size(); ++i)
        if (entries[i].mean > worst) {
          worst = entries[i].mean;
          skip = i;
        }
    }
    for (size_t i = 0; i < entries.size(); ++i) {
      if (i == skip) continue;
      auto& entry = entries[i];
      switch (entry.which) {
        case 0: bfs_bipartition(g, max_bw, rng, part); break;
        case 1: ggg_bipartition(g, max_bw, rng, part); break;
        default: random_bipartition(g, max_bw, rng, part); break;
      }
      fm_refine(g, part, max_bw, cfg.fm, rng);
      const int64_t cut = cut_of(g, part);
      const int64_t overload = overload_of(g, part, max_bw);
      const double score = (double)cut + (double)overload * 1000.0;
      entry.runs += 1;
      entry.mean += (score - entry.mean) / (double)entry.runs;
      if (overload < best_overload ||
          (overload == best_overload && cut < best_cut)) {
        best_overload = overload;
        best_cut = cut;
        best_part = part;
      }
    }
  }
}

}  // namespace

// ------------------------------------------------------------- C ABI --
extern "C" int64_t kmp_ml_bipartition(
    int64_t n, const int64_t* xadj, const int32_t* adjncy,
    const int64_t* node_w, const int64_t* edge_w, int64_t max_w0,
    int64_t max_w1,
    // initial coarsening (initial_coarsener.cc loop)
    int64_t ic_contraction_limit, double ic_convergence_threshold,
    int64_t max_cluster_weight,
    // pool (initial_pool_bipartitioner.h)
    int64_t pool_min_reps, int64_t pool_min_nonadaptive_reps,
    int64_t pool_max_reps, double pool_rep_multiplier, int32_t pool_adaptive,
    int32_t enable_bfs, int32_t enable_ggg, int32_t enable_random,
    // pool-internal FM
    int32_t pfm_disabled, int32_t pfm_stopping_rule,
    int64_t pfm_num_fruitless_moves, double pfm_alpha,
    int64_t pfm_num_iterations,
    // per-level FM (outer refinement ctx)
    int32_t fm_disabled, int32_t fm_stopping_rule,
    int64_t fm_num_fruitless_moves, double fm_alpha, int64_t fm_num_iterations,
    uint64_t seed, int8_t* out_part) {
  if (n <= 0) return 0;
  Rng rng(seed);
  const int64_t max_bw[2] = {max_w0, max_w1};

  Graph root;
  root.n = n;
  root.m = xadj[n];
  root.xadj = xadj;
  root.adjncy = adjncy;
  root.node_w = node_w;
  root.edge_w = edge_w;

  // --- coarsen (coarsen_for_bipartition) ---
  // deque, NOT vector: `current` points into the container while new
  // levels are appended; vector reallocation would dangle it
  std::deque<Level> levels;
  const Graph* current = &root;
  const int64_t limit = 2 * ic_contraction_limit;
  std::vector<int32_t> labels;
  while (current->n > limit) {
    const int64_t distinct =
        lp_cluster(*current, max_cluster_weight, rng, labels);
    if ((double)distinct >=
        (1.0 - ic_convergence_threshold) * (double)current->n)
      break;  // converged, not shrinking enough
    levels.emplace_back();
    contract(*current, labels, levels.back().coarse, levels.back().cmap);
    current = &levels.back().coarse;
  }

  // --- flat pool on the coarsest ---
  PoolConfig pool_cfg;
  pool_cfg.min_reps = pool_min_reps;
  pool_cfg.min_nonadaptive_reps = pool_min_nonadaptive_reps;
  pool_cfg.max_reps = pool_max_reps;
  pool_cfg.rep_multiplier = pool_rep_multiplier;
  pool_cfg.adaptive = pool_adaptive;
  pool_cfg.enable[0] = enable_bfs;
  pool_cfg.enable[1] = enable_ggg;
  pool_cfg.enable[2] = enable_random;
  pool_cfg.fm = {pfm_disabled, pfm_stopping_rule, pfm_num_fruitless_moves,
                 pfm_alpha, pfm_num_iterations};
  std::vector<int8_t> part;
  pool_bipartition(*current, max_bw, pool_cfg, rng, part);

  // --- uncoarsen with FM per level ---
  const FmConfig fm_cfg = {fm_disabled, fm_stopping_rule,
                           fm_num_fruitless_moves, fm_alpha,
                           fm_num_iterations};
  for (int64_t i = (int64_t)levels.size() - 1; i >= 0; --i) {
    const auto& cmap = levels[i].cmap;
    const Graph& fine = (i > 0) ? levels[i - 1].coarse : root;
    std::vector<int8_t> fine_part(fine.n);
    for (int64_t u = 0; u < fine.n; ++u) fine_part[u] = part[cmap[u]];
    part.swap(fine_part);
    fm_refine(fine, part, max_bw, fm_cfg, rng);
  }

  std::memcpy(out_part, part.data(), (size_t)n);
  return cut_of(root, part);
}
