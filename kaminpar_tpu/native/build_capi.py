"""Build libckaminpar_tpu.so — the C ABI shared library.

Usage: python -m kaminpar_tpu.native.build_capi [output_dir]

Compiles kaminpar_tpu/native/ckaminpar.cpp against the running
interpreter's embedding flags (python3-config --embed) so C/C++ programs
can link the partitioner via include/ckaminpar_tpu.h — the parity path
for the reference's ckaminpar C API target.
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig


def build(out_dir: str | None = None) -> str:
    src_dir = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(src_dir, "ckaminpar.cpp")
    out_dir = out_dir or src_dir
    out = os.path.join(out_dir, "libckaminpar_tpu.so")

    include = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    version = sysconfig.get_config_var("LDVERSION") or sysconfig.get_config_var(
        "VERSION"
    )
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17", src,
        f"-I{include}",
        f"-L{libdir}",
        f"-lpython{version}",
        "-o", out,
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return out


if __name__ == "__main__":
    path = build(sys.argv[1] if len(sys.argv) > 1 else None)
    print(path)
