"""Build libckaminpar_tpu.so — the C ABI shared library.

Usage: python -m kaminpar_tpu.native.build_capi [output_dir]

Compiles kaminpar_tpu/native/ckaminpar.cpp against the running
interpreter's embedding flags (python3-config --embed) so C/C++ programs
can link the partitioner via include/ckaminpar_tpu.h — the parity path
for the reference's ckaminpar C API target.
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig


def build(out_dir: str | None = None) -> str:
    src_dir = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(src_dir, "ckaminpar.cpp")
    from . import sanitize_flags

    if sanitize_flags() and out_dir is None:
        # the fixed link name (-lckaminpar_tpu) cannot key the sanitize
        # mode, so a sanitized build must never overwrite the package
        # dir's plain artifact — a later plain consumer would abort at
        # load (libasan not preloaded) with nothing in the filename to
        # explain why
        raise ValueError(
            "KMP_SANITIZE is set: pass an explicit output dir so the "
            "sanitized libckaminpar_tpu.so cannot shadow the plain one "
            "(scripts/run_native_sanitized.sh builds into a tmp dir)"
        )
    out_dir = out_dir or src_dir
    out = os.path.join(out_dir, "libckaminpar_tpu.so")

    include = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    version = sysconfig.get_config_var("LDVERSION") or sysconfig.get_config_var(
        "VERSION"
    )
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        *sanitize_flags(),
        src,
        f"-I{include}",
        f"-L{libdir}",
        f"-lpython{version}",
        "-o", out,
    ]
    # the compile runs under the shared native-build timeout
    # (KAMINPAR_TPU_NATIVE_BUILD_TIMEOUT) and surfaces failure as the
    # structured NativeUnavailable of the `native-build` degradation
    # site; a stale/corrupted previous artifact gets one clean retry
    # (link errors against a half-written .so are retried without it)
    from . import build_timeout
    from ..resilience import NativeUnavailable

    for attempt in (0, 1):
        try:
            subprocess.run(
                cmd, check=True, capture_output=True, text=True,
                timeout=build_timeout(),
            )
            return out
        except subprocess.TimeoutExpired as e:
            raise NativeUnavailable(
                f"C-API build timed out after {build_timeout():.0f}s "
                "(KAMINPAR_TPU_NATIVE_BUILD_TIMEOUT raises the limit)"
            ) from e
        except subprocess.CalledProcessError as e:
            if attempt == 0 and os.path.exists(out):
                try:
                    os.remove(out)  # clean-rebuild retry
                    continue
                except OSError:
                    pass
            raise NativeUnavailable(
                f"C-API build failed: {(e.stderr or '')[-400:]}"
            ) from e
        except OSError as e:
            raise NativeUnavailable(f"toolchain unavailable: {e}") from e
    raise AssertionError("unreachable")


if __name__ == "__main__":
    path = build(sys.argv[1] if len(sys.argv) > 1 else None)
    print(path)
