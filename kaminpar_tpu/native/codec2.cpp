// Native host runtime: TeraPart-parity neighborhood codec ("v2").
//
// The reference's compressed neighborhoods combine gap coding with
// interval encoding for runs of consecutive ids and an SIMD StreamVByte
// batch codec (kaminpar-common/graph_compression/
// compressed_neighborhoods.h:52-60, streamvbyte.h, varint.h), plus
// interleaved varint edge weights.  This file is the framework's native
// equivalent, one stream per node:
//
//   varint(num_intervals)
//   per interval: varint(delta_left), varint(len - MIN_INTERVAL)
//     (left endpoints gap-coded against the previous interval's end;
//      first one biased +1)
//   per residual group of 4: one control byte (2 bits per value =
//     byte length 1..4), then the packed value bytes — the StreamVByte
//     wire idea in scalar form (gaps: first residual biased +1, then
//     diffs against the previous residual)
//
// Edge weights ride in a SEPARATE varint stream in EMIT order (interval
// members first, then residuals), so decoded adjacency and weights pair
// 1:1.  The reference's high-degree split exists to parallelize decode
// across threads; bulk decode here is a single native pass, so the split
// is unnecessary — degree skew costs nothing.
//
// C ABI consumed via ctypes (kaminpar_tpu/native/__init__.py).

#include <cstdint>
#include <cstring>

#if defined(__SSSE3__)
#include <tmmintrin.h>
#endif

namespace {

#if defined(__SSSE3__)
// StreamVByte SIMD decode tables (streamvbyte.h parity): for each
// control byte, a pshufb mask scattering the packed 1..4-byte values
// into four u32 lanes, and the group's total payload length.
struct SvbTables {
  alignas(16) uint8_t shuf[256][16];
  uint8_t len[256];
};

inline const SvbTables& svb_tables() {
  static const SvbTables t = [] {
    SvbTables t{};
    for (int c = 0; c < 256; ++c) {
      int pos = 0;
      for (int i = 0; i < 4; ++i) {
        const int l = ((c >> (2 * i)) & 3) + 1;
        for (int b = 0; b < 4; ++b)
          t.shuf[c][4 * i + b] =
              b < l ? (uint8_t)(pos + b) : (uint8_t)0xFF;
        pos += l;
      }
      t.len[c] = (uint8_t)pos;
    }
    return t;
  }();
  return t;
}
#endif

constexpr int64_t MIN_INTERVAL = 3;  // compressed_neighborhoods interval
                                     // length threshold

inline int varint_size64(uint64_t x) {
  int s = 1;
  while (x >= 0x80) {
    x >>= 7;
    ++s;
  }
  return s;
}

inline uint8_t* varint_write64(uint8_t* p, uint64_t x) {
  while (x >= 0x80) {
    *p++ = (uint8_t)(x | 0x80);
    x >>= 7;
  }
  *p++ = (uint8_t)x;
  return p;
}

inline const uint8_t* varint_read64(const uint8_t* p, uint64_t* out) {
  uint64_t x = 0;
  int shift = 0;
  while (true) {
    const uint8_t b = *p++;
    x |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
  }
  *out = x;
  return p;
}

inline int svb_len(uint32_t x) {
  return x < (1u << 8) ? 1 : x < (1u << 16) ? 2 : x < (1u << 24) ? 3 : 4;
}

// walk one sorted neighborhood, classifying runs >= MIN_INTERVAL as
// intervals; calls iv(left, len) then res(value) per residual
template <class IvFn, class ResFn>
inline void walk(const int32_t* nb, int64_t deg, IvFn&& iv, ResFn&& res) {
  int64_t i = 0;
  while (i < deg) {
    int64_t j = i + 1;
    while (j < deg && nb[j] == nb[j - 1] + 1) ++j;
    if (j - i >= MIN_INTERVAL)
      iv((uint32_t)nb[i], (uint32_t)(j - i));
    else
      for (int64_t t = i; t < j; ++t) res((uint32_t)nb[t]);
    i = j;
  }
}

}  // namespace

extern "C" {

// sizes pass: fills offsets[n+1] (byte offsets per node), returns total
int64_t kmp_encode_v2_size(int64_t n, const int64_t* xadj,
                           const int32_t* adjncy, int64_t* offsets) {
  int64_t total = 0;
  for (int64_t u = 0; u < n; ++u) {
    offsets[u] = total;
    const int32_t* nb = adjncy + xadj[u];
    const int64_t deg = xadj[u + 1] - xadj[u];
    if (deg == 0) continue;
    int64_t n_iv = 0, sz_iv = 0, n_res = 0, sz_res = 0;
    uint32_t prev_end = 0;  // bias handled below
    bool first_iv = true;
    uint32_t prev_res = 0;
    bool first_res = true;
    walk(
        nb, deg,
        [&](uint32_t left, uint32_t len) {
          const uint32_t delta =
              first_iv ? left + 1 : left - prev_end;
          sz_iv += varint_size64(delta) +
                   varint_size64(len - MIN_INTERVAL);
          prev_end = left + len - 1;
          first_iv = false;
          ++n_iv;
        },
        [&](uint32_t v) {
          const uint32_t gap = first_res ? v + 1 : v - prev_res;
          sz_res += svb_len(gap);
          prev_res = v;
          first_res = false;
          ++n_res;
        });
    total += varint_size64((uint64_t)n_iv) + sz_iv;
    total += (n_res + 3) / 4 + sz_res;  // control bytes + data
  }
  offsets[n] = total;
  return total;
}

void kmp_encode_v2(int64_t n, const int64_t* xadj, const int32_t* adjncy,
                   const int64_t* offsets, uint8_t* out) {
  for (int64_t u = 0; u < n; ++u) {
    uint8_t* p = out + offsets[u];
    const int32_t* nb = adjncy + xadj[u];
    const int64_t deg = xadj[u + 1] - xadj[u];
    if (deg == 0) continue;
    // pass 1: collect interval/residual split
    int64_t n_iv = 0;
    walk(nb, deg, [&](uint32_t, uint32_t) { ++n_iv; }, [&](uint32_t) {});
    p = varint_write64(p, (uint64_t)n_iv);
    uint32_t prev_end = 0;
    bool first_iv = true;
    // residual staging (gaps)
    uint32_t gaps[4];
    int ngap = 0;
    uint32_t prev_res = 0;
    bool first_res = true;
    // control/data write positions: count residuals first
    int64_t n_res = 0;
    walk(nb, deg, [&](uint32_t, uint32_t) {}, [&](uint32_t) { ++n_res; });
    // write intervals
    walk(
        nb, deg,
        [&](uint32_t left, uint32_t len) {
          const uint32_t delta = first_iv ? left + 1 : left - prev_end;
          p = varint_write64(p, delta);
          p = varint_write64(p, len - MIN_INTERVAL);
          prev_end = left + len - 1;
          first_iv = false;
        },
        [&](uint32_t) {});
    // write residuals: control bytes interleaved per group of 4
    uint8_t* ctrl = p;
    uint8_t* data = p + (n_res + 3) / 4;
    auto flush = [&]() {
      if (ngap == 0) return;
      uint8_t c = 0;
      for (int i = 0; i < ngap; ++i) {
        const int len = svb_len(gaps[i]);
        c |= (uint8_t)(len - 1) << (2 * i);
        for (int b = 0; b < len; ++b) {
          *data++ = (uint8_t)(gaps[i] & 0xFF);
          gaps[i] >>= 8;
        }
      }
      *ctrl++ = c;
      ngap = 0;
    };
    walk(
        nb, deg, [&](uint32_t, uint32_t) {},
        [&](uint32_t v) {
          const uint32_t gap = first_res ? v + 1 : v - prev_res;
          prev_res = v;
          first_res = false;
          gaps[ngap++] = gap;
          if (ngap == 4) flush();
        });
    flush();
  }
}

// decode ALL neighborhoods; out must hold xadj[n] entries.  Neighbors
// are emitted interval-members-first (matching the weight stream order).
void kmp_decode_v2(int64_t n, const int64_t* xadj, const int64_t* offsets,
                   const uint8_t* data, int32_t* out) {
  for (int64_t u = 0; u < n; ++u) {
    const uint8_t* p = data + offsets[u];
    const int64_t deg = xadj[u + 1] - xadj[u];
    if (deg == 0) continue;
    int32_t* o = out + xadj[u];
    uint64_t n_iv;
    p = varint_read64(p, &n_iv);
    uint32_t prev_end = 0;
    int64_t emitted = 0;
    for (uint64_t i = 0; i < n_iv; ++i) {
      uint64_t delta, lenm;
      p = varint_read64(p, &delta);
      p = varint_read64(p, &lenm);
      const uint32_t left = (i == 0) ? (uint32_t)delta - 1
                                     : prev_end + (uint32_t)delta;
      const uint32_t len = (uint32_t)lenm + MIN_INTERVAL;
      for (uint32_t t = 0; t < len; ++t) *o++ = (int32_t)(left + t);
      prev_end = left + len - 1;
      emitted += len;
    }
    const int64_t n_res = deg - emitted;
    const uint8_t* ctrl = p;
    const uint8_t* d = p + (n_res + 3) / 4;
    uint32_t prev = 0;
    int64_t i = 0;
#if defined(__SSSE3__)
    if (n_res >= 8) {
      const SvbTables& T = svb_tables();
      // exact payload size from the control stream bounds the 16-byte
      // loads.  The final PARTIAL group must be summed field-by-field:
      // its unused 2-bit controls are zero, which T.len would count as
      // 1 byte each — overshooting the true buffer end by up to 3
      // bytes and letting the last SIMD load read past the allocation.
      const int64_t nfull = n_res / 4;
      int64_t payload = 0;
      for (int64_t g = 0; g < nfull; ++g) payload += T.len[ctrl[g]];
      for (int64_t r = 4 * nfull; r < n_res; ++r)
        payload += ((ctrl[r >> 2] >> (2 * (r & 3))) & 3) + 1;
      const uint8_t* d_end = d + payload;
      // group 0 scalar: the first-residual bias lives there
      for (; i < 4; ++i) {
        const int len = ((ctrl[0] >> (2 * i)) & 3) + 1;
        uint32_t v = 0;
        for (int b = 0; b < len; ++b) v |= (uint32_t)(*d++) << (8 * b);
        prev = (i == 0) ? v - 1 : prev + v;
        *o++ = (int32_t)prev;
      }
      // full groups: one pshufb + two shifted adds (in-register prefix
      // sum of the gaps) per 4 values — the streamvbyte.h decode shape
      __m128i vprev = _mm_set1_epi32((int)prev);
      while (i + 4 <= n_res && d + 16 <= d_end) {
        const uint8_t c = ctrl[i >> 2];
        const __m128i raw = _mm_loadu_si128((const __m128i*)d);
        __m128i gaps = _mm_shuffle_epi8(
            raw, _mm_load_si128((const __m128i*)T.shuf[c]));
        gaps = _mm_add_epi32(gaps, _mm_slli_si128(gaps, 4));
        gaps = _mm_add_epi32(gaps, _mm_slli_si128(gaps, 8));
        const __m128i vals = _mm_add_epi32(gaps, vprev);
        _mm_storeu_si128((__m128i*)o, vals);
        o += 4;
        vprev = _mm_shuffle_epi32(vals, _MM_SHUFFLE(3, 3, 3, 3));
        d += T.len[c];
        i += 4;
      }
      prev = (uint32_t)_mm_cvtsi128_si32(vprev);
    }
#endif
    for (; i < n_res; ++i) {
      const int len = ((ctrl[i >> 2] >> (2 * (i & 3))) & 3) + 1;
      uint32_t v = 0;
      for (int b = 0; b < len; ++b) v |= (uint32_t)(*d++) << (8 * b);
      prev = (i == 0) ? v - 1 : prev + v;
      *o++ = (int32_t)prev;
    }
  }
}

int64_t kmp_decode_v2_node(int64_t u, const int64_t* xadj,
                           const int64_t* offsets, const uint8_t* data,
                           int32_t* out) {
  int64_t x2[2] = {0, xadj[u + 1] - xadj[u]};
  int64_t o2[2] = {0, 0};
  kmp_decode_v2(1, x2, o2, data + offsets[u], out);
  return x2[1];
}

// edge weights in EMIT order, varint per edge
int64_t kmp_encode_v2_weights_size(int64_t n, const int64_t* xadj,
                                   const int32_t* adjncy,
                                   const int64_t* edge_w,
                                   int64_t* woffsets) {
  int64_t total = 0;
  for (int64_t u = 0; u < n; ++u) {
    woffsets[u] = total;
    const int32_t* nb = adjncy + xadj[u];
    const int64_t deg = xadj[u + 1] - xadj[u];
    const int64_t* w = edge_w + xadj[u];
    // emit order: walk twice (intervals, then residuals), tracking the
    // source position of each neighbor
    int64_t pos = 0;
    walk(
        nb, deg,
        [&](uint32_t, uint32_t len) {
          for (uint32_t t = 0; t < len; ++t)
            total += varint_size64((uint64_t)w[pos++]);
        },
        [&](uint32_t) { ++pos; });
    // second pass for residual positions
    pos = 0;
    walk(
        nb, deg,
        [&](uint32_t, uint32_t len) { pos += len; },
        [&](uint32_t) { total += varint_size64((uint64_t)w[pos++]); });
  }
  woffsets[n] = total;
  return total;
}

void kmp_encode_v2_weights(int64_t n, const int64_t* xadj,
                           const int32_t* adjncy, const int64_t* edge_w,
                           const int64_t* woffsets, uint8_t* out) {
  for (int64_t u = 0; u < n; ++u) {
    uint8_t* p = out + woffsets[u];
    const int32_t* nb = adjncy + xadj[u];
    const int64_t deg = xadj[u + 1] - xadj[u];
    const int64_t* w = edge_w + xadj[u];
    int64_t pos = 0;
    walk(
        nb, deg,
        [&](uint32_t, uint32_t len) {
          for (uint32_t t = 0; t < len; ++t)
            p = varint_write64(p, (uint64_t)w[pos++]);
        },
        [&](uint32_t) { ++pos; });
    pos = 0;
    walk(
        nb, deg,
        [&](uint32_t, uint32_t len) { pos += len; },
        [&](uint32_t) { p = varint_write64(p, (uint64_t)w[pos++]); });
  }
}

void kmp_decode_v2_weights(int64_t n, const int64_t* xadj,
                           const int64_t* woffsets, const uint8_t* data,
                           int64_t* out) {
  for (int64_t u = 0; u < n; ++u) {
    const uint8_t* p = data + woffsets[u];
    const int64_t deg = xadj[u + 1] - xadj[u];
    int64_t* o = out + xadj[u];
    for (int64_t i = 0; i < deg; ++i) {
      uint64_t v;
      p = varint_read64(p, &v);
      *o++ = (int64_t)v;
    }
  }
}

}  // extern "C"
