// Localized batch k-way FM refinement (native host runtime).
//
// The native analog of the reference's parallel localized FM
// (kaminpar-shm/refinement/fm/fm_refiner.cc:48-110 FMRefiner/
// LocalizedFMRefiner, gains/delta_gain_caches.h:202): seed nodes are
// polled from a shared border queue, each batch grows a localized region
// speculatively against a DELTA overlay of the partition and gain table,
// and only the best prefix of the batch's moves is committed to the
// global state; non-moved region nodes are released for later batches.
// This is exactly the reference's scheme minus the thread pool — batches
// run one after another on the host (the TPU has no per-node PQ path;
// see kaminpar_tpu/refinement/fm.py) — with the same state machinery:
// dense (n, k) gain table (gains/sparse_gain_cache.h lineage), sparse
// delta map, adaptive (Osipov-Sanders) or simple stopping.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <queue>
#include <tuple>
#include <unordered_map>
#include <vector>

namespace {

struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed ^ 0x9E3779B97F4A7C15ULL) {
    if (s == 0) s = 0x2545F4914F6CDD1DULL;
  }
  uint64_t next() {
    uint64_t z = (s += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
  }
  uint32_t tie() { return (uint32_t)(next() >> 32); }
};

struct Ctx {
  int64_t n, k;
  const int64_t* xadj;
  const int32_t* adjncy;
  const int64_t* node_w;
  const int64_t* edge_w;
  const int64_t* max_bw;
  int32_t* part;
  std::vector<int64_t> conn;  // dense (n, k) connection table
  std::vector<int64_t> bw;    // global block weights

  int64_t conn_at(int64_t u, int64_t b) const { return conn[u * k + b]; }
};

// node states within a pass
enum : uint8_t { FREE = 0, IN_REGION = 1, MOVED = 2 };

void build_conn(Ctx& c) {
  std::fill(c.conn.begin(), c.conn.end(), 0);
  std::fill(c.bw.begin(), c.bw.end(), 0);
  for (int64_t u = 0; u < c.n; ++u) {
    c.bw[c.part[u]] += c.node_w[u];
    for (int64_t e = c.xadj[u]; e < c.xadj[u + 1]; ++e)
      c.conn[u * c.k + c.part[c.adjncy[e]]] += c.edge_w[e];
  }
}

// Delta overlay (delta_gain_caches.h analog): tentative partition and
// gain-table deltas for the current batch.  Touched nodes get a dense
// ARENA row copy of their (k-wide) connection row plus a tentative
// block field — one hash lookup per row access instead of k map probes
// per gain query (the hot path of the whole refiner).
struct Delta {
  const Ctx* c;
  std::unordered_map<int64_t, int32_t> slot;  // u -> arena index
  std::vector<int64_t> rows;                  // arena, k per slot
  std::vector<int32_t> blocks;                // arena slot -> tent. block
  std::vector<int64_t> bw_delta;

  explicit Delta(const Ctx& ctx) : c(&ctx), bw_delta(ctx.k, 0) {
    slot.reserve(1 << 14);
  }
  void clear() {
    slot.clear();
    rows.clear();
    blocks.clear();
    std::fill(bw_delta.begin(), bw_delta.end(), 0);
  }
  // arena row of u, materialized from the global table on first touch
  int64_t* row(int64_t u) {
    auto [it, fresh] = slot.try_emplace(u, (int32_t)blocks.size());
    if (fresh) {
      rows.insert(rows.end(), c->conn.begin() + u * c->k,
                  c->conn.begin() + (u + 1) * c->k);
      blocks.push_back(c->part[u]);
    }
    return rows.data() + (int64_t)it->second * c->k;
  }
  int32_t block(int64_t u) const {
    auto it = slot.find(u);
    return it == slot.end() ? c->part[u] : blocks[it->second];
  }
  // read-only row view (global when untouched)
  const int64_t* row_view(int64_t u) const {
    auto it = slot.find(u);
    return it == slot.end() ? c->conn.data() + u * c->k
                            : rows.data() + (int64_t)it->second * c->k;
  }
  int64_t weight(int64_t b) const { return c->bw[b] + bw_delta[b]; }
  // tentatively move u from -> to, updating neighbor rows
  void move(int64_t u, int32_t from, int32_t to) {
    row(u);  // materialize so the block override has a slot
    blocks[slot.find(u)->second] = to;
    bw_delta[from] -= c->node_w[u];
    bw_delta[to] += c->node_w[u];
    for (int64_t e = c->xadj[u]; e < c->xadj[u + 1]; ++e) {
      const int32_t v = c->adjncy[e];
      int64_t* r = row(v);
      r[from] -= c->edge_w[e];
      r[to] += c->edge_w[e];
    }
  }
};

// best feasible move of u under the delta view: (gain, target) or
// (INT64_MIN, -1)
std::pair<int64_t, int32_t> best_move(const Delta& d, int64_t u, Rng& rng) {
  const Ctx& c = *d.c;
  const int32_t b = d.block(u);
  const int64_t* r = d.row_view(u);
  const int64_t own = r[b];
  int64_t best_gain = INT64_MIN;
  int32_t best_t = -1;
  uint32_t best_tie = 0;
  for (int32_t t = 0; t < c.k; ++t) {
    if (t == b) continue;
    if (d.weight(t) + c.node_w[u] > c.max_bw[t]) continue;
    const int64_t g = r[t] - own;
    if (g > best_gain) {
      best_gain = g;
      best_t = t;
      best_tie = rng.tie();
    } else if (g == best_gain && best_t >= 0) {
      const uint32_t tb = rng.tie();
      if (tb > best_tie) {
        best_t = t;
        best_tie = tb;
      }
    }
  }
  return {best_gain, best_t};
}

// commit a move to the GLOBAL state
void commit_move(Ctx& c, int64_t u, int32_t from, int32_t to) {
  c.part[u] = to;
  c.bw[from] -= c.node_w[u];
  c.bw[to] += c.node_w[u];
  for (int64_t e = c.xadj[u]; e < c.xadj[u + 1]; ++e) {
    const int32_t v = c.adjncy[e];
    c.conn[(int64_t)v * c.k + from] -= c.edge_w[e];
    c.conn[(int64_t)v * c.k + to] += c.edge_w[e];
  }
}

struct Move {
  int64_t u;
  int32_t from, to;
  int64_t gain;
};

// one localized batch (LocalizedFMRefiner::run_batch); returns committed
// gain
int64_t run_batch(Ctx& c, Delta& d, std::vector<uint8_t>& state,
                  const std::vector<int64_t>& seeds, double alpha,
                  int64_t num_fruitless, int use_adaptive, Rng& rng) {
  d.clear();
  using Entry = std::tuple<int64_t, uint32_t, int64_t, int32_t>;
  std::priority_queue<Entry> pq;
  std::vector<int64_t> touched;

  auto push = [&](int64_t u) {
    auto [g, t] = best_move(d, u, rng);
    if (t >= 0) pq.push({g, rng.tie(), u, t});
  };
  for (int64_t s : seeds) {
    if (state[s] == FREE) {
      state[s] = IN_REGION;
      touched.push_back(s);
      push(s);
    }
  }

  std::vector<Move> moves;
  int64_t cur = 0, best = 0;
  size_t best_len = 0;
  int64_t fruitless = 0;
  int64_t steps = 0;
  double mean = 0.0, m2 = 0.0;
  const size_t max_moves = 4096;  // region safety cap

  while (!pq.empty() && moves.size() < max_moves) {
    auto [g, tie, u, t] = pq.top();
    pq.pop();
    if (state[u] == MOVED) continue;
    // stale check: gains shift as the region moves.  Re-queue only on a
    // GAIN change — the target may legitimately differ on ties (random
    // tie-break per query), and re-queuing on target alone could cycle
    auto [g2, t2] = best_move(d, u, rng);
    if (t2 < 0) continue;
    if (g2 != g) {
      pq.push({g2, rng.tie(), u, t2});
      continue;
    }
    t = t2;
    const int32_t b = d.block(u);
    d.move(u, b, t);
    moves.push_back({u, b, t, g2});
    cur += g2;
    if (cur > best) {
      best = cur;
      best_len = moves.size();
    }
    // expand: adjacent FREE nodes join the region
    for (int64_t e = c.xadj[u]; e < c.xadj[u + 1]; ++e) {
      const int32_t v = c.adjncy[e];
      if (state[v] == FREE) {
        state[v] = IN_REGION;
        touched.push_back(v);
        push(v);
      } else if (state[v] == IN_REGION) {
        push(v);
      }
    }
    // stopping policies (stopping_policies.h:16)
    if (use_adaptive) {
      ++steps;
      const double dlt = (double)g - mean;
      mean += dlt / (double)steps;
      m2 += dlt * ((double)g - mean);
      if (steps >= 2) {
        const double variance = m2 / (double)(steps - 1);
        if (mean < 0 &&
            (double)steps * mean * mean > alpha * variance + 10.0)
          break;
      }
    } else {
      fruitless = (g > 0) ? 0 : fruitless + 1;
      if (fruitless >= num_fruitless) break;
    }
  }

  // commit the best prefix globally; release the rest
  for (size_t i = 0; i < best_len; ++i) {
    commit_move(c, moves[i].u, moves[i].from, moves[i].to);
    state[moves[i].u] = MOVED;
  }
  for (int64_t u : touched)
    if (state[u] == IN_REGION) state[u] = FREE;
  return best;
}

}  // namespace

extern "C" int64_t kmp_fm_refine(
    int64_t n, const int64_t* xadj, const int32_t* adjncy,
    const int64_t* node_w, const int64_t* edge_w, int64_t k,
    const int64_t* max_bw, int32_t* part, int64_t num_iterations,
    int64_t num_seed_nodes, double alpha, int64_t num_fruitless_moves,
    int32_t use_adaptive, uint64_t seed) {
  if (n <= 0 || k <= 1) return 0;
  // dense (n, k) table: refuse absurd sizes (large-k uses other refiners)
  if (n * k > (int64_t)3e8) return 0;
  Ctx c{n, k, xadj, adjncy, node_w, edge_w, max_bw, part, {}, {}};
  c.conn.resize(n * k);
  c.bw.resize(k);
  Rng rng(seed);
  build_conn(c);

  int64_t total = 0;
  int64_t first_pass_gain = 0;
  std::vector<uint8_t> state(n);
  std::vector<int64_t> border;
  std::vector<int64_t> seeds;
  for (int64_t pass = 0; pass < std::max<int64_t>(1, num_iterations);
       ++pass) {
    // border nodes: nonzero external connection
    border.clear();
    for (int64_t u = 0; u < n; ++u) {
      const int64_t own = c.conn_at(u, c.part[u]);
      int64_t deg_w = 0;
      for (int64_t b = 0; b < k; ++b) deg_w += c.conn_at(u, b);
      if (deg_w > own) border.push_back(u);
    }
    if (border.empty()) break;
    for (int64_t i = (int64_t)border.size() - 1; i > 0; --i)
      std::swap(border[i], border[(int64_t)(rng.next() % (uint64_t)(i + 1))]);

    std::fill(state.begin(), state.end(), FREE);
    Delta d(c);
    int64_t pass_gain = 0;
    size_t head = 0;
    const int64_t nseeds = std::max<int64_t>(1, num_seed_nodes);
    while (head < border.size()) {
      seeds.clear();
      while (head < border.size() && (int64_t)seeds.size() < nseeds) {
        const int64_t u = border[head++];
        if (state[u] == FREE) seeds.push_back(u);
      }
      if (seeds.empty()) break;
      pass_gain += run_batch(c, d, state, seeds, alpha,
                             num_fruitless_moves, use_adaptive, rng);
    }
    total += pass_gain;
    if (pass_gain <= 0) break;
    // improvement abortion (initial_fm_refiner improvement_abortion
    // lineage): later passes chase diminishing returns at full pass cost
    if (pass == 0)
      first_pass_gain = pass_gain;
    else if (pass_gain * 20 < first_pass_gain)
      break;
  }
  return total;
}
