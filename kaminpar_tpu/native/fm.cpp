// Localized batch k-way FM refinement (native host runtime).
//
// The native analog of the reference's parallel localized FM
// (kaminpar-shm/refinement/fm/fm_refiner.cc:48-110 FMRefiner/
// LocalizedFMRefiner, gains/delta_gain_caches.h:202): seed nodes are
// polled from a shared border queue, each batch grows a localized region
// speculatively against a DELTA overlay of the partition and gain table,
// and only the best prefix of the batch's moves is committed to the
// global state; non-moved region nodes are released for later batches.
//
// Threading mirrors the reference's scheme: a pool of workers pulls seed
// batches from the shared border queue; per-node ownership claims (the
// NodeTracker analog, fm_refiner.cc NodeTracker) keep regions disjoint,
// global partition/gain-table/block-weight accesses go through relaxed
// std::atomic_ref (the reference's atomic gain cache), and commits
// re-check the block-weight caps with fetch_add + rollback so the cap
// is NEVER exceeded — stricter than the reference's transient
// overshoot.  num_threads <= 1 runs the identical code on one thread
// and visits exactly the old sequential state sequence (rerun
// determinism for tests and 1-CPU hosts).  Stale gains from concurrent
// commits are tolerated exactly like the reference tolerates them: the
// delta overlay re-checks gains before applying, and the global table
// stays exact because every update is an exact integer fetch_add.
//
// Dense (n, k) gain table (gains/sparse_gain_cache.h lineage), sparse
// delta map, adaptive (Osipov-Sanders) or simple stopping.

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <memory>
#include <queue>
#include <thread>
#include <tuple>
#include <unordered_map>
#include <vector>

namespace {

struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed ^ 0x9E3779B97F4A7C15ULL) {
    if (s == 0) s = 0x2545F4914F6CDD1DULL;
  }
  uint64_t next() {
    uint64_t z = (s += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
  }
  uint32_t tie() { return (uint32_t)(next() >> 32); }
};

constexpr auto kRelaxed = std::memory_order_relaxed;

struct Ctx {
  int64_t n, k;
  const int64_t* xadj;
  const int32_t* adjncy;
  const int64_t* node_w;
  const int64_t* edge_w;
  const int64_t* max_bw;
  int32_t* part;
  std::vector<int64_t> conn;  // dense (n, k) connection table
  std::vector<int64_t> bw;    // global block weights

  // relaxed-atomic views of the shared state (plain loads/stores when
  // single-threaded; the values are identical either way)
  int64_t conn_at(int64_t u, int64_t b) const {
    return std::atomic_ref(const_cast<int64_t&>(conn[u * k + b]))
        .load(kRelaxed);
  }
  int32_t part_at(int64_t u) const {
    return std::atomic_ref(const_cast<int32_t&>(part[u])).load(kRelaxed);
  }
  int64_t bw_at(int64_t b) const {
    return std::atomic_ref(const_cast<int64_t&>(bw[b])).load(kRelaxed);
  }
};

// per-node ownership within a pass (NodeTracker analog):
// kFree = claimable, kMoved = committed this pass, else owning batch id
constexpr int32_t kFree = -1;
constexpr int32_t kMoved = -2;

void build_conn(Ctx& c) {
  std::fill(c.conn.begin(), c.conn.end(), 0);
  std::fill(c.bw.begin(), c.bw.end(), 0);
  for (int64_t u = 0; u < c.n; ++u) {
    c.bw[c.part[u]] += c.node_w[u];
    for (int64_t e = c.xadj[u]; e < c.xadj[u + 1]; ++e)
      c.conn[u * c.k + c.part[c.adjncy[e]]] += c.edge_w[e];
  }
}

// Delta overlay (delta_gain_caches.h analog): tentative partition and
// gain-table deltas for the current batch.  Touched nodes get a dense
// ARENA row copy of their (k-wide) connection row plus a tentative
// block field — one hash lookup per row access instead of k map probes
// per gain query (the hot path of the whole refiner).
struct Delta {
  const Ctx* c;
  std::unordered_map<int64_t, int32_t> slot;  // u -> arena index
  std::vector<int64_t> rows;                  // arena, k per slot
  std::vector<int32_t> blocks;                // arena slot -> tent. block
  std::vector<int64_t> bw_delta;

  explicit Delta(const Ctx& ctx) : c(&ctx), bw_delta(ctx.k, 0) {
    slot.reserve(1 << 14);
  }
  void clear() {
    slot.clear();
    rows.clear();
    blocks.clear();
    std::fill(bw_delta.begin(), bw_delta.end(), 0);
  }
  // arena row of u, materialized from the global table on first touch
  int64_t* row(int64_t u) {
    auto [it, fresh] = slot.try_emplace(u, (int32_t)blocks.size());
    if (fresh) {
      const size_t base = rows.size();
      rows.resize(base + c->k);
      for (int64_t b = 0; b < c->k; ++b)
        rows[base + b] = c->conn_at(u, b);
      blocks.push_back(c->part_at(u));
    }
    return rows.data() + (int64_t)it->second * c->k;
  }
  int32_t block(int64_t u) const {
    auto it = slot.find(u);
    return it == slot.end() ? c->part_at(u) : blocks[it->second];
  }
  // row view: the arena row when touched, else a temp copy of the
  // global row (atomic loads — the global row may be concurrently
  // updated by other batches' commits)
  const int64_t* row_view(int64_t u, int64_t* scratch) const {
    auto it = slot.find(u);
    if (it != slot.end())
      return rows.data() + (int64_t)it->second * c->k;
    for (int64_t b = 0; b < c->k; ++b) scratch[b] = c->conn_at(u, b);
    return scratch;
  }
  int64_t weight(int64_t b) const { return c->bw_at(b) + bw_delta[b]; }
  // tentatively move u from -> to, updating neighbor rows
  void move(int64_t u, int32_t from, int32_t to) {
    row(u);  // materialize so the block override has a slot
    blocks[slot.find(u)->second] = to;
    bw_delta[from] -= c->node_w[u];
    bw_delta[to] += c->node_w[u];
    for (int64_t e = c->xadj[u]; e < c->xadj[u + 1]; ++e) {
      const int32_t v = c->adjncy[e];
      int64_t* r = row(v);
      r[from] -= c->edge_w[e];
      r[to] += c->edge_w[e];
    }
  }
};

// best feasible move of u under the delta view: (gain, target) or
// (INT64_MIN, -1)
std::pair<int64_t, int32_t> best_move(const Delta& d, int64_t u, Rng& rng,
                                      int64_t* scratch) {
  const Ctx& c = *d.c;
  const int32_t b = d.block(u);
  const int64_t* r = d.row_view(u, scratch);
  const int64_t own = r[b];
  int64_t best_gain = INT64_MIN;
  int32_t best_t = -1;
  uint32_t best_tie = 0;
  for (int32_t t = 0; t < c.k; ++t) {
    if (t == b) continue;
    if (d.weight(t) + c.node_w[u] > c.max_bw[t]) continue;
    const int64_t g = r[t] - own;
    if (g > best_gain) {
      best_gain = g;
      best_t = t;
      best_tie = rng.tie();
    } else if (g == best_gain && best_t >= 0) {
      const uint32_t tb = rng.tie();
      if (tb > best_tie) {
        best_t = t;
        best_tie = tb;
      }
    }
  }
  return {best_gain, best_t};
}

// commit a move to the GLOBAL state with a cap re-check: concurrent
// batches may have filled the target block since the delta check, so
// reserve the weight first and roll back on overshoot.  Returns false
// (and leaves the state untouched) when the target no longer fits —
// the block-weight cap is never exceeded, even transiently beyond this
// one reservation.
bool commit_move(Ctx& c, int64_t u, int32_t from, int32_t to) {
  const int64_t w = c.node_w[u];
  std::atomic_ref bw_to(c.bw[to]);
  if (bw_to.fetch_add(w, kRelaxed) + w > c.max_bw[to]) {
    bw_to.fetch_sub(w, kRelaxed);
    return false;
  }
  std::atomic_ref(c.bw[from]).fetch_sub(w, kRelaxed);
  std::atomic_ref(c.part[u]).store(to, kRelaxed);
  for (int64_t e = c.xadj[u]; e < c.xadj[u + 1]; ++e) {
    const int32_t v = c.adjncy[e];
    std::atomic_ref(c.conn[(int64_t)v * c.k + from])
        .fetch_sub(c.edge_w[e], kRelaxed);
    std::atomic_ref(c.conn[(int64_t)v * c.k + to])
        .fetch_add(c.edge_w[e], kRelaxed);
  }
  return true;
}

struct Move {
  int64_t u;
  int32_t from, to;
  int64_t gain;
};

// one localized batch (LocalizedFMRefiner::run_batch); returns committed
// gain.  `owner` claims keep concurrent regions disjoint.
int64_t run_batch(Ctx& c, Delta& d, std::atomic<int32_t>* owner,
                  int32_t my_id, const std::vector<int64_t>& seeds,
                  double alpha, int64_t num_fruitless, int use_adaptive,
                  Rng& rng, std::vector<int64_t>& scratch) {
  d.clear();
  using Entry = std::tuple<int64_t, uint32_t, int64_t, int32_t>;
  std::priority_queue<Entry> pq;
  std::vector<int64_t> touched;

  auto claim = [&](int64_t u) {
    int32_t expect = kFree;
    return owner[u].compare_exchange_strong(expect, my_id, kRelaxed);
  };
  auto push = [&](int64_t u) {
    auto [g, t] = best_move(d, u, rng, scratch.data());
    if (t >= 0) pq.push({g, rng.tie(), u, t});
  };
  for (int64_t s : seeds) {
    // seeds arrive pre-claimed by the seed poller
    touched.push_back(s);
    push(s);
  }
  if (pq.empty()) {
    for (int64_t u : touched) owner[u].store(kFree, kRelaxed);
    return 0;
  }

  std::vector<Move> moves;
  int64_t cur = 0, best = 0;
  size_t best_len = 0;
  int64_t fruitless = 0;
  int64_t steps = 0;
  double mean = 0.0, m2 = 0.0;
  const size_t max_moves = 4096;  // region safety cap

  while (!pq.empty() && moves.size() < max_moves) {
    auto [g, tie, u, t] = pq.top();
    pq.pop();
    if (owner[u].load(kRelaxed) != my_id) continue;  // lost to a commit
    // stale check: gains shift as the region moves.  Re-queue only on a
    // GAIN change — the target may legitimately differ on ties (random
    // tie-break per query), and re-queuing on target alone could cycle
    auto [g2, t2] = best_move(d, u, rng, scratch.data());
    if (t2 < 0) continue;
    if (g2 != g) {
      pq.push({g2, rng.tie(), u, t2});
      continue;
    }
    t = t2;
    const int32_t b = d.block(u);
    d.move(u, b, t);
    moves.push_back({u, b, t, g2});
    cur += g2;
    if (cur > best) {
      best = cur;
      best_len = moves.size();
    }
    // expand: adjacent unclaimed nodes join the region
    for (int64_t e = c.xadj[u]; e < c.xadj[u + 1]; ++e) {
      const int32_t v = c.adjncy[e];
      const int32_t o = owner[v].load(kRelaxed);
      if (o == kFree) {
        if (claim(v)) {
          touched.push_back(v);
          push(v);
        }
      } else if (o == my_id) {
        push(v);
      }
    }
    // stopping policies (stopping_policies.h:16)
    if (use_adaptive) {
      ++steps;
      const double dlt = (double)g - mean;
      mean += dlt / (double)steps;
      m2 += dlt * ((double)g - mean);
      if (steps >= 2) {
        const double variance = m2 / (double)(steps - 1);
        if (mean < 0 &&
            (double)steps * mean * mean > alpha * variance + 10.0)
          break;
      }
    } else {
      fruitless = (g > 0) ? 0 : fruitless + 1;
      if (fruitless >= num_fruitless) break;
    }
  }

  // commit the best prefix globally; release the rest.  A cap re-check
  // failure aborts the remainder of the prefix (the delta gains beyond
  // a skipped move are no longer meaningful).
  int64_t committed_gain = 0;
  size_t i = 0;
  for (; i < best_len; ++i) {
    if (!commit_move(c, moves[i].u, moves[i].from, moves[i].to)) break;
    owner[moves[i].u].store(kMoved, kRelaxed);
    committed_gain += moves[i].gain;
  }
  for (int64_t u : touched)
    if (owner[u].load(kRelaxed) == my_id) owner[u].store(kFree, kRelaxed);
  return committed_gain;
}

}  // namespace

extern "C" int64_t kmp_fm_refine(
    int64_t n, const int64_t* xadj, const int32_t* adjncy,
    const int64_t* node_w, const int64_t* edge_w, int64_t k,
    const int64_t* max_bw, int32_t* part, int64_t num_iterations,
    int64_t num_seed_nodes, double alpha, int64_t num_fruitless_moves,
    int32_t use_adaptive, uint64_t seed, int64_t num_threads) {
  if (n <= 0 || k <= 1) return 0;
  // dense (n, k) table: refuse absurd sizes (large-k uses other refiners)
  if (n * k > (int64_t)3e8) return 0;
  Ctx c{n, k, xadj, adjncy, node_w, edge_w, max_bw, part, {}, {}};
  c.conn.resize(n * k);
  c.bw.resize(k);
  Rng rng(seed);
  build_conn(c);

  const int64_t T = std::max<int64_t>(1, num_threads);
  std::unique_ptr<std::atomic<int32_t>[]> owner(
      new std::atomic<int32_t>[n]);

  int64_t total = 0;
  int64_t first_pass_gain = 0;
  std::vector<int64_t> border;
  for (int64_t pass = 0; pass < std::max<int64_t>(1, num_iterations);
       ++pass) {
    // border nodes: nonzero external connection
    border.clear();
    for (int64_t u = 0; u < n; ++u) {
      const int64_t own = c.conn_at(u, c.part[u]);
      int64_t deg_w = 0;
      for (int64_t b = 0; b < k; ++b) deg_w += c.conn_at(u, b);
      if (deg_w > own) border.push_back(u);
    }
    if (border.empty()) break;
    for (int64_t i = (int64_t)border.size() - 1; i > 0; --i)
      std::swap(border[i], border[(int64_t)(rng.next() % (uint64_t)(i + 1))]);

    for (int64_t u = 0; u < n; ++u) owner[u].store(kFree, kRelaxed);
    const int64_t nseeds = std::max<int64_t>(1, num_seed_nodes);
    std::atomic<size_t> head{0};
    std::atomic<int64_t> pass_gain{0};
    std::atomic<int32_t> next_batch_id{0};

    auto worker = [&](int64_t tid) {
      Delta d(c);
      Rng wrng(seed ^ (0x9E3779B9ULL * (uint64_t)(pass * T + tid + 1)));
      // thread 0 on a single-thread run reuses the pass RNG so the
      // sequential state sequence matches the pre-threading code
      Rng& r = (T == 1) ? rng : wrng;
      std::vector<int64_t> scratch(k);
      std::vector<int64_t> seeds;
      for (;;) {
        // allocate the batch id FIRST so seed claims are uniquely
        // tagged from the start (a provisional shared tag could make a
        // foreign region adopt the seed)
        const int32_t my_id = next_batch_id.fetch_add(1, kRelaxed) + 1;
        seeds.clear();
        while ((int64_t)seeds.size() < nseeds) {
          const size_t i = head.fetch_add(1, kRelaxed);
          if (i >= border.size()) break;
          const int64_t u = border[i];
          int32_t expect = kFree;
          if (owner[u].compare_exchange_strong(expect, my_id, kRelaxed))
            seeds.push_back(u);
        }
        if (seeds.empty()) break;
        pass_gain.fetch_add(
            run_batch(c, d, owner.get(), my_id, seeds, alpha,
                      num_fruitless_moves, use_adaptive, r, scratch),
            kRelaxed);
      }
    };

    if (T == 1) {
      worker(0);
    } else {
      std::vector<std::thread> pool;
      pool.reserve(T);
      for (int64_t t = 0; t < T; ++t) pool.emplace_back(worker, t);
      for (auto& th : pool) th.join();
    }

    const int64_t pg = pass_gain.load(kRelaxed);
    total += pg;
    if (pg <= 0) break;
    // improvement abortion (initial_fm_refiner improvement_abortion
    // lineage): later passes chase diminishing returns at full pass cost
    if (pass == 0)
      first_pass_gain = pg;
    else if (pg * 20 < first_pass_gain)
      break;
  }
  return total;
}
