// Localized batch k-way FM refinement (native host runtime).
//
// The native analog of the reference's parallel localized FM
// (kaminpar-shm/refinement/fm/fm_refiner.cc:48-110 FMRefiner/
// LocalizedFMRefiner, gains/delta_gain_caches.h:202): seed nodes are
// polled from a shared border queue, each batch grows a localized region
// speculatively against a DELTA overlay of the partition and gain table,
// and only the best prefix of the batch's moves is committed to the
// global state; non-moved region nodes are released for later batches.
//
// Threading mirrors the reference's scheme: a pool of workers pulls seed
// batches from the shared border queue; per-node ownership claims (the
// NodeTracker analog, fm_refiner.cc NodeTracker) keep regions disjoint,
// global partition/gain-table/block-weight accesses go through relaxed
// std::atomic_ref (the reference's atomic gain cache), and commits
// re-check the block-weight caps with fetch_add + rollback so the cap
// is NEVER exceeded — stricter than the reference's transient
// overshoot.  num_threads <= 1 runs the identical code on one thread
// and visits exactly the old sequential state sequence (rerun
// determinism for tests and 1-CPU hosts).  Stale gains from concurrent
// commits are tolerated exactly like the reference tolerates them: the
// delta overlay re-checks gains before applying, and the global table
// stays exact because every update is an exact integer fetch_add.
//
// Dense (n, k) gain table (gains/sparse_gain_cache.h lineage), sparse
// delta map, adaptive (Osipov-Sanders) or simple stopping.

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <memory>
#include <queue>
#include <thread>
#include <tuple>
#include <unordered_map>
#include <vector>

namespace {

struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed ^ 0x9E3779B97F4A7C15ULL) {
    if (s == 0) s = 0x2545F4914F6CDD1DULL;
  }
  uint64_t next() {
    uint64_t z = (s += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
  }
  uint32_t tie() { return (uint32_t)(next() >> 32); }
};

constexpr auto kRelaxed = std::memory_order_relaxed;

struct Ctx {
  int64_t n, k;
  const int64_t* xadj;
  const int32_t* adjncy;
  const int64_t* node_w;
  const int64_t* edge_w;
  const int64_t* max_bw;
  int32_t* part;
  std::vector<int64_t> conn;  // dense (n, k) connection table
  std::vector<int64_t> bw;    // global block weights

  // relaxed-atomic views of the shared state (plain loads/stores when
  // single-threaded; the values are identical either way)
  int64_t conn_at(int64_t u, int64_t b) const {
    return std::atomic_ref(const_cast<int64_t&>(conn[u * k + b]))
        .load(kRelaxed);
  }
  int32_t part_at(int64_t u) const {
    return std::atomic_ref(const_cast<int32_t&>(part[u])).load(kRelaxed);
  }
  int64_t bw_at(int64_t b) const {
    return std::atomic_ref(const_cast<int64_t&>(bw[b])).load(kRelaxed);
  }
};

// per-node ownership within a pass (NodeTracker analog):
// kFree = claimable, kMoved = committed this pass, else owning batch id
constexpr int32_t kFree = -1;
constexpr int32_t kMoved = -2;

void build_conn(Ctx& c) {
  std::fill(c.conn.begin(), c.conn.end(), 0);
  std::fill(c.bw.begin(), c.bw.end(), 0);
  for (int64_t u = 0; u < c.n; ++u) {
    c.bw[c.part[u]] += c.node_w[u];
    for (int64_t e = c.xadj[u]; e < c.xadj[u + 1]; ++e)
      c.conn[u * c.k + c.part[c.adjncy[e]]] += c.edge_w[e];
  }
}

// Delta overlay (delta_gain_caches.h analog): tentative partition and
// gain-table deltas for the current batch.  Touched nodes get a dense
// ARENA row copy of their (k-wide) connection row plus a tentative
// block field — one hash lookup per row access instead of k map probes
// per gain query (the hot path of the whole refiner).
struct Delta {
  const Ctx* c;
  std::unordered_map<int64_t, int32_t> slot;  // u -> arena index
  std::vector<int64_t> rows;                  // arena, k per slot
  std::vector<int32_t> blocks;                // arena slot -> tent. block
  std::vector<int64_t> bw_delta;

  explicit Delta(const Ctx& ctx) : c(&ctx), bw_delta(ctx.k, 0) {
    slot.reserve(1 << 14);
  }
  void clear() {
    slot.clear();
    rows.clear();
    blocks.clear();
    std::fill(bw_delta.begin(), bw_delta.end(), 0);
  }
  // arena row of u, materialized from the global table on first touch
  int64_t* row(int64_t u) {
    auto [it, fresh] = slot.try_emplace(u, (int32_t)blocks.size());
    if (fresh) {
      const size_t base = rows.size();
      rows.resize(base + c->k);
      for (int64_t b = 0; b < c->k; ++b)
        rows[base + b] = c->conn_at(u, b);
      blocks.push_back(c->part_at(u));
    }
    return rows.data() + (int64_t)it->second * c->k;
  }
  int32_t block(int64_t u) const {
    auto it = slot.find(u);
    return it == slot.end() ? c->part_at(u) : blocks[it->second];
  }
  // row view: the arena row when touched, else a temp copy of the
  // global row (atomic loads — the global row may be concurrently
  // updated by other batches' commits)
  const int64_t* row_view(int64_t u, int64_t* scratch) const {
    auto it = slot.find(u);
    if (it != slot.end())
      return rows.data() + (int64_t)it->second * c->k;
    for (int64_t b = 0; b < c->k; ++b) scratch[b] = c->conn_at(u, b);
    return scratch;
  }
  int64_t weight(int64_t b) const { return c->bw_at(b) + bw_delta[b]; }
  // tentatively move u from -> to, updating neighbor rows
  void move(int64_t u, int32_t from, int32_t to) {
    row(u);  // materialize so the block override has a slot
    blocks[slot.find(u)->second] = to;
    bw_delta[from] -= c->node_w[u];
    bw_delta[to] += c->node_w[u];
    for (int64_t e = c->xadj[u]; e < c->xadj[u + 1]; ++e) {
      const int32_t v = c->adjncy[e];
      int64_t* r = row(v);
      r[from] -= c->edge_w[e];
      r[to] += c->edge_w[e];
    }
  }
};

// best feasible move of u under the delta view: (gain, target) or
// (INT64_MIN, -1)
std::pair<int64_t, int32_t> best_move(const Delta& d, int64_t u, Rng& rng,
                                      int64_t* scratch) {
  const Ctx& c = *d.c;
  const int32_t b = d.block(u);
  const int64_t* r = d.row_view(u, scratch);
  const int64_t own = r[b];
  int64_t best_gain = INT64_MIN;
  int32_t best_t = -1;
  uint32_t best_tie = 0;
  for (int32_t t = 0; t < c.k; ++t) {
    if (t == b) continue;
    if (d.weight(t) + c.node_w[u] > c.max_bw[t]) continue;
    const int64_t g = r[t] - own;
    if (g > best_gain) {
      best_gain = g;
      best_t = t;
      best_tie = rng.tie();
    } else if (g == best_gain && best_t >= 0) {
      const uint32_t tb = rng.tie();
      if (tb > best_tie) {
        best_t = t;
        best_tie = tb;
      }
    }
  }
  return {best_gain, best_t};
}

// commit a move to the GLOBAL state with a cap re-check: concurrent
// batches may have filled the target block since the delta check, so
// reserve the weight first and roll back on overshoot.  Returns false
// (and leaves the state untouched) when the target no longer fits —
// the block-weight cap is never exceeded, even transiently beyond this
// one reservation.
bool commit_move(Ctx& c, int64_t u, int32_t from, int32_t to) {
  const int64_t w = c.node_w[u];
  std::atomic_ref bw_to(c.bw[to]);
  if (bw_to.fetch_add(w, kRelaxed) + w > c.max_bw[to]) {
    bw_to.fetch_sub(w, kRelaxed);
    return false;
  }
  std::atomic_ref(c.bw[from]).fetch_sub(w, kRelaxed);
  std::atomic_ref(c.part[u]).store(to, kRelaxed);
  for (int64_t e = c.xadj[u]; e < c.xadj[u + 1]; ++e) {
    const int32_t v = c.adjncy[e];
    std::atomic_ref(c.conn[(int64_t)v * c.k + from])
        .fetch_sub(c.edge_w[e], kRelaxed);
    std::atomic_ref(c.conn[(int64_t)v * c.k + to])
        .fetch_add(c.edge_w[e], kRelaxed);
  }
  return true;
}

struct Move {
  int64_t u;
  int32_t from, to;
  int64_t gain;
};

// one localized batch (LocalizedFMRefiner::run_batch); returns committed
// gain.  `owner` claims keep concurrent regions disjoint.
int64_t run_batch(Ctx& c, Delta& d, std::atomic<int32_t>* owner,
                  int32_t my_id, const std::vector<int64_t>& seeds,
                  double alpha, int64_t num_fruitless, int use_adaptive,
                  Rng& rng, std::vector<int64_t>& scratch) {
  d.clear();
  using Entry = std::tuple<int64_t, uint32_t, int64_t, int32_t>;
  std::priority_queue<Entry> pq;
  std::vector<int64_t> touched;

  auto claim = [&](int64_t u) {
    int32_t expect = kFree;
    return owner[u].compare_exchange_strong(expect, my_id, kRelaxed);
  };
  auto push = [&](int64_t u) {
    auto [g, t] = best_move(d, u, rng, scratch.data());
    if (t >= 0) pq.push({g, rng.tie(), u, t});
  };
  for (int64_t s : seeds) {
    // seeds arrive pre-claimed by the seed poller
    touched.push_back(s);
    push(s);
  }
  if (pq.empty()) {
    for (int64_t u : touched) owner[u].store(kFree, kRelaxed);
    return 0;
  }

  std::vector<Move> moves;
  int64_t cur = 0, best = 0;
  size_t best_len = 0;
  int64_t fruitless = 0;
  int64_t steps = 0;
  double mean = 0.0, m2 = 0.0;
  const size_t max_moves = 4096;  // region safety cap

  while (!pq.empty() && moves.size() < max_moves) {
    auto [g, tie, u, t] = pq.top();
    pq.pop();
    if (owner[u].load(kRelaxed) != my_id) continue;  // lost to a commit
    // stale check: gains shift as the region moves.  Re-queue only on a
    // GAIN change — the target may legitimately differ on ties (random
    // tie-break per query), and re-queuing on target alone could cycle
    auto [g2, t2] = best_move(d, u, rng, scratch.data());
    if (t2 < 0) continue;
    if (g2 != g) {
      pq.push({g2, rng.tie(), u, t2});
      continue;
    }
    t = t2;
    const int32_t b = d.block(u);
    d.move(u, b, t);
    moves.push_back({u, b, t, g2});
    cur += g2;
    if (cur > best) {
      best = cur;
      best_len = moves.size();
    }
    // expand: adjacent unclaimed nodes join the region
    for (int64_t e = c.xadj[u]; e < c.xadj[u + 1]; ++e) {
      const int32_t v = c.adjncy[e];
      const int32_t o = owner[v].load(kRelaxed);
      if (o == kFree) {
        if (claim(v)) {
          touched.push_back(v);
          push(v);
        }
      } else if (o == my_id) {
        push(v);
      }
    }
    // stopping policies (stopping_policies.h:16)
    if (use_adaptive) {
      ++steps;
      const double dlt = (double)g - mean;
      mean += dlt / (double)steps;
      m2 += dlt * ((double)g - mean);
      if (steps >= 2) {
        const double variance = m2 / (double)(steps - 1);
        if (mean < 0 &&
            (double)steps * mean * mean > alpha * variance + 10.0)
          break;
      }
    } else {
      fruitless = (g > 0) ? 0 : fruitless + 1;
      if (fruitless >= num_fruitless) break;
    }
  }

  // commit the best prefix globally; release the rest.  A cap re-check
  // failure aborts the remainder of the prefix (the delta gains beyond
  // a skipped move are no longer meaningful).
  int64_t committed_gain = 0;
  size_t i = 0;
  for (; i < best_len; ++i) {
    if (!commit_move(c, moves[i].u, moves[i].from, moves[i].to)) break;
    owner[moves[i].u].store(kMoved, kRelaxed);
    committed_gain += moves[i].gain;
  }
  for (int64_t u : touched)
    if (owner[u].load(kRelaxed) == my_id) owner[u].store(kFree, kRelaxed);
  return committed_gain;
}

// ---------------------------------------------------------------------------
// Sparse compact-hashing connection table + FM path (large k).
//
// The dense (n, k) table above is O(n*k) memory — impossible at the
// reference's large-k operating point (README.MD:17 rides
// gains/compact_hashing_gain_cache.h:34 there).  This path stores, per
// node, a power-of-two open-addressing table of (block, weight) entries
// sized 2*ceil2(min(deg, k)) — distinct adjacent blocks never exceed
// deg, the 2x headroom absorbs tombstones, and a row is rebuilt exactly
// from the adjacency when probing saturates.  Entries pack
// (block + 1) << 48 | weight, so a weight update is one fetch_add and
// an insert is one CAS.  Total memory O(sum 2*ceil2(deg)) = O(m).
// ---------------------------------------------------------------------------

namespace sparse_fm {

constexpr int64_t kTagShift = 48;
constexpr int64_t kWeightMask = ((int64_t)1 << kTagShift) - 1;

inline int64_t pack(int32_t block, int64_t w) {
  return ((int64_t)(block + 1) << kTagShift) | w;
}
// unsigned shift: block+1 can reach bit 63's neighborhood at large k
// and an arithmetic shift would sign-extend into a wrong (negative) tag
inline int32_t tag_of(int64_t e) {
  return (int32_t)((uint64_t)e >> kTagShift) - 1;
}
inline int64_t weight_of(int64_t e) { return e & kWeightMask; }

inline uint64_t hash_block(int32_t b) {
  uint64_t z = (uint64_t)b * 0x9E3779B97F4A7C15ULL;
  return z ^ (z >> 29);
}

struct SparseCtx {
  int64_t n, k;
  const int64_t* xadj;
  const int32_t* adjncy;
  const int64_t* node_w;
  const int64_t* edge_w;
  const int64_t* max_bw;
  int32_t* part;
  std::vector<int64_t> off;      // slot ranges (off[u]..off[u+1]), pow2 caps
  std::vector<int64_t> entries;  // packed atomic slots
  std::vector<int64_t> wdeg;     // weighted degree (border test)
  std::vector<int64_t> bw;

  int64_t cap(int64_t u) const { return off[u + 1] - off[u]; }
  int32_t part_at(int64_t u) const {
    return std::atomic_ref(const_cast<int32_t&>(part[u])).load(kRelaxed);
  }
  int64_t bw_at(int64_t b) const {
    return std::atomic_ref(const_cast<int64_t&>(bw[b])).load(kRelaxed);
  }

  int64_t load(int64_t u, int32_t b) const {
    const int64_t base = off[u], c = cap(u);
    if (c == 0) return 0;
    const int64_t mask = c - 1;
    for (int64_t i = 0; i < c; ++i) {
      const int64_t s = base + ((hash_block(b) + (uint64_t)i) & mask);
      const int64_t e =
          std::atomic_ref(const_cast<int64_t&>(entries[s])).load(kRelaxed);
      if (e == 0) return 0;
      if (tag_of(e) == b) return weight_of(e);
    }
    return 0;  // saturated row without the tag: weight is 0
  }

  // add w (may be negative) to (u, b); returns false when the row needs
  // a rebuild (all slots probed, tag absent — only possible for w > 0)
  bool add(int64_t u, int32_t b, int64_t w) {
    const int64_t base = off[u], c = cap(u);
    if (c == 0) return true;
    const int64_t mask = c - 1;
    for (int64_t i = 0; i < c; ++i) {
      const int64_t s = base + ((hash_block(b) + (uint64_t)i) & mask);
      std::atomic_ref<int64_t> ref(entries[s]);
      int64_t e = ref.load(kRelaxed);
      while (e == 0) {
        // claim the empty slot (tag + weight in one CAS); a zero-weight
        // claim is fine — it acts as a pre-claimed tombstone
        if (ref.compare_exchange_weak(e, pack(b, w), kRelaxed)) return true;
      }
      if (tag_of(e) == b) {
        ref.fetch_add(w, kRelaxed);  // weight field only; tag untouched
        return true;
      }
    }
    return false;
  }

  // exact rebuild of u's row from the adjacency + current partition
  // (clears tombstones; single-threaded callers only)
  void rebuild_row(int64_t u) {
    std::fill(entries.begin() + off[u], entries.begin() + off[u + 1], 0);
    for (int64_t e = xadj[u]; e < xadj[u + 1]; ++e)
      (void)add(u, part_at(adjncy[e]), edge_w[e]);
  }

  template <class Fn>
  void for_entries(int64_t u, Fn&& fn) const {
    for (int64_t s = off[u]; s < off[u + 1]; ++s) {
      const int64_t e =
          std::atomic_ref(const_cast<int64_t&>(entries[s])).load(kRelaxed);
      if (e != 0 && weight_of(e) > 0) fn(tag_of(e), weight_of(e));
    }
  }
};

inline int64_t ceil2_i64(int64_t x) {
  int64_t p = 1;
  while (p < x) p <<= 1;
  return p;
}

void build_sparse(SparseCtx& c) {
  c.off.assign(c.n + 1, 0);
  for (int64_t u = 0; u < c.n; ++u) {
    const int64_t deg = c.xadj[u + 1] - c.xadj[u];
    const int64_t distinct = std::min<int64_t>(deg, c.k);
    c.off[u + 1] =
        c.off[u] + (distinct == 0 ? 0 : 2 * ceil2_i64(distinct));
  }
  c.entries.assign(c.off[c.n], 0);
  c.wdeg.assign(c.n, 0);
  c.bw.assign(c.k, 0);
  for (int64_t u = 0; u < c.n; ++u) {
    c.bw[c.part[u]] += c.node_w[u];
    for (int64_t e = c.xadj[u]; e < c.xadj[u + 1]; ++e) {
      c.wdeg[u] += c.edge_w[e];
      (void)c.add(u, c.part[c.adjncy[e]], c.edge_w[e]);
    }
  }
}

// Delta overlay: private copies of touched rows (cap-sized, same
// probing), tentative blocks, block-weight deltas.
struct SparseDelta {
  SparseCtx* c;
  std::unordered_map<int64_t, int64_t> slot;  // u -> arena offset
  std::vector<int64_t> arena;                 // cap(u) packed entries per row
  std::unordered_map<int64_t, int32_t> blocks;
  std::vector<int64_t> bw_delta;

  explicit SparseDelta(SparseCtx& ctx) : c(&ctx), bw_delta(ctx.k, 0) {
    slot.reserve(1 << 12);
  }
  void clear() {
    slot.clear();
    arena.clear();
    blocks.clear();
    std::fill(bw_delta.begin(), bw_delta.end(), 0);
  }
  int64_t* row(int64_t u) {
    auto [it, fresh] = slot.try_emplace(u, (int64_t)arena.size());
    if (fresh) {
      const size_t base = arena.size();
      arena.resize(base + c->cap(u));
      for (int64_t s = 0; s < c->cap(u); ++s)
        arena[base + s] = std::atomic_ref(c->entries[c->off[u] + s])
                              .load(kRelaxed);
    }
    return arena.data() + it->second;
  }
  int32_t block(int64_t u) const {
    auto it = blocks.find(u);
    return it == blocks.end() ? c->part_at(u) : it->second;
  }
  int64_t weight(int64_t b) const { return c->bw_at(b) + bw_delta[b]; }

  // private-row add with exact rebuild on saturation
  void row_add(int64_t u, int32_t b, int64_t w) {
    int64_t* r = row(u);
    const int64_t cp = c->cap(u);
    if (cp == 0) return;
    const int64_t mask = cp - 1;
    for (int64_t i = 0; i < cp; ++i) {
      int64_t& e = r[(hash_block(b) + (uint64_t)i) & mask];
      if (e == 0) {
        e = pack(b, w);
        return;
      }
      if (tag_of(e) == b) {
        e += w;
        return;
      }
    }
    // saturated: rebuild the private row exactly from the adjacency
    // under the delta's tentative blocks (rare; O(deg * probe))
    std::fill(r, r + cp, 0);
    for (int64_t e2 = c->xadj[u]; e2 < c->xadj[u + 1]; ++e2) {
      const int32_t bb = block(c->adjncy[e2]);
      const int64_t mask2 = cp - 1;
      for (int64_t i = 0; i < cp; ++i) {
        int64_t& e = r[(hash_block(bb) + (uint64_t)i) & mask2];
        if (e == 0) {
          e = pack(bb, c->edge_w[e2]);
          break;
        }
        if (tag_of(e) == bb) {
          e += c->edge_w[e2];
          break;
        }
      }
    }
  }

  int64_t row_load(int64_t u, int32_t b) const {
    auto it = slot.find(u);
    if (it == slot.end()) return c->load(u, b);
    const int64_t* r = arena.data() + it->second;
    const int64_t cp = c->cap(u);
    if (cp == 0) return 0;
    const int64_t mask = cp - 1;
    for (int64_t i = 0; i < cp; ++i) {
      const int64_t e = r[(hash_block(b) + (uint64_t)i) & mask];
      if (e == 0) return 0;
      if (tag_of(e) == b) return weight_of(e);
    }
    return 0;
  }

  void move(int64_t u, int32_t from, int32_t to) {
    row(u);
    blocks[u] = to;
    bw_delta[from] -= c->node_w[u];
    bw_delta[to] += c->node_w[u];
    for (int64_t e = c->xadj[u]; e < c->xadj[u + 1]; ++e) {
      const int32_t v = c->adjncy[e];
      row_add(v, from, -c->edge_w[e]);
      row_add(v, to, c->edge_w[e]);
    }
  }

  // best feasible move among u's ADJACENT blocks (the compact-hashing
  // cache iterates its entries — non-adjacent targets are the
  // balancers' job, as in the reference's large-k configuration)
  std::pair<int64_t, int32_t> best_move(int64_t u, Rng& rng) const {
    const int32_t b = block(u);
    const int64_t own = row_load(u, b);
    int64_t best_gain = INT64_MIN;
    int32_t best_t = -1;
    uint32_t best_tie = 0;
    auto consider = [&](int32_t t, int64_t w) {
      if (t == b) return;
      if (weight(t) + c->node_w[u] > c->max_bw[t]) return;
      const int64_t g = w - own;
      if (g > best_gain) {
        best_gain = g;
        best_t = t;
        best_tie = rng.tie();
      } else if (g == best_gain && best_t >= 0) {
        const uint32_t tb = rng.tie();
        if (tb > best_tie) {
          best_t = t;
          best_tie = tb;
        }
      }
    };
    auto it = slot.find(u);
    if (it == slot.end()) {
      c->for_entries(u, consider);
    } else {
      const int64_t* r = arena.data() + it->second;
      for (int64_t s = 0; s < c->cap(u); ++s)
        if (r[s] != 0 && weight_of(r[s]) > 0)
          consider(tag_of(r[s]), weight_of(r[s]));
    }
    return {best_gain, best_t};
  }
};

// commit with cap re-check (mirrors dense commit_move); a saturated
// neighbor row is rebuilt exactly (single-threaded path — the sparse
// configuration runs T=1, see kmp_fm_refine)
bool commit_move(SparseCtx& c, int64_t u, int32_t from, int32_t to) {
  const int64_t w = c.node_w[u];
  std::atomic_ref bw_to(c.bw[to]);
  if (bw_to.fetch_add(w, kRelaxed) + w > c.max_bw[to]) {
    bw_to.fetch_sub(w, kRelaxed);
    return false;
  }
  std::atomic_ref(c.bw[from]).fetch_sub(w, kRelaxed);
  std::atomic_ref(c.part[u]).store(to, kRelaxed);
  for (int64_t e = c.xadj[u]; e < c.xadj[u + 1]; ++e) {
    const int32_t v = c.adjncy[e];
    (void)c.add(v, from, -c.edge_w[e]);
    if (!c.add(v, to, c.edge_w[e])) c.rebuild_row(v);
  }
  return true;
}

int64_t run_batch(SparseCtx& c, SparseDelta& d,
                  std::atomic<int32_t>* owner, int32_t my_id,
                  const std::vector<int64_t>& seeds, double alpha,
                  int64_t num_fruitless, int use_adaptive, Rng& rng) {
  d.clear();
  using Entry = std::tuple<int64_t, uint32_t, int64_t, int32_t>;
  std::priority_queue<Entry> pq;
  std::vector<int64_t> touched;

  auto claim = [&](int64_t u) {
    int32_t expect = kFree;
    return owner[u].compare_exchange_strong(expect, my_id, kRelaxed);
  };
  auto push = [&](int64_t u) {
    auto [g, t] = d.best_move(u, rng);
    if (t >= 0) pq.push({g, rng.tie(), u, t});
  };
  for (int64_t s : seeds) {
    touched.push_back(s);
    push(s);
  }
  if (pq.empty()) {
    for (int64_t u : touched) owner[u].store(kFree, kRelaxed);
    return 0;
  }

  std::vector<Move> moves;
  int64_t cur = 0, best = 0;
  size_t best_len = 0;
  int64_t fruitless = 0;
  int64_t steps = 0;
  double mean = 0.0, m2 = 0.0;
  const size_t max_moves = 4096;

  while (!pq.empty() && moves.size() < max_moves) {
    auto [g, tie, u, t] = pq.top();
    pq.pop();
    if (owner[u].load(kRelaxed) != my_id) continue;
    auto [g2, t2] = d.best_move(u, rng);
    if (t2 < 0) continue;
    if (g2 != g) {
      pq.push({g2, rng.tie(), u, t2});
      continue;
    }
    t = t2;
    const int32_t b = d.block(u);
    d.move(u, b, t);
    moves.push_back({u, b, t, g2});
    cur += g2;
    if (cur > best) {
      best = cur;
      best_len = moves.size();
    }
    for (int64_t e = c.xadj[u]; e < c.xadj[u + 1]; ++e) {
      const int32_t v = c.adjncy[e];
      const int32_t o = owner[v].load(kRelaxed);
      if (o == kFree) {
        if (claim(v)) {
          touched.push_back(v);
          push(v);
        }
      } else if (o == my_id) {
        push(v);
      }
    }
    if (use_adaptive) {
      ++steps;
      const double dlt = (double)g - mean;
      mean += dlt / (double)steps;
      m2 += dlt * ((double)g - mean);
      if (steps >= 2) {
        const double variance = m2 / (double)(steps - 1);
        if (mean < 0 &&
            (double)steps * mean * mean > alpha * variance + 10.0)
          break;
      }
    } else {
      fruitless = (g > 0) ? 0 : fruitless + 1;
      if (fruitless >= num_fruitless) break;
    }
  }

  int64_t committed_gain = 0;
  for (size_t i = 0; i < best_len; ++i) {
    if (!commit_move(c, moves[i].u, moves[i].from, moves[i].to)) break;
    owner[moves[i].u].store(kMoved, kRelaxed);
    committed_gain += moves[i].gain;
  }
  for (int64_t u : touched)
    if (owner[u].load(kRelaxed) == my_id) owner[u].store(kFree, kRelaxed);
  return committed_gain;
}

int64_t refine(int64_t n, const int64_t* xadj, const int32_t* adjncy,
               const int64_t* node_w, const int64_t* edge_w, int64_t k,
               const int64_t* max_bw, int32_t* part,
               int64_t num_iterations, int64_t num_seed_nodes,
               double alpha, int64_t num_fruitless_moves,
               int32_t use_adaptive, uint64_t seed) {
  // the packed tag field holds block+1 in 16 bits (max tag = k).
  // INT64_MIN is the REFUSAL sentinel — the caller must distinguish "FM
  // did not run" from "FM found no improvement" (ADVICE round 5 low #3),
  // and a small negative value would be ambiguous: with threads > 1 a
  // cap-race-aborted commit prefix can legitimately sum negative.
  if (k > 0xFFFF) return INT64_MIN;
  SparseCtx c{n, k, xadj, adjncy, node_w, edge_w, max_bw, part,
              {}, {}, {}, {}};
  Rng rng(seed);
  build_sparse(c);

  std::unique_ptr<std::atomic<int32_t>[]> owner(
      new std::atomic<int32_t>[n]);
  SparseDelta d(c);

  int64_t total = 0;
  int64_t first_pass_gain = 0;
  std::vector<int64_t> border;
  for (int64_t pass = 0; pass < std::max<int64_t>(1, num_iterations);
       ++pass) {
    border.clear();
    for (int64_t u = 0; u < n; ++u)
      if (c.load(u, c.part[u]) < c.wdeg[u]) border.push_back(u);
    if (border.empty()) break;
    for (int64_t i = (int64_t)border.size() - 1; i > 0; --i)
      std::swap(border[i],
                border[(int64_t)(rng.next() % (uint64_t)(i + 1))]);

    for (int64_t u = 0; u < n; ++u) owner[u].store(kFree, kRelaxed);
    const int64_t nseeds = std::max<int64_t>(1, num_seed_nodes);
    size_t head = 0;
    int64_t pass_gain = 0;
    int32_t next_batch_id = 0;

    for (;;) {
      const int32_t my_id = ++next_batch_id;
      std::vector<int64_t> seeds;
      while ((int64_t)seeds.size() < nseeds && head < border.size()) {
        const int64_t u = border[head++];
        int32_t expect = kFree;
        if (owner[u].compare_exchange_strong(expect, my_id, kRelaxed))
          seeds.push_back(u);
      }
      if (seeds.empty()) break;
      pass_gain += run_batch(c, d, owner.get(), my_id, seeds, alpha,
                             num_fruitless_moves, use_adaptive, rng);
    }

    total += pass_gain;
    if (pass_gain <= 0) break;
    if (pass == 0)
      first_pass_gain = pass_gain;
    else if (pass_gain * 20 < first_pass_gain)
      break;
  }
  return total;
}

}  // namespace sparse_fm

}  // namespace

// test hook: force the sparse compact-hashing path at any k (the
// normal entry dispatches on table size; tests exercise both on the
// same small graph and assert both improve the cut)
extern "C" int64_t kmp_fm_refine_sparse(
    int64_t n, const int64_t* xadj, const int32_t* adjncy,
    const int64_t* node_w, const int64_t* edge_w, int64_t k,
    const int64_t* max_bw, int32_t* part, int64_t num_iterations,
    int64_t num_seed_nodes, double alpha, int64_t num_fruitless_moves,
    int32_t use_adaptive, uint64_t seed, int64_t /*num_threads*/) {
  if (n <= 0 || k <= 1) return 0;
  return sparse_fm::refine(n, xadj, adjncy, node_w, edge_w, k, max_bw,
                           part, num_iterations, num_seed_nodes, alpha,
                           num_fruitless_moves, use_adaptive, seed);
}

extern "C" int64_t kmp_fm_refine(
    int64_t n, const int64_t* xadj, const int32_t* adjncy,
    const int64_t* node_w, const int64_t* edge_w, int64_t k,
    const int64_t* max_bw, int32_t* part, int64_t num_iterations,
    int64_t num_seed_nodes, double alpha, int64_t num_fruitless_moves,
    int32_t use_adaptive, uint64_t seed, int64_t num_threads) {
  if (n <= 0 || k <= 1) return 0;
  if (n * k > (int64_t)3e8) {
    // large k: the dense (n, k) table is unaffordable — run the sparse
    // compact-hashing path (compact_hashing_gain_cache.h:34 analog),
    // O(m) memory.  Single-threaded: its exact rebuild-on-saturation
    // is not written for concurrent writers.
    return sparse_fm::refine(n, xadj, adjncy, node_w, edge_w, k, max_bw,
                             part, num_iterations, num_seed_nodes, alpha,
                             num_fruitless_moves, use_adaptive, seed);
  }
  Ctx c{n, k, xadj, adjncy, node_w, edge_w, max_bw, part, {}, {}};
  c.conn.resize(n * k);
  c.bw.resize(k);
  Rng rng(seed);
  build_conn(c);

  const int64_t T = std::max<int64_t>(1, num_threads);
  std::unique_ptr<std::atomic<int32_t>[]> owner(
      new std::atomic<int32_t>[n]);

  int64_t total = 0;
  int64_t first_pass_gain = 0;
  std::vector<int64_t> border;
  for (int64_t pass = 0; pass < std::max<int64_t>(1, num_iterations);
       ++pass) {
    // border nodes: nonzero external connection
    border.clear();
    for (int64_t u = 0; u < n; ++u) {
      const int64_t own = c.conn_at(u, c.part[u]);
      int64_t deg_w = 0;
      for (int64_t b = 0; b < k; ++b) deg_w += c.conn_at(u, b);
      if (deg_w > own) border.push_back(u);
    }
    if (border.empty()) break;
    for (int64_t i = (int64_t)border.size() - 1; i > 0; --i)
      std::swap(border[i], border[(int64_t)(rng.next() % (uint64_t)(i + 1))]);

    for (int64_t u = 0; u < n; ++u) owner[u].store(kFree, kRelaxed);
    const int64_t nseeds = std::max<int64_t>(1, num_seed_nodes);
    std::atomic<size_t> head{0};
    std::atomic<int64_t> pass_gain{0};
    std::atomic<int32_t> next_batch_id{0};

    auto worker = [&](int64_t tid) {
      Delta d(c);
      Rng wrng(seed ^ (0x9E3779B9ULL * (uint64_t)(pass * T + tid + 1)));
      // thread 0 on a single-thread run reuses the pass RNG so the
      // sequential state sequence matches the pre-threading code
      Rng& r = (T == 1) ? rng : wrng;
      std::vector<int64_t> scratch(k);
      std::vector<int64_t> seeds;
      for (;;) {
        // allocate the batch id FIRST so seed claims are uniquely
        // tagged from the start (a provisional shared tag could make a
        // foreign region adopt the seed)
        const int32_t my_id = next_batch_id.fetch_add(1, kRelaxed) + 1;
        seeds.clear();
        while ((int64_t)seeds.size() < nseeds) {
          const size_t i = head.fetch_add(1, kRelaxed);
          if (i >= border.size()) break;
          const int64_t u = border[i];
          int32_t expect = kFree;
          if (owner[u].compare_exchange_strong(expect, my_id, kRelaxed))
            seeds.push_back(u);
        }
        if (seeds.empty()) break;
        pass_gain.fetch_add(
            run_batch(c, d, owner.get(), my_id, seeds, alpha,
                      num_fruitless_moves, use_adaptive, r, scratch),
            kRelaxed);
      }
    };

    if (T == 1) {
      worker(0);
    } else {
      std::vector<std::thread> pool;
      pool.reserve(T);
      for (int64_t t = 0; t < T; ++t) pool.emplace_back(worker, t);
      for (auto& th : pool) th.join();
    }

    const int64_t pg = pass_gain.load(kRelaxed);
    total += pg;
    if (pg <= 0) break;
    // improvement abortion (initial_fm_refiner improvement_abortion
    // lineage): later passes chase diminishing returns at full pass cost
    if (pass == 0)
      first_pass_gain = pg;
    else if (pg * 20 < first_pass_gain)
      break;
  }
  return total;
}
