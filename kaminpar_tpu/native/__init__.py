"""Native host-runtime components (C++, loaded via ctypes).

The reference's host runtime — graph compression codecs and parsers — is
C++ (kaminpar-common/graph_compression/, kaminpar-io/).  This package
builds the framework's native equivalents from codec.cpp on first use with
the system toolchain and exposes them via ctypes; every entry point has a
pure-numpy fallback, so the framework works (slower) without a compiler.

Build artifacts are cached next to the source keyed by a source hash.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import platform
import subprocess
import tempfile
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRCS = [
    os.path.join(_DIR, "codec.cpp"),
    os.path.join(_DIR, "codec2.cpp"),
    os.path.join(_DIR, "ip.cpp"),
    os.path.join(_DIR, "fm.cpp"),
]

_lib: Optional[ctypes.CDLL] = None
_tried = False

# Build-cache directory override (tests poison a tmp cache dir to
# exercise the corrupted-cache clean-rebuild path without touching the
# package's real artifacts) and the compile timeout.
CACHE_DIR_ENV = "KAMINPAR_TPU_NATIVE_CACHE_DIR"
BUILD_TIMEOUT_ENV = "KAMINPAR_TPU_NATIVE_BUILD_TIMEOUT"
DEFAULT_BUILD_TIMEOUT_S = 300.0


def cache_dir() -> str:
    """Where built artifacts are cached (package dir unless overridden)."""
    return os.environ.get(CACHE_DIR_ENV, "") or _DIR


def build_timeout() -> float:
    """Native compile timeout in seconds (KAMINPAR_TPU_NATIVE_BUILD_TIMEOUT;
    a hung compiler must degrade to ctypes-free mode, not hang the run)."""
    raw = os.environ.get(BUILD_TIMEOUT_ENV, "")
    try:
        return float(raw) if raw else DEFAULT_BUILD_TIMEOUT_S
    except ValueError:
        return DEFAULT_BUILD_TIMEOUT_S


def sanitize_flags() -> list:
    """Extra compile flags from KMP_SANITIZE (e.g. 'address,undefined').

    The sanitizer build mode for the native layer: frame pointers and
    debug info stay in, optimization drops to -O1 so reports map to
    source lines.  scripts/run_native_sanitized.sh drives a full
    rebuild + test run under it (LD_PRELOAD of libasan included)."""
    san = os.environ.get("KMP_SANITIZE", "").strip()
    if not san:
        return []
    return [f"-fsanitize={san}", "-fno-omit-frame-pointer", "-g", "-O1"]


def _build() -> str:
    """Compile (or reuse) the cached native library; returns its path.

    Raises resilience.NativeUnavailable on a missing toolchain, a failed
    compile, or a compile exceeding build_timeout() — the structured
    error the `native-build` degradation site routes to ctypes-free
    mode."""
    from ..resilience import NativeUnavailable

    h = hashlib.sha256()
    for src in _SRCS:
        with open(src, "rb") as f:
            h.update(f.read())
    # sanitized and plain builds must not share a cache slot
    h.update(",".join(sanitize_flags()).encode())
    tag = h.hexdigest()[:16]
    cdir = cache_dir()
    out = os.path.join(cdir, f"libkmpnative-{tag}.so")
    if os.path.exists(out):
        return out
    try:
        os.makedirs(cdir, exist_ok=True)
        # stale builds from older source versions
        for name in os.listdir(cdir):
            if name.startswith("libkmpnative-") and name.endswith(".so"):
                try:
                    os.remove(os.path.join(cdir, name))
                except OSError:
                    pass
    except OSError as e:
        # an unusable cache dir (bad KAMINPAR_TPU_NATIVE_CACHE_DIR,
        # permissions) must degrade to ctypes-free mode, not crash
        raise NativeUnavailable(f"build cache dir unusable: {e}") from e
    tmp_path = None
    try:
        with tempfile.NamedTemporaryFile(
            suffix=".so", dir=cdir, delete=False
        ) as tmp:
            tmp_path = tmp.name
        subprocess.run(
            # -mssse3 (x86 only): the StreamVByte-class SIMD residual
            # decode in codec2.cpp (guarded by __SSSE3__, scalar on
            # other architectures)
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++20", "-pthread",
             *(["-mssse3"] if platform.machine() in
               ("x86_64", "AMD64", "i686") else []),
             *sanitize_flags(),
             *_SRCS, "-o", tmp_path],
            check=True,
            capture_output=True,
            timeout=build_timeout(),
        )
        os.replace(tmp_path, out)
        return out
    except subprocess.TimeoutExpired as e:
        raise NativeUnavailable(
            f"native build timed out after {build_timeout():.0f}s "
            f"(raise {BUILD_TIMEOUT_ENV} if the toolchain is just slow)"
        ) from e
    except subprocess.CalledProcessError as e:
        stderr = (e.stderr or b"").decode("utf-8", "replace")[-400:]
        raise NativeUnavailable(f"g++ failed: {stderr}") from e
    except OSError as e:
        raise NativeUnavailable(f"toolchain unavailable: {e}") from e
    finally:
        if tmp_path is not None and os.path.exists(tmp_path):
            try:
                os.remove(tmp_path)
            except OSError:
                pass


def _load_native() -> ctypes.CDLL:
    """Build + dlopen + bind signatures, with ONE automatic clean-rebuild
    retry when the cached artifact is corrupted (truncated file, wrong
    architecture, poisoned cache dir: dlopen or symbol binding fails)."""
    from ..resilience import NativeUnavailable
    from ..utils.logger import log_warning

    path = _build()
    try:
        return _bind(ctypes.CDLL(path))
    except (OSError, AttributeError) as e:
        try:
            os.remove(path)
        except OSError:
            pass
        log_warning(
            f"native build cache corrupted ({type(e).__name__}: "
            f"{str(e)[:120]}); clean rebuild"
        )
        path = _build()  # artifact removed -> full recompile
        try:
            return _bind(ctypes.CDLL(path))
        except (OSError, AttributeError) as e2:
            raise NativeUnavailable(
                f"native library unusable after clean rebuild: {e2}"
            ) from e2


def get_lib() -> Optional[ctypes.CDLL]:
    """The native library, building it on first call; None if unavailable.

    Build/load failures degrade through the `native-build` site: a
    `degraded` telemetry event is emitted once and every native entry
    point falls back to its ctypes-free numpy twin for the rest of the
    process."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    from ..resilience import with_fallback

    _lib = with_fallback(_load_native, lambda exc: None, site="native-build")
    return _lib


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    """Declare every exported symbol's signature (raises AttributeError
    on a library that is loadable but not ours — a corrupted cache)."""
    i64 = ctypes.c_int64
    p_i64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    p_i32 = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    p_u8 = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")

    lib.kmp_encode_gaps_size.restype = i64
    lib.kmp_encode_gaps_size.argtypes = [i64, p_i64, p_i32, p_i64]
    lib.kmp_encode_gaps.restype = None
    lib.kmp_encode_gaps.argtypes = [i64, p_i64, p_i32, p_i64, p_u8]
    lib.kmp_decode_gaps.restype = None
    lib.kmp_decode_gaps.argtypes = [i64, p_i64, p_i64, p_u8, p_i32]
    lib.kmp_decode_node.restype = i64
    lib.kmp_decode_node.argtypes = [i64, p_i64, p_i64, p_u8, p_i32]
    lib.kmp_parse_metis_body.restype = i64
    lib.kmp_parse_metis_body.argtypes = [
        ctypes.c_char_p, i64, i64, ctypes.c_int, ctypes.c_int, i64,
        p_i64, p_i32, p_i64, p_i64,
    ]
    i32 = ctypes.c_int32
    f64 = ctypes.c_double
    p_i8 = np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS")
    lib.kmp_ml_bipartition.restype = i64
    lib.kmp_ml_bipartition.argtypes = [
        i64, p_i64, p_i32, p_i64, p_i64, i64, i64,       # graph + caps
        i64, f64, i64,                                   # coarsening
        i64, i64, i64, f64, i32, i32, i32, i32,          # pool
        i32, i32, i64, f64, i64,                         # pool FM
        i32, i32, i64, f64, i64,                         # per-level FM
        ctypes.c_uint64, p_i8,
    ]
    lib.kmp_fm_refine.restype = i64
    lib.kmp_fm_refine.argtypes = [
        i64, p_i64, p_i32, p_i64, p_i64, i64, p_i64,
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS,WRITEABLE"),
        i64, i64, f64, i64, i32, ctypes.c_uint64, i64,
    ]
    lib.kmp_fm_refine_sparse.restype = i64
    lib.kmp_fm_refine_sparse.argtypes = lib.kmp_fm_refine.argtypes
    # v2 codec (interval + streamvbyte-class residuals + varint weights)
    lib.kmp_encode_v2_size.restype = i64
    lib.kmp_encode_v2_size.argtypes = [i64, p_i64, p_i32, p_i64]
    lib.kmp_encode_v2.restype = None
    lib.kmp_encode_v2.argtypes = [i64, p_i64, p_i32, p_i64, p_u8]
    lib.kmp_decode_v2.restype = None
    lib.kmp_decode_v2.argtypes = [i64, p_i64, p_i64, p_u8, p_i32]
    lib.kmp_decode_v2_node.restype = i64
    lib.kmp_decode_v2_node.argtypes = [i64, p_i64, p_i64, p_u8, p_i32]
    lib.kmp_encode_v2_weights_size.restype = i64
    lib.kmp_encode_v2_weights_size.argtypes = [i64, p_i64, p_i32, p_i64, p_i64]
    lib.kmp_encode_v2_weights.restype = None
    lib.kmp_encode_v2_weights.argtypes = [i64, p_i64, p_i32, p_i64, p_i64, p_u8]
    lib.kmp_decode_v2_weights.restype = None
    lib.kmp_decode_v2_weights.argtypes = [i64, p_i64, p_i64, p_u8, p_i64]
    return lib


def available() -> bool:
    return get_lib() is not None


# ---------------------------------------------------------------------------
# Varint gap codec (native with numpy fallback)
# ---------------------------------------------------------------------------


def encode_gaps(xadj: np.ndarray, adjncy: np.ndarray):
    """Encode sorted CSR neighborhoods as varint gap streams.

    Returns (bytes u8[total], offsets i64[n+1])."""
    n = len(xadj) - 1
    xadj = np.ascontiguousarray(xadj, dtype=np.int64)
    adjncy = np.ascontiguousarray(adjncy, dtype=np.int32)
    lib = get_lib()
    offsets = np.zeros(n + 1, dtype=np.int64)
    if lib is not None:
        total = lib.kmp_encode_gaps_size(n, xadj, adjncy, offsets)
        out = np.empty(total, dtype=np.uint8)
        lib.kmp_encode_gaps(n, xadj, adjncy, offsets, out)
        return out, offsets
    return _encode_gaps_np(n, xadj, adjncy)


def decode_gaps(xadj: np.ndarray, offsets: np.ndarray, data: np.ndarray):
    """Inverse of encode_gaps; returns adjncy i32[m]."""
    n = len(xadj) - 1
    xadj = np.ascontiguousarray(xadj, dtype=np.int64)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    out = np.empty(int(xadj[-1]), dtype=np.int32)
    lib = get_lib()
    if lib is not None:
        lib.kmp_decode_gaps(n, xadj, offsets, data, out)
        return out
    return _decode_gaps_np(n, xadj, offsets, data, out)


def decode_node(u: int, xadj, offsets, data) -> np.ndarray:
    """Decode a single node's neighborhood."""
    deg = int(xadj[u + 1] - xadj[u])
    out = np.empty(deg, dtype=np.int32)
    lib = get_lib()
    if lib is not None and deg:
        lib.kmp_decode_node(
            int(u),
            np.ascontiguousarray(xadj, dtype=np.int64),
            np.ascontiguousarray(offsets, dtype=np.int64),
            np.ascontiguousarray(data, dtype=np.uint8),
            out,
        )
        return out
    if deg == 0:
        return out
    sub_x = np.array([0, deg], dtype=np.int64)
    sub_off = np.array([0, 0], dtype=np.int64)
    piece = np.asarray(data[int(offsets[u]) : int(offsets[u + 1])], np.uint8)
    return _decode_gaps_np(1, sub_x, sub_off, piece, out)


def _varint_sizes_np(vals: np.ndarray) -> np.ndarray:
    v = vals.astype(np.uint64)
    sizes = np.ones(len(vals), dtype=np.int64)
    for k in range(1, 5):
        sizes += (v >= (1 << (7 * k))).astype(np.int64)
    return sizes


def _encode_gaps_np(n, xadj, adjncy):
    m = int(xadj[-1])
    first_mask = np.zeros(m, dtype=bool)
    nonempty = xadj[1:] > xadj[:-1]
    first_mask[xadj[:-1][nonempty]] = True
    gaps = np.empty(m, dtype=np.uint32)
    if m:
        gaps[1:] = np.diff(adjncy.astype(np.int64)).astype(np.uint32)
        gaps[first_mask] = adjncy[first_mask].astype(np.uint32) + 1
    sizes = _varint_sizes_np(gaps) if m else np.zeros(0, dtype=np.int64)
    csum = np.concatenate([[0], np.cumsum(sizes)])
    offsets = csum[xadj]
    total = int(csum[-1])
    out = np.zeros(total, dtype=np.uint8)
    # byte-by-byte scatter, vectorized over the byte position
    pos = csum[:-1].copy() if m else csum[:0]
    rem = gaps.copy()
    active = np.ones(m, dtype=bool)
    while m and active.any():
        idx = np.nonzero(active)[0]
        b = (rem[idx] & 0x7F).astype(np.uint8)
        more = rem[idx] >= 0x80
        out[pos[idx]] = b | (more.astype(np.uint8) << 7)
        pos[idx] += 1
        rem[idx] >>= 7
        active[idx] = more
    return out, offsets


def _decode_gaps_np(n, xadj, offsets, data, out):
    # sequential fallback decode (native path is the fast one)
    for u in range(n):
        p = int(offsets[u])
        lo, hi = int(xadj[u]), int(xadj[u + 1])
        prev = -1
        for e in range(lo, hi):
            x = 0
            shift = 0
            while True:
                byte = int(data[p])
                p += 1
                x |= (byte & 0x7F) << shift
                if not byte & 0x80:
                    break
                shift += 7
            prev = x - 1 if e == lo else prev + x
            out[e] = prev
    return out


# ---------------------------------------------------------------------------
# Native sequential multilevel bipartitioner (ip.cpp)
# ---------------------------------------------------------------------------


def ml_bipartition(graph, max_block_weights, ip_ctx, seed: int):
    """Run the native multilevel 2-way bipartitioner on a HostGraph.

    Native counterpart of initial.InitialMultilevelBipartitioner (see
    ip.cpp header); returns an int8 partition, or None when the native
    library is unavailable (caller falls back to the numpy path).
    """
    lib = get_lib()
    if lib is None or graph.n == 0:
        return None
    from ..context import FMStoppingRule

    xadj = np.ascontiguousarray(graph.xadj, dtype=np.int64)
    adjncy = np.ascontiguousarray(graph.adjncy, dtype=np.int32)
    node_w = np.ascontiguousarray(graph.node_weight_array(), dtype=np.int64)
    edge_w = np.ascontiguousarray(graph.edge_weight_array(), dtype=np.int64)
    max_bw = np.asarray(max_block_weights, dtype=np.int64)
    ic = ip_ctx.coarsening
    pool = ip_ctx.pool
    pfm = pool.refinement
    fm = ip_ctx.refinement
    max_cluster_weight = max(
        1, int(ic.cluster_weight_multiplier * int(max_bw.max()))
    )
    out = np.empty(graph.n, dtype=np.int8)
    lib.kmp_ml_bipartition(
        graph.n, xadj, adjncy, node_w, edge_w,
        int(max_bw[0]), int(max_bw[1]),
        int(ic.contraction_limit), float(ic.convergence_threshold),
        max_cluster_weight,
        int(pool.min_num_repetitions),
        int(pool.min_num_non_adaptive_repetitions),
        int(pool.max_num_repetitions), float(pool.repetition_multiplier),
        int(bool(pool.use_adaptive_bipartitioner_selection)),
        int(bool(pool.enable_bfs_bipartitioner)),
        int(bool(pool.enable_ggg_bipartitioner)),
        int(bool(pool.enable_random_bipartitioner)),
        int(bool(pfm.disabled)),
        int(pfm.stopping_rule == FMStoppingRule.ADAPTIVE),
        int(pfm.num_fruitless_moves), float(pfm.alpha),
        int(pfm.num_iterations),
        int(bool(fm.disabled)),
        int(fm.stopping_rule == FMStoppingRule.ADAPTIVE),
        int(fm.num_fruitless_moves), float(fm.alpha),
        int(fm.num_iterations),
        int(seed) & 0xFFFFFFFFFFFFFFFF, out,
    )
    return out


# ---------------------------------------------------------------------------
# Native localized batch k-way FM (fm.cpp)
# ---------------------------------------------------------------------------


# fm_refine's refusal sentinel: native FM could not run at this (n, k).
# INT64_MIN, matching fm.cpp — NOT a small negative, which a threaded run
# whose commit prefix was cut short by a cap race can legitimately return.
FM_REFUSED = -(1 << 63)


def fm_refine(graph, partition, k, max_block_weights, fm_ctx, seed: int,
              threads: int = 1, force_sparse: bool = False):
    """Run the native localized batch FM on a HostGraph partition.

    Native counterpart of the reference's parallel localized FM scheme
    (see fm.cpp header); refines `partition` IN PLACE and returns the
    total cut improvement, or None when the native library is
    unavailable.  `threads` > 1 runs the reference-style worker pool
    (NodeTracker claims + atomic gain table); 1 is bitwise-deterministic.

    Above the dense-table size limit the native side automatically
    switches to the sparse compact-hashing gain cache
    (compact_hashing_gain_cache.h:34 analog, O(m) memory), so FM stays
    active at large k.  `force_sparse` exercises that path at any k
    (tests).

    Returns FM_REFUSED (INT64_MIN) when the native side REFUSED to run —
    k above the sparse engine's 16-bit packed-tag limit (0xFFFF) with the
    dense (n, k) table also unaffordable — so the caller can tell "FM
    did not run" from "FM found no improvement"; the refusal is also
    recorded as an `fm-refused` telemetry event for the run report."""
    lib = get_lib()
    if lib is None or graph.n == 0 or k <= 1:
        return None
    xadj = np.ascontiguousarray(graph.xadj, dtype=np.int64)
    adjncy = np.ascontiguousarray(graph.adjncy, dtype=np.int32)
    node_w = np.ascontiguousarray(graph.node_weight_array(), dtype=np.int64)
    edge_w = np.ascontiguousarray(graph.edge_weight_array(), dtype=np.int64)
    max_bw = np.ascontiguousarray(max_block_weights, dtype=np.int64)
    assert partition.dtype == np.int32 and partition.flags.c_contiguous
    fn = lib.kmp_fm_refine_sparse if force_sparse else lib.kmp_fm_refine
    ret = int(
        fn(
            graph.n, xadj, adjncy, node_w, edge_w, int(k), max_bw,
            partition,
            int(fm_ctx.num_iterations), int(fm_ctx.num_seed_nodes),
            float(fm_ctx.alpha), int(fm_ctx.num_fruitless_moves),
            1,  # adaptive stopping (the reference's default for FM)
            int(seed) & 0xFFFFFFFFFFFFFFFF,
            max(1, int(threads)),
        )
    )
    if ret == FM_REFUSED:
        from .. import telemetry
        from ..utils.logger import log_warning

        # only the sparse engine refuses (16-bit packed tags); the normal
        # entry reaches it because the dense table is over the cap, the
        # test hook because the caller forced the sparse path
        reason = "k exceeds the sparse engine's 16-bit tag limit (0xFFFF)"
        reason += (
            " (sparse path forced)" if force_sparse
            else " and the dense (n, k) table is unaffordable"
        )
        telemetry.event(
            "fm-refused", n=int(graph.n), k=int(k), reason=reason
        )
        log_warning(f"native FM did not run: {reason} (n={graph.n}, k={k})")
    return ret


# ---------------------------------------------------------------------------
# v2 codec: interval + streamvbyte-class residuals + varint edge weights
# (codec2.cpp — the TeraPart compressed_neighborhoods parity codec).
# Native-only: the numpy fallback keeps the v1 gap codec.
# ---------------------------------------------------------------------------


def encode_v2(xadj, adjncy):
    """Encode sorted CSR neighborhoods with the v2 codec.
    Returns (bytes u8[total], offsets i64[n+1]) or None without the lib."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(xadj) - 1
    xadj = np.ascontiguousarray(xadj, dtype=np.int64)
    adjncy = np.ascontiguousarray(adjncy, dtype=np.int32)
    offsets = np.zeros(n + 1, dtype=np.int64)
    total = lib.kmp_encode_v2_size(n, xadj, adjncy, offsets)
    out = np.empty(total, dtype=np.uint8)
    lib.kmp_encode_v2(n, xadj, adjncy, offsets, out)
    return out, offsets


def decode_v2(xadj, offsets, data):
    """Decode a v2 stream; returns adjncy i32[m] in EMIT order
    (interval members first — pairs 1:1 with the weight stream)."""
    lib = get_lib()
    assert lib is not None, "v2 codec requires the native library"
    n = len(xadj) - 1
    xadj = np.ascontiguousarray(xadj, dtype=np.int64)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    out = np.empty(int(xadj[-1]), dtype=np.int32)
    lib.kmp_decode_v2(n, xadj, offsets, data, out)
    return out


def decode_v2_node(u, xadj, offsets, data):
    lib = get_lib()
    assert lib is not None, "v2 codec requires the native library"
    deg = int(xadj[u + 1] - xadj[u])
    out = np.empty(deg, dtype=np.int32)
    if deg:
        lib.kmp_decode_v2_node(
            int(u),
            np.ascontiguousarray(xadj, dtype=np.int64),
            np.ascontiguousarray(offsets, dtype=np.int64),
            np.ascontiguousarray(data, dtype=np.uint8),
            out,
        )
    return out


def encode_v2_weights(xadj, adjncy, edge_w):
    """Varint-encode edge weights in the v2 EMIT order.
    Returns (bytes, woffsets) or None without the lib."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(xadj) - 1
    xadj = np.ascontiguousarray(xadj, dtype=np.int64)
    adjncy = np.ascontiguousarray(adjncy, dtype=np.int32)
    edge_w = np.ascontiguousarray(edge_w, dtype=np.int64)
    woffsets = np.zeros(n + 1, dtype=np.int64)
    total = lib.kmp_encode_v2_weights_size(n, xadj, adjncy, edge_w, woffsets)
    out = np.empty(total, dtype=np.uint8)
    lib.kmp_encode_v2_weights(n, xadj, adjncy, edge_w, woffsets, out)
    return out, woffsets


def decode_v2_weights(xadj, woffsets, wdata):
    lib = get_lib()
    assert lib is not None, "v2 codec requires the native library"
    n = len(xadj) - 1
    xadj = np.ascontiguousarray(xadj, dtype=np.int64)
    woffsets = np.ascontiguousarray(woffsets, dtype=np.int64)
    wdata = np.ascontiguousarray(wdata, dtype=np.uint8)
    out = np.empty(int(xadj[-1]), dtype=np.int64)
    lib.kmp_decode_v2_weights(n, xadj, woffsets, wdata, out)
    return out
