// Native host runtime: graph codecs + text tokenizer.
//
// The reference implements its memory-frugal graph storage and IO in C++
// (kaminpar-common/graph_compression/varint.h, streamvbyte.h;
// kaminpar-io/metis_parser.cc with the mmap tokenizer util/file_toker.h).
// This file is the TPU framework's native equivalent: bulk varint-gap
// encode/decode of sorted CSR neighborhoods and a one-pass METIS body
// tokenizer, exposed through a C ABI consumed via ctypes
// (kaminpar_tpu/native/__init__.py).  The device compute path stays
// JAX/XLA; this is host-runtime code on the ingest/storage path.
//
// Build: g++ -O3 -march=native -shared -fPIC codec.cpp -o libkmpnative.so

#include <cstdint>
#include <cstring>

extern "C" {

// --------------------------------------------------------------------------
// Varint gap codec.
//
// Per node u with sorted neighborhood v_0 < v_1 < ... the stored stream is
// varint(v_0 + 1), varint(v_1 - v_0), ... (first neighbor biased by +1 so a
// gap of 0 never appears; gaps between distinct sorted neighbors are >= 1).
// Unsigned LEB128, 7 bits per byte.
// --------------------------------------------------------------------------

static inline int varint_size(uint32_t x) {
  int s = 1;
  while (x >= 0x80) {
    x >>= 7;
    ++s;
  }
  return s;
}

static inline uint8_t* varint_write(uint8_t* p, uint32_t x) {
  while (x >= 0x80) {
    *p++ = (uint8_t)(x | 0x80);
    x >>= 7;
  }
  *p++ = (uint8_t)x;
  return p;
}

static inline const uint8_t* varint_read(const uint8_t* p, uint32_t* out) {
  uint32_t x = 0;
  int shift = 0;
  while (true) {
    uint8_t b = *p++;
    x |= (uint32_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
  }
  *out = x;
  return p;
}

// Size pass: bytes needed to encode every neighborhood.  offsets[u] receives
// the byte offset of node u's stream; returns the total byte count.
int64_t kmp_encode_gaps_size(int64_t n, const int64_t* xadj,
                             const int32_t* adjncy, int64_t* offsets) {
  int64_t total = 0;
  for (int64_t u = 0; u < n; ++u) {
    offsets[u] = total;
    int64_t lo = xadj[u], hi = xadj[u + 1];
    if (lo < hi) {
      total += varint_size((uint32_t)adjncy[lo] + 1u);
      for (int64_t e = lo + 1; e < hi; ++e)
        total += varint_size((uint32_t)(adjncy[e] - adjncy[e - 1]));
    }
  }
  offsets[n] = total;
  return total;
}

// Write pass into a caller-allocated buffer of kmp_encode_gaps_size bytes.
void kmp_encode_gaps(int64_t n, const int64_t* xadj, const int32_t* adjncy,
                     const int64_t* offsets, uint8_t* out) {
  for (int64_t u = 0; u < n; ++u) {
    uint8_t* p = out + offsets[u];
    int64_t lo = xadj[u], hi = xadj[u + 1];
    if (lo < hi) {
      p = varint_write(p, (uint32_t)adjncy[lo] + 1u);
      for (int64_t e = lo + 1; e < hi; ++e)
        p = varint_write(p, (uint32_t)(adjncy[e] - adjncy[e - 1]));
    }
  }
}

// Decode all neighborhoods back into CSR (xadj must match the original).
void kmp_decode_gaps(int64_t n, const int64_t* xadj, const int64_t* offsets,
                     const uint8_t* bytes, int32_t* adjncy_out) {
  for (int64_t u = 0; u < n; ++u) {
    const uint8_t* p = bytes + offsets[u];
    int64_t lo = xadj[u], hi = xadj[u + 1];
    if (lo < hi) {
      uint32_t first;
      p = varint_read(p, &first);
      adjncy_out[lo] = (int32_t)(first - 1u);
      int32_t prev = adjncy_out[lo];
      for (int64_t e = lo + 1; e < hi; ++e) {
        uint32_t gap;
        p = varint_read(p, &gap);
        prev += (int32_t)gap;
        adjncy_out[e] = prev;
      }
    }
  }
}

// Decode one node's neighborhood; returns its degree.
int64_t kmp_decode_node(int64_t u, const int64_t* xadj, const int64_t* offsets,
                        const uint8_t* bytes, int32_t* out) {
  const uint8_t* p = bytes + offsets[u];
  int64_t deg = xadj[u + 1] - xadj[u];
  if (deg > 0) {
    uint32_t first;
    p = varint_read(p, &first);
    out[0] = (int32_t)(first - 1u);
    for (int64_t i = 1; i < deg; ++i) {
      uint32_t gap;
      p = varint_read(p, &gap);
      out[i] = out[i - 1] + (int32_t)gap;
    }
  }
  return deg;
}

// --------------------------------------------------------------------------
// METIS body tokenizer (one pass over the mmap'd text after the header).
//
// Contract mirrors kaminpar-io/metis_parser.cc semantics: one line per node,
// optional leading node weight, neighbor ids 1-based, optional per-neighbor
// edge weight, '%' comment lines skipped, empty line = isolated node.
// Returns the number of directed edges written, or -(line) on malformed
// input.  xadj must have n+1 slots; adjncy/edge weights sized by the header
// edge count * 2.
// --------------------------------------------------------------------------

int64_t kmp_parse_metis_body(const char* buf, int64_t len, int64_t n,
                             int has_vw, int has_ew, int64_t max_m,
                             int64_t* xadj, int32_t* adjncy, int64_t* vw,
                             int64_t* ew) {
  const char* p = buf;
  const char* end = buf + len;
  int64_t edge = 0;
  int64_t node = 0;

  while (node < n) {
    if (p >= end) {
      // trailing nodes with no line: treat as isolated (tolerant like the
      // reference's parser at EOF)
      xadj[node] = edge;
      if (has_vw) vw[node] = 1;
      ++node;
      continue;
    }
    if (*p == '%') {  // comment line
      while (p < end && *p != '\n') ++p;
      if (p < end) ++p;
      continue;
    }
    xadj[node] = edge;
    bool read_vw = !has_vw;
    int64_t first_tok = 1;
    // parse tokens until newline
    while (p < end && *p != '\n') {
      // skip spaces/tabs/CR
      while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
      if (p >= end || *p == '\n') break;
      uint64_t val = 0;
      if (*p < '0' || *p > '9') return -(node + 1);
      while (p < end && *p >= '0' && *p <= '9') {
        val = val * 10 + (uint64_t)(*p - '0');
        ++p;
      }
      if (!read_vw) {
        vw[node] = (int64_t)val;
        read_vw = true;
      } else if (first_tok || !has_ew) {
        if (edge >= max_m) return -(node + 1);
        if (val == 0) return -(node + 1);  // ids are 1-based
        adjncy[edge] = (int32_t)(val - 1);
        if (has_ew) {
          first_tok = 0;  // next numeric token is this edge's weight
        } else {
          ++edge;
        }
      } else {
        ew[edge] = (int64_t)val;
        ++edge;
        first_tok = 1;
      }
    }
    if (has_ew && !first_tok) return -(node + 1);  // dangling neighbor
    if (has_vw && !read_vw) vw[node] = 1;  // empty line, weighted graph
    if (p < end) ++p;  // consume newline
    ++node;
  }
  xadj[n] = edge;
  return edge;
}

}  // extern "C"
