"""Command-line interface (analog of apps/KaMinPar.cc:405 main +
kaminpar-cli/kaminpar_arguments.cc).

The reference's CLI11 surface maps ~150 flags onto the Context tree, loads
TOML config files (-C) and dumps the effective config (--dump-config,
apps/KaMinPar.cc:90-112).  This argparse CLI covers the same capability
groups: preset selection, partition parameters (k / epsilon / explicit
block weights), algorithm overrides, IO formats, seed, output files,
timers, and config round-tripping (TOML in via tomllib, TOML out via a
small emitter).

Usage:  python -m kaminpar_tpu <graph> -k 16 [-P preset] [options]
"""

from __future__ import annotations

import argparse
import dataclasses
import enum
import os
import sys
import time
from typing import Any, Dict, List, Optional

import numpy as np

from . import io as io_mod
from .context import (
    Context,
    PartitioningMode,
    RefinementAlgorithm,
)
from .kaminpar import KaMinPar
from .presets import create_context_by_preset_name, get_preset_names
from .utils import timer
from .utils.logger import OutputLevel


# ---------------------------------------------------------------------------
# Context <-> plain dict (for -C config files and --dump-config)
# ---------------------------------------------------------------------------

# re-exported from context.py (historical home; the checkpoint ctx
# fingerprint needs it below the CLI layer)
from .context import context_to_dict  # noqa: F401,E402


def apply_dict_to_context(ctx: Any, data: Dict[str, Any]) -> None:
    """Overlay a (possibly partial) nested dict onto the dataclass tree."""
    for key, value in data.items():
        if not hasattr(ctx, key):
            raise ValueError(f"unknown config key: {key!r}")
        current = getattr(ctx, key)
        if dataclasses.is_dataclass(current) and isinstance(value, dict):
            apply_dict_to_context(current, value)
        elif isinstance(current, enum.Enum):
            setattr(ctx, key, type(current)(value))
        elif isinstance(current, list) and current and isinstance(
            current[0], enum.Enum
        ):
            setattr(ctx, key, [type(current[0])(v) for v in value])
        elif key == "algorithms":  # empty refiner list: elements are enums
            setattr(ctx, key, [RefinementAlgorithm(v) for v in value])
        elif value == "inf":
            setattr(ctx, key, float("inf"))
        else:
            setattr(ctx, key, type(current)(value) if current is not None else value)


def dump_toml(data: Dict[str, Any], prefix: str = "") -> List[str]:
    """Minimal TOML emitter for the context dict (scalars, lists, tables)."""
    lines: List[str] = []
    scalars = {k: v for k, v in data.items() if not isinstance(v, dict)}
    tables = {k: v for k, v in data.items() if isinstance(v, dict)}
    for k, v in scalars.items():
        if v is None:
            continue
        if isinstance(v, bool):
            lines.append(f"{k} = {'true' if v else 'false'}")
        elif isinstance(v, (int, float)):
            lines.append(f"{k} = {v}")
        elif isinstance(v, str):
            lines.append(f'{k} = "{v}"')
        elif isinstance(v, list):
            items = ", ".join(
                f'"{x}"' if isinstance(x, str) else str(x) for x in v
            )
            lines.append(f"{k} = [{items}]")
    for k, v in tables.items():
        name = f"{prefix}.{k}" if prefix else k
        lines.append("")
        lines.append(f"[{name}]")
        lines.extend(dump_toml(v, name))
    return lines


# ---------------------------------------------------------------------------
# Argument parser (kaminpar_arguments.cc flag groups)
# ---------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kaminpar_tpu",
        description="TPU-native deep multilevel graph partitioner",
    )
    p.add_argument("graph", nargs="?", help="input graph file")
    p.add_argument("-k", "--k", type=int, default=None, help="number of blocks")
    p.add_argument(
        "-e", "--epsilon", type=float, default=None,
        help="max imbalance, e.g. 0.03 (default)",
    )
    p.add_argument(
        "-B", "--max-block-weights", type=int, nargs="+", default=None,
        help="explicit per-block max weights (overrides -k/-e)",
    )
    p.add_argument(
        "--min-epsilon", type=float, default=None,
        help="enforce min block weights (1-eps)*perfect",
    )
    p.add_argument(
        "-P", "--preset", default="default",
        choices=sorted(get_preset_names()), help="configuration preset",
    )
    p.add_argument("-C", "--config", default=None, help="TOML config file")
    p.add_argument(
        "--dump-config", action="store_true",
        help="print the effective config as TOML and exit",
    )
    p.add_argument("-s", "--seed", type=int, default=None, help="RNG seed")
    p.add_argument(
        "-f", "--format", default="auto",
        choices=["auto", "metis", "parhip", "compressed"],
        help="input graph format",
    )
    p.add_argument(
        "--node-ordering", default="natural",
        choices=["natural", "degree-buckets"],
        help="node ordering applied after loading (NodeOrdering analog)",
    )
    p.add_argument("-o", "--output", default=None, help="partition output file")
    p.add_argument(
        "--output-block-sizes", default=None, help="block size output file"
    )
    p.add_argument(
        "--output-remapping", default=None,
        help="write the node remapping applied by --node-ordering "
        "(write_remapping analog)",
    )
    p.add_argument("-q", "--quiet", action="store_true", help="no output")
    p.add_argument(
        "--validate", action="store_true",
        help="validate the input graph (graph_validator analog)",
    )
    p.add_argument(
        "--no-repair", action="store_true",
        help="disable the output gate's greedy balance-repair pass "
        "(the strict-balance check still runs and reports violations; "
        "see docs/robustness.md)",
    )
    p.add_argument(
        "--checkpoint-dir", default=None, metavar="DIR",
        help="write atomic pipeline-barrier checkpoints (versioned, "
        "checksummed manifest) under DIR; a preempted run can then "
        "--resume without re-running completed levels "
        "(docs/robustness.md)",
    )
    p.add_argument(
        "--resume", action="store_true",
        help="re-enter the pipeline at the stage recorded in "
        "--checkpoint-dir (graph + config fingerprints must match, "
        "else a clean restart); requires --checkpoint-dir",
    )
    p.add_argument(
        "--time-budget", type=float, default=None, metavar="SECS",
        help="anytime mode: wind down at the next pipeline barrier once "
        "SECS of partitioning have elapsed and return the best "
        "gate-valid partition reached (report annotated anytime: true)",
    )
    p.add_argument(
        "--budget-grace", type=float, default=None, metavar="SECS",
        help="declared wind-down allowance on top of --time-budget for "
        "the mandatory tail (extension, gate/repair, final checkpoint; "
        "default 30).  Advisory: reported in the anytime section so "
        "operators can size preemption windows; the tail is not "
        "forcibly interrupted",
    )
    p.add_argument(
        "--memory-budget", type=float, default=None, metavar="BYTES",
        help="declared device-memory budget (bytes; also readable from "
        "KAMINPAR_TPU_HBM_BYTES): the run either fits it or degrades "
        "through the memory governor's recovery ladder (tight pads -> "
        "host-spilled hierarchy -> semi-external streaming -> "
        "host-only) — never RESOURCE_EXHAUSTED (docs/robustness.md)",
    )
    p.add_argument(
        "--delta-batch", default=None, metavar="DELTAS.json",
        help="dynamic repartitioning (kaminpar_tpu/dynamic/): apply the "
        "JSON delta chain (edge inserts/deletes, vertex add/remove, "
        "weight updates) to the positional graph step by step; each "
        "step gets a warm-started v-cycle repartition (or a cold run "
        "when the drift estimator says warm-starting would lose) and "
        "the PR-4 diff cut gate asserts stability across deltas.  "
        "Per-step DYNAMIC lines on stdout, the `dynamic` report "
        "section in --report-json; works with --checkpoint-dir/"
        "--resume (mid-chain kill-and-resume restores the session "
        "cut-identically; docs/robustness.md)",
    )
    p.add_argument(
        "--dynamic-replicas", type=int, default=None, metavar="G",
        help="delta-batch mode: race the warm v-cycle against G-1 cold "
        "replicas per step and keep the better cut (PASCO-style "
        "replicated repartitioning; default 1 = drift decision only)",
    )
    p.add_argument(
        "--serve-batch", default=None, metavar="BATCH.json",
        help="serve/batch mode (partitioning-as-a-service): run every "
        "request in the JSON batch spec through the admission-"
        "controlled PartitionService — per-request fault isolation, "
        "bounded result cache, per-request deadlines, SIGTERM drain; "
        "verdicts land in the report's `serving` section "
        "(docs/robustness.md).  The positional graph and -k are not "
        "used in this mode",
    )
    p.add_argument(
        "--serve-queue-depth", type=int, default=None, metavar="N",
        help="serve mode: admission queue-depth cap (default 64; "
        "overload is rejected, never queued unboundedly)",
    )
    p.add_argument(
        "--serve-cost-cap", type=float, default=None, metavar="BYTES",
        help="serve mode: total estimated-cost admission cap across "
        "queued requests, in bytes of estimated device footprint (the "
        "memory governor's sizing model, resilience/memory.py; "
        "default 8 GiB)",
    )
    p.add_argument(
        "--serve-isolation", default=None,
        choices=["inproc", "process"],
        help="serve mode: execution isolation (default inproc). "
        "`process` runs every request's compute in a supervised worker "
        "subprocess (resilience/supervisor.py): a worker hung past its "
        "hard wall-clock ceiling is SIGKILLed (verdict "
        "failed/worker-hang), a worker segfault/OOM-kill is classified "
        "(failed/worker-crash), and the service keeps draining the "
        "queue; workers are warm-reused and recycled on request-count "
        "or RSS watermarks (docs/robustness.md, supervision contract)",
    )
    p.add_argument(
        "--heartbeat-file", default=None, metavar="PATH",
        help="touch PATH's mtime at every pipeline barrier and from "
        "the watchdog tick while nothing is hung, so external "
        "supervisors (k8s liveness probes, systemd WatchdogSec) can "
        "tell slow-but-alive from hung without parsing output (also "
        "via KAMINPAR_TPU_HEARTBEAT_FILE; docs/robustness.md)",
    )
    p.add_argument(
        "--metrics-file", default=None, metavar="PATH",
        help="export live metrics (request verdicts, rps, queue depth, "
        "cache hit rate, comm bytes) to PATH in Prometheus text "
        "format, rewritten atomically on a cadence (also via "
        "KAMINPAR_TPU_METRICS_FILE; docs/observability.md)",
    )
    p.add_argument(
        "-T", "--timers", action="store_true", help="print the timer tree"
    )
    p.add_argument(
        "--machine-timers", action="store_true",
        help="print the timer tree as one machine-readable line",
    )
    p.add_argument(
        "-H", "--heap-profile", action="store_true",
        help="profile host/device memory per phase (heap_profiler analog)",
    )
    p.add_argument(
        "--statistics", action="store_true",
        help="collect and print detailed statistics (IFSTATS analog)",
    )
    from . import telemetry

    telemetry.add_cli_args(p)
    p.add_argument(
        "-m", "--mode", default=None,
        choices=[m.value for m in PartitioningMode],
        help="partitioning scheme override",
    )
    p.add_argument(
        "--scheme", dest="mode",
        choices=[m.value for m in PartitioningMode],
        help="alias of --mode; `--scheme external` runs the out-of-core "
        "streaming partitioner (kaminpar_tpu/external/): the fine graph "
        "stays host/disk-resident in chunks (gen: specs are regenerated "
        "chunk-by-chunk and never materialized), LP + contraction "
        "stream padded edge blocks through the device, and only coarse "
        "levels are ever device-resident (docs/performance.md)",
    )
    p.add_argument(
        "--external-chunk-edges", type=int, default=None, metavar="M",
        help="external scheme: target edges per streamed chunk (default "
        "2^22; shrunk automatically to fit --memory-budget)",
    )
    p.add_argument(
        "--external-spill-dir", default=None, metavar="DIR",
        help="external scheme: spill decoded fine-level chunks to DIR "
        "once and re-read them per pass (fine graphs bigger than host "
        "RAM stream from disk)",
    )
    # common algorithm overrides (kaminpar_arguments.cc coarsening/refinement)
    p.add_argument("--lp-iterations", type=int, default=None)
    p.add_argument(
        "--lp-rating", default=None,
        choices=["auto", "scatter", "sort2", "sort", "hash", "dense"],
        help="LP rating engine (default auto: per-level density-adaptive "
        "selection; see ops/rating.py and docs/performance.md)",
    )
    p.add_argument(
        "--lp-rating-slots", type=int, default=None,
        help="hashed slots per node row for the scatter/hash engines",
    )
    p.add_argument("--contraction-limit", type=int, default=None)
    p.add_argument(
        "--refinement", default=None,
        help="semicolon-separated refiner list, e.g. "
        "'overload-balancer;lp;underload-balancer'",
    )
    p.add_argument(
        "--vcycles", type=int, nargs="+", default=None,
        help="block counts per v-cycle (vcycle mode)",
    )
    # debug dumps (kaminpar_arguments.cc debug group / DebugContext flags)
    p.add_argument(
        "--debug-dump", nargs="+", default=None, metavar="WHAT",
        choices=[
            "toplevel-graph", "toplevel-partition", "coarsest-graph",
            "coarsest-partition", "graph-hierarchy", "partition-hierarchy",
        ],
        help="write hierarchy dumps (debug.cc analog)",
    )
    p.add_argument(
        "--debug-dump-dir", default=None, help="directory for debug dumps"
    )
    return p


def make_context(args: argparse.Namespace) -> Context:
    ctx = create_context_by_preset_name(args.preset)
    if args.config:
        import tomllib

        with open(args.config, "rb") as f:
            apply_dict_to_context(ctx, tomllib.load(f))
    if args.mode:
        ctx.partitioning.mode = PartitioningMode(args.mode)
    if args.lp_iterations is not None:
        ctx.coarsening.clustering.lp.num_iterations = args.lp_iterations
    if args.lp_rating is not None:
        ctx.coarsening.clustering.lp.rating = args.lp_rating
    if args.lp_rating_slots is not None:
        ctx.coarsening.clustering.lp.rating_slots = args.lp_rating_slots
    if args.contraction_limit is not None:
        ctx.coarsening.contraction_limit = args.contraction_limit
    if args.refinement is not None:
        ctx.refinement.algorithms = [
            RefinementAlgorithm(a) for a in args.refinement.split(";") if a
        ]
    if args.vcycles is not None:
        ctx.partitioning.vcycles = list(args.vcycles)
    if args.debug_dump:
        for what in args.debug_dump:
            setattr(ctx.debug, "dump_" + what.replace("-", "_"), True)
    if args.debug_dump_dir:
        ctx.debug.dump_dir = args.debug_dump_dir
    if args.no_repair:
        ctx.resilience.repair = False
    if args.checkpoint_dir:
        ctx.resilience.checkpoint_dir = args.checkpoint_dir
    if args.resume:
        ctx.resilience.resume = True
    if args.time_budget is not None:
        ctx.resilience.time_budget = args.time_budget
    if args.budget_grace is not None:
        ctx.resilience.budget_grace = args.budget_grace
    if args.memory_budget is not None:
        ctx.resilience.memory_budget = args.memory_budget
    if args.external_chunk_edges is not None:
        ctx.external.chunk_edges = args.external_chunk_edges
    if args.external_spill_dir is not None:
        ctx.external.spill_dir = args.external_spill_dir
    if getattr(args, "dynamic_replicas", None) is not None:
        ctx.dynamic.replicas = int(args.dynamic_replicas)
    if args.seed is not None:  # -C config may set the seed; flag wins
        ctx.seed = args.seed
    return ctx


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    ctx = make_context(args)

    if args.dump_config:
        print("\n".join(dump_toml(context_to_dict(ctx))))
        return 0

    if args.serve_batch is None:
        if args.graph is None:
            print("error: no graph file given", file=sys.stderr)
            return 1
        if args.k is None and args.max_block_weights is None:
            print("error: need -k or -B/--max-block-weights",
                  file=sys.stderr)
            return 1
    if args.delta_batch is not None:
        if args.serve_batch is not None:
            print("error: --delta-batch and --serve-batch are mutually "
                  "exclusive (session requests inside a batch spec "
                  "cover the serve-mode story)", file=sys.stderr)
            return 2
        if args.k is None:
            print("error: --delta-batch needs -k", file=sys.stderr)
            return 2
        if args.node_ordering != "natural":
            print("error: --delta-batch needs natural node ordering "
                  "(delta vertex ids refer to file order; a "
                  "permutation would silently remap them)",
                  file=sys.stderr)
            return 2
        if args.output_remapping:
            print("error: --output-remapping is not supported with "
                  "--delta-batch (vertex add/remove deltas change the "
                  "node set, so no input-file-indexed remapping "
                  "exists; the partition output is indexed by the "
                  "FINAL node set)", file=sys.stderr)
            return 2
    if args.resume and not args.checkpoint_dir:
        print("error: --resume requires --checkpoint-dir", file=sys.stderr)
        return 2

    # preemption routing (resilience/deadline.py): SIGTERM/SIGINT wind
    # the pipeline down at its next barrier and still produce a valid
    # partition + final checkpoint; a second signal forces the classic
    # behavior (handled by the emergency path below)
    from .resilience import deadline as deadline_mod

    deadline_mod.install_signal_handlers()

    # liveness heartbeat (resilience/supervisor.py): configured before
    # any long-running work so the very first barrier already advances
    # the file external supervisors watch
    if args.heartbeat_file:
        from .resilience import supervisor as supervisor_mod

        supervisor_mod.set_heartbeat(args.heartbeat_file)

    # live metrics export (telemetry/metrics.py): armed before the run
    # so the cadence thread publishes scrapes while work is in flight
    # (configure() also folds in KAMINPAR_TPU_METRICS_FILE; no-op when
    # neither names a file — the registry stays dormant)
    from .telemetry import metrics as metrics_mod

    metrics_mod.configure(args.metrics_file)

    from . import telemetry
    from .utils import heap_profiler, statistics

    if args.diff_base and not args.report_json:
        # fail BEFORE the (possibly long) run, like the fault-plan echo:
        # the user asked for a regression gate that could never fire
        print("error: --diff-base requires --report-json", file=sys.stderr)
        return 2

    if args.heap_profile:
        heap_profiler.enable()
    if args.statistics:
        statistics.enable()
    telemetry.enable_if_requested(args)

    # fault-plan echo: an active injection plan changes every result —
    # it must be impossible to mistake a chaos run for a clean one (the
    # run report carries the same plan in its `faults` section).  The
    # plan is parsed HERE so a typo fails at startup with a clear
    # message, not minutes in at the first registered site.
    from .resilience import faults as faults_mod

    fault_plan = os.environ.get(faults_mod.ENV_VAR, "")
    if fault_plan:
        try:
            faults_mod.parse_plan(fault_plan)
        except faults_mod.FaultPlanError as e:
            print(f"error: bad {faults_mod.ENV_VAR}: {e}", file=sys.stderr)
            return 1
        if not args.quiet:
            print(
                f"FAULTS plan={fault_plan} (fault injection ACTIVE; "
                "see the report's 'faults' section)"
            )

    if args.serve_batch is not None:
        # serve/batch mode: the serving layer owns the request loop —
        # admission, isolation, caching, drain — and the report export.
        # The signal handlers installed above make SIGTERM/SIGINT drain
        # the queue instead of killing the process.
        from .serving.batch import run_batch_cli

        return run_batch_cli(args, ctx)

    t_io = time.perf_counter()
    external_mode = ctx.partitioning.mode == PartitioningMode.EXTERNAL
    if args.graph.startswith("gen:"):
        # synthetic input, KaGen option-string style (the dKaMinPar CLI's
        # -G generator surface, kaminpar-io/dist_skagen.h):
        #   gen:rmat;n=65536;m=1000000;seed=1
        graph = None
        if external_mode:
            # the external scheme streams generator specs: skagen chunk
            # regeneration means the synthetic fine graph is NEVER
            # materialized (generators with no streaming form fall back
            # to the in-RAM build below and stream from host CSR)
            from .external.chunkstore import StreamedSpecGraph

            try:
                graph = StreamedSpecGraph(
                    args.graph, target_edges=ctx.external.chunk_edges
                )
            except ValueError:
                graph = None
        if graph is None:
            from .graphs.factories import generate

            graph = generate(args.graph)
    else:
        graph = io_mod.load_graph(
            args.graph, fmt=args.format,
            # disk-backed fine graphs stream without a full-file RAM
            # spike: the external scheme asks for the lazy/mmap load of
            # compressed containers (io/compressed_binary.py)
            lazy=external_mode,
        )
    perm = None
    if args.node_ordering == "degree-buckets":
        from .external.chunkstore import StreamedSpecGraph
        from .graphs.compressed import CompressedHostGraph

        if isinstance(graph, (CompressedHostGraph, StreamedSpecGraph)):
            print(
                "error: --node-ordering is not supported for compressed "
                "containers or streamed generator specs",
                file=sys.stderr,
            )
            return 1
        from .graphs import apply_permutation, degree_bucket_permutation

        perm = degree_bucket_permutation(graph)
        graph = apply_permutation(graph, perm)
    io_s = time.perf_counter() - t_io
    if not ctx.debug.graph_name:
        base = os.path.basename(args.graph)
        ctx.debug.graph_name = os.path.splitext(base)[0] or "graph"

    if args.delta_batch is not None:
        return _run_delta_chain(args, ctx, graph, io_s)

    partitioner = KaMinPar(ctx)
    if args.quiet:
        # instance-scoped: compute_partition applies and restores it
        partitioner.set_output_level(OutputLevel.QUIET)
    partitioner.set_graph(graph, validate=args.validate)

    if args.min_epsilon is not None:
        # needs k/weights set up first; compute_partition redoes setup,
        # so pre-setup here only to derive min weights
        ctx.partition.setup(graph, k=args.k, epsilon=args.epsilon,
                            max_block_weights=args.max_block_weights)
        ctx.partition.setup_min_block_weights(args.min_epsilon)

    t0 = time.perf_counter()
    try:
        partition = partitioner.compute_partition(
            k=args.k,
            epsilon=args.epsilon,
            max_block_weights=(
                np.asarray(args.max_block_weights, dtype=np.int64)
                if args.max_block_weights
                else None
            ),
            seed=args.seed,
        )
    except KeyboardInterrupt:
        # a forced interrupt (second SIGINT) can surface from deep
        # inside a jitted while_loop with timer scopes still open;
        # close them so the emergency run report stays schema-valid,
        # then write whatever observability artifacts were requested
        return _emergency_interrupt_exit(args, t0)
    wall = time.perf_counter() - t0

    if not args.quiet:
        print(f"TIME io={io_s:.3f}s partitioning={wall:.3f}s")
        # one-line cut-loss attribution headline (telemetry/quality.py)
        # next to RESULT/TIME — None when the quality layer recorded
        # nothing (telemetry off, KAMINPAR_TPU_QUALITY=0, no hierarchy)
        from .telemetry import quality as quality_mod

        quality_line = quality_mod.headline()
        if quality_line:
            print(quality_line)
    if args.timers and not args.quiet:
        print(timer.GLOBAL_TIMER.render())
    if args.machine_timers and not args.quiet:
        print("TIMERS " + timer.GLOBAL_TIMER.render_machine())
    if args.heap_profile and not args.quiet:
        print(heap_profiler.render())
    if args.statistics and not args.quiet:
        print(statistics.render())

    # non-zero when --diff-base found a regression against the baseline
    # report (telemetry/diff.py); output files are still written below
    rc = telemetry.export_cli_outputs(
        args,
        extra_run={"io_seconds": round(io_s, 3),
                   "partition_seconds": round(wall, 3)},
        quiet=args.quiet,
    )

    if perm is not None:
        # partition is indexed by reordered node ids; write in file order
        # (the permutation-aware output of kaminpar.cc:437-448)
        partition = partition[perm.old_to_new]
    if args.output_remapping:
        io_mod.write_remapping(
            args.output_remapping,
            perm.old_to_new if perm is not None
            else np.arange(graph.n, dtype=np.int64),  # natural = identity
        )
    if args.output:
        io_mod.write_partition(args.output, partition)
    if args.output_block_sizes:
        io_mod.write_block_sizes(
            args.output_block_sizes, partition, ctx.partition.k
        )
    return rc


def _run_delta_chain(args, ctx, graph, io_s: float) -> int:
    """``--delta-batch`` mode: drive the delta chain through the
    dynamic session driver (register -> per-delta mutate + warm/cold
    repartition), print per-step DYNAMIC lines, annotate the `dynamic`
    report section, and write the FINAL partition via the ordinary
    output flags."""
    from . import telemetry
    from .dynamic import load_delta_file, run_chain
    from .io.errors import GraphFormatError

    try:
        batches = load_delta_file(args.delta_batch)
    except GraphFormatError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    def _cb(step: int, row: dict) -> None:
        if not args.quiet:
            print(
                "DYNAMIC step={} mode={} cut={} drift={} stable={} "
                "gate_valid={} wall={:.3f}s".format(
                    step, row.get("mode"), row.get("cut"),
                    row.get("drift"), row.get("stable"),
                    row.get("gate_valid"), row.get("wall_s", 0.0),
                )
            )

    t0 = time.perf_counter()
    try:
        partition, section = run_chain(
            graph, batches, ctx,
            k=int(args.k),
            # None keeps a -C config's epsilon, like the single-shot path
            epsilon=args.epsilon,
            seed=args.seed, quiet=bool(args.quiet), step_cb=_cb,
        )
    except KeyboardInterrupt:
        return _emergency_interrupt_exit(args, t0)
    except GraphFormatError as e:
        # a malformed delta (or a non-CSR input) is a data problem,
        # exactly like a malformed graph file in single-shot mode
        print(f"error: {e}", file=sys.stderr)
        return 1
    wall = time.perf_counter() - t0

    # the stream belongs to the LAST step's run; the chain-level
    # sections ride on it (the serving layer's annotate-after idiom)
    telemetry.annotate(dynamic=section)
    if not args.quiet:
        counts = section.get("counts", {})
        print(
            "DYNAMIC-CHAIN steps={} warm={} cold={} replica={} "
            "in_place={} rebuilds={} final_cut={} wall={:.3f}s".format(
                len(section.get("decisions", [])),
                counts.get("warm", 0), counts.get("cold", 0),
                counts.get("replica", 0), counts.get("in_place", 0),
                counts.get("rebuilds", 0),
                (section.get("cut_trajectory") or [None])[-1], wall,
            )
        )
    rc = telemetry.export_cli_outputs(
        args,
        extra_run={"io_seconds": round(io_s, 3),
                   "delta_batch": args.delta_batch,
                   "delta_steps": len(batches),
                   "partition_seconds": round(wall, 3)},
        quiet=args.quiet,
    )
    if args.output:
        io_mod.write_partition(args.output, partition)
    if args.output_block_sizes:
        # args.k, not ctx.partition.k: a resumed chain may never run
        # ctx.partition.setup in this process (register fast-forwarded)
        io_mod.write_block_sizes(
            args.output_block_sizes, partition, int(args.k)
        )
    return rc


def _emergency_interrupt_exit(args, t0: float) -> int:
    """The hard-interrupt path (shared by cli and dcli): unwind open
    timer scopes — SIGINT during a jitted while_loop used to leave them
    open, making the emergency report schema-invalid — annotate the
    interruption, and export any requested report/trace before exiting
    with the conventional 130."""
    from . import telemetry
    from .resilience import deadline as deadline_mod

    closed = timer.GLOBAL_TIMER.unwind()
    if telemetry.enabled():
        anytime = {
            "anytime": True,
            "reason": "keyboard-interrupt",
            "elapsed_s": round(time.perf_counter() - t0, 3),
        }
        if deadline_mod.stage_reached():
            anytime["stage"] = deadline_mod.stage_reached()
        telemetry.annotate(anytime=anytime)
        if "result" not in telemetry.run_info():
            # no partition was produced; the schema-required result
            # section carries an explicit no-result sentinel (cut -1,
            # infeasible) rather than going missing — run.interrupted
            # marks the report for downstream consumers (telemetry.diff)
            telemetry.annotate(
                result={"cut": -1, "imbalance": 0.0, "feasible": False}
            )
        telemetry.export_cli_outputs(
            args,
            extra_run={"interrupted": True,
                       "partition_seconds": round(
                           time.perf_counter() - t0, 3)},
            quiet=args.quiet,
        )
    print(
        f"interrupted: {closed} open timer scope(s) closed"
        + (", emergency report written" if getattr(args, "report_json", None)
           else ""),
        file=sys.stderr,
    )
    return 130


if __name__ == "__main__":
    sys.exit(main())
