"""Unified telemetry layer: spans, one-shot decision events, run annotations.

The reference solver's observability story is its parseable hierarchical
timer tree plus the per-PE min/avg/max finalize (kaminpar-common/
timer.{h,cc}, kaminpar-dist/timer.cc).  This package is the shared stream
those utilities publish into here: every `utils.timer` scope exit emits a
structured *span* (name, dotted path, wall time, optional sync time,
host/HBM peaks when heap profiling is on, statistics-counter deltas), and
discrete runtime decisions that previously vanished — the lane-gather
support-probe verdict, jit (re)traces of collective phases, native FM
refusals, host balancer fallbacks — are recorded as one-shot *events*.

Two exporters consume the stream:

  * `telemetry.chrome_trace` — Chrome trace-event JSON (`--trace-out`),
    loadable in Perfetto / chrome://tracing, one track per process on
    multi-host runs;
  * `telemetry.report` — a per-partition-call JSON run report
    (`--report-json`) carrying the scope tree, result metrics, per-level
    graph sizes, the collective-traffic table and an environment stamp.
    `bench.py` embeds the same dict into its BENCH line so the perf
    trajectory and ad-hoc runs share one schema
    (`run_report.schema.json`, validated by
    `scripts/check_report_schema.py`).

Disabled (the default) the layer is free: producers guard on one module
bool and record nothing — the zero-overhead-when-disabled contract the
existing timer/heap-profiler/statistics utilities already honor.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List

_enabled = False

_lock = threading.Lock()
_epoch = time.perf_counter()
_spans: List["Span"] = []
_events: List["Event"] = []
_progress: List["ProgressSeries"] = []
_run_info: Dict[str, Any] = {}
_tids: Dict[int, int] = {}


@dataclass
class Span:
    """One closed timer scope (the stream twin of a TimerNode visit)."""

    name: str
    path: str  # dotted scope path, identical to the timer tree's paths
    start: float  # seconds since the run epoch
    duration: float  # wall seconds
    tid: int = 0
    attrs: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "path": self.path,
            "start": self.start,
            "duration": self.duration,
            "tid": self.tid,
            "attrs": self.attrs,
        }


@dataclass
class Event:
    """One discrete decision (probe verdict, refusal, fallback, trace)."""

    name: str
    t: float  # seconds since the run epoch
    attrs: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"name": self.name, "t": self.t, "attrs": self.attrs}


@dataclass
class ProgressSeries:
    """Per-iteration convergence series of one algorithm loop run
    (telemetry/progress.py): parallel same-length lists keyed by stat
    name, plus the dotted scope path of the enclosing timer scope."""

    kind: str  # "lp", "jet", "fm", "balancer", "dist-lp", "dist-jet"
    path: str  # dotted scope path at emit time (timer-tree aligned)
    t0: float  # loop entry, seconds since the run epoch (0 if unknown)
    t1: float  # emit time, seconds since the run epoch
    iterations: int
    series: Dict[str, List[Any]] = field(default_factory=dict)
    attrs: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "path": self.path,
            "t0": self.t0,
            "t1": self.t1,
            "iterations": self.iterations,
            "series": self.series,
            "attrs": self.attrs,
        }


def enabled() -> bool:
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True
    # compile-cost accounting listens on jax.monitoring; installation is
    # idempotent and the listeners no-op while telemetry is disabled
    try:
        from . import compile_account

        compile_account.install()
    except Exception:
        pass
    # the perf observatory hooks the backend-compile boundary the same
    # way (idempotent, no-op while disabled / KAMINPAR_TPU_PERF=0)
    try:
        from . import perf

        perf.install()
    except Exception:
        pass
    # the execution ledger hooks the executable-call boundary
    # (idempotent, no-op while disabled / KAMINPAR_TPU_LEDGER=0)
    try:
        from . import ledger

        ledger.install()
    except Exception:
        pass


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Clear the stream and restart the run epoch (enable state is kept).

    Callers that may run nested inside another pipeline (shm KaMinPar as
    the distributed driver's initial partitioner) must guard with
    `utils.timer.GLOBAL_TIMER.idle()` — the same open-scope caveat the
    timer's own reset documents."""
    global _epoch
    with _lock:
        _spans.clear()
        _events.clear()
        _progress.clear()
        _run_info.clear()
        _tids.clear()
        _epoch = time.perf_counter()
    try:
        from . import compile_account

        compile_account.reset()
    except Exception:
        pass
    try:
        from . import perf

        perf.reset()
    except Exception:
        pass
    try:
        from . import ledger

        ledger.reset()
    except Exception:
        pass
    try:
        from . import quality

        quality.reset()
    except Exception:
        pass


def jsonable(v: Any) -> Any:
    """Coerce attribute values to JSON-clean types (numpy scalars/arrays
    included); anything exotic degrades to str rather than poisoning an
    export."""
    if v is None or isinstance(v, (str, bool, int, float)):
        return v
    if isinstance(v, dict):
        return {str(k): jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [jsonable(x) for x in v]
    for conv in ("item", "tolist"):
        fn = getattr(v, conv, None)
        if callable(fn):
            try:
                return jsonable(fn())
            except Exception:
                pass
    return str(v)


def _tid() -> int:
    ident = threading.get_ident()
    t = _tids.get(ident)
    if t is None:
        t = _tids[ident] = len(_tids)
    return t


def record_span(name: str, path: str, start: float, duration: float,
                **attrs: Any) -> None:
    """Record a closed scope.  `start` is a time.perf_counter() stamp."""
    if not _enabled:
        return
    clean = {k: jsonable(v) for k, v in attrs.items() if v is not None}
    with _lock:
        _spans.append(
            Span(name, path, start - _epoch, duration, _tid(), clean)
        )


def event(name: str, **attrs: Any) -> None:
    """Record a one-shot event at the current time."""
    if not _enabled:
        return
    clean = {k: jsonable(v) for k, v in attrs.items() if v is not None}
    with _lock:
        _events.append(Event(name, time.perf_counter() - _epoch, clean))


def current_scope_path() -> str:
    """Dotted path of the open timer-scope stack ("" at top level) —
    progress series and compile-cost records align to the same paths
    the scope tree and the spans use."""
    try:
        from ..utils.timer import GLOBAL_TIMER

        return ".".join(n.name for n in GLOBAL_TIMER._stack[1:])
    except Exception:
        return ""


def record_progress(kind: str, series: Dict[str, list], iterations: int,
                    t0: float | None = None, **attrs: Any) -> None:
    """Record one per-iteration convergence series (progress.emit*)."""
    if not _enabled:
        return
    t1 = time.perf_counter() - _epoch
    clean = {k: jsonable(v) for k, v in attrs.items() if v is not None}
    entry = ProgressSeries(
        kind=kind,
        path=current_scope_path(),
        t0=(t0 - _epoch) if t0 is not None else t1,
        t1=t1,
        iterations=int(iterations),
        series={str(k): jsonable(v) for k, v in series.items()},
        attrs=clean,
    )
    with _lock:
        _progress.append(entry)


def progress_series(kind: str | None = None) -> List["ProgressSeries"]:
    """Recorded convergence series (named to avoid shadowing the
    `telemetry.progress` submodule)."""
    with _lock:
        out = list(_progress)
    if kind is not None:
        out = [p for p in out if p.kind == kind]
    return out


def annotate(**kv: Any) -> None:
    """Attach run-level key/values (preset, k, result metrics, ...) that
    the run report surfaces as its `run` / `result` sections."""
    if not _enabled:
        return
    clean = {k: jsonable(v) for k, v in kv.items()}
    with _lock:
        _run_info.update(clean)


def spans() -> List[Span]:
    with _lock:
        return list(_spans)


def events(name: str | None = None) -> List[Event]:
    with _lock:
        evs = list(_events)
    if name is not None:
        evs = [e for e in evs if e.name == name]
    return evs


def run_info() -> Dict[str, Any]:
    with _lock:
        return dict(_run_info)


def gate_verdict() -> Any:
    """The current stream's output-gate verdict as a tri-state:
    True/False when the gate checked this run's partition, None when it
    never ran (gate disabled, no partition in this stream).  The one
    place the `output_gate` annotation shape is interpreted — the
    serving layer and the dynamic repartition policy both read it."""
    gate = run_info().get("output_gate")
    if isinstance(gate, dict) and gate.get("checked"):
        return bool(gate.get("valid"))
    return None


def is_primary_process() -> bool:
    """True on process 0 (or without a backend).  File-writing exporters
    gate on this: on multi-host runs every process must still CALL them
    (their gathers are collective), but only one may write the path."""
    try:
        from ..utils.platform import process_index

        return process_index() == 0
    except Exception:
        return True


# --- shared CLI surface (cli.py + dcli.py) --------------------------------


def add_cli_args(parser) -> None:
    """The --trace-out / --report-json flags, shared by both CLIs."""
    parser.add_argument(
        "--trace-out", default=None, metavar="PATH",
        help="write a Chrome trace-event JSON of the run (open in "
        "Perfetto / chrome://tracing; one track per process); enables "
        "telemetry",
    )
    parser.add_argument(
        "--report-json", default=None, metavar="PATH",
        help="write the per-run JSON report (scope tree, result metrics, "
        "comm table, events; schema: "
        "kaminpar_tpu/telemetry/run_report.schema.json); enables telemetry",
    )
    parser.add_argument(
        "--diff-base", default=None, metavar="BASE.report.json",
        help="after the run, diff this run's --report-json against a "
        "baseline report (telemetry.diff) and exit non-zero past the "
        "regression thresholds; requires --report-json",
    )
    parser.add_argument(
        "--diff-wall-threshold", type=float, default=None, metavar="FRAC",
        help="fractional wall-time regression tolerated by --diff-base "
        "(default 0.10)",
    )
    parser.add_argument(
        "--diff-cut-threshold", type=float, default=None, metavar="FRAC",
        help="fractional edge-cut regression tolerated by --diff-base "
        "(default 0.10)",
    )


def enable_if_requested(args) -> None:
    """Enable telemetry when either CLI output flag was given."""
    if getattr(args, "trace_out", None) or getattr(args, "report_json", None):
        enable()


def export_cli_outputs(args, extra_run=None, quiet: bool = False) -> int:
    """Write the files requested via add_cli_args (no-op without flags).
    Collective on multi-host runs — call from every process.

    Returns a process exit code: 0 normally; with --diff-base, the
    telemetry.diff verdict against the baseline report (non-zero on a
    regression past the thresholds, primary process only)."""
    primary = is_primary_process()
    if getattr(args, "trace_out", None):
        from .chrome_trace import write_chrome_trace

        write_chrome_trace(args.trace_out)
        if not quiet and primary:
            print(f"TRACE written to {args.trace_out} (open in Perfetto)")
    if getattr(args, "report_json", None):
        from .report import write_run_report

        report = write_run_report(args.report_json, extra_run=extra_run)
        if not quiet and primary:
            print(f"REPORT written to {args.report_json}")
            print(
                "  triage: python -m kaminpar_tpu.telemetry.top "
                f"{args.report_json}"
            )
            if (report.get("quality") or {}).get("levels"):
                print(
                    "  quality: python -m kaminpar_tpu.telemetry.quality "
                    f"{args.report_json}"
                )
    if getattr(args, "diff_base", None):
        if not getattr(args, "report_json", None):
            import sys

            print("error: --diff-base requires --report-json",
                  file=sys.stderr)
            return 2
        if not primary:
            return 0
        from .diff import main as diff_main

        argv = [args.diff_base, args.report_json]
        if getattr(args, "diff_wall_threshold", None) is not None:
            argv += ["--wall-threshold", str(args.diff_wall_threshold)]
        if getattr(args, "diff_cut_threshold", None) is not None:
            argv += ["--cut-threshold", str(args.diff_cut_threshold)]
        if quiet:
            argv.append("--quiet")
        return diff_main(argv)
    return 0
