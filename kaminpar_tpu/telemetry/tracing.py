"""End-to-end request tracing: per-request trace ids and span timelines.

The span stream (telemetry/__init__.py) answers "where did THIS
process's wall go, per timer scope"; a serving operator needs the
per-REQUEST twin: one trace id per :class:`PartitionRequest`, with
spans for admission -> queue wait -> resolve -> compute -> gate ->
repair, surviving the supervised-worker process boundary (a
``--serve-isolation process`` request shows its spawn/ship overhead
next to the worker-side compute scopes) and carried across
GraphSession repartitions and dist ranks (rank-annotated rows via the
span ``attrs``).

Storage contract: traces live in this module's OWN bounded store, NOT
the telemetry stream — the serving facade resets the stream per
request mid-batch (so per-run reports stay per-run), but the batch's
traces must survive until the batch-level report is built.
``telemetry.reset()`` therefore does not touch them;
:func:`reset_traces` is the explicit clear (test isolation, service
construction).

Dormancy: tracing is active iff telemetry is enabled — the same single
producer gate every other layer checks.  :func:`new_trace` returns ""
while disabled and every recording helper no-ops on a falsy trace id,
so the dormant cost is one bool check.  All recording is host-side
request bookkeeping; nothing here runs inside jitted code.

Worker-boundary semantics (supervisor.py): the worker harvests its own
depth-1 telemetry spans (:func:`harvest_worker_rows` — worker-relative
ms, origin "worker") and marshals them on the result message; the
parent re-bases them into the request timeline with
:func:`record_worker_reply`, which also records a "worker-spawn-ship"
span of the roundtrip wall the worker itself cannot see.  Ship
overhead is attributed BEFORE the worker window (the dominant cost is
the request npz/pipe ship + a cold worker's spawn), so the timeline
reads: spawn/ship, then the worker's own scopes, ending at the
roundtrip's end.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from . import enabled as _telemetry_enabled
from . import jsonable

#: Bounded trace store: oldest traces are evicted past this count (a
#: long-lived service must not grow without bound; 256 comfortably
#: covers a batch report).
MAX_TRACES = 256

#: Per-trace span cap — a pathological repair loop cannot balloon the
#: report section.
MAX_SPANS_PER_TRACE = 128

_lock = threading.Lock()
_counter = itertools.count(1)
_traces: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()

#: The trace the CURRENT run belongs to (thread-local: the serving
#: layer executes serially but submit() producers may be concurrent).
#: Deep layers that never see the request object — the dist driver's
#: rank rollup, the dynamic session commit — attach rank/session
#: annotated spans to whatever trace is current without any plumbing.
_tls = threading.local()


def set_current(trace_id: str) -> None:
    """Install ("") / clear the executing request's trace id for this
    thread — the deep-layer span hook point."""
    _tls.trace_id = trace_id or ""


def current() -> str:
    """This thread's executing trace id ("" when none)."""
    return getattr(_tls, "trace_id", "")


def enabled() -> bool:
    """Tracing rides the telemetry master switch."""
    return _telemetry_enabled()


def new_trace(request_id: str, **attrs: Any) -> str:
    """Open a trace for one request and return its id ("" while
    telemetry is disabled — callers thread the falsy id through and
    every later helper no-ops)."""
    if not enabled():
        return ""
    trace_id = f"tr-{os.getpid()}-{next(_counter)}"
    entry = {
        "trace_id": trace_id,
        "request_id": str(request_id),
        "t0": time.perf_counter(),
        "spans": [],
        "attrs": {k: jsonable(v) for k, v in attrs.items()
                  if v is not None},
    }
    with _lock:
        _traces[trace_id] = entry
        while len(_traces) > MAX_TRACES:
            _traces.popitem(last=False)
    return trace_id


def span(trace_id: str, name: str, start: Optional[float] = None,
         duration_s: float = 0.0, origin: str = "service",
         **attrs: Any) -> None:
    """Record one span.  ``start`` is a time.perf_counter() stamp
    (defaults to now - duration); stored relative to the trace's t0 in
    milliseconds."""
    if not trace_id:
        return
    with _lock:
        entry = _traces.get(trace_id)
        if entry is None or len(entry["spans"]) >= MAX_SPANS_PER_TRACE:
            return
        if start is None:
            start = time.perf_counter() - max(float(duration_s), 0.0)
        entry["spans"].append({
            "name": str(name),
            "origin": str(origin),
            "start_ms": round((start - entry["t0"]) * 1000.0, 3),
            "duration_ms": round(max(float(duration_s), 0.0) * 1000.0, 3),
            "attrs": {k: jsonable(v) for k, v in attrs.items()
                      if v is not None},
        })


def annotate(trace_id: str, **attrs: Any) -> None:
    """Attach request-level key/values to a trace (verdict, class, k)."""
    if not trace_id:
        return
    with _lock:
        entry = _traces.get(trace_id)
        if entry is not None:
            entry["attrs"].update(
                {k: jsonable(v) for k, v in attrs.items()
                 if v is not None}
            )


# ---------------------------------------------------------------------------
# the supervised-worker boundary
# ---------------------------------------------------------------------------


def harvest_worker_rows(max_rows: int = 48) -> List[dict]:
    """Called INSIDE a supervised worker after compute: its depth-1
    telemetry spans (path without a dot — the top-level timer scopes,
    e.g. ``partitioning``) as marshal-ready rows with worker-relative
    start_ms and origin "worker".  The worker's telemetry stream was
    reset at request start, so these stamps are relative to the
    request's own compute window."""
    from . import spans as _spans

    rows: List[dict] = []
    pid = os.getpid()
    for s in _spans():
        if "." in s.path:
            continue
        rows.append({
            "name": s.name,
            "origin": "worker",
            "start_ms": round(s.start * 1000.0, 3),
            "duration_ms": round(s.duration * 1000.0, 3),
            "attrs": {**s.attrs, "worker_pid": pid},
        })
        if len(rows) >= max_rows:
            break
    return rows


def record_worker_reply(trace_id: str, rows: List[dict], t_send: float,
                        roundtrip_s: float, worker_wall_s: float,
                        worker_pid: Optional[int] = None) -> None:
    """Parent-side merge of a worker's marshalled span rows: record the
    spawn/ship overhead span (roundtrip wall minus the worker's own
    wall — the containment boundary's price), then re-base each worker
    row into this trace's timeline after that overhead."""
    if not trace_id:
        return
    overhead_s = max(float(roundtrip_s) - float(worker_wall_s), 0.0)
    span(
        trace_id, "worker-spawn-ship", start=t_send,
        duration_s=overhead_s, origin="service",
        worker_pid=worker_pid,
    )
    with _lock:
        entry = _traces.get(trace_id)
        if entry is None:
            return
        base_ms = (t_send - entry["t0"] + overhead_s) * 1000.0
        for row in rows or []:
            if len(entry["spans"]) >= MAX_SPANS_PER_TRACE:
                break
            entry["spans"].append({
                "name": str(row.get("name", "")),
                "origin": str(row.get("origin", "worker")),
                "start_ms": round(
                    base_ms + float(row.get("start_ms", 0.0)), 3
                ),
                "duration_ms": round(
                    float(row.get("duration_ms", 0.0)), 3
                ),
                "attrs": {
                    k: jsonable(v)
                    for k, v in (row.get("attrs") or {}).items()
                },
            })


# ---------------------------------------------------------------------------
# consumers
# ---------------------------------------------------------------------------


def get(trace_id: str) -> Optional[dict]:
    with _lock:
        entry = _traces.get(trace_id)
        return _public(entry) if entry is not None else None


def traces() -> List[dict]:
    with _lock:
        return [_public(e) for e in _traces.values()]


def _public(entry: Dict[str, Any]) -> dict:
    return {
        "trace_id": entry["trace_id"],
        "request_id": entry["request_id"],
        "spans": [dict(s) for s in entry["spans"]],
        "attrs": dict(entry["attrs"]),
    }


def snapshot() -> dict:
    """The run report's ``tracing`` section (schema v12)."""
    return {"enabled": enabled(), "traces": traces()}


def reset_traces() -> None:
    """Explicit clear — deliberately NOT wired into telemetry.reset()
    (the serving facade resets the stream per request mid-batch; traces
    must outlive that to reach the batch report)."""
    with _lock:
        _traces.clear()
