"""Compile-cost accounting: XLA trace/lower/compile time per phase.

XLA compile time is the dominant small-graph cost (graphs/csr.py's own
shape-floor rationale: 30-80 s of compiles through the remote tunnel for
graphs of a few thousand nodes), yet it was invisible in the run report
— a "slow run" could not be split into compile vs execute.  jax already
meters every stage through `jax.monitoring`:

  duration events
    /jax/core/compile/jaxpr_trace_duration           (python tracing)
    /jax/core/compile/jaxpr_to_mlir_module_duration  (lowering)
    /jax/core/compile/backend_compile_duration       (XLA backend compile)
    /jax/compilation_cache/compile_time_saved_sec    (persistent-cache hit)
    /jax/compilation_cache/cache_retrieval_time_sec
  count events
    /jax/compilation_cache/cache_hits | cache_misses (persistent cache)
    /jax/compilation_cache/compile_requests_use_cache

This module registers listeners (once, idempotent) and attributes every
duration to the dotted timer-scope path open at dispatch time — jit
compiles run synchronously under the caller's scope, so the attribution
matches the scope tree and the spans.  The aggregate surfaces as the run
report's `compile` section and splits wall time into compile vs execute
per phase (docs/performance.md triage workflow).

Caveats (stamped on the section): an executable-cache hit (in-process
jit cache or warm persistent cache) registers ~nothing, so a warm run
showing zero compile seconds is the cache working, not a meter failure;
persistent hit/miss counters only move when jax's compilation cache is
configured (bench.py turns it on).
"""

from __future__ import annotations

import threading
from typing import Any, Dict

CAVEAT = (
    "durations are metered via jax.monitoring at dispatch time and "
    "attributed to the open timer scope; executable-cache hits register "
    "no compile time, and persistent-cache hit/miss counters only move "
    "when jax_compilation_cache_dir is configured"
)

_DURATION_KEYS = {
    "/jax/core/compile/jaxpr_trace_duration": "trace_s",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lower_s",
    "/jax/core/compile/backend_compile_duration": "compile_s",
}
_TOTAL_ONLY_DURATION_KEYS = {
    "/jax/compilation_cache/compile_time_saved_sec": "cache_saved_s",
    "/jax/compilation_cache/cache_retrieval_time_sec": "cache_retrieval_s",
}
_COUNT_KEYS = {
    "/jax/compilation_cache/cache_hits": "persistent_cache_hits",
    "/jax/compilation_cache/cache_misses": "persistent_cache_misses",
    "/jax/compilation_cache/compile_requests_use_cache": "cache_requests",
}

_lock = threading.Lock()
_installed = False
# phase path -> {trace_s, lower_s, compile_s, compiles}
_phases: Dict[str, Dict[str, float]] = {}
_totals: Dict[str, float] = {}


def _on_duration(event: str, duration_secs: float, **kw: Any) -> None:
    from . import enabled as _telemetry_enabled

    if not _telemetry_enabled():
        return
    key = _DURATION_KEYS.get(event)
    if key is not None:
        from . import current_scope_path

        path = current_scope_path() or "(outside scopes)"
        with _lock:
            entry = _phases.setdefault(
                path,
                {"trace_s": 0.0, "lower_s": 0.0, "compile_s": 0.0,
                 "compiles": 0},
            )
            entry[key] += float(duration_secs)
            if key == "compile_s":
                entry["compiles"] += 1
            _totals[key] = _totals.get(key, 0.0) + float(duration_secs)
        return
    key = _TOTAL_ONLY_DURATION_KEYS.get(event)
    if key is not None:
        with _lock:
            _totals[key] = _totals.get(key, 0.0) + float(duration_secs)


def _on_event(event: str, **kw: Any) -> None:
    from . import enabled as _telemetry_enabled

    if not _telemetry_enabled():
        return
    key = _COUNT_KEYS.get(event)
    if key is not None:
        with _lock:
            _totals[key] = _totals.get(key, 0) + 1


def install() -> None:
    """Register the jax.monitoring listeners (idempotent; the callbacks
    no-op while telemetry is disabled, so installation is free)."""
    global _installed
    if _installed:
        return
    from jax import monitoring

    monitoring.register_event_duration_secs_listener(_on_duration)
    monitoring.register_event_listener(_on_event)
    _installed = True


def reset() -> None:
    with _lock:
        _phases.clear()
        _totals.clear()


def snapshot() -> dict:
    """The run report's `compile` section."""
    with _lock:
        phases = {
            p: {
                "trace_s": round(e["trace_s"], 6),
                "lower_s": round(e["lower_s"], 6),
                "compile_s": round(e["compile_s"], 6),
                "compiles": int(e["compiles"]),
            }
            for p, e in _phases.items()
        }
        totals: Dict[str, Any] = {
            "trace_s": 0.0, "lower_s": 0.0, "compile_s": 0.0,
            "persistent_cache_hits": 0, "persistent_cache_misses": 0,
            "cache_requests": 0,
        }
        for k, v in _totals.items():
            totals[k] = round(v, 6) if isinstance(v, float) else int(v)
    totals["compiles"] = sum(e["compiles"] for e in phases.values())
    return {"caveat": CAVEAT, "totals": totals, "phases": phases}


def render() -> str:
    """Human-readable compile-vs-execute table (docs/performance.md)."""
    snap = snapshot()
    t = snap["totals"]
    lines = [
        f"compile totals: trace={t['trace_s']:.3f}s "
        f"lower={t['lower_s']:.3f}s compile={t['compile_s']:.3f}s "
        f"({t['compiles']} backend compiles; persistent cache "
        f"{t['persistent_cache_hits']} hit / "
        f"{t['persistent_cache_misses']} miss)",
    ]
    for path, e in sorted(
        snap["phases"].items(), key=lambda kv: -kv[1]["compile_s"]
    ):
        lines.append(
            f"  {path}: trace={e['trace_s']:.3f}s lower={e['lower_s']:.3f}s "
            f"compile={e['compile_s']:.3f}s ({e['compiles']}x)"
        )
    return "\n".join(lines)
