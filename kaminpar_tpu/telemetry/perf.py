"""Performance observatory: per-scope roofline accounting, device-memory
watermarks, padding-waste attribution, and serving-latency histograms.

PR 4's compile accounting answers "was the slow part compile or execute";
this layer answers the next question every ROADMAP item 1-4 PR has to ask
before writing kernel code: *where do the bytes, FLOPs and padded-away
slots actually go, and how far below the roofline does each scope sit*.
Four concerns, one module:

  * **roofline accounting** — `install()` wraps jax's backend-compile
    boundary (the same dispatch-time attribution contract as
    `compile_account`): every freshly compiled executable's XLA cost
    analysis (FLOPs, bytes accessed) and compiled memory stats (output /
    temp / argument bytes) are recorded against the dotted timer-scope
    path open at compile time.  `snapshot()` joins those costs with the
    measured per-scope wall from the hierarchical timer and a
    configurable device peak (`KAMINPAR_TPU_PEAK_GBPS` /
    `KAMINPAR_TPU_PEAK_GFLOPS`, defaulting from the detected backend) to
    report achieved bytes/s and FLOPs/s *vs peak* per scope — the
    `vs peak` column BASELINE.json notes used to hand-compute.
  * **device-memory watermarks** — `sample_memory(stage)` records the
    live-device-byte figure (plus backend memory_stats where exposed) as
    a `perf-memory` telemetry event; the PR-5 multilevel barriers call it
    (resilience/checkpoint.barrier), so every coarsen / initial /
    uncoarsen boundary gets a resident-bytes sample with zero code in
    jitted regions.  chrome_trace renders the samples as counter tracks;
    the report's `perf.memory` subsection carries peak bytes, per-stage
    samples, per-level CSR buffer bytes and headroom vs the HBM limit.
  * **padding-waste attribution** — `record_padding(...)` (forwarded by
    `caching.record_padding` from every shape-bucket pad site: device
    CSR upload, contraction, subgraph slicing, the k bucket, the dist
    shards) aggregates real-vs-padded element counts per (scope, bucket)
    and axis, so the report shows what fraction of every kernel launch
    was padding — the direct input ROADMAP item 1 needs to pick fusion
    targets and item 5's bucketing-policy refactor needs to tune caps.
  * **latency histograms** — :class:`Histogram`, a fixed log-spaced
    streaming histogram (p50/p95/p99 without storing samples); the
    serving layer keeps one per request phase and per request class.

Instrumentation contract (pinned by tests/test_perf.py's jaxpr-equality
test): cost capture happens at compile boundaries, memory sampling at
barriers, pad accounting at host-side pad computations — NEVER inside
jitted code, so the traced jaxprs are identical whether the layer is on,
off (`KAMINPAR_TPU_PERF=0`), or telemetry is disabled entirely.

Meter honesty (stamped per roofline row since PR 19): cost is captured
once per *backend compile* and joined with the execution ledger's
per-launch counts (telemetry/ledger.py) — a row whose every launch ran a
costed executable carries ``honest: true`` and launch-multiplied bytes/
FLOPs; a row that saw a launch whose cost was never captured (e.g. a
persistent-cache warm start) carries ``honest: false`` and falls back to
the compile-time lower bound.
"""

from __future__ import annotations

import bisect
import math
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

ENV_VAR = "KAMINPAR_TPU_PERF"
ENV_PEAK_GBPS = "KAMINPAR_TPU_PEAK_GBPS"
ENV_PEAK_GFLOPS = "KAMINPAR_TPU_PEAK_GFLOPS"
ENV_HBM_BYTES = "KAMINPAR_TPU_HBM_BYTES"

#: (GB/s, GFLOP/s) defaults per detected backend.  The TPU numbers are
#: the v5e figures the BASELINE/bench notes already use (819 GB/s HBM;
#: ~197 TFLOP/s bf16); the CPU figures are deliberately rough — on the
#: CPU test backend utilization is a smoke signal, not a measurement.
DEFAULT_PEAKS: Dict[str, Tuple[float, float]] = {
    "tpu": (819.0, 197_000.0),
    "axon": (819.0, 197_000.0),
    "cpu": (40.0, 150.0),
}
FALLBACK_PEAK: Tuple[float, float] = (100.0, 1_000.0)

CAVEAT = (
    "costs are captured once per backend compile, attributed to the "
    "open timer scope, and joined with the execution ledger's "
    "per-launch counts (KAMINPAR_TPU_LEDGER); rows with honest=true "
    "multiply cost by measured launches, rows with honest=false saw a "
    "launch whose cost was never captured (e.g. persistent-cache warm "
    "start) and fall back to the compile-time lower bound; peaks are "
    "configurable via KAMINPAR_TPU_PEAK_GBPS / KAMINPAR_TPU_PEAK_GFLOPS"
)

#: Per-scope executable detail kept for triage; aggregates are unbounded
#: (one entry per distinct scope path — O(scope tree)).
MAX_EXECUTABLES_PER_SCOPE = 32

_lock = threading.Lock()
_installed = False
# dotted scope path -> {"flops","bytes","output_bytes","temp_bytes",
#                       "arg_bytes","compiles","executables":[...]}
_scopes: Dict[str, Dict[str, Any]] = {}
# (dotted scope path, bucket str) -> axis counters
_pad: Dict[Tuple[str, str], Dict[str, int]] = {}


def enabled() -> bool:
    """True iff telemetry is on and KAMINPAR_TPU_PERF is not 0 — the one
    gate every producer checks before doing any work."""
    if os.environ.get(ENV_VAR, "") == "0":
        return False
    from . import enabled as _telemetry_enabled

    return _telemetry_enabled()


def reset() -> None:
    with _lock:
        _scopes.clear()
        _pad.clear()


# ---------------------------------------------------------------------------
# roofline: compile-time cost capture
# ---------------------------------------------------------------------------


def install() -> None:
    """Wrap jax's backend-compile entry point (idempotent; the wrapper
    no-ops while the layer is disabled, so installation is free).  Best
    effort: a jax refactor that moves the entry point degrades to
    "roofline unavailable", never an import error."""
    global _installed
    if _installed:
        return
    try:
        from jax._src import compiler as _compiler
    except Exception:
        return
    orig = getattr(_compiler, "backend_compile", None)
    if orig is None or getattr(orig, "_kaminpar_perf_wrapped", False):
        _installed = True
        return

    def _wrapped(*args: Any, **kwargs: Any):
        exe = orig(*args, **kwargs)
        try:
            if enabled():
                _record_executable(exe)
        except Exception:
            pass  # telemetry must never break a compile
        return exe

    _wrapped._kaminpar_perf_wrapped = True  # type: ignore[attr-defined]
    _compiler.backend_compile = _wrapped
    _installed = True


def _record_executable(exe: Any) -> None:
    """Harvest one freshly compiled executable's cost analysis and
    attribute it to the open scope (compiles run synchronously under the
    caller's scope — the compile_account attribution contract)."""
    cost: Dict[str, Any] = {}
    try:
        ca = exe.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        cost = dict(ca or {})
    except Exception:
        pass
    flops = max(float(cost.get("flops", 0.0) or 0.0), 0.0)
    nbytes = max(float(cost.get("bytes accessed", 0.0) or 0.0), 0.0)
    out_b = temp_b = arg_b = 0
    try:
        ms = exe.get_compiled_memory_stats()
        out_b = int(ms.output_size_in_bytes)
        temp_b = int(ms.temp_size_in_bytes)
        arg_b = int(ms.argument_size_in_bytes)
    except Exception:
        pass
    name = ""
    try:
        name = exe.hlo_modules()[0].name
    except Exception:
        pass
    try:
        # the execution ledger joins launches back to this compile's
        # cost by executable identity (telemetry/ledger.py)
        from . import ledger

        ledger.register_executable(exe, flops=flops, nbytes=nbytes,
                                   name=name)
    except Exception:
        pass
    from . import current_scope_path

    path = current_scope_path() or "(outside scopes)"
    with _lock:
        entry = _scopes.setdefault(
            path,
            {"flops": 0.0, "bytes": 0.0, "output_bytes": 0,
             "temp_bytes": 0, "arg_bytes": 0, "compiles": 0,
             "executables": []},
        )
        entry["flops"] += flops
        entry["bytes"] += nbytes
        entry["output_bytes"] += out_b
        entry["temp_bytes"] += temp_b
        entry["arg_bytes"] += arg_b
        entry["compiles"] += 1
        if len(entry["executables"]) < MAX_EXECUTABLES_PER_SCOPE:
            entry["executables"].append(
                {"name": name, "flops": flops, "bytes": nbytes,
                 "output_bytes": out_b}
            )


def peaks() -> Dict[str, Any]:
    """The roofline ceiling this process compares against: env override
    first, else a default from the detected backend."""
    source = "env"
    gbps = _env_float(ENV_PEAK_GBPS)
    gflops = _env_float(ENV_PEAK_GFLOPS)
    if gbps is None or gflops is None:
        backend = "unknown"
        try:
            from ..utils import platform

            backend = platform.default_backend()
        except Exception:
            pass
        d_gbps, d_gflops = DEFAULT_PEAKS.get(backend, FALLBACK_PEAK)
        if gbps is None:
            gbps = d_gbps
        if gflops is None:
            gflops = d_gflops
        source = f"default:{backend}"
    return {"gbps": float(gbps), "gflops": float(gflops),
            "source": source}


def _env_float(name: str) -> Optional[float]:
    raw = os.environ.get(name, "")
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


# ---------------------------------------------------------------------------
# device-memory watermarks
# ---------------------------------------------------------------------------


def _device_memory_stats() -> Dict[str, int]:
    """bytes_in_use / peak / limit where the backend exposes them (TPU
    does via memory_stats; CPU returns {})."""
    try:
        from ..utils import platform

        stats = platform.local_devices()[0].memory_stats()
    except Exception:
        stats = None
    if not stats:
        return {}
    out: Dict[str, int] = {}
    for src, dst in (
        ("bytes_in_use", "bytes_in_use"),
        ("peak_bytes_in_use", "peak_bytes_in_use"),
        ("bytes_limit", "bytes_limit"),
    ):
        if src in stats:
            out[dst] = int(stats[src])
    return out


def hbm_limit_bytes() -> Optional[int]:
    """The device memory ceiling headroom is computed against:
    KAMINPAR_TPU_HBM_BYTES first, else the backend's bytes_limit."""
    raw = _env_float(ENV_HBM_BYTES)
    if raw is not None:
        return int(raw)
    limit = _device_memory_stats().get("bytes_limit")
    return int(limit) if limit else None


def sample_memory(stage: str, level: Optional[int] = None
                  ) -> Optional[dict]:
    """Record one resident-memory sample as a `perf-memory` telemetry
    event (events ride the existing multi-host gather and become Chrome
    counter tracks).  Called from the PR-5 multilevel barriers — host
    side, between device launches, never inside traced code.  Returns
    the sample attrs, or None when the layer is off."""
    if not enabled():
        return None
    from ..utils import heap_profiler

    attrs: Dict[str, Any] = {
        "stage": str(stage),
        "live_bytes": int(heap_profiler.live_device_bytes()),
    }
    if level is not None:
        attrs["level"] = int(level)
    attrs.update(_device_memory_stats())
    from . import event

    event("perf-memory", **attrs)
    return attrs


def rank_memory_rollup() -> List[dict]:
    """Per-process live-device-bytes figures ([{rank, live_bytes}]).

    Collective on multi-host runs (allgather) — every process must call
    it together, same contract as the aggregated timers; single-process
    runs return just the local row.  The dist driver stamps the result
    into the run report (`perf.memory.ranks`)."""
    from ..utils import heap_profiler

    local = int(heap_profiler.live_device_bytes())
    try:
        from ..utils.platform import process_count, process_index

        nproc = process_count()
        rank = process_index()
    except Exception:
        return [{"rank": 0, "live_bytes": local}]
    if nproc <= 1:
        return [{"rank": int(rank), "live_bytes": local}]
    import numpy as np
    from jax.experimental import multihost_utils

    gathered = np.asarray(
        multihost_utils.process_allgather(
            np.array([local], dtype=np.int64)
        )
    ).reshape(-1)
    try:
        from . import ledger

        ledger.transfer("d2h", gathered.nbytes, "dist-gather")
    except Exception:
        pass
    return [
        {"rank": p, "live_bytes": int(gathered[p])} for p in range(nproc)
    ]


# ---------------------------------------------------------------------------
# padding-waste attribution
# ---------------------------------------------------------------------------


def record_padding(
    n: Optional[int] = None, n_pad: Optional[int] = None,
    m: Optional[int] = None, m_pad: Optional[int] = None,
    k: Optional[int] = None, k_pad: Optional[int] = None,
) -> None:
    """Record one padded launch shape: real vs padded element counts per
    axis, keyed by (open scope path, padded bucket).  Callers pass only
    the axes they padded; host-side, a dict update, nothing traced."""
    if not enabled():
        return
    from . import current_scope_path

    path = current_scope_path() or "(outside scopes)"
    bucket = "/".join(
        str(int(v)) if v is not None else "-"
        for v in (n_pad, m_pad, k_pad)
    )
    with _lock:
        e = _pad.setdefault(
            (path, bucket),
            {"launches": 0, "n": 0, "n_pad": 0, "m": 0, "m_pad": 0,
             "k": 0, "k_pad": 0},
        )
        e["launches"] += 1
        for axis, real, padded in (
            ("n", n, n_pad), ("m", m, m_pad), ("k", k, k_pad)
        ):
            if padded:
                e[axis] += int(real or 0)
                e[axis + "_pad"] += int(padded)


def _waste(real: int, padded: int) -> Optional[float]:
    if not padded:
        return None
    return round(1.0 - real / padded, 4)


# ---------------------------------------------------------------------------
# streaming latency histogram
# ---------------------------------------------------------------------------


class Histogram:
    """Fixed log-spaced streaming histogram over seconds.

    42 bucket edges from 100 µs up by sqrt(2) per bucket (~148 s span);
    a value exactly on an edge lands in the bucket *starting* at that
    edge, values below the first edge share bucket 0, values past the
    last edge share the final bucket.  Quantiles interpolate to the
    bucket's upper edge clamped to the observed maximum — conservative
    (never under-reports a latency SLO) and exact for boundary values.
    Single-writer by design (the serving loop is serial); snapshots are
    consistent under the GIL.
    """

    EDGES: Tuple[float, ...] = tuple(
        1e-4 * (2 ** (i / 2.0)) for i in range(42)
    )

    def __init__(self) -> None:
        self.counts = [0] * len(self.EDGES)
        self.count = 0
        self.total = 0.0
        self.max = 0.0

    def record(self, seconds: float) -> None:
        v = max(float(seconds), 0.0)
        i = bisect.bisect_right(self.EDGES, v) - 1
        if i < 0:
            i = 0
        self.counts[i] += 1
        self.count += 1
        self.total += v
        if v > self.max:
            self.max = v

    def quantile(self, q: float) -> Optional[float]:
        """The q-quantile in seconds (None when empty)."""
        if self.count == 0:
            return None
        target = max(1, math.ceil(q * self.count))
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= target:
                upper = (
                    self.EDGES[i + 1] if i + 1 < len(self.EDGES)
                    else self.max
                )
                return min(upper, self.max)
        return self.max

    def reset(self) -> None:
        self.counts = [0] * len(self.EDGES)
        self.count = 0
        self.total = 0.0
        self.max = 0.0

    def snapshot(self) -> dict:
        """Report-ready summary (milliseconds; empty histograms report
        null quantiles rather than inventing a zero)."""
        def ms(v: Optional[float]) -> Optional[float]:
            return round(v * 1000.0, 3) if v is not None else None

        nonzero = [
            [ms(self.EDGES[i]), c]
            for i, c in enumerate(self.counts) if c
        ]
        return {
            "count": int(self.count),
            "mean_ms": ms(self.total / self.count) if self.count else None,
            "max_ms": ms(self.max) if self.count else None,
            "p50_ms": ms(self.quantile(0.50)),
            "p95_ms": ms(self.quantile(0.95)),
            "p99_ms": ms(self.quantile(0.99)),
            "buckets": nonzero,
        }


# ---------------------------------------------------------------------------
# snapshot: the run report's `perf` section
# ---------------------------------------------------------------------------


def _timer_walls() -> Dict[str, Tuple[float, float, int]]:
    """Per-scope (inclusive wall, exclusive/self wall, call count).

    Self wall (inclusive minus the children's inclusive time) is what a
    cost attributed to a non-leaf scope actually ran in — a compile
    dispatched while only `coarsening` was open executed in coarsening's
    own time, not its children's — so the deficit ranking uses it; the
    inclusive figure stays the human-facing wall column."""
    from ..utils import timer

    out: Dict[str, Tuple[float, float, int]] = {}

    def rec(node, path: str) -> None:
        for child in node.children.values():
            p = f"{path}.{child.name}" if path else child.name
            child_total = sum(
                c.elapsed for c in child.children.values()
            )
            self_wall = max(0.0, child.elapsed - child_total)
            out[p] = (child.elapsed, self_wall, child.count)
            rec(child, p)

    rec(timer.GLOBAL_TIMER.root, "")
    return out


def _total_wall() -> float:
    from ..utils import timer

    return sum(
        c.elapsed for c in timer.GLOBAL_TIMER.root.children.values()
    )


def snapshot() -> dict:
    """Assemble the `perf` report section from the current state.

    Roofline rows join the per-scope compile costs with the scope's
    measured wall; memory samples come from the `perf-memory` event
    stream (so a multi-host report sees every rank's samples the same
    way spans are gathered); pad-waste rows aggregate per (scope,
    bucket) with per-axis waste fractions."""
    on = enabled()
    pk = peaks()
    with _lock:
        scopes = {p: dict(e) for p, e in _scopes.items()}
        pad_items = [(key, dict(e)) for key, e in _pad.items()]

    try:
        from . import ledger as _ledger

        launch_map = _ledger.launch_totals()
    except Exception:
        launch_map = {}

    walls = _timer_walls()
    roofline: Dict[str, Any] = {}
    tot_flops = tot_bytes = 0.0
    tot_eff_flops = tot_eff_bytes = 0.0
    tot_launches = tot_uncosted = 0
    empty = {"flops": 0.0, "bytes": 0.0, "output_bytes": 0,
             "temp_bytes": 0, "arg_bytes": 0, "compiles": 0,
             "executables": []}
    for path in sorted(set(scopes) | set(launch_map)):
        # a scope can launch without compiling (warm cache under a
        # fresh scope path) — it still gets a roofline row
        e = scopes.get(path, empty)
        lm = launch_map.get(
            path, {"launches": 0, "uncosted": 0, "bytes": 0.0,
                   "flops": 0.0},
        )
        wall, self_wall, calls = walls.get(path, (0.0, 0.0, 0))
        # honest: every launch in this scope ran a costed executable,
        # so the ledger figures are the true moved bytes/FLOPs; stale
        # (honest=false) rows fall back to the compile-time lower bound
        honest = lm["launches"] > 0 and lm["uncosted"] == 0
        eff_bytes = lm["bytes"] if honest else max(e["bytes"], lm["bytes"])
        eff_flops = lm["flops"] if honest else max(e["flops"], lm["flops"])
        row: Dict[str, Any] = {
            "flops": round(e["flops"], 1),
            "bytes": round(e["bytes"], 1),
            "output_bytes": int(e["output_bytes"]),
            "temp_bytes": int(e["temp_bytes"]),
            "compiles": int(e["compiles"]),
            "launches": int(lm["launches"]),
            "uncosted_launches": int(lm["uncosted"]),
            "ledger_bytes": round(lm["bytes"], 1),
            "ledger_flops": round(lm["flops"], 1),
            "honest": honest,
            "wall_s": round(wall, 6),
            "self_s": round(self_wall, 6),
            "calls": int(calls),
            "executables": e["executables"],
        }
        if wall > 0:
            achieved_gbps = eff_bytes / wall / 1e9
            achieved_gflops = eff_flops / wall / 1e9
            hbm_util = achieved_gbps / pk["gbps"] if pk["gbps"] else 0.0
            flops_util = (
                achieved_gflops / pk["gflops"] if pk["gflops"] else 0.0
            )
            row.update(
                achieved_gbps=round(achieved_gbps, 3),
                achieved_gflops=round(achieved_gflops, 3),
                hbm_util=round(hbm_util, 4),
                flops_util=round(flops_util, 4),
                # wall spent below the roofline: the triage ranking key
                # (telemetry.top --by util-deficit).  Exclusive wall, so
                # a non-leaf scope with one attributed compile does not
                # re-count its children's time and per-row deficits sum
                # to at most the total wall.
                deficit_s=round(
                    self_wall
                    * (1.0 - min(1.0, max(hbm_util, flops_util))), 6
                ),
            )
        roofline[path] = row
        tot_flops += e["flops"]
        tot_bytes += e["bytes"]
        tot_eff_flops += eff_flops
        tot_eff_bytes += eff_bytes
        tot_launches += lm["launches"]
        tot_uncosted += lm["uncosted"]

    pad_rows: List[dict] = []
    pad_real = pad_padded = 0
    axis_real = {"n": 0, "m": 0, "k": 0}
    axis_padded = {"n": 0, "m": 0, "k": 0}
    for (path, bucket), e in pad_items:
        row = {
            "scope": path,
            "bucket": bucket,
            "launches": int(e["launches"]),
        }
        for axis in ("n", "m", "k"):
            w = _waste(e[axis], e[axis + "_pad"])
            if w is not None:
                row[axis + "_real"] = int(e[axis])
                row[axis + "_pad"] = int(e[axis + "_pad"])
                row[axis + "_waste"] = w
                # per-bucket pad slack ("headroom", element count per
                # launch): the free padded slots of this bucket — the
                # same number that decides whether a dynamic-session
                # delta can apply IN PLACE (same executable bucket,
                # dynamic/session.py) or must rebuild and re-upload
                row[axis + "_slack"] = int(
                    (e[axis + "_pad"] - e[axis])
                    // max(int(e["launches"]), 1)
                )
                pad_real += e[axis]
                pad_padded += e[axis + "_pad"]
                axis_real[axis] += e[axis]
                axis_padded[axis] += e[axis + "_pad"]
        pad_rows.append(row)
    pad_rows.sort(key=lambda r: (-r["launches"], r["scope"], r["bucket"]))

    from . import events as _events

    samples = [
        {"t": round(e.t, 6), **e.attrs} for e in _events("perf-memory")
    ]
    peak_live = max((s.get("live_bytes", 0) for s in samples), default=0)
    limit = hbm_limit_bytes()
    memory: Dict[str, Any] = {
        "peak_live_bytes": int(peak_live),
        "samples": samples,
    }
    if limit:
        memory["hbm_limit_bytes"] = int(limit)
        memory["headroom_bytes"] = int(limit - peak_live)

    total_wall = _total_wall()
    totals: Dict[str, Any] = {
        "flops": round(tot_flops, 1),
        "bytes": round(tot_bytes, 1),
        # launch-honest twins (execution ledger): compile-time figures
        # above stay flat across re-launches, these scale with them
        "ledger_flops": round(tot_eff_flops, 1),
        "ledger_bytes": round(tot_eff_bytes, 1),
        "launches": int(tot_launches),
        "util_honest": bool(tot_launches > 0 and tot_uncosted == 0),
        "compiles": sum(e["compiles"] for e in scopes.values()),
        "wall_s": round(total_wall, 6),
        "pad_waste": _waste(pad_real, pad_padded),
        # per-axis twins: the headline sums element counts across axes,
        # so edge counts (m >> n >> k) numerically dominate it — a 25%
        # k-bucket waste is invisible there but plain in pad_waste_axes
        "pad_waste_axes": {
            axis: w
            for axis in ("n", "m", "k")
            if (w := _waste(axis_real[axis], axis_padded[axis]))
            is not None
        },
        # per-axis total slack (padded - real element counts): the
        # aggregate headroom twin of the per-row *_slack figures
        "pad_slack_axes": {
            axis: int(axis_padded[axis] - axis_real[axis])
            for axis in ("n", "m", "k")
            if axis_padded[axis]
        },
    }
    if total_wall > 0:
        # launch-honest: the effective (ledger-joined) byte/FLOP totals
        # drive the headline utilization; totals["bytes"]/["flops"]
        # remain the flat compile-time figures for comparison
        totals["hbm_util"] = round(
            tot_eff_bytes / total_wall / 1e9 / pk["gbps"], 4
        ) if pk["gbps"] else 0.0
        totals["flops_util"] = round(
            tot_eff_flops / total_wall / 1e9 / pk["gflops"], 4
        ) if pk["gflops"] else 0.0

    return {
        "enabled": on,
        "caveat": CAVEAT,
        "peaks": pk,
        "totals": totals,
        "roofline": roofline,
        "memory": memory,
        "pad_waste": pad_rows,
    }
