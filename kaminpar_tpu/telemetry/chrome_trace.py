"""Chrome trace-event exporter (`--trace-out`).

Serializes the telemetry stream to the Chrome trace-event JSON object
format (the `{"traceEvents": [...]}` flavor Perfetto and chrome://tracing
both accept): every closed span becomes a complete ("ph": "X") event,
every one-shot decision an instant ("ph": "i") event, and every
algorithm-progress series (telemetry/progress.py) a counter ("ph": "C")
track — moved nodes / cut / fruitless counters render as per-iteration
curves under the phase that produced them.

Multi-host runs get one track per process: each process's local stream is
gathered with the same `process_allgather` machinery the distributed
timer finalize uses (utils/timer.aggregate_across_processes), and the
exporter emits the union with per-process `pid`s plus `process_name` /
`thread_name` metadata ("ph": "M") so Perfetto labels tracks by RANK
instead of bare pids — the Perfetto analog of the reference's per-PE
timer rows (kaminpar-dist/timer.cc).
"""

from __future__ import annotations

import json
from typing import List, Tuple

from . import events as _events
from . import progress_series as _progress_series
from . import spans as _spans


def _local_payload() -> dict:
    return {
        "spans": [s.to_dict() for s in _spans()],
        "events": [e.to_dict() for e in _events()],
        "progress": [p.to_dict() for p in _progress_series()],
    }


def gather_payloads() -> List[Tuple[int, dict]]:
    """[(process index, {"spans": [...], "events": [...]})] across all
    processes; a single-process run (or an unreachable backend) returns
    just the local stream under pid 0."""
    local = _local_payload()
    try:
        from ..utils.platform import process_count, process_index

        nproc = process_count()
        pid = process_index()
    except Exception:
        return [(0, local)]
    if nproc <= 1:
        return [(pid, local)]
    # all hosts must call this together (same code path), mirroring the
    # collective finalize contract of aggregate_across_processes
    import numpy as np
    from jax.experimental import multihost_utils

    blob = np.frombuffer(json.dumps(local).encode("utf-8"), np.uint8)
    lens = np.asarray(
        multihost_utils.process_allgather(
            np.array([blob.size], dtype=np.int64)
        )
    ).reshape(-1)
    width = int(lens.max())
    padded = np.zeros(width, np.uint8)
    padded[: blob.size] = blob
    gathered = np.asarray(
        multihost_utils.process_allgather(padded)
    ).reshape(nproc, width)
    out = []
    for p in range(nproc):
        raw = bytes(gathered[p][: int(lens[p])])
        out.append((p, json.loads(raw.decode("utf-8"))))
    return out


def _counter_events(pid: int, series: dict) -> List[dict]:
    """Counter ("ph": "C") events for one progress series: iteration
    values spread uniformly over the loop's [t0, t1] wall window (the
    per-iteration device timestamps never leave the fused loop — the
    spread places the curve under the right span without inventing
    precision the buffer does not have)."""
    out: List[dict] = []
    t0 = float(series.get("t0", 0.0))
    t1 = max(float(series.get("t1", t0)), t0)
    names = list(series.get("series", {}).keys())
    n = int(series.get("iterations", 0))
    if not names or n <= 0:
        return out
    kind = series.get("kind", "progress")
    step = (t1 - t0) / n
    for stat in names:
        vals = series["series"][stat]
        for i, v in enumerate(vals[:n]):
            out.append(
                {
                    "ph": "C",
                    "cat": "progress",
                    "name": f"{kind}.{stat}",
                    "ts": round((t0 + (i + 1) * step) * 1e6, 3),
                    "pid": pid,
                    "tid": 0,
                    "args": {stat: v},
                }
            )
    return out


def chrome_trace() -> dict:
    """The trace-event JSON object for the current stream."""
    trace_events: List[dict] = []
    for pid, payload in gather_payloads():
        # rank-labeled metadata tracks: on multi-host runs the pid IS
        # the process index, so "rank N" reads directly in Perfetto
        trace_events.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "tid": 0,
                "args": {"name": f"kaminpar-tpu rank {pid}"},
            }
        )
        tids = sorted({int(s.get("tid", 0)) for s in payload["spans"]} | {0})
        for t in tids:
            trace_events.append(
                {
                    "ph": "M",
                    "name": "thread_name",
                    "pid": pid,
                    "tid": t,
                    "args": {"name": "main" if t == 0 else f"worker-{t}"},
                }
            )
        for s in payload["spans"]:
            trace_events.append(
                {
                    "ph": "X",
                    "cat": "span",
                    "name": s["name"],
                    "ts": round(s["start"] * 1e6, 3),
                    "dur": round(s["duration"] * 1e6, 3),
                    "pid": pid,
                    "tid": int(s.get("tid", 0)),
                    "args": {"path": s["path"], **s.get("attrs", {})},
                }
            )
        for e in payload["events"]:
            trace_events.append(
                {
                    "ph": "i",
                    "s": "p",  # process-scoped instant
                    "cat": "event",
                    "name": e["name"],
                    "ts": round(e["t"] * 1e6, 3),
                    "pid": pid,
                    "tid": 0,
                    "args": e.get("attrs", {}),
                }
            )
            if e["name"] == "quality-level":
                # per-level cut-loss attribution renders as counter
                # tracks (telemetry/quality.py): the projected / refined
                # / floor cut curve and the locked/left split per level
                attrs = e.get("attrs", {})
                cuts = {
                    key: attrs[key]
                    for key in ("projected_cut", "refined_cut",
                                "floor_cut")
                    if attrs.get(key) is not None
                }
                split = {
                    key: attrs[key]
                    for key in ("coarsening_locked", "refinement_left")
                    if attrs.get(key) is not None
                }
                for name, counters in (("quality.cut", cuts),
                                       ("quality.attribution", split)):
                    if counters:
                        trace_events.append(
                            {
                                "ph": "C",
                                "cat": "quality",
                                "name": name,
                                "ts": round(e["t"] * 1e6, 3),
                                "pid": pid,
                                "tid": 0,
                                "args": counters,
                            }
                        )
            if e["name"] == "perf-memory":
                # barrier memory watermarks render as a counter track
                # (telemetry/perf.py samples; one curve per byte figure)
                attrs = e.get("attrs", {})
                counters = {
                    key: attrs[key]
                    for key in ("live_bytes", "bytes_in_use")
                    if key in attrs
                }
                if counters:
                    trace_events.append(
                        {
                            "ph": "C",
                            "cat": "perf",
                            "name": "memory",
                            "ts": round(e["t"] * 1e6, 3),
                            "pid": pid,
                            "tid": 0,
                            "args": counters,
                        }
                    )
            if e["name"] == "ledger-transfer":
                # host<->device transfer bytes render as cumulative
                # counter tracks (telemetry/ledger.py): each event
                # carries the running h2d/d2h totals, so the curve's
                # slope is the transfer rate and steps mark chokepoints
                attrs = e.get("attrs", {})
                counters = {
                    key: attrs[key]
                    for key in ("h2d_total", "d2h_total")
                    if key in attrs
                }
                if counters:
                    trace_events.append(
                        {
                            "ph": "C",
                            "cat": "ledger",
                            "name": "transfer-bytes",
                            "ts": round(e["t"] * 1e6, 3),
                            "pid": pid,
                            "tid": 0,
                            "args": counters,
                        }
                    )
        for series in payload.get("progress", []):
            trace_events.extend(_counter_events(pid, series))
    trace_events.extend(_request_trace_events())
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


#: pid offset of the per-request trace tracks — far above any real
#: process index so request timelines never collide with rank tracks.
_REQUEST_PID_BASE = 1000


def _request_trace_events() -> List[dict]:
    """Per-request trace timelines (telemetry/tracing.py) as their own
    Perfetto tracks: one pid per request, service spans on tid 0 and
    worker-origin spans on tid 1 — the spawn/ship overhead span and the
    worker's re-based scopes read directly against the service-side
    compute span above them."""
    from . import tracing as _tracing

    out: List[dict] = []
    for i, tr in enumerate(_tracing.traces()):
        spans = tr.get("spans") or []
        if not spans:
            continue
        pid = _REQUEST_PID_BASE + i
        out.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "tid": 0,
                "args": {"name": f"request {tr.get('request_id', '?')}"},
            }
        )
        for tid, label in ((0, "service"), (1, "worker")):
            out.append(
                {
                    "ph": "M",
                    "name": "thread_name",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": label},
                }
            )
        for s in spans:
            out.append(
                {
                    "ph": "X",
                    "cat": "request",
                    "name": s["name"],
                    "ts": round(float(s["start_ms"]) * 1e3, 3),
                    "dur": round(float(s["duration_ms"]) * 1e3, 3),
                    "pid": pid,
                    "tid": 1 if s.get("origin") == "worker" else 0,
                    "args": {
                        "trace_id": tr.get("trace_id", ""),
                        **(s.get("attrs") or {}),
                    },
                }
            )
    return out


def write_chrome_trace(path: str) -> None:
    """Write the trace to `path` (open in Perfetto: ui.perfetto.dev).

    Collective on multi-host runs: every process must call this (the
    payload gather allgathers), but only process 0 writes the file —
    concurrent writers on a shared filesystem would interleave."""
    from . import is_primary_process

    trace = chrome_trace()
    if is_primary_process():
        with open(path, "w") as f:
            json.dump(trace, f)
