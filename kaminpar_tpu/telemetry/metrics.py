"""Live metrics registry + Prometheus text-format exporter.

The perf (PR 7) and quality (PR 11) observatories are post-hoc report
files; nothing tells an operator what a *running* service is doing.
This module is the live half of the fleet observatory: a thread-safe
registry of counters / gauges / windowed rates / latency histograms
(reusing :class:`..telemetry.perf.Histogram`) that the serving,
supervision, dist and dynamic layers feed, exported on a cadence as
Prometheus text format to a file an operator (or node_exporter's
textfile collector) can scrape.

Dormancy contract (the same pin every prior telemetry layer carries):
the registry is **dormant by default** — producers call through
:func:`enabled`-guarded helpers that return immediately unless a
metrics file has been configured via ``--metrics-file`` (both CLIs),
``ServiceConfig.metrics_file``, or the ``KAMINPAR_TPU_METRICS_FILE``
environment variable.  Instrumentation lives exclusively on the host
side (request bookkeeping, summary hooks, collective *accounting* —
never inside jitted code), so traced jaxprs are bitwise-identical
whether the exporter is on or off (pinned by
tests/test_fleet_obs.py::test_metrics_dormancy_jaxpr).

Export is atomic like the heartbeat touches (tmp + ``os.replace`` in
the target directory), so a scrape mid-batch never sees a torn file.
A background cadence thread (default 2 s, ``KAMINPAR_TPU_METRICS_CADENCE_S``)
rewrites the file while work is in flight; :func:`write_now` forces a
flush at batch boundaries so short-lived CLI runs always leave a final
scrape behind.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from .perf import Histogram

ENV_VAR = "KAMINPAR_TPU_METRICS_FILE"
ENV_CADENCE = "KAMINPAR_TPU_METRICS_CADENCE_S"
DEFAULT_CADENCE_S = 2.0

#: Sliding window the requests_per_second figure is computed over.
DEFAULT_WINDOW_S = 30.0

#: Every metric this module exports carries the kmp_ namespace prefix.
PREFIX = "kmp_"

_lock = threading.RLock()
_path: Optional[str] = None
_cadence_s: float = DEFAULT_CADENCE_S
_thread: Optional[threading.Thread] = None
_stop = threading.Event()
_metrics: "Dict[str, _Metric]" = {}
_atexit_armed = False


# ---------------------------------------------------------------------------
# metric kinds
# ---------------------------------------------------------------------------


class _Metric:
    """Base: a named family with fixed label names and per-labelset
    float samples.  All mutation happens under the module lock."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str,
                 labelnames: Tuple[str, ...] = ()) -> None:
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self.values: Dict[Tuple[str, ...], float] = {}

    def _key(self, labels: Dict[str, Any]) -> Tuple[str, ...]:
        return tuple(str(labels.get(k, "")) for k in self.labelnames)

    def samples(self) -> List[Tuple[Tuple[str, ...], float]]:
        with _lock:
            return sorted(self.values.items())

    def clear(self) -> None:
        with _lock:
            self.values.clear()


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, **labels: Any) -> None:
        key = self._key(labels)
        with _lock:
            self.values[key] = self.values.get(key, 0.0) + float(value)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels: Any) -> None:
        with _lock:
            self.values[self._key(labels)] = float(value)


class WindowRate(_Metric):
    """Events-per-second over a sliding window (the live
    ``requests_per_second`` figure).

    Semantics (pinned by tests/test_fleet_obs.py): ``rate()`` counts the
    marks inside the trailing ``window_s`` seconds and divides by the
    window actually *covered* — ``min(window_s, now - first_mark)`` —
    floored at 1 s so a burst in the first instant reads as events/s,
    not events/ε.  The clock is injectable for deterministic tests.
    """

    kind = "gauge"

    def __init__(self, name: str, help_text: str,
                 window_s: float = DEFAULT_WINDOW_S,
                 clock: Callable[[], float] = time.monotonic) -> None:
        super().__init__(name, help_text, ())
        self.window_s = float(window_s)
        self.clock = clock
        self._stamps: deque = deque()
        self._t_first: Optional[float] = None

    def mark(self, n: int = 1) -> None:
        now = self.clock()
        with _lock:
            if self._t_first is None:
                self._t_first = now
            for _ in range(int(n)):
                self._stamps.append(now)
            self._prune(now)

    def _prune(self, now: float) -> None:
        horizon = now - self.window_s
        while self._stamps and self._stamps[0] < horizon:
            self._stamps.popleft()

    def rate(self) -> float:
        now = self.clock()
        with _lock:
            self._prune(now)
            if not self._stamps or self._t_first is None:
                return 0.0
            covered = max(1.0, min(self.window_s, now - self._t_first))
            return len(self._stamps) / covered

    def samples(self) -> List[Tuple[Tuple[str, ...], float]]:
        return [((), self.rate())]

    def clear(self) -> None:
        with _lock:
            self._stamps.clear()
            self._t_first = None


class HistogramMetric(_Metric):
    """A perf.Histogram rendered as a Prometheus summary (quantile
    labels + _sum/_count) — the registry twin of the serving layer's
    per-phase latency histograms."""

    kind = "summary"

    def __init__(self, name: str, help_text: str) -> None:
        super().__init__(name, help_text, ())
        self.hist = Histogram()

    def observe(self, seconds: float) -> None:
        with _lock:
            self.hist.record(seconds)

    def clear(self) -> None:
        with _lock:
            self.hist.reset()


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def _get_or_make(cls, name: str, help_text: str, labelnames=(),
                 **kwargs: Any):
    with _lock:
        m = _metrics.get(name)
        if m is None:
            if cls is HistogramMetric or cls is WindowRate:
                m = cls(name, help_text, **kwargs)
            else:
                m = cls(name, help_text, labelnames)
            _metrics[name] = m
        return m


def counter(name: str, help_text: str = "", labelnames=()) -> Counter:
    return _get_or_make(Counter, name, help_text, labelnames)


def gauge(name: str, help_text: str = "", labelnames=()) -> Gauge:
    return _get_or_make(Gauge, name, help_text, labelnames)


def window_rate(name: str, help_text: str = "",
                window_s: float = DEFAULT_WINDOW_S,
                clock: Callable[[], float] = time.monotonic
                ) -> WindowRate:
    return _get_or_make(WindowRate, name, help_text,
                        window_s=window_s, clock=clock)


def histogram(name: str, help_text: str = "") -> HistogramMetric:
    return _get_or_make(HistogramMetric, name, help_text)


# ---------------------------------------------------------------------------
# producer-facing helpers (no-ops while dormant)
# ---------------------------------------------------------------------------


def enabled() -> bool:
    """True iff a metrics file has been configured — the single gate
    every producer checks before touching the registry."""
    return _path is not None


def inc(name: str, help_text: str = "", value: float = 1.0,
        **labels: Any) -> None:
    if not enabled():
        return
    counter(name, help_text, tuple(sorted(labels))).inc(value, **labels)


def set_gauge(name: str, value: float, help_text: str = "",
              **labels: Any) -> None:
    if not enabled():
        return
    gauge(name, help_text, tuple(sorted(labels))).set(value, **labels)


def observe(name: str, seconds: float, help_text: str = "") -> None:
    if not enabled():
        return
    histogram(name, help_text).observe(seconds)


def mark(name: str, help_text: str = "", n: int = 1) -> None:
    if not enabled():
        return
    window_rate(name, help_text).mark(n)


def rate(name: str) -> float:
    """Current value of a windowed rate (0.0 when absent/dormant)."""
    with _lock:
        m = _metrics.get(name)
    return m.rate() if isinstance(m, WindowRate) else 0.0


def gauge_value(name: str, **labels: Any) -> Optional[float]:
    """Current value of a gauge/counter labelset (None when absent)."""
    with _lock:
        m = _metrics.get(name)
        if m is None or isinstance(m, (WindowRate, HistogramMetric)):
            return None
        return m.values.get(m._key(labels))


# ---------------------------------------------------------------------------
# Prometheus text rendering
# ---------------------------------------------------------------------------


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(value: str) -> str:
    return (
        value.replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _fmt(value: float) -> str:
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _render_labels(labelnames: Tuple[str, ...],
                   labelvalues: Tuple[str, ...],
                   extra: Optional[Tuple[str, str]] = None) -> str:
    parts = [
        f'{k}="{_escape_label(v)}"'
        for k, v in zip(labelnames, labelvalues)
    ]
    if extra is not None:
        parts.append(f'{extra[0]}="{_escape_label(extra[1])}"')
    return "{" + ",".join(parts) + "}" if parts else ""


def render() -> str:
    """The registry as Prometheus text format (version 0.0.4)."""
    lines: List[str] = []
    with _lock:
        families = sorted(_metrics.values(), key=lambda m: m.name)
    for m in families:
        lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        if isinstance(m, HistogramMetric):
            with _lock:
                h = m.hist
                count, total = h.count, h.total
                quantiles = [
                    (q, h.quantile(q)) for q in (0.5, 0.95, 0.99)
                ]
            for q, v in quantiles:
                if v is None:
                    continue
                lines.append(
                    f"{m.name}"
                    f'{{quantile="{q}"}} {_fmt(v)}'
                )
            lines.append(f"{m.name}_sum {_fmt(total)}")
            lines.append(f"{m.name}_count {_fmt(float(count))}")
            continue
        for labelvalues, value in m.samples():
            labels = _render_labels(m.labelnames, labelvalues)
            lines.append(f"{m.name}{labels} {_fmt(value)}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# exporter: configure / cadence thread / atomic writes
# ---------------------------------------------------------------------------


def configure(path: Optional[str] = None,
              cadence_s: Optional[float] = None) -> bool:
    """Arm the exporter.  ``path`` wins over ``KAMINPAR_TPU_METRICS_FILE``;
    with neither set this is a no-op and the registry stays dormant.
    Returns True iff the exporter is (now) armed.  Idempotent — a second
    call with a path just re-points the file."""
    global _path, _cadence_s, _atexit_armed
    resolved = path or os.environ.get(ENV_VAR, "")
    if not resolved:
        return enabled()
    with _lock:
        _path = resolved
        raw = os.environ.get(ENV_CADENCE, "")
        if cadence_s is not None:
            _cadence_s = float(cadence_s)
        elif raw:
            try:
                _cadence_s = float(raw)
            except ValueError:
                pass
        if not _atexit_armed:
            # every CLI exit path leaves a final scrape behind without
            # per-return-point wiring (a no-op once reset() disarmed)
            import atexit

            atexit.register(shutdown)
            _atexit_armed = True
    _start_thread()
    return True


def _start_thread() -> None:
    global _thread
    with _lock:
        if _thread is not None and _thread.is_alive():
            return
        _stop.clear()
        _thread = threading.Thread(
            target=_cadence_loop, name="kmp-metrics-exporter", daemon=True
        )
        _thread.start()


def _cadence_loop() -> None:
    while not _stop.wait(_cadence_s):
        try:
            write_now()
        except Exception:
            pass  # the exporter must never take the service down


def write_now() -> Optional[str]:
    """Render and atomically publish the scrape file (tmp +
    ``os.replace`` in the target directory — a reader never observes a
    torn write).  Returns the path written, or None while dormant."""
    path = _path
    if path is None:
        return None
    text = render()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def shutdown(final_write: bool = True) -> None:
    """Stop the cadence thread (tests; CLI exit).  Leaves the registry
    and path armed so a final :func:`write_now` still works."""
    global _thread
    _stop.set()
    t = _thread
    if t is not None and t.is_alive():
        t.join(timeout=5.0)
    _thread = None
    if final_write and enabled():
        try:
            write_now()
        except Exception:
            pass


def reset() -> None:
    """Disarm and clear everything (test isolation)."""
    global _path, _cadence_s
    shutdown(final_write=False)
    with _lock:
        _path = None
        _cadence_s = DEFAULT_CADENCE_S
        _metrics.clear()


def snapshot() -> Dict[str, Any]:
    """Registry contents as plain data (tests, the top comm panel)."""
    out: Dict[str, Any] = {}
    with _lock:
        metrics = dict(_metrics)
    for name, m in sorted(metrics.items()):
        if isinstance(m, HistogramMetric):
            out[name] = m.hist.snapshot()
        elif isinstance(m, WindowRate):
            out[name] = round(m.rate(), 4)
        else:
            out[name] = {
                ",".join(k) if k else "": v
                for k, v in m.samples()
            }
    return out
