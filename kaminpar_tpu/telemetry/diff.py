"""Run-report diff and regression gate.

`python -m kaminpar_tpu.telemetry.diff BASE.report.json CAND.report.json`
aligns two run reports (schema v1 through v5) by dotted scope path, by
progress series, and — for serving runs — by request id, prints the
wall / cut / convergence / serving deltas, and exits non-zero when the
candidate regresses past the configurable thresholds — the mechanical
answer to "are these two runs the same solver?" that the reference's
parseable timer output only enables by hand.

Gated (exit 1 on regression):
  * edge cut:        cand.result.cut  > base * (1 + --cut-threshold)
  * feasibility:     base feasible but cand infeasible
  * total wall:      cand wall > base * (1 + --wall-threshold), with an
                     absolute --min-wall-s floor so micro-run noise
                     cannot trip the gate
  * serving (both reports carry an enabled v4+ `serving` section):
      - served rate: cand served a smaller fraction of its batch than
        base (rate, not absolute count — batch sizes may differ)
      - cache hit rate: cand dropped more than --hit-rate-threshold
        (absolute) below base

Informational (printed, never gated):
  * per-scope wall deltas (scope_tree alignment, largest first)
  * compile vs execute split deltas (schema v2 `compile` section)
  * progress-series convergence deltas: iterations to converge and, for
    series carrying a `cut` stat, the final per-series cut
  * per-request verdict transitions (serving requests aligned by id)
  * roofline totals deltas (schema v5 `perf` section: bytes, hbm_util,
    pad waste)
  * quality attribution deltas (schema v7 `quality` section: per-level
    coarsening_locked / refinement_left movement and verdict flips)
  * comm-volume deltas (schema v12 `comm` section: bytes_total and
    per-phase traced collective payload movement)

Exit codes: 0 pass, 1 regression, 2 usage/IO error.  check_all.sh runs
the self-diff (identical reports, expect 0) and a perturbed diff
(expect 1) as a CI self-test; the CLIs wire it via `--diff-base`.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_WALL_THRESHOLD = 0.10
DEFAULT_CUT_THRESHOLD = 0.10
DEFAULT_MIN_WALL_S = 0.05
#: absolute serving cache hit-rate drop tolerated before the gate fires
DEFAULT_HIT_RATE_THRESHOLD = 0.10


def load_report(path: str) -> dict:
    with open(path) as f:
        report = json.load(f)
    if not isinstance(report, dict) or "schema_version" not in report:
        raise ValueError(f"{path}: not a run report (no schema_version)")
    return report


def total_wall_s(report: dict) -> Optional[float]:
    """Total partitioning wall: the CLI's measured seconds when present,
    else the scope tree's top-level elapsed sum."""
    run = report.get("run", {})
    for key in ("partition_seconds", "wall_seconds"):
        v = run.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
    tree = report.get("scope_tree", {})
    if tree:
        return sum(
            float(node.get("elapsed_s", 0.0)) for node in tree.values()
        )
    return None


def flatten_scopes(tree: dict, prefix: str = "") -> Dict[str, float]:
    out: Dict[str, float] = {}
    for name, node in tree.items():
        path = f"{prefix}.{name}" if prefix else name
        out[path] = float(node.get("elapsed_s", 0.0))
        out.update(flatten_scopes(node.get("children", {}), path))
    return out


def _progress_key(entry: dict) -> Tuple:
    attrs = entry.get("attrs", {})
    return (
        entry.get("kind", ""),
        entry.get("path", ""),
        attrs.get("level"),
        attrs.get("round"),
    )


def align_progress(base: dict, cand: dict) -> List[Tuple[dict, dict]]:
    """Pair progress series by (kind, path, level, round), in order of
    appearance within each key (k-th occurrence pairs with k-th)."""
    def grouped(report):
        groups: Dict[Tuple, List[dict]] = {}
        for entry in report.get("progress", []) or []:
            groups.setdefault(_progress_key(entry), []).append(entry)
        return groups

    gb, gc = grouped(base), grouped(cand)
    pairs: List[Tuple[dict, dict]] = []
    for key, bs in gb.items():
        cs = gc.get(key, [])
        for b, c in zip(bs, cs):
            pairs.append((b, c))
    return pairs


def _final(series: dict, name: str) -> Optional[float]:
    vals = series.get("series", {}).get(name)
    if vals:
        return float(vals[-1])
    return None


def _pct(new: float, old: float) -> str:
    if old == 0:
        return "n/a" if new == 0 else "+inf"
    return f"{100.0 * (new - old) / abs(old):+.1f}%"


def diff_serving(
    base: dict,
    cand: dict,
    hit_rate_threshold: float = DEFAULT_HIT_RATE_THRESHOLD,
) -> Tuple[List[str], List[str]]:
    """Serving-section diff (schema v4+): align requests by id, report
    verdict transitions, gate served-rate and cache-hit-rate
    regressions.  Returns (lines, failures); both empty unless BOTH
    reports carry an enabled serving section — a single-shot run diffed
    against a serving run is a workload change, not a regression."""
    sb = base.get("serving") or {}
    sc = cand.get("serving") or {}
    lines: List[str] = []
    failures: List[str] = []
    if not (sb.get("enabled") and sc.get("enabled")):
        if sb.get("enabled") != sc.get("enabled"):
            lines.append(
                "serving: only "
                + ("base" if sb.get("enabled") else "cand")
                + " ran in serve mode (section not compared)"
            )
        return lines, failures

    counts_b = sb.get("counts") or {}
    counts_c = sc.get("counts") or {}
    served_b = int(counts_b.get("served", 0))
    served_c = int(counts_c.get("served", 0))
    total_b = sum(int(v) for v in counts_b.values())
    total_c = sum(int(v) for v in counts_c.values())
    lines.append(
        "serving: served {}/{} -> {}/{}, failed {} -> {}, "
        "rejected {} -> {}".format(
            served_b, total_b, served_c, total_c,
            counts_b.get("failed", 0), counts_c.get("failed", 0),
            counts_b.get("rejected", 0), counts_c.get("rejected", 0),
        )
    )
    # gate on the served *rate*, not the absolute count — base and cand
    # may come from different batch sizes, and a 12/12 candidate is no
    # regression against a 16/16 base
    if total_b > 0 and total_c > 0:
        rate_b = served_b / total_b
        rate_c = served_c / total_c
        if rate_c < rate_b - 1e-9:
            failures.append(
                "served rate regressed: "
                f"{served_b}/{total_b} -> {served_c}/{total_c}"
            )

    hr_b = (sb.get("cache") or {}).get("hit_rate")
    hr_c = (sc.get("cache") or {}).get("hit_rate")
    if hr_b is not None and hr_c is not None:
        lines.append(f"serving cache hit_rate: {hr_b} -> {hr_c}")
        if float(hr_c) < float(hr_b) - hit_rate_threshold:
            failures.append(
                f"serving cache hit rate regressed {hr_b} -> {hr_c} "
                f"(threshold -{hit_rate_threshold})"
            )

    # per-request alignment by id: verdict transitions are the triage
    # detail behind a served-count regression (informational — the
    # count gate above decides pass/fail)
    rb = {r.get("request_id"): r for r in sb.get("requests") or []}
    rc = {r.get("request_id"): r for r in sc.get("requests") or []}
    changed = [
        (rid, rb[rid].get("verdict"), rc[rid].get("verdict"))
        for rid in rb
        if rid in rc and rb[rid].get("verdict") != rc[rid].get("verdict")
    ]
    for rid, vb, vc in changed[:8]:
        lines.append(f"  request {rid}: {vb} -> {vc}")
    only_b = sorted(set(rb) - set(rc))
    only_c = sorted(set(rc) - set(rb))
    if only_b:
        lines.append(f"  requests only in base: {only_b[:5]}")
    if only_c:
        lines.append(f"  requests only in cand: {only_c[:5]}")

    # latency movement (informational): p95 of the caller-observed total
    def p95(s):
        return (
            ((s.get("latency") or {}).get("phases") or {})
            .get("total", {}).get("p95_ms")
        )

    pb, pc = p95(sb), p95(sc)
    if pb is not None and pc is not None:
        lines.append(f"serving p95 total: {pb}ms -> {pc}ms")
    return lines, failures


def diff_quality(base: dict, cand: dict) -> Tuple[List[str], List[str]]:
    """Quality-section diff (schema v7): align levels by level index,
    report per-level locked/left deltas and verdict flips, plus the
    headline fraction movement.  Informational — the cut gate above is
    the pass/fail signal; attribution tells you WHERE it moved.  Both
    reports must carry an enabled quality section (a pre-v7 baseline is
    a schema transition, not a regression)."""
    qb = base.get("quality") or {}
    qc = cand.get("quality") or {}
    lines: List[str] = []
    failures: List[str] = []
    if not (qb.get("enabled") and qc.get("enabled")):
        if qb.get("enabled") != qc.get("enabled"):
            lines.append(
                "quality: only "
                + ("base" if qb.get("enabled") else "cand")
                + " carries a quality section (not compared)"
            )
        return lines, failures

    tb = qb.get("totals") or {}
    tc = qc.get("totals") or {}
    lines.append(
        "quality: gap_mass {} -> {}, coarsening_locked_frac {} -> {}, "
        "refinement_left_frac {} -> {}".format(
            tb.get("gap_mass"), tc.get("gap_mass"),
            tb.get("coarsening_locked_frac"),
            tc.get("coarsening_locked_frac"),
            tb.get("refinement_left_frac"),
            tc.get("refinement_left_frac"),
        )
    )
    lb = {row.get("level"): row for row in qb.get("levels") or []}
    lc = {row.get("level"): row for row in qc.get("levels") or []}
    for level in sorted(set(lb) & set(lc)):
        rb_, rc_ = lb[level], lc[level]
        bits = []
        for key, label in (("coarsening_locked", "locked"),
                           ("refinement_left", "left")):
            vb, vc = rb_.get(key), rc_.get(key)
            if vb is not None and vc is not None and vb != vc:
                bits.append(f"{label} {vb} -> {vc}")
        vb, vc = rb_.get("verdict"), rc_.get("verdict")
        if vb is not None and vc is not None and vb != vc:
            bits.append(f"verdict {vb} -> {vc}")
        if bits:
            lines.append(f"  quality level {level}: " + ", ".join(bits))
    only_b = sorted(set(lb) - set(lc))
    only_c = sorted(set(lc) - set(lb))
    if only_b:
        lines.append(f"  quality levels only in base: {only_b[:5]}")
    if only_c:
        lines.append(f"  quality levels only in cand: {only_c[:5]}")
    return lines, failures


def diff_reports(
    base: dict,
    cand: dict,
    wall_threshold: float = DEFAULT_WALL_THRESHOLD,
    cut_threshold: float = DEFAULT_CUT_THRESHOLD,
    min_wall_s: float = DEFAULT_MIN_WALL_S,
    hit_rate_threshold: float = DEFAULT_HIT_RATE_THRESHOLD,
) -> Tuple[List[str], List[str]]:
    """Returns (report lines, gated failures); empty failures = pass."""
    lines: List[str] = []
    failures: List[str] = []

    # -- result: cut + feasibility (the gate's primary signal) -----------
    rb, rc = base.get("result", {}), cand.get("result", {})
    cut_b, cut_c = rb.get("cut"), rc.get("cut")
    if isinstance(cut_b, int) and isinstance(cut_c, int):
        lines.append(f"cut: {cut_b} -> {cut_c} ({_pct(cut_c, cut_b)})")
        if cut_c > cut_b * (1.0 + cut_threshold):
            failures.append(
                f"cut regressed {_pct(cut_c, cut_b)} "
                f"(threshold +{cut_threshold * 100:.0f}%)"
            )
    if rb.get("feasible") is True and rc.get("feasible") is False:
        failures.append("feasibility regressed: base feasible, cand not")

    # -- total wall ------------------------------------------------------
    # gate on EXECUTE wall when both reports meter compile time (schema
    # v2): raw wall embeds XLA compile whose run-to-run jitter exceeds
    # 10% on small runs, so gating it false-positives on identical code;
    # subtracting each report's own compile_s compares what the solver
    # actually did (and makes injected raw-wall regressions MORE
    # visible, since the compile constant cancels)
    wb, wc = total_wall_s(base), total_wall_s(cand)
    if wb is not None and wc is not None:
        lines.append(f"wall: {wb:.3f}s -> {wc:.3f}s ({_pct(wc, wb)})")
        cb = base.get("compile", {}).get("totals", {}).get("compile_s")
        cc = cand.get("compile", {}).get("totals", {}).get("compile_s")
        if cb is not None and cc is not None:
            wb_x = max(wb - float(cb), 0.0)
            wc_x = max(wc - float(cc), 0.0)
            lines.append(
                f"wall minus compile: {wb_x:.3f}s -> {wc_x:.3f}s "
                f"({_pct(wc_x, wb_x)}) [gated]"
            )
        else:
            wb_x, wc_x = wb, wc
        if wc_x > wb_x * (1.0 + wall_threshold) and (wc_x - wb_x) > min_wall_s:
            failures.append(
                f"execute wall regressed {_pct(wc_x, wb_x)} "
                f"(threshold +{wall_threshold * 100:.0f}%, "
                f"floor {min_wall_s}s)"
            )

    # -- per-scope walls (informational) ---------------------------------
    sb = flatten_scopes(base.get("scope_tree", {}))
    sc = flatten_scopes(cand.get("scope_tree", {}))
    deltas = [
        (abs(sc[p] - sb[p]), p, sb[p], sc[p])
        for p in sorted(set(sb) & set(sc))
        if max(sb[p], sc[p]) >= min_wall_s and sc[p] != sb[p]
    ]
    for _, path, b, c in sorted(deltas, reverse=True)[:8]:
        lines.append(f"  scope {path}: {b:.3f}s -> {c:.3f}s ({_pct(c, b)})")
    only_b, only_c = set(sb) - set(sc), set(sc) - set(sb)
    if only_b:
        lines.append(f"  scopes only in base: {sorted(only_b)[:5]}")
    if only_c:
        lines.append(f"  scopes only in cand: {sorted(only_c)[:5]}")

    # -- compile split (schema v2; informational) ------------------------
    tb = base.get("compile", {}).get("totals")
    tc = cand.get("compile", {}).get("totals")
    if tb and tc:
        lines.append(
            f"compile: {tb.get('compile_s', 0.0):.3f}s "
            f"({tb.get('compiles', 0)}x) -> "
            f"{tc.get('compile_s', 0.0):.3f}s ({tc.get('compiles', 0)}x); "
            f"persistent cache {tb.get('persistent_cache_hits', 0)}/"
            f"{tb.get('persistent_cache_misses', 0)} -> "
            f"{tc.get('persistent_cache_hits', 0)}/"
            f"{tc.get('persistent_cache_misses', 0)} hit/miss"
        )

    # -- progress convergence (schema v2; informational) -----------------
    pairs = align_progress(base, cand)
    for b, c in pairs:
        key = _progress_key(b)
        label = f"{key[0]}@{key[1] or '(top)'}"
        if key[2] is not None:
            label += f" level={key[2]}"
        if key[3] is not None:
            label += f" round={key[3]}"
        ib, ic = b.get("iterations", 0), c.get("iterations", 0)
        msg = f"  progress {label}: iters {ib} -> {ic}"
        fb, fc = _final(b, "cut"), _final(c, "cut")
        if fb is not None and fc is not None:
            msg += f", final cut {fb:.0f} -> {fc:.0f} ({_pct(fc, fb)})"
        mb, mc = _final(b, "moved"), _final(c, "moved")
        if mb is not None and mc is not None:
            msg += f", final moved {mb:.0f} -> {mc:.0f}"
        if ib != ic or (fb, mb) != (fc, mc):
            lines.append(msg)
    nb = len(base.get("progress", []) or [])
    nc = len(cand.get("progress", []) or [])
    if nb or nc:
        lines.append(
            f"progress series: {nb} base / {nc} cand, {len(pairs)} aligned"
        )

    # -- serving (schema v4+; gated on served rate + cache hit rate) -----
    s_lines, s_failures = diff_serving(
        base, cand, hit_rate_threshold=hit_rate_threshold
    )
    lines.extend(s_lines)
    failures.extend(s_failures)

    # -- quality attribution (schema v7; informational) ------------------
    q_lines, q_failures = diff_quality(base, cand)
    lines.extend(q_lines)
    failures.extend(q_failures)

    # -- perf roofline totals (schema v5; informational) -----------------
    pb = (base.get("perf") or {}).get("totals") or {}
    pc = (cand.get("perf") or {}).get("totals") or {}
    if pb and pc:
        parts = [
            f"perf: bytes {pb.get('bytes', 0):.3g} -> "
            f"{pc.get('bytes', 0):.3g}"
        ]
        for key in ("hbm_util", "pad_waste"):
            vb, vc = pb.get(key), pc.get(key)
            if vb is not None and vc is not None:
                parts.append(f"{key} {vb} -> {vc}")
        lines.append(", ".join(parts))

    # -- comm volume (schema v12; informational) -------------------------
    # trace-time per-phase collective payloads: a composition change
    # that doubles halo traffic shows up here before it shows up in
    # wall (COMM_CAVEAT: traced bytes per device, not link-level)
    cb_ = base.get("comm") or {}
    cc_ = cand.get("comm") or {}
    phb = cb_.get("phases") or {}
    phc = cc_.get("phases") or {}
    if phb or phc:
        lines.append(
            f"comm bytes_total: {cb_.get('bytes_total', 0)} -> "
            f"{cc_.get('bytes_total', 0)}"
        )
        for phase in sorted(set(phb) | set(phc)):
            vb = (phb.get(phase) or {}).get("bytes_total", 0)
            vc = (phc.get(phase) or {}).get("bytes_total", 0)
            if vb != vc:
                lines.append(f"  comm {phase}: {vb} -> {vc} bytes")
    return lines, failures


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="kaminpar_tpu.telemetry.diff",
        description="diff two run reports; exit 1 on wall/cut regression",
    )
    ap.add_argument("base", help="baseline run report (--report-json)")
    ap.add_argument("cand", help="candidate run report")
    ap.add_argument(
        "--wall-threshold", type=float, default=DEFAULT_WALL_THRESHOLD,
        help="fractional total-wall regression tolerated (default 0.10)",
    )
    ap.add_argument(
        "--cut-threshold", type=float, default=DEFAULT_CUT_THRESHOLD,
        help="fractional edge-cut regression tolerated (default 0.10)",
    )
    ap.add_argument(
        "--min-wall-s", type=float, default=DEFAULT_MIN_WALL_S,
        help="absolute wall-delta floor below which the wall gate never "
        "fires (default 0.05 s)",
    )
    ap.add_argument(
        "--hit-rate-threshold", type=float,
        default=DEFAULT_HIT_RATE_THRESHOLD,
        help="absolute serving cache hit-rate drop tolerated before the "
        "serving gate fires (default 0.10; only applies when both "
        "reports ran in serve mode)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit the verdict as one JSON line instead of text",
    )
    ap.add_argument("--quiet", action="store_true", help="verdict only")
    args = ap.parse_args(argv)

    try:
        base = load_report(args.base)
        cand = load_report(args.cand)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    lines, failures = diff_reports(
        base, cand,
        wall_threshold=args.wall_threshold,
        cut_threshold=args.cut_threshold,
        min_wall_s=args.min_wall_s,
        hit_rate_threshold=args.hit_rate_threshold,
    )
    if args.json:
        print(json.dumps({
            "base": args.base,
            "cand": args.cand,
            "pass": not failures,
            "failures": failures,
            "detail": lines,
        }))
    else:
        if not args.quiet:
            for line in lines:
                print(line)
        for f in failures:
            print(f"REGRESSION: {f}", file=sys.stderr)
        print(f"DIFF {'FAIL' if failures else 'OK'} "
              f"({len(failures)} regression(s))")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
