"""Execution ledger: launch-honest rooflines, a host<->device transfer
ledger, and donation audits.

The perf observatory (telemetry/perf.py) captures XLA cost analysis once
per *backend compile*, so a scope that re-launches one compiled program
hundreds of times (every LP round, every level) under-counts bytes and
FLOPs by exactly its launch count — the utilization figures ROADMAP
item 2 gates on were lower bounds, not measurements.  This module is the
execution half of that observatory, three legs:

  * **launch ledger** — ``install()`` wraps the compiled-executable call
    boundary (``pxla.ExecuteReplicated.__call__``, the same
    dispatch-time host-side attribution contract as ``compile_account``
    and ``perf.install``) and counts executions per (scope path,
    executable).  jax's C++ pjit fastpath normally dispatches warm calls
    without touching Python, so while the ledger is armed the module
    also gates ``jax._src.pjit._get_fastpath_data`` to return ``None``:
    every dispatch then routes through the Python path where the wrapper
    can see it.  Tracing/compile caches are untouched (verified: launch
    counting adds zero recompiles); the only cost is Python dispatch
    overhead, paid exclusively while telemetry is on.  Per-launch costs
    join against the per-executable cost registry that
    ``perf._record_executable`` forwards here; a launch whose
    executable's cost was never captured (e.g. a persistent-cache warm
    start that skipped ``backend_compile``) is counted as *uncosted* and
    poisons the scope's ``honest`` stamp instead of silently
    under-reporting.  Distinct executables are distinct shape buckets
    (the jit cache keys on padded shapes — caching.bucket_key), so the
    per-executable launch counts are the per-bucket counts.
  * **transfer ledger** — ``transfer(direction, nbytes, kind)`` is the
    one hook every host-boundary chokepoint calls (device CSR upload,
    checkpoint spill/reload, chunkstore upload/pull, progress/stat
    pulls, dist gathers).  Aggregated per (scope, direction, kind) and
    rolled up per phase into the schema-v13 ``ledger`` report section;
    mirrored live into ``kmp_xfer_*`` fleet-observatory counters and a
    capped ``ledger-transfer`` event stream that chrome_trace renders as
    cumulative counter tracks.
  * **donation audit** — ``donation_begin(arrays)`` /
    ``donation_end(token)`` bracket a donated-buffer call (LP round
    carry, hierarchy level handoff) and verify the donated inputs were
    actually aliased: primary signal is the runtime ``is_deleted()``
    flag on each donated array (a donated buffer is invalidated by the
    runtime iff the aliasing was honored), cross-checked against the
    executable's ``input_output_alias`` metadata recorded at compile
    time, with a measured live-bytes-delta fallback when the flag is
    unavailable.  Reported as ``donation {requested, honored,
    bytes_saved}`` per scope.

Standing dormancy contract (pinned by tests/test_ledger.py): the kill
switch is ``KAMINPAR_TPU_LEDGER=0``; every hook is host-side (dispatch
boundaries, host pulls, compile results) so the traced jaxprs are
bitwise identical whether the ledger is on, off, or telemetry is
disabled entirely.  Disabled, every entry point is one bool check.

Arm telemetry BEFORE the first dispatch of the executables you want
counted: once a warm call has been served by the C++ fastpath cache
(ledger off at that moment), jax keeps dispatching that executable from
C++ and its launches stay invisible — the same cold-run methodology
bench.py already follows.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Tuple

ENV_VAR = "KAMINPAR_TPU_LEDGER"

#: Per-scope executable-name launch detail kept for triage; scopes and
#: transfer kinds are O(scope tree) / O(chokepoints), never per-launch.
MAX_EXECUTABLES_PER_SCOPE = 32
#: Cost registry bound: id(executable) -> cost.  Executables live as
#: long as the jit caches that own them, so id reuse is rare; a full
#: registry drops new entries (their launches then read as uncosted —
#: visible, not wrong).
MAX_EXECUTABLE_COSTS = 4096
#: Cap on ledger-transfer telemetry events (the chrome-trace counter
#: track); aggregation continues past the cap, only the event stream
#: stops growing.
MAX_TRANSFER_EVENTS = 512

_lock = threading.Lock()
_installed = False
# id(executable) -> {"flops","bytes","name","donated_params"}
_exe_costs: Dict[int, Dict[str, Any]] = {}
# dotted scope path -> {"launches","uncosted","bytes","flops",
#                       "executables": {name: count}}
_launches: Dict[str, Dict[str, Any]] = {}
# (dotted scope path, direction, kind) -> {"bytes","count"}
_transfers: Dict[Tuple[str, str, str], Dict[str, int]] = {}
# dotted scope path -> {"requested","honored","requested_bytes",
#                       "bytes_saved"}
_donation: Dict[str, Dict[str, int]] = {}
_transfer_events = 0
_xfer_totals = {"h2d": 0, "d2h": 0}


def enabled() -> bool:
    """True iff telemetry is on and KAMINPAR_TPU_LEDGER is not 0 — the
    one gate every hook checks before doing any work."""
    if os.environ.get(ENV_VAR, "") == "0":
        return False
    from . import enabled as _telemetry_enabled

    return _telemetry_enabled()


def reset() -> None:
    """Clear launch/transfer/donation state.  The executable cost
    registry survives: jit caches outlive a telemetry reset, and a warm
    executable whose compile predates the reset must still join."""
    global _transfer_events
    with _lock:
        _launches.clear()
        _transfers.clear()
        _donation.clear()
        _transfer_events = 0
        _xfer_totals["h2d"] = 0
        _xfer_totals["d2h"] = 0


# ---------------------------------------------------------------------------
# launch ledger
# ---------------------------------------------------------------------------


def install() -> None:
    """Wrap the compiled-executable call boundary (idempotent; the
    wrappers no-op while the ledger is disabled, so installation is
    free).  Best effort: a jax refactor that moves either entry point
    degrades to "launch counts unavailable", never an import error."""
    global _installed
    if _installed:
        return
    try:
        from jax._src.interpreters import pxla
    except Exception:
        return
    orig_call = getattr(pxla.ExecuteReplicated, "__call__", None)
    if orig_call is None or getattr(
        orig_call, "_kaminpar_ledger_wrapped", False
    ):
        _installed = True
        return

    def _wrapped_call(self, *args: Any, **kwargs: Any):
        try:
            if enabled():
                _record_launch(getattr(self, "xla_executable", None))
        except Exception:
            pass  # the ledger must never break a dispatch
        return orig_call(self, *args, **kwargs)

    _wrapped_call._kaminpar_ledger_wrapped = True  # type: ignore[attr-defined]
    pxla.ExecuteReplicated.__call__ = _wrapped_call

    # Warm pjit calls are dispatched from C++ and never reach the
    # Python wrapper above; returning None here keeps the fastpath
    # uncached so every dispatch stays countable while the ledger is
    # armed.  Disabled, the original fastpath is untouched.
    try:
        from jax._src import pjit as _pjit

        orig_fastpath = getattr(_pjit, "_get_fastpath_data", None)
        if orig_fastpath is not None and not getattr(
            orig_fastpath, "_kaminpar_ledger_wrapped", False
        ):
            def _gated_fastpath(*args: Any, **kwargs: Any):
                try:
                    if enabled():
                        return None
                except Exception:
                    pass
                return orig_fastpath(*args, **kwargs)

            _gated_fastpath._kaminpar_ledger_wrapped = True  # type: ignore[attr-defined]
            _pjit._get_fastpath_data = _gated_fastpath
    except Exception:
        pass
    _installed = True


def register_executable(exe: Any, flops: float, nbytes: float,
                        name: str = "") -> None:
    """Record one freshly compiled executable's cost so later launches
    can join it (called by perf._record_executable at the compile
    boundary).  Also parses the executable's input/output alias
    metadata — the compile-time half of the donation audit."""
    donated = _parse_donated_params(exe)
    with _lock:
        if len(_exe_costs) >= MAX_EXECUTABLE_COSTS:
            return
        _exe_costs[id(exe)] = {
            "flops": float(flops),
            "bytes": float(nbytes),
            "name": str(name),
            "donated_params": donated,
        }


def _parse_donated_params(exe: Any) -> int:
    """Count aliased parameters from the HloModule header's
    ``input_output_alias={...}`` map (empty/absent -> 0)."""
    try:
        text = exe.hlo_modules()[0].to_string()
        header = text[: text.index("\n")] if "\n" in text else text
        marker = "input_output_alias={"
        i = header.find(marker)
        if i < 0:
            return 0
        body = header[i + len(marker): header.index("}", i)]
        return body.count(":") or (1 if body.strip() else 0)
    except Exception:
        return 0


def _record_launch(exe: Any) -> None:
    from . import current_scope_path

    path = current_scope_path() or "(outside scopes)"
    key = id(exe)
    with _lock:
        cost = _exe_costs.get(key)
        e = _launches.setdefault(
            path,
            {"launches": 0, "uncosted": 0, "bytes": 0.0, "flops": 0.0,
             "executables": {}},
        )
        e["launches"] += 1
        if cost is None:
            e["uncosted"] += 1
            exe_name = "(uncosted)"
        else:
            e["bytes"] += cost["bytes"]
            e["flops"] += cost["flops"]
            exe_name = cost["name"] or "(unnamed)"
        names = e["executables"]
        if exe_name in names or len(names) < MAX_EXECUTABLES_PER_SCOPE:
            names[exe_name] = names.get(exe_name, 0) + 1
    try:
        from . import metrics

        metrics.inc(
            "kmp_launches_total",
            "compiled-executable launches recorded by the execution "
            "ledger",
            1,
        )
    except Exception:
        pass


def launch_totals() -> Dict[str, Dict[str, Any]]:
    """Per-scope launch aggregates for the perf.snapshot() roofline
    join: {path: {launches, uncosted, bytes, flops}}."""
    with _lock:
        return {
            path: {k: e[k] for k in ("launches", "uncosted", "bytes",
                                     "flops")}
            for path, e in _launches.items()
        }


# ---------------------------------------------------------------------------
# transfer ledger
# ---------------------------------------------------------------------------


def transfer(direction: str, nbytes: Any, kind: str = "") -> None:
    """Record one host<->device transfer at a boundary chokepoint.

    ``direction`` is ``"h2d"`` or ``"d2h"``; ``nbytes`` the payload
    size; ``kind`` a short chokepoint tag (``csr-upload``,
    ``checkpoint-spill``, ``stat-pull``, ...).  Host-side aggregation
    keyed by the open timer scope — call from the factored chokepoint
    helpers, never from inside a driver span block (tpulint R1's hook
    shape, pinned by tests/lint_fixtures/r1_ledger_*)."""
    if not enabled():
        return
    try:
        nb = int(nbytes)
    except (TypeError, ValueError):
        return
    if nb <= 0 or direction not in ("h2d", "d2h"):
        return
    from . import current_scope_path

    path = current_scope_path() or "(outside scopes)"
    global _transfer_events
    with _lock:
        e = _transfers.setdefault(
            (path, direction, kind or "-"), {"bytes": 0, "count": 0}
        )
        e["bytes"] += nb
        e["count"] += 1
        _xfer_totals[direction] += nb
        emit_event = _transfer_events < MAX_TRANSFER_EVENTS
        if emit_event:
            _transfer_events += 1
        h2d_total, d2h_total = _xfer_totals["h2d"], _xfer_totals["d2h"]
    try:
        from . import metrics

        metrics.inc(
            f"kmp_xfer_{direction}_bytes_total",
            "host<->device transfer bytes by direction and chokepoint "
            "kind (execution ledger)",
            nb, kind=kind or "-",
        )
        metrics.inc(
            f"kmp_xfer_{direction}_total",
            "host<->device transfers by direction and chokepoint kind "
            "(execution ledger)",
            1, kind=kind or "-",
        )
    except Exception:
        pass
    if emit_event:
        from . import event

        # cumulative totals ride each event so chrome_trace can render
        # a monotone counter track without re-aggregating
        event(
            "ledger-transfer", direction=direction, kind=kind or "-",
            bytes=nb, h2d_total=h2d_total, d2h_total=d2h_total,
        )


# ---------------------------------------------------------------------------
# donation audit
# ---------------------------------------------------------------------------


def donation_begin(arrays: Any, kind: str = "") -> Optional[dict]:
    """Open one donated-buffer audit: capture the donated inputs and
    their sizes BEFORE the donating call (the call rebinds the carry,
    so the caller's references are gone afterwards).  Returns an opaque
    token for donation_end, or None while the ledger is off."""
    if not enabled():
        return None
    arrs = list(arrays)
    sizes = []
    for a in arrs:
        try:
            sizes.append(int(a.nbytes))
        except Exception:
            sizes.append(0)
    from . import current_scope_path

    token: Dict[str, Any] = {
        "arrays": arrs,
        "sizes": sizes,
        "kind": kind,
        "path": current_scope_path() or "(outside scopes)",
        "live0": None,
    }
    try:
        from ..utils import heap_profiler

        token["live0"] = int(heap_profiler.live_device_bytes())
    except Exception:
        pass
    return token


def donation_end(token: Optional[dict]) -> Optional[dict]:
    """Close a donation audit after the donating call returned: a
    donated input whose buffer the runtime invalidated
    (``is_deleted()``) was aliased — the donation was honored and its
    bytes were saved.  Falls back to the live-bytes delta when the flag
    is unavailable.  Aggregates per scope; returns this audit's
    {requested, honored, bytes_saved} (None while off)."""
    if token is None:
        return None
    requested = len(token["arrays"])
    requested_bytes = sum(token["sizes"])
    honored = 0
    bytes_saved = 0
    flag_failed = False
    for arr, nb in zip(token["arrays"], token["sizes"]):
        try:
            if arr.is_deleted():
                honored += 1
                bytes_saved += nb
        except Exception:
            flag_failed = True
    if flag_failed and honored == 0 and token.get("live0") is not None:
        # fallback: if live device bytes did not grow by the donated
        # footprint, the buffers were reused (coarse — stamped as the
        # whole audit honored or not, never per array)
        try:
            from ..utils import heap_profiler

            grown = int(heap_profiler.live_device_bytes()) - token["live0"]
            if grown <= requested_bytes // 2:
                honored = requested
                bytes_saved = requested_bytes
        except Exception:
            pass
    path = token["path"]
    with _lock:
        e = _donation.setdefault(
            path,
            {"requested": 0, "honored": 0, "requested_bytes": 0,
             "bytes_saved": 0},
        )
        e["requested"] += requested
        e["honored"] += honored
        e["requested_bytes"] += requested_bytes
        e["bytes_saved"] += bytes_saved
    return {"requested": requested, "honored": honored,
            "bytes_saved": bytes_saved}


# ---------------------------------------------------------------------------
# supervised-worker marshal
# ---------------------------------------------------------------------------


def marshal_summary() -> Optional[dict]:
    """The worker-side half of the supervised marshal: a small,
    pickle/JSON-safe headline of this process's ledger (launch totals +
    transfer totals), shipped back on the worker's result reply.  None
    while the ledger is off."""
    if not enabled():
        return None
    with _lock:
        return {
            "launches": sum(e["launches"] for e in _launches.values()),
            "uncosted_launches": sum(
                e["uncosted"] for e in _launches.values()
            ),
            "h2d_bytes": int(_xfer_totals["h2d"]),
            "d2h_bytes": int(_xfer_totals["d2h"]),
        }


def absorb(summary: Optional[dict], kind: str = "worker") -> None:
    """The parent-side half: fold a worker's marshalled transfer totals
    into THIS process's ledger under the current scope (the serving
    layer calls this after a supervised request returns, so supervised
    runs keep their h2d/d2h accounting — the bytes moved in the worker
    on the request's behalf).  Launch counts are NOT absorbed: they
    cannot be joined with per-scope costs across the process boundary,
    and a fake uncosted entry would poison the parent's honest stamps
    for work the worker accounted honestly on its own."""
    if not summary or not enabled():
        return
    for direction in ("h2d", "d2h"):
        transfer(direction, summary.get(f"{direction}_bytes", 0),
                 kind=kind)


# ---------------------------------------------------------------------------
# snapshot: the run report's `ledger` section
# ---------------------------------------------------------------------------


def _phase_of(path: str) -> str:
    """Phase key for the per-phase transfer rollup: the first two
    dotted segments (``partitioning.coarsening``), matching the
    granularity bench.py's phase walls report at."""
    if not path or path == "(outside scopes)":
        return "(outside scopes)"
    return ".".join(path.split(".")[:2])


def snapshot() -> dict:
    """Assemble the schema-v13 ``ledger`` report section."""
    on = enabled()
    with _lock:
        launches = {
            p: {
                "launches": int(e["launches"]),
                "uncosted_launches": int(e["uncosted"]),
                "bytes": round(float(e["bytes"]), 1),
                "flops": round(float(e["flops"]), 1),
                "executables": dict(e["executables"]),
            }
            for p, e in _launches.items()
        }
        xfer_items = [(k, dict(e)) for k, e in _transfers.items()]
        donation = {p: dict(e) for p, e in _donation.items()}
        costed_exes = len(_exe_costs)

    rows: List[dict] = []
    by_phase: Dict[str, Dict[str, int]] = {}
    totals = {"h2d_bytes": 0, "d2h_bytes": 0, "h2d_count": 0,
              "d2h_count": 0}
    for (path, direction, kind), e in xfer_items:
        rows.append({
            "scope": path, "direction": direction, "kind": kind,
            "bytes": int(e["bytes"]), "count": int(e["count"]),
        })
        ph = by_phase.setdefault(
            _phase_of(path),
            {"h2d_bytes": 0, "d2h_bytes": 0, "h2d_count": 0,
             "d2h_count": 0},
        )
        ph[f"{direction}_bytes"] += int(e["bytes"])
        ph[f"{direction}_count"] += int(e["count"])
        totals[f"{direction}_bytes"] += int(e["bytes"])
        totals[f"{direction}_count"] += int(e["count"])
    rows.sort(key=lambda r: (-r["bytes"], r["scope"], r["kind"]))

    return {
        "enabled": on,
        "launches": launches,
        "totals": {
            "launches": sum(e["launches"] for e in launches.values()),
            "uncosted_launches": sum(
                e["uncosted_launches"] for e in launches.values()
            ),
            "costed_executables": int(costed_exes),
        },
        "transfers": {
            "rows": rows,
            "by_phase": by_phase,
            "totals": totals,
        },
        "donation": donation,
    }
