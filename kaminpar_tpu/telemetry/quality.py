"""Quality observatory: hierarchical cut-loss attribution, per-level
coarsening-quality metrics, and refinement-efficacy verdicts.

PR 7's perf observatory answers "where do the seconds and bytes go";
this layer answers ROADMAP item 1's prior question: **which hierarchy
level is responsible for the lost cut** — is the damage locked in by
coarsening (clusters that internalize too little edge weight, the
failure mode size-constrained clustering addresses, arXiv 1402.3281) or
left on the table by refinement that stalls at coarse levels.  Three
concerns, one module:

  * **cut-loss attribution** — during uncoarsening the drivers record
    the projected-in cut and the post-refinement cut per level, and the
    coarsener records each level's fine->coarse cluster map.  At the
    end of the run `finalize_*` pushes the FINAL fine partition back up
    through the recorded cluster maps (weighted-majority block per
    cluster) and evaluates the **coarsening floor** per level: the cut
    of the best cluster-constant approximation of the final partition —
    i.e. the best cut level L could have reached given the contraction
    decisions.  Each level's total gap vs the level-0 lower bound (the
    final cut itself) then splits EXACTLY into

        coarsening_locked(L) = floor_cut(L)    - final_cut
        refinement_left(L)   = refined_cut(L)  - floor_cut(L)
        gap(L)               = refined_cut(L)  - final_cut
                             = coarsening_locked(L) + refinement_left(L)

    A level with a high locked fraction had its structure destroyed by
    coarsening before refinement ever saw it; a high left fraction
    means the level could express a much better partition and the
    refiners stalled (tests/test_quality.py pins the sum invariant and
    the floor math against a brute-force recompute).
  * **coarsening-quality metrics** — per contraction: internalized
    edge-weight ratio, cluster-size distribution vs the size constraint
    (max/mean/singleton fraction), and weight skew / cap utilization,
    from one small device reduction per level (ops/metrics.
    coarsening_stats) pulled host-side between launches.
  * **refinement-efficacy verdicts** — at snapshot time the PR-4
    progress series (LP/Jet/FM/balancer, tagged with the uncoarsening
    level) are joined into per-level ``converged | stalled |
    budget-capped`` verdicts with realized-vs-remaining gain mass,
    plus any deadline `refine-skipped` events.

Instrumentation contract (pinned by tests/test_quality.py's
jaxpr-equality test): every hook is host-side driver code between
device launches — cluster-map pulls at uncoarsening pops, cut
evaluations through the separately-jitted ``ops.metrics.edge_cut_jit``,
stats through ``ops.metrics.coarsening_stats`` — NEVER inside the
LP/Jet/contraction programs, so their jaxprs are bitwise-identical
whether the layer is on, off (``KAMINPAR_TPU_QUALITY=0``), or telemetry
is disabled entirely.  Host readbacks live in this module's helpers,
outside the drivers' timer-span blocks (the tpulint R1 hook shape,
tests/lint_fixtures/r1_quality_*.py).

Caveats (stamped on the section): the floor is relative to the RUN'S
OWN final partition, not a true optimum — it bounds what refinement at
a level could have recovered *of the result actually reached*; the
level-0 row is the identity push (floor == final cut, locked == 0).
The surface: run-report ``quality`` section (schema v7), the triage CLI
``python -m kaminpar_tpu.telemetry.quality REPORT [--diff BASE]``,
Chrome-trace counter tracks, and the BENCH keys
``coarsening_locked_frac`` / ``refinement_left_frac``.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, List, Optional

import numpy as np

ENV_VAR = "KAMINPAR_TPU_QUALITY"

CAVEAT = (
    "floors are measured against the run's own final partition pushed "
    "back up through the recorded cluster maps (weighted-majority block "
    "per cluster) — they bound what refinement at a level could have "
    "recovered of the result actually reached, not a true optimum; "
    "disable with KAMINPAR_TPU_QUALITY=0"
)

#: Keys a level row carries when the attribution pass completed for it.
ATTRIBUTION_KEYS = ("floor_cut", "refined_cut", "coarsening_locked",
                    "refinement_left", "gap")

_lock = threading.Lock()
#: the last finalized (or partially recorded) hierarchy's section —
#: report.py snapshots it; "last wins" so a v-cycle's final cycle (and
#: an outer run after its nested IP runs) owns the report section.
#: Stored with the hierarchy's id so the verdict join only picks up
#: progress series tagged by THIS hierarchy's refiners.
_last: Optional[dict] = None
_last_hid: Optional[int] = None
_next_hid = 0

_tls = threading.local()  # .stack: list of _Hierarchy (nesting-safe)


def enabled() -> bool:
    """True iff telemetry is on and KAMINPAR_TPU_QUALITY is not 0 — the
    one gate every hook checks before doing any work."""
    if os.environ.get(ENV_VAR, "") == "0":
        return False
    from . import enabled as _telemetry_enabled

    return _telemetry_enabled()


def reset() -> None:
    """Clear the module state (called by telemetry.reset at run start);
    a stack left behind by an exceptional unwind is dropped too."""
    global _last, _last_hid
    with _lock:
        _last = None
        _last_hid = None
    _tls.stack = []


def _stack() -> List["_Hierarchy"]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _top() -> Optional["_Hierarchy"]:
    stack = _stack()
    return stack[-1] if stack else None


def current_id() -> Optional[int]:
    """The active hierarchy's id (None outside a recording scope).  The
    refiner tags its progress series and refine-skipped events with it,
    so the verdict join can tell THIS hierarchy's series apart from a
    nested IP run's or an earlier v-cycle's — they share one telemetry
    stream and one level numbering."""
    h = _top()
    return h.hid if h is not None else None


class _Hierarchy:
    """One multilevel hierarchy's recorded state (per driver run or
    v-cycle; nesting-safe via the thread-local stack)."""

    def __init__(self, scheme: str):
        global _next_hid
        with _lock:
            _next_hid += 1
            self.hid = _next_hid
        self.scheme = scheme
        # contraction level L (>= 1) -> i32[fine_n(G_{L-1})] cluster map
        # into G_L's coarse ids (host copies, recorded at uncoarsen pops)
        self.cmaps: Dict[int, np.ndarray] = {}
        # level (graph index; 0 = input) -> recorded per-level fields
        self.levels: Dict[int, Dict[str, Any]] = {}
        self.final_cut: Optional[int] = None
        self.finalized = False


# ---------------------------------------------------------------------------
# recording hooks (drivers + coarsener; all no-ops while disabled)
# ---------------------------------------------------------------------------


def begin(scheme: str) -> Optional[_Hierarchy]:
    """Open a hierarchy recording scope.  Returns None (and records
    nothing) while the layer is disabled; `end()` accepts either."""
    if not enabled():
        return None
    h = _Hierarchy(scheme)
    _stack().append(h)
    return h


def end(handle: Optional[_Hierarchy]) -> None:
    """Close a hierarchy scope (always call from a finally).  A
    hierarchy that recorded data but never finalized (interrupted run)
    still publishes its partial section — cuts and coarsening stats
    without floors."""
    if handle is None:
        return
    stack = _stack()
    if handle in stack:
        # drop this handle and anything a crashed nested run left above
        del stack[stack.index(handle):]
    if not handle.finalized and (handle.levels or handle.cmaps):
        _publish(handle)


def _level_entry(h: _Hierarchy, level: int) -> Dict[str, Any]:
    return h.levels.setdefault(int(level), {"level": int(level)})


def note_cmap(level: int, cmap, fine_n: int) -> None:
    """Record contraction `level`'s fine->coarse cluster map (the map
    INTO graph G_level); one host pull of fine_n ints, at the
    uncoarsening pop where the map is already in hand (or already
    host-side for a spilled level)."""
    h = _top()
    if h is None:
        return
    h.cmaps[int(level)] = np.asarray(cmap)[: int(fine_n)].astype(np.int64)


def note_contraction(
    level: int,
    fine_graph,
    coarse,
    fine_n: int,
    coarse_n: int,
    coarse_m: int,
    max_cluster_weight,
    total_node_weight: int,
) -> None:
    """Record one contraction's coarsening-quality metrics (`level` is
    the coarse graph's index).  One small device reduction
    (ops/metrics.coarsening_stats) pulled host-side between launches —
    the existing kernels' jaxprs are untouched."""
    h = _top()
    if h is None:
        return
    from ..ops import metrics

    fine_ew, coarse_ew, max_size, singletons, max_w = (
        int(x) for x in metrics.coarsening_stats(
            fine_graph, coarse.graph, coarse.cmap
        )
    )
    _note_coarsening(
        h, level, fine_n, coarse_n, coarse_m, fine_ew, coarse_ew,
        max_size, singletons, max_w, int(max_cluster_weight),
        total_node_weight,
    )


def note_contraction_host(
    level: int,
    coarse_host,
    cmap,
    fine_n: int,
    max_cluster_weight,
    total_node_weight: int,
    fine_edge_weight: Optional[int] = None,
) -> None:
    """Host-CSR twin of :func:`note_contraction` (the dist driver keeps
    its hierarchy host-side).  `fine_edge_weight` may be None when the
    fine level is still compressed — the internalized ratio is then
    omitted rather than forcing a decode."""
    h = _top()
    if h is None:
        return
    cm = np.asarray(cmap)[: int(fine_n)]
    coarse_n = max(int(coarse_host.n), 1)
    sizes = np.bincount(cm, minlength=coarse_n)
    nw = coarse_host.node_weight_array()
    _note_coarsening(
        h, level, fine_n, coarse_n, int(coarse_host.m),
        int(fine_edge_weight) if fine_edge_weight else 0,
        int(coarse_host.edge_weight_array().sum())
        if fine_edge_weight else 0,
        int(sizes.max(initial=0)), int((sizes == 1).sum()),
        int(nw.max(initial=0)), int(max_cluster_weight),
        total_node_weight,
    )


def _note_coarsening(
    h: _Hierarchy, level: int, fine_n: int, coarse_n: int, coarse_m: int,
    fine_ew: int, coarse_ew: int, max_size: int, singletons: int,
    max_w: int, mcw: int, total_node_weight: int,
) -> None:
    coarse_n = max(int(coarse_n), 1)
    mean_w = max(total_node_weight, 1) / coarse_n
    stats = {
        # fraction of the fine level's edge weight the clustering
        # internalized (1 - coarse/fine; both sums count each
        # undirected edge twice, so the ratio is exact)
        "internal_ew_ratio": (
            round(1.0 - coarse_ew / fine_ew, 4) if fine_ew > 0 else None
        ),
        "max_cluster_size": max_size,
        "mean_cluster_size": round(int(fine_n) / coarse_n, 2),
        "singleton_frac": round(singletons / coarse_n, 4),
        "max_cluster_weight": max_w,
        "weight_skew": round(max_w / max(mean_w, 1e-9), 2),
        "cap_utilization": round(max_w / max(mcw, 1), 4),
        "mcw": mcw,
    }
    entry = _level_entry(h, level)
    entry["fine_n"] = int(fine_n)
    entry["coarse_n"] = int(coarse_n)
    entry["coarse_m"] = int(coarse_m)
    entry["coarsening"] = stats
    from . import event

    event("coarsening-quality", level=int(level), **stats)


def _cut_device(graph, partition) -> int:
    from ..ops import metrics

    return int(metrics.edge_cut_jit(graph, partition))


def note_projected(level: int, graph=None, partition=None,
                   cut: Optional[int] = None,
                   k: Optional[int] = None) -> None:
    """Record the projected-in cut at `level` (right after projecting
    the coarser partition up, before any refinement there).  Pass a
    precomputed `cut` (the dist driver) or a device graph+partition;
    `k` is the block count the cut was measured at (deep mode doubles k
    during uncoarsening, so coarse-level cuts live at a smaller k)."""
    h = _top()
    if h is None:
        return
    if cut is None:
        cut = _cut_device(graph, partition)
    entry = _level_entry(h, level)
    entry["projected_cut"] = int(cut)
    if k is not None:
        entry["projected_k"] = int(k)


def note_refined(level: int, graph=None, partition=None,
                 cut: Optional[int] = None, k: Optional[int] = None,
                 spans=None, input_k: Optional[int] = None) -> None:
    """Record the post-refinement cut at `level` (after the level's
    refinement — and, in deep mode, its k-doubling extensions).

    `k` is the block count the level ran at.  When it differs from the
    final k, the driver's span bookkeeping (`spans` + `input_k`) yields
    a final-block -> level-block map that lets finalize measure this
    level's lower bound at the level's OWN k — the final partition
    mapped down in k — so the locked/left split stays coherent across
    deep mode's k-doubling.  The map is built HERE, after the disabled
    early-return, so dormant runs do no span work."""
    h = _top()
    if h is None:
        return
    if cut is None:
        cut = _cut_device(graph, partition)
    entry = _level_entry(h, level)
    entry["refined_cut"] = int(cut)
    if k is not None:
        entry["k_at_level"] = int(k)
    if spans is not None and input_k:
        bm = block_map_from_spans(spans, input_k)
        if bm is not None:
            entry["_block_map"] = bm


# ---------------------------------------------------------------------------
# finalize: the coarsening floors + per-level attribution
# ---------------------------------------------------------------------------


def block_map_from_spans(spans, input_k: int) -> Optional[np.ndarray]:
    """final block -> current block from a driver's span bookkeeping
    (one shared implementation for the shm deep and dist drivers —
    this mapping is what keeps small-k levels' bounds coherent under
    k-doubling).  Accepts _BlockSpan-like objects (`.first`/`.count`)
    or (first, count) tuples; None when the level already runs at the
    final k."""
    if len(spans) == int(input_k):
        return None
    bm = np.zeros(int(input_k), dtype=np.int32)
    for b, span in enumerate(spans):
        first, count = (
            (span.first, span.count) if hasattr(span, "first") else span
        )
        bm[first: first + count] = b
    return bm


def weighted_majority(phi: np.ndarray, part: np.ndarray,
                      node_w: np.ndarray, coarse_n: int) -> np.ndarray:
    """Per-cluster weighted-majority block: Q[c] = the block holding the
    most node weight among fine nodes with phi == c (ties broken toward
    the smaller block id; clusters with no nodes get block 0).  Pure
    numpy, sort-based — no (coarse_n x k) dense table, so huge-k runs
    stay bounded."""
    phi = np.asarray(phi, dtype=np.int64)
    part = np.asarray(part, dtype=np.int64)
    w = np.asarray(node_w, dtype=np.int64)
    k = int(part.max(initial=0)) + 1
    key = phi * k + part
    order = np.argsort(key, kind="stable")
    sk = key[order]
    sw = w[order]
    if sk.size == 0:
        return np.zeros(coarse_n, dtype=np.int32)
    starts = np.flatnonzero(np.concatenate([[True], sk[1:] != sk[:-1]]))
    sums = np.add.reduceat(sw, starts)
    uk = sk[starts]
    cluster = uk // k
    block = uk % k
    # per-cluster argmax with smallest-block tie-break: sort by
    # (cluster, -weight, block) and keep each cluster's first row
    sel = np.lexsort((block, -sums, cluster))
    cl = cluster[sel]
    first = np.concatenate([[True], cl[1:] != cl[:-1]])
    out = np.zeros(coarse_n, dtype=np.int32)
    out[cl[first]] = block[sel][first].astype(np.int32)
    return out


def _finalize(h: _Hierarchy, part: np.ndarray, node_w: np.ndarray,
              cut_of: Callable[[np.ndarray], int]) -> None:
    """Compute every level's coarsening floor by pushing the final
    partition up through the recorded cluster maps, then split each
    level's gap into locked vs left (module docstring identity).

    Each level's lower bound is the final partition mapped to the
    level's OWN k (identity when the level ran at the final k; via the
    recorded span block-map under deep's k-doubling), so the identity

        gap(L) = refined_cut(L) - bound_cut(L)
               = coarsening_locked(L) + refinement_left(L)

    holds exactly at every level regardless of where the k-doubling
    schedule stood when the level refined."""
    final_cut = cut_of(part.astype(np.int32))
    h.final_cut = int(final_cut)
    final_k = int(part.max(initial=0)) + 1
    # level 0 is the identity push: floor == bound == final cut,
    # locked == 0 — "the level-0 lower bound".  Its left/gap only make
    # sense when the recorded cut was measured at the final k (the dist
    # tiny-graph fallback re-partitions at full k AFTER the level-0
    # note, leaving a stale smaller-k cut behind).
    ent0 = _level_entry(h, 0)
    ent0["floor_cut"] = int(final_cut)
    ent0["bound_cut"] = int(final_cut)
    ent0["coarsening_locked"] = 0
    k0 = ent0.get("k_at_level")
    if "refined_cut" in ent0 and (k0 is None or k0 >= final_k):
        ent0["refinement_left"] = int(ent0["refined_cut"]) - int(final_cut)
        ent0["gap"] = ent0["refinement_left"]
    from . import event

    # the final partition mapped down in k, memoized per distinct
    # block-map (deep runs share one map across its small-k levels)
    bound_cache: Dict[int, int] = {}
    phi = np.arange(part.shape[0], dtype=np.int64)
    for level in sorted(h.cmaps):
        cmap = h.cmaps[level]
        if phi.size and int(phi.max()) >= cmap.shape[0]:
            # inconsistent recording (a level restored outside this
            # hierarchy's scope) — stop composing rather than mis-index
            break
        phi = cmap[phi]
        entry = _level_entry(h, level)
        bm = entry.pop("_block_map", None)
        if bm is None:
            base_part = part
            bound = int(final_cut)
        else:
            base_part = bm[np.clip(part, 0, bm.shape[0] - 1)]
            key = hash(bm.tobytes())
            if key not in bound_cache:
                bound_cache[key] = cut_of(base_part.astype(np.int32))
            bound = bound_cache[key]
        coarse_n = int(phi.max(initial=-1)) + 1
        q = weighted_majority(phi, base_part, node_w, max(coarse_n, 1))
        floor = cut_of(q[phi].astype(np.int32))
        entry["floor_cut"] = int(floor)
        entry["bound_cut"] = int(bound)
        entry["coarsening_locked"] = int(floor) - int(bound)
        if "refined_cut" in entry:
            entry["refinement_left"] = int(entry["refined_cut"]) - int(floor)
            entry["gap"] = int(entry["refined_cut"]) - int(bound)
        event(
            "quality-level",
            level=int(level),
            floor_cut=int(floor),
            bound_cut=int(bound),
            projected_cut=entry.get("projected_cut"),
            refined_cut=entry.get("refined_cut"),
            coarsening_locked=entry.get("coarsening_locked"),
            refinement_left=entry.get("refinement_left"),
            k_at_level=entry.get("k_at_level"),
        )
    h.finalized = True
    _publish(h)


def finalize_device(handle: Optional[_Hierarchy], dgraph, partition,
                    n: int) -> None:
    """Finalize against a device input graph: floors are evaluated by
    uploading each pushed partition into the input pad bucket and
    running the separately-jitted edge-cut reduction (one executable,
    reused per level)."""
    if handle is None or not enabled():
        return
    import jax.numpy as jnp

    n = int(n)
    part = np.asarray(partition)[:n]
    node_w = np.asarray(dgraph.node_w)[:n]
    n_pad = dgraph.n_pad

    def cut_of(p_real: np.ndarray) -> int:
        full = np.zeros(n_pad, dtype=np.int32)
        full[:n] = p_real
        return _cut_device(dgraph, jnp.asarray(full))

    _finalize(handle, part, node_w, cut_of)


def finalize_host(handle: Optional[_Hierarchy], host_graph,
                  partition) -> None:
    """Finalize against a host CSR (the dist driver and tests): floors
    are plain numpy cut sweeps over the input adjacency."""
    if handle is None or not enabled():
        return
    part = np.asarray(partition)[: host_graph.n]
    node_w = host_graph.node_weight_array()
    src = host_graph.edge_sources()
    adj = host_graph.adjncy
    ew = host_graph.edge_weight_array()

    def cut_of(p_real: np.ndarray) -> int:
        return int(ew[p_real[src] != p_real[adj]].sum() // 2)

    _finalize(handle, part, node_w, cut_of)


def _publish(h: _Hierarchy) -> None:
    global _last, _last_hid
    section = _assemble(h)
    with _lock:
        _last = section
        _last_hid = h.hid


def _assemble(h: _Hierarchy) -> dict:
    levels = [
        {key: v for key, v in h.levels[lv].items()
         if not key.startswith("_")}
        for lv in sorted(h.levels)
    ]
    attributed = [
        row for row in levels
        if row.get("gap") is not None and row["level"] > 0
    ]
    gap_mass = sum(int(row["gap"]) for row in attributed)
    locked_mass = sum(int(row["coarsening_locked"]) for row in attributed)
    left_mass = sum(int(row["refinement_left"]) for row in attributed)
    # headline fractions over the POSITIVE components: a level whose
    # floor undercuts its bound (majority rounding traded balance for
    # cut) carries negative locked mass — real, kept in the raw masses
    # and per-level rows, but the two headline fractions stay in [0, 1]
    # and sum to 1 so bench_trend can plot them round-over-round
    locked_pos = sum(
        max(int(row["coarsening_locked"]), 0) for row in attributed
    )
    left_pos = sum(
        max(int(row["refinement_left"]), 0) for row in attributed
    )
    pos_mass = locked_pos + left_pos
    worst = max(attributed, key=lambda r: r["gap"], default=None)
    totals: Dict[str, Any] = {
        "attribution_rows": len(attributed),
        "gap_mass": gap_mass,
        "locked_mass": locked_mass,
        "left_mass": left_mass,
        "coarsening_locked_frac": (
            round(locked_pos / pos_mass, 4) if pos_mass > 0 else None
        ),
        "refinement_left_frac": (
            round(left_pos / pos_mass, 4) if pos_mass > 0 else None
        ),
        "worst_level": worst["level"] if worst is not None else None,
    }
    return {
        "enabled": True,
        "caveat": CAVEAT,
        "scheme": h.scheme,
        "finalized": h.finalized,
        "final_cut": h.final_cut,
        "levels": levels,
        "totals": totals,
    }


# ---------------------------------------------------------------------------
# refinement-efficacy verdicts (joined from the PR-4 progress series)
# ---------------------------------------------------------------------------


def classify_series(series: Dict[str, list]) -> Dict[str, Any]:
    """One progress series -> {verdict, realized, remaining}.

    ``converged``    — the loop self-terminated with nothing left to do
                       (moved reached 0 / the last FM pass gained <= 0).
    ``budget-capped`` — the loop was still making progress when its
                       iteration budget (or a deadline) stopped it.
    ``stalled``      — movement without cut progress: the loop ended
                       with nodes still wanting to move but the tail of
                       the series gained nothing.

    Gain mass: `realized` is the improvement the series achieved (cut
    delta for Jet, committed gain for FM, total moves for LP/balancer);
    `remaining` is the final iteration's residual movement/gain — the
    mass a deeper schedule could still chase.  Deterministic, pinned by
    tests/test_quality.py's unit table."""
    moved = [int(v) for v in (series.get("moved") or [])]
    cut = [int(v) for v in (series.get("cut") or [])]
    gain = [int(v) for v in (series.get("gain") or [])]
    if cut:
        realized = max(cut[0] - min(cut), 0)
        remaining = moved[-1] if moved else 0
        if moved and moved[-1] == 0:
            verdict = "converged"
        else:
            tail_n = max(1, len(cut) // 3)
            head_min = min(cut[:-tail_n]) if len(cut) > tail_n else cut[0]
            tail_gain = max(head_min - min(cut[-tail_n:]), 0)
            verdict = "budget-capped" if tail_gain > 0 else "stalled"
        return {"verdict": verdict, "realized": realized,
                "remaining": remaining}
    if gain:  # FM: per-pass committed gain (terminates on gain <= 0)
        realized = sum(g for g in gain if g > 0)
        remaining = max(gain[-1], 0)
        verdict = "converged" if gain[-1] <= 0 else "budget-capped"
        return {"verdict": verdict, "realized": realized,
                "remaining": remaining}
    if moved:
        realized = sum(moved)
        remaining = moved[-1]
        if moved[-1] == 0:
            verdict = "converged"
        elif moved[-1] >= 0.25 * max(moved):
            # exited while still moving in bulk: the iteration budget
            # (not convergence) ended the loop
            verdict = "budget-capped"
        else:
            verdict = "stalled"
        return {"verdict": verdict, "realized": realized,
                "remaining": remaining}
    return {"verdict": "converged", "realized": 0, "remaining": 0}


#: level verdict = the worst of its series verdicts, in this order
_VERDICT_SEVERITY = {"converged": 0, "stalled": 1, "budget-capped": 2}


def _verdicts_by_level(hid: Optional[int]) -> Dict[int, List[dict]]:
    """Refinement-side progress series grouped by uncoarsening level,
    each classified; plus deadline `refine-skipped` events (a skipped
    refiner is budget-capped by definition).

    Series carrying a `quality_hierarchy` tag (the shm RefinerPipeline
    stamps `current_id()`) join only when it matches the published
    hierarchy's id — nested IP runs and earlier v-cycle cycles share
    the telemetry stream AND the level numbering, so an id mismatch
    would flip a converged level to budget-capped with someone else's
    series.  Untagged series (the dist refiners) join unconditionally."""
    from . import events as _events
    from . import progress_series as _progress_series

    out: Dict[int, List[dict]] = {}
    for entry in _progress_series():
        attrs = entry.attrs or {}
        if attrs.get("phase") == "cluster":
            continue  # coarsening LP: not a refinement series
        level = attrs.get("level")
        if level is None:
            continue
        tag = attrs.get("quality_hierarchy")
        if tag is not None and hid is not None and tag != hid:
            continue
        v = classify_series(entry.series)
        v["kind"] = entry.kind
        if attrs.get("round") is not None:
            v["round"] = attrs["round"]
        out.setdefault(int(level), []).append(v)
    for e in _events("refine-skipped"):
        level = e.attrs.get("level")
        if level is None:
            continue
        tag = e.attrs.get("quality_hierarchy")
        if tag is not None and hid is not None and tag != hid:
            continue
        out.setdefault(int(level), []).append({
            "verdict": "budget-capped",
            "kind": e.attrs.get("algorithm", "refiner"),
            "realized": 0,
            "remaining": None,
            "skipped": True,
        })
    return out


def level_verdict(verdicts: List[dict]) -> Optional[str]:
    if not verdicts:
        return None
    return max(
        (v["verdict"] for v in verdicts),
        key=lambda s: _VERDICT_SEVERITY.get(s, 0),
    )


# ---------------------------------------------------------------------------
# snapshot: the run report's `quality` section
# ---------------------------------------------------------------------------


def snapshot() -> dict:
    """The report section: the last published hierarchy with the
    refinement-efficacy verdicts joined in (verdicts come from the live
    progress stream, so they are computed at report-build time)."""
    with _lock:
        section = None if _last is None else dict(_last)
        hid = _last_hid
    if section is None:
        return {"enabled": False}
    verdicts = _verdicts_by_level(hid)
    levels = []
    for row in section["levels"]:
        row = dict(row)
        vs = verdicts.get(int(row["level"]))
        if vs:
            row["verdicts"] = vs
            row["verdict"] = level_verdict(vs)
        levels.append(row)
    section["levels"] = levels
    return section


def headline() -> Optional[str]:
    """One-line CLI summary (None when nothing was recorded) — the
    QUALITY line both CLIs print next to RESULT."""
    section = snapshot()
    if not section.get("enabled"):
        return None
    totals = section.get("totals") or {}
    if not totals.get("attribution_rows"):
        return None
    parts = [
        f"levels={totals['attribution_rows']}",
        f"gap_mass={totals.get('gap_mass')}",
        f"coarsening_locked_frac={totals.get('coarsening_locked_frac')}",
        f"refinement_left_frac={totals.get('refinement_left_frac')}",
    ]
    if totals.get("worst_level") is not None:
        parts.append(f"worst=level{totals['worst_level']}")
    return "QUALITY " + " ".join(parts)


def rank_rollup() -> List[dict]:
    """Per-process attribution headline ([{rank, gap_mass, locked_mass,
    left_mass}]) — collective on multi-host runs (allgather, same
    contract as perf.rank_memory_rollup); the dist driver stamps it
    into the report (`quality.ranks`)."""
    with _lock:
        section = _last
    totals = (section or {}).get("totals") or {}
    local = [
        int(totals.get("gap_mass") or 0),
        int(totals.get("locked_mass") or 0),
        int(totals.get("left_mass") or 0),
    ]
    try:
        from ..utils.platform import process_count, process_index

        nproc = process_count()
        rank = process_index()
    except Exception:
        nproc, rank = 1, 0
    rows = [{"rank": int(rank), "gap_mass": local[0],
             "locked_mass": local[1], "left_mass": local[2]}]
    if nproc <= 1:
        return rows
    from jax.experimental import multihost_utils

    gathered = np.asarray(
        multihost_utils.process_allgather(
            np.asarray(local, dtype=np.int64)
        )
    ).reshape(nproc, 3)
    return [
        {"rank": p, "gap_mass": int(gathered[p][0]),
         "locked_mass": int(gathered[p][1]),
         "left_mass": int(gathered[p][2])}
        for p in range(nproc)
    ]


# ---------------------------------------------------------------------------
# triage CLI: python -m kaminpar_tpu.telemetry.quality REPORT [--diff BASE]
# ---------------------------------------------------------------------------


def attribution_rows(report: dict) -> List[dict]:
    """Level rows carrying a complete attribution split (floor +
    refined + the locked/left components)."""
    section = report.get("quality") or {}
    return [
        row for row in section.get("levels") or []
        if all(row.get(key) is not None for key in ATTRIBUTION_KEYS)
        and row.get("level", 0) > 0
    ]


# one table renderer per package: telemetry/top.py owns it
from .top import _fmt, _table  # noqa: E402


def render_report(report: dict, top_n: int = 16) -> List[str]:
    """Levels ranked by cut responsibility (gap vs the level-0 bound),
    with the coarsening stats cross-reference next to each verdict —
    the docs/performance.md quality-triage workflow in one table."""
    lines: List[str] = []
    section = report.get("quality") or {}
    if not section.get("enabled"):
        lines.append(
            "no quality section (schema < 7, KAMINPAR_TPU_QUALITY=0, or "
            "the run recorded no hierarchy)"
        )
        return lines
    totals = section.get("totals") or {}
    lines.append(
        f"scheme={section.get('scheme', '?')} "
        f"final_cut={_fmt(section.get('final_cut'))} "
        f"gap_mass={_fmt(totals.get('gap_mass'))} "
        f"coarsening_locked_frac={_fmt(totals.get('coarsening_locked_frac'))} "
        f"refinement_left_frac={_fmt(totals.get('refinement_left_frac'))}"
    )
    if not section.get("finalized", True):
        lines.append("(hierarchy not finalized — interrupted run; floors "
                     "may be missing)")
    rows = attribution_rows(report)
    if rows:
        ranked = sorted(rows, key=lambda r: -int(r["gap"]))[:top_n]
        lines.append("")
        lines.append(
            "levels by cut responsibility (gap = locked + left vs the "
            "level-0 bound):"
        )
        table_rows = []
        for r in ranked:
            gap = int(r["gap"])
            locked = int(r["coarsening_locked"])
            stats = r.get("coarsening") or {}
            table_rows.append([
                r["level"], r.get("coarse_n"), r.get("k_at_level"),
                gap, locked, int(r["refinement_left"]),
                round(locked / gap, 3) if gap > 0 else None,
                r.get("projected_cut"), r.get("refined_cut"),
                r.get("floor_cut"), r.get("bound_cut"),
                r.get("verdict"),
                stats.get("internal_ew_ratio"),
                stats.get("singleton_frac"),
            ])
        lines.extend(_table(
            ["level", "n", "k", "gap", "locked", "left", "locked%",
             "projected", "refined", "floor", "bound", "verdict",
             "int_ew", "singleton"],
            table_rows,
        ))
        worst = ranked[0]
        if int(worst["gap"]) > 0:
            # clamped share: with a negative refinement_left component
            # the raw locked/gap ratio exceeds 1 (the headline totals
            # clamp for the same reason) — print a [0, 1] share
            share = max(
                0.0, min(1.0, int(worst["coarsening_locked"])
                         / int(worst["gap"]))
            )
            blame = (
                "coarsening (re-cluster: raise internal_ew_ratio, check "
                "the size constraint)"
                if share >= 0.5
                else "refinement (deepen the schedule at this level)"
            )
            lines.append(
                f"worst: level {worst['level']} — "
                f"{_fmt(round(share, 3))} "
                f"of its gap is locked by coarsening; aim at {blame}"
            )
        else:
            lines.append(
                "no positive gap mass: every level's refined cut sits at "
                "or below its bound (a NEGATIVE gap at a small-k level "
                "means the k-doubling extensions below it leaked quality "
                "— the signed rows above are the signal)"
            )
    else:
        lines.append("")
        lines.append("no attribution rows (run interrupted before "
                     "finalize, or no coarsening levels)")
    # verdict-only rows (level 0 + levels without floors) still matter
    other = [
        row for row in section.get("levels") or []
        if row.get("verdict") is not None
        and row not in rows
    ]
    if other:
        lines.append("")
        lines.extend(_table(
            ["level", "verdict", "series"],
            [[r["level"], r["verdict"], len(r.get("verdicts") or [])]
             for r in other],
        ))
    return lines


def render_diff(base: dict, cand: dict) -> List[str]:
    """Per-level locked/left deltas + verdict flips (shared with
    telemetry.diff's quality block)."""
    from .diff import diff_quality

    lines, _ = diff_quality(base, cand)
    return lines or ["no quality sections to compare"]


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json
    import sys

    from .diff import load_report

    ap = argparse.ArgumentParser(
        prog="kaminpar_tpu.telemetry.quality",
        description="per-level convergence triage: rank hierarchy levels "
        "by cut responsibility (coarsening_locked vs refinement_left), "
        "with coarsening-quality stats and refinement verdicts",
    )
    ap.add_argument("report", help="run-report JSON (--report-json)")
    ap.add_argument(
        "--top", type=int, default=16, metavar="N",
        help="level rows to print (default 16)",
    )
    ap.add_argument(
        "--diff", default=None, metavar="BASE.report.json",
        help="also print per-level locked/left deltas and verdict flips "
        "against a baseline report",
    )
    ap.add_argument(
        "--require-attribution", action="store_true",
        help="exit 1 when the report carries no attribution rows (CI "
        "assertion that the observatory ran)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit the quality section as JSON instead of tables",
    )
    args = ap.parse_args(argv)

    try:
        report = load_report(args.report)
        base = load_report(args.diff) if args.diff else None
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(report.get("quality") or {}))
    else:
        for line in render_report(report, top_n=args.top):
            print(line)
        if base is not None:
            print()
            for line in render_diff(base, report):
                print(line)
    if args.require_attribution and not attribution_rows(report):
        print(
            "error: report carries no attribution rows "
            "(--require-attribution)", file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
