"""Algorithm-progress capture: per-iteration convergence series.

PR 1's spans show *where time goes*; this layer shows *what the
algorithms are doing* while it passes.  LP, Jet, FM and the balancers
run fully fused inside `lax.while_loop`, so the per-iteration state the
reference's statistics registry would print (moved nodes, cut, fruitless
counter, balancer violation mass — kaminpar-common/statistics lineage,
"Tera-Scale Multilevel Graph Partitioning" §6) is computed on device
every round and then thrown away.  Here each instrumented loop threads a
fixed-size stat buffer through its carry:

  * `new_buffer(rows, stats)` allocates an i-indexed (rows, stats)
    ACC_DTYPE buffer filled with the UNWRITTEN sentinel;
  * `record(buf, i, *stats)` writes row `i` device-side
    (`.at[i].set(..., mode="drop")` — iterations beyond the buffer are
    dropped, never clamped onto another row);
  * `emit(kind, names, buf, t0)` pulls the buffer ONCE at loop exit
    (host-side, outside jit — no new host syncs inside traced code, so
    tpulint R1/R2 stay clean) and records a ProgressSeries on the
    telemetry stream.

Zero-overhead-when-disabled contract: the buffer rides the carry as an
optional pytree leaf.  Callers pass `None` when `capture()` is false,
and every `record()` site is guarded by `if buf is not None` — a
trace-time python branch — so the disabled jaxpr is IDENTICAL to the
uninstrumented loop (no extra carry, no retrace; pinned by
tests/test_telemetry.py's jaxpr-equality test).  Because the buffer is
an ordinary argument, the jit cache keys the two variants apart by
pytree structure; toggling telemetry can never serve a stale trace.

Drivers label series with loop-external context (coarsening level,
uncoarsening level, v-cycle) via the `tag(...)` context manager; the
tags ride into the series' attrs.
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager
from typing import Any, Dict, Sequence

from . import enabled as _telemetry_enabled
from . import record_progress

# Rows are indexed by the loop counter; the sentinel marks never-written
# rows (early convergence) so emit() can trim the tail.  All recorded
# stats are counts/cuts >= 0, so any negative sentinel is unambiguous.
UNWRITTEN = -(2**31)

ENV_VAR = "KAMINPAR_TPU_PROGRESS"

# driver-pushed context tags (level, round, ...) merged into every
# series emitted while the tag scope is open
_tags: Dict[str, Any] = {}


def capture() -> bool:
    """Whether loops should thread stat buffers through their carries.

    True iff telemetry is enabled and KAMINPAR_TPU_PROGRESS is not 0 —
    read at TRACE time by the non-jit entry points, which pass the
    buffer (or None) down as an ordinary argument."""
    if os.environ.get(ENV_VAR, "") == "0":
        return False
    return _telemetry_enabled()


@contextmanager
def tag(**kv: Any):
    """Label series emitted inside with driver context (level=3, ...)."""
    saved = {k: _tags.get(k) for k in kv}
    _tags.update(kv)
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                _tags.pop(k, None)
            else:
                _tags[k] = v


def current_tags() -> Dict[str, Any]:
    return dict(_tags)


def new_buffer(rows: int, stats: int):
    """Device-side (rows, stats) stat buffer (ACC_DTYPE, sentinel-filled).

    `rows` is a static python int — size it to the loop's iteration
    budget so every iteration has a row; extra iterations drop."""
    import jax.numpy as jnp

    from ..dtypes import ACC_DTYPE

    return jnp.full((max(int(rows), 1), stats), UNWRITTEN, dtype=ACC_DTYPE)


def record(buf, i, *stats):
    """Write row `i` (traced) of the buffer; out-of-range rows drop.

    Device-side, call inside the loop body ONLY under an
    `if buf is not None` trace-time guard."""
    import jax.numpy as jnp

    row = jnp.stack([jnp.asarray(s).astype(buf.dtype) for s in stats])
    return buf.at[i].set(row, mode="drop")


def emit(kind: str, names: Sequence[str], buf, t0: float | None = None,
         **attrs: Any) -> None:
    """Pull a stat buffer (ONE host transfer) and record the series.

    Call from host-side driver code after the loop exits, never from
    jit-TRACED code (the pull is a device sync and would fail on a
    tracer).  Calling from inside an open timer scope is by design —
    that is where the series' dotted path comes from; the pull just
    must not sit lexically inside a `with scoped_timer(...)` block of a
    driver module, which tpulint R1 polices (these emit sites live in
    the ops modules, outside the drivers' span blocks).  No-op when
    `buf` is None, the loop never ran (all-sentinel buffer — e.g. an
    already-feasible balancer), or telemetry got disabled meanwhile."""
    if buf is None or not _telemetry_enabled():
        return
    import numpy as np

    arr = np.asarray(buf)
    from . import ledger

    ledger.transfer("d2h", arr.nbytes, kind="progress-pull")
    # select written rows (loop order is preserved): buffers indexed by
    # a global counter across rounds legitimately leave sentinel gaps
    # when a round early-exits, so compress rather than prefix-slice
    arr = arr[arr[:, 0] != UNWRITTEN]
    n = arr.shape[0]
    if n == 0:
        # the loop body never executed (e.g. the balancer's feasibility
        # check was true on entry) — an empty series carries no
        # information and would bloat multi-level reports
        return
    series = {
        name: arr[:, j].tolist() for j, name in enumerate(names)
    }
    merged = dict(_tags)
    merged.update({k: v for k, v in attrs.items() if v is not None})
    record_progress(kind, series, iterations=n, t0=t0, **merged)


def emit_host(kind: str, series: Dict[str, Sequence], t0: float | None = None,
              **attrs: Any) -> None:
    """Record a series assembled host-side (the FM refiner, chunked
    device loops that already read back their convergence scalar)."""
    if not _telemetry_enabled():
        return
    n = max((len(v) for v in series.values()), default=0)
    merged = dict(_tags)
    merged.update({k: v for k, v in attrs.items() if v is not None})
    record_progress(
        kind, {k: list(v) for k, v in series.items()},
        iterations=n, t0=t0, **merged,
    )


def now() -> float:
    """Loop-entry timestamp for emit(t0=...) (host clock, run-relative
    conversion happens in record_progress)."""
    return time.perf_counter()


def instrumented(call, kind: str, names: Sequence[str],
                 rows: int | None = None, **attrs: Any):
    """Run one instrumented loop entry point, centralizing the capture
    dance every public wrapper would otherwise repeat: decide capture,
    allocate the buffer, invoke, unpack, emit, return the bare result.

    `call` receives ONE argument and must honor the stats/None contract
    the loops implement:

      * `rows` given  — the argument is a fresh `(rows, len(names))`
        buffer (or None when capture is off); the impl threads it
        through its carry and returns `(result, stats)` when it got a
        buffer, else just `result`.
      * `rows` None   — the argument is the capture BOOL (for shard_map
        impls that must allocate the buffer inside the traced region,
        keyed by a static `record` flag); same return contract.
    """
    rec = capture()
    t0 = now()
    if rows is not None:
        stats = new_buffer(rows, len(names)) if rec else None
        out = call(stats)
    else:
        out = call(rec)
    if not rec:
        return out
    result, stats = out
    emit(kind, names, stats, t0, **attrs)
    return result
