"""Run-report triage CLI: `python -m kaminpar_tpu.telemetry.top REPORT`.

The read-first tool of the performance observatory (docs/performance.md
"roofline triage workflow"): given one `--report-json` artifact it
renders the top-N scopes by wall, by bytes moved, by utilization
deficit (wall spent below the roofline — the fusion-target ranking),
and the pad-waste rows (what fraction of each launch was padding —
cross-reference BEFORE blaming a kernel), plus the memory watermarks
and, for serve-mode reports, the latency percentiles.

`--diff BASE` aligns a second report by scope path (the same alignment
`telemetry.diff` gates on) and prints wall / bytes / utilization
deltas side by side.

Exit codes: 0 rendered, 1 only with `--require-roofline` when the
report carries no roofline rows (the check_all.sh smoke assertion that
the observatory did not silently die), 2 usage/IO error.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from .diff import flatten_scopes, load_report

DEFAULT_TOP_N = 8


def _fmt(v: Any, digits: int = 3) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{digits}g}"
    return str(v)


def _table(headers: List[str], rows: List[List[Any]]) -> List[str]:
    table = [headers] + [[_fmt(c) for c in row] for row in rows]
    widths = [
        max(len(str(row[i])) for row in table)
        for i in range(len(headers))
    ]
    return [
        "  ".join(str(cell).ljust(widths[i])
                  for i, cell in enumerate(row))
        for row in table
    ]


def roofline_rows(report: dict) -> Dict[str, dict]:
    return (report.get("perf") or {}).get("roofline") or {}


def render_report(report: dict, top_n: int = DEFAULT_TOP_N) -> List[str]:
    lines: List[str] = []
    perf = report.get("perf") or {}
    peaks = perf.get("peaks") or {}
    totals = perf.get("totals") or {}
    lines.append(
        f"peaks: {_fmt(peaks.get('gbps'))} GB/s, "
        f"{_fmt(peaks.get('gflops'))} GFLOP/s "
        f"({peaks.get('source', '?')}); totals: "
        f"{_fmt(totals.get('bytes'))} bytes, "
        f"{_fmt(totals.get('flops'))} flops, "
        f"hbm_util={_fmt(totals.get('hbm_util'))}, "
        f"pad_waste={_fmt(totals.get('pad_waste'))}"
    )
    axes = totals.get("pad_waste_axes") or {}
    if axes:
        # the headline sums element counts across axes, so m dominates;
        # the per-axis twins are where a k-bucket regression shows up
        lines.append(
            "pad_waste by axis: "
            + ", ".join(
                f"{a}={_fmt(axes[a])}" for a in ("n", "m", "k")
                if a in axes
            )
        )
    slack = totals.get("pad_slack_axes") or {}
    if slack:
        # aggregate headroom (free padded elements) — the budget that
        # decides in-place dynamic-delta application (dynamic/)
        lines.append(
            "pad_slack by axis (headroom, elements): "
            + ", ".join(
                f"{a}={_fmt(slack[a])}" for a in ("n", "m", "k")
                if a in slack
            )
        )

    # -- top scopes by wall (every report has a scope tree) --------------
    scopes = flatten_scopes(report.get("scope_tree", {}))
    by_wall = sorted(scopes.items(), key=lambda kv: -kv[1])[:top_n]
    if by_wall:
        lines.append("")
        lines.append(f"top {len(by_wall)} scopes by wall:")
        lines.extend(_table(
            ["scope", "wall_s"],
            [[path, round(w, 4)] for path, w in by_wall],
        ))

    # -- roofline: by bytes and by utilization deficit -------------------
    roof = roofline_rows(report)
    if roof:
        def honest_mark(e: dict) -> str:
            if "honest" not in e:
                return "-"  # pre-v13 report: no launch ledger
            return "yes" if e.get("honest") else "no"

        by_bytes = sorted(
            roof.items(), key=lambda kv: -kv[1].get("bytes", 0)
        )[:top_n]
        lines.append("")
        lines.append(f"top {len(by_bytes)} scopes by bytes accessed:")
        lines.extend(_table(
            ["scope", "bytes", "flops", "wall_s", "GB/s", "hbm_util",
             "launches", "honest"],
            [
                [p, e.get("bytes"), e.get("flops"), e.get("wall_s"),
                 e.get("achieved_gbps"), e.get("hbm_util"),
                 e.get("launches"), honest_mark(e)]
                for p, e in by_bytes
            ],
        ))
        with_deficit = [
            (p, e) for p, e in roof.items() if e.get("deficit_s")
        ]
        # honest rows first: their deficit is computed from measured
        # launch-joined bytes/flops, so they are trustworthy fusion
        # targets; compile-time-only rows (honest=false / pre-v13) rank
        # after them regardless of deficit magnitude
        by_deficit = sorted(
            with_deficit,
            key=lambda kv: (not kv[1].get("honest", False),
                            -kv[1]["deficit_s"]),
        )[:top_n]
        if by_deficit:
            lines.append("")
            lines.append(
                f"top {len(by_deficit)} scopes by utilization deficit "
                "(wall below the roofline — fusion-target ranking; "
                "honest rows first):"
            )
            lines.extend(_table(
                ["scope", "deficit_s", "hbm_util", "flops_util",
                 "compiles", "launches", "honest"],
                [
                    [p, e.get("deficit_s"), e.get("hbm_util"),
                     e.get("flops_util"), e.get("compiles"),
                     e.get("launches"), honest_mark(e)]
                    for p, e in by_deficit
                ],
            ))
    else:
        lines.append("")
        lines.append(
            "no roofline rows (schema < 5, KAMINPAR_TPU_PERF=0, or a "
            "fully warm executable cache — cost is captured per backend "
            "compile)"
        )

    # -- pad waste -------------------------------------------------------
    pad = perf.get("pad_waste") or []

    def worst_waste(row: dict) -> float:
        return max(
            (row.get(axis + "_waste", 0.0) for axis in ("n", "m", "k")),
            default=0.0,
        )

    by_waste = sorted(pad, key=lambda r: -worst_waste(r))[:top_n]
    if by_waste:
        lines.append("")
        # *_slack = per-launch free padded slots of the bucket (the
        # headroom a dynamic-session delta can grow into IN PLACE
        # before crossing buckets — dynamic/session.py)
        lines.append(f"top {len(by_waste)} pad-waste rows "
                     "(slack = per-launch headroom, elements):")
        lines.extend(_table(
            ["scope", "bucket", "launches", "n_waste", "m_waste",
             "k_waste", "n_slack", "m_slack"],
            [
                [r.get("scope"), r.get("bucket"), r.get("launches"),
                 r.get("n_waste"), r.get("m_waste"), r.get("k_waste"),
                 r.get("n_slack"), r.get("m_slack")]
                for r in by_waste
            ],
        ))

    # -- memory watermarks ----------------------------------------------
    mem = perf.get("memory") or {}
    samples = mem.get("samples") or []
    if samples or mem.get("peak_live_bytes"):
        lines.append("")
        head = f"memory: peak live {_fmt(mem.get('peak_live_bytes'))} B"
        if mem.get("hbm_limit_bytes"):
            head += (
                f", HBM limit {_fmt(mem.get('hbm_limit_bytes'))} B, "
                f"headroom {_fmt(mem.get('headroom_bytes'))} B"
            )
        lines.append(head)
        top_samples = sorted(
            samples, key=lambda s: -s.get("live_bytes", 0)
        )[:top_n]
        if top_samples:
            lines.extend(_table(
                ["stage", "live_bytes"],
                [[s.get("stage"), s.get("live_bytes")]
                 for s in top_samples],
            ))
        levels = mem.get("levels") or []
        if levels:
            lines.append("per-level buffers:")
            lines.extend(_table(
                ["level", "n", "m", "n_pad", "m_pad", "buffer_bytes"],
                [
                    [lv.get("level"), lv.get("n"), lv.get("m"),
                     lv.get("n_pad"), lv.get("m_pad"),
                     lv.get("buffer_bytes")]
                    for lv in levels
                ],
            ))
        ranks = mem.get("ranks") or []
        if len(ranks) > 1:
            lines.append("per-rank live bytes:")
            lines.extend(_table(
                ["rank", "live_bytes"],
                [[r.get("rank"), r.get("live_bytes")] for r in ranks],
            ))

    # -- communication volume (schema v12 per-phase rollup) --------------
    comm = report.get("comm") or {}
    comm_phases = comm.get("phases") or {}
    if comm_phases:
        lines.append("")
        lines.append(
            f"comm volume: {_fmt(comm.get('bytes_total'))} bytes total "
            "(logical, pre-padding — see comm.caveat):"
        )
        lines.extend(_table(
            ["phase", "bytes", "calls"],
            [
                [phase, t.get("bytes_total"), t.get("calls")]
                for phase, t in sorted(
                    comm_phases.items(),
                    key=lambda kv: -kv[1].get("bytes_total", 0),
                )[:top_n]
            ],
        ))

    # -- host<->device transfers (schema v13 execution ledger) -----------
    ledger = report.get("ledger") or {}
    xfers = ledger.get("transfers") or {}
    xfer_totals = xfers.get("totals") or {}
    if xfer_totals.get("h2d_bytes") or xfer_totals.get("d2h_bytes"):
        lines.append("")
        lines.append(
            "host<->device transfers: "
            f"h2d {_fmt(xfer_totals.get('h2d_bytes'))} B "
            f"({_fmt(xfer_totals.get('h2d_count'))} xfers), "
            f"d2h {_fmt(xfer_totals.get('d2h_bytes'))} B "
            f"({_fmt(xfer_totals.get('d2h_count'))} xfers):"
        )
        rows = xfers.get("rows") or []
        lines.extend(_table(
            ["scope", "dir", "kind", "bytes", "count"],
            [
                [r.get("scope"), r.get("direction"), r.get("kind"),
                 r.get("bytes"), r.get("count")]
                for r in rows[:top_n]
            ],
        ))
        by_phase = xfers.get("by_phase") or {}
        if by_phase:
            lines.append("transfer bytes by phase:")
            lines.extend(_table(
                ["phase", "h2d_bytes", "d2h_bytes"],
                [
                    [phase, t.get("h2d_bytes"), t.get("d2h_bytes")]
                    for phase, t in sorted(
                        by_phase.items(),
                        key=lambda kv: -(kv[1].get("h2d_bytes", 0)
                                         + kv[1].get("d2h_bytes", 0)),
                    )[:top_n]
                ],
            ))
    donation = ledger.get("donation") or {}
    don_rows = [
        (p, d) for p, d in sorted(donation.items())
        if d.get("requested")
    ]
    if don_rows:
        lines.append("")
        lines.append("donated-buffer audit (aliasing honored by XLA):")
        lines.extend(_table(
            ["scope", "requested", "honored", "bytes_saved"],
            [
                [p, d.get("requested"), d.get("honored"),
                 d.get("bytes_saved")]
                for p, d in don_rows[:top_n]
            ],
        ))

    # -- serving latency -------------------------------------------------
    serving = report.get("serving") or {}
    latency = serving.get("latency") or {}
    phases = latency.get("phases") or {}
    if serving.get("enabled") and phases:
        lines.append("")
        throughput = serving.get("throughput") or {}
        if throughput:
            lines.append(
                "serving throughput: "
                f"rps={_fmt(throughput.get('requests_per_second'))}, "
                f"queue_peak={_fmt(throughput.get('queue_peak'))}, "
                f"batch_occupancy={_fmt(throughput.get('batch_occupancy'))}"
            )
        lines.append("serving latency (per phase):")
        lines.extend(_table(
            ["phase", "count", "p50_ms", "p95_ms", "p99_ms", "max_ms"],
            [
                [name, h.get("count"), h.get("p50_ms"), h.get("p95_ms"),
                 h.get("p99_ms"), h.get("max_ms")]
                for name, h in phases.items()
            ],
        ))
        classes = latency.get("classes") or {}
        if classes:
            lines.append("per request class (executable bucket):")
            lines.extend(_table(
                ["class", "requests", "p50_ms", "p95_ms", "reuse"],
                [
                    [cls, c.get("requests"), c.get("p50_ms"),
                     c.get("p95_ms"), c.get("executable_reuse")]
                    for cls, c in sorted(classes.items())
                ],
            ))
    return lines


def render_diff(base: dict, cand: dict,
                top_n: int = DEFAULT_TOP_N) -> List[str]:
    """Side-by-side scope deltas: wall from the scope trees (every
    schema), bytes/utilization from the roofline rows (v5)."""
    lines: List[str] = []
    sb = flatten_scopes(base.get("scope_tree", {}))
    sc = flatten_scopes(cand.get("scope_tree", {}))
    shared = sorted(
        set(sb) & set(sc),
        key=lambda p: -abs(sc[p] - sb[p]),
    )[:top_n]
    rb, rc = roofline_rows(base), roofline_rows(cand)
    if shared:
        lines.append("scope deltas (base -> cand):")
        rows = []
        for path in shared:
            eb, ec = rb.get(path, {}), rc.get(path, {})
            rows.append([
                path,
                f"{sb[path]:.3f}->{sc[path]:.3f}",
                f"{_fmt(eb.get('bytes'))}->{_fmt(ec.get('bytes'))}",
                f"{_fmt(eb.get('hbm_util'))}->"
                f"{_fmt(ec.get('hbm_util'))}",
            ])
        lines.extend(_table(
            ["scope", "wall_s", "bytes", "hbm_util"], rows
        ))
    tb = (base.get("perf") or {}).get("totals") or {}
    tc = (cand.get("perf") or {}).get("totals") or {}
    if tb or tc:
        lines.append(
            f"totals: hbm_util {_fmt(tb.get('hbm_util'))} -> "
            f"{_fmt(tc.get('hbm_util'))}, pad_waste "
            f"{_fmt(tb.get('pad_waste'))} -> "
            f"{_fmt(tc.get('pad_waste'))}"
        )
    # v13 ledger delta (informational — never a gate): transfer bytes
    # drifting up between runs is the first sign of a new host sync
    xb = ((base.get("ledger") or {}).get("transfers") or {}) \
        .get("totals") or {}
    xc = ((cand.get("ledger") or {}).get("transfers") or {}) \
        .get("totals") or {}
    if xb or xc:
        lines.append(
            f"transfers: h2d {_fmt(xb.get('h2d_bytes'))} -> "
            f"{_fmt(xc.get('h2d_bytes'))} B, d2h "
            f"{_fmt(xb.get('d2h_bytes'))} -> "
            f"{_fmt(xc.get('d2h_bytes'))} B"
        )
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="kaminpar_tpu.telemetry.top",
        description="triage a run report: top scopes by wall / bytes / "
        "utilization deficit / pad waste, memory watermarks, serving "
        "latency",
    )
    ap.add_argument("report", help="run-report JSON (--report-json)")
    ap.add_argument(
        "--top", type=int, default=DEFAULT_TOP_N, metavar="N",
        help=f"rows per ranking (default {DEFAULT_TOP_N})",
    )
    ap.add_argument(
        "--diff", default=None, metavar="BASE.report.json",
        help="also print scope-aligned wall/bytes/utilization deltas "
        "against a baseline report",
    )
    ap.add_argument(
        "--require-roofline", action="store_true",
        help="exit 1 when the report carries no roofline rows (CI "
        "assertion that cost capture ran)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit the perf section as JSON instead of tables",
    )
    args = ap.parse_args(argv)

    try:
        report = load_report(args.report)
        base = load_report(args.diff) if args.diff else None
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(report.get("perf") or {}))
    else:
        for line in render_report(report, top_n=args.top):
            print(line)
        # v7 quality cross-pointer: seconds/bytes triage lives here, cut
        # responsibility lives in the quality observatory
        quality = report.get("quality") or {}
        if quality.get("levels"):
            totals = quality.get("totals") or {}
            print()
            print(
                "quality: "
                f"{totals.get('attribution_rows', 0)} attribution "
                "level(s), coarsening_locked_frac="
                f"{_fmt(totals.get('coarsening_locked_frac'))} — "
                "python -m kaminpar_tpu.telemetry.quality "
                f"{args.report}"
            )
        if base is not None:
            print()
            for line in render_diff(base, report, top_n=args.top):
                print(line)
    if args.require_roofline and not roofline_rows(report):
        print(
            "error: report carries no roofline rows "
            "(--require-roofline)", file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
