"""Per-run JSON report (`--report-json`).

One machine-readable artifact per partition call, the analog of the
reference's parseable RESULT + TIME output promoted to a single schema:
scope tree (from the hierarchical timer), result metrics, per-level
graph sizes (from the coarsener's telemetry events), the collective
traffic table (parallel/mesh comm accounting), the lane-gather probe
verdict, statistics counters, and an environment stamp.  `bench.py`
embeds the same dict into its BENCH line so ad-hoc runs and the perf
trajectory share one schema.

The schema is checked in at `run_report.schema.json` and enforced by
`scripts/check_report_schema.py` (invoked from a tier-1 test, so schema
drift is caught at commit time).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

from . import events as _events
from . import jsonable
from . import progress_series as _progress_series
from . import run_info as _run_info

SCHEMA_VERSION = 14
SCHEMA_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "run_report.schema.json"
)


def environment_stamp() -> dict:
    """Platform / device-count / version stamp for the report header."""
    from .. import __version__

    import platform as _platform

    env: Dict[str, Any] = {
        "version": __version__,
        "python": _platform.python_version(),
    }
    try:
        import jax

        from ..utils import platform

        env["jax_version"] = jax.__version__
        devices = platform.devices()
        env["platform"] = devices[0].platform
        env["device_count"] = len(devices)
        env["process_count"] = platform.process_count()
    except Exception:
        env.setdefault("jax_version", "unavailable")
        env.setdefault("platform", "unknown")
        env.setdefault("device_count", 0)
        env.setdefault("process_count", 1)
    return env


def _compile_section() -> dict:
    """Compile-cost aggregate (trace/lower/compile seconds per phase,
    cache hit/miss totals); empty-but-well-formed when the monitoring
    listeners never installed (telemetry enabled mid-run)."""
    try:
        from . import compile_account

        return compile_account.snapshot()
    except Exception:
        return {"caveat": "compile accounting unavailable",
                "totals": {}, "phases": {}}


def _perf_section(levels, perf_ranks=None) -> dict:
    """Schema v5 `perf` section: roofline rows, memory watermarks (with
    the per-level CSR buffer accounting folded in), pad-waste rows.
    Well-formed disabled default when the observatory is unavailable."""
    try:
        from . import perf

        section = perf.snapshot()
    except Exception:
        return {"enabled": False,
                "caveat": "perf observatory unavailable"}
    mem = section.setdefault("memory", {})
    # per-level resident CSR/partition buffer bytes, from the
    # coarsener's level events (host-side metadata, never a device pull)
    mem["levels"] = [
        {k: lv[k] for k in ("level", "n", "m", "n_pad", "m_pad",
                            "buffer_bytes") if k in lv}
        for lv in levels
        if "buffer_bytes" in lv
    ]
    if perf_ranks:
        mem["ranks"] = perf_ranks
    return section


def _ledger_section() -> dict:
    """Schema v13 ``ledger`` section: per-scope launch counts joined
    with executable costs, the host<->device transfer ledger (per
    scope/kind, per phase, totals), and the donation audit
    (telemetry/ledger.py).  Well-formed disabled default when the
    ledger is unavailable."""
    try:
        from . import ledger

        return ledger.snapshot()
    except Exception:
        return {"enabled": False,
                "caveat": "execution ledger unavailable"}


def _integrity_section() -> dict:
    """Schema v14 ``integrity`` section: sentinel check/violation
    counts, the retry-from-barrier ladder outcome (verdict clean /
    detected / recovered / corrupt-result), exchange-digest tallies,
    and the sampled re-execution audits per scope
    (resilience/integrity.py).  Well-formed disabled default when the
    kill switch is set and nothing ran."""
    try:
        from ..resilience import integrity

        return integrity.summary()
    except Exception:
        return {"enabled": False}


def _quality_section(ranks=None) -> dict:
    """Schema v7 `quality` section: per-level cut-loss attribution
    (projected / refined / floor cuts, coarsening_locked vs
    refinement_left), coarsening-quality stats, and refinement-efficacy
    verdicts (telemetry/quality.py).  Well-formed disabled default when
    the observatory recorded nothing."""
    try:
        from . import quality

        section = quality.snapshot()
    except Exception:
        return {"enabled": False,
                "caveat": "quality observatory unavailable"}
    if ranks:
        section["ranks"] = ranks
    return section


def _supervision_section() -> dict:
    """Schema v10 `supervision` section from the module state (the
    serving layer overrides this with its pool-aware summary); the
    disabled default when nothing supervision-shaped ever armed."""
    try:
        from ..resilience import supervisor

        return supervisor.summary()
    except Exception:
        return {"enabled": False}


def _fault_section() -> dict:
    """The fault-plan echo (CLI satellite): plan, sites, injected log."""
    try:
        from ..resilience import faults

        return faults.plan_summary()
    except Exception:
        return {"plan": None, "sites": [], "injected": []}


def _scope_tree(node) -> dict:
    return {
        child.name: {
            "elapsed_s": round(child.elapsed, 6),
            "count": child.count,
            "children": _scope_tree(child),
        }
        for child in node.children.values()
    }


def build_run_report(extra_run: Optional[dict] = None) -> dict:
    """Assemble the report from the current telemetry/timer/stats state.

    Call after `compute_partition` returns (the facade annotates the run
    and result sections during the call); `extra_run` keys (e.g. CLI io /
    wall seconds) are merged into the `run` section."""
    from ..ops import lane_gather
    from ..utils import statistics, timer

    info = _run_info()
    result = info.pop("result", {})
    # the output gate's verdict (resilience/gate.py); absent when the
    # gate was disabled or no partition ran in this stream
    gate_verdict = info.pop("output_gate", {"checked": False})
    # schema v3 resilience sections: the checkpoint manager's summary
    # (resilience/checkpoint.py) and the anytime/wind-down annotation
    # (resilience/deadline.py); well-formed defaults when the run used
    # neither
    ckpt_summary = info.pop("checkpoint", {"enabled": False})
    anytime = info.pop("anytime", {"anytime": False})
    # schema v4: the serving layer's per-request verdicts + admission
    # and cache statistics (serving/service.py); single-shot runs carry
    # the well-formed disabled default
    serving = info.pop("serving", {"enabled": False})
    # schema v5: the dist driver's per-rank memory rollup (collective,
    # gathered before the report) folds into the perf section below
    perf_ranks = info.pop("perf_ranks", None)
    # schema v6: the memory governor's audit trail (resilience/memory.py
    # — budget, estimate, ladder rung, spill/reload accounting); runs
    # with no declared budget and no OOM carry the disabled default
    memory_budget = info.pop("memory_budget", {"enabled": False})
    # schema v7: the dist driver's per-rank attribution rollup
    # (collective, gathered before the report) folds into the quality
    # section below
    quality_ranks = info.pop("quality_ranks", None)
    # schema v8: the dist resilience audit trail (divergence-sentinel
    # counters + per-rank dump, shard fingerprints, the agreed ladder
    # rung, what was resumed) — annotated by the dist driver; shm runs
    # carry the well-formed disabled default
    dist_resilience = info.pop("dist_resilience", {"enabled": False})
    # schema v9: the out-of-core streaming audit trail (external/driver
    # annotates it: chunk counts, decoded vs uploaded bytes, the
    # upload/compute overlap fraction, fine-level device residency);
    # in-core runs carry the well-formed disabled default
    external = info.pop("external", {"enabled": False})
    # schema v10: the supervision audit trail (resilience/supervisor.py
    # — worker lifecycle, hang events, heartbeat, watchdog).  The
    # serving layer annotates its pool-aware view; otherwise the module
    # state is read directly (a single-shot run with a heartbeat or an
    # armed watchdog still reports), and a run that configured nothing
    # carries the well-formed disabled default.
    supervision = info.pop("supervision", None)
    if supervision is None:
        supervision = _supervision_section()
    # schema v11: the dynamic-repartitioning audit trail (kaminpar_tpu/
    # dynamic/) — live sessions (deltas applied, in-place vs rebuild
    # counts, chain digest), the warm/cold/replica decision log with
    # drift scores and diff-gate verdicts, and the per-step cut
    # trajectory.  Annotated by the chain driver / serving layer; runs
    # with no sessions carry the well-formed disabled default.
    dynamic = info.pop("dynamic", {"enabled": False})
    run = dict(info)
    if extra_run:
        run.update({k: jsonable(v) for k, v in extra_run.items()})

    levels = [
        {"level": e.attrs.get("level"), **{
            k: e.attrs[k]
            for k in ("n", "m", "retries", "n_pad", "m_pad",
                      "buffer_bytes")
            if k in e.attrs
        }}
        for e in _events("coarsening-level")
    ]

    # per-level rating-engine choices (ops/rating.select_engine via the
    # coarsener's `rating-engine` events) + a per-engine level count —
    # the report-field twin of the telemetry event, so "which engine ran
    # where and why" is a read (bench_trend renders the counts column)
    rating_levels = [
        {k: e.attrs[k]
         for k in ("level", "engine", "reason", "avg_degree",
                   "degree_skew", "n", "m")
         if k in e.attrs}
        for e in _events("rating-engine")
    ]
    rating_counts: Dict[str, int] = {}
    for lv in rating_levels:
        eng = lv.get("engine")
        if eng:
            rating_counts[eng] = rating_counts.get(eng, 0) + 1
    rating_section = {"levels": rating_levels, "engines": rating_counts}

    try:
        from ..parallel import mesh

        phase_totals = mesh.comm_phase_totals()
        comm = {
            "caveat": mesh.COMM_CAVEAT,
            "records": mesh.comm_records(),
            # opened-vs-traced lets report consumers spot cache-hit
            # phases (opened but zero traced rows) explicitly
            "phase_opens": mesh.phase_opens(),
            # schema v12 (additive): the per-phase rollup + grand total
            # ROADMAP item 4 asks for — "comm bytes per phase" as a
            # read, next to the raw per-(phase, op, shape) records
            "phases": phase_totals,
            "bytes_total": sum(
                t["bytes_total"] for t in phase_totals.values()
            ),
        }
    except Exception:  # mesh pulls in jax; stay robust without a backend
        comm = {"caveat": "comm accounting unavailable", "records": []}

    # schema v12: per-request trace timelines (telemetry/tracing.py) —
    # the serving layer's end-to-end spans (admission wait -> resolve ->
    # compute -> gate, plus the supervised-worker boundary rows);
    # non-serving runs carry the well-formed empty default
    try:
        from . import tracing as _tracing

        tracing_section = _tracing.snapshot()
    except Exception:
        tracing_section = {"enabled": False, "traces": []}

    # distributed finalize: per-scope min/avg/max across processes (the
    # kaminpar-dist/timer.cc analog); on one process min == avg == max.
    # This is itself a host-side collective — the `collective`
    # degradation site covers it: a sick link degrades the report to
    # local-only timers instead of hanging or dying.  Runs BEFORE the
    # event lists below are snapshotted so its own `degraded` event (if
    # any) lands in this report.
    from ..resilience import CollectiveTimeout, with_fallback

    def _aggregate():
        try:
            return timer.aggregate_across_processes()
        except (TypeError, AttributeError, KeyError, IndexError,
                AssertionError, NameError):
            # programming-shaped errors are bugs, not degradations —
            # they must stay loud (docs/static_analysis.md hazard note)
            raise
        except Exception as e:
            # infra-shaped failures (backend/link/timeout) degrade
            raise CollectiveTimeout(
                f"timer aggregation failed: {type(e).__name__}: {e}"
            ) from e

    agg = with_fallback(
        _aggregate, lambda exc: None, site="collective",
        where="report-timers",
    )

    report: Dict[str, Any] = {
        "schema_version": SCHEMA_VERSION,
        "environment": environment_stamp(),
        "run": run,
        "result": result,
        "scope_tree": _scope_tree(timer.GLOBAL_TIMER.root),
        "levels": levels,
        # schema v6 (additive): per-level rating-engine choices — the
        # density-adaptive selection audit trail (ops/rating.py)
        "rating": rating_section,
        "comm": comm,
        "events": [e.to_dict() for e in _events()],
        "counters": statistics.as_dict() if statistics.enabled() else {},
        "lane_gather": lane_gather.probe_status(),
        # resilience sections: the active fault plan (and every injected
        # fault), each degradation the policy wrapper recorded, and the
        # output gate's verdict — the run report is the audit trail of
        # what degraded and whether the postcondition still held
        "faults": _fault_section(),
        "degraded": [e.to_dict() for e in _events("degraded")],
        "output_gate": gate_verdict,
        # schema v2: per-iteration convergence series from the
        # instrumented device loops (telemetry/progress.py) and the
        # compile-cost split (telemetry/compile_account.py) — together
        # they answer "what did the algorithms do" and "was the slow
        # part compile or execute"
        "progress": [p.to_dict() for p in _progress_series()],
        "compile": _compile_section(),
        # schema v3: preemption-safety audit trail — what was
        # checkpointed (and whether durability degraded to memory-only)
        # and whether the run wound down early under a deadline/signal
        "checkpoint": ckpt_summary,
        "anytime": anytime,
        # schema v4: partitioning-as-a-service — every request's verdict
        # (served/anytime/degraded/rejected/failed), admission caps, and
        # the bounded result/executable cache hit rates
        "serving": serving,
        # schema v5: the performance observatory — per-scope roofline
        # rows (FLOPs/bytes vs measured wall vs device peak), barrier
        # memory watermarks + per-level buffer bytes, and pad-waste
        # attribution per (scope, bucket)
        "perf": _perf_section(levels, perf_ranks),
        # schema v6: the memory-pressure governor — declared budget vs
        # estimate vs watermark, the recovery-ladder rung the run ended
        # at, and spill/reload byte accounting (docs/robustness.md)
        "memory_budget": memory_budget,
        # schema v7: the quality observatory — per-level cut-loss
        # attribution (coarsening_locked vs refinement_left vs the
        # level-0 lower bound), coarsening-quality stats, and
        # refinement-efficacy verdicts (telemetry/quality.py)
        "quality": _quality_section(quality_ranks),
        # schema v8: the dist resilience audit trail — cross-rank
        # divergence-sentinel counters (+ the per-rank dump when one
        # fired), the input's shard-fingerprint vector, the agreed
        # memory-ladder rung, and the dist resume record
        # (resilience/agreement.py, docs/robustness.md)
        "dist_resilience": dist_resilience,
        # schema v9: the out-of-core streaming (external scheme)
        # section — per-level chunk/byte/overlap accounting, the
        # handoff point, and the fine level's device residency (0 for
        # any run that actually streamed)
        "external": external,
        # schema v10: the supervision audit trail — worker lifecycle
        # counters (spawned/recycled/killed/crashed), hang events with
        # the stuck stage/scope path, heartbeat file + touch count, and
        # watchdog arm/fire counts (resilience/supervisor.py,
        # docs/robustness.md "Supervision contract")
        "supervision": supervision,
        # schema v11: dynamic repartitioning — graph sessions (delta
        # chains, in-place vs rebuild bucket accounting, chain
        # digests), warm/cold/replica decisions with drift scores and
        # the PR-4 diff-gate verdict per step, and the cut trajectory
        # (kaminpar_tpu/dynamic/, docs/robustness.md "Dynamic
        # sessions")
        "dynamic": dynamic,
        # schema v12: per-request trace timelines — one row per span
        # (name, origin service/worker, start_ms, duration_ms, attrs),
        # per trace id; the report half of the fleet observatory
        # (docs/observability.md "Request tracing")
        "tracing": tracing_section,
        # schema v13: the execution ledger — per-scope launch counts
        # (the launch-honest half of the perf roofline), the
        # host<->device transfer ledger aggregated per scope/kind and
        # per phase, and the donation audit {requested, honored,
        # bytes_saved} per scope (telemetry/ledger.py,
        # docs/observability.md "Execution ledger")
        "ledger": _ledger_section(),
        # schema v14: the integrity audit — invariant-sentinel checks
        # and violations (named invariant + level + scope), the
        # retry-from-last-good-barrier outcome, exchange-digest
        # computed/verified/mismatched tallies, and the sampled
        # re-execution audits {audited, mismatched} per scope
        # (resilience/integrity.py, docs/robustness.md "Integrity
        # contract")
        "integrity": _integrity_section(),
    }
    if agg is not None:
        report["timers_aggregated"] = agg

    from ..utils import heap_profiler

    if heap_profiler.profiling_enabled():
        report["heap"] = heap_profiler.tree_dict()
    return report


def write_run_report(path: str, extra_run: Optional[dict] = None) -> dict:
    """Build the report, write it to `path`, and return it.

    Collective on multi-host runs: every process must call this (the
    aggregated-timer section allgathers), but only process 0 writes the
    file — concurrent writers on a shared filesystem would interleave.
    The written report is process 0's local view plus the cross-process
    min/avg/max timers."""
    from . import is_primary_process

    report = build_run_report(extra_run=extra_run)
    if is_primary_process():
        with open(path, "w") as f:
            json.dump(report, f, indent=1)
    return report
