"""Tool subcommands (analog of apps/tools/*.cc).

The reference ships five standalone tool binaries: graph properties,
partition properties, graph compression, graph rearrangement, and
connected components.  Here they are subcommands:

    python -m kaminpar_tpu.tools properties  <graph>
    python -m kaminpar_tpu.tools partition-properties <graph> <partition>
    python -m kaminpar_tpu.tools compress    <graph> -o out.npz
    python -m kaminpar_tpu.tools decompress  <graph.npz> -o out.metis
    python -m kaminpar_tpu.tools rearrange   <graph> -o out.metis
    python -m kaminpar_tpu.tools components  <graph>
    python -m kaminpar_tpu.tools convert     <graph> -o out.{metis,parhip}
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

import numpy as np

from . import io as io_mod
from .graphs.host import (
    HostGraph,
    apply_permutation,
    count_isolated_nodes,
    degree_bucket_permutation,
)


def _load(path: str, fmt: str = "auto") -> HostGraph:
    g = io_mod.load_graph(path, fmt=fmt)
    from .graphs.compressed import CompressedHostGraph

    if isinstance(g, CompressedHostGraph):
        g = g.decode()
    return g


def cmd_properties(args) -> int:
    """apps/tools graph properties: n, m, weights, degree stats."""
    g = _load(args.graph, args.format)
    deg = g.degrees()
    print(f"n={g.n} m={g.m // 2} (directed {g.m})")
    print(
        f"node_weighted={g.is_node_weighted()} edge_weighted={g.is_edge_weighted()}"
    )
    print(f"total_node_weight={g.total_node_weight}")
    print(f"total_edge_weight={g.total_edge_weight}")
    if g.n:
        print(
            f"degree min={int(deg.min())} max={int(deg.max())} "
            f"avg={float(deg.mean()):.2f}"
        )
    print(f"isolated_nodes={count_isolated_nodes(g)}")
    return 0


def cmd_partition_properties(args) -> int:
    """apps/tools partition properties: cut, imbalance, block weights."""
    g = _load(args.graph, args.format)
    part = io_mod.read_partition(args.partition)
    if len(part) != g.n:
        print(f"error: partition has {len(part)} entries, graph {g.n} nodes",
              file=sys.stderr)
        return 1
    from .graphs.host import host_partition_metrics

    k = int(part.max()) + 1 if len(part) else 0
    m = host_partition_metrics(g, part, k)
    bw = m["block_weights"]
    print(f"k={k} cut={m['cut']}")
    print(f"imbalance={m['imbalance']:.6f}")
    print(f"block_weights min={int(bw.min())} max={int(bw.max())}")
    return 0


def cmd_compress(args) -> int:
    """apps/tools graph compression: write the compressed container."""
    from .graphs.compressed import compress_host_graph

    g = _load(args.graph, args.format)
    cg = compress_host_graph(g)
    io_mod.write_compressed(args.output, cg)
    print(
        f"compressed {args.graph} -> {args.output} "
        f"(ratio {cg.compression_ratio():.2f}x, {cg.memory_bytes()} bytes)"
    )
    return 0


def cmd_decompress(args) -> int:
    g = _load(args.graph, "compressed")
    io_mod.write_metis(g, args.output)
    print(f"decompressed {args.graph} -> {args.output}")
    return 0


def cmd_rearrange(args) -> int:
    """apps/tools rearrangement: degree-bucket node order
    (graphutils/permutator.h rearrange_by_degree_buckets)."""
    g = _load(args.graph, args.format)
    perm = degree_bucket_permutation(g)
    out = apply_permutation(g, perm)
    io_mod.write_metis(out, args.output)
    print(f"rearranged {args.graph} -> {args.output}")
    return 0


def cmd_components(args) -> int:
    """Connected components via the device kernel (ops/components.py)."""
    from .graphs.csr import device_graph_from_host
    from .ops.components import connected_components

    g = _load(args.graph, args.format)
    dg = device_graph_from_host(g)
    labels = np.asarray(connected_components(dg))[: g.n]
    comps, sizes = np.unique(labels, return_counts=True)
    print(f"components={len(comps)}")
    if len(comps):
        print(f"largest={int(sizes.max())} smallest={int(sizes.min())}")
    if args.output:
        io_mod.write_partition(args.output, np.searchsorted(comps, labels))
    return 0


def cmd_convert(args) -> int:
    g = _load(args.graph, args.format)
    if args.output.endswith(".parhip") or args.to == "parhip":
        io_mod.write_parhip(g, args.output)
    else:
        io_mod.write_metis(g, args.output)
    print(f"converted {args.graph} -> {args.output}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="kaminpar_tpu.tools")
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp, output=False, required_output=False):
        sp.add_argument("graph")
        sp.add_argument("-f", "--format", default="auto")
        if output:
            sp.add_argument(
                "-o", "--output", required=required_output, default=None
            )

    common(sub.add_parser("properties"))
    spp = sub.add_parser("partition-properties")
    common(spp)
    spp.add_argument("partition")
    common(sub.add_parser("compress"), output=True, required_output=True)
    common(sub.add_parser("decompress"), output=True, required_output=True)
    common(sub.add_parser("rearrange"), output=True, required_output=True)
    sc = sub.add_parser("components")
    common(sc, output=True)
    scv = sub.add_parser("convert")
    common(scv, output=True, required_output=True)
    scv.add_argument("--to", default=None, choices=[None, "metis", "parhip"])

    args = p.parse_args(argv)
    return {
        "properties": cmd_properties,
        "partition-properties": cmd_partition_properties,
        "compress": cmd_compress,
        "decompress": cmd_decompress,
        "rearrange": cmd_rearrange,
        "components": cmd_components,
        "convert": cmd_convert,
    }[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
