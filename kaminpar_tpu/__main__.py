"""`python -m kaminpar_tpu` — the KaMinPar CLI (apps/KaMinPar.cc analog)."""

import sys

from .cli import main

sys.exit(main())
