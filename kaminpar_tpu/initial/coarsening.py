"""Sequential host-side coarsening for initial bipartitioning.

Analog of kaminpar-shm/initial_partitioning/initial_coarsener.cc (456 LoC):
sequential size-constrained LP clustering interleaved with contraction,
used only on already-small coarsest graphs (hundreds to thousands of nodes)
before flat bipartitioning.  numpy-vectorized rounds.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from ..context import InitialCoarseningContext
from ..graphs.host import HostGraph


@dataclass
class HostCoarseLevel:
    graph: HostGraph
    cmap: np.ndarray  # fine node -> coarse node


def host_lp_cluster(
    graph: HostGraph,
    max_cluster_weight: int,
    rng: np.random.Generator,
    num_iterations: int = 3,
) -> np.ndarray:
    """Sequential LP clustering (initial_coarsener's ClusteringAlgorithm):
    visit nodes in random order, join the adjacent cluster with max
    connection weight subject to the weight cap."""
    n = graph.n
    labels = np.arange(n, dtype=np.int64)
    cw = graph.node_weight_array().copy()
    node_w = graph.node_weight_array()
    edge_w = graph.edge_weight_array()

    for _ in range(num_iterations):
        moved = False
        for u in rng.permutation(n):
            lo, hi = int(graph.xadj[u]), int(graph.xadj[u + 1])
            if lo == hi:
                continue
            neigh = graph.adjncy[lo:hi]
            w = edge_w[lo:hi]
            cl = labels[neigh]
            # rating map: sum weights per adjacent cluster
            uniq, inv = np.unique(cl, return_inverse=True)
            ratings = np.bincount(inv, weights=w)
            cur = labels[u]
            ok = (uniq == cur) | (cw[uniq] + node_w[u] <= max_cluster_weight)
            if not ok.any():
                continue
            ratings = np.where(ok, ratings, -1)
            best_rating = ratings.max()
            ties = np.flatnonzero(ratings == best_rating)
            best = int(uniq[ties[rng.integers(0, len(ties))]])
            cur_rating = ratings[uniq == cur][0] if (uniq == cur).any() else 0
            if best != cur and best_rating >= max(cur_rating, 1):
                cw[cur] -= node_w[u]
                cw[best] += node_w[u]
                labels[u] = best
                moved = True
        if not moved:
            break
    return labels


def host_contract(
    graph: HostGraph, labels: np.ndarray
) -> Tuple[HostGraph, np.ndarray]:
    """Contract a clustering on the host (sequential analog of
    contraction/cluster_contraction.h)."""
    uniq, cmap = np.unique(labels, return_inverse=True)
    c_n = len(uniq)
    node_w = graph.node_weight_array()
    c_node_w = np.zeros(c_n, dtype=np.int64)
    np.add.at(c_node_w, cmap, node_w)

    src = graph.edge_sources()
    cu = cmap[src]
    cv = cmap[graph.adjncy]
    ew = graph.edge_weight_array()
    keep = cu != cv
    cu, cv, ew = cu[keep], cv[keep], ew[keep]
    key = cu.astype(np.int64) * c_n + cv
    order = np.argsort(key, kind="stable")
    key, cu, cv, ew = key[order], cu[order], cv[order], ew[order]
    if len(key):
        new_group = np.empty(len(key), dtype=bool)
        new_group[0] = True
        new_group[1:] = key[1:] != key[:-1]
        gid = np.cumsum(new_group) - 1
        g_w = np.bincount(gid, weights=ew).astype(np.int64)
        g_cu = cu[new_group]
        g_cv = cv[new_group]
    else:
        g_w = np.zeros(0, dtype=np.int64)
        g_cu = np.zeros(0, dtype=np.int64)
        g_cv = np.zeros(0, dtype=np.int64)

    xadj = np.zeros(c_n + 1, dtype=np.int64)
    np.add.at(xadj, g_cu + 1, 1)
    xadj = np.cumsum(xadj)
    coarse = HostGraph(
        xadj=xadj,
        adjncy=g_cv.astype(np.int32),
        node_weights=c_node_w,
        edge_weights=g_w if len(g_w) and not (g_w == 1).all() else None,
    )
    return coarse, cmap


def coarsen_for_bipartition(
    graph: HostGraph,
    ctx: InitialCoarseningContext,
    rng: np.random.Generator,
    max_block_weight: int,
) -> List[HostCoarseLevel]:
    """Build the sequential coarse hierarchy until n <= 2*contraction_limit
    or convergence (initial_coarsener.cc loop).  Returns levels fine->coarse
    (the input graph is not included)."""
    levels: List[HostCoarseLevel] = []
    current = graph
    limit = 2 * ctx.contraction_limit
    while current.n > limit:
        # BLOCK_WEIGHT-style cluster cap (presets.cc:188-189)
        max_cluster_weight = max(
            1, int(ctx.cluster_weight_multiplier * max_block_weight)
        )
        labels = host_lp_cluster(current, max_cluster_weight, rng)
        coarse, cmap = host_contract(current, labels)
        if coarse.n >= (1.0 - ctx.convergence_threshold) * current.n:
            break  # converged, not shrinking enough
        levels.append(HostCoarseLevel(graph=coarse, cmap=cmap))
        current = coarse
    return levels
