"""Sequential host-side coarsening for initial bipartitioning.

Analog of kaminpar-shm/initial_partitioning/initial_coarsener.cc (456 LoC):
sequential size-constrained LP clustering interleaved with contraction,
used only on already-small coarsest graphs (hundreds to thousands of nodes)
before flat bipartitioning.  numpy-vectorized rounds.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from ..context import InitialCoarseningContext
from ..graphs.host import HostGraph


@dataclass
class HostCoarseLevel:
    graph: HostGraph
    cmap: np.ndarray  # fine node -> coarse node


def host_lp_cluster(
    graph: HostGraph,
    max_cluster_weight: int,
    rng: np.random.Generator,
    num_iterations: int = 3,
) -> np.ndarray:
    """LP clustering for initial coarsening (initial_coarsener's
    ClusteringAlgorithm analog), numpy-vectorized.

    The reference visits nodes asynchronously in random order; a python
    per-node loop is the wall-clock whale of the whole pipeline (the
    coarsest graphs are a few thousand nodes but this runs hundreds of
    times across extend-partition).  Vectorized scheme per sub-round:
    rate all (node, adjacent-cluster) pairs with one groupby, pick each
    node's best admissible cluster, filter movers by a coin flip (breaks
    A<->B swap oscillation the async order avoided naturally), and admit
    movers per target cluster in priority order up to the weight cap —
    so the cap is never exceeded, exactly like the async version.
    """
    n = graph.n
    labels = np.arange(n, dtype=np.int64)
    if n == 0 or graph.m == 0:
        return labels
    node_w = graph.node_weight_array()
    cw = node_w.astype(np.int64).copy()
    edge_w = graph.edge_weight_array()
    src = graph.edge_sources()
    dst = graph.adjncy

    dry_subrounds = 0
    for it in range(2 * num_iterations):
        cl = labels[dst]
        # rate: groupby (src, cluster) -> summed edge weight
        key = src.astype(np.int64) * n + cl
        order = np.argsort(key, kind="stable")
        k_s, u_s, cl_s = key[order], src[order], cl[order]
        w_s = edge_w[order].astype(np.int64)
        new_group = np.empty(len(k_s), dtype=bool)
        new_group[0] = True
        new_group[1:] = k_s[1:] != k_s[:-1]
        gid = np.cumsum(new_group) - 1
        g_rating = np.bincount(gid, weights=w_s).astype(np.int64)
        g_u = u_s[new_group]
        g_cl = cl_s[new_group]

        # admissible: own cluster, or target under the weight cap
        own = g_cl == labels[g_u]
        ok = own | (cw[g_cl] + node_w[g_u] <= max_cluster_weight)

        # current-cluster rating per node (0 if no internal edge)
        cur_rating = np.zeros(n, dtype=np.int64)
        cur_rating[g_u[own]] = g_rating[own]

        # best admissible cluster per node: sort groups by (u, rating,
        # tie hash) and take the last group of each node's run
        tie = (g_cl * 1000003 + it * 7919) % 1013904223
        sort2 = np.lexsort((tie, np.where(ok, g_rating, -1), g_u))
        gu2 = g_u[sort2]
        last = np.empty(len(gu2), dtype=bool)
        last[:-1] = gu2[:-1] != gu2[1:]
        last[-1] = True
        top = sort2[last]
        best_u = g_u[top]
        best_cl = np.where(ok[top], g_cl[top], labels[best_u])
        best_rating = np.where(ok[top], g_rating[top], 0)

        target = labels.copy()
        target[best_u] = best_cl
        rating_of_target = np.zeros(n, dtype=np.int64)
        rating_of_target[best_u] = best_rating

        move = (target != labels) & (
            rating_of_target >= np.maximum(cur_rating, 1)
        )
        # coin filter: half the nodes per sub-round (swap-oscillation
        # guard).  The coin is a fixed per-node hash — independent of the
        # sub-round — so sub-rounds 2j and 2j+1 cover COMPLEMENTARY
        # halves and the two-dry-sub-rounds convergence check below
        # really has seen every node
        coin = ((np.arange(n) * 2654435761) >> 7) & 1
        move &= coin == (it & 1)
        movers = np.flatnonzero(move)
        if len(movers) == 0:
            # converged only when BOTH coin halves of a pair are dry — a
            # single empty half says nothing about the other half's nodes
            if dry_subrounds >= 1:
                break
            dry_subrounds += 1
            continue

        # capacity commit: per target cluster, admit movers in hashed
        # priority order while the cluster stays under the cap
        t = target[movers]
        prio = (movers * 1566083941 + it * 12345) % 2147483647
        corder = np.lexsort((prio, t))
        t_s = t[corder]
        m_s = movers[corder]
        w_m = node_w[m_s].astype(np.int64)
        csum = np.cumsum(w_m)
        first = np.empty(len(t_s), dtype=bool)
        first[0] = True
        first[1:] = t_s[1:] != t_s[:-1]
        base = np.where(first, csum - w_m, 0)
        np.maximum.accumulate(base, out=base)
        within = csum - base  # cumulative weight within the target group
        admit = cw[t_s] + within <= max_cluster_weight
        adm = m_s[admit]
        if len(adm) == 0:
            if dry_subrounds >= 1:
                break
            dry_subrounds += 1
            continue
        dry_subrounds = 0
        old = labels[adm]
        labels[adm] = target[adm]
        np.subtract.at(cw, old, node_w[adm])
        np.add.at(cw, target[adm], node_w[adm])
    return labels


def host_contract(
    graph: HostGraph, labels: np.ndarray
) -> Tuple[HostGraph, np.ndarray]:
    """Contract a clustering on the host (sequential analog of
    contraction/cluster_contraction.h)."""
    uniq, cmap = np.unique(labels, return_inverse=True)
    c_n = len(uniq)
    node_w = graph.node_weight_array()
    c_node_w = np.zeros(c_n, dtype=np.int64)
    np.add.at(c_node_w, cmap, node_w)

    src = graph.edge_sources()
    cu = cmap[src]
    cv = cmap[graph.adjncy]
    ew = graph.edge_weight_array()
    keep = cu != cv
    cu, cv, ew = cu[keep], cv[keep], ew[keep]
    key = cu.astype(np.int64) * c_n + cv
    order = np.argsort(key, kind="stable")
    key, cu, cv, ew = key[order], cu[order], cv[order], ew[order]
    if len(key):
        new_group = np.empty(len(key), dtype=bool)
        new_group[0] = True
        new_group[1:] = key[1:] != key[:-1]
        gid = np.cumsum(new_group) - 1
        g_w = np.bincount(gid, weights=ew).astype(np.int64)
        g_cu = cu[new_group]
        g_cv = cv[new_group]
    else:
        g_w = np.zeros(0, dtype=np.int64)
        g_cu = np.zeros(0, dtype=np.int64)
        g_cv = np.zeros(0, dtype=np.int64)

    xadj = np.zeros(c_n + 1, dtype=np.int64)
    np.add.at(xadj, g_cu + 1, 1)
    xadj = np.cumsum(xadj)
    coarse = HostGraph(
        xadj=xadj,
        adjncy=g_cv.astype(np.int32),
        node_weights=c_node_w,
        edge_weights=g_w if len(g_w) and not (g_w == 1).all() else None,
    )
    return coarse, cmap


def coarsen_for_bipartition(
    graph: HostGraph,
    ctx: InitialCoarseningContext,
    rng: np.random.Generator,
    max_block_weight: int,
) -> List[HostCoarseLevel]:
    """Build the sequential coarse hierarchy until n <= 2*contraction_limit
    or convergence (initial_coarsener.cc loop).  Returns levels fine->coarse
    (the input graph is not included)."""
    levels: List[HostCoarseLevel] = []
    current = graph
    limit = 2 * ctx.contraction_limit
    while current.n > limit:
        # BLOCK_WEIGHT-style cluster cap (presets.cc:188-189)
        max_cluster_weight = max(
            1, int(ctx.cluster_weight_multiplier * max_block_weight)
        )
        labels = host_lp_cluster(current, max_cluster_weight, rng)
        coarse, cmap = host_contract(current, labels)
        if coarse.n >= (1.0 - ctx.convergence_threshold) * current.n:
            break  # converged, not shrinking enough
        levels.append(HostCoarseLevel(graph=coarse, cmap=cmap))
        current = coarse
    return levels
