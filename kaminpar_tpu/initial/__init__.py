from .bipartitioner import (  # noqa: F401
    InitialMultilevelBipartitioner,
    PoolBipartitioner,
    bipartition,
)
from .fm import fm_bipartition_refine  # noqa: F401
