"""Sequential 2-way FM refinement on the host.

Analog of kaminpar-shm/initial_partitioning/initial_fm_refiner.h:68 (466
LoC): classic Fiduccia–Mattheyses with two priority queues, best-prefix
rollback, and the reference's stopping policies (simple = abort after
`num_fruitless_moves` non-improving moves; adaptive = Osipov/Sanders random
walk model with parameter alpha, stopping_policies analog).

Runs on coarsest-level graphs (tens to hundreds of nodes), so python/heapq
is appropriate — this mirrors the reference keeping initial bipartitioning
sequential per thread.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass

import numpy as np

from ..context import FMStoppingRule, InitialRefinementContext
from ..graphs.host import HostGraph


@dataclass
class _SimpleStopper:
    """initial_fm_refiner stopping policy SIMPLE."""

    num_fruitless_moves: int
    fruitless: int = 0

    def reset(self) -> None:
        self.fruitless = 0

    def update(self, gain: int) -> None:
        if gain > 0:
            self.fruitless = 0
        else:
            self.fruitless += 1

    def should_stop(self) -> bool:
        return self.fruitless >= self.num_fruitless_moves


@dataclass
class _AdaptiveStopper:
    """Adaptive stopping rule (stopping_policies.h:16): stop when the
    expected gain of continuing the random walk becomes negative, i.e.
    num_steps * expected_gain^2 > alpha * variance + beta."""

    alpha: float
    beta: float = 10.0
    num_steps: int = 0
    mean: float = 0.0
    m2: float = 0.0

    def reset(self) -> None:
        self.num_steps = 0
        self.mean = 0.0
        self.m2 = 0.0

    def update(self, gain: int) -> None:
        self.num_steps += 1
        delta = gain - self.mean
        self.mean += delta / self.num_steps
        self.m2 += delta * (gain - self.mean)

    def should_stop(self) -> bool:
        if self.num_steps < 2:
            return False
        variance = self.m2 / (self.num_steps - 1)
        return (
            self.mean < 0
            and self.num_steps * self.mean * self.mean
            > self.alpha * variance + self.beta
        )


def fm_bipartition_refine(
    graph: HostGraph,
    partition: np.ndarray,
    max_block_weights: np.ndarray,
    ctx: InitialRefinementContext,
    rng: np.random.Generator,
) -> int:
    """Refine a 2-way partition in place; returns the total cut improvement.

    One call runs up to ctx.num_iterations FM passes (initial_fm_refiner
    num_iterations=5 default); each pass moves nodes one at a time picking
    the max-gain feasible move, tracks the best prefix, and rolls back the
    tail."""
    if graph.n == 0:
        return 0
    node_w = graph.node_weight_array()
    edge_w = graph.edge_weight_array()
    total_improvement = 0

    if ctx.stopping_rule == FMStoppingRule.ADAPTIVE:
        stopper = _AdaptiveStopper(alpha=ctx.alpha)
    else:
        stopper = _SimpleStopper(num_fruitless_moves=ctx.num_fruitless_moves)

    # static CSR views as plain lists, converted once per refine call —
    # the per-move loop in _fm_pass reads them millions of times and
    # python list access beats numpy scalar indexing severalfold
    csr = (
        graph.xadj.tolist(),
        graph.adjncy.tolist(),
        edge_w.tolist(),
        node_w.tolist(),
    )
    for _ in range(max(1, ctx.num_iterations)):
        improvement = _fm_pass(
            graph, partition, node_w, edge_w, max_block_weights, stopper,
            rng, csr,
        )
        total_improvement += improvement
        if improvement == 0:
            break
    return total_improvement


def _gains(graph, partition, edge_w):
    """gain[u] = weight to other block - weight to own block."""
    src = graph.edge_sources()
    ext = np.zeros(graph.n, dtype=np.int64)
    internal = np.zeros(graph.n, dtype=np.int64)
    cut_mask = partition[src] != partition[graph.adjncy]
    np.add.at(ext, src[cut_mask], edge_w[cut_mask])
    np.add.at(internal, src[~cut_mask], edge_w[~cut_mask])
    return ext - internal


def _fm_pass(
    graph, partition, node_w, edge_w, max_block_weights, stopper, rng, csr
):
    """One FM pass.  Hot loop works on plain python lists/ints: numpy
    scalar indexing in the per-move inner loop is several times slower
    than list access, and this pass runs hundreds of times per
    partition call (same algorithm, same results)."""
    n = graph.n
    gain = _gains(graph, partition, edge_w).tolist()
    bw0 = int(node_w[partition == 0].sum())
    bw1 = int(node_w[partition == 1].sum())
    block_w = [bw0, bw1]
    max_bw = [int(max_block_weights[0]), int(max_block_weights[1])]

    part = partition.tolist()
    xadj, adjncy, edge_w_l, node_w_l = csr

    # two PQs keyed by gain with random tiebreak (lazy deletion)
    tie = rng.random(n).tolist()
    pqs = ([], [])
    for u in range(n):
        pqs[part[u]].append((-gain[u], tie[u], u))
    heapq.heapify(pqs[0])
    heapq.heapify(pqs[1])
    locked = bytearray(n)
    stopper.reset()

    moves = []
    cur_delta = 0
    best_delta = 0
    best_len = 0

    while True:
        # choose source block: prefer the feasible move with higher gain
        candidates = []
        for b in (0, 1):
            pq = pqs[b]
            while pq:
                negg, t, u = pq[0]
                if locked[u] or part[u] != b or -negg != gain[u]:
                    heapq.heappop(pq)
                    continue
                candidates.append((negg, t, u, b))
                break
        feasible = [
            c
            for c in candidates
            if block_w[1 - c[3]] + node_w_l[c[2]] <= max_bw[1 - c[3]]
        ]
        if feasible:
            feasible.sort()
            negg, _, u, b = feasible[0]
        else:
            # no balance-feasible move: move from the heavier block (the
            # only direction that improves balance); candidates from the
            # lighter block stay in their PQ for later
            heavier = int(block_w[1] > block_w[0])
            from_heavier = [c for c in candidates if c[3] == heavier]
            if not from_heavier:
                break
            negg, _, u, b = from_heavier[0]
        heapq.heappop(pqs[b])

        # apply move u: b -> 1-b
        locked[u] = 1
        part[u] = 1 - b
        block_w[b] -= node_w_l[u]
        block_w[1 - b] += node_w_l[u]
        g = -negg
        cur_delta += g
        moves.append(u)
        stopper.update(g)
        if cur_delta > best_delta:
            best_delta = cur_delta
            best_len = len(moves)

        # update neighbor gains
        for e in range(xadj[u], xadj[u + 1]):
            v = adjncy[e]
            w = edge_w_l[e]
            # v's connection to u's old block fell, to new block rose
            if part[v] == b:
                gain[v] += 2 * w
            else:
                gain[v] -= 2 * w
            if not locked[v]:
                heapq.heappush(pqs[part[v]], (-gain[v], tie[v], v))
        gain[u] = -gain[u]

        if stopper.should_stop():
            break

    # roll back to best prefix
    for u in moves[best_len:]:
        part[u] = 1 - part[u]
    partition[:] = part
    return best_delta
