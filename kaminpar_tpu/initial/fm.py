"""Sequential 2-way FM refinement on the host.

Analog of kaminpar-shm/initial_partitioning/initial_fm_refiner.h:68 (466
LoC): classic Fiduccia–Mattheyses with two priority queues, best-prefix
rollback, and the reference's stopping policies (simple = abort after
`num_fruitless_moves` non-improving moves; adaptive = Osipov/Sanders random
walk model with parameter alpha, stopping_policies analog).

Runs on coarsest-level graphs (tens to hundreds of nodes), so python/heapq
is appropriate — this mirrors the reference keeping initial bipartitioning
sequential per thread.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass

import numpy as np

from ..context import FMStoppingRule, InitialRefinementContext
from ..graphs.host import HostGraph


@dataclass
class _SimpleStopper:
    """initial_fm_refiner stopping policy SIMPLE."""

    num_fruitless_moves: int
    fruitless: int = 0

    def reset(self) -> None:
        self.fruitless = 0

    def update(self, gain: int) -> None:
        if gain > 0:
            self.fruitless = 0
        else:
            self.fruitless += 1

    def should_stop(self) -> bool:
        return self.fruitless >= self.num_fruitless_moves


@dataclass
class _AdaptiveStopper:
    """Adaptive stopping rule (stopping_policies.h:16): stop when the
    expected gain of continuing the random walk becomes negative, i.e.
    num_steps * expected_gain^2 > alpha * variance + beta."""

    alpha: float
    beta: float = 10.0
    num_steps: int = 0
    mean: float = 0.0
    m2: float = 0.0

    def reset(self) -> None:
        self.num_steps = 0
        self.mean = 0.0
        self.m2 = 0.0

    def update(self, gain: int) -> None:
        self.num_steps += 1
        delta = gain - self.mean
        self.mean += delta / self.num_steps
        self.m2 += delta * (gain - self.mean)

    def should_stop(self) -> bool:
        if self.num_steps < 2:
            return False
        variance = self.m2 / (self.num_steps - 1)
        return (
            self.mean < 0
            and self.num_steps * self.mean * self.mean
            > self.alpha * variance + self.beta
        )


def fm_bipartition_refine(
    graph: HostGraph,
    partition: np.ndarray,
    max_block_weights: np.ndarray,
    ctx: InitialRefinementContext,
    rng: np.random.Generator,
) -> int:
    """Refine a 2-way partition in place; returns the total cut improvement.

    One call runs up to ctx.num_iterations FM passes (initial_fm_refiner
    num_iterations=5 default); each pass moves nodes one at a time picking
    the max-gain feasible move, tracks the best prefix, and rolls back the
    tail."""
    if graph.n == 0:
        return 0
    node_w = graph.node_weight_array()
    edge_w = graph.edge_weight_array()
    total_improvement = 0

    if ctx.stopping_rule == FMStoppingRule.ADAPTIVE:
        stopper = _AdaptiveStopper(alpha=ctx.alpha)
    else:
        stopper = _SimpleStopper(num_fruitless_moves=ctx.num_fruitless_moves)

    for _ in range(max(1, ctx.num_iterations)):
        improvement = _fm_pass(
            graph, partition, node_w, edge_w, max_block_weights, stopper, rng
        )
        total_improvement += improvement
        if improvement == 0:
            break
    return total_improvement


def _gains(graph, partition, edge_w):
    """gain[u] = weight to other block - weight to own block."""
    src = graph.edge_sources()
    ext = np.zeros(graph.n, dtype=np.int64)
    internal = np.zeros(graph.n, dtype=np.int64)
    cut_mask = partition[src] != partition[graph.adjncy]
    np.add.at(ext, src[cut_mask], edge_w[cut_mask])
    np.add.at(internal, src[~cut_mask], edge_w[~cut_mask])
    return ext - internal


def _fm_pass(graph, partition, node_w, edge_w, max_block_weights, stopper, rng):
    n = graph.n
    gain = _gains(graph, partition, edge_w)
    block_w = np.zeros(2, dtype=np.int64)
    np.add.at(block_w, partition, node_w)

    # two PQs keyed by gain with random tiebreak (lazy deletion)
    pqs = ([], [])
    tie = rng.random(n)
    for u in range(n):
        heapq.heappush(pqs[partition[u]], (-int(gain[u]), tie[u], u))
    locked = np.zeros(n, dtype=bool)
    stopper.reset()

    moves = []
    cur_delta = 0
    best_delta = 0
    best_len = 0

    while True:
        # choose source block: prefer the feasible move with higher gain
        candidates = []
        for b in (0, 1):
            while pqs[b]:
                negg, t, u = pqs[b][0]
                if locked[u] or partition[u] != b or -negg != gain[u]:
                    heapq.heappop(pqs[b])
                    continue
                candidates.append((negg, t, u, b))
                break
        feasible = [
            c
            for c in candidates
            if block_w[1 - c[3]] + node_w[c[2]] <= max_block_weights[1 - c[3]]
        ]
        if feasible:
            feasible.sort()
            negg, _, u, b = feasible[0]
        else:
            # no balance-feasible move: move from the heavier block (the
            # only direction that improves balance); candidates from the
            # lighter block stay in their PQ for later
            heavier = int(block_w[1] > block_w[0])
            from_heavier = [c for c in candidates if c[3] == heavier]
            if not from_heavier:
                break
            negg, _, u, b = from_heavier[0]
        heapq.heappop(pqs[b])

        # apply move u: b -> 1-b
        locked[u] = True
        partition[u] = 1 - b
        block_w[b] -= node_w[u]
        block_w[1 - b] += node_w[u]
        g = -negg
        cur_delta += g
        moves.append(u)
        stopper.update(g)
        if cur_delta > best_delta:
            best_delta = cur_delta
            best_len = len(moves)

        # update neighbor gains
        lo, hi = int(graph.xadj[u]), int(graph.xadj[u + 1])
        for e in range(lo, hi):
            v = int(graph.adjncy[e])
            w = int(edge_w[e])
            # v's connection to u's old block fell, to new block rose
            if partition[v] == b:
                gain[v] += 2 * w
            else:
                gain[v] -= 2 * w
            if not locked[v]:
                heapq.heappush(pqs[partition[v]], (-int(gain[v]), tie[v], v))
        gain[u] = -gain[u]

        if stopper.should_stop():
            break

    # roll back to best prefix
    for u in moves[best_len:]:
        partition[u] = 1 - partition[u]
    return best_delta
