"""Flat (single-level) 2-way bipartitioners on the host.

Analogs of kaminpar-shm/initial_partitioning/'s pool members:
  * RandomBipartitioner  (initial_random_bipartitioner.h:16)
  * BfsBipartitioner     (initial_bfs_bipartitioner.h:41, greedy BFS growth)
  * GreedyGraphGrowing   (initial_ggg_bipartitioner.h:18, gain-ordered growth)

These run on the coarsest graphs only (n <= ~2*contraction_limit after
initial coarsening), so plain numpy/python is the right tool — exactly the
reference's design point of keeping initial bipartitioning sequential on CPU
(initial_bipartitioner_worker_pool.h:42, BASELINE.json north star).

All bipartitioners take (graph, max_block_weights[2], rng) and return an
int8 partition array; they may violate balance slightly if the graph forces
it (the FM refiner + balancer repair later), matching reference behavior.
"""

from __future__ import annotations

import heapq
from typing import Tuple

import numpy as np

from ..graphs.host import HostGraph


def _greedy_block(weights_sorted_idx, node_w, max_w0):
    """Assign nodes in the given order to block 0 until it is full."""
    part = np.ones(len(node_w), dtype=np.int8)
    w0 = 0
    for u in weights_sorted_idx:
        if w0 + node_w[u] <= max_w0:
            part[u] = 0
            w0 += node_w[u]
    return part


def random_bipartition(
    graph: HostGraph, max_block_weights: np.ndarray, rng: np.random.Generator
) -> np.ndarray:
    """Random assignment with capacity check: each node goes to a random
    block that still has room, else the other (initial_random_bipartitioner
    behavior)."""
    n = graph.n
    node_w = graph.node_weight_array()
    part = np.zeros(n, dtype=np.int8)
    weights = [0, 0]
    order = rng.permutation(n)
    choice = rng.integers(0, 2, size=n)
    for u in order:
        b = int(choice[u])
        if weights[b] + node_w[u] > max_block_weights[b]:
            b = 1 - b
        part[u] = b
        weights[b] += node_w[u]
    return part


def bfs_bipartition(
    graph: HostGraph, max_block_weights: np.ndarray, rng: np.random.Generator
) -> np.ndarray:
    """Grow block 0 via BFS from a random seed until it reaches its
    perfectly-balanced weight (initial_bfs_bipartitioner.h:41)."""
    n = graph.n
    if n == 0:
        return np.zeros(0, dtype=np.int8)
    node_w = graph.node_weight_array()
    total = int(node_w.sum())
    target0 = min(int(max_block_weights[0]), total - 0)
    # stop growing once block 0 holds ~half the total weight
    stop_at = max(total - int(max_block_weights[1]), (total + 1) // 2)

    part = np.ones(n, dtype=np.int8)
    visited = np.zeros(n, dtype=bool)
    queue = [int(rng.integers(0, n))]
    visited[queue[0]] = True
    w0 = 0
    while queue and w0 < stop_at:
        u = queue.pop(0)
        if w0 + node_w[u] > target0:
            continue
        part[u] = 0
        w0 += node_w[u]
        for v in graph.neighbors(u):
            if not visited[v]:
                visited[v] = True
                queue.append(int(v))
        if not queue:
            remaining = np.flatnonzero(~visited)
            if len(remaining):
                s = int(rng.choice(remaining))
                visited[s] = True
                queue.append(s)
    return part


def ggg_bipartition(
    graph: HostGraph, max_block_weights: np.ndarray, rng: np.random.Generator
) -> np.ndarray:
    """Greedy graph growing (initial_ggg_bipartitioner.h:18): grow block 0
    from a random seed, always absorbing the frontier node with the highest
    gain (connection to block 0 minus connection to block 1)."""
    n = graph.n
    if n == 0:
        return np.zeros(0, dtype=np.int8)
    node_w = graph.node_weight_array()
    edge_w = graph.edge_weight_array()
    total = int(node_w.sum())
    stop_at = max(total - int(max_block_weights[1]), (total + 1) // 2)
    target0 = int(max_block_weights[0])

    part = np.ones(n, dtype=np.int8)
    in_b0 = np.zeros(n, dtype=bool)
    gain = np.zeros(n, dtype=np.int64)  # connection to block 0 (rest is b1)
    pq: list = []
    seed = int(rng.integers(0, n))
    heapq.heappush(pq, (0, seed))
    queued = np.zeros(n, dtype=bool)
    queued[seed] = True
    w0 = 0
    while w0 < stop_at:
        while pq:
            negg, u = heapq.heappop(pq)
            if not in_b0[u] and -negg == gain[u]:
                break
        else:
            remaining = np.flatnonzero(~in_b0 & ~queued)
            if len(remaining) == 0:
                break
            u = int(rng.choice(remaining))
            queued[u] = True
        if in_b0[u] or w0 + node_w[u] > target0:
            continue
        in_b0[u] = True
        part[u] = 0
        w0 += node_w[u]
        lo, hi = int(graph.xadj[u]), int(graph.xadj[u + 1])
        for e in range(lo, hi):
            v = int(graph.adjncy[e])
            if not in_b0[v]:
                gain[v] += int(edge_w[e])
                queued[v] = True
                heapq.heappush(pq, (-int(gain[v]), v))
    return part
