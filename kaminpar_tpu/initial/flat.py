"""Flat (single-level) 2-way bipartitioners on the host.

Analogs of kaminpar-shm/initial_partitioning/'s pool members:
  * RandomBipartitioner  (initial_random_bipartitioner.h:16)
  * BfsBipartitioner     (initial_bfs_bipartitioner.h:41, greedy BFS growth)
  * GreedyGraphGrowing   (initial_ggg_bipartitioner.h:18, gain-ordered growth)

These run on the coarsest graphs only (n <= ~2*contraction_limit after
initial coarsening), so plain numpy/python is the right tool — exactly the
reference's design point of keeping initial bipartitioning sequential on CPU
(initial_bipartitioner_worker_pool.h:42, BASELINE.json north star).

All bipartitioners take (graph, max_block_weights[2], rng) and return an
int8 partition array; they may violate balance slightly if the graph forces
it (the FM refiner + balancer repair later), matching reference behavior.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from ..graphs.host import HostGraph


def _greedy_block(weights_sorted_idx, node_w, max_w0):
    """Assign nodes in the given order to block 0 until it is full."""
    part = np.ones(len(node_w), dtype=np.int8)
    w0 = 0
    for u in weights_sorted_idx:
        if w0 + node_w[u] <= max_w0:
            part[u] = 0
            w0 += node_w[u]
    return part


def random_bipartition(
    graph: HostGraph, max_block_weights: np.ndarray, rng: np.random.Generator
) -> np.ndarray:
    """Random assignment with capacity check: each node goes to a random
    block that still has room, else the other (initial_random_bipartitioner
    behavior)."""
    n = graph.n
    node_w = graph.node_weight_array()
    part = np.zeros(n, dtype=np.int8)
    weights = [0, 0]
    order = rng.permutation(n)
    choice = rng.integers(0, 2, size=n)
    for u in order:
        b = int(choice[u])
        if weights[b] + node_w[u] > max_block_weights[b]:
            b = 1 - b
        part[u] = b
        weights[b] += node_w[u]
    return part


def _expand_frontier(graph: HostGraph, frontier: np.ndarray) -> np.ndarray:
    """All neighbors of `frontier` (with duplicates), via one CSR gather."""
    starts = graph.xadj[frontier]
    lens = (graph.xadj[frontier + 1] - starts).astype(np.int64)
    total = int(lens.sum())
    if total == 0:
        return np.zeros(0, dtype=graph.adjncy.dtype)
    bases = np.cumsum(lens) - lens
    pos = np.arange(total) - np.repeat(bases, lens) + np.repeat(starts, lens)
    return graph.adjncy[pos]


def bfs_bipartition(
    graph: HostGraph, max_block_weights: np.ndarray, rng: np.random.Generator
) -> np.ndarray:
    """Grow block 0 via BFS from a random seed until it reaches its
    perfectly-balanced weight (initial_bfs_bipartitioner.h:41).

    Vectorized level-by-level: a whole BFS level is admitted by weight
    prefix (the async original admits node-by-node in queue order and
    skips single too-heavy nodes; the prefix cut is the same rule applied
    at level granularity — quality is recovered by FM/pool-best anyway).
    """
    n = graph.n
    if n == 0:
        return np.zeros(0, dtype=np.int8)
    node_w = graph.node_weight_array()
    total = int(node_w.sum())
    target0 = min(int(max_block_weights[0]), total - 0)
    # stop growing once block 0 holds ~half the total weight
    stop_at = max(total - int(max_block_weights[1]), (total + 1) // 2)

    part = np.ones(n, dtype=np.int8)
    visited = np.zeros(n, dtype=bool)
    seed = int(rng.integers(0, n))
    frontier = np.array([seed], dtype=np.int64)
    visited[seed] = True
    w0 = 0
    reseed_streak = 0
    admitted_since_reseed = 0
    while w0 < stop_at:
        # admit lightest-first until the target: within a BFS level the
        # queue order is arbitrary, and this matches the original's
        # skip-too-heavy-but-keep-going rule (a single heavy node never
        # blocks the light nodes behind it)
        order = frontier[np.argsort(node_w[frontier], kind="stable")]
        csum = w0 + np.cumsum(node_w[order])
        fits = csum <= target0
        admit = order[fits]
        if len(admit):
            part[admit] = 0
            w0 = int(csum[fits][-1])
            admitted_since_reseed += len(admit)
        neigh = np.unique(_expand_frontier(graph, admit))
        nxt = neigh[~visited[neigh]]
        visited[nxt] = True
        if len(nxt) == 0:
            remaining = np.flatnonzero(~visited)
            if len(remaining) == 0 or w0 >= stop_at:
                break
            # a dead end right after a reseed means the seeded component
            # was tiny; many in a row means the remainder is fragmented
            # and the original's one-node-per-pop reseed loop would
            # degenerate to python-per-node — bulk-admit a random
            # weight-prefix instead.  A reseed that grew a real region
            # (several admissions) resets the streak.
            if admitted_since_reseed >= 4:
                reseed_streak = 0
            if reseed_streak >= 16:
                order = rng.permutation(remaining)
                csum = w0 + np.cumsum(node_w[order])
                fits = (csum <= target0) & (csum - node_w[order] < stop_at)
                part[order[fits]] = 0
                break
            reseed_streak += 1
            admitted_since_reseed = 0
            s = int(rng.choice(remaining))
            visited[s] = True
            nxt = np.array([s], dtype=np.int64)
        frontier = nxt
    return part


def ggg_bipartition(
    graph: HostGraph, max_block_weights: np.ndarray, rng: np.random.Generator
) -> np.ndarray:
    """Greedy graph growing (initial_ggg_bipartitioner.h:18): grow block 0
    from a random seed, always absorbing the frontier node with the highest
    gain (connection to block 0 minus connection to block 1)."""
    n = graph.n
    if n == 0:
        return np.zeros(0, dtype=np.int8)
    node_w = graph.node_weight_array()
    edge_w = graph.edge_weight_array()
    total = int(node_w.sum())
    stop_at = max(total - int(max_block_weights[1]), (total + 1) // 2)
    target0 = int(max_block_weights[0])

    part = np.ones(n, dtype=np.int8)
    in_b0 = np.zeros(n, dtype=bool)
    # connection to block 0; -1 marks "not on the frontier".  A flat
    # argmax per absorption replaces the lazy heap: O(n) per step in C
    # beats O(deg log n) python heap churn on these graph sizes.
    gain = np.full(n, -1, dtype=np.int64)
    seed = int(rng.integers(0, n))
    gain[seed] = 0
    w0 = 0
    reseed_streak = 0
    while w0 < stop_at:
        u = int(np.argmax(gain))
        if gain[u] < 0:
            remaining = np.flatnonzero(~in_b0 & (gain < 0))
            if len(remaining) == 0:
                break
            if reseed_streak >= 16:
                # fragmented remainder (see bfs_bipartition): bulk-admit
                # a random weight-prefix instead of one python iteration
                # per isolated node
                order = rng.permutation(remaining)
                csum = w0 + np.cumsum(node_w[order])
                fits = (csum <= target0) & (csum - node_w[order] < stop_at)
                part[order[fits]] = 0
                break
            reseed_streak += 1
            u = int(rng.choice(remaining))
        else:
            reseed_streak = 0
        if w0 + node_w[u] > target0:
            # too heavy: drop from the frontier (the heap version's skip)
            gain[u] = -1
            in_b0[u] = True  # never reconsidered, stays in block 1
            continue
        in_b0[u] = True
        part[u] = 0
        w0 += node_w[u]
        gain[u] = -1
        lo, hi = int(graph.xadj[u]), int(graph.xadj[u + 1])
        neigh = graph.adjncy[lo:hi]
        w = edge_w[lo:hi]
        live = ~in_b0[neigh]
        np.maximum.at(gain, neigh[live], 0)
        np.add.at(gain, neigh[live], w[live])
    return part
