"""Sequential multilevel 2-way bipartitioner + adaptive pool.

Analog of kaminpar-shm/initial_partitioning/:
  * InitialMultilevelBipartitioner (initial_multilevel_bipartitioner.cc:
    55 initialize, 83 partition): sequential LP coarsening, flat
    bipartitioner pool on the coarsest level, 2-way FM at every level of
    the uncoarsening.
  * InitialPoolBipartitioner (initial_pool_bipartitioner.h:24-56): runs
    repetitions of the enabled flat bipartitioners, keeps the best result,
    and adaptively disables bipartitioners whose running score is worst
    (use_adaptive_bipartitioner_selection).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..context import InitialPartitioningContext, InitialPoolContext
from ..graphs.host import HostGraph
from ..utils import timer
from .coarsening import coarsen_for_bipartition
from .flat import bfs_bipartition, ggg_bipartition, random_bipartition
from .fm import fm_bipartition_refine


def _host_cut(graph: HostGraph, partition: np.ndarray) -> int:
    src = graph.edge_sources()
    ew = graph.edge_weight_array()
    return int(ew[partition[src] != partition[graph.adjncy]].sum()) // 2


def _host_block_weights(graph: HostGraph, partition: np.ndarray) -> np.ndarray:
    bw = np.zeros(2, dtype=np.int64)
    np.add.at(bw, partition, graph.node_weight_array())
    return bw


@dataclass
class _PoolEntry:
    name: str
    fn: Callable
    runs: int = 0
    mean: float = 0.0
    m2: float = 0.0

    def record(self, cut: int) -> None:
        self.runs += 1
        delta = cut - self.mean
        self.mean += delta / self.runs
        self.m2 += delta * (cut - self.mean)

    def score(self) -> float:
        return self.mean


class PoolBipartitioner:
    """Adaptive pool over the flat bipartitioners
    (initial_pool_bipartitioner.h:24-56)."""

    def __init__(self, ctx: InitialPoolContext):
        self.ctx = ctx
        self.entries: List[_PoolEntry] = []
        if ctx.enable_bfs_bipartitioner:
            self.entries.append(_PoolEntry("bfs", bfs_bipartition))
        if ctx.enable_ggg_bipartitioner:
            self.entries.append(_PoolEntry("ggg", ggg_bipartition))
        if ctx.enable_random_bipartitioner:
            self.entries.append(_PoolEntry("random", random_bipartition))
        if not self.entries:
            self.entries.append(_PoolEntry("random", random_bipartition))

    def bipartition(
        self,
        graph: HostGraph,
        max_block_weights: np.ndarray,
        rng: np.random.Generator,
    ) -> np.ndarray:
        ctx = self.ctx
        n_reps = int(
            np.clip(
                round(ctx.repetition_multiplier * ctx.min_num_repetitions),
                1,
                ctx.max_num_repetitions,
            )
        )
        best_part: Optional[np.ndarray] = None
        best_key: Tuple[int, int] = (1 << 62, 1 << 62)
        for rep in range(n_reps):
            active = self.entries
            if (
                ctx.use_adaptive_bipartitioner_selection
                and rep >= ctx.min_num_non_adaptive_repetitions
                and len(self.entries) > 1
            ):
                # keep all but the worst-scoring bipartitioner
                ranked = sorted(self.entries, key=lambda e: e.score())
                active = ranked[:-1]
            for entry in active:
                with timer.scoped_timer(f"ip-flat-{entry.name}"):
                    part = entry.fn(graph, max_block_weights, rng)
                if not ctx.refinement.disabled:
                    with timer.scoped_timer("ip-fm"):
                        fm_bipartition_refine(
                            graph, part, max_block_weights, ctx.refinement, rng
                        )
                cut = _host_cut(graph, part)
                bw = _host_block_weights(graph, part)
                overload = int(
                    np.maximum(bw - np.asarray(max_block_weights), 0).sum()
                )
                entry.record(cut + overload * 1000)
                key = (overload, cut)
                if key < best_key:
                    best_key = key
                    best_part = part.copy()
        assert best_part is not None
        return best_part


class InitialMultilevelBipartitioner:
    """Sequential multilevel bipartitioner
    (initial_multilevel_bipartitioner.cc)."""

    def __init__(self, ctx: InitialPartitioningContext):
        self.ctx = ctx
        self.pool = PoolBipartitioner(ctx.pool)

    def bipartition(
        self,
        graph: HostGraph,
        max_block_weights: np.ndarray,
        rng: np.random.Generator,
    ) -> np.ndarray:
        """Coarsen -> flat pool bipartition -> uncoarsen with FM refinement.
        Returns int8 partition of `graph`.

        Runs the native (C++) multilevel bipartitioner when the library is
        available — the reference's design point of sequential native
        initial partitioning (initial_bipartitioner_worker_pool.h:42); the
        numpy/python path below is the fallback and the behavioral spec."""
        if graph.n == 0:
            return np.zeros(0, dtype=np.int8)
        max_block_weights = np.asarray(max_block_weights, dtype=np.int64)
        if os.environ.get("KAMINPAR_TPU_NO_NATIVE_IP", "") != "1":
            from .. import native
            from ..resilience import NativeUnavailable, with_fallback

            # check availability BEFORE drawing the seed: the fallback
            # must see the same rng stream whether the native path was
            # skipped by env flag or by a missing toolchain
            if native.available():
                seed = int(rng.integers(0, 2**62))

                def _native_ip():
                    with timer.scoped_timer("ip-native"):
                        part = native.ml_bipartition(
                            graph, max_block_weights, self.ctx, seed=seed
                        )
                    if part is None:
                        raise NativeUnavailable(
                            "native bipartitioner unavailable"
                        )
                    return part

                # fallback: fall through to the numpy multilevel path
                # below (the behavioral spec of the native engine)
                part = with_fallback(
                    _native_ip, lambda exc: None, site="native-ip"
                )
                if part is not None:
                    return part
        with timer.scoped_timer("ip-coarsen"):
            levels = coarsen_for_bipartition(
                graph,
                self.ctx.coarsening,
                rng,
                max_block_weight=int(max_block_weights.max()),
            )
        coarsest = levels[-1].graph if levels else graph
        part = self.pool.bipartition(coarsest, max_block_weights, rng)

        for i in range(len(levels) - 1, -1, -1):
            part = part[levels[i].cmap]  # project up
            fine_graph = levels[i - 1].graph if i > 0 else graph
            if not self.ctx.refinement.disabled:
                with timer.scoped_timer("ip-fm"):
                    fm_bipartition_refine(
                        fine_graph, part, max_block_weights,
                        self.ctx.refinement, rng,
                    )
        return part.astype(np.int8)


def bipartition(
    graph: HostGraph,
    max_block_weights: np.ndarray,
    ctx: InitialPartitioningContext,
    rng: np.random.Generator,
) -> np.ndarray:
    """Convenience entry point (InitialBipartitionerWorkerPool analog)."""
    return InitialMultilevelBipartitioner(ctx).bipartition(
        graph, max_block_weights, rng
    )
